"""run_test: setup → interpret generators → teardown → check → store.

Mirrors jepsen.core/run!'s structure (reference call stack SURVEY.md §3.1):
  1. OS + DB setup per node, concurrently (reference src/jepsen/etcdemo.clj:161,34-55)
  2. Worker tasks — `concurrency` clients + 1 nemesis — pull ops from the
     generator, invoke them, and record invoke/completion pairs
  3. DB teardown per node (:57-60), log collection (db/LogFiles, :62-64)
  4. checker.check over the recorded history (:115-119,165-167)
  5. persist everything under store/<name>/<ts>/ (§1 L1)

Worker/process model (jepsen semantics the checker depends on): each worker
thread runs one logical *process*. A process that completes an op :info is
considered crashed — it never invokes again; the worker reincarnates as
process + concurrency with a freshly opened client. This is what makes
:info ops "open forever" in the history.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from typing import Any, Optional

from .. import obs
from ..clients.base import Client
from ..generators.core import (Gen, GenContext, Pending, Phases, NEMESIS,
                               SECOND)
from ..nemesis.base import Nemesis
from ..ops.op import Op, INVOKE
from ..control.runner import runner_for
from .history import HistoryRecorder

log = logging.getLogger(__name__)


class _RunState:
    """Shared state of the interpreter loop (single event loop — no locks)."""

    def __init__(self, recorder: HistoryRecorder, rng: random.Random):
        self.recorder = recorder
        self.rng = rng
        self.in_flight = 0
        # jtlint: disable=JTL202 -- lifetime argument: _RunState is
        # constructed inside interpret_generators (already on the run's
        # loop) and dies with the run; it can never see a second
        # asyncio.run. ADVICE r5's bug was a primitive CACHED across
        # runs (db/etcd.py), which this is not.
        self.wake = asyncio.Condition()

    async def notify(self):
        async with self.wake:
            self.wake.notify_all()


async def _worker(test: dict, gen: Gen, state: _RunState,
                  worker_id: int, concurrency: int,
                  client_proto: Optional[Client], nemesis: Optional[Nemesis]):
    """One worker: repeatedly ask the generator, invoke, record."""
    is_nemesis = worker_id < 0
    process: Any = NEMESIS if is_nemesis else worker_id
    client: Optional[Client] = None
    nodes = test["nodes"]

    async def ensure_client():
        nonlocal client
        if not is_nemesis and client is None:
            node = nodes[int(process) % len(nodes)]
            client = await client_proto.open(test, node)

    try:
        while True:
            ctx = GenContext(state.recorder.now(), process, state.rng, test)
            out = gen.next_for(ctx)
            if out is None:
                # Exhausted for us. A pending phase barrier may still open a
                # new phase; Phases returns Pending in that window, so a plain
                # None is final.
                return
            if isinstance(out, Pending):
                await _wait(state, out.wake, ctx.time)
                _maybe_open_barrier(gen, state)
                continue
            op: Op = out
            if op.type == "log":
                log.info("%s", op.value)
                continue
            op.process = process
            state.in_flight += 1
            state.recorder.append(op)
            metrics = obs.get_metrics()
            t_op = time.monotonic()
            try:
                if is_nemesis:
                    # A span per fault op (rare, long): the nemesis's own
                    # per-node fault events correlate to it by span id.
                    with obs.get_tracer().span(
                            f"nemesis.{op.f}",
                            nemesis=type(nemesis).__name__):
                        completion = await nemesis.invoke(test, op)
                else:
                    await ensure_client()
                    completion = await client.invoke(test, op)
            except Exception as e:  # client bug or unexpected edge: crash op
                log.exception("invoke crashed for %s", op)
                completion = Op(type="info", f=op.f, value=op.value,
                                process=process, error=f"crash: {e}")
            finally:
                state.in_flight -= 1
            completion.process = process
            state.recorder.append(completion)
            if not is_nemesis:
                # Counters + latency histogram, not per-op spans: client
                # ops are the hot path (rate * concurrency per second).
                # jtlint: disable=JTL107 -- bounded family: completion
                # .type is the closed jepsen op-type set {ok, fail,
                # info} (ops/op.py), three names total.
                metrics.counter(f"runner.ops_{completion.type}").add(1)
                metrics.histogram("runner.op_latency_s").observe(
                    time.monotonic() - t_op)
            if not is_nemesis and completion.type == "info":
                # Process crashed (indeterminate op): reincarnate.
                if client is not None:
                    try:
                        await client.close(test)
                    except Exception:
                        pass
                    client = None
                obs.get_tracer().event(
                    "worker.reincarnate", worker=worker_id,
                    dead_process=int(process),
                    new_process=int(process) + concurrency, f=op.f)
                metrics.counter("runner.reincarnations").add(1)
                process = int(process) + concurrency
            _maybe_open_barrier(gen, state)
            await state.notify()
    finally:
        if client is not None:
            try:
                await client.close(test)
            except Exception:
                pass


async def _wait(state: _RunState, wake: Optional[int], now: int):
    """Sleep until `wake` (relative ns) or until some completion/barrier
    changes the world."""
    if wake is not None:
        delay = max(0.0, (wake - now) / SECOND)
        await asyncio.sleep(min(delay, 0.5) if delay else 0.001)
        return
    async with state.wake:
        try:
            await asyncio.wait_for(state.wake.wait(), timeout=0.2)
        except asyncio.TimeoutError:
            pass


def _maybe_open_barrier(gen: Gen, state: _RunState):
    """Phase barrier: flip to the next phase once nothing is in flight
    (jepsen: all workers must finish phase N before N+1 starts)."""
    if isinstance(gen, Phases) and gen.barrier_pending() \
            and state.in_flight == 0:
        gen.barrier_done()


async def interpret_generators(test: dict, recorder: HistoryRecorder,
                               stop_check=None) -> list[Op]:
    """Run the generator interpreter loop to exhaustion; returns history.

    `stop_check` (--fail-fast, runner check-mode stream): a zero-arg
    callable polled every 50 ms; when it turns true the worker tasks are
    cancelled and the history recorded so far is returned — the
    streamed checker has already falsified the run, so finishing the
    generators would only burn wall clock on a known-invalid test."""
    concurrency = int(test.get("concurrency", 10))
    # Publish the RESOLVED value: thread-identity consumers (generators
    # mapping reincarnated process p + concurrency back to its worker
    # thread, e.g. EachThread/ConcurrentGenerator) must never re-apply
    # their own default.
    test["concurrency"] = concurrency
    rng = random.Random(test.get("seed", 0))
    state = _RunState(recorder, rng)
    gen = test["generator"]
    client_proto = test.get("client")
    nemesis = test.get("nemesis")

    tasks = [asyncio.create_task(
        _worker(test, gen, state, i, concurrency, client_proto, nemesis))
        for i in range(concurrency)]
    if nemesis is not None:
        tasks.append(asyncio.create_task(
            _worker(test, gen, state, -1, concurrency, None, nemesis)))
    if stop_check is None:
        await asyncio.gather(*tasks)
        return recorder.history

    stopped = False

    async def watch():
        nonlocal stopped
        while True:
            await asyncio.sleep(0.05)
            if stop_check():
                stopped = True
                break
            # A worker that crashed outright (not cancelled) must tear
            # the rest down NOW: gather(return_exceptions=True) below
            # would otherwise sit on the exception until every other
            # worker exhausts the generator — the full --time-limit the
            # post-mode gather() surfaces immediately. `stopped` stays
            # False, so the raise-after-gather path re-raises it.
            if any(t.done() and not t.cancelled()
                   and t.exception() is not None for t in tasks):
                break
        # Keep cancelling until every worker is actually done: a lone
        # cancel() can be silently swallowed when it races the worker's
        # own wait_for timeout in _wait (bpo-37658 — the inner waiter
        # completing during cancellation eats the CancelledError on
        # py<=3.10), which would leave gather() blocked until the
        # generator exhausts naturally — the full --time-limit the
        # abort exists to cut short.
        while any(not t.done() for t in tasks):
            for t in tasks:
                if not t.done():
                    t.cancel()
            await asyncio.sleep(0.05)

    watcher = asyncio.create_task(watch())
    try:
        results = await asyncio.gather(*tasks, return_exceptions=True)
    finally:
        watcher.cancel()
        try:
            await watcher
        except asyncio.CancelledError:
            pass
    for r in results:
        if isinstance(r, BaseException) \
                and not isinstance(r, asyncio.CancelledError):
            if stopped:
                # Workers torn down mid-await can surface secondary
                # errors; the abort verdict is already decided.
                log.warning("worker error during fail-fast abort: %r", r)
            else:
                raise r
    if stopped:
        log.info("=== fail-fast: streamed check falsified the run; "
                 "aborting with %d history entries", len(recorder.history))
    return recorder.history


async def _setup_run(test: dict
                     ) -> tuple[Optional[Client], Optional[Nemesis]]:
    """Node + client data-plane + nemesis setup (reference
    Client.setup!, set.clj:15-16) — the run-lifecycle prologue shared
    by run_test and run_workload."""
    await _setup_nodes(test)
    client_proto: Optional[Client] = test.get("client")
    if client_proto is not None:
        c = await client_proto.open(test, test["nodes"][0])
        await c.setup(test)
        await c.close(test)
    nemesis: Optional[Nemesis] = test.get("nemesis")
    if nemesis is not None:
        await nemesis.setup(test)
    return client_proto, nemesis


async def _teardown_run(test: dict, client_proto: Optional[Client],
                        nemesis: Optional[Nemesis], store_dir=None
                        ) -> None:
    """The matching epilogue: nemesis heal -> client data-plane
    teardown -> node teardown (with log download when a store dir is
    given). ONE copy of the ordering — a reorder here serves
    `jepsen-tpu test` and the campaign alike."""
    if nemesis is not None:
        await nemesis.teardown(test)
    if client_proto is not None:
        c = await client_proto.open(test, test["nodes"][0])
        await c.teardown(test)
        await c.close(test)
    await _teardown_nodes(test, store_dir)


async def run_workload(test: dict, recorder: HistoryRecorder,
                       stop_check=None) -> list[Op]:
    """The slim embedding path (campaign/engine.py): client/nemesis
    setup -> generator interpretation -> client/nemesis teardown,
    WITHOUT the store, telemetry capture, or check phase `run_test`
    wraps around it. Callers that run thousands of scenarios (the
    scenario factory) own those concerns in batch: one obs capture per
    campaign, one corpus-batched check per campaign — paying the
    per-run versions thousands of times over is exactly the overhead
    the campaign exists to amortize. The caller supplies the recorder
    (a virtual-clock one for deterministic sim runs) and the optional
    fail-fast `stop_check` (same contract as interpret_generators)."""
    client_proto, nemesis = await _setup_run(test)
    try:
        return await interpret_generators(test, recorder,
                                          stop_check=stop_check)
    finally:
        await _teardown_run(test, client_proto, nemesis)


async def _setup_nodes(test: dict):
    db = test.get("db")
    os_setup = test.get("os_setup")

    async def setup_one(node):
        r = runner_for(test, node)
        if os_setup is not None:
            await os_setup(r, node)
        if db is not None:
            await db.setup(test, r, node)

    await asyncio.gather(*(setup_one(n) for n in test["nodes"]))


async def _teardown_nodes(test: dict, store_dir=None):
    db = test.get("db")
    if db is None:
        return
    async def teardown_one(node):
        r = runner_for(test, node)
        if store_dir is not None:
            for remote in db.log_files(test, node):
                local = store_dir / f"{node}-{remote.rsplit('/', 1)[-1]}"
                dl = getattr(r, "download", None)
                if dl is not None:
                    await dl(remote, str(local))
        await db.teardown(test, r, node)
    await asyncio.gather(*(teardown_one(n) for n in test["nodes"]))


async def run_test(test: dict) -> dict:
    """Execute a full test; returns the result map (with "valid").

    Opens a telemetry capture for the run's lifetime: phase spans
    (setup/run/teardown/check/store), worker/kernel metrics, and nemesis
    fault events land in <run_dir>/telemetry.jsonl + metrics.json next
    to the other store artifacts (obs/__init__.py; JEPSEN_TPU_TELEMETRY=0
    disables)."""
    from ..store import Store

    store = None
    log_handler = None
    if test.get("store_root") is not None:
        store = Store(test["store_root"]).new_run(test.get("name", "test"))
        log_handler = _attach_file_log(store.path)
    with obs.capture(store.path if store else None):
        try:
            return await _run_test_inner(test, store)
        finally:
            # Detach per-run file handler so later runs in the same process
            # (--test-count > 1) don't keep appending to this run's
            # jepsen.log.
            if log_handler is not None:
                _detach_file_log(log_handler)


async def _run_test_inner(test: dict, store) -> dict:
    tracer = obs.get_tracer()
    log.info("=== %s: setting up %d nodes", test.get("name"),
             len(test["nodes"]))
    t0 = time.monotonic()
    with tracer.span("setup", nodes=len(test["nodes"]),
                     workload=str(test.get("workload", ""))):
        client_proto, nemesis = await _setup_run(test)

    log.info("=== running workload")
    # Streaming check mode (ISSUE 5): the recorder's listener feeds a
    # background session that watermark-encodes and chunk-dispatches the
    # stable prefix into the resumable dense sweep WHILE workers run;
    # the check phase below becomes drain + finalize. Post remains the
    # default with zero behavior change; a non-streamable checker
    # topology falls back to post (stream/engine.session_for_test).
    check_mode = str(test.get("check_mode") or "post").lower()
    fail_fast = bool(test.get("fail_fast"))
    session = None
    if check_mode == "stream":
        from ..stream import session_for_test

        session = session_for_test(test)
        if session is None:
            log.info("=== check-mode stream unavailable for this checker; "
                     "running post-hoc")
        elif fail_fast:
            # Keys the workload rotates away from would otherwise hold
            # their last partial chunk unswept until the final drain —
            # at production chunk sizes the abort could never fire.
            session.enable_eager_flush()
    recorder = HistoryRecorder(listener=session.feed if session else None)

    def stop_check():
        # Fail-fast trigger: the streamed frontier falsified the run.
        if session.falsified():
            session.aborted = True
            return True
        return False

    try:
        with tracer.span("run",
                         concurrency=int(test.get("concurrency", 10)),
                         check_mode="stream" if session else "post") as sp:
            history = await interpret_generators(
                test, recorder,
                stop_check=stop_check if (session and fail_fast) else None)
            sp.set(history_entries=len(history))
    finally:
        if session is not None:
            # Close the stream's overlap window; the drain continues on
            # its own thread underneath the teardown below.
            session.finish_input()
        with tracer.span("teardown"):
            await _teardown_run(test, client_proto, nemesis,
                                store.path if store else None)

    run_s = time.monotonic() - t0
    log.info("=== run complete: %d history entries in %.1fs; checking",
             len(history), run_s)

    checker = test.get("checker")
    opts = {"store_dir": str(store.path)} if store else {}
    # Check phase compiles WGL kernels: point jax's persistent compile
    # cache under the store first (sched/compile_cache.py; idempotent —
    # a CLI-level enable wins), so embedding callers of run_test get the
    # cross-process compile reuse too, not only `jepsen-tpu test`.
    from ..sched import enable_persistent_cache

    enable_persistent_cache(test.get("store_root"))
    # Backend health (obs/health.py): the check phase periodically
    # drives the supervisor's active probe (rate-limited — a fresh
    # process never pays the subprocess inside its first interval), and
    # a completed check is a passive health proof. The supervisor's
    # state feeds /healthz and the bench record.
    supervisor = obs.health.get_supervisor()
    supervisor.maybe_probe(source="runner.check")
    with tracer.span("check") as sp, \
            obs.maybe_jax_trace(store.path if store else None):
        if session is not None:
            # Drain + finalize: most of the check already happened
            # during the run; valid streamed verdicts settle their keys
            # in the checkers below, invalid keys re-run post-hoc for
            # witness reconstruction.
            with tracer.span("check.stream_drain"):
                stream_results = session.finalize()
            if stream_results is not None:
                opts["stream_results"] = stream_results
        result = (checker.check(test, history, opts)
                  if checker is not None else {"valid": True})
        sp.set(valid=str(result.get("valid")),
               profile=obs.active_profile_hash())
        if checker is not None:
            supervisor.note_ok(source="runner.check")
    result.setdefault("op_count",
                      sum(1 for o in history if o.type == INVOKE))
    result["run_seconds"] = run_s
    result["check_mode"] = "stream" if session is not None else "post"
    if session is not None:
        result["stream"] = session.stats()
    # Which tuning profile the check resolved (ISSUE 4): hash + every
    # non-default KernelLimits field with its provenance tag — lands in
    # results.json so the web run index can say which profile produced
    # each verdict/throughput figure.
    try:
        from ..tune.profile import run_record

        result["profile"] = run_record()
    except Exception:
        pass   # profile stamping is observability, never a failure mode

    if store is not None:
        with tracer.span("store"):
            store.write_run(test, history, result)
        log.info("=== stored run at %s", store.path)
    log.info("=== valid: %s", result.get("valid"))
    return result


def _attach_file_log(store_path) -> logging.Handler:
    """Tee the framework log into the run dir (reference: logback writes
    jepsen.log into the store [dep], SURVEY.md §5.5). Caller must detach
    with _detach_file_log. The run log always captures INFO regardless of
    the embedding app's root level (the artifact must be useful even when
    the host process never configured logging)."""
    root = logging.getLogger()
    handler = logging.FileHandler(store_path / "jepsen.log")
    handler.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname)s %(name)s: %(message)s"))
    handler.setLevel(logging.INFO)
    handler._prev_root_level = root.level  # restored on detach
    if root.getEffectiveLevel() > logging.INFO:
        root.setLevel(logging.INFO)
    root.addHandler(handler)
    return handler


def _detach_file_log(handler: logging.Handler) -> None:
    root = logging.getLogger()
    root.removeHandler(handler)
    root.setLevel(handler._prev_root_level)
    handler.close()
