"""History recorder: the single synchronization point of a run.

The recorded history is jepsen's central artifact — an ordered vector of
invoke/complete entries with process ids and timestamps [dep: jepsen core
recorder]. Append assigns the index and relative-time fields. All appends
happen on the one event loop, so ordering is the loop's scheduling order —
the same "real time" order a concurrent checker needs.
"""

from __future__ import annotations

import time
from typing import Optional

from ..ops.op import Op


class HistoryRecorder:
    def __init__(self, start_ns: Optional[int] = None):
        self.start_ns = start_ns if start_ns is not None else time.monotonic_ns()
        self.entries: list[Op] = []

    def now(self) -> int:
        """Relative ns since test start."""
        return time.monotonic_ns() - self.start_ns

    def append(self, op: Op) -> Op:
        op.index = len(self.entries)
        op.time = self.now()
        self.entries.append(op)
        return op

    @property
    def history(self) -> list[Op]:
        return self.entries
