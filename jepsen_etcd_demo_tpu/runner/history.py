"""History recorder: the single synchronization point of a run.

The recorded history is jepsen's central artifact — an ordered vector of
invoke/complete entries with process ids and timestamps [dep: jepsen core
recorder]. Append assigns the index and relative-time fields. All appends
happen on the one event loop, so ordering is the loop's scheduling order —
the same "real time" order a concurrent checker needs.

Two streaming-check additions (ISSUE 5):

  * every appended entry is stamped with a monotonic per-op ``seq``
    from a process-local counter. ``time`` (monotonic_ns) is
    NON-DECREASING but can tie under thread-scheduling jitter; the
    streaming checker's stable-prefix watermark needs a strict total
    order, and ``seq`` is that order (it coincides with ``index`` for
    an unfiltered history, but survives filtering/splitting).
  * an optional ``listener`` is invoked with each entry AFTER it is
    fully stamped — the feed point of the streaming check engine
    (stream/engine.py). A listener must be O(enqueue) cheap (it runs on
    the event loop); a listener that raises is detached, never allowed
    to take the run down.
"""

from __future__ import annotations

import itertools
import logging
import time
from typing import Callable, Optional

from ..ops.op import Op

log = logging.getLogger(__name__)


class HistoryRecorder:
    def __init__(self, start_ns: Optional[int] = None,
                 listener: Optional[Callable[[Op], None]] = None):
        self.start_ns = start_ns if start_ns is not None else time.monotonic_ns()
        self.entries: list[Op] = []
        self.listener = listener
        self._seq = itertools.count()

    def now(self) -> int:
        """Relative ns since test start."""
        return time.monotonic_ns() - self.start_ns

    def append(self, op: Op) -> Op:
        op.index = len(self.entries)
        op.seq = next(self._seq)
        op.time = self.now()
        self.entries.append(op)
        if self.listener is not None:
            try:
                self.listener(op)
            except Exception:
                log.exception("history listener failed; detaching it")
                self.listener = None
        return op

    @property
    def history(self) -> list[Op]:
        return self.entries
