"""CLI: `test`, `analyze`, and `serve` subcommands.

Mirror of the reference's entry (src/jepsen/etcdemo.clj:192-199: cli/run!
over single-test-cmd + serve-cmd) with the demo's four flags
(-q/--quorum, -r/--rate, --ops-per-key, -w/--workload; :177-190) plus the
framework-standard flags the test-map merge supplies (--nodes, --time-limit,
--concurrency, --test-count, --username, --password, --ssh-port,
--private-key; :147-152 docstring + noop-test [dep]). `analyze` is the
stored-history re-check flow (check is re-runnable without re-running the
cluster, SURVEY.md §5.4); the reference demo itself doesn't expose it but
jepsen does.

Exit code contract: nonzero iff a test's result is not valid (jepsen's run!
behavior [dep])."""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import sys
from typing import Optional, Sequence

from ..compose import WORKLOADS, etcd_test, fake_test
from ..runner import run_test


def positive_float(s: str) -> float:
    v = float(s)
    if v <= 0:
        # the reference validates "must be a positive number" (:183)
        raise argparse.ArgumentTypeError("must be a positive number")
    return v


def nonnegative_float(s: str) -> float:
    v = float(s)
    if v < 0:
        raise argparse.ArgumentTypeError("must be >= 0 (0 = unbounded)")
    return v


def positive_int(s: str) -> int:
    v = int(s)
    if v <= 0:
        raise argparse.ArgumentTypeError("must be a positive integer")
    return v


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="jepsen-tpu",
        description="TPU-native distributed-systems correctness harness")
    sub = p.add_subparsers(dest="command", required=True)

    t = sub.add_parser("test", help="run a test")
    t.add_argument("-w", "--workload", required=True,
                   choices=sorted(WORKLOADS),
                   help="test workload to run (required, like the "
                        "reference's -w)")
    t.add_argument("-q", "--quorum", action="store_true", default=False,
                   help="use quorum reads (default false)")
    t.add_argument("-r", "--rate", type=positive_float, default=10.0,
                   metavar="HZ", help="approximate request rate (default 10)")
    t.add_argument("--ops-per-key", type=positive_int, default=100,
                   help="ops per key before rotating (default 100)")
    t.add_argument("--nodes", default="n1,n2,n3,n4,n5",
                   help="comma-separated node list")
    t.add_argument("--nodes-file", default=None,
                   help="file with one node per line (overrides --nodes; "
                        "the jepsen-standard flag)")
    t.add_argument("--time-limit", type=positive_float, default=30.0,
                   help="main-phase wall clock budget in seconds")
    t.add_argument("--recovery-wait", type=nonnegative_float, default=10.0,
                   help="quiet window after healing before the final "
                        "phase (seconds; the reference's post-nemesis "
                        "sleep — hermetic runs can shrink it, the "
                        "in-process fake heals instantly)")
    t.add_argument("--concurrency", type=positive_int, default=10,
                   help="client worker count")
    t.add_argument("--test-count", type=positive_int, default=1,
                   help="number of times to run the test")
    t.add_argument("--username", default="root", help="ssh username")
    t.add_argument("--private-key", default=None, help="ssh identity file")
    t.add_argument("--password", default=None,
                   help="ssh password (jepsen's standard flag; rides "
                        "sshpass — the password travels via the SSHPASS "
                        "env var, never on a visible argv)")
    t.add_argument("--ssh-port", type=positive_int, default=22,
                   help="ssh port on every node (jepsen's standard flag; "
                        "also makes a non-22 throwaway sshd reachable "
                        "through the product surface)")
    t.add_argument("--seed", type=int, default=0,
                   help="schedule/value rng seed (determinism!)")
    t.add_argument("--store", default="store", help="results store root")
    t.add_argument("--fake", action="store_true",
                   help="hermetic run against the in-process fake cluster "
                        "(no ssh/etcd)")
    t.add_argument("--no-nemesis", action="store_true",
                   help="disable fault injection")
    t.add_argument("--nemesis", default="partition",
                   choices=["partition", "partition-node",
                            "partition-bridge", "partition-ring",
                            "clock", "clock-strobe", "kill", "pause",
                            "noop"],
                   help="fault to inject on the nemesis channel "
                        "(kill/pause, clock-strobe and "
                        "partition-bridge/-ring need a real DB, "
                        "not --fake)")
    t.add_argument("--version", default="v3.1.5",
                   help="etcd version to install")
    t.add_argument("--stale-read-prob", type=float, default=0.0,
                   help="[fake] inject stale non-quorum reads")
    t.add_argument("--lost-write-prob", type=float, default=0.0,
                   help="[fake] inject acked-but-lost updates")
    t.add_argument("--check-mode", default="post",
                   choices=["post", "stream"],
                   help="post (default): record the full history, then "
                        "check it — unchanged behavior. stream: overlap "
                        "the linearizability check with the live run "
                        "(stable-prefix chunk dispatch, stream/; the "
                        "check phase becomes drain+finalize; verdicts "
                        "are bit-identical). Non-streamable workloads "
                        "fall back to post.")
    t.add_argument("--fail-fast", action="store_true",
                   help="with --check-mode stream: tear the test down "
                        "the moment the streamed frontier falsifies the "
                        "history (detection lag bounded by the "
                        "stream_max_lag_chunks knob) instead of running "
                        "the full --time-limit")
    t.add_argument("--check-budget-s", type=nonnegative_float, default=120.0,
                   help="wall-clock bound per linearizability search "
                        "(0 = unbounded); expiry yields the tri-state "
                        "'unknown' verdict instead of grinding on "
                        "combinatorial frontiers")
    t.add_argument("--elle-realtime", action="store_true",
                   help="append/txnregister workloads: assert STRICT "
                        "serializability (wall-clock order joins the elle "
                        "dependency graph)")
    t.add_argument("--duplicate-cas-prob", type=float, default=0.0,
                   help="[fake] a failed CAS may actually have applied")
    t.add_argument("--reorder-prob", type=float, default=0.0,
                   help="[fake] queue dequeues pop a random position "
                        "(FIFO violation)")
    t.add_argument("--duplicate-delivery-prob", type=float, default=0.0,
                   help="[fake] queue dequeues deliver without removing")
    t.add_argument("--live-port", type=positive_int, default=None,
                   metavar="PORT",
                   help="serve the live observability plane from THIS "
                        "process while the test runs: /live (SSE "
                        "in-flight view), /metrics (Prometheus), "
                        "/healthz (backend supervisor) plus the normal "
                        "store index on 127.0.0.1:PORT")
    _add_sweep_mode_flag(t)
    _add_mesh_shape_flag(t)

    a = sub.add_parser("analyze", help="re-check a stored history")
    a.add_argument("run_dir", help="store/<name>/<ts> directory")
    a.add_argument("-w", "--workload", default=None,
                   choices=sorted(WORKLOADS),
                   help="default: the workload the run's test.json records")
    a.add_argument("--model", default=None,
                   help="linearizability model (default: the workload's — "
                        "cas-register for register, fifo-queue for queue)")
    a.add_argument("--backend", default="jax", choices=["jax", "oracle"])
    a.add_argument("--no-encode-cache", action="store_true",
                   help="disable the content-addressed encoded-tensor "
                        "cache (re-encode from history.jsonl every time)")
    _add_sweep_mode_flag(a)
    _add_mesh_shape_flag(a)

    c = sub.add_parser(
        "corpus",
        help="re-check EVERY stored run's per-key histories in one "
             "batched kernel launch (corpus replay)")
    c.add_argument("store_root", help="results store root directory")
    c.add_argument("--model", default="cas-register")
    c.add_argument("--reencode", action="store_true",
                   help="re-encode from history.jsonl instead of loading "
                        "stored history-*.npz tensors")
    c.add_argument("--no-encode-cache", action="store_true",
                   help="disable the content-addressed encoded-tensor "
                        "cache for the re-encode path")
    # DCN multislice (BASELINE configs[4]): every participating host runs
    # the SAME corpus command against the same store, plus these flags;
    # the batch shards over the ("slice", "batch") mesh and every process
    # prints the identical gathered verdict.
    c.add_argument("--coordinator", metavar="HOST:PORT",
                   help="jax.distributed coordinator address; enables "
                        "multi-process (DCN multislice) corpus sharding")
    c.add_argument("--num-processes", type=int, default=1,
                   help="total processes in the multislice run")
    c.add_argument("--process-id", type=int, default=0,
                   help="this process's rank [0, num-processes)")
    c.add_argument("--local-devices", type=int, default=None,
                   help="simulate with N virtual CPU devices per process "
                        "(CI / one-machine dryrun)")
    _add_sweep_mode_flag(c)
    _add_mesh_shape_flag(c)

    g = sub.add_parser(
        "campaign",
        help="the scenario factory (campaign/; doc/campaign.md): sample "
             "N deterministic scenarios over the generator algebra, run "
             "them against in-process clusters, corpus-batch-check "
             "everything, dedupe falsifying runs by anomaly signature, "
             "ddmin-shrink one witness per signature at TPU parallelism "
             "and bank the minimal counterexamples under store/corpus/")
    g.add_argument("--specs", type=positive_int, default=256,
                   help="scenario count (default 256)")
    g.add_argument("--seed", type=int, default=0,
                   help="campaign seed: same seed -> same spec list -> "
                        "same verdicts, signatures and minimal "
                        "witnesses (determinism!)")
    g.add_argument("--families", default=None,
                   help="comma-separated workload families (default: "
                        "register,gset,queue,multiregister)")
    g.add_argument("--bug-rate", type=float, default=0.25,
                   help="fraction of specs carrying a seeded injectable "
                        "bug (default 0.25)")
    g.add_argument("--live", type=int, default=0,
                   help="how many specs run against a live in-process "
                        "minietcd cluster (real HTTP, stream fail-fast, "
                        "the member-churn/disk-fault/lease-skew planes; "
                        "default 0 — sim only)")
    g.add_argument("--scale", type=positive_float, default=1.0,
                   help="schedule-size multiplier (bench smokes use <1)")
    g.add_argument("--workers", type=positive_int, default=4,
                   help="executor threads for sim scenarios (default 4)")
    g.add_argument("--route", default="direct",
                   choices=["direct", "serve"],
                   help="check route: direct = sched.check_corpus on "
                        "the warm pool (default); serve = submit every "
                        "wave to the continuous-batching scheduler as "
                        "the 'campaign' tenant")
    g.add_argument("--no-shrink", action="store_true",
                   help="triage only — skip the ddmin shrinker")
    g.add_argument("--no-bank", action="store_true",
                   help="do not persist minimal witnesses")
    g.add_argument("--max-shrink-checks", type=positive_int, default=4096,
                   help="candidate-recheck budget per shrink "
                        "(default 4096)")
    g.add_argument("--replay-corpus", action="store_true",
                   help="skip the campaign: re-falsify every banked "
                        "witness under store/corpus/ in one batched "
                        "launch per model; exit 1 if any no longer "
                        "falsifies")
    g.add_argument("--store", default="store",
                   help="results store root (the corpus bank lives at "
                        "<store>/corpus/)")
    _add_sweep_mode_flag(g)
    _add_mesh_shape_flag(g)

    u = sub.add_parser(
        "tune",
        help="autotune the KernelLimits knob space on THIS machine and "
             "persist a tuning profile (tune/; doc/perf.md 'Autotuning')")
    u.add_argument("--budget-s", type=positive_float, default=120.0,
                   help="wall-clock probe budget; expiry keeps defaults "
                        "for un-probed knobs (default 120)")
    u.add_argument("--knobs", default=None,
                   help="comma-separated KernelLimits field or probe-"
                        "group names (default: every knob with a probe "
                        "group; groups: dense_sweep, sparse, sched, "
                        "pipeline, pallas, stream, pod)")
    u.add_argument("--repeats", type=positive_int, default=2,
                   help="best-of repeats per measurement (default 2)")
    u.add_argument("--scale", type=positive_float, default=1.0,
                   help="probe fixture size multiplier (CI smokes use "
                        "~0.1; default 1.0)")
    u.add_argument("--dry-run", action="store_true",
                   help="measure and print, persist nothing")
    u.add_argument("--print-profile", action="store_true",
                   help="print the RESOLVED active limits with per-field "
                        "provenance (env/set/tuned/default) and exit — "
                        "no probing (tools/print_profile.py equivalent)")
    u.add_argument("--store", default="store",
                   help="results store root (locates the persistent "
                        "compile cache the probes warm)")

    s = sub.add_parser(
        "serve",
        help="serve the results store over http (plus /live, /metrics, "
             "/healthz — live data needs the runner in-process: "
             "`jepsen-tpu test --live-port`); --check additionally runs "
             "the checking-as-a-service daemon (serve/; doc/serve.md)")
    s.add_argument("--port", type=int, default=8080,
                   help="0 = ephemeral; the bound port is printed as one "
                        "JSON line at startup")
    s.add_argument("--host", default="127.0.0.1")
    s.add_argument("--store", default="store")
    s.add_argument("--check", action="store_true",
                   help="checking-as-a-service: accept histories over "
                        "HTTP (POST /check, /serve/session) and verify "
                        "them on the continuous-batching scheduler over "
                        "the process-wide warm-kernel pool; verdicts "
                        "land in the store as browsable runs")
    s.add_argument("--model", default="cas-register",
                   help="[--check] default linearizability model for "
                        "requests that don't name one")
    s.add_argument("--coalesce-ms", type=int, default=None,
                   metavar="MS",
                   help="[--check] max-linger of the coalescing "
                        "scheduler (default: limits().serve_coalesce_ms "
                        "— env/tuned-profile resolved)")
    s.add_argument("--max-batch", type=positive_int, default=None,
                   help="[--check] requests per coalesced batch "
                        "(default: limits().serve_max_batch)")
    s.add_argument("--max-inflight", type=positive_int, default=None,
                   help="[--check] per-tenant admitted-request bound "
                        "(default: limits().serve_max_inflight)")
    s.add_argument("--ready-file", default=None,
                   help="[--check] also write the startup JSON (port, "
                        "url) to this file once bound")
    s.add_argument("--fleet", action="store_true",
                   help="[--check] fleet mode: spawn N serve replicas "
                        "and route requests by (model, step bucket) via "
                        "rendezvous hashing with health-aware spillover "
                        "and zero-downtime restarts (serve/fleet.py; "
                        "doc/serve.md 'Fleet')")
    s.add_argument("--replicas", type=positive_int, default=None,
                   help="[--fleet] replica count (default: "
                        "limits().fleet_replicas)")

    wu = sub.add_parser(
        "warmup",
        help="pre-compile the plan-family corpus into the persistent "
             "XLA cache (sched/warmup.py; doc/perf.md 'Pod "
             "efficiency') — run once from a blessed host so fleet "
             "cold-compiles never land on the dispatch critical path")
    wu.add_argument("--rungs", type=positive_int, default=2,
                    help="step-bucket ladder rungs to compile, from the "
                         "tuned floor (default 2)")
    wu.add_argument("--k-slots", type=positive_int, default=16,
                    help="concurrency-slot geometry to warm (default 16)")
    wu.add_argument("--no-encoder", action="store_true",
                    help="skip the device-side encoder family")
    wu.add_argument("--store", default="store",
                    help="results store root (locates the persistent "
                         "compile cache at <store>/.xla-cache)")
    _add_mesh_shape_flag(wu)

    pl = sub.add_parser(
        "plan",
        help="dump the resolved KernelPlan registry + provenance for a "
             "kernel family (plan/; doc/perf.md 'KernelPlan & "
             "pod-scale') — the plan layer's tools/print_profile.py")
    pl.add_argument("--family", default=None,
                    help="one kernel family (contracts.json name, e.g. "
                         "wgl3-chunk); default: every family")
    pl.add_argument("--print", action="store_true", dest="print_plan",
                    help="(default — the verb only prints)")

    # Stub for --help only: `lint` is intercepted in main() BEFORE this
    # parser runs, so the jtlint path never imports the run/check stack
    # (analysis/ is jax-free and must stay fast — tier-1 runs it).
    sub.add_parser(
        "lint", add_help=False,
        help="jtlint: JAX kernel hygiene + concurrency + jtflow "
             "cross-module contract analysis (doc/analysis.md; "
             "--strict gates tier-1, --changed/--format sarif for CI, "
             "--contracts/--write-contracts for contracts.json)")
    return p


# --sweep-mode values -> limits().sparse_mode (ops/limits.py): the
# sparse active-tile engine's dense/sparse routing for the dense lattice
# kernels (ops/wgl3_sparse.py; doc/perf.md "Sparse sweeps").
SWEEP_MODES = {"auto": 0, "dense": 1, "sparse": 2}


# The env var parallel/mesh.py reads for the default N-D mesh shape
# (duplicated as a literal here so the CLI layer stays jax-free until a
# command actually runs; parallel.mesh.MESH_SHAPE_ENV is the authority).
MESH_SHAPE_ENV = "JEPSEN_TPU_MESH_SHAPE"

# What _apply_mesh_shape displaced (same restore discipline as the
# sweep-mode flag: no cross-invocation leak, operator exports survive).
_MESH_ENV_DISPLACED: tuple | None = None


def _add_mesh_shape_flag(parser) -> None:
    parser.add_argument(
        "--mesh-shape", default=None, metavar="HxC",
        help="N-D device mesh shape for the sharded lanes, outer axis "
             "first (e.g. 2x4 = 2 hosts x 4 chips; plain N = 1-D). "
             "Elastic: more devices requested than visible re-derives "
             "the largest valid shape instead of failing "
             "(parallel/mesh.py; doc/perf.md 'KernelPlan & pod-scale')")


def _apply_mesh_shape(args) -> None:
    global _MESH_ENV_DISPLACED
    import os

    spec = getattr(args, "mesh_shape", None)
    if spec is None:
        if _MESH_ENV_DISPLACED is not None:
            (orig,) = _MESH_ENV_DISPLACED
            if orig is None:
                os.environ.pop(MESH_SHAPE_ENV, None)
            else:
                os.environ[MESH_SHAPE_ENV] = orig
            _MESH_ENV_DISPLACED = None
        return
    # Grammar check WITHOUT importing parallel.mesh (which imports jax):
    # the mesh builders re-parse through parse_mesh_shape at use time.
    parts = spec.lower().split("x")
    if not parts or not all(pt.isdigit() and int(pt) >= 1 for pt in parts):
        raise SystemExit(f"error: --mesh-shape {spec!r} is not NxM "
                         f"positive integers (e.g. 2x4)")
    if len(parts) > 2:
        # The sharded lanes build at most 2-D ("host", chips) meshes —
        # fail here with the flag named, not from inside jax Mesh
        # construction mid-run.
        raise SystemExit(f"error: --mesh-shape {spec!r} has "
                         f"{len(parts)} dimensions; at most 2 (HxC) "
                         f"are supported")
    if _MESH_ENV_DISPLACED is None:
        _MESH_ENV_DISPLACED = (os.environ.get(MESH_SHAPE_ENV),)
    os.environ[MESH_SHAPE_ENV] = spec


def _add_sweep_mode_flag(parser) -> None:
    parser.add_argument(
        "--sweep-mode", default=None, choices=sorted(SWEEP_MODES),
        help="dense-lattice sweep engine: auto = sparse active-tile "
             "sweeps on eligible geometries with the density-threshold "
             "crossover (default), dense = sparse engine off, sparse = "
             "prefer sparse rounds regardless of density (the bench/"
             "debug lane). Verdicts are bit-identical in every mode.")


# What _apply_sweep_mode displaced, so a later in-process invocation
# WITHOUT --sweep-mode restores it (None = nothing displaced yet;
# (original,) = the operator's prior env value or None). The flag must
# not leak across cli.main() calls, nor permanently clobber an
# operator-exported JEPSEN_TPU_LIMIT_SPARSE_MODE.
_SWEEP_ENV_DISPLACED: tuple | None = None


def _apply_sweep_mode(args) -> None:
    global _SWEEP_ENV_DISPLACED
    import os

    from ..ops import limits as limits_mod

    var = limits_mod.env_var("sparse_mode")
    mode = getattr(args, "sweep_mode", None)
    if mode is None:
        if _SWEEP_ENV_DISPLACED is not None:
            (orig,) = _SWEEP_ENV_DISPLACED
            if orig is None:
                os.environ.pop(var, None)
            else:
                os.environ[var] = orig
            _SWEEP_ENV_DISPLACED = None
            limits_mod._reload()
        return
    # Through the ENV layer, not set_limits: this runs before any jax
    # backend exists, so a set_limits(replace(limits(), ...)) here would
    # freeze a resolution snapshot that can never include the machine's
    # tuned profile (ops/limits.py loads it lazily once jax is up). An
    # env override composes — it pins exactly this one field (provenance
    # "env", inherited by subprocesses, which is what a CLI-wide mode
    # switch means) and still lets the tuned profile drive the rest.
    if _SWEEP_ENV_DISPLACED is None:
        _SWEEP_ENV_DISPLACED = (os.environ.get(var),)
    os.environ[var] = str(SWEEP_MODES[mode])
    limits_mod._reload()


def _read_nodes(args) -> list[str]:
    if args.nodes_file:
        with open(args.nodes_file) as f:
            return [ln.strip() for ln in f if ln.strip()]
    return [n.strip() for n in args.nodes.split(",") if n.strip()]


def _test_opts(args) -> dict:
    return {
        "workload": args.workload,
        "quorum": args.quorum,
        "rate": args.rate,
        "ops_per_key": args.ops_per_key,
        "nodes": _read_nodes(args),
        "time_limit": args.time_limit,
        "recovery_wait": args.recovery_wait,
        "concurrency": args.concurrency,
        "seed": args.seed,
        "store_root": args.store,
        "no_nemesis": args.no_nemesis,
        "nemesis": args.nemesis,
        "version": args.version,
        "ssh": {"username": args.username, "private_key": args.private_key,
                "password": args.password, "port": args.ssh_port},
        "stale_read_prob": args.stale_read_prob,
        "lost_write_prob": args.lost_write_prob,
        "duplicate_cas_prob": args.duplicate_cas_prob,
        "elle_realtime": args.elle_realtime,
        "check_budget_s": args.check_budget_s,
        "reorder_prob": args.reorder_prob,
        "duplicate_delivery_prob": args.duplicate_delivery_prob,
        "check_mode": args.check_mode,
        "fail_fast": args.fail_fast,
    }


def cmd_test(args) -> int:
    enable_compilation_cache(args.store)
    _apply_sweep_mode(args)
    _apply_mesh_shape(args)
    live_server = None
    if getattr(args, "live_port", None):
        # The live observability plane (web/server.py, ISSUE 8) only
        # shows a run in flight when it shares the runner's process —
        # serve it for the duration of the test loop.
        import threading

        from http.server import ThreadingHTTPServer

        from ..web.server import make_handler

        live_server = ThreadingHTTPServer(("127.0.0.1", args.live_port),
                                          make_handler(args.store))
        threading.Thread(target=live_server.serve_forever,
                         name="live-plane", daemon=True).start()
        print(f"# live plane on http://127.0.0.1:{args.live_port}/live "
              f"(/metrics, /healthz)", file=sys.stderr)
    rc = 0
    try:
        for i in range(args.test_count):
            opts = _test_opts(args)
            opts["seed"] = args.seed + i
            test = fake_test(opts) if args.fake else etcd_test(opts)
            result = asyncio.run(run_test(test))
            print(json.dumps({"valid": result.get("valid"),
                              "op_count": result.get("op_count"),
                              "run_seconds": round(
                                  result.get("run_seconds", 0), 2)}))
            if result.get("valid") is not True:
                rc = 1
    finally:
        if live_server is not None:
            live_server.shutdown()
            live_server.server_close()
    return rc


def cmd_analyze(args) -> int:
    from ..store import encode_cache
    from ..store.store import RunDir
    from ..checkers import (Compose, ElleChecker, IndependentChecker,
                            Linearizable, SetChecker, TimelineChecker)
    from ..checkers.perf import PerfChecker

    enable_compilation_cache()
    _apply_sweep_mode(args)
    _apply_mesh_shape(args)
    run = RunDir(args.run_dir)
    history = run.read_history()
    try:
        stored_test = run.read_test()
    except (ValueError, OSError) as e:
        print(f"# warning: cannot read test.json ({e}); assuming register "
              f"workload, serializable elle", file=sys.stderr)
        stored_test = {}
    workload = args.workload or stored_test.get("workload", "register")
    model = args.model or CORPUS_MODELS.get(workload, "cas-register")
    # Re-check under the run's own search budget (combinatorial mutex
    # histories would otherwise grind unbounded on analyze).
    from ..compose import check_budget
    budget = check_budget(stored_test)
    if workload == "set":
        sub = SetChecker()
        checker = Compose({"perf": PerfChecker(), "indep": sub})
    elif workload in WHOLE_HISTORY_MODELS:
        # One whole-run history — no independent-key split.
        checker = Compose({"perf": PerfChecker(),
                           "indep": Compose({
                               "linear": Linearizable(
                                   args.model or
                                   WHOLE_HISTORY_MODELS[workload],
                                   backend=args.backend,
                                   time_budget_s=budget),
                               "timeline": TimelineChecker()})})
    elif workload in ("append", "txnregister"):
        # Re-check under the same strictness the run recorded (a strict-
        # serializability run must not silently downgrade on analyze).
        from ..checkers.elle import ElleRwChecker

        elle_cls = ElleChecker if workload == "append" else ElleRwChecker
        checker = Compose({"perf": PerfChecker(),
                           "indep": Compose({
                               "elle": elle_cls(realtime=bool(
                                   stored_test.get("elle_realtime"))),
                               "timeline": TimelineChecker()})})
    else:
        checker = Compose({"perf": PerfChecker(),
                           "indep": IndependentChecker(Compose({
                               "linear": Linearizable(
                                   model, backend=args.backend,
                                   time_budget_s=budget),
                               "timeline": TimelineChecker()}))})
    # Encoded-tensor cache in the run dir: re-analyzing the same run
    # skips the host re-encode (--no-encode-cache restores the old path).
    cache_root = (None if args.no_encode_cache
                  else run.path / encode_cache.CACHE_DIRNAME)
    with encode_cache.activated(cache_root):
        result = checker.check({}, history, {"store_dir": str(run.path)})
    run.write_results(result)
    print(json.dumps({"valid": result.get("valid")}))
    return 0 if result.get("valid") is True else 1


# Which linearizability model re-checks a stored run's per-key histories,
# by the workload recorded in its test.json. Workloads whose checker is
# not per-key linearizability (set durability, elle, the whole-history
# models below) are skipped by `corpus`.
CORPUS_MODELS = {"register": "cas-register", "queue": "fifo-queue"}

# Workloads checked as ONE whole-run history (no independent-key split),
# and the model each re-checks under.
WHOLE_HISTORY_MODELS = {"multiregister": "multi-register", "gset": "gset",
                        "mutex": "mutex"}


def cmd_corpus(args) -> int:
    """Corpus replay (BASELINE configs[4]): gather every stored run's
    per-key histories and verify them in ONE batched launch of the dense
    kernel per model — the framework's answer to re-checking a store full
    of histories after a checker change. Each run's model comes from the
    workload its test.json records (--model overrides it for register
    runs only, preserving `corpus <root> --model register` style checks).

    Histories load from the stored device-plane tensors (history-*.npz,
    SURVEY.md §5.4) when present and model-matching — no host re-encode;
    --reencode forces the JSONL path (e.g. after an encoder fix), with a
    content-addressed encode cache under the store so replaying an
    unchanged store re-encodes nothing (--no-encode-cache disables).
    Batched launches route through the corpus throughput engine
    (sched/engine.py): length-bucketed, shape-cached, padding-bounded."""
    import contextlib

    # Multislice first: jax.distributed must initialize before ANY backend
    # use (the store/encode imports below never touch a device).
    multislice = args.coordinator is not None
    if multislice:
        from ..parallel.multislice import init_multislice

        init_multislice(args.coordinator, args.num_processes,
                        args.process_id, local_devices=args.local_devices)

    from ..store import encode_cache
    from ..store.store import Store

    enable_compilation_cache(args.store_root)
    _apply_sweep_mode(args)
    _apply_mesh_shape(args)
    # --reencode means "re-encode from source" — it must bypass cache
    # LOOKUPS too (an encoder fix is its stated purpose), while still
    # refreshing the entries for later replays.
    cache_cm = (contextlib.nullcontext() if args.no_encode_cache
                else encode_cache.activated(
                    str(Store(args.store_root).root
                        / encode_cache.CACHE_DIRNAME),
                    refresh=args.reencode))
    with cache_cm:
        return _cmd_corpus_checked(args, multislice)


def _cmd_corpus_checked(args, multislice: bool) -> int:
    import time

    from .. import sched
    from ..checkers import Linearizable
    from ..checkers.independent import split_by_key
    from ..store.store import Store, read_encoded_tensors

    by_model: dict[str, list] = {}   # model name -> [(run, key, encoded)]
    runs_seen = set()
    n_from_tensors = 0
    for run in Store(args.store_root).runs():
        try:
            workload = run.read_test().get("workload", "register")
        except (ValueError, OSError):
            workload = "register"
        whole = workload in WHOLE_HISTORY_MODELS
        model_name = (WHOLE_HISTORY_MODELS[workload] if whole
                      else CORPUS_MODELS.get(workload))
        if model_name is None:
            print(f"# skipping {run.path}: workload {workload!r} is not "
                  f"linearizability-checked", file=sys.stderr)
            continue
        if workload == "register":
            model_name = args.model
        if not args.reencode:
            # The tensor set must COVER the run (an interrupted original
            # check may have persisted only some keys): the run-time
            # results.json records how many keys the check saw; a
            # whole-history run has exactly one tensor (history.npz).
            tensors = read_encoded_tensors(run.path, model_name)
            if whole:
                expected = 1
            else:
                try:
                    expected = run.read_results()["indep"]["key_count"]
                except (ValueError, OSError, KeyError, TypeError):
                    expected = None
            if tensors and len(tensors) == expected:
                runs_seen.add(str(run.path))
                n_from_tensors += len(tensors)
                by_model.setdefault(model_name, []).extend(
                    (str(run.path), k, enc) for k, enc in tensors)
                continue
        # Linearizable.encode: model op-translation + slot-table escalation
        # (a run whose partitions piled up >32 forever-pending :info ops
        # must not crash the whole corpus pass).
        lin = Linearizable(model=model_name)
        try:
            history = run.read_history()
            if whole:
                keyed = {None: [op for op in history
                                if op.process != "nemesis"]}
            else:
                keyed = split_by_key(history)
        except (ValueError, OSError) as e:
            print(f"# skipping {run.path}: {e}", file=sys.stderr)
            continue
        runs_seen.add(str(run.path))
        for k, h in sorted(keyed.items(), key=lambda kv: str(kv[0])):
            try:
                # str(k): one key identity whichever load path ran (the
                # tensor path's keys are filename-derived strings).
                entry = (str(run.path), None if k is None else str(k),
                         lin.encode(h))
            except ValueError as e:
                print(f"# skipping {run.path} key {k}: {e}",
                      file=sys.stderr)
                continue
            by_model.setdefault(model_name, []).append(entry)
    if not by_model:
        print(json.dumps({"valid": True, "runs": 0, "keys": 0}))
        return 0
    t0 = time.perf_counter()
    invalid, kernels, n_keys = [], set(), 0
    sched_stats: dict = {}
    for model_name, entries in sorted(by_model.items()):
        model = Linearizable(model=model_name).model
        if multislice:
            from ..parallel.multislice import check_corpus_multislice

            # kernel comes back from the checker itself (ADVICE r4: the
            # dense-infeasible minority — or a whole corpus — can fall
            # back to the per-process local ladder; don't misreport it).
            results, kernel = check_corpus_multislice(
                [e[2] for e in entries], model)
        else:
            results, kernel, stats = sched.check_corpus(
                [e[2] for e in entries], model)
            sched.fold_stats(sched_stats, stats)
        kernels.add(kernel)
        n_keys += len(entries)
        invalid.extend({"run": r, "key": k, "model": model_name}
                       for (r, k, _), one in zip(entries, results)
                       if one["valid"] is not True)
    wall = time.perf_counter() - t0
    out = {
        "valid": not invalid,
        "runs": len(runs_seen),
        "keys": n_keys,
        "invalid": invalid,
        "kernel": kernels.pop() if len(kernels) == 1 else "mixed",
        "from_tensors": n_from_tensors,
        "wall_s": round(wall, 3),
    }
    if not multislice:
        out["launches"] = sched_stats["launches"]
        out["padding_waste"] = (
            round(sched_stats["steps_padded"] / sched_stats["steps_real"], 4)
            if sched_stats["steps_real"] else 0.0)
        out["cache_hit_rate"] = round(
            sched.kernel_cache().stats()["hit_rate"], 4)
        # Sparse-sweep exposure (doc/perf.md "Sparse sweeps"): how many
        # long-sweep steps the corpus pass ran in each mode — plus the
        # frontier-dedup / overflow accounting (doc/perf.md "Frontier
        # dedup", ISSUE 10).
        out["sweep_steps_sparse"] = sched_stats["sweep_steps_sparse"]
        out["sweep_steps_dense"] = sched_stats["sweep_steps_dense"]
        out["configs_pruned"] = sched_stats["configs_pruned"]
        out["sparse_overflow_rounds"] = \
            sched_stats["sparse_overflow_rounds"]
    if multislice:
        import jax

        out["processes"] = jax.process_count()
        out["process_id"] = jax.process_index()
        out["devices"] = jax.device_count()
    print(json.dumps(out))
    return 0 if not invalid else 1


def cmd_campaign(args) -> int:
    """`jepsen-tpu campaign`: the scenario factory end to end, or
    (--replay-corpus) the regression lane over the banked corpus. One
    obs capture and one warm kernel pool span the whole campaign —
    that amortization is the design (campaign/engine.py)."""
    from .. import obs
    from ..campaign import replay_corpus, run_campaign

    enable_compilation_cache(args.store)
    _apply_sweep_mode(args)
    _apply_mesh_shape(args)
    # Startup pre-warm (ISSUE 17): same hook as the serve daemon — the
    # campaign's first wave should hit the persistent cache, not
    # compile on the critical path. Env-gated, never fatal.
    from ..sched import startup_warmup

    startup_warmup(args.store, source="campaign")
    families = ([f.strip() for f in args.families.split(",") if f.strip()]
                if args.families else None)
    with obs.capture():
        if args.replay_corpus:
            report = replay_corpus(args.store)
            print(json.dumps(report))
            return 0 if report["ok"] else 1
        try:
            report = run_campaign(
                n_specs=args.specs, seed=args.seed, families=families,
                bug_rate=args.bug_rate, live=args.live,
                scale=args.scale, workers=args.workers,
                route=args.route, shrink=not args.no_shrink,
                bank=not args.no_bank, store_root=args.store,
                max_shrink_checks=args.max_shrink_checks)
        except ValueError as e:   # e.g. an unknown --families entry
            print(f"error: {e}", file=sys.stderr)
            return 2
    print(json.dumps(report.to_dict()))
    return 0


def cmd_tune(args) -> int:
    """`jepsen-tpu tune`: measure the KernelLimits knob space on this
    machine (tune/probes.py fixed-seed microbenchmarks, tune/search.py
    bounded coordinate descent) and persist the winning values as this
    platform's tuning profile — auto-loaded by limits() on every later
    run with precedence env > set_limits > tuned profile > default."""
    from .. import obs
    from ..tune import resolve_knobs, run_tune
    from ..tune import profile as tune_profile

    # The compile-cache dir must be enabled BEFORE any profile-path
    # resolution: tuned_profile.json lives next to the cache, and a
    # --print-profile that skipped this would report the home-cache file
    # while real `--store` runs resolve <store>/.xla-cache's.
    enable_compilation_cache(args.store)
    if args.print_profile:
        print(json.dumps(tune_profile.report(), indent=2))
        return 0
    try:
        knobs = resolve_knobs(args.knobs)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    with obs.capture():
        out = run_tune(knobs=knobs, budget_s=args.budget_s,
                       repeats=args.repeats, scale=args.scale,
                       dry_run=args.dry_run)
    print(json.dumps(out, indent=2))
    return 0


def cmd_plan(args) -> int:
    """`jepsen-tpu plan --print`: the resolved plan registry for one
    (or every) kernel family — backend module/factory, donation set,
    packed schema, carry, mesh axes, current-platform device counts,
    and the registry↔contracts sync verdict. Exit 1 when the registry
    drifted (the same diff JTL407 and the tier-1 sync test report)."""
    from ..plan import plan_report

    try:
        report = plan_report(args.family)
    except KeyError as e:
        print(f"error: {e.args[0]}", file=sys.stderr)
        return 2
    print(json.dumps(report, indent=2))
    return 0 if report["sync"] == "ok" else 1


def cmd_warmup(args) -> int:
    """`jepsen-tpu warmup`: replay the plan-family corpus through the
    persistent XLA cache (sched/warmup.py) so the fleet's first real
    launches are disk-cache hits. Prints one WARMUP JSON line — the
    ledger-armed warmup record (check_ledger_record-clean)."""
    from ..sched import warmup_plans

    enable_compilation_cache(args.store)
    _apply_mesh_shape(args)
    try:
        rec = warmup_plans(rungs=args.rungs, k_slots=args.k_slots,
                           encoder=not args.no_encoder,
                           store_root=args.store)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    print("WARMUP " + json.dumps(rec, sort_keys=True))
    return 0


def cmd_serve(args) -> int:
    if getattr(args, "fleet", False):
        # Fleet-scale serving (ISSUE 18): N subprocess replicas behind
        # the shape-affine rendezvous router, sharing one store root
        # (one persistent XLA cache + one O_EXCL tuned-profile file).
        from ..serve.fleet import serve_fleet

        return serve_fleet(
            args.store, host=args.host, port=args.port,
            replicas=args.replicas, default_model=args.model,
            coalesce_ms=args.coalesce_ms, max_batch=args.max_batch,
            max_inflight=args.max_inflight, ready_file=args.ready_file)
    if getattr(args, "check", False):
        # Checking-as-a-service (serve/, ISSUE 13): the warm pool only
        # pays off across requests if compiles persist, so the daemon
        # enables the same compilation cache production runs use.
        from ..sched import startup_warmup
        from ..serve.daemon import serve_check

        enable_compilation_cache(args.store)
        # Startup pre-warm (ISSUE 17): fill the persistent cache with
        # the plan-family corpus BEFORE accepting traffic, so the first
        # request never pays a cold compile. JEPSEN_TPU_NO_WARMUP=1
        # skips; failures are swallowed (warmup is an optimization).
        wrec = startup_warmup(args.store, source="serve")
        return serve_check(
            args.store, host=args.host, port=args.port,
            default_model=args.model, coalesce_ms=args.coalesce_ms,
            max_batch=args.max_batch, max_inflight=args.max_inflight,
            ready_file=args.ready_file, warmup=wrec)
    from ..web.server import serve
    serve(args.store, host=args.host, port=args.port)
    return 0


def enable_compilation_cache(store_root: str | None = None) -> None:
    """Persist XLA compilations across processes (VERDICT r2 weak #2: the
    ~2.6 s cold compile dominated one-shot `analyze` UX). The jit caches
    inside one process already dedupe by (model, geometry); this extends
    them across invocations. Thin shim over
    sched.enable_persistent_cache: directory precedence is
    JEPSEN_TPU_COMPILE_CACHE, then JAX_COMPILATION_CACHE_DIR, then
    <store_root>/.xla-cache when a store is known, then
    ~/.cache/jepsen_tpu_xla; JEPSEN_TPU_NO_COMPILE_CACHE=1 disables."""
    from ..sched import enable_persistent_cache

    enable_persistent_cache(store_root)


def _honor_platform_env() -> None:
    """Make JAX_PLATFORMS effective even where a sitecustomize pre-imports
    jax before this process's env-based selection would apply (the axon
    image does): re-assert it via jax.config before any backend init.
    Without this, a hermetic `JAX_PLATFORMS=cpu` CLI run still dials the
    TPU tunnel — and hangs with it — despite needing no device."""
    import os

    plats = os.environ.get("JAX_PLATFORMS")
    if not plats:
        return
    try:
        import jax

        jax.config.update("jax_platforms", plats)
    except Exception:   # platform forcing is best-effort, never fatal
        pass


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv[:1] == ["lint"]:
        # Dispatch before argparse/jax/backend setup: the lint verb is
        # pure AST analysis (analysis/cli.py owns its own argparse).
        from ..analysis.cli import main as lint_main

        return lint_main(argv[1:])
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s")
    _honor_platform_env()
    args = build_parser().parse_args(argv)
    if args.command == "test":
        return cmd_test(args)
    if args.command == "analyze":
        return cmd_analyze(args)
    if args.command == "corpus":
        return cmd_corpus(args)
    if args.command == "campaign":
        return cmd_campaign(args)
    if args.command == "tune":
        return cmd_tune(args)
    if args.command == "plan":
        return cmd_plan(args)
    if args.command == "serve":
        return cmd_serve(args)
    if args.command == "warmup":
        return cmd_warmup(args)
    return 2


if __name__ == "__main__":
    sys.exit(main())
