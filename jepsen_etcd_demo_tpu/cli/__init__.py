"""Command-line entry — jepsen.cli equivalent (reference -main,
src/jepsen/etcdemo.clj:192-199)."""

from .main import main, build_parser  # noqa: F401
