"""Compilation caching for the corpus throughput engine — two layers.

1. The PERSISTENT cache: jax's on-disk compilation cache, so the second
   process-lifetime run of any (kernel, geometry, bucket shape) skips the
   XLA compile tax entirely (bench_100k.json measured it at ~3.3 s of the
   3.9 s cold start). Directory precedence:

     JEPSEN_TPU_COMPILE_CACHE          explicit harness-level override
     JAX_COMPILATION_CACHE_DIR         the stock jax env var
     <store_root>/.xla-cache           when a store root is known (the
                                       cache travels with the results it
                                       accelerated re-checking)
     ~/.cache/jepsen_tpu_xla           per-user fallback

   JEPSEN_TPU_NO_COMPILE_CACHE=1 disables it. Enabling is idempotent and
   first-caller-wins within a process (jax reads the config at compile
   time; flipping directories mid-process would split the cache).

2. The IN-PROCESS kernel LRU: one resolved checker callable per
   (kernel, model, bucket-shape) key, with hit/miss accounting surfaced
   through obs metrics (`sched.cache_hits` / `sched.cache_misses`) and
   the bench's `cache_hit_rate` field. The jit caches inside ops/ already
   dedupe by (model, geometry); this layer adds the SHAPE axis the bucket
   scheduler introduces, so the number of distinct compilations per
   kernel is exactly the bucket count — observable, not folklore.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Any, Callable

from ..obs import get_metrics
from ..ops.limits import limits

_enabled_dir: str | None = None
_enable_lock = threading.Lock()


def compile_cache_dir(store_root: str | os.PathLike | None = None) -> str:
    env = os.environ.get("JEPSEN_TPU_COMPILE_CACHE")
    if env:
        return env
    env = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if env:
        return env
    if store_root is not None:
        return os.path.join(str(store_root), ".xla-cache")
    return os.path.expanduser("~/.cache/jepsen_tpu_xla")


def enable_persistent_cache(store_root: str | os.PathLike | None = None
                            ) -> str | None:
    """Point jax's persistent compilation cache at compile_cache_dir().
    Returns the active directory (None when disabled/unavailable)."""
    global _enabled_dir
    if os.environ.get("JEPSEN_TPU_NO_COMPILE_CACHE"):
        return None
    with _enable_lock:
        if _enabled_dir is not None:
            return _enabled_dir
        try:
            import jax

            cache_dir = compile_cache_dir(store_root)
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.5)
            _enabled_dir = cache_dir
        except Exception:   # the cache is an optimization, never a failure
            return None
        return _enabled_dir


class KernelCache:
    """Thread-safe LRU of resolved checker callables keyed by
    (kernel, model, bucket-shape). Values are built once per key by the
    caller-supplied builder and evicted least-recently-used past
    limits().kernel_cache_entries (evicting the wrapper frees nothing the
    jit caches still hold — the LRU bounds WRAPPER bookkeeping, while the
    persistent cache keeps recompiles of an evicted shape cheap)."""

    def __init__(self, capacity: int | None = None):
        from ..obs.sync import maybe_wrap

        self._capacity = capacity
        self._entries: OrderedDict[tuple, Any] = OrderedDict()
        self._lock = maybe_wrap(
            threading.Lock(), "sched.compile_cache.KernelCache._lock")
        self.hits = 0
        self.misses = 0

    def _cap(self) -> int:
        return self._capacity or limits().kernel_cache_entries

    def get(self, key: tuple, build: Callable[[], Any]) -> Any:
        with self._lock:
            if key in self._entries:
                self.hits += 1
                self._entries.move_to_end(key)
                get_metrics().counter("sched.cache_hits").add(1)
                return self._entries[key]
            self.misses += 1
            get_metrics().counter("sched.cache_misses").add(1)
        value = build()   # build outside the lock: builders jit-trace
        with self._lock:
            self._entries[key] = value
            self._entries.move_to_end(key)
            while len(self._entries) > self._cap():
                self._entries.popitem(last=False)
        return value

    def stats(self) -> dict:
        # Under the lock: a concurrent get() mutating hits/misses/
        # entries must not tear the snapshot (the serve daemon reads
        # stats from handler threads while its dispatcher populates).
        with self._lock:
            total = self.hits + self.misses
            return {"hits": self.hits, "misses": self.misses,
                    "entries": len(self._entries),
                    "hit_rate": (self.hits / total) if total else 0.0}

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self.hits = 0
            self.misses = 0


_KERNEL_CACHE = KernelCache()


def kernel_cache() -> KernelCache:
    """The process-wide scheduler kernel LRU."""
    return _KERNEL_CACHE
