"""sched — the corpus throughput engine (ISSUE 2 tentpole).

Three coordinated layers that make checking MANY histories as fast as
the hardware allows:

  * engine.py        — length-bucketed batch scheduler over the corpus /
                       independent-key lanes (bounded padding waste,
                       bounded compilations per kernel)
  * pipeline.py      — double-buffered chunk pipelining primitives used
                       by the resumable sweeps in ops/wgl2 + ops/wgl3
  * compile_cache.py — the persistent (on-disk, JEPSEN_TPU_COMPILE_CACHE)
                       and in-process (per-bucket-shape LRU) compilation
                       caches, with the hit accounting behind the bench's
                       cache_hit_rate field

See doc/perf.md for the operator-facing story.
"""

from .compile_cache import (compile_cache_dir, enable_persistent_cache,
                            kernel_cache)
from .engine import (assign_step_buckets, check_corpus, corpus_executor,
                     fold_stats, lpt_shard_order, submit_corpus)
from .pipeline import InflightWindow, double_buffer
from .warmup import startup_warmup, warmup_plans

__all__ = [
    "assign_step_buckets",
    "check_corpus",
    "compile_cache_dir",
    "corpus_executor",
    "double_buffer",
    "enable_persistent_cache",
    "fold_stats",
    "InflightWindow",
    "kernel_cache",
    "lpt_shard_order",
    "startup_warmup",
    "submit_corpus",
    "warmup_plans",
]
