"""Double-buffered host->device chunk pipelining primitives.

The chunked sweeps (ops/wgl2.py check_steps_resumable, ops/wgl3.py
check_steps3_long) used to serialize host prep, H2D transfer, device
execution, and the per-chunk status fetch: the device sat idle while the
host sliced/padded/transferred the next chunk, and the host sat idle
while the device ran. These helpers overlap them:

  * `double_buffer` stages (transfers) chunk N+1 while the caller is
    still consuming chunk N — jax transfers are async, so the H2D enqueue
    returns immediately and the copy proceeds while the device executes
    the previous chunk's program.
  * `InflightWindow` bounds speculative dispatch for loops that must
    fetch a per-chunk flag (the sort sweep's overflow check): chunk N+1
    is already dispatched when chunk N's flag is fetched, so the fetch
    round trip hides under real work instead of stalling the device.

Neither helper knows anything about the search; they move buffers and
order operations only, so verdicts are bit-identical to the synchronous
loops by construction.
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Iterable, Iterator, TypeVar

T = TypeVar("T")
S = TypeVar("S")


def double_buffer(items: Iterable[T], stage: Callable[[T], S]
                  ) -> Iterator[S]:
    """Yield stage(item) for each item, always staging one item AHEAD of
    the one being yielded: when the caller dispatches work on chunk N,
    chunk N+1's transfer is already enqueued. `stage` is typically a
    jnp.asarray/device_put wrapper (async H2D)."""
    prev: S | None = None
    have_prev = False
    for x in items:
        cur = stage(x)
        if have_prev:
            yield prev
        prev = cur
        have_prev = True
    if have_prev:
        yield prev


class InflightWindow:
    """Bounded queue of dispatched-but-unresolved chunks.

    push() after dispatching a chunk; full() says when the caller must
    resolve (fetch) the oldest entry before dispatching more; pop()
    returns it. depth=1 degenerates to the fully synchronous loop."""

    def __init__(self, depth: int):
        self.depth = max(1, int(depth))
        self._q: deque = deque()

    def push(self, entry) -> None:
        self._q.append(entry)

    def full(self) -> bool:
        return len(self._q) >= self.depth

    def pop(self):
        return self._q.popleft()

    def clear(self) -> None:
        self._q.clear()

    def __len__(self) -> int:
        return len(self._q)

    def __bool__(self) -> bool:
        return bool(self._q)
