"""Length-bucketed corpus scheduler — the batched-checking throughput engine.

The batched entry points used to pad EVERY history in a corpus to the
longest member's step count (wgl3.batch_steps3: one shared r_cap), so a
corpus of mostly-short histories paid the long tail's padding on every
lane, and every distinct corpus shape compiled its own kernel. This
module is the scheduler PR 1's step-padding gauge argued for:

  * histories are grouped into {2^k, 1.5*2^k} PADDED-LENGTH BUCKETS of
    their return-step counts (floor tunable via
    limits().step_bucket_floor), bounding per-bucket padding waste to
    <1.5x and capping distinct jit compilations per kernel to the bucket
    count;
  * the batch axis is bucketed too (all-pad histories, stripped from
    results), so corpora of varying size reuse the same compiled shapes;
  * launches are dispatched ASYNC and fetched at drain: while the device
    runs bucket N, the host stacks/transfers bucket N+1 (the corpus-level
    face of the double-buffered chunk pipelining in ops/wgl2+wgl3);
  * resolved checker callables live in the sched kernel LRU
    (compile_cache.py) keyed by (kernel, model, bucket-shape), with
    hit/miss accounting behind the bench's cache_hit_rate field.

Verdicts are bit-identical to the unbatched path: bucket pads are
all-pad scan steps (targets = -1) that the kernels skip by construction,
and batch pads are all-pad histories stripped before assembly
(tests/test_sched.py pins equivalence on golden + fuzz corpora).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Sequence

import numpy as np

from .. import obs
from ..ops.limits import limits


def assign_step_buckets(step_counts: Sequence[int]) -> list[int]:
    """Padded-length bucket per entry — a pure function of the counts and
    the active limits profile, so bucket assignment is deterministic and
    order-independent (same count -> same bucket wherever it sits in the
    corpus)."""
    from ..ops import wgl3

    floor = limits().step_bucket_floor
    return [wgl3.step_bucket(int(n), floor=floor) for n in step_counts]


def lpt_shard_order(step_counts: Sequence[int], n_shards: int
                    ) -> list[int]:
    """Deterministic LPT (longest-processing-time) bin packing of a
    padded launch's histories into the mesh's contiguous per-shard
    blocks, balanced by REAL step count — the shard-aware half of the
    bucketed scheduler (limits().shard_bucket_mode).

    The sharded routes split the [B] axis into n_shards equal
    CONTIGUOUS blocks (obs.ledger.shard_real_steps is the accounting
    twin), so batch ORDER is the packing degree of freedom: the legacy
    append-pads-at-the-end order loads the leading shards with every
    real step while the trailing shards sweep all-pad lanes — the
    MULTICHIP_r06 straggler_table smoking gun (shards
    [3913, ..., 2305, 0, 0]). This permutation assigns histories
    longest-first to the least-loaded shard with remaining capacity
    (ties -> lowest shard id), then restores ascending original order
    within each shard, so equal-work shards retire their bucket
    together and the mesh stops idling behind one straggler.

    Returns the permutation `perm` such that position j of the packed
    launch holds original entry perm[j]; identity when the batch does
    not split evenly (mirroring shard_real_steps' degraded contract) or
    there is nothing to balance. Pure and deterministic — same counts,
    same shard count, same order — so verdicts and compiled shapes are
    independent of packing (tests/test_pod_scaling.py pins determinism
    across mesh shapes)."""
    n = len(step_counts)
    if n_shards <= 1 or n == 0 or n % n_shards:
        return list(range(n))
    cap = n // n_shards
    order = sorted(range(n), key=lambda i: (-int(step_counts[i]), i))
    loads = [0] * n_shards
    fill: list[list[int]] = [[] for _ in range(n_shards)]
    for i in order:
        s = min((j for j in range(n_shards) if len(fill[j]) < cap),
                key=lambda j: (loads[j], j))
        fill[s].append(i)
        loads[s] += int(step_counts[i])
    for block in fill:
        block.sort()
    return [i for block in fill for i in block]


def _batch_bucket(n: int, cap: int) -> int:
    """Batch-axis bucket: {2^k, 1.5*2^k} growth from the batch floor,
    capped by the launch-size cap. The sharding-multiple round-up
    happens at the call site AFTER bucketing, because the multiple must
    be derived from the BUCKETED size (a bucket can inflate a 1-history
    part past 1, flipping the launcher onto the sharded kernel)."""
    from ..ops import wgl3

    b = min(wgl3.step_bucket(n, floor=limits().batch_bucket_floor), cap)
    return max(b, n)


def _pad_rs(k_slots: int):
    """An all-pad (0-step) ReturnSteps history for batch-axis padding:
    every step a pad, trivially valid, zero search work."""
    from ..ops.encode import ReturnSteps

    return ReturnSteps(
        slot_tabs=np.zeros((0, k_slots, 4), np.int32),
        slot_active=np.zeros((0, k_slots), bool),
        targets=np.zeros((0,), np.int32),
        n_steps=0, n_ops=0, k_slots=k_slots, max_pending=0, max_value=0)


def _dense_bucket_launcher(model, cfg, b: int, r: int):
    """Resolved packed checker for one (batch, step) bucket shape,
    through the KernelPlan layer (plan/dispatch.py plan_dense_batch —
    the one copy of the sharded-vs-local and pallas-vs-XLA routing this
    function used to duplicate): run(tabs, act, tgt) -> DEVICE packed
    i32 rows. The single-device pallas route emits i32[b, 5]
    (wgl3.PACKED_FIELDS); the XLA routes emit i32[b, 6]
    (wgl3.PACKED_FIELDS_XLA — the live-tile telemetry column rides
    along). The drain unpacks through wgl3.unpack_np, which accepts
    both widths — that dual-width contract is the one jtflow pins
    (doc/analysis.md "Contracts"). The plan's cache key carries the
    mesh identity, so an elastic re-shard between runs can only MISS
    the kernel LRU, never serve a stale compiled launch.
    Returns (run, plan) — the plan's label is the kernel name and its
    identity feeds the scaling ledger's launch context."""
    from .. import plan as kplan

    p = kplan.plan_dense_batch(model, cfg, n_steps=r, batch=b)
    return kplan.resolve(p), p


def _launch_multiple(model, cfg, b: int, r: int) -> int:
    """The [B]-axis multiple a launch of this shape must pad to."""
    from .. import plan as kplan

    return kplan.launch_multiple(model, cfg, n_steps=r, batch=b)


class _Stats:
    """Per-call corpus accounting. Mutations take the instance lock:
    check_corpus itself records from one thread, but the serve daemon
    (serve/scheduler.py) folds several calls' stats concurrently with
    its dispatch thread and the obs counters ride along — hit/bucket
    accounting must not tear under concurrent submitters (ISSUE 13
    thread-safety pass)."""

    def __init__(self):
        from ..obs.sync import maybe_wrap

        self._lock = maybe_wrap(threading.Lock(),
                                "sched.engine._Stats._lock")
        self.steps_real = 0
        self.steps_padded = 0
        self.launches = 0
        self.buckets: dict[int, int] = {}
        self.sweep_steps_sparse = 0
        self.sweep_steps_dense = 0
        self.configs_pruned = 0
        self.sparse_overflow_rounds = 0

    def record_sweep(self, result: dict) -> None:
        """Fold a long-sweep result's sparse-engine record (ops/
        wgl3_sparse.py) and frontier-dedup accounting (ops/canon.py)
        into the corpus stats — the scheduler's half of the bench/CLI
        sweep exposure."""
        sweep = result.get("sweep")
        dedup = result.get("dedup")
        with self._lock:
            if isinstance(sweep, dict):
                self.sweep_steps_sparse += int(sweep.get("steps_sparse", 0))
                self.sweep_steps_dense += int(sweep.get("steps_dense", 0))
                self.sparse_overflow_rounds += int(
                    sweep.get("overflow_rounds", 0))
            if isinstance(dedup, dict):
                self.configs_pruned += int(dedup.get("configs_pruned", 0))

    def record_launch(self, real: int, b: int, r: int) -> None:
        padded = b * r
        with self._lock:
            self.steps_real += real
            self.steps_padded += padded
            self.launches += 1
            self.buckets[r] = self.buckets.get(r, 0) + 1
        m = obs.get_metrics()
        m.counter("sched.steps_real").add(real)
        m.counter("sched.steps_padded").add(padded)
        m.counter("sched.launches").add(1)
        if real:
            m.gauge("sched.padding_waste_ratio").set(padded / real)

    def to_dict(self) -> dict:
        with self._lock:
            out = {
                "launches": self.launches,
                "buckets": sorted(self.buckets.items()),
                "steps_real": self.steps_real,
                "steps_padded": self.steps_padded,
                "padding_waste": (round(
                    self.steps_padded / self.steps_real, 4)
                    if self.steps_real else 0.0),
                "sweep_steps_sparse": self.sweep_steps_sparse,
                "sweep_steps_dense": self.sweep_steps_dense,
                "configs_pruned": self.configs_pruned,
                "sparse_overflow_rounds": self.sparse_overflow_rounds,
            }
        return out


#: The numeric stats-dict fields a multi-call consumer folds (the CLI
#: corpus verb sums across models; the campaign engine sums across
#: check waves). One list so the two cannot drift from to_dict().
FOLDABLE_STATS = ("launches", "steps_real", "steps_padded",
                  "sweep_steps_sparse", "sweep_steps_dense",
                  "configs_pruned", "sparse_overflow_rounds")


def fold_stats(total: dict, stats: dict) -> dict:
    """Accumulate one check_corpus stats dict into a running total
    (missing keys initialize to 0; padding_waste is derived by the
    consumer from the folded step counters)."""
    for f in FOLDABLE_STATS:
        total[f] = total.get(f, 0) + int(stats.get(f, 0) or 0)
    return total


def check_corpus(encs: Sequence, model=None, f_cap: int = 256
                 ) -> tuple[list[dict], str, dict]:
    """Check a corpus of encoded histories through the bucketed scheduler;
    returns (per-history results aligned with `encs`, kernel_name —
    "mixed" when histories split across backends, stats dict).

    Routing policy is SHARED with ops/wgl3_pallas.check_batch_encoded_auto
    (partition_dense / run_long_dense / ladder_tail live there, in one
    copy) — the scheduler only changes HOW the dense majority is padded
    and launched, never which kernel checks what."""
    from ..ops import wgl3, wgl3_pallas
    from ..ops.encode import encode_return_steps, reslot_events

    if model is None:
        from ..models import CASRegister

        model = CASRegister()
    stats = _Stats()
    if len(encs) <= 1:
        # Single histories keep the auto router's full treatment (the
        # oracle latency route included) — nothing to bucket.
        results, kernel = wgl3_pallas.check_batch_encoded_auto(encs, model)
        return results, kernel, stats.to_dict()

    with obs.get_tracer().span("sched.check_corpus",
                               histories=len(encs)) as sp:
        # Backend health (obs/health.py): corpus dispatch is one of the
        # supervisor's periodic drivers — rate-limited active probe on
        # entry (a no-op inside the probe interval), passive ok/failure
        # notes from the launch drain below.
        supervisor = obs.health.get_supervisor()
        supervisor.maybe_probe(source="sched.dispatch")
        results: list[Any] = [None] * len(encs)
        kernels: set[str] = set()

        dense_idx, general_idx = wgl3_pallas.partition_dense(encs, model)
        cfg = None
        if dense_idx:
            k = max(wgl3.tight_k_slots(encs[i]) for i in dense_idx)
            cfg = wgl3.dense_config(
                model, k, max(encs[i].max_value for i in dense_idx))
            if cfg is None:
                # Individually feasible but not under one shared geometry:
                # ladder each (rare extreme — same policy as the auto
                # router).
                general_idx = sorted(general_idx + dense_idx)
                dense_idx = []

        if dense_idx:
            lim = limits()
            steps_of: dict[int, Any] = {}
            long_idx, short_idx = [], []
            for i in dense_idx:
                e = encs[i]
                rs = encode_return_steps(
                    reslot_events(e, k) if e.k_slots != k else e)
                steps_of[i] = rs
                (long_idx if rs.n_steps > lim.long_scan_max
                 else short_idx).append(i)

            # Long histories: host-chunked (now pipelined) sweeps, one at
            # a time — arrays are never stacked. Eligible geometries ride
            # the sparse active-tile engine automatically
            # (wgl3.check_steps3_long -> sparse_plan); the per-mode step
            # counts land in the stats dict.
            for i in long_idx:
                one = wgl3_pallas.run_long_dense(steps_of[i], model, cfg)
                results[i] = one
                kernels.add(one["kernel"])
                stats.record_sweep(one)

            # The bucketed batched lanes: group by padded step length,
            # dispatch every launch async, fetch once at drain.
            buckets: dict[int, list[int]] = {}
            for i, r in zip(short_idx,
                            assign_step_buckets(
                                [steps_of[i].n_steps for i in short_idx])):
                buckets.setdefault(r, []).append(i)
            def _fetch_launch(entry):
                part, part_steps, dev, lctx, perm = entry
                t0f = time.monotonic_ns()
                try:
                    fetched = np.asarray(dev)
                except Exception as e:
                    # The drain fetch is where a dead backend finally
                    # surfaces for async launches — tell the supervisor
                    # before propagating.
                    supervisor.note_failure(f"{type(e).__name__}: {e}",
                                            source="sched.dispatch")
                    raise
                # The drain fetch is where async device time surfaces
                # on the host — ledger it under the launch's context so
                # padding/straggler decomposition sees the real wait.
                obs.get_ledger().record_fetch(t0f, time.monotonic_ns(),
                                              ctx=lctx)
                if perm is None:
                    rows = fetched[:len(part)]
                else:
                    # Shard packing permuted the batch: row j holds
                    # original lane perm[j]; invert to read the real
                    # histories back in part order.
                    inv = [0] * len(perm)
                    for j, p in enumerate(perm):
                        inv[p] = j
                    rows = fetched[[inv[p] for p in range(len(part))]]
                out = wgl3.unpack_np(rows)
                for i, one in zip(part, wgl3.assemble_batch_results(
                        out, part_steps, cfg)):
                    results[i] = one

            # In-flight launch window (plan/dispatch.py LaunchPipeline,
            # depth = limits().pod_pipeline_depth): bucket N+1's host
            # stack + H2D staging overlaps bucket N's device execute,
            # and undrained device results stay bounded — the corpus-
            # level form of the long sweep's double buffering.
            from ..plan import LaunchPipeline

            pipe = LaunchPipeline(resolve=_fetch_launch)
            for r in sorted(buckets):
                idxs = buckets[r]
                # Launch-size cap: stacked bytes for one launch stay
                # inside the tested-good element budget.
                per_hist = max(1, r * (cfg.k_slots * 5 + 1))
                chunk = max(1, lim.stack_element_budget // per_hist)
                for c0 in range(0, len(idxs), chunk):
                    part = idxs[c0:c0 + chunk]
                    part_steps = [steps_of[i] for i in part]
                    # Bucket FIRST, then derive the sharding multiple
                    # from the bucketed size: the launcher picks the
                    # sharded kernel by the PADDED batch, so a part the
                    # bucket inflates past 1 must pad to the device
                    # multiple even though the raw part would have run
                    # single-history (a batch_bucket_floor that is not a
                    # multiple of the device count — any tuned floor on a
                    # pod — crashed here otherwise).
                    b0 = _batch_bucket(len(part), chunk)
                    mult = _launch_multiple(model, cfg, b0, r)
                    b = (b0 + mult - 1) // mult * mult
                    run, plan_obj = _dense_bucket_launcher(model, cfg,
                                                           b, r)
                    padded = part_steps + [_pad_rs(k)] * (b - len(part))
                    # Scaling ledger launch context: plan identity +
                    # the bucket economics (real vs padded steps/batch,
                    # per-shard real steps for straggler attribution) —
                    # the instrumented kernel call and the H2D staging
                    # inside the block inherit it.
                    real = sum(s.n_steps for s in part_steps)
                    lctx = obs.ledger.plan_context(plan_obj)
                    lctx.update(batch_real=len(part), batch_padded=b,
                                steps_real=real, steps_padded=b * r)
                    perm = None
                    n_shards = lctx.get("n_shards", 1)
                    if n_shards > 1:
                        if lim.shard_bucket_mode:
                            # Shard-aware packing: permute the padded
                            # batch so contiguous per-shard blocks carry
                            # balanced REAL steps (pads interleave
                            # instead of stacking on the tail shards).
                            perm = lpt_shard_order(
                                [s.n_steps for s in padded], n_shards)
                            if perm == list(range(len(padded))):
                                perm = None
                            else:
                                padded = [padded[j] for j in perm]
                                lctx["shard_packed"] = True
                        lctx["shard_real"] = obs.ledger.shard_real_steps(
                            [s.n_steps for s in padded], n_shards)
                    with obs.ledger.launch_context(**lctx):
                        arrays = wgl3.stack_steps3(padded, r)
                        dev = run(*arrays)
                    pipe.submit((part, part_steps, dev, lctx, perm))
                    stats.record_launch(real, b, r)
                    kernels.add(plan_obj.label)
            pipe.drain()
            if pipe.dispatched:
                supervisor.note_ok(source="sched.dispatch")

        if general_idx:
            _check_general(encs, general_idx, model, results, kernels,
                           f_cap)

        sp.set(launches=stats.launches,
               buckets=len(stats.buckets))
        kernel = kernels.pop() if len(kernels) == 1 else (
            "mixed" if kernels else "none")
        return results, kernel, stats.to_dict()


def _check_general(encs, general_idx, model, results, kernels,
                   f_cap: int) -> None:
    """The non-dense partition (wide pending sets / huge values): the
    batched sort-kernel tiers, grouped by return-count bucket so a
    corpus's short sort histories don't pad to its longest, then the
    per-history exact ladder for whatever the tiers couldn't settle —
    the same tail policy as check_batch_encoded_auto."""
    from ..ops import wgl3_pallas
    from ..ops.encode import EV_RETURN

    def return_count(e) -> int:
        ev = np.asarray(e.events[: e.n_events])
        return int((ev[:, 0] == EV_RETURN).sum()) if e.n_events else 0

    groups: dict[int, list[int]] = {}
    for i, r in zip(general_idx,
                    assign_step_buckets(
                        [return_count(encs[i]) for i in general_idx])):
        groups.setdefault(r, []).append(i)
    overflow_seeds: list[tuple[int, int]] = []   # (idx, seed f_cap)
    too_long_all: list[int] = []
    for r in sorted(groups):
        overflowed, too_long, top = wgl3_pallas._batch_general(
            encs, groups[r], model, results, kernels, f_cap=f_cap)
        overflow_seeds.extend(
            (i, wgl3_pallas.LADDER_SEED_FACTOR * top) for i in overflowed)
        too_long_all.extend(too_long)
    wgl3_pallas.ladder_tail(encs, model, results, kernels, too_long_all,
                            overflow_seeds)


# -- async submit/await face (ISSUE 13) -------------------------------------
#
# check_corpus is re-entrant (per-call _Stats, the locked kernel LRU,
# thread-safe obs registries), but the device itself is a serial
# resource: concurrent submitters gain nothing by racing dispatches and
# can interleave compile traces. submit_corpus serializes every
# submitter through ONE process-wide single-worker executor — the serve
# daemon's dispatch loop, tests, and ad-hoc callers all await the same
# queue, so a launch in flight is never preempted by another thread's.

_executor_lock = threading.Lock()
_executor: ThreadPoolExecutor | None = None


def corpus_executor() -> ThreadPoolExecutor:
    """The process-wide single-worker executor corpus launches serialize
    on (created on first use; daemon threads, so interpreter shutdown is
    never blocked on a drained queue)."""
    global _executor
    with _executor_lock:
        if _executor is None:
            # jtlint: disable=JTL505 -- process-lifetime singleton by
            # design (docstring above): one daemon worker thread that
            # serializes every corpus submitter for the life of the
            # process; there is no later point to shut it down from,
            # and daemon=True means it never blocks interpreter exit.
            _executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="sched-corpus")
        return _executor


def submit_corpus(encs: Sequence, model=None, f_cap: int = 256) -> Future:
    """Async submit/await face of :func:`check_corpus`: returns a
    Future resolving to the same (results, kernel, stats) tuple.
    Submissions from any thread serialize on :func:`corpus_executor`,
    so concurrent callers (the serve daemon's coalesced batches, a
    bench arm, a test) never race device dispatches."""
    return corpus_executor().submit(check_corpus, encs, model, f_cap)
