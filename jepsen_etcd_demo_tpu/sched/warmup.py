"""Fleet-wide compile-cache pre-warm (ISSUE 17 tentpole d).

Cold XLA compiles are the one latency the pod pipeline cannot overlap
away: the FIRST launch of every (kernel family, geometry) pays seconds
of compile on the dispatch critical path, once per process — multiplied
across a fleet, once per host. The persistent compilation cache
(sched/compile_cache.py) already makes compiles shareable across
processes; what was missing is a way to FILL it ahead of traffic.

``warmup_plans`` replays a plan-family corpus — the dense sharded
checker and the device-side encoder over the bucket geometries the
scheduler actually launches ({2^k, 1.5*2^k} step rungs at the tuned
floors, batch buckets padded to the mesh multiple) — through the
persistent cache with all-pad inputs (targets=-1: zero search work,
full compile + one execute each). Run it from one blessed host
(`jepsen-tpu warmup`) and every other host's first real launch becomes
a disk-cache hit; the serve daemon and campaign runner call the same
function at startup (one cheap rung) so a cold store never puts a
compile on a request's critical path.

The report is ledger-armed: every warmup launch runs under an obs
capture, and the returned record carries the zeros-never-absent
``ledger`` object the bench contract requires
(tools/bench_compare.py check_ledger_record — smoked by tier-1).
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Optional

from .compile_cache import enable_persistent_cache

#: Env kill switch for the serve/campaign startup hooks (the explicit
#: CLI verb ignores it — asking for a warmup means wanting one).
NO_WARMUP_ENV = "JEPSEN_TPU_NO_WARMUP"


def step_rungs(n: int, floor: Optional[int] = None) -> list[int]:
    """The first `n` rungs of the {2^k, 1.5*2^k} step-bucket ladder the
    scheduler launches at (wgl3.step_bucket from the tuned floor) — the
    geometries worth pre-compiling."""
    from ..ops import wgl3
    from ..ops.limits import limits

    if floor is None:
        floor = limits().step_bucket_floor
    rungs, r = [], floor
    while len(rungs) < n:
        rungs.append(r)
        nxt = wgl3.step_bucket(r + 1, floor=floor)
        if nxt <= r:
            break
        r = nxt
    return rungs


def warmup_plans(model=None, mesh=None, *, k_slots: int = 16,
                 rungs: int = 2, max_value: int = 8,
                 store_root: Optional[str] = None,
                 encoder: bool = True) -> dict[str, Any]:
    """Pre-compile the plan-family corpus for this platform into the
    persistent XLA cache. Returns the warmup record: per-family launch
    labels, compile/execute seconds (the ledger object), wall, and the
    active cache directory (None when the cache is disabled)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from .. import obs
    from ..models import CASRegister
    from ..obs import ledger as obs_ledger
    from ..ops import wgl3
    from ..ops.encode import EVENT_WIDTH
    from ..ops.limits import limits
    from ..parallel import dense as pdense
    from ..plan import plan_dense_batch, resolve

    t0 = time.monotonic()
    cache_dir = enable_persistent_cache(store_root)
    if model is None:
        model = CASRegister()
    if mesh is None:
        mesh = pdense.batch_mesh()
    cfg = wgl3.dense_config(model, k_slots, max_value)
    if cfg is None:
        raise ValueError(
            f"dense kernel infeasible at k_slots={k_slots} "
            f"max_value={max_value} — nothing to warm")
    lim = limits()
    families: list[str] = []
    launches = 0
    with obs.capture() as cap:
        for r in step_rungs(max(1, rungs)):
            mult = pdense.batch_multiple(model, cfg, mesh, n_steps=r,
                                         batch=lim.batch_bucket_floor)
            b = (wgl3.step_bucket(1, floor=lim.batch_bucket_floor)
                 + mult - 1) // mult * mult
            p = plan_dense_batch(model, cfg, n_steps=r, batch=b,
                                 mesh=mesh)
            check = resolve(p)
            lctx = obs_ledger.plan_context(p)
            lctx.update(batch_real=0, batch_padded=b, steps_real=0,
                        steps_padded=b * r)
            # All-pad inputs: targets=-1 rows are zero search work, so
            # the launch is almost pure compile — exactly what a warmup
            # wants on the ledger.
            tabs = np.zeros((b, r, cfg.k_slots, 4), np.int32)
            act = np.zeros((b, r, cfg.k_slots), bool)
            tgt = np.full((b, r), -1, np.int32)
            with obs_ledger.launch_context(**lctx):
                # jtlint: disable=JTL103 -- warmup wants the block: each
                # rung's fetch materializes its compile into the
                # persistent cache before the next rung is measured
                np.asarray(check(jnp.asarray(tabs), jnp.asarray(act),
                                 jnp.asarray(tgt)))
            families.append(p.label)
            launches += 1
            if encoder and lim.encode_mode != 1:
                from ..ops import encode_device

                e_cap = encode_device.event_bucket(2 * r)
                if e_cap * cfg.k_slots <= lim.stack_element_budget:
                    ev = np.zeros((b, e_cap, EVENT_WIDTH), np.int32)
                    ev[:, :, 0] = 2          # EV_PAD
                    enc_fn = pdense.sharded_device_encoder(
                        cfg.k_slots, e_cap, r, mesh)
                    with obs_ledger.launch_context(**lctx):
                        for a in enc_fn(jnp.asarray(ev)):
                            np.asarray(a)
                    families.append("wgl3-encode-sharded")
                    launches += 1
        led = obs.ledger_stats(cap.metrics)
    return {
        "value": launches,
        "backend": jax.default_backend(),
        "cache_dir": cache_dir,
        "mesh_shape": dict(mesh.shape),
        "families": sorted(set(families)),
        "launches": launches,
        "wall_s": round(time.monotonic() - t0, 4),
        "ledger": led,
    }


def startup_warmup(store_root: Optional[str] = None, *,
                   source: str = "startup") -> Optional[dict]:
    """The serve/campaign startup hook: one cheap rung through
    warmup_plans, swallowing every failure (a warmup must never take a
    daemon down) and honoring the JEPSEN_TPU_NO_WARMUP kill switch.
    Returns the warmup record, or None when skipped/failed."""
    if os.environ.get(NO_WARMUP_ENV):
        return None
    try:
        rec = warmup_plans(rungs=1, store_root=store_root)
    except Exception as e:   # never fatal — warmup is an optimization
        print(f"warmup ({source}): skipped ({type(e).__name__}: {e})",
              file=sys.stderr)
        return None
    # stderr: the serve daemon's stdout is a line-JSON protocol (the
    # ready record must be the first line a supervisor reads).
    print(f"WARMUP {json.dumps(rec, sort_keys=True)}", file=sys.stderr)
    return rec
