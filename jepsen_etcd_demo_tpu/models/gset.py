"""Grow-only set model — knossos model/set equivalent.

Part of the knossos model surface the reference ships (knossos 0.3.7,
jepsen.etcdemo.iml:58). The reference's set WORKLOAD is checked with pure
set algebra (checker/set, src/jepsen/etcdemo/set.clj:46 — see
checkers/set_checker.py); this model is the stronger LINEARIZABILITY check
over the same op language: every read must observe exactly the adds
linearized before it, not merely a superset of the acknowledged ones.

TPU-first state design: the set over values 0..30 is its int32
characteristic bitmask, so

  add(v)  — always legal; state' = state | (1 << v)
  read(S) — legal iff state == bitmask(S)  (an exact observation)

and every transition is single-instruction bit algebra — no set objects,
no hashing. With the reference's value domain (rand-int 5 ⇒ values 0..4,
src/jepsen/etcdemo.clj:68) the whole state space is 32 states, so the
dense subset-lattice kernel (ops/wgl3.py) checks gset histories with the
table fully resident in one (8,128) VPU tile.

Op language (encode_invocation): `add` carries the value on the invoke;
`read` carries the observed collection of values on the ok completion.
Indeterminate reads are dropped by the encoder (F_READ convention,
ops/encode.py); indeterminate adds stay pending forever — exactly the
reference's :info semantics for set adds (src/jepsen/etcdemo/set.clj:33-36).
"""

from __future__ import annotations

from typing import Iterable

import jax.numpy as jnp

from .base import Model
from ..ops.encode import EncodeError, NIL, F_READ, F_ADD

MAX_ELEMENT = 30  # bit 31 would flip the int32 sign


def _element_bit(v) -> int:
    v = int(v)
    if not 0 <= v <= MAX_ELEMENT:
        raise EncodeError(
            f"gset elements must be in 0..{MAX_ELEMENT} (got {v}); the set "
            f"state is an int32 bitmask")
    return 1 << v


class GSet(Model):
    name = "gset"
    packable_states = True
    state_offset = 0

    def init_state(self) -> int:
        return 0  # empty set

    def state_bound(self, max_value: int) -> int:
        # Every reachable state is an OR of add-masks, each <= max_value
        # (the largest encoded field), so states fit its bit width. NOT
        # max_value itself: adds of values 0 and 4 give masks 1 and 16 but
        # state 17.
        return (1 << max(int(max_value), 1).bit_length()) - 1

    def encode_invocation(self, f_name, invoke_value, ok_value, status):
        if f_name == "add":
            return F_ADD, _element_bit(invoke_value), 0, NIL
        if f_name == "read":
            if ok_value is None:
                return F_READ, 0, 0, NIL
            mask = 0
            for v in ok_value:
                mask |= _element_bit(v)
            return F_READ, 0, 0, mask
        raise EncodeError(f"unsupported gset op f={f_name!r}")

    def describe_op(self, f, a1, a2, rv):
        if f == F_ADD:
            return f"add({int(a1).bit_length() - 1})"
        if f == F_READ:
            els = [i for i in range(MAX_ELEMENT + 1) if int(rv) >> i & 1]
            return f"read -> {{{', '.join(map(str, els))}}}"
        return super().describe_op(f, a1, a2, rv)

    def step_py(self, state, f, a1, a2, rv):
        if f == F_ADD:
            return (True, state | a1)
        if f == F_READ:
            return (state == rv, state)
        raise ValueError(f"bad f {f}")

    def step(self, state, f, a1, a2, rv):
        is_add = f == F_ADD
        legal = jnp.where(is_add, True, (f == F_READ) & (state == rv))
        nxt = jnp.where(is_add, state | a1, state)
        return legal, nxt.astype(jnp.int32)
