"""State-machine models checked for linearizability.

The reference checks a CAS register via knossos.model/cas-register
(src/jepsen/etcdemo.clj:15,117). Models here expose two equivalent step
functions: `step_py` (Python scalars, used by the oracle checker) and `step`
(branchless array math, traced into the JAX kernel).
"""

from .base import Model  # noqa: F401
from .cas_register import CASRegister  # noqa: F401
from .mutex import Mutex  # noqa: F401
from .register import Register  # noqa: F401

REGISTRY = {
    "cas-register": CASRegister,
    "register": Register,
    "mutex": Mutex,
}


def get_model(name: str) -> Model:
    try:
        return REGISTRY[name]()
    except KeyError:
        raise KeyError(f"unknown model {name!r}; known: {sorted(REGISTRY)}")
