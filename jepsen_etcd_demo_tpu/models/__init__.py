"""State-machine models checked for linearizability.

The reference checks a CAS register via knossos.model/cas-register
(src/jepsen/etcdemo.clj:15,117); the other families mirror the rest of the
knossos model surface the reference ships as a dependency (knossos 0.3.7,
jepsen.etcdemo.iml:58). Models expose two equivalent step functions:
`step_py` (Python scalars, used by the oracle checker) and `step`
(branchless array math, traced into the JAX kernels); richer-than-scalar
states (sets, queues, register files) are bit-packed into one int32 so
every model rides the same flat-int32-frontier kernels.
"""

from .base import Model  # noqa: F401
from .cas_register import CASRegister  # noqa: F401
from .gset import GSet  # noqa: F401
from .multi_register import MultiRegister  # noqa: F401
from .mutex import Mutex  # noqa: F401
from .queues import FIFOQueue, UnorderedQueue  # noqa: F401
from .register import Register  # noqa: F401

REGISTRY = {
    "cas-register": CASRegister,
    "register": Register,
    "mutex": Mutex,
    "gset": GSet,
    "unordered-queue": UnorderedQueue,
    "fifo-queue": FIFOQueue,
    "multi-register": MultiRegister,
}


def get_model(name: str) -> Model:
    try:
        return REGISTRY[name]()
    except KeyError:
        raise KeyError(f"unknown model {name!r}; known: {sorted(REGISTRY)}")
