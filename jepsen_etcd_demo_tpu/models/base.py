"""Model protocol: a deterministic state machine stepped by linearized ops.

Equivalent of knossos.model's Model/step seam (exercised by the reference at
src/jepsen/etcdemo.clj:117). A step either yields a successor state or is
illegal (the knossos "inconsistent" result); the checker prunes illegal
transitions from candidate linearization orders.
"""

from __future__ import annotations

import abc
from typing import Tuple


class Model(abc.ABC):
    """A state machine over int32 scalar states.

    States are int32 scalars so a search frontier is a flat int32 vector.
    Models with richer state must encode it into one int32 (or a future
    vector-state extension of the kernel).
    """

    name: str = "model"

    def cache_key(self) -> tuple:
        """Hashable identity for jit-compilation caches. Two models with equal
        cache keys must have identical step semantics."""
        return (self.name, self.init_state())

    @abc.abstractmethod
    def init_state(self) -> int:
        ...

    @abc.abstractmethod
    def step_py(self, state: int, f: int, a1: int, a2: int, rv: int
                ) -> Tuple[bool, int]:
        """Python-scalar step: (legal, next_state)."""

    @abc.abstractmethod
    def step(self, state, f, a1, a2, rv):
        """Branchless array step: (legal, next_state).

        Must be expressible with arithmetic/where only (no data-dependent
        Python control flow) so it vmaps and compiles on TPU.
        """
