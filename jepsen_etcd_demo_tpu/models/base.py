"""Model protocol: a deterministic state machine stepped by linearized ops.

Equivalent of knossos.model's Model/step seam (exercised by the reference at
src/jepsen/etcdemo.clj:117). A step either yields a successor state or is
illegal (the knossos "inconsistent" result); the checker prunes illegal
transitions from candidate linearization orders.
"""

from __future__ import annotations

import abc
from typing import Tuple


class Model(abc.ABC):
    """A state machine over int32 scalar states.

    States are int32 scalars so a search frontier is a flat int32 vector.
    Models with richer state must encode it into one int32 (or a future
    vector-state extension of the kernel).
    """

    name: str = "model"

    # Packing: when every reachable state is bounded by the values appearing
    # in the history (register-like models), (state, linearized-mask) can
    # live in ONE uint32 sort key — a payload-free single-key dedup in the
    # checker. `packable_states=True` opts in; `state_offset` maps the
    # smallest state (NIL=-1) to 0. The actual bit width is derived from
    # each history's real values via pack_bits(), NEVER from an assumed
    # value range (any int32 value is legal in a history, encode.py:46).
    packable_states: bool = False
    state_offset: int = 0

    def state_bound(self, max_value: int) -> int:
        """Largest shifted state index reachable, given the largest value
        encoded in the history (shift = state_offset, so the result is the
        top ROW index of a dense state table / top packed-key value).

        The reachable range is {init_state()} ∪ history values — the initial
        state counts even when no history value comes near it (a large
        `initial` that silently wrapped into mask bits was a reproduced
        soundness bug). Negative values never reach here: the encoder
        rejects them (NIL=-1 is a reserved sentinel, encode.py). Single
        source of truth for BOTH the packed sort-key dedup (wgl2) and the
        dense lattice table (wgl3)."""
        return max(int(max_value), int(self.init_state())) + self.state_offset

    def pack_bits(self, max_value: int) -> int:
        """Bits needed to pack any reachable state, given the largest value
        encoded in the history; 0 = not packable."""
        if not self.packable_states:
            return 0
        return max(1, self.state_bound(max_value).bit_length())

    def cache_key(self) -> tuple:
        """Hashable identity for jit-compilation caches. Two models with equal
        cache keys must have identical step semantics."""
        return (self.name, self.init_state())

    def prepare_history(self, history):
        """Model-level op translation applied before encoding (e.g. the
        mutex model rewrites acquire/release into CAS ops). Identity by
        default; must return Ops encode_invocation accepts."""
        return history

    def encode_invocation(self, f_name: str, invoke_value, ok_value,
                          status: str) -> Tuple[int, int, int, int]:
        """Op-language codec: map one paired invocation to the (f, a1, a2,
        rv) event-row fields the step functions consume. `ok_value` is the
        completion's value for OK *and* INFO completions (an indeterminate
        op may still carry the value it tried to take), None otherwise.
        Default: the register language (read/write/cas — the reference's
        op set, src/jepsen/etcdemo.clj:67-69). Models with a different op
        language override this; by convention code F_READ must be reserved
        for pure observations (the encoder drops indeterminate F_READ ops
        as constraint-free, ops/encode.py)."""
        from ..ops.encode import register_fields

        return register_fields(f_name, invoke_value, ok_value, status)

    def describe_op(self, f: int, a1: int, a2: int, rv: int) -> str:
        """Human-readable rendering of an encoded op (witness artifacts,
        checkers/witness.py). Default: the register language."""
        from ..ops.encode import NIL, F_READ, F_WRITE, F_CAS

        if f == F_READ:
            return f"read -> {'nil' if rv == NIL else rv}"
        if f == F_WRITE:
            return f"write({a1})"
        if f == F_CAS:
            return f"cas({a1} -> {a2})"
        return f"op({f}, {a1}, {a2}, {rv})"

    @abc.abstractmethod
    def init_state(self) -> int:
        ...

    @abc.abstractmethod
    def step_py(self, state: int, f: int, a1: int, a2: int, rv: int
                ) -> Tuple[bool, int]:
        """Python-scalar step: (legal, next_state)."""

    @abc.abstractmethod
    def step(self, state, f, a1, a2, rv):
        """Branchless array step: (legal, next_state).

        Must be expressible with arithmetic/where only (no data-dependent
        Python control flow) so it vmaps and compiles on TPU.
        """
