"""Queue models — knossos unordered-queue / fifo-queue equivalents.

Part of the knossos model surface the reference ships (knossos 0.3.7,
jepsen.etcdemo.iml:58; the demo itself only instantiates cas-register at
src/jepsen/etcdemo.clj:117). Both models re-design the queue state for the
TPU kernels — branchless int32 bit algebra instead of persistent
collections:

* `UnorderedQueue` — a bag with unique elements 0..30; state is the int32
  characteristic bitmask of the elements currently queued. Enqueue sets a
  bit, dequeue requires-and-clears it; dequeue order is unconstrained
  (that's the "unordered" in knossos's model). Uniqueness is the standard
  jepsen queue-workload shape (each enqueued value is a fresh int) and is
  validated at encode time.

* `FIFOQueue` — a bounded queue over values 0..max_value; state packs up
  to `capacity` digits of `digit_bits` each into one int32, head at the
  low bits. Values are stored as v+1 so digit 0 means "empty slot"; the
  queue is always contiguous from the head (enqueue appends at the first
  zero digit, dequeue shifts right), so the digit count is the queue
  length. Enqueue beyond `capacity` is modelled as illegal, which would
  wrongly prune real linearizations — so encoding REJECTS histories with
  more total enqueues than `capacity` instead of risking a wrong verdict.

Indeterminate (:info) enqueues stay pending forever, exactly like
indeterminate register writes (reference :info mapping,
src/jepsen/etcdemo.clj:100-102). Indeterminate DEQUEUES are encodable only
when the completion records the CLAIMED element (a compare-and-delete whose
response was lost — clients/etcd.py): the op becomes pending-forever with
that value. Without a claimed value they are rejected at encode time —
fixed fields cannot express "removed an unknown element", and silently
dropping it would make the checker accept histories it shouldn't.
"""

from __future__ import annotations

import jax.numpy as jnp

from .base import Model
from ..ops.encode import EncodeError, NIL, F_ENQ, F_DEQ
from ..ops.op import INFO, INVOKE
from .gset import MAX_ELEMENT, _element_bit


class UnorderedQueue(Model):
    name = "unordered-queue"
    packable_states = True
    state_offset = 0

    def init_state(self) -> int:
        return 0  # empty bag

    def state_bound(self, max_value: int) -> int:
        # States are ORs of element bits, each <= max_value (gset argument).
        return (1 << max(int(max_value), 1).bit_length()) - 1

    def prepare_history(self, history):
        seen: set[int] = set()
        for op in history:
            if op.type == INVOKE and op.f == "enqueue":
                v = int(op.value)
                if v in seen:
                    raise EncodeError(
                        f"unordered-queue requires unique enqueue values "
                        f"(duplicate {v}); the bag state is a bitmask")
                seen.add(v)
        return history

    def encode_invocation(self, f_name, invoke_value, ok_value, status):
        if f_name == "enqueue":
            return F_ENQ, _element_bit(invoke_value), 0, NIL
        if f_name == "dequeue":
            if status == INFO and ok_value is None:
                raise EncodeError(
                    "indeterminate dequeue with no claimed value cannot be "
                    "encoded soundly; fail it or record the candidate "
                    "(clients/etcd.py IndeterminateDequeue)")
            if ok_value is None:
                return F_DEQ, 0, 0, NIL  # fail: dropped by the encoder
            # ok, or info with a known claimed element: the op may (info:
            # may never) have removed exactly this element — a pending
            # F_DEQ with rv set is the exact WGL encoding of that.
            return F_DEQ, 0, 0, _element_bit(ok_value)
        raise EncodeError(f"unsupported unordered-queue op f={f_name!r}")

    def describe_op(self, f, a1, a2, rv):
        if f == F_ENQ:
            return f"enqueue({int(a1).bit_length() - 1})"
        if f == F_DEQ:
            return f"dequeue -> {int(rv).bit_length() - 1}"
        return super().describe_op(f, a1, a2, rv)

    def step_py(self, state, f, a1, a2, rv):
        if f == F_ENQ:
            return (True, state | a1)
        if f == F_DEQ:
            return (bool(state & rv), state & ~rv)
        raise ValueError(f"bad f {f}")

    def step(self, state, f, a1, a2, rv):
        is_enq = f == F_ENQ
        is_deq = f == F_DEQ
        legal = jnp.where(is_enq, True, is_deq & ((state & rv) != 0))
        nxt = jnp.where(is_enq, state | a1,
                        jnp.where(is_deq, state & ~rv, state))
        return legal, nxt.astype(jnp.int32)


class FIFOQueue(Model):
    name = "fifo-queue"
    packable_states = True
    state_offset = 0

    def __init__(self, max_value: int = 4, capacity: int = 10):
        # Digit width: v+1 must fit, so bits for max_value+1 (v+1's top).
        self.max_value = int(max_value)
        self.capacity = int(capacity)
        self.digit_bits = (self.max_value + 1).bit_length()
        if self.capacity * self.digit_bits > 30:
            raise ValueError(
                f"fifo-queue state needs {self.capacity * self.digit_bits} "
                f"bits (capacity {self.capacity} x {self.digit_bits}-bit "
                f"digits); int32 admits 30 — shrink capacity or max_value")
        self.digit_mask = (1 << self.digit_bits) - 1

    def cache_key(self) -> tuple:
        return (self.name, self.max_value, self.capacity)

    def init_state(self) -> int:
        return 0  # empty queue

    def state_bound(self, max_value: int) -> int:
        # Fixed by the geometry, not the history's values: any digit layout.
        return (1 << (self.capacity * self.digit_bits)) - 1

    def _check_value(self, v) -> int:
        v = int(v)
        if not 0 <= v <= self.max_value:
            raise EncodeError(
                f"fifo-queue value {v} outside 0..{self.max_value}")
        return v

    def prepare_history(self, history):
        enqueues = sum(1 for op in history
                       if op.type == INVOKE and op.f == "enqueue")
        if enqueues > self.capacity:
            raise EncodeError(
                f"history has {enqueues} enqueues but fifo-queue capacity "
                f"is {self.capacity}: a linearization could overflow the "
                f"bounded state and be wrongly pruned — raise capacity")
        return history

    def encode_invocation(self, f_name, invoke_value, ok_value, status):
        if f_name == "enqueue":
            return F_ENQ, self._check_value(invoke_value), 0, NIL
        if f_name == "dequeue":
            if status == INFO and ok_value is None:
                raise EncodeError(
                    "indeterminate dequeue with no claimed value cannot be "
                    "encoded soundly; fail it or record the candidate "
                    "(clients/etcd.py IndeterminateDequeue)")
            if ok_value is None:
                return F_DEQ, 0, 0, NIL  # fail: dropped by the encoder
            # ok, or info with a known claimed element (see UnorderedQueue).
            return F_DEQ, 0, 0, self._check_value(ok_value)
        raise EncodeError(f"unsupported fifo-queue op f={f_name!r}")

    def describe_op(self, f, a1, a2, rv):
        if f == F_ENQ:
            return f"enqueue({a1})"
        if f == F_DEQ:
            return f"dequeue -> {rv}"
        return super().describe_op(f, a1, a2, rv)

    def _digits(self, state):
        b, m = self.digit_bits, self.digit_mask
        return [(state >> (i * b)) & m for i in range(self.capacity)]

    def step_py(self, state, f, a1, a2, rv):
        b, m = self.digit_bits, self.digit_mask
        if f == F_ENQ:
            length = sum(1 for d in self._digits(state) if d != 0)
            if length >= self.capacity:
                return (False, state)
            return (True, state | ((a1 + 1) << (length * b)))
        if f == F_DEQ:
            head = state & m
            return (head == rv + 1, state >> b)
        raise ValueError(f"bad f {f}")

    def step(self, state, f, a1, a2, rv):
        b, m, cap = self.digit_bits, self.digit_mask, self.capacity
        is_enq = f == F_ENQ
        is_deq = f == F_DEQ
        # Queue length = count of nonzero digits (contiguous from head).
        length = sum((((state >> (i * b)) & m) != 0).astype(jnp.int32)
                     for i in range(cap))
        can_enq = is_enq & (length < cap)
        # Shift for the append position; clamp so the computed (discarded)
        # value at length==cap stays in-word.
        enq_shift = jnp.minimum(length, cap - 1) * b
        enq_state = state | ((a1 + 1) << enq_shift)
        head = state & m
        can_deq = is_deq & (head == rv + 1)
        legal = can_enq | can_deq
        nxt = jnp.where(can_enq, enq_state,
                        jnp.where(is_deq, state >> b, state))
        return legal, nxt.astype(jnp.int32)
