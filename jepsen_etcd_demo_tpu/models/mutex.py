"""Mutex model — knossos model/mutex equivalent.

Part of the knossos model surface the reference ships (knossos 0.3.7,
jepsen.etcdemo.iml:58; the demo itself only instantiates cas-register at
src/jepsen/etcdemo.clj:117). Semantics: `acquire` is legal iff unlocked,
`release` iff locked — i.e. exactly a CAS register over {0 unlocked,
1 locked} with acquire = cas(0->1) and release = cas(1->0). The model
therefore REUSES the CAS step function (same kernel, same packing) and
contributes only the op translation, applied before encoding via
prepare_history().
"""

from __future__ import annotations

from dataclasses import replace
from typing import Sequence

from .cas_register import CASRegister
from ..ops.op import Op

UNLOCKED, LOCKED = 0, 1


class Mutex(CASRegister):
    name = "mutex"

    def __init__(self):
        super().__init__(initial=UNLOCKED)

    def describe_op(self, f, a1, a2, rv):
        from ..ops.encode import F_CAS

        if f == F_CAS and (a1, a2) == (UNLOCKED, LOCKED):
            return "acquire"
        if f == F_CAS and (a1, a2) == (LOCKED, UNLOCKED):
            return "release"
        return super().describe_op(f, a1, a2, rv)

    def prepare_history(self, history: Sequence[Op]) -> list[Op]:
        out = []
        for op in history:
            if op.f == "acquire":
                out.append(replace(op, f="cas", value=(UNLOCKED, LOCKED)))
            elif op.f == "release":
                out.append(replace(op, f="cas", value=(LOCKED, UNLOCKED)))
            else:
                raise ValueError(
                    f"mutex history may only acquire/release, got {op.f!r}")
        return out
