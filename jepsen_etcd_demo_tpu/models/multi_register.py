"""Multi-register model — knossos multi-register equivalent.

Part of the knossos model surface the reference ships (knossos 0.3.7,
jepsen.etcdemo.iml:58). An array of `n_registers` independent registers,
read and written one at a time: `write(i, v)` / `read(i) -> v`.

TPU-first state design: the register file packs into ONE int32 — each
register is a `digit_bits`-wide field holding v+1 (0 = never written /
NIL, matching the reference's missing-key reads, src/jepsen/etcdemo.clj:
87-90) — so a step is two shifts and a mask, branchless, and the frontier
stays a flat int32 vector like every other model. With small geometries
(e.g. 2 registers over values 0..2) the whole state space fits the dense
subset-lattice kernel's 32-state table.

Op language (encode_invocation): values are (index, value) pairs —
`write` carries both on the invoke; `read` carries the index on the
invoke and the observed value on the ok completion ((i, v), v alone, or
None for a never-written register).
"""

from __future__ import annotations

import jax.numpy as jnp

from .base import Model
from ..ops.encode import EncodeError, NIL, F_READ, F_WRITE


class MultiRegister(Model):
    name = "multi-register"
    packable_states = True
    state_offset = 0

    def __init__(self, n_registers: int = 3, max_value: int = 4):
        self.n_registers = int(n_registers)
        self.max_value = int(max_value)
        self.digit_bits = (self.max_value + 1).bit_length()
        if self.n_registers * self.digit_bits > 30:
            raise ValueError(
                f"multi-register state needs "
                f"{self.n_registers * self.digit_bits} bits "
                f"({self.n_registers} x {self.digit_bits}-bit registers); "
                f"int32 admits 30 — shrink n_registers or max_value")
        self.digit_mask = (1 << self.digit_bits) - 1

    def cache_key(self) -> tuple:
        return (self.name, self.n_registers, self.max_value)

    def init_state(self) -> int:
        return 0  # every register NIL (never written)

    def state_bound(self, max_value: int) -> int:
        # Fixed by the geometry, not the history's values.
        return (1 << (self.n_registers * self.digit_bits)) - 1

    def _check_index(self, i) -> int:
        i = int(i)
        if not 0 <= i < self.n_registers:
            raise EncodeError(
                f"register index {i} outside 0..{self.n_registers - 1}")
        return i

    def _check_value(self, v) -> int:
        v = int(v)
        if not 0 <= v <= self.max_value:
            raise EncodeError(
                f"multi-register value {v} outside 0..{self.max_value}")
        return v

    def encode_invocation(self, f_name, invoke_value, ok_value, status):
        if f_name == "write":
            i, v = invoke_value
            return F_WRITE, self._check_index(i), self._check_value(v), NIL
        if f_name == "read":
            # Invoke carries (i, _) or bare i; the ok completion carries the
            # observed value as (i, v) or bare v; None = register unwritten.
            i = (invoke_value[0] if isinstance(invoke_value, (tuple, list))
                 else invoke_value)
            i = self._check_index(i)
            if ok_value is None:
                return F_READ, i, 0, NIL
            v = (ok_value[1] if isinstance(ok_value, (tuple, list))
                 else ok_value)
            return F_READ, i, 0, (NIL if v is None else self._check_value(v))
        raise EncodeError(f"unsupported multi-register op f={f_name!r}")

    def describe_op(self, f, a1, a2, rv):
        if f == F_WRITE:
            return f"write(r{a1} = {a2})"
        if f == F_READ:
            return f"read(r{a1}) -> {'nil' if rv == NIL else rv}"
        return super().describe_op(f, a1, a2, rv)

    def step_py(self, state, f, a1, a2, rv):
        b, m = self.digit_bits, self.digit_mask
        shift = a1 * b
        digit = (state >> shift) & m
        if f == F_READ:
            return (digit == rv + 1, state)
        if f == F_WRITE:
            return (True, (state & ~(m << shift)) | ((a2 + 1) << shift))
        raise ValueError(f"bad f {f}")

    def step(self, state, f, a1, a2, rv):
        b, m = self.digit_bits, self.digit_mask
        shift = a1 * b
        digit = (state >> shift) & m
        is_read = f == F_READ
        is_write = f == F_WRITE
        legal = jnp.where(is_read, digit == rv + 1, is_write)
        nxt = jnp.where(is_write,
                        (state & ~(m << shift)) | ((a2 + 1) << shift), state)
        return legal, nxt.astype(jnp.int32)
