"""Plain read/write register (no CAS) — the simplest linearizability model.

Not used by the reference demo directly (it always checks cas-register,
src/jepsen/etcdemo.clj:117) but part of the knossos model family the checker
seam supports; useful for tests and for histories without CAS ops.
"""

from __future__ import annotations

import jax.numpy as jnp

from .base import Model
from ..ops.encode import NIL, F_READ, F_WRITE


class Register(Model):
    name = "register"
    packable_states = True  # states ⊆ {initial} ∪ history values

    def __init__(self, initial: int = NIL):
        self.initial = initial
        self.state_offset = -min(NIL, initial)

    def init_state(self) -> int:
        return self.initial

    def step_py(self, state, f, a1, a2, rv):
        if f == F_READ:
            return (state == rv, state)
        if f == F_WRITE:
            return (True, a1)
        return (False, state)  # cas unsupported in the plain register

    def step(self, state, f, a1, a2, rv):
        is_read = f == F_READ
        is_write = f == F_WRITE
        legal = jnp.where(is_read, state == rv, is_write)
        nxt = jnp.where(is_write, a1, state)
        return legal, nxt.astype(jnp.int32)
