"""CAS register: the model the reference's linearizability check runs over.

Semantics mirror knossos.model/cas-register as exercised by the demo
(src/jepsen/etcdemo.clj:117; client semantics :83-105):
  read  — legal iff the current value equals the observed value `rv`
          (NIL means the key was absent / parse-long of nil, :87-90).
  write — always legal; sets the value (:92-93).
  cas   — legal iff current value == old (a1); sets value to new (a2)
          (:95-98). A cas that returned :fail never reaches the model: failed
          ops are excluded from the history (encode.py).
"""

from __future__ import annotations

import jax.numpy as jnp

from .base import Model
from ..ops.encode import NIL, F_READ, F_WRITE, F_CAS


class CASRegister(Model):
    name = "cas-register"
    packable_states = True  # states ⊆ {initial} ∪ history values

    def __init__(self, initial: int = NIL):
        self.initial = initial
        self.state_offset = -min(NIL, initial)

    def init_state(self) -> int:
        return self.initial

    def step_py(self, state, f, a1, a2, rv):
        if f == F_READ:
            return (state == rv, state)
        if f == F_WRITE:
            return (True, a1)
        if f == F_CAS:
            return (state == a1, a2 if state == a1 else state)
        raise ValueError(f"bad f {f}")

    def step(self, state, f, a1, a2, rv):
        is_read = f == F_READ
        is_write = f == F_WRITE
        is_cas = f == F_CAS
        legal = jnp.where(is_read, state == rv,
                          jnp.where(is_cas, state == a1, is_write))
        nxt = jnp.where(is_write, a1,
                        jnp.where(is_cas & (state == a1), a2, state))
        return legal, nxt.astype(jnp.int32)
