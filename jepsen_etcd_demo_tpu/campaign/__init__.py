"""campaign — the scenario factory (ISSUE 15 tentpole; ROADMAP item 5).

The harness can check millions of configs/s (sched/, stream/, serve/)
but until now explored scenarios one handcrafted `jepsen-tpu test` at a
time. This package closes that gap: the MACHINE imagines the scenarios,
runs them at high concurrency, and turns what falsifies into a
regression corpus.

  * specs.py   — deterministic ScenarioSpec sampler over the existing
    generator algebra (mix/stagger/phases, compose.py) × workload
    families × nemesis schedules × injectable-bug axes × cluster
    shapes. Same seed -> same spec list, always.
  * vclock.py  — the virtual-time asyncio loop that executes a REAL
    composed fake_test deterministically and at memory speed: every
    stagger delay, nemesis sleep and time-limit is virtual, so a
    30-virtual-second scenario runs in milliseconds and two runs of the
    same spec produce the IDENTICAL history.
  * cluster.py — the in-process minietcd cluster (db/minietcd.py's
    KeyStore + HTTP handler served from ephemeral ports inside this
    process) for live-backend scenarios — the substrate the new fault
    planes (nemesis/cluster_faults.py: member churn, disk faults,
    lease skew) operate on.
  * engine.py  — the executor: runs specs (virtual or live, live with
    stream/'s fail-fast abort), batches every per-key history through
    sched.check_corpus so campaign throughput rides the same bucket /
    warm-kernel-pool discipline as everything else (or submits them to
    the serve scheduler as the "campaign" background tenant).
  * triage.py  — anomaly signatures (dedupe falsifying runs) and the
    TPU-parallel ddmin shrinker: every delta-debugging round's
    candidate op-subsets are re-checked as ONE vmapped corpus launch.
  * bank.py    — the regression corpus: minimal witnesses persisted
    under store/corpus/ with full spec provenance, replayed by
    `jepsen-tpu campaign --replay-corpus`, the bench campaign lane and
    tier-1.

See doc/campaign.md for the spec schema, the signature taxonomy, the
batched-ddmin soundness argument and capacity planning.
"""

from .bank import BankedWitness, bank_witness, load_corpus, replay_corpus
from .engine import CampaignReport, run_campaign
from .specs import ScenarioSpec, sample_specs
from .triage import Signature, classify, ddmin_shrink, verify_routes

__all__ = [
    "BankedWitness",
    "CampaignReport",
    "ScenarioSpec",
    "Signature",
    "bank_witness",
    "classify",
    "ddmin_shrink",
    "load_corpus",
    "replay_corpus",
    "run_campaign",
    "sample_specs",
    "verify_routes",
]
