"""Virtual-time asyncio: deterministic, instant execution of a real run.

The runner (runner/core.py) is an asyncio interpreter whose only
nondeterminism sources are wall-clock time (the recorder's monotonic
clock drives stagger/time-limit/sleep generators) and the scheduling
jitter real sleeps introduce. Replace the clock and both vanish: this
module's loop never blocks in `select` — when the loop would sleep for
its next timer it ADVANCES a virtual clock by that amount instead — and
the recorder reads that same virtual clock. The result:

  * a 30-virtual-second scenario executes in milliseconds of real time
    (the campaign's specs/s comes from here, not from trimming the
    generator schedules);
  * two executions of the same composed test with the same seed produce
    the IDENTICAL history (timer order is (when, tiebreak-counter),
    ready-queue order is FIFO, no foreign wakeups) — the determinism
    the spec/verdict reproducibility contract stands on.

Only loops with NO real I/O qualify: the fake in-process cluster
(clients/fake_kv.py) awaits locks and sleeps exclusively, so fake_test
compositions run here; live minietcd scenarios (campaign/cluster.py)
use a normal loop — HTTP round-trips are real time.
"""

from __future__ import annotations

import asyncio
import selectors
from typing import Awaitable, Callable, TypeVar

from ..runner.history import HistoryRecorder

T = TypeVar("T")


class _InstantSelector:
    """Selector shim that never blocks: a `select(timeout)` that would
    have slept advances the owning loop's virtual clock by `timeout`
    and polls (timeout 0) instead. Registration calls delegate to a
    real selector so the loop's self-pipe keeps working."""

    def __init__(self, loop: "VirtualTimeLoop"):
        self._loop = loop
        self._inner = selectors.DefaultSelector()

    def select(self, timeout=None):
        if timeout:
            self._loop._vtime += timeout
        return self._inner.select(0)

    def register(self, *a, **kw):
        return self._inner.register(*a, **kw)

    def unregister(self, *a, **kw):
        return self._inner.unregister(*a, **kw)

    def modify(self, *a, **kw):
        return self._inner.modify(*a, **kw)

    def get_map(self):
        return self._inner.get_map()

    def get_key(self, fileobj):
        return self._inner.get_key(fileobj)

    def close(self):
        return self._inner.close()


class VirtualTimeLoop(asyncio.SelectorEventLoop):
    """SelectorEventLoop whose `time()` is a virtual clock advanced by
    would-be sleeps. Timers (`call_later`, and everything built on them:
    asyncio.sleep, wait_for, Condition timeouts) fire in exact virtual
    order with zero real delay."""

    def __init__(self):
        self._vtime = 0.0
        super().__init__(None)
        self._selector = _InstantSelector(self)

    def time(self) -> float:
        return self._vtime


class VirtualRecorder(HistoryRecorder):
    """HistoryRecorder whose clock is the virtual loop's, so generator
    combinators (stagger/time-limit/sleep) see virtual time and the
    recorded op timestamps are deterministic."""

    def __init__(self, loop: VirtualTimeLoop, listener=None):
        super().__init__(start_ns=0, listener=listener)
        self._loop = loop

    def now(self) -> int:
        return int(self._loop.time() * 1e9)


def run_virtual(main: Callable[[VirtualTimeLoop, VirtualRecorder],
                               Awaitable[T]]) -> T:
    """Run `main(loop, recorder)` to completion on a fresh virtual-time
    loop. The loop is private to this call (never installed as the
    thread default beyond it) so campaign executor threads can each
    drive their own scenario concurrently."""
    loop = VirtualTimeLoop()
    recorder = VirtualRecorder(loop)
    try:
        asyncio.set_event_loop(loop)
        return loop.run_until_complete(main(loop, recorder))
    finally:
        asyncio.set_event_loop(None)
        loop.close()
