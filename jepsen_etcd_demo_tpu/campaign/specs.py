"""ScenarioSpec: the campaign's deterministic scenario language.

A spec is plain data naming one point in the scenario space the
existing composition layer (compose.py) already spans: a workload
family, the generator schedule knobs (rate/stagger, ops-per-key, phase
lengths — compose.add_phase_generator's mix/stagger/phases algebra),
a nemesis schedule, a cluster shape, the injectable-bug axes of the
fake cluster (clients/fake_kv.py) or the live minietcd fault planes
(nemesis/cluster_faults.py), and a seed. `sample_specs` is a pure
function of (n, seed, options): same inputs -> same spec list, byte for
byte — the determinism the campaign's reproducibility contract (and
tests/test_campaign.py) stands on.

Families are the linearizability-checked workloads (the fuzz families
of utils/fuzz.py): register / gset / queue / multiregister. The
durability-only `set` workload and the combinatorial `mutex` workload
are deliberately out (nothing to shrink / DNF-shaped); the elle txn
families have their own streaming path and are future campaign work
(doc/campaign.md).
"""

from __future__ import annotations

import hashlib
import random
from dataclasses import asdict, dataclass, field
from typing import Any, Optional

# family -> (linearizability model, keyed under the independent wrapper?)
FAMILY_MODELS: dict[str, tuple[str, bool]] = {
    "register": ("cas-register", True),
    "gset": ("gset", False),
    "queue": ("fifo-queue", True),
    "multiregister": ("multi-register", False),
}

# Injectable-bug axes that the family's checker can actually falsify
# (a seeded bug a family cannot observe would dilute the campaign's
# falsification rate for nothing).
FAMILY_FAULTS: dict[str, tuple[str, ...]] = {
    "register": ("stale_read_prob", "lost_write_prob",
                 "duplicate_cas_prob"),
    "gset": ("stale_read_prob",),
    "queue": ("reorder_prob", "duplicate_delivery_prob"),
    "multiregister": ("stale_read_prob", "lost_write_prob"),
}

# Nemesis kinds per backend. The sim backend drives the fake store's
# fault hooks (compose.pick_nemesis fakes); the minietcd backend drives
# the new cluster fault planes (nemesis/cluster_faults.py).
SIM_NEMESES = ("noop", "partition", "partition-node", "clock")
CLUSTER_NEMESES = ("noop", "member-churn", "disk-full", "corrupt-write",
                   "lease-skew")


@dataclass(frozen=True)
class ScenarioSpec:
    """One deterministic scenario. Frozen: a spec is identity — the
    campaign report, the triage signatures and the corpus bank's
    provenance all reference it by value."""

    spec_id: int
    family: str                      # FAMILY_MODELS key
    backend: str                     # "sim" | "minietcd"
    seed: int                        # every rng in the scenario derives
    concurrency: int
    rate: float                      # Hz across all client workers
    time_limit: float                # main-phase seconds (virtual on sim)
    ops_per_key: int
    nemesis: str
    nemesis_interval: float
    recovery_wait: float
    quorum: bool
    op_delay: float                  # store-side latency (virtual) — the
    #                                  source of overlapping op windows
    faults: dict[str, float] = field(default_factory=dict)
    nodes: int = 5

    @property
    def model_name(self) -> str:
        return FAMILY_MODELS[self.family][0]

    @property
    def keyed(self) -> bool:
        return FAMILY_MODELS[self.family][1]

    def to_dict(self) -> dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "ScenarioSpec":
        return cls(**{k: v for k, v in d.items()
                      if k in cls.__dataclass_fields__})

    def test_opts(self) -> dict[str, Any]:
        """The compose.fake_test / cluster-test opts this spec names.
        store_root stays None: campaign runs are checked in batch, not
        persisted one dir per scenario (the corpus bank persists what
        matters — the minimal witnesses)."""
        opts = {
            "workload": self.family,
            "seed": self.seed,
            "store_root": None,
            "concurrency": self.concurrency,
            "rate": self.rate,
            "time_limit": self.time_limit,
            "ops_per_key": self.ops_per_key,
            "recovery_wait": self.recovery_wait,
            "nemesis_interval": self.nemesis_interval,
            "quorum": self.quorum,
            "nodes": [f"n{i + 1}" for i in range(self.nodes)],
            "op_delay": self.op_delay,
            "no_nemesis": self.nemesis == "noop",
            "nemesis": "noop" if self.nemesis == "noop" else self.nemesis,
            # The batched campaign check owns the search budget; the
            # per-run composition must not also arm one.
            "check_budget_s": 0,
        }
        opts.update(self.faults)
        return opts


def spec_seed(campaign_seed: int, spec_id: int) -> int:
    """Stable per-spec seed: a hash, not campaign_seed + spec_id, so
    two campaigns at nearby seeds don't share prefix scenarios."""
    h = hashlib.sha1(f"{campaign_seed}:{spec_id}".encode()).digest()
    return int.from_bytes(h[:8], "big") & 0x7FFFFFFF


def sample_specs(n: int, seed: int,
                 families: Optional[list[str]] = None,
                 bug_rate: float = 0.25,
                 live: int = 0,
                 scale: float = 1.0) -> list[ScenarioSpec]:
    """Compose `n` deterministic scenarios. `bug_rate` is the fraction
    carrying a seeded injectable bug (the campaign's falsification
    supply); `live` caps how many run on the in-process minietcd
    cluster backend (real HTTP, real wall clock — spent on the new
    fault planes); `scale` multiplies the schedule sizes (bench lanes
    pass <1 for smoke-sized scenarios).

    Purely a function of its arguments: same (n, seed, families,
    bug_rate, live, scale) -> same list.
    """
    fams = list(families or FAMILY_MODELS)
    unknown = [f for f in fams if f not in FAMILY_MODELS]
    if unknown:
        raise ValueError(
            f"unknown campaign families {unknown}; have "
            f"{sorted(FAMILY_MODELS)}")
    rng = random.Random(seed)
    specs: list[ScenarioSpec] = []
    for i in range(n):
        family = fams[rng.randrange(len(fams))]
        # Live lane: the first `live` specs draw the cluster backend —
        # register family only (the minietcd data plane speaks the
        # register/queue v2 surface; register keeps the lane uniform).
        is_live = i < live
        backend = "minietcd" if is_live else "sim"
        if is_live:
            family = "register"
        nemeses = CLUSTER_NEMESES if is_live else SIM_NEMESES
        nemesis = nemeses[rng.randrange(len(nemeses))]
        faults: dict[str, float] = {}
        seeded_bug = rng.random() < bug_rate
        if seeded_bug and not is_live:
            axis = FAMILY_FAULTS[family][
                rng.randrange(len(FAMILY_FAULTS[family]))]
            faults[axis] = round(rng.uniform(0.15, 0.5), 3)
        elif seeded_bug and nemesis == "member-churn":
            # The live lane's seeded bugs ARE the fault planes: disk
            # faults and lease skew falsify whenever they fire, but
            # member churn is healthy by default — its bug is the
            # forked (stale-replica) standby, armed here so sampled
            # campaigns can actually reach it
            # (engine._execute_live -> MemberChurnNemesis(fork=True)).
            faults["churn_fork"] = 1.0
        specs.append(ScenarioSpec(
            spec_id=i,
            family=family,
            backend=backend,
            seed=spec_seed(seed, i),
            concurrency=rng.choice((4, 5, 8, 10)),
            rate=float(rng.choice((10, 25, 50, 100))),
            # Live scenarios pay real wall clock: keep their schedules
            # a fraction of the virtual ones' regardless of scale.
            time_limit=round((0.8 if is_live
                              else max(1.0, scale * rng.uniform(4, 12))),
                             2),
            ops_per_key=max(4, int(scale * rng.choice((10, 20, 40)))),
            nemesis=nemesis,
            # Live runs pay real wall clock on a short time_limit, so
            # the fault window must FIT: interval <= time_limit/3
            # leaves room for :start, the fault to bite, and the :stop
            # leg (the disk planes falsify only via :stop's
            # crash-restart) all inside the run. Virtual-time sims can
            # afford lazier schedules.
            nemesis_interval=round(rng.uniform(0.1, 0.25) if is_live
                                   else rng.uniform(0.5, 2.0), 2),
            recovery_wait=0.5 if not is_live else 0.1,
            quorum=bool(rng.random() < 0.3),
            op_delay=round(rng.uniform(0.0, 0.01), 4),
            faults=faults,
            nodes=rng.choice((3, 5)),
        ))
    return specs
