"""The regression corpus bank: minimal witnesses under store/corpus/.

Every shrunk, route-verified counterexample the campaign finds is
banked as one JSON file under

    <store>/corpus/<signature-slug>/<content-hash>.json

carrying the minimal history, the checker expectation (valid False +
dead_step), the signature, and full provenance: the ScenarioSpec that
produced it, the campaign (seed, spec count) it ran in, and the shrink
accounting (from/to op counts, rounds, candidate checks). File names
are content hashes (signature + model + history bytes), so re-running
the same campaign re-banks byte-identically instead of duplicating —
and the bank's CONTENT is deterministic even though `banked_at` is not
part of the hash.

`replay_corpus` is the regression lane: load every banked witness,
re-check them all in one corpus-batched launch per model (the same
bucket/warm-pool discipline the campaign used), and demand each still
falsifies with its banked dead_step. `jepsen-tpu campaign
--replay-corpus`, the bench campaign lane and tier-1 all drive it.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass
from datetime import datetime, timezone
from pathlib import Path
from typing import Optional

from ..ops.op import Op, history_from_jsonl, history_to_jsonl
from ..store.store import CORPUS_DIRNAME
from .triage import Signature

BANK_VERSION = 1


@dataclass
class BankedWitness:
    path: Path
    signature: dict
    model: str
    history: list[Op]
    expect: dict
    spec: dict
    campaign: dict
    shrink: dict

    @classmethod
    def load(cls, path: Path) -> "BankedWitness":
        d = json.loads(path.read_text())
        return cls(path=path, signature=d["signature"], model=d["model"],
                   history=history_from_jsonl(d["history"]),
                   expect=d["expect"], spec=d.get("spec", {}),
                   campaign=d.get("campaign", {}),
                   shrink=d.get("shrink", {}))


def corpus_root(store_root: str | Path) -> Path:
    return Path(store_root) / CORPUS_DIRNAME


def _content_hash(sig_slug: str, model: str, history_jsonl: str) -> str:
    h = hashlib.sha1()
    h.update(sig_slug.encode())
    h.update(model.encode())
    h.update(history_jsonl.encode())
    return h.hexdigest()[:16]


def bank_witness(store_root: str | Path, sig: Signature, model: str,
                 history: list[Op], expect: dict, spec: dict,
                 campaign: dict, shrink: dict) -> Path:
    """Persist one minimal witness; idempotent by content hash."""
    hist_jsonl = history_to_jsonl(history)
    name = _content_hash(sig.slug, model, hist_jsonl)
    out = corpus_root(store_root) / sig.slug / f"{name}.json"
    if out.exists():
        return out
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps({
        "version": BANK_VERSION,
        "signature": sig.to_dict(),
        "model": model,
        "history": hist_jsonl,
        "expect": expect,
        "spec": spec,
        "campaign": campaign,
        "shrink": shrink,
        "banked_at": datetime.now(timezone.utc).isoformat(),
    }, indent=2))
    return out


def load_corpus(store_root: str | Path) -> list[BankedWitness]:
    """Every banked witness, in deterministic (slug, hash) order.
    Unreadable entries are skipped with a stderr note, never fatal —
    the replay lane must report on the healthy majority."""
    import sys

    root = corpus_root(store_root)
    out: list[BankedWitness] = []
    if not root.is_dir():
        return out
    for path in sorted(root.glob("*/*.json")):
        try:
            out.append(BankedWitness.load(path))
        except (ValueError, KeyError, OSError) as e:
            print(f"# skipping corpus entry {path}: {e}", file=sys.stderr)
    return out


def replay_corpus(store_root: str | Path,
                  route_check=None) -> dict:
    """Re-falsify the whole bank: one corpus-batched launch per model
    (via `route_check(encs, model) -> results`, default
    sched.check_corpus). Returns the replay report; `ok` is False when
    any banked witness no longer falsifies (a checker regression — the
    exact event the bank exists to catch) or falsifies at a different
    dead_step than banked."""
    from .. import obs, sched
    from ..checkers.linearizable import Linearizable

    if route_check is None:
        def route_check(encs, model):
            results, _kernel, _stats = sched.check_corpus(encs, model)
            return results

    entries = load_corpus(store_root)
    failures: list[dict] = []
    checked = 0
    by_model: dict[str, list[BankedWitness]] = {}
    for w in entries:
        by_model.setdefault(w.model, []).append(w)
    for model_name in sorted(by_model):
        group = by_model[model_name]
        lin = Linearizable(model=model_name)
        encs, bank = [], []
        for w in group:
            try:
                encs.append(lin.encode(w.history))
                bank.append(w)
            except Exception as e:
                failures.append({"path": str(w.path),
                                 "error": f"encode: {e}"})
        if not encs:
            continue
        results = route_check(encs, lin.model)
        checked += len(encs)
        for w, one in zip(bank, results):
            if one.get("valid") is not False:
                failures.append({
                    "path": str(w.path),
                    "error": f"no longer falsifies (valid="
                             f"{one.get('valid')!r})"})
            elif int(one.get("dead_step", -1)) \
                    != int(w.expect.get("dead_step", -1)):
                failures.append({
                    "path": str(w.path),
                    "error": f"dead_step drifted: banked "
                             f"{w.expect.get('dead_step')} vs "
                             f"{one.get('dead_step')}"})
    m = obs.get_metrics()
    m.counter("campaign.replayed").add(checked)
    if failures:
        m.counter("campaign.replay_failures").add(len(failures))
    return {
        "ok": not failures,
        "entries": len(entries),
        "checked": checked,
        "signatures": len({w.signature.get("slug") for w in entries}),
        "failures": failures,
    }


def bank_summary(store_root: str | Path) -> Optional[dict]:
    """Cheap index-page summary: witness count per signature slug (a
    directory listing, no JSON parse). None when no bank exists."""
    root = corpus_root(store_root)
    if not root.is_dir():
        return None
    per_sig = {d.name: len(list(d.glob("*.json")))
               for d in sorted(root.iterdir()) if d.is_dir()}
    per_sig = {k: v for k, v in per_sig.items() if v}
    if not per_sig:
        return None
    return {"signatures": per_sig, "total": sum(per_sig.values())}
