"""The campaign executor: run thousands of scenarios, batch-check them,
triage what falsifies, shrink, bank.

Phases (one obs capture and one warm kernel pool span all of them):

  1. **Execute.** Sim specs run a REAL composed fake_test on the
     virtual-time loop (vclock.py) — deterministic, milliseconds per
     scenario — across a worker thread pool; live specs run
     sequentially against a fresh in-process minietcd cluster
     (cluster.py) with stream/'s fail-fast session attached, so a
     falsified live run aborts the moment the streamed frontier dies
     instead of burning its time limit. (Live specs are sequential on
     purpose: the disk-fault plane scopes a process-wide env gate to
     its fault window, and live wall clock is real either way.)
  2. **Check.** Every run's per-key histories are encoded once and
     checked in model-grouped corpus batches — `route="direct"` goes
     straight through sched.check_corpus (the bucket/warm-pool
     discipline everything else rides); `route="serve"` submits the
     same waves to a CoalescingScheduler as the CAMPAIGN_TENANT — the
     campaign as one more tenant of checking-as-a-service, WFQ'd
     against interactive traffic.
  3. **Triage.** Falsifying keys classify into anomaly signatures
     (triage.classify); duplicates dedupe; the smallest witness per
     signature delta-debugs to a 1-minimal counterexample with every
     ddmin round's candidates re-checked as ONE batched launch.
  4. **Bank.** Minimal witnesses that re-verify bit-identical across
     the dense / batched / oracle routes (triage.verify_routes) land in
     the regression corpus (bank.py) with full spec provenance.

Determinism: same (specs, seed) -> same histories (sim), same verdicts,
same signatures, same minimal witnesses — pinned by
tests/test_campaign.py. Wall-clock fields (specs_per_sec) are reported,
not part of that contract.
"""

from __future__ import annotations

import asyncio
import logging
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from .. import obs, sched
from ..checkers.independent import split_by_key
from ..checkers.linearizable import Linearizable
from ..ops.encode import EncodeError
from ..ops.op import Op
from ..runner.history import HistoryRecorder
from . import triage
from .bank import bank_witness
from .specs import ScenarioSpec, sample_specs
from .vclock import run_virtual

log = logging.getLogger(__name__)

# Check-wave size: histories per corpus submission. Bounds host-side
# stacking memory and, on the serve route, respects the per-tenant
# admission bound (waves are re-chunked to max_inflight there).
WAVE = 512

# Combinatorial-history guard: a history with more simultaneously
# pending (mostly forever-pending, reincarnation-piled) ops than this
# explodes the sort-kernel frontier as C(pending, k) — the knossos-DNF
# shape the runner's per-run check budget converts to "unknown". The
# campaign has no per-key budget to burn (throughput IS the product),
# so such keys are skipped up front and counted
# (campaign.keys_skipped_hard / report.keys_skipped_hard) — an honest
# "unknown", never a silent drop. Nemesis-heavy partition scenarios at
# high rate produce a few per thousand keys.
HARD_PENDING_CAP = 24


@dataclass
class SpecOutcome:
    """One executed scenario, pre-check."""

    spec: ScenarioSpec
    keyed: dict[Any, list[Op]] = field(default_factory=dict)
    ops: int = 0
    aborted: bool = False
    error: Optional[str] = None


@dataclass
class CampaignReport:
    seed: int
    route: str
    specs: int = 0
    executed: int = 0
    run_errors: int = 0
    aborted_runs: int = 0
    keys_checked: int = 0
    keys_skipped_hard: int = 0
    encode_errors: int = 0
    falsified_runs: int = 0
    falsified_keys: int = 0
    signatures: dict[str, dict] = field(default_factory=dict)
    shrinks: list[dict] = field(default_factory=list)
    banked: list[str] = field(default_factory=list)
    replay: Optional[dict] = None
    # Folded check_corpus launch stats — direct route only (the serve
    # route's batches belong to the scheduler; see serve_route).
    sched: dict = field(default_factory=dict)
    wall_s: float = 0.0
    specs_per_sec: float = 0.0

    def to_dict(self) -> dict:
        return {
            "seed": self.seed, "route": self.route, "specs": self.specs,
            "executed": self.executed, "run_errors": self.run_errors,
            "aborted_runs": self.aborted_runs,
            "keys_checked": self.keys_checked,
            "keys_skipped_hard": self.keys_skipped_hard,
            "encode_errors": self.encode_errors,
            "falsified_runs": self.falsified_runs,
            "falsified_keys": self.falsified_keys,
            "unique_signatures": len(self.signatures),
            "signatures": self.signatures,
            "shrinks": self.shrinks,
            "banked": self.banked,
            "replay": self.replay,
            "sched": self.sched,
            "wall_s": round(self.wall_s, 3),
            "specs_per_sec": round(self.specs_per_sec, 2),
        }


# -- execute ----------------------------------------------------------------

def _execute_sim(spec: ScenarioSpec) -> SpecOutcome:
    """One deterministic virtual-time run of the composed fake test.
    fake_test builds the FakeKVStore straight from the opts —
    spec.test_opts() already carries seed, op_delay and every seeded
    fault axis, so there is exactly ONE construction site to keep in
    sync with specs.FAMILY_FAULTS."""
    from ..compose import fake_test
    from ..runner.core import run_workload

    test = fake_test(spec.test_opts())

    async def main(loop, recorder):
        return await run_workload(test, recorder)

    out = SpecOutcome(spec=spec)
    try:
        history = run_virtual(main)
    except Exception as e:   # a single broken scenario must not end the
        out.error = f"{type(e).__name__}: {e}"        # campaign
        log.exception("campaign spec %d (sim) crashed", spec.spec_id)
        return out
    out.ops = sum(1 for op in history if op.type == "invoke")
    out.keyed = _split(spec, history)
    return out


def _execute_live(spec: ScenarioSpec) -> SpecOutcome:
    """One live run against a fresh in-process minietcd cluster, with
    the stream fail-fast session attached: a falsified run aborts as
    soon as the streamed frontier dies."""
    import tempfile

    from ..compose import compose_test
    from ..db.fake import FakeDB
    from ..nemesis import NoopNemesis
    from ..nemesis.cluster_faults import (DiskFaultNemesis,
                                          LeaseSkewNemesis,
                                          MemberChurnNemesis)
    from ..runner.core import run_workload
    from ..stream import session_for_test
    from .cluster import MiniCluster

    out = SpecOutcome(spec=spec)
    with tempfile.TemporaryDirectory() as td:
        cluster = MiniCluster(
            nodes=[f"n{i + 1}" for i in range(spec.nodes)], data_dir=td)
        session = None
        try:
            test = compose_test(spec.test_opts(),
                                cluster.conn_factory())
            test["db"] = FakeDB()   # members are already serving
            nem = {
                "member-churn": lambda: MemberChurnNemesis(
                    cluster, seed=spec.seed,
                    fork=bool(spec.faults.get("churn_fork"))),
                "disk-full": lambda: DiskFaultNemesis(
                    cluster, mode="disk-full", seed=spec.seed),
                "corrupt-write": lambda: DiskFaultNemesis(
                    cluster, mode="corrupt-write", seed=spec.seed),
                "lease-skew": lambda: LeaseSkewNemesis(
                    cluster, seed=spec.seed),
            }.get(spec.nemesis, NoopNemesis)()
            test["nemesis"] = nem
            session = session_for_test(test)
            recorder = HistoryRecorder(
                listener=session.feed if session else None)
            if session is not None:
                session.enable_eager_flush()

            def stop_check():
                if session.falsified():
                    session.aborted = True
                    return True
                return False

            async def go():
                return await run_workload(
                    test, recorder,
                    stop_check=stop_check if session else None)

            history = asyncio.run(go())
            out.ops = sum(1 for op in history if op.type == "invoke")
            out.keyed = _split(spec, history)
        except Exception as e:
            out.error = f"{type(e).__name__}: {e}"
            log.exception("campaign spec %d (live) crashed", spec.spec_id)
        finally:
            if session is not None:
                # Join the consumer thread (abort-aware) on EVERY exit
                # path: a crashed run that skipped finalize would leak
                # one 'stream-check' thread per erroring spec — the
                # JTL505 join-on-shutdown discipline this package is in.
                out.aborted = session.aborted
                session.finalize()
            cluster.close()
    return out


def _split(spec: ScenarioSpec, history: list[Op]) -> dict[Any, list[Op]]:
    if spec.keyed:
        return split_by_key(history)
    return {None: [op for op in history if op.process != "nemesis"]}


# -- check routing ----------------------------------------------------------

RouteCheck = Callable[[list, Any], list]   # (encs, model) -> results


def direct_route(stats_sink: dict) -> RouteCheck:
    """sched.check_corpus in WAVE-sized submissions through the shared
    single-worker corpus executor (serializes with any concurrent serve
    daemon in the process)."""

    def route(encs, model):
        results = []
        for i in range(0, len(encs), WAVE):
            outs, _kernel, stats = sched.submit_corpus(
                encs[i:i + WAVE], model).result()
            sched.fold_stats(stats_sink, stats)
            results.extend(outs)
        return results

    return route


def serve_route(scheduler) -> RouteCheck:
    """Submit every wave to the serve scheduler as the campaign tenant
    (serve/scheduler.CAMPAIGN_TENANT): the campaign's checks coalesce
    into the SAME continuous batches interactive tenants ride, WFQ'd so
    they cannot starve anyone. No per-launch sched stats surface here —
    the scheduler owns its batches (serve.* metrics), so
    CampaignReport.sched stays empty on this route (direct-route-only
    by design)."""
    from ..serve.scheduler import CAMPAIGN_TENANT

    def route(encs, model):
        results = []
        bound = max(1, scheduler.max_inflight())
        for i in range(0, len(encs), bound):
            reqs = scheduler.submit_many(CAMPAIGN_TENANT,
                                         encs[i:i + bound],
                                         model_name=model.name)
            for req in reqs:
                if not req.wait(300):
                    raise TimeoutError(
                        "campaign serve-route verdict timed out")
                one = req.result
                if one is None or one.get("route") == "error":
                    # The scheduler's all-routes-failed verdict
                    # ({"valid": None, "route": "error", ...}): treating
                    # it as "did not falsify" would silently launder a
                    # check failure into a clean scenario (and let ddmin
                    # bank a non-minimal witness). The direct route
                    # propagates its exceptions; so do we.
                    raise RuntimeError(
                        "campaign serve-route check failed: "
                        f"{(one or {}).get('error', 'no result')}")
                results.append(one)
        return results

    return route


# -- the campaign -----------------------------------------------------------

def run_campaign(n_specs: int = 256, seed: int = 0,
                 specs: Optional[list[ScenarioSpec]] = None,
                 families: Optional[list[str]] = None,
                 bug_rate: float = 0.25, live: int = 0,
                 scale: float = 1.0, workers: int = 4,
                 route: str = "direct", scheduler=None,
                 shrink: bool = True, bank: bool = True,
                 store_root: Optional[str] = None,
                 max_shrink_checks: int = 4096) -> CampaignReport:
    """Run one campaign end to end (module docstring). `specs`
    overrides the sampler; `scheduler` supplies an existing serve
    scheduler for route="serve" (one is created and closed here
    otherwise); banking needs `store_root`."""
    m = obs.get_metrics()
    t0 = time.perf_counter()
    if specs is None:
        specs = sample_specs(n_specs, seed, families=families,
                             bug_rate=bug_rate, live=live, scale=scale)
    report = CampaignReport(seed=seed, route=route, specs=len(specs))

    own_scheduler = None
    if route == "serve" and scheduler is None:
        from ..serve.scheduler import CoalescingScheduler

        scheduler = own_scheduler = CoalescingScheduler(coalesce_ms=2)
    try:
        route_check = (serve_route(scheduler) if route == "serve"
                       else direct_route(report.sched))

        # 1. Execute: sim specs across the pool (deterministic
        # per-spec; pool.map preserves order), live specs sequential.
        sim = [s for s in specs if s.backend == "sim"]
        live_specs = [s for s in specs if s.backend != "sim"]
        outcomes: dict[int, SpecOutcome] = {}
        with obs.get_tracer().span("campaign.execute", specs=len(specs),
                                   live=len(live_specs)):
            with ThreadPoolExecutor(
                    max_workers=max(1, workers),
                    thread_name_prefix="campaign") as pool:
                for out in pool.map(_execute_sim, sim):
                    outcomes[out.spec.spec_id] = out
            for spec in live_specs:
                outcomes[spec.spec_id] = _execute_live(spec)
        ordered = [outcomes[s.spec_id] for s in specs]
        report.executed = sum(1 for o in ordered if o.error is None)
        report.run_errors = sum(1 for o in ordered if o.error is not None)
        report.aborted_runs = sum(1 for o in ordered if o.aborted)
        m.counter("campaign.specs").add(len(specs))
        m.counter("campaign.aborted_runs").add(report.aborted_runs)

        # 2. Check: encode every key once, corpus-batch per model.
        by_model: dict[str, list[tuple[int, Any, list[Op]]]] = {}
        for o in ordered:
            for key, hist in sorted(o.keyed.items(),
                                    key=lambda kv: str(kv[0])):
                if hist:
                    by_model.setdefault(o.spec.model_name, []).append(
                        (o.spec.spec_id, key, hist))
        falsified: list[tuple[ScenarioSpec, Any, list[Op], dict]] = []
        spec_of = {s.spec_id: s for s in specs}
        with obs.get_tracer().span("campaign.check",
                                   models=len(by_model)) as sp:
            for model_name in sorted(by_model):
                entries = by_model[model_name]
                lin = Linearizable(model=model_name)
                encs, kept = [], []
                for sid, key, hist in entries:
                    try:
                        enc = lin.encode(hist)
                    except (EncodeError, ValueError):
                        report.encode_errors += 1
                        continue
                    if enc.n_events == 0:
                        continue
                    if enc.max_pending > HARD_PENDING_CAP:
                        # The combinatorial-frontier shape (see
                        # HARD_PENDING_CAP): an honest "unknown".
                        report.keys_skipped_hard += 1
                        m.counter("campaign.keys_skipped_hard").add(1)
                        continue
                    encs.append(enc)
                    kept.append((sid, key, hist))
                if not encs:
                    continue
                results = route_check(encs, lin.model)
                report.keys_checked += len(encs)
                for (sid, key, hist), one in zip(kept, results):
                    if one.get("valid") is False:
                        falsified.append((spec_of[sid], key, hist, one))
            sp.set(keys=report.keys_checked,
                   falsified=len(falsified))
        m.counter("campaign.keys_checked").add(report.keys_checked)
        report.falsified_keys = len(falsified)
        report.falsified_runs = len({s.spec_id for s, *_ in falsified})
        m.counter("campaign.runs_falsified").add(report.falsified_runs)

        # 3. Triage: signature dedupe, then one shrink per signature.
        groups: dict[str, dict] = {}
        for spec, key, hist, result in falsified:
            model = Linearizable(model=spec.model_name).model
            sig = triage.classify(spec.family, model, hist, result)
            g = groups.setdefault(sig.slug, {
                "sig": sig, "count": 0, "witnesses": []})
            g["count"] += 1
            g["witnesses"].append((spec, key, hist, result))
        m.gauge("campaign.unique_signatures").set(len(groups))
        for slug in sorted(groups):
            g = groups[slug]
            sig: triage.Signature = g["sig"]
            # The cheapest witness shrinks fastest; the tiebreak keeps
            # representative selection deterministic.
            spec, key, hist, result = min(
                g["witnesses"],
                key=lambda w: (len(w[2]), w[0].spec_id, str(w[1])))
            report.signatures[slug] = {
                **sig.to_dict(), "count": g["count"],
                "example_spec": spec.spec_id,
                "example_key": None if key is None else str(key),
                "witness_ops": len(hist),
            }
            if not shrink:
                continue
            model = Linearizable(model=spec.model_name).model
            with obs.get_tracer().span("campaign.shrink", signature=slug,
                                       ops=len(hist)):
                check_batch = triage.make_check_batch(model, route_check)
                sres = triage.ddmin_shrink(
                    hist, check_batch, max_checks=max_shrink_checks)
                sres.verify = triage.verify_routes(sres.minimal, model)
            m.counter("campaign.shrink_checks").add(sres.checks)
            m.counter("campaign.shrink_launches").add(sres.launches)
            if sres.from_ops:
                m.gauge("campaign.shrink_ratio").set(
                    sres.to_ops / sres.from_ops)
            shrink_rec = {
                "signature": slug,
                "from_ops": sres.from_ops, "to_ops": sres.to_ops,
                "rounds": sres.rounds, "checks": sres.checks,
                "launches": sres.launches,
                "one_minimal": sres.one_minimal,
                "budget_exhausted": sres.budget_exhausted,
                "verified_identical": sres.verify.get("identical"),
            }
            report.shrinks.append(shrink_rec)
            # 4. Bank: only route-verified, still-falsifying minima.
            if bank and store_root is not None \
                    and sres.verify.get("identical") \
                    and sres.verify["batched"]["valid"] is False:
                path = bank_witness(
                    store_root, sig, spec.model_name, sres.minimal,
                    expect={
                        "valid": False,
                        "dead_step":
                            sres.verify["batched"]["dead_step"]},
                    spec=spec.to_dict(),
                    campaign={"seed": seed, "specs": len(specs),
                              "route": route},
                    shrink=shrink_rec)
                report.banked.append(str(path))
                m.counter("campaign.banked").add(1)
    finally:
        if own_scheduler is not None:
            own_scheduler.close()
    report.wall_s = time.perf_counter() - t0
    report.specs_per_sec = (len(specs) / report.wall_s
                            if report.wall_s else 0.0)
    m.gauge("campaign.specs_per_sec").set(report.specs_per_sec)
    return report
