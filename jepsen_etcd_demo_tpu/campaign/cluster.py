"""MiniCluster: an in-process minietcd cluster for live campaign runs.

db/minietcd.py promoted the etcd stub to a REAL spawnable process; this
module promotes it to a REAL spawnable *cluster* without leaving the
campaign's process: N members, each the minietcd HTTP handler served
from an ephemeral 127.0.0.1 port by a ThreadingHTTPServer on its own
thread, each holding the standby-peer shape (a bound peer socket) the
single-member server holds. The members share ONE KeyStore — a
single-copy register served from N frontends, which is exactly what
makes a valid verdict against the healthy cluster meaningful (the
replication story is perfect by construction; the interesting physics
is what the fault planes bend):

  * **Member churn** (nemesis/cluster_faults.MemberChurnNemesis):
    spawn_member / teardown_member at runtime. Clients of a torn-down
    member get connection-refused (determinate :fail, clients/etcd.py),
    and the healthy churn preserves linearizability. The SEEDED BUG is
    `fork=True`: the spawned standby boots from a snapshot FORK of the
    store instead of the shared object — a stale replica whose reads
    the checker falsifies.
  * **Disk faults** (DiskFaultNemesis): the shared KeyStore's env-gated
    persistence hook (db/minietcd.py FAULT_DISK_FULL /
    FAULT_CORRUPT_WRITE) plus `restart_from_disk()` — the crash-restart
    leg that surfaces lost acked writes / corrupted values.
  * **Lease skew** (LeaseSkewNemesis): `grant_lease(member)` freezes a
    snapshot the member serves non-quorum reads from — the
    clock-skewed leaseholder that believes its read lease is still
    valid and answers stale. Quorum reads bypass the lease, matching
    etcd's q=true semantics.

Thread shape (jtsan JTL505): every member's serve thread is joined by
`teardown_member` / `close`; `close` is idempotent and the campaign
engine calls it in a finally.
"""

from __future__ import annotations

import socket
import threading
from http.server import ThreadingHTTPServer
from typing import Optional

from ..db.minietcd import KeyStore, _handler_for


class _MemberStore:
    """One member's view over the cluster store. The faithful path
    delegates every call to the shared KeyStore; the fault planes bend
    it per member: a forked standby serves its own stale KeyStore, a
    leased member answers non-quorum GETs from its frozen snapshot."""

    def __init__(self, cluster: "MiniCluster", name: str):
        self._cluster = cluster
        self._name = name

    def _store(self) -> KeyStore:
        return self._cluster.store_for(self._name)

    @property
    def index(self) -> int:
        return self._store().index

    def get(self, key: str, quorum: bool = False):
        lease = self._cluster.lease_snapshot(self._name)
        if lease is not None and not quorum:
            # The expired-lease read: answer from the frozen snapshot
            # (key missing there = etcd 100, like the real store).
            if key not in lease:
                return 404, {"errorCode": 100, "message": "Key not found",
                             "cause": f"/{key}", "index": self.index}
            v, idx = lease[key]
            return 200, {"action": "get",
                         "node": {"key": f"/{key}", "value": v,
                                  "modifiedIndex": idx,
                                  "createdIndex": idx}}
        return self._store().get(key)

    def put(self, key, value, prev_value, prev_index):
        return self._store().put(key, value, prev_value, prev_index)

    def post(self, key, value):
        return self._store().post(key, value)

    def delete(self, key, prev_index):
        return self._store().delete(key, prev_index)


class _Member:
    """One spawned frontend: HTTP server + serve thread + the bound
    standby-peer socket (the shape minietcd.main holds). `port` 0 =
    ephemeral; a respawn passes the node's previous port so clients
    pinned to the old URL reconnect (real churn heals in place)."""

    def __init__(self, name: str, handler_cls, port: int = 0):
        self.name = name
        self.server = ThreadingHTTPServer(("127.0.0.1", port), handler_cls)
        self.server.daemon_threads = True
        self.port = self.server.server_address[1]
        self.peer_sock = socket.socket()
        self.peer_sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.peer_sock.bind(("127.0.0.1", 0))
        self.peer_sock.listen(1)
        self.thread = threading.Thread(
            target=self.server.serve_forever,
            name=f"minicluster-{name}", daemon=True)
        self.thread.start()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def close(self) -> None:
        self.server.shutdown()
        self.thread.join(timeout=5.0)
        self.server.server_close()
        self.peer_sock.close()


class MiniCluster:
    """The in-process cluster (module docstring). Node names map to the
    member CURRENTLY serving them; a torn-down node keeps its (now
    dead) last URL so clients see connection-refused, like real churn.
    """

    def __init__(self, nodes=("n1", "n2", "n3"),
                 data_dir: Optional[str] = None):
        self.data_dir = data_dir
        self.store = KeyStore(data_dir)
        self._lock = threading.Lock()
        # jtsan: guarded-by=self._lock
        self._members: dict[str, _Member] = {}
        self._urls: dict[str, str] = {}      # last-known URL per node
        self._ports: dict[str, int] = {}     # last bound port per node
        self._forks: dict[str, KeyStore] = {}    # buggy standby stores
        self._leases: dict[str, dict] = {}       # frozen lease snapshots
        self._closed = False
        for n in nodes:
            self.spawn_member(n)

    # -- store routing (member handler threads) ---------------------------
    def store_for(self, name: str) -> KeyStore:
        with self._lock:
            return self._forks.get(name, self.store)

    def lease_snapshot(self, name: str) -> Optional[dict]:
        with self._lock:
            return self._leases.get(name)

    # -- membership (nemesis thread / event loop) -------------------------
    def members(self) -> list[str]:
        with self._lock:
            return sorted(self._members)

    def url(self, node: str) -> str:
        with self._lock:
            url = self._urls.get(node)
        if url is None:
            raise KeyError(f"unknown cluster node {node!r}")
        return url

    def spawn_member(self, name: str, fork: bool = False) -> str:
        """Spawn (or replace) the frontend serving `name` — ON the
        node's previous port when it had one, so worker clients pinned
        to the old URL reconnect after churn/heal like they would
        against a real restarted member (ephemeral fallback if the OS
        gave the port away meanwhile). fork=True is the seeded churn
        bug: the standby boots from a snapshot COPY of the store — a
        stale replica that never sees later writes."""
        forked: Optional[KeyStore] = None
        if fork:
            forked = KeyStore()
            with self._lock:
                store = self.store
            # Lock order: cluster lock strictly before the store lock.
            with store.lock:
                forked.data = dict(store.data)
                forked.index = store.index
        with self._lock:
            if self._closed:
                raise RuntimeError("cluster is closed")
            old = self._members.pop(name, None)
            port = self._ports.get(name, 0)
        # Join the old frontend OUTSIDE the cluster lock (JTL504:
        # close() blocks on the serve thread) and BEFORE rebinding its
        # port.
        if old is not None:
            old.close()
        handler_cls = _handler_for(_MemberStore(self, name))
        try:
            member = _Member(name, handler_cls, port=port)
        except OSError:
            member = _Member(name, handler_cls)
        installed = False
        # jtlint: disable=JTL503 -- the _ports write records the port
        # the bind ACTUALLY produced (member.port is ground truth; the
        # earlier read was only a binding hint with an ephemeral
        # fallback), and concurrent same-name spawns are excluded by
        # the nemesis protocol (one spawner per node; racing spawns
        # would be last-wins on _members too, the same semantic).
        with self._lock:
            if not self._closed:
                if forked is not None:
                    self._forks[name] = forked
                else:
                    self._forks.pop(name, None)
                self._members[name] = member
                self._urls[name] = member.url
                self._ports[name] = member.port
                installed = True
        if not installed:
            member.close()
            raise RuntimeError("cluster is closed")
        return member.url

    def teardown_member(self, name: str) -> None:
        """Remove a member. Its node keeps the dead URL: clients dial
        connection-refused until (and unless) a replacement spawns."""
        with self._lock:
            member = self._members.pop(name, None)
            self._forks.pop(name, None)
            self._leases.pop(name, None)
        if member is not None:
            member.close()

    # -- fault-plane hooks ------------------------------------------------
    def grant_lease(self, name: str) -> None:
        """Freeze `name`'s read lease at the current store state — the
        clock-skewed leaseholder serves non-quorum reads from it until
        revoke_leases()."""
        with self._lock:
            store = self.store
        # Lock order: cluster lock strictly before the store lock (the
        # handler threads take them in that order too via store_for).
        with store.lock:
            snap = dict(store.data)
        with self._lock:
            self._leases[name] = snap

    def revoke_leases(self) -> None:
        with self._lock:
            self._leases.clear()

    def restart_from_disk(self) -> None:
        """Crash-restart the storage plane: reload the shared KeyStore
        from its snapshot file (the DiskFaultNemesis restart leg —
        whatever the fault hook kept off the disk is now gone)."""
        if self.data_dir is None:
            raise RuntimeError("restart_from_disk needs a data_dir")
        with self._lock:
            mode = self.store.fault_mode
        fresh = KeyStore(self.data_dir)
        fresh.fault_mode = mode
        with self._lock:
            self.store = fresh

    # -- client plumbing --------------------------------------------------
    def conn_factory(self, timeout_s: float = 5.0):
        """conn_factory for compose_test: node name -> an EtcdClient
        dialing that node's current member URL (live HTTP through the
        real client, exactly the EtcdDB data plane without SSH)."""
        from ..clients.etcd import EtcdClient

        def factory(test, node):
            return EtcdClient(self.url(node), timeout_s=timeout_s)

        return factory

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            members = list(self._members.values())
            self._members.clear()
            self._leases.clear()
            self._forks.clear()
        for m in members:
            m.close()
