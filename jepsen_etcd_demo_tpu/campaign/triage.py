"""Triage: anomaly signatures and the TPU-parallel ddmin shrinker.

A campaign that falsifies hundreds of runs is only useful if those runs
collapse into a handful of BUGS. Two classic pieces do that here:

  * **Signatures** — every falsifying per-key history is classified by
    (workload family, model, anomaly kind, failing op) derived from the
    checker verdict: the dead return step maps back through the
    encoder's pairing (ops/encode.pair_history — ok completions in
    completion order ARE the return steps) to the concrete op whose
    return killed the frontier, and the anomaly kind is that op's
    function bucketed into the taxonomy below. Duplicate witnesses of
    the same signature dedupe; ONE representative per signature is
    shrunk and banked.

  * **ddmin** (Zeller & Hildebrandt's delta debugging, adapted) — the
    witness shrinks at the granularity of LOGICAL operations (an invoke
    and its completion removed together, so every candidate stays a
    well-paired history). The twist that makes shrinking nearly free on
    this harness: each round's candidate subsets and complements are
    re-checked as ONE vmapped corpus launch through the batched check
    route (sched.check_corpus' bucket/warm-pool discipline), instead of
    one kernel dispatch per candidate. Soundness: verdicts are pure
    functions of the candidate history, and the reduction rule picks
    the FIRST failing candidate in a fixed order (subsets before
    complements, split order within each), so the batched algorithm
    traverses exactly the state sequence a sequential ddmin with the
    same order would — it merely learns the later candidates' verdicts
    for free (doc/campaign.md spells the argument out). Termination at
    n == |ops| with no failing complement is the standard 1-minimality
    guarantee: removing any single remaining op makes the history pass.

Every minimal witness is re-verified across the single-history dense
route and the batched corpus route before banking (`verify_routes`) —
bit-identical valid/dead_step or the shrink is rejected.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

from ..checkers.linearizable import Linearizable
from ..ops.encode import OK, EncodeError, pair_history
from ..ops.op import INVOKE, Op

# The anomaly taxonomy: failing-op function -> anomaly kind. The
# function that killed the frontier names the observable contradiction
# (a read that no linearization explains is a stale/invented read, a
# dequeue is an order/duplication violation, ...). Unlisted functions
# fall back to "nonlinearizable-<f>".
ANOMALY_BY_F = {
    "read": "stale-read",
    "write": "unwritable-state",
    "cas": "cas-divergence",
    "dequeue": "queue-order",
    "enqueue": "queue-loss",
    "add": "set-divergence",
}


@dataclass(frozen=True)
class Signature:
    """The dedupe key of one bug class."""

    family: str
    model: str
    anomaly: str
    failing_f: str

    @property
    def slug(self) -> str:
        return "-".join((self.family, self.model, self.anomaly)) \
            .replace("/", "_")

    def to_dict(self) -> dict:
        return {"family": self.family, "model": self.model,
                "anomaly": self.anomaly, "failing_f": self.failing_f,
                "slug": self.slug}


def failing_op(history: Sequence[Op], model, dead_step: int
               ) -> Optional[Op]:
    """The concrete completion op whose return step killed the
    frontier. Return steps are exactly the ok completions of the
    model-translated history in completion order (ops/encode.py
    _timeline_points: fail ops and info reads never emit EV_RETURN), so
    dead_step indexes that list directly."""
    prepared = model.prepare_history(
        [op for op in history if op.process != "nemesis"])
    try:
        invs = pair_history(prepared, model)
    except EncodeError:
        return None
    oks = sorted((i for i in invs if i.status == OK),
                 key=lambda i: i.complete_index)
    if not 0 <= dead_step < len(oks):
        return None
    return prepared[oks[dead_step].complete_index]


def classify(family: str, model, history: Sequence[Op],
             result: dict) -> Signature:
    """Signature of one falsifying (history, verdict) pair."""
    op = failing_op(history, model, int(result.get("dead_step", -1)))
    f = op.f if op is not None else "unknown"
    anomaly = ANOMALY_BY_F.get(f, f"nonlinearizable-{f}")
    return Signature(family=family, model=model.name, anomaly=anomaly,
                     failing_f=f)


# -- logical-op grouping ----------------------------------------------------

def logical_ops(history: Sequence[Op]) -> list[list[Op]]:
    """Group history entries into logical operations: each invoke with
    its completion (paired by process, jepsen's one-outstanding-op
    worker model). Removing a whole group always leaves a well-paired
    candidate history. Stray completions (no pending invoke — cannot
    occur in recorder output) group alone."""
    groups: list[list[Op]] = []
    open_of: dict = {}
    for op in history:
        if op.type == INVOKE:
            grp = [op]
            groups.append(grp)
            open_of[op.process] = grp
        else:
            grp = open_of.pop(op.process, None)
            if grp is None:
                groups.append([op])
            else:
                grp.append(op)
    return groups


def _rebuild(groups: Sequence[list[Op]]) -> list[Op]:
    """Flatten a group subset back into a history in original record
    order (seq when stamped, else index — both total orders on one
    key's entries)."""
    ops = [op for grp in groups for op in grp]
    ops.sort(key=lambda o: (o.seq if o.seq >= 0 else o.index, o.index))
    return ops


# -- ddmin ------------------------------------------------------------------

CheckBatch = Callable[[list[list[Op]]], list[bool]]
#   candidates -> [still_falsifies?] — ONE batched corpus launch.


@dataclass
class ShrinkResult:
    minimal: list[Op]
    from_ops: int                     # logical ops before shrinking
    to_ops: int                       # logical ops after
    rounds: int = 0
    checks: int = 0                   # candidate histories re-checked
    launches: int = 0                 # batched check launches
    one_minimal: bool = False
    budget_exhausted: bool = False
    verify: dict = field(default_factory=dict)


def _partition(ops: list, n: int) -> list[list]:
    """Split into n near-even contiguous chunks (every chunk non-empty
    when n <= len)."""
    k, m = divmod(len(ops), n)
    out, start = [], 0
    for i in range(n):
        size = k + (1 if i < m else 0)
        out.append(ops[start:start + size])
        start += size
    return [c for c in out if c]


def ddmin_shrink(history: Sequence[Op], check_batch: CheckBatch,
                 max_checks: int = 4096) -> ShrinkResult:
    """Delta-debug `history` (already known falsifying) to a 1-minimal
    counterexample. `check_batch` re-checks a whole round's candidates
    as one corpus launch; `max_checks` bounds total candidate checks —
    on exhaustion the smallest failing history found so far is returned
    with budget_exhausted=True (still a witness, just not proven
    1-minimal)."""
    ops = logical_ops(history)
    res = ShrinkResult(minimal=list(history), from_ops=len(ops),
                       to_ops=len(ops))
    if len(ops) < 2:
        res.one_minimal = True
        return res
    n = 2
    while len(ops) >= 2:
        if res.checks >= max_checks:
            res.budget_exhausted = True
            break
        res.rounds += 1
        chunks = _partition(ops, n)
        # Candidate order is the soundness anchor: subsets first, then
        # complements, each in split order — the batched check learns
        # every verdict, the reduction applies the FIRST failing one.
        candidates = list(chunks)
        if len(chunks) > 2:
            candidates += [[g for c2 in chunks if c2 is not c for g in c2]
                           for c in chunks]
        histories = [_rebuild(c) for c in candidates]
        verdicts = check_batch(histories)
        res.checks += len(histories)
        res.launches += 1
        hit = next((i for i, bad in enumerate(verdicts) if bad), None)
        if hit is None:
            if n >= len(ops):
                # Every single-op-removed complement passes: 1-minimal.
                res.one_minimal = True
                break
            n = min(len(ops), 2 * n)
            continue
        if hit < len(chunks):
            ops = candidates[hit]
            n = 2
        else:
            ops = candidates[hit]
            n = max(n - 1, 2)
        res.minimal = _rebuild(ops)
        res.to_ops = len(ops)
        if len(ops) == 1:
            res.one_minimal = True
            break
    # n == 2 complements ARE the subsets (each chunk is the other's
    # complement), so the len(chunks) > 2 guard above skips the
    # duplicates — but then a 2-op history terminates via the subset
    # arm or the n >= len(ops) exit, both covered.
    res.to_ops = len(ops)
    return res


# -- cross-route verification ----------------------------------------------

def verify_routes(history: Sequence[Op], model) -> dict:
    """Re-check a minimal witness on BOTH check routes and on the exact
    host oracle, asserting the verdicts bit-identical:

      * dense single-history route — wgl3_pallas.check_batch_encoded_auto
        on [enc], exactly what `jepsen-tpu analyze` resolves through;
      * batched corpus route — sched.check_corpus on a 2-wide batch
        (the witness submitted twice: a second same-shape entry keeps
        the scheduler on its bucketed batch path rather than the
        single-history bypass, and both verdicts are the same pure
        function of the history);
      * the pure-Python WGL oracle (checkers/oracle.py) as the
        dense-oracle anchor.

    Returns the comparison record the bank persists; `identical` is the
    gate the campaign enforces before banking."""
    import numpy as np

    from .. import sched
    from ..checkers.linearizable import _event_to_step
    from ..checkers.oracle import check_events_oracle
    from ..ops import wgl3_pallas

    lin = Linearizable(model=model)
    enc = lin.encode([op for op in history if op.process != "nemesis"])
    dense_out, dense_kernel = wgl3_pallas.check_batch_encoded_auto(
        [enc], lin.model)
    dense = dense_out[0]
    batch_out, batch_kernel, _stats = sched.check_corpus(
        [enc, enc], lin.model)
    batched = batch_out[0]
    oracle = check_events_oracle(enc, lin.model).to_dict()
    oracle_dead = _event_to_step(enc, oracle["dead_event"])
    identical = (
        bool(dense["valid"]) == bool(batched["valid"])
        == bool(oracle["valid"])
        and int(dense["dead_step"]) == int(batched["dead_step"])
        == int(oracle_dead))
    # max_frontier is a kernel-route metric: compare it only when the
    # latency router kept the single history off the host oracle (tiny
    # witnesses legitimately route there; the oracle's verdict fields
    # are the exactness anchor either way).
    if identical and "oracle" not in str(dense_kernel):
        identical = int(dense["max_frontier"]) \
            == int(batched["max_frontier"])
    return {
        "identical": bool(identical),
        "dense": {"valid": bool(np.asarray(dense["valid"])),
                  "dead_step": int(dense["dead_step"]),
                  "max_frontier": int(dense["max_frontier"]),
                  "kernel": dense_kernel},
        "batched": {"valid": bool(np.asarray(batched["valid"])),
                    "dead_step": int(batched["dead_step"]),
                    "max_frontier": int(batched["max_frontier"]),
                    "kernel": batch_kernel},
        "oracle": {"valid": bool(oracle["valid"]),
                   "dead_step": int(oracle_dead)},
    }


def make_check_batch(model, route_check) -> CheckBatch:
    """The engine-supplied batched falsification probe: encode every
    candidate (unencodable candidates count as passing — they are not
    witnesses) and re-check the encodable ones in ONE launch through
    `route_check(encs, model) -> results`."""
    lin = Linearizable(model=model)

    def check_batch(histories: list[list[Op]]) -> list[bool]:
        encs, idx = [], []
        verdicts = [False] * len(histories)
        for i, h in enumerate(histories):
            try:
                enc = lin.encode(h)
            except (EncodeError, ValueError):
                continue
            if enc.n_events == 0:
                continue
            encs.append(enc)
            idx.append(i)
        if encs:
            results = route_check(encs, lin.model)
            for i, one in zip(idx, results):
                verdicts[i] = one.get("valid") is False
        return verdicts

    return check_batch
