"""Operation-scheduling generator combinators.

Equivalent of jepsen.generator as exercised by the reference demo
(src/jepsen/etcdemo.clj:120-125,134-144,168-174; set.clj:47-49): `mix`,
`limit`, `stagger`, `time-limit`, `phases`, `nemesis`, `clients`, `log`,
`sleep`, `once`, `cycle`, plus jepsen.independent's `concurrent-generator`
(src/jepsen/etcdemo.clj:120-125).

Design. The reference opts into jepsen's *pure* generator engine
(`:pure-generators true`, src/jepsen/etcdemo.clj:158) whose point is that op
scheduling has no shared-mutable-state races across worker threads. This build
achieves the same property differently: generators are small state machines
that are only ever advanced by the runner's single-threaded dispatcher (one
asyncio event loop task touches them; workers await on queues), and all
randomness flows through one seeded `random.Random` — so schedules are
deterministic under a seed, which the reference engine does not even provide.

Protocol: `Gen.next_for(ctx)` returns
  * an `Op`          — dispatch it now (consumes the op),
  * `Pending(wake)`  — nothing for this asker until `wake` (ns; None = until
                       some other event changes the world),
  * `None`           — exhausted for this asker, forever.

`ctx` carries the asking process ("nemesis" or a client int), the current
relative time in ns, and the shared rng. Time is injected, never read from the
wall clock, so generators are unit-testable with a fake clock (SURVEY.md §4).
"""

from .core import (  # noqa: F401
    Gen, GenContext, Pending, NEMESIS,
    fn_gen, lift, Mix, Limit, Once, TimeLimit, Stagger, Sleep, Log, Seq,
    Cycle, Repeat, OnNemesis, OnClients, Phases,
    mix, limit, once, time_limit, stagger, sleep, log, seq, cycle, repeat,
    each_thread,
    nemesis_gen, clients_gen, phases,
)
from .independent import ConcurrentGenerator, concurrent_generator, tuple_gen  # noqa: F401
