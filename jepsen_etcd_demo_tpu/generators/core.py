"""Core generator combinators (see package docstring for the protocol)."""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Any, Callable, Iterable, Optional, Sequence, Union

from ..ops.op import Op, INVOKE

NEMESIS = "nemesis"

SECOND = 1_000_000_000  # ns


@dataclass(frozen=True)
class Pending:
    """Nothing to dispatch for this asker right now.

    wake: relative time (ns) at which asking again may yield an op, or None
    when the generator is waiting on an external event (e.g. another phase)."""

    wake: Optional[int] = None


@dataclass
class GenContext:
    """What a generator may observe when asked for an op."""

    time: int                    # relative ns since test start
    process: Any                 # asking worker: client int or NEMESIS
    rng: random.Random
    test: dict | None = None

    def for_process(self, process) -> "GenContext":
        return GenContext(self.time, process, self.rng, self.test)


NextResult = Union[Op, Pending, None]


class Gen:
    """Base generator: exhausted immediately."""

    def next_for(self, ctx: GenContext) -> NextResult:
        return None


class _FnGen(Gen):
    """Wraps a callable returning an Op (or a dict of Op fields) per call.

    The reference's op constructors r/w/cas (src/jepsen/etcdemo.clj:67-69) map
    to fn generators: each call constructs a fresh invoke op, drawing
    randomness from the shared seeded rng via ctx."""

    def __init__(self, fn: Callable):
        self.fn = fn

    def next_for(self, ctx: GenContext) -> NextResult:
        out = self.fn(ctx)
        return _as_op(out, ctx)


def _as_op(out, ctx: GenContext) -> NextResult:
    if out is None or isinstance(out, (Op, Pending)):
        return out
    if isinstance(out, dict):
        d = dict(out)
        d.setdefault("type", INVOKE)
        return Op(**d)
    raise TypeError(f"generator fn returned {out!r}")


def fn_gen(fn: Callable) -> Gen:
    return _FnGen(fn)


def lift(x) -> Gen:
    """Coerce: Gen | callable | Op | dict | iterable-of-those -> Gen."""
    if isinstance(x, Gen):
        return x
    if callable(x):
        return _FnGen(x)
    if isinstance(x, Op):
        return Once(_ConstGen(x))
    if isinstance(x, dict):
        d = dict(x)
        d.setdefault("type", INVOKE)
        return Once(_ConstGen(Op(**d)))
    if isinstance(x, (list, tuple)):
        return Seq([lift(e) for e in x])
    raise TypeError(f"cannot lift {x!r} to a generator")


class _ConstGen(Gen):
    def __init__(self, op: Op):
        self.op = op

    def next_for(self, ctx: GenContext) -> NextResult:
        # Fresh copy each emission: downstream mutates process/time/index.
        o = self.op
        return Op(type=o.type, f=o.f, value=o.value, process=o.process,
                  time=o.time, error=o.error)


class Mix(Gen):
    """Random uniform choice among sub-generators per emission — gen/mix
    (reference src/jepsen/etcdemo.clj:123). Exhausted sub-gens drop out; the
    mix is exhausted when all are."""

    def __init__(self, gens: Sequence):
        self.gens = [lift(g) for g in gens]

    def next_for(self, ctx: GenContext) -> NextResult:
        live = list(range(len(self.gens)))
        best_wake = None
        while live:
            i = live[ctx.rng.randrange(len(live))]
            out = self.gens[i].next_for(ctx)
            if isinstance(out, Op):
                return out
            if isinstance(out, Pending):
                if out.wake is not None:
                    best_wake = (out.wake if best_wake is None
                                 else min(best_wake, out.wake))
                live.remove(i)
            else:
                self.gens.pop(i)
                live = [j if j < i else j - 1 for j in live if j != i]
        if self.gens:
            return Pending(best_wake)
        return None


class Limit(Gen):
    """At most n ops, then exhausted — gen/limit
    (reference src/jepsen/etcdemo.clj:124, :ops-per-key)."""

    def __init__(self, n: int, gen):
        self.remaining = n
        self.gen = lift(gen)

    def next_for(self, ctx: GenContext) -> NextResult:
        if self.remaining <= 0:
            return None
        out = self.gen.next_for(ctx)
        if isinstance(out, Op):
            self.remaining -= 1
        return out


def once(gen) -> Gen:
    """gen/once — exactly one op (reference src/jepsen/etcdemo.clj:171)."""
    return Limit(1, gen)


Once = once


class TimeLimit(Gen):
    """Exhausted once ctx.time exceeds the budget — gen/time-limit
    (reference src/jepsen/etcdemo.clj:144). The window starts at the first
    ask, matching jepsen (each phase's time-limit is relative to its start)."""

    def __init__(self, seconds: float, gen):
        self.budget_ns = int(seconds * SECOND)
        self.deadline: Optional[int] = None
        self.gen = lift(gen)

    def next_for(self, ctx: GenContext) -> NextResult:
        if self.deadline is None:
            self.deadline = ctx.time + self.budget_ns
        if ctx.time >= self.deadline:
            return None
        return self.gen.next_for(ctx)


class Stagger(Gen):
    """Rate limiting: successive ops are spaced by a uniform random delay in
    [0, 2*mean) so the long-run rate is 1/mean — gen/stagger semantics
    (reference src/jepsen/etcdemo.clj:137 uses (/ rate) i.e. mean = 1/rate
    seconds across ALL workers of the channel, not per worker)."""

    def __init__(self, mean_seconds: float, gen):
        self.mean_ns = int(mean_seconds * SECOND)
        self.next_time: Optional[int] = None
        self.gen = lift(gen)

    def next_for(self, ctx: GenContext) -> NextResult:
        if self.next_time is None:
            self.next_time = ctx.time
        if ctx.time < self.next_time:
            return Pending(self.next_time)
        out = self.gen.next_for(ctx)
        if isinstance(out, Op):
            self.next_time += ctx.rng.randrange(max(1, 2 * self.mean_ns))
            # Never fall behind more than one interval (jepsen catches up
            # after stalls rather than bursting).
            self.next_time = max(self.next_time, ctx.time)
        return out


class Sleep(Gen):
    """Emit nothing for `seconds`, then exhausted — gen/sleep
    (reference src/jepsen/etcdemo.clj:139,141,173)."""

    def __init__(self, seconds: float):
        self.budget_ns = int(seconds * SECOND)
        self.deadline: Optional[int] = None

    def next_for(self, ctx: GenContext) -> NextResult:
        if self.deadline is None:
            self.deadline = ctx.time + self.budget_ns
        if ctx.time >= self.deadline:
            return None
        return Pending(self.deadline)


class Log(Gen):
    """Emit one :log pseudo-op the runner prints — gen/log
    (reference src/jepsen/etcdemo.clj:170,172)."""

    def __init__(self, message: str):
        self.message: Optional[str] = message

    def next_for(self, ctx: GenContext) -> NextResult:
        if self.message is None:
            return None
        msg, self.message = self.message, None
        return Op(type="log", f="log", value=msg)


class Seq(Gen):
    """Sub-generators in order; advance when the head exhausts. (Unlike
    Phases there is NO barrier: the next gen starts as soon as the previous
    stops emitting, concurrent with in-flight ops.)"""

    def __init__(self, gens: Sequence):
        self.gens = [lift(g) for g in gens]

    def next_for(self, ctx: GenContext) -> NextResult:
        while self.gens:
            out = self.gens[0].next_for(ctx)
            if out is not None:
                return out
            self.gens.pop(0)
        return None


class Cycle(Gen):
    """Endlessly rebuild-and-drain a generator from a factory — gen/cycle
    (the reference's nemesis schedule, src/jepsen/etcdemo.clj:138-143)."""

    def __init__(self, factory: Callable[[], Any]):
        self.factory = factory
        self.current = lift(factory())

    def next_for(self, ctx: GenContext) -> NextResult:
        for _ in range(2):
            out = self.current.next_for(ctx)
            if out is not None:
                return out
            self.current = lift(self.factory())
        # A factory whose product is immediately exhausted would spin forever.
        return None


def cycle(*gens_or_factory) -> Gen:
    if len(gens_or_factory) == 1 and callable(gens_or_factory[0]) \
            and not isinstance(gens_or_factory[0], Gen):
        return Cycle(gens_or_factory[0])
    items = list(gens_or_factory)
    return Cycle(lambda: [_rebuild(g) for g in items])


def _rebuild(g):
    """Cycle needs fresh stateful combinators each lap; specs that are plain
    data (dicts, Ops, callables) are re-lifted, Gen instances are reused
    (only valid if stateless)."""
    return lift(g)


class Repeat(Gen):
    """Emit ops from (a fresh copy of) the underlying fn generator forever."""

    def __init__(self, fn: Callable):
        self.gen = _FnGen(fn)

    def next_for(self, ctx: GenContext) -> NextResult:
        return self.gen.next_for(ctx)


class EachThread(Gen):
    """One independent sub-generator per worker THREAD — jepsen's
    gen/each-thread. The factory is called once per thread (thread =
    process mod concurrency: jepsen reincarnates a crashed process as
    p + concurrency on the SAME thread, which keeps its generator).
    The canonical use is a per-thread state machine like the mutex
    workload's acquire/release alternation (compose.py)."""

    def __init__(self, factory: Callable[[], Any]):
        self.factory = factory
        self.per_thread: dict[int, Gen] = {}

    def next_for(self, ctx: GenContext) -> NextResult:
        if ctx.process == NEMESIS:
            return Pending(None)
        # The runner publishes its resolved concurrency into the test map
        # (runner/core.py); the default here only serves generators driven
        # outside the runner (unit tests), where processes don't
        # reincarnate.
        conc = int((ctx.test or {}).get("concurrency", 10))
        thread = int(ctx.process) % conc
        if thread not in self.per_thread:
            self.per_thread[thread] = lift(self.factory())
        return self.per_thread[thread].next_for(ctx)


def each_thread(factory: Callable[[], Any]) -> Gen:
    return EachThread(factory)


class OnNemesis(Gen):
    """Route a generator to the nemesis channel only — gen/nemesis
    (reference src/jepsen/etcdemo.clj:138). Client askers see Pending."""

    def __init__(self, gen):
        self.gen = lift(gen)

    def next_for(self, ctx: GenContext) -> NextResult:
        if ctx.process != NEMESIS:
            return Pending(None)
        return self.gen.next_for(ctx)


class OnClients(Gen):
    """Route to client workers only — gen/clients
    (reference src/jepsen/etcdemo.clj:136-137)."""

    def __init__(self, gen):
        self.gen = lift(gen)

    def next_for(self, ctx: GenContext) -> NextResult:
        if ctx.process == NEMESIS:
            return Pending(None)
        return self.gen.next_for(ctx)


class Phases(Gen):
    """Sequential phases with a full barrier between them — gen/phases
    (reference src/jepsen/etcdemo.clj:168-174). The runner detects the
    phase boundary via `barrier_pending()` and drains in-flight ops before
    the next phase starts."""

    def __init__(self, *gens):
        self.phases = [lift(g) for g in gens]
        self.index = 0
        self._need_barrier = False

    def barrier_pending(self) -> bool:
        return self._need_barrier

    def barrier_done(self):
        self._need_barrier = False

    def next_for(self, ctx: GenContext) -> NextResult:
        while self.index < len(self.phases):
            if self._need_barrier:
                return Pending(None)
            out = self.phases[self.index].next_for(ctx)
            if out is not None:
                return out
            # This asker found the phase exhausted. The phase flips only when
            # the runner confirms the barrier (all workers idle).
            self.index += 1
            self._need_barrier = self.index < len(self.phases)
        return None


# Lowercase constructors mirroring the jepsen namespace.
def mix(gens) -> Gen:
    return Mix(gens)


def limit(n: int, gen) -> Gen:
    return Limit(n, gen)


def time_limit(seconds: float, gen) -> Gen:
    return TimeLimit(seconds, gen)


def stagger(mean_seconds: float, gen) -> Gen:
    return Stagger(mean_seconds, gen)


def sleep(seconds: float) -> Gen:
    return Sleep(seconds)


def log(message: str) -> Gen:
    return Log(message)


def seq(*gens) -> Gen:
    return Seq(list(gens))


def repeat(fn: Callable) -> Gen:
    return Repeat(fn)


def nemesis_gen(gen) -> Gen:
    return OnNemesis(gen)


def clients_gen(gen) -> Gen:
    return OnClients(gen)


def phases(*gens) -> Phases:
    return Phases(*gens)
