"""jepsen.independent's concurrent-generator equivalent.

The reference multiplexes a single-key workload over many independent keys:
`independent/concurrent-generator 10 (range) (fn [k] ...)` — 10 worker
threads per key, each key's generator limited to :ops-per-key, groups
rotating to fresh keys as their key exhausts (src/jepsen/etcdemo.clj:120-125).
Emitted op values become (key, value) tuples (src/jepsen/etcdemo.clj:90),
which `IndependentChecker` later splits per key — the vmap batch axis of the
TPU checker (SURVEY.md §2.4).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, Optional

from ..ops.op import Op
from .core import Gen, GenContext, NextResult, Pending, NEMESIS, lift


class tuple_gen(Gen):
    """Wrap a generator so each emitted op's value becomes (key, value)."""

    def __init__(self, key, gen):
        self.key = key
        self.gen = lift(gen)

    def next_for(self, ctx: GenContext) -> NextResult:
        out = self.gen.next_for(ctx)
        if isinstance(out, Op):
            out.value = (self.key, out.value)
        return out


class ConcurrentGenerator(Gen):
    """n workers per key; worker groups rotate through the key stream.

    Group g = client_process // n. Each group holds its own sub-generator
    (fn(key), tuple-wrapped); when it exhausts, the group pulls the next key
    from the shared stream. Nemesis askers always see Pending (this generator
    feeds the client channel only, like the reference's)."""

    def __init__(self, n: int, keys: Iterable, fn: Callable[[Any], Any]):
        if n <= 0:
            raise ValueError("n must be positive")
        self.n = n
        self.keys: Iterator = iter(keys)
        self.fn = fn
        self.group_gens: dict[int, Optional[Gen]] = {}
        self.exhausted_keys = False

    def _fresh(self) -> Optional[Gen]:
        try:
            key = next(self.keys)
        except StopIteration:
            self.exhausted_keys = True
            return None
        return tuple_gen(key, self.fn(key))

    def next_for(self, ctx: GenContext) -> NextResult:
        if ctx.process == NEMESIS:
            return Pending(None)
        # Processes reincarnate as p + concurrency after :info crashes, but
        # the group is a property of the worker THREAD (jepsen maps threads,
        # not processes, to keys).
        conc = (ctx.test or {}).get("concurrency")
        thread = int(ctx.process) % int(conc) if conc else int(ctx.process)
        group = thread // self.n
        if group not in self.group_gens:
            self.group_gens[group] = self._fresh()
        while True:
            gen = self.group_gens[group]
            if gen is None:
                return None
            out = gen.next_for(ctx)
            if out is not None:
                return out
            self.group_gens[group] = self._fresh()


def concurrent_generator(n: int, keys: Iterable,
                         fn: Callable[[Any], Any]) -> Gen:
    return ConcurrentGenerator(n, keys, fn)
