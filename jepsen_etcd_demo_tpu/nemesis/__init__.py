"""Fault-injection (nemesis) layer.

Equivalent of jepsen.nemesis as used by the reference:
nemesis/partition-random-halves (src/jepsen/etcdemo.clj:164), driven by
:start/:stop ops on the nemesis generator channel (:138-143) and healed in
the final phase (:170-171).
"""

from .base import Nemesis, NoopNemesis  # noqa: F401
from .partition import (  # noqa: F401
    FakeIsolatedNodeNemesis, FakePartitionNemesis, GrudgePartitioner,
    PartitionBridge, PartitionIsolatedNode, PartitionMajoritiesRing,
    PartitionRandomHalves, bisect_nodes, random_halves,
)
from .process_faults import KillNemesis, PauseNemesis  # noqa: F401
from .clock import (ClockSkewNemesis, ClockStrobeNemesis,  # noqa: F401
                    FakeClockSkewNemesis)
from .cluster_faults import (DiskFaultNemesis,  # noqa: F401
                             LeaseSkewNemesis, MemberChurnNemesis)
