"""Cluster fault planes the roadmap's item 4 named and never shipped:
membership churn, disk faults, and lease/watch skew (ISSUE 15
satellite). All three operate on the in-process minietcd cluster
(campaign/cluster.MiniCluster) — the campaign's live backend — through
the standard Nemesis protocol, so the composition layer schedules them
exactly like the partition/kill/pause family.

Each plane carries its own SEEDED BUG so the campaign (and the golden
tests in tests/test_campaign.py) can prove the checker falsifies it:

  * MemberChurnNemesis — healthy churn (spawn/teardown of standby
    members over the shared store) preserves linearizability;
    fork=True boots the standby from a snapshot FORK — a stale replica
    whose reads falsify.
  * DiskFaultNemesis — drives the env-gated KeyStore persistence hook
    (db/minietcd.py): "disk-full" acks writes that never reach the
    snapshot, "corrupt-write" garbles the last value on its way to
    disk; the :stop leg crash-restarts the storage plane from disk,
    surfacing the lost/corrupted state the checker falsifies. The env
    gate is set only for the fault window and always restored.
  * LeaseSkewNemesis — grants a minority of members a frozen read
    lease (the clock-skewed leaseholder): their non-quorum reads
    answer from the expired snapshot until :stop revokes — the
    stale-read plane quorum reads are immune to, matching etcd's
    q=true semantics.
"""

from __future__ import annotations

import os
import random

from .. import obs
from ..db.minietcd import FAULT_HOOK_ENV
from ..ops.op import Op
from .base import Nemesis, random_minority


class MemberChurnNemesis(Nemesis):
    """:start tears down a random minority of members and spawns one
    standby replacement per removed member; :stop restores the original
    membership. `fork` seeds the stale-replica bug on every spawned
    standby."""

    def __init__(self, cluster, seed: int = 0, fork: bool = False):
        self.cluster = cluster
        self.rng = random.Random(seed)
        self.fork = fork
        self.churned: list[str] = []

    async def invoke(self, test: dict, op: Op) -> Op:
        if op.f == "start":
            self.churned = random_minority(self.rng,
                                           self.cluster.members())
            for node in self.churned:
                self.cluster.teardown_member(node)
                # The standby replacement: same node name, fresh
                # frontend (fork=True -> the seeded stale-replica bug).
                self.cluster.spawn_member(node, fork=self.fork)
                obs.get_tracer().event("fault.member_churn", node=node,
                                       fork=self.fork)
            value = {"churned": self.churned, "fork": self.fork}
        elif op.f == "stop":
            for node in self.churned:
                # Heal: replace whatever serves the node with a faithful
                # shared-store member.
                self.cluster.spawn_member(node, fork=False)
                obs.get_tracer().event("fault.member_restore", node=node)
            value = {"restored": self.churned}
            self.churned = []
        else:
            value = f"unknown nemesis op {op.f}"
        return Op(type="info", f=op.f, value=value, process=op.process)

    async def teardown(self, test: dict) -> None:
        for node in self.churned:
            self.cluster.spawn_member(node, fork=False)
        self.churned = []


class DiskFaultNemesis(Nemesis):
    """:start arms the KeyStore persistence fault (mode "disk-full" or
    "corrupt-write") behind its env gate; :stop disarms it and
    CRASH-RESTARTS the storage plane from disk — the leg that turns the
    silently-bent persistence into checker-visible lost/invented
    state."""

    def __init__(self, cluster, mode: str = "disk-full", seed: int = 0):
        self.cluster = cluster
        self.mode = mode
        self.rng = random.Random(seed)
        self._env_prev: str | None = None
        self._armed = False

    def _arm(self) -> None:
        if not self._armed:
            self._env_prev = os.environ.get(FAULT_HOOK_ENV)
            os.environ[FAULT_HOOK_ENV] = "1"
            self._armed = True
        self.cluster.store.fault_mode = self.mode

    def _disarm(self) -> None:
        self.cluster.store.fault_mode = None
        if self._armed:
            if self._env_prev is None:
                os.environ.pop(FAULT_HOOK_ENV, None)
            else:
                os.environ[FAULT_HOOK_ENV] = self._env_prev
            self._armed = False

    async def invoke(self, test: dict, op: Op) -> Op:
        if op.f == "start":
            self._arm()
            obs.get_tracer().event("fault.disk", mode=self.mode)
            value = {"disk_fault": self.mode}
        elif op.f == "stop":
            injected = self.cluster.store.faults_injected
            # Restart BEFORE disarming: restart_from_disk copies the
            # armed fault_mode onto the fresh store, so a client write
            # racing this :stop cannot slip a healthy full-dict persist
            # in between and silently heal the lost/garbled state the
            # restart exists to surface. _disarm then clears the fresh
            # store's mode + the env gate.
            self.cluster.restart_from_disk()
            self._disarm()
            obs.get_tracer().event("fault.disk_restart", mode=self.mode,
                                   injected=injected)
            value = {"restarted_after": self.mode, "injected": injected}
        else:
            value = f"unknown nemesis op {op.f}"
        return Op(type="info", f=op.f, value=value, process=op.process)

    async def teardown(self, test: dict) -> None:
        self._disarm()


class LeaseSkewNemesis(Nemesis):
    """:start freezes a read lease on a random minority of members —
    the clock-skewed leaseholders serve non-quorum reads from the
    expired snapshot; :stop revokes every lease."""

    def __init__(self, cluster, seed: int = 0):
        self.cluster = cluster
        self.rng = random.Random(seed)
        self.leased: list[str] = []

    async def invoke(self, test: dict, op: Op) -> Op:
        if op.f == "start":
            self.leased = random_minority(self.rng,
                                          self.cluster.members())
            for node in self.leased:
                self.cluster.grant_lease(node)
                obs.get_tracer().event("fault.lease_skew", node=node)
            value = {"leased": self.leased}
        elif op.f == "stop":
            self.cluster.revoke_leases()
            obs.get_tracer().event("fault.lease_revoke",
                                   nodes=self.leased)
            value = {"revoked": self.leased}
            self.leased = []
        else:
            value = f"unknown nemesis op {op.f}"
        return Op(type="info", f=op.f, value=value, process=op.process)

    async def teardown(self, test: dict) -> None:
        self.cluster.revoke_leases()
        self.leased = []
