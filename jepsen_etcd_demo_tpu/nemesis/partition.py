"""Network partitions.

PartitionRandomHalves = the reference's nemesis/partition-random-halves
(src/jepsen/etcdemo.clj:164): on :start, split nodes into a random
majority/minority and drop traffic between the halves with iptables over the
control plane; on :stop, heal. FakePartitionNemesis does the same against the
in-process FakeKVStore (isolates the minority) so partition tests run
hermetically.
"""

from __future__ import annotations

import random
from typing import Optional

from ..control.runner import Runner, runner_for
from ..ops.op import Op
from .base import Nemesis


def bisect_nodes(nodes: list[str], rng: random.Random
                 ) -> tuple[list[str], list[str]]:
    """Random majority/minority split (jepsen shuffles then bisects; with odd
    n the first half is the minority)."""
    shuffled = list(nodes)
    rng.shuffle(shuffled)
    half = len(shuffled) // 2
    return shuffled[:half], shuffled[half:]


def random_halves(nodes: list[str], rng: random.Random
                  ) -> dict[str, list[str]]:
    """Map each node -> nodes it can still reach."""
    minority, majority = bisect_nodes(nodes, rng)
    reach = {}
    for n in minority:
        reach[n] = list(minority)
    for n in majority:
        reach[n] = list(majority)
    return reach


class PartitionRandomHalves(Nemesis):
    """iptables-based partition over SSH, like jepsen's partitioner."""

    def __init__(self, seed: int = 0):
        self.rng = random.Random(seed)
        self.active: Optional[tuple[list[str], list[str]]] = None

    async def setup(self, test: dict) -> None:
        await self._heal(test)

    async def invoke(self, test: dict, op: Op) -> Op:
        if op.f == "start":
            minority, majority = bisect_nodes(test["nodes"], self.rng)
            await self._partition(test, minority, majority)
            self.active = (minority, majority)
            value = {"isolated": minority, "majority": majority}
        elif op.f == "stop":
            await self._heal(test)
            self.active = None
            value = "network healed"
        else:
            value = f"unknown nemesis op {op.f}"
        return Op(type="info", f=op.f, value=value, process=op.process)

    async def teardown(self, test: dict) -> None:
        await self._heal(test)

    async def _partition(self, test: dict, minority: list[str],
                         majority: list[str]) -> None:
        # Drop in both directions on every node so the cut is symmetric even
        # if one side's rules fail to land.
        for side, other in ((minority, majority), (majority, minority)):
            for node in side:
                r = runner_for(test, node)
                for peer in other:
                    await r.run(
                        f"iptables -A INPUT -s {peer} -j DROP -w", su=True,
                        check=False)

    async def _heal(self, test: dict) -> None:
        for node in test["nodes"]:
            r = runner_for(test, node)
            await r.run("iptables -F -w && iptables -X -w", su=True,
                        check=False)


class FakePartitionNemesis(Nemesis):
    """Partition the in-process FakeKVStore: isolate a random minority.

    Same op surface and :start/:stop semantics as the real partitioner, so
    the reference's nemesis schedule (5s on / 5s off cycle,
    src/jepsen/etcdemo.clj:138-143) runs unchanged in hermetic tests."""

    def __init__(self, store, seed: int = 0):
        self.store = store
        self.rng = random.Random(seed)

    async def invoke(self, test: dict, op: Op) -> Op:
        if op.f == "start":
            minority, majority = bisect_nodes(test["nodes"], self.rng)
            self.store.isolate(set(minority))
            value = {"isolated": minority, "majority": majority}
        elif op.f == "stop":
            self.store.heal()
            value = "network healed"
        else:
            value = f"unknown nemesis op {op.f}"
        return Op(type="info", f=op.f, value=value, process=op.process)

    async def teardown(self, test: dict) -> None:
        self.store.heal()
