"""Network partitions.

PartitionRandomHalves = the reference's nemesis/partition-random-halves
(src/jepsen/etcdemo.clj:164): on :start, split nodes into a random
majority/minority and drop traffic between the halves with iptables over the
control plane; on :stop, heal. FakePartitionNemesis does the same against the
in-process FakeKVStore (isolates the minority) so partition tests run
hermetically.

The rest of the jepsen.nemesis partition family rides the same iptables
machinery via pluggable "grudge" functions (node -> reachable set, the
term jepsen.nemesis uses):

  * PartitionIsolatedNode  — cut one random node off from everyone
    (jepsen's partition-node / isolate-self-primaries style single cut);
  * PartitionBridge        — two halves that cannot see each other, plus
    one bridge node both halves still see (jepsen's `bridge`: raft must
    not count the bridge toward BOTH quorums at once);
  * PartitionMajoritiesRing — every node sees a majority, but no two
    nodes see the SAME majority (jepsen's partition-majorities-ring,
    the classic raft split-brain stressor): symmetric ring
    neighborhoods of the smallest radius whose window is a majority.

These three are REAL-cluster shapes (iptables over SSH). The hermetic
FakeKVStore models reachability as one isolated set, which can express
random-halves and isolated-node but not bridge/ring overlap — the fake
registry (compose.pick_nemesis) lists exactly what it supports.
"""

from __future__ import annotations

import random
from typing import Optional

from .. import obs
from ..control.runner import Runner, runner_for
from ..ops.op import Op
from .base import Nemesis


def bisect_nodes(nodes: list[str], rng: random.Random
                 ) -> tuple[list[str], list[str]]:
    """Random majority/minority split (jepsen shuffles then bisects; with odd
    n the first half is the minority)."""
    shuffled = list(nodes)
    rng.shuffle(shuffled)
    half = len(shuffled) // 2
    return shuffled[:half], shuffled[half:]


def random_halves(nodes: list[str], rng: random.Random
                  ) -> dict[str, list[str]]:
    """Map each node -> nodes it can still reach."""
    minority, majority = bisect_nodes(nodes, rng)
    reach = {}
    for n in minority:
        reach[n] = list(minority)
    for n in majority:
        reach[n] = list(majority)
    return reach


def isolated_node_grudge(nodes: list[str], rng: random.Random
                         ) -> dict[str, list[str]]:
    """One random node cut off from every peer."""
    victim = rng.choice(list(nodes))
    reach = {n: [p for p in nodes if p != victim] for n in nodes
             if n != victim}
    reach[victim] = [victim]
    return reach


def bridge_grudge(nodes: list[str], rng: random.Random
                  ) -> dict[str, list[str]]:
    """Two halves that cannot see each other; one bridge node sees (and
    is seen by) everyone. Needs n >= 3."""
    if len(nodes) < 3:
        raise ValueError("bridge partition needs >= 3 nodes")
    shuffled = list(nodes)
    rng.shuffle(shuffled)
    bridge = shuffled[0]
    rest = shuffled[1:]
    half = len(rest) // 2
    a, b = rest[:half], rest[half:]
    reach = {bridge: list(nodes)}
    for n in a:
        reach[n] = a + [bridge]
    for n in b:
        reach[n] = b + [bridge]
    return reach


def majorities_ring_grudge(nodes: list[str], rng: random.Random
                           ) -> dict[str, list[str]]:
    """Every node sees a majority; adjacent ring positions see shifted
    (distinct, overlapping) majorities. The radius is the smallest h
    with 2h+1 >= majority(n); for n <= 3 the window is all nodes and no
    cut exists (same degenerate edge jepsen has)."""
    ring = list(nodes)
    rng.shuffle(ring)
    n = len(ring)
    majority = n // 2 + 1
    h = (majority - 1 + 1) // 2        # ceil((majority-1)/2)
    reach = {}
    for i, node in enumerate(ring):
        reach[node] = sorted({ring[(i + d) % n]
                              for d in range(-h, h + 1)})
    return reach


class GrudgePartitioner(Nemesis):
    """iptables-based partition over SSH, like jepsen's partitioner:
    :start computes a reachability map ("grudge") and drops every
    non-reachable pair symmetrically; :stop flushes the rules. Subclasses
    pick the grudge (jepsen.nemesis's partitioner/grudge split)."""

    #: grudge(nodes, rng) -> {node: reachable nodes (incl. itself)}
    grudge = staticmethod(random_halves)

    def __init__(self, seed: int = 0):
        self.rng = random.Random(seed)
        self.active: Optional[dict[str, list[str]]] = None

    async def setup(self, test: dict) -> None:
        await self._heal(test)

    async def invoke(self, test: dict, op: Op) -> Op:
        if op.f == "start":
            reach = type(self).grudge(test["nodes"], self.rng)
            await self._partition(test, reach)
            self.active = reach
            value = self.describe(reach)
            # Fault-plane telemetry: correlated by span id to the
            # nemesis.<f> span the runner opened around this invoke.
            obs.get_tracer().event("fault.partition",
                                   kind=type(self).__name__,
                                   cut={n: sorted(v)
                                        for n, v in reach.items()})
        elif op.f == "stop":
            await self._heal(test)
            self.active = None
            value = "network healed"
            obs.get_tracer().event("fault.heal", kind=type(self).__name__)
        else:
            value = f"unknown nemesis op {op.f}"
        return Op(type="info", f=op.f, value=value, process=op.process)

    def describe(self, reach: dict[str, list[str]]):
        """The :info value recorded in the history."""
        return {"reachable": reach}

    async def teardown(self, test: dict) -> None:
        await self._heal(test)

    async def _partition(self, test: dict,
                         reach: dict[str, list[str]]) -> None:
        # Drop INPUT on both endpoints of every cut pair so the cut is
        # symmetric even if one side's rules fail to land.
        for node in test["nodes"]:
            r = runner_for(test, node)
            reachable = set(reach.get(node, [])) | {node}
            for peer in test["nodes"]:
                if peer != node and peer not in reachable:
                    await r.run(
                        f"iptables -A INPUT -s {peer} -j DROP -w", su=True,
                        check=False)

    async def _heal(self, test: dict) -> None:
        for node in test["nodes"]:
            r = runner_for(test, node)
            await r.run("iptables -F -w && iptables -X -w", su=True,
                        check=False)


class PartitionRandomHalves(GrudgePartitioner):
    """The reference's shape (src/jepsen/etcdemo.clj:164)."""

    grudge = staticmethod(random_halves)

    def describe(self, reach):
        # Keep the reference-era history value shape (tests and the
        # timeline rendering read isolated/majority).
        sides = sorted({frozenset(v) for v in reach.values()},
                       key=lambda s: (len(s), sorted(s)))
        if len(sides) == 1:               # degenerate n<2: nothing cut
            return {"isolated": [], "majority": sorted(sides[0])}
        return {"isolated": sorted(sides[0]),
                "majority": sorted(sides[-1])}


class PartitionIsolatedNode(GrudgePartitioner):
    grudge = staticmethod(isolated_node_grudge)

    def describe(self, reach):
        victim = next(n for n, v in reach.items() if v == [n])
        return {"isolated": [victim],
                "majority": sorted(n for n in reach if n != victim)}


class PartitionBridge(GrudgePartitioner):
    grudge = staticmethod(bridge_grudge)

    def describe(self, reach):
        bridge = max(reach, key=lambda n: len(reach[n]))
        return {"bridge": bridge, "reachable": reach}


class PartitionMajoritiesRing(GrudgePartitioner):
    grudge = staticmethod(majorities_ring_grudge)


class FakePartitionNemesis(Nemesis):
    """Partition the in-process FakeKVStore: isolate a random minority.

    Same op surface and :start/:stop semantics as the real partitioner, so
    the reference's nemesis schedule (5s on / 5s off cycle,
    src/jepsen/etcdemo.clj:138-143) runs unchanged in hermetic tests."""

    def __init__(self, store, seed: int = 0):
        self.store = store
        self.rng = random.Random(seed)

    def _split(self, nodes: list[str]) -> tuple[list[str], list[str]]:
        """(isolated, rest) — the one degree of freedom the fake's
        single-isolated-set reachability model allows; subclasses pick
        differently."""
        return bisect_nodes(nodes, self.rng)

    async def invoke(self, test: dict, op: Op) -> Op:
        if op.f == "start":
            minority, majority = self._split(test["nodes"])
            self.store.isolate(set(minority))
            value = {"isolated": minority, "majority": majority}
            obs.get_tracer().event("fault.partition",
                                   kind=type(self).__name__,
                                   isolated=sorted(minority))
        elif op.f == "stop":
            self.store.heal()
            value = "network healed"
            obs.get_tracer().event("fault.heal", kind=type(self).__name__)
        else:
            value = f"unknown nemesis op {op.f}"
        return Op(type="info", f=op.f, value=value, process=op.process)

    async def teardown(self, test: dict) -> None:
        self.store.heal()


class FakeIsolatedNodeNemesis(FakePartitionNemesis):
    """Single-node cut against the FakeKVStore — the one non-default
    partition shape its one-isolated-set reachability model can express
    (bridge/ring overlap cannot be faked; those are real-cluster-only)."""

    def _split(self, nodes: list[str]) -> tuple[list[str], list[str]]:
        victim = self.rng.choice(list(nodes))
        return [victim], [n for n in nodes if n != victim]
