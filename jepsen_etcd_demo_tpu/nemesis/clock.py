"""Clock-skew nemesis.

Beyond the reference demo (which only partitions) but part of the jepsen
nemesis family this build's fault-injection ABC covers (SURVEY.md §2.2
"jepsen.nemesis" row: partition, kill, pause, clock skew). jepsen's
nemesis/clock bumps node wall clocks and resets them on heal; correctness
of the HARNESS is unaffected (histories are timestamped client-side), so
this fault targets the system under test's clock assumptions (leases,
TTLs, leader election timeouts in etcd).

Real path: `date -s @<epoch+delta>` over the control plane (su), recording
each node's applied delta; :stop / teardown restores by applying the
inverse delta relative to the node's CURRENT clock (the node kept ticking
while skewed, so absolute restore would lose elapsed time).

Fake path: records the skew on the in-process store (`store.clock_skew`)
so hermetic runs exercise the same op/plumbing; the fake register is
linearizable regardless of clocks, so verdicts must stay valid — which is
itself the soundness property the e2e test pins down.
"""

from __future__ import annotations

import random
from typing import Optional

from .. import obs
from ..control.runner import runner_for
from ..ops.op import Op
from .base import Nemesis, random_minority


class ClockSkewNemesis(Nemesis):
    """:start skews a random subset's clocks by up to +/- max_skew_s;
    :stop applies the inverse deltas."""

    def __init__(self, seed: int = 0, max_skew_s: float = 60.0):
        self.rng = random.Random(seed)
        self.max_skew_s = max_skew_s
        self.applied: dict[str, float] = {}

    async def _shift(self, test: dict, node: str, delta_s: int) -> bool:
        """Shift relative to the node's own current clock; True iff the
        date command actually succeeded (no CAP_SYS_TIME / sudo problems
        must not be recorded as applied — the heal pass would then skew a
        clock that was never skewed)."""
        r = runner_for(test, node)
        res = await r.run(
            f"date -s @$(( $(date +%s) + {delta_s} ))",
            su=True, check=False)
        return res.ok

    async def invoke(self, test: dict, op: Op) -> Op:
        if op.f == "start":
            for node in random_minority(self.rng, test["nodes"]):
                # Whole seconds, drawn once: the same value is applied,
                # recorded, and inverted (a float here would silently
                # truncate in the shell while the history reported it).
                delta = 0
                while delta == 0:
                    delta = self.rng.randint(-int(self.max_skew_s),
                                             int(self.max_skew_s))
                if await self._shift(test, node, delta):
                    self.applied[node] = self.applied.get(node, 0) + delta
                    obs.get_tracer().event("fault.clock_skew", node=node,
                                           delta_s=delta)
            value = {"skewed": dict(self.applied)}
        elif op.f == "stop":
            await self._restore(test)
            value = "clocks restored"
            obs.get_tracer().event("fault.clock_restore")
        else:
            value = f"unknown nemesis op {op.f}"
        return Op(type="info", f=op.f, value=value, process=op.process)

    async def _restore(self, test: dict) -> None:
        for node, delta in list(self.applied.items()):
            if await self._shift(test, node, -delta):
                del self.applied[node]

    async def teardown(self, test: dict) -> None:
        await self._restore(test)


class ClockStrobeNemesis(ClockSkewNemesis):
    """jepsen's strobe-clock: rapidly OSCILLATE a minority's clocks
    (+delta, -delta, ...) for a short burst instead of holding a steady
    skew — the shape that breaks lease/TTL logic which tolerates a
    constant offset but not a clock that won't advance monotonically.

    The whole burst runs as ONE shell program per node, concurrently
    across the minority, and ends by restoring the wall clock from the
    MONOTONIC clock (/proc/uptime): every `date -s` truncates fractions,
    so a naive balanced loop walks the clock ~2*cycles*period_s behind
    real time — instead the restore computes t0 + elapsed-monotonic and
    sets that, under a shell EXIT trap so an interrupted burst (shell
    TERM, ssh drop) still restores. A SIGKILL of the remote shell can
    leak the in-flight half-cycle's skew, same exposure jepsen's
    strobe has; `applied` stays empty because the program self-restores."""

    def __init__(self, seed: int = 0, max_skew_s: float = 8.0,
                 cycles: int = 20, period_s: float = 0.1):
        super().__init__(seed=seed, max_skew_s=max_skew_s)
        self.cycles = cycles
        self.period_s = period_s

    def _burst_cmd(self, delta: int) -> str:
        return (
            "t0=$(date +%s.%N); m0=$(cut -d' ' -f1 /proc/uptime); "
            "restore() { m1=$(cut -d' ' -f1 /proc/uptime); "
            "date -s @$(awk -v t0=\"$t0\" -v m0=\"$m0\" -v m1=\"$m1\" "
            "'BEGIN{printf \"%.6f\", t0 + (m1 - m0)}') >/dev/null || :; }; "
            # Signals exit via `exit` so the EXIT trap (the restore)
            # still fires — a bare TERM/HUP would skip it in dash.
            "trap restore EXIT; trap 'exit 143' TERM HUP INT; "
            # Every failed set marks the burst failed (no CAP_SYS_TIME /
            # sudo misconfiguration must not record the node as
            # strobed): the loop's last `sleep` would otherwise mask
            # every date error with exit 0.
            f"fail=0; for i in $(seq {self.cycles}); do "
            f"date -s @$(( $(date +%s) + {delta} )) >/dev/null || fail=1; "
            f"sleep {self.period_s}; "
            f"date -s @$(( $(date +%s) - {delta} )) >/dev/null || fail=1; "
            f"sleep {self.period_s}; "
            "done; exit $fail")

    async def invoke(self, test: dict, op: Op) -> Op:
        if op.f != "start":
            return await super().invoke(test, op)
        import asyncio

        # Deltas drawn BEFORE the gather: rng order stays deterministic
        # regardless of task interleaving.
        targets = [(node, self.rng.randint(1, max(1, int(self.max_skew_s))))
                   for node in random_minority(self.rng, test["nodes"])]
        timeout = 60.0 + 4 * self.cycles * self.period_s

        async def burst(node: str, delta: int) -> bool:
            r = runner_for(test, node)
            res = await r.run(self._burst_cmd(delta), su=True, check=False,
                              timeout_s=timeout)
            return res.ok

        # Concurrent: the fault shape is the MINORITY strobing at once,
        # not nodes taking turns.
        oks = await asyncio.gather(*(burst(n, d) for n, d in targets))
        value = {"strobed": {n: {"delta_s": d, "cycles": self.cycles}
                             for (n, d), ok in zip(targets, oks) if ok}}
        return Op(type="info", f=op.f, value=value, process=op.process)


class FakeClockSkewNemesis(Nemesis):
    """Hermetic twin: records skews on the FakeKVStore (which is
    linearizable regardless, so the checker verdict must stay valid)."""

    def __init__(self, store, seed: int = 0, max_skew_s: float = 60.0):
        self.store = store
        self.rng = random.Random(seed)
        self.max_skew_s = max_skew_s
        if not hasattr(store, "clock_skew"):
            store.clock_skew = {}

    async def invoke(self, test: dict, op: Op) -> Op:
        if op.f == "start":
            for node in random_minority(self.rng, self.store.nodes):
                self.store.clock_skew[node] = self.rng.uniform(
                    -self.max_skew_s, self.max_skew_s)
            value = {"skewed": {k: round(v, 1) for k, v
                               in self.store.clock_skew.items()}}
        elif op.f == "stop":
            self.store.clock_skew.clear()
            value = "clocks restored"
        else:
            value = f"unknown nemesis op {op.f}"
        return Op(type="info", f=op.f, value=value, process=op.process)

    async def teardown(self, test: dict) -> None:
        self.store.clock_skew.clear()
