"""Process-level faults: kill/restart and pause/resume the DB daemon.

Beyond the reference demo (which only partitions), but part of the jepsen
nemesis family the build's fault-injection ABC covers (SURVEY.md §5.3:
"partition first (same semantics), then kill/pause")."""

from __future__ import annotations

import random

from .. import obs
from ..control.runner import runner_for
from ..ops.op import Op
from .base import Nemesis, random_minority


class KillNemesis(Nemesis):
    """:start kills the DB daemon on a random subset; :stop restarts it."""

    def __init__(self, db, seed: int = 0):
        self.db = db
        self.rng = random.Random(seed)
        self.killed: list[str] = []

    async def invoke(self, test: dict, op: Op) -> Op:
        if op.f == "start":
            self.killed = random_minority(self.rng, test["nodes"])
            for node in self.killed:
                r = runner_for(test, node)
                # Both legs go through the DB protocol (db.kill /
                # db.start) so a non-etcd DB is killable by overriding
                # them, not by happening to share etcd's pidfile path.
                await self.db.kill(test, r, node)
                obs.get_tracer().event("fault.kill", node=node)
            value = {"killed": self.killed}
        elif op.f == "stop":
            for node in self.killed:
                r = runner_for(test, node)
                # start, not setup: the binary and data dir survived the
                # kill; reinstalling would stretch the outage for nothing
                # (jepsen's db/kill! restart leg).
                await self.db.start(test, r, node)
                obs.get_tracer().event("fault.restart", node=node)
            value = {"restarted": self.killed}
            self.killed = []
        else:
            value = f"unknown nemesis op {op.f}"
        return Op(type="info", f=op.f, value=value, process=op.process)

    async def teardown(self, test: dict) -> None:
        pass


class PauseNemesis(Nemesis):
    """:start SIGSTOPs the daemon on a random subset; :stop SIGCONTs.

    `pidfile` may be a fixed path or a node->path callable — co-hosted
    nodes (db/etcd.py PORT_MAP) write per-node pidfiles, and a pause
    aimed at the shared default path would silently hit nothing while
    the history records the fault as fired."""

    def __init__(self, pidfile, seed: int = 0):
        self.pidfile = pidfile
        self.rng = random.Random(seed)
        self.paused: list[str] = []

    def _pidfile(self, node: str) -> str:
        return self.pidfile(node) if callable(self.pidfile) \
            else self.pidfile

    async def invoke(self, test: dict, op: Op) -> Op:
        if op.f == "start":
            self.paused = random_minority(self.rng, test["nodes"])
            for node in self.paused:
                r = runner_for(test, node)
                await r.run(f"kill -STOP $(cat {self._pidfile(node)})",
                            su=True, check=False)
                obs.get_tracer().event("fault.pause", node=node)
            value = {"paused": self.paused}
        elif op.f == "stop":
            for node in self.paused:
                r = runner_for(test, node)
                await r.run(f"kill -CONT $(cat {self._pidfile(node)})",
                            su=True, check=False)
                obs.get_tracer().event("fault.resume", node=node)
            value = {"resumed": self.paused}
            self.paused = []
        else:
            value = f"unknown nemesis op {op.f}"
        return Op(type="info", f=op.f, value=value, process=op.process)

    async def teardown(self, test: dict) -> None:
        for node in self.paused:
            r = runner_for(test, node)
            await r.run(f"kill -CONT $(cat {self._pidfile(node)})",
                        su=True, check=False)
