"""Nemesis protocol: a special client on the fault plane.

Like jepsen.nemesis, a nemesis has client-shaped lifecycle (setup/invoke/
teardown) but its ops target the environment, not the data plane. The runner
gives it ops from the nemesis generator channel ({:f :start}/{:f :stop},
reference src/jepsen/etcdemo.clj:138-143)."""

from __future__ import annotations

import abc

from ..ops.op import Op


class Nemesis(abc.ABC):
    async def setup(self, test: dict) -> None:
        pass

    @abc.abstractmethod
    async def invoke(self, test: dict, op: Op) -> Op:
        """Execute the fault op; return its completion (:info with a
        description value, like jepsen nemeses)."""

    async def teardown(self, test: dict) -> None:
        """Must leave the environment healed."""


class NoopNemesis(Nemesis):
    async def invoke(self, test: dict, op: Op) -> Op:
        return Op(type="info", f=op.f, value="noop", process=op.process)


def random_minority(rng, nodes: list) -> list:
    """Random non-empty subset of at most half the nodes — the shared
    target-selection rule of the kill/pause/clock nemeses (a strict
    minority, so a quorum always survives the fault)."""
    n = rng.randrange(1, max(2, len(nodes) // 2 + 1))
    return rng.sample(list(nodes), n)
