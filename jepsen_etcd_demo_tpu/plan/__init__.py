"""KernelPlan — one plan/dispatch spine under every kernel.

``plan.registry`` declares the family table (verified against
contracts.json by jtflow JTL407 + the tier-1 sync test),
``plan.core`` the KernelPlan runtime object, ``plan.dispatch`` the
routing planners and the resolve/dispatch choke point. See
doc/perf.md "KernelPlan & pod-scale".
"""

from .core import (CONTRACTS_FILE, KernelPlan, MeshSpec,  # noqa: F401
                   PlanContractError, build_plan, check_registry,
                   load_contracts, plan_report, verify_registry)
from .dispatch import (LaunchPipeline, dispatch,  # noqa: F401
                       dispatch_long, launch_multiple, plan_dense_batch,
                       plan_device_encode, plan_elle_batch,
                       plan_elle_single, plan_long_sweep, plan_resumable,
                       plan_stream_chunk, resolve)
from .registry import (PLAN_FAMILIES, backend_callable,  # noqa: F401
                       family_entry)
