"""The KernelPlan family registry — the plan layer's half of the
contract that ``contracts.json`` (analysis/flow/contracts.py) is the
other half of.

``PLAN_FAMILIES`` is a PURE LITERAL, deliberately: jtflow's JTL407
(analysis/rules/flow_rules.py) parses it straight out of the AST and
diffs it against the checked-in ``contracts.json`` — every kernel
family the spec declares must resolve to a registry entry here (same
module, factory, donation set, packed schema, carry, mesh axes), and
every family this layer can dispatch must appear in the spec. The
runtime twin (``plan.core.verify_registry``) runs the same diff from
the tier-1 sync test, so the plan layer cannot silently drift from the
contract it was seeded from in either representation.

Entry fields (per family, keyed by the kernel's ``instrument_kernel``
name):

  module   repo-relative path of the backend module (== contracts)
  factory  the factory function contracts.json records  (== contracts)
  donates  donated operand positions                    (== contracts)
  packed   packed-result schema ref or None             (== contracts)
  carry    resumable-carry NamedTuple name or None (must exist in the
           contracts ``carries`` section when set)
  axes     mesh axis names the kernel shards over (every name must be
           declared in the contracts ``meshes`` section)
  role     how dispatch drives it: "launch" (call with stacked
           arrays), "chunk" (host-loop resumable chunk fn), "prep"/
           "transitions" (internal half of a two-stage launch),
           "launcher" (shape-parameterized pallas launcher)
  entry    attribute dispatch resolves when it differs from `factory`
           (e.g. the packed form of a dict-result factory); not part
           of the contracts diff
"""

from __future__ import annotations

import importlib
from typing import Any

# jtflow directives quoted here are prose, not annotations (comments
# only bind from real comment tokens — analysis/flow/facts.py).

PLAN_FAMILIES = {
    "elle-closure": {
        "module": "jepsen_etcd_demo_tpu/ops/cycles.py",
        "factory": "_closure_fn",
        "donates": [],
        "packed": None,
        "carry": None,
        "axes": [],
        "role": "launch",
    },
    "elle-closure-batch": {
        "module": "jepsen_etcd_demo_tpu/ops/cycles.py",
        "factory": "_batch_closure_fn",
        "donates": [],
        "packed": None,
        "carry": None,
        "axes": [],
        "role": "launch",
    },
    "elle-closure-tiled": {
        "module": "jepsen_etcd_demo_tpu/ops/cycles_tiled.py",
        "factory": "_occ_fn",
        "donates": [0],
        "packed": None,
        "carry": None,
        "axes": [],
        "role": "launch",
    },
    "elle-closure-tiled-pallas": {
        "module": "jepsen_etcd_demo_tpu/ops/cycles_tiled.py",
        "factory": "_sparse_round_fn",
        "donates": [0],
        "packed": None,
        "carry": None,
        "axes": [],
        "role": "launch",
    },
    "lattice-transitions": {
        "module": "jepsen_etcd_demo_tpu/parallel/lattice.py",
        "factory": "_transitions_fn",
        "donates": [],
        "packed": None,
        "carry": None,
        "axes": [],
        "role": "transitions",
    },
    "wgl2-batch": {
        "module": "jepsen_etcd_demo_tpu/ops/wgl2.py",
        "factory": "cached_batch_checker2",
        "donates": [],
        "packed": None,
        "carry": None,
        "axes": [],
        "role": "launch",
    },
    "wgl2-chunk": {
        "module": "jepsen_etcd_demo_tpu/ops/wgl2.py",
        "factory": "cached_chunk2",
        "donates": [],
        "packed": None,
        "carry": "_Carry2",
        "axes": [],
        "role": "chunk",
    },
    "wgl2-single": {
        "module": "jepsen_etcd_demo_tpu/ops/wgl2.py",
        "factory": "cached_checker2",
        "donates": [],
        "packed": None,
        "carry": None,
        "axes": [],
        "role": "launch",
    },
    "wgl2-sort-sharded": {
        "module": "jepsen_etcd_demo_tpu/parallel/dense.py",
        "factory": "sharded_batch_checker2",
        "donates": [],
        "packed": None,
        "carry": None,
        "axes": ["batch"],
        "role": "launch",
    },
    "wgl3-batch": {
        "module": "jepsen_etcd_demo_tpu/ops/wgl3.py",
        "factory": "cached_batch_checker3",
        "donates": [],
        "packed": "wgl3.PACKED_FIELDS_XLA",
        "carry": None,
        "axes": [],
        "role": "launch",
        "entry": "cached_batch_checker3_packed",
    },
    "wgl3-chunk": {
        "module": "jepsen_etcd_demo_tpu/ops/wgl3.py",
        "factory": "_cached_chunk_run",
        "donates": [0],
        "packed": None,
        "carry": "_Carry3",
        "axes": [],
        "role": "chunk",
    },
    "wgl3-chunk-dedup": {
        "module": "jepsen_etcd_demo_tpu/ops/wgl3.py",
        "factory": "_cached_chunk_run_dedup",
        "donates": [0],
        "packed": None,
        "carry": "_Carry3",
        "axes": [],
        "role": "chunk",
    },
    "wgl3-dense-multislice": {
        "module": "jepsen_etcd_demo_tpu/parallel/multislice.py",
        "factory": "_sharded_batch_checker",
        "donates": [],
        "packed": None,
        "carry": None,
        "axes": ["slice", "batch"],
        "role": "launch",
    },
    "wgl3-dense-sharded": {
        "module": "jepsen_etcd_demo_tpu/parallel/dense.py",
        "factory": "sharded_batch_checker3_packed",
        "donates": [],
        "packed": "wgl3.PACKED_FIELDS_XLA",
        "carry": None,
        "axes": ["batch"],
        "role": "launch",
    },
    "wgl3-encode": {
        "module": "jepsen_etcd_demo_tpu/ops/encode_device.py",
        "factory": "cached_device_encoder",
        "donates": [],
        "packed": None,
        "carry": None,
        "axes": [],
        "role": "launch",
    },
    "wgl3-encode-sharded": {
        "module": "jepsen_etcd_demo_tpu/parallel/dense.py",
        "factory": "sharded_device_encoder",
        "donates": [],
        "packed": None,
        "carry": None,
        "axes": ["batch"],
        "role": "launch",
    },
    "wgl3-lattice-chunk": {
        "module": "jepsen_etcd_demo_tpu/parallel/lattice.py",
        "factory": "make_lattice_chunk_fn",
        "donates": [],
        "packed": None,
        "carry": None,
        "axes": ["lattice"],
        "role": "chunk",
        "entry": "cached_lattice_chunk",
    },
    "wgl3-pallas": {
        "module": "jepsen_etcd_demo_tpu/ops/wgl3_pallas.py",
        "factory": "local_pallas_launcher",
        "donates": [],
        "packed": "wgl3.PACKED_FIELDS",
        "carry": None,
        "axes": [],
        "role": "launcher",
        "entry": "cached_batch_checker_pallas",
    },
    "wgl3-pallas-grouped": {
        "module": "jepsen_etcd_demo_tpu/ops/wgl3_pallas.py",
        "factory": "local_pallas_launcher_grouped",
        "donates": [],
        "packed": "wgl3.PACKED_FIELDS",
        "carry": None,
        "axes": [],
        "role": "launcher",
        "entry": "cached_batch_checker_pallas_grouped",
    },
    "wgl3-pallas-prep": {
        "module": "jepsen_etcd_demo_tpu/ops/wgl3_pallas.py",
        "factory": "_cached_prep",
        "donates": [],
        "packed": None,
        "carry": None,
        "axes": [],
        "role": "prep",
    },
    "wgl3-pallas-resumable": {
        "module": "jepsen_etcd_demo_tpu/ops/wgl3_pallas.py",
        "factory": "local_pallas_launcher_resumable",
        "donates": [1, 4],
        "packed": None,
        "carry": None,
        "axes": [],
        "role": "launcher",
        "entry": "_cached_resumable_launcher",
    },
    "wgl3-pallas-sharded": {
        "module": "jepsen_etcd_demo_tpu/parallel/dense.py",
        "factory": "sharded_batch_checker_pallas",
        "donates": [],
        "packed": None,
        "carry": None,
        "axes": ["batch"],
        "role": "launch",
    },
    "wgl3-pallas-sharded-prep": {
        "module": "jepsen_etcd_demo_tpu/parallel/dense.py",
        "factory": "sharded_batch_checker_pallas",
        "donates": [],
        "packed": None,
        "carry": None,
        "axes": ["batch"],
        "role": "prep",
    },
    "wgl3-pallas-sparse-resumable": {
        "module": "jepsen_etcd_demo_tpu/ops/wgl3_pallas.py",
        "factory": "local_pallas_launcher_sparse_resumable",
        "donates": [1, 4],
        "packed": None,
        "carry": None,
        "axes": [],
        "role": "launcher",
        "entry": "_cached_sparse_resumable_launcher",
    },
    "wgl3-single": {
        "module": "jepsen_etcd_demo_tpu/ops/wgl3.py",
        "factory": "cached_checker3_packed",
        "donates": [],
        "packed": "wgl3.PACKED_FIELDS_XLA",
        "carry": None,
        "axes": [],
        "role": "launch",
    },
    "wgl3-sparse-chunk": {
        "module": "jepsen_etcd_demo_tpu/ops/wgl3_sparse.py",
        "factory": "_cached_sparse_chunk",
        "donates": [0],
        "packed": None,
        "carry": "_Carry3",
        "axes": [],
        "role": "chunk",
    },
    "wgl3-sparse-chunk-dedup": {
        "module": "jepsen_etcd_demo_tpu/ops/wgl3_sparse.py",
        "factory": "_cached_sparse_chunk_dedup",
        "donates": [0],
        "packed": None,
        "carry": "_Carry3",
        "axes": [],
        "role": "chunk",
    },
}


def family_entry(family: str) -> dict:
    try:
        return PLAN_FAMILIES[family]
    except KeyError:
        raise KeyError(
            f"unknown kernel family {family!r} — not in the plan "
            f"registry (known: {', '.join(sorted(PLAN_FAMILIES))})"
        ) from None


def backend_callable(family: str) -> Any:
    """The backend factory/entry callable for a family, resolved from
    the registry's module path (lazy — importing a backend module may
    pull in jax)."""
    ent = family_entry(family)
    modname = ent["module"].replace("/", ".").removesuffix(".py")
    mod = importlib.import_module(modname)
    return getattr(mod, ent.get("entry") or ent["factory"])
