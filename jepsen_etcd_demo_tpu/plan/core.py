"""KernelPlan — the one plan object under every kernel launch.

A ``KernelPlan`` is the runtime face of one ``contracts.json`` kernel
family: geometry (DenseConfig / WGLConfig / tile shape), chunking,
batch shape, mesh + axis names, sparsity/dedup mode, the donation set
and carry fields the contract declares, and the provenance of each
choice. Plans are built by the routing planners in ``plan.dispatch``
(which own the policy that used to be copied into sched / stream /
wgl3_pallas / parallel.dense) and executed through
``KernelPlan.dispatch`` — the single choke point every production
launch goes through.

Elasticity lives in the key discipline: ``KernelPlan.cache_key()``
includes the mesh identity (axes + shape + device ids,
parallel/mesh.mesh_key), so when the visible device count changes
between runs the plan re-buckets and every kernel-LRU lookup MISSES
instead of serving a compiled launch for a mesh that no longer exists
(tests/test_plan_elastic.py pins this).

The registry (``plan.registry.PLAN_FAMILIES``) is verified against
``contracts.json`` twice: statically by jtflow JTL407 and at runtime
by :func:`verify_registry` (the tier-1 contracts↔plan sync test) — the
plan layer cannot drift from the spec it was seeded from.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Optional

from .registry import PLAN_FAMILIES, family_entry

CONTRACTS_FILE = "contracts.json"


class PlanContractError(RuntimeError):
    """The plan registry and contracts.json disagree — the drift JTL407
    exists to catch, surfaced at runtime with the same wording."""


def repo_root() -> Path:
    """The tree root contracts.json lives in (two levels above plan/)."""
    return Path(__file__).resolve().parents[2]


_CONTRACTS: Optional[dict] = None


def load_contracts(root: Optional[Path] = None) -> Optional[dict]:
    """The checked-in contracts.json (parsed once per process), or None
    when the tree doesn't carry one (an installed package without the
    repo — plans still build, from the registry alone)."""
    global _CONTRACTS
    if root is not None:
        path = Path(root) / CONTRACTS_FILE
        try:
            return json.loads(path.read_text(encoding="utf-8"))
        except (OSError, ValueError):
            return None
    if _CONTRACTS is None:
        path = repo_root() / CONTRACTS_FILE
        try:
            _CONTRACTS = json.loads(path.read_text(encoding="utf-8"))
        except (OSError, ValueError):
            _CONTRACTS = {}
    return _CONTRACTS or None


def verify_registry(contracts: Optional[dict] = None) -> list[str]:
    """The contracts↔plan diff, as a list of mismatch strings (empty =
    in sync). The runtime twin of jtflow JTL407: every spec family must
    resolve to a registry entry with matching module / factory /
    donation set / packed schema, every registry family must appear in
    the spec, declared carries must exist in the spec's carries
    section, and declared mesh axes in its meshes section."""
    if contracts is None:
        contracts = load_contracts()
    if contracts is None:
        return ["contracts.json missing — run `jepsen-tpu lint "
                "--write-contracts`"]
    problems: list[str] = []
    spec = contracts.get("kernels", {})
    carries = set(contracts.get("carries", {}))
    meshes = set(contracts.get("meshes", {}))
    for fam in sorted(set(spec) - set(PLAN_FAMILIES)):
        problems.append(
            f"kernel family {fam!r} is in contracts.json but has no "
            f"KernelPlan registry entry — the plan layer cannot "
            f"dispatch it")
    for fam in sorted(set(PLAN_FAMILIES) - set(spec)):
        problems.append(
            f"plan registry dispatches backend {fam!r}, which "
            f"contracts.json does not declare — dispatch target "
            f"outside the spec")
    for fam in sorted(set(spec) & set(PLAN_FAMILIES)):
        ent, dec = PLAN_FAMILIES[fam], spec[fam]
        for fld in ("module", "factory"):
            if ent[fld] != dec.get(fld):
                problems.append(
                    f"{fam}: registry {fld} {ent[fld]!r} != contracts "
                    f"{dec.get(fld)!r}")
        if sorted(ent["donates"]) != sorted(dec.get("donates", [])):
            problems.append(
                f"{fam}: registry donates {sorted(ent['donates'])} != "
                f"contracts {sorted(dec.get('donates', []))}")
        if (ent["packed"] or None) != dec.get("packed"):
            problems.append(
                f"{fam}: registry packed {ent['packed']!r} != contracts "
                f"{dec.get('packed')!r}")
        if ent["carry"] and ent["carry"] not in carries:
            problems.append(
                f"{fam}: registry carry {ent['carry']!r} is not a "
                f"contracts carries entry ({sorted(carries)})")
        for ax in ent["axes"]:
            if ax not in meshes:
                problems.append(
                    f"{fam}: registry mesh axis {ax!r} is not declared "
                    f"by any mesh construction (contracts meshes: "
                    f"{sorted(meshes)})")
    return problems


def check_registry() -> None:
    """Raise PlanContractError when the registry drifted from the spec
    (dispatch calls this once per process before the first resolve)."""
    problems = verify_registry()
    if problems:
        raise PlanContractError(
            "plan registry out of sync with contracts.json:\n  "
            + "\n  ".join(problems))


@dataclass(frozen=True)
class MeshSpec:
    """The mesh identity a plan keys its compiled launches on."""

    axes: tuple[str, ...]
    shape: tuple[int, ...]
    device_ids: tuple[int, ...]

    @classmethod
    def from_mesh(cls, mesh) -> "MeshSpec":
        from ..parallel.mesh import mesh_key

        axes, shape, ids = mesh_key(mesh)
        return cls(axes=axes, shape=shape, device_ids=ids)

    @property
    def total(self) -> int:
        n = 1
        for s in self.shape:
            n *= s
        return n

    def key(self) -> tuple:
        return (self.axes, self.shape, self.device_ids)


@dataclass(frozen=True, eq=False)
class KernelPlan:
    """One resolved launch plan: a contracts.json family plus the
    runtime choices (geometry, chunking, batch, mesh, sparsity) the
    planners made for this call shape. `extra` carries family-specific
    builder arguments as a sorted, hashable tuple of (name, value)
    pairs; `model` rides along un-hashed (its cache_key() joins the
    plan key)."""

    family: str
    label: str                      # human-facing kernel string
    model: Any = None
    geometry: Any = None            # DenseConfig / WGLConfig / None
    n_steps: Optional[int] = None
    batch: Optional[int] = None
    chunk: Optional[int] = None
    mesh: Optional[MeshSpec] = None
    sparse: bool = False
    dedup: bool = False
    extra: tuple = ()
    # contract-declared facts, filled by build_plan from the registry:
    donates: tuple = ()
    packed: Optional[str] = None
    carry: Optional[str] = None
    provenance: tuple = ()          # sorted (field, source) pairs

    def cache_key(self) -> tuple:
        """The kernel-LRU key for this plan's compiled launch. Includes
        the mesh identity — the elastic-reshard safety invariant (a
        device-count change can only MISS, never alias)."""
        return ("plan", self.family,
                self.model.cache_key() if self.model is not None else None,
                self.geometry, self.n_steps, self.batch, self.chunk,
                self.mesh.key() if self.mesh is not None else None,
                self.sparse, self.dedup, self.extra)

    def dispatch(self, *args, **kwargs):
        """Resolve this plan's backend kernel and launch it — THE entry
        every rerouted caller funnels through (plan.dispatch module)."""
        from .dispatch import dispatch

        return dispatch(self, *args, **kwargs)

    def resolve(self):
        from .dispatch import resolve

        return resolve(self)

    def describe(self) -> dict:
        """JSON-friendly dump (the `jepsen-tpu plan --print` payload)."""
        ent = family_entry(self.family)
        return {
            "family": self.family,
            "label": self.label,
            "model": getattr(self.model, "name", None),
            "geometry": repr(self.geometry) if self.geometry is not None
            else None,
            "n_steps": self.n_steps,
            "batch": self.batch,
            "chunk": self.chunk,
            "mesh": {"axes": list(self.mesh.axes),
                     "shape": list(self.mesh.shape)}
            if self.mesh is not None else None,
            "sparse": self.sparse,
            "dedup": self.dedup,
            "extra": {k: repr(v) for k, v in self.extra},
            "backend": {"module": ent["module"], "factory": ent["factory"],
                        "entry": ent.get("entry") or ent["factory"],
                        "role": ent["role"]},
            "donates": list(self.donates),
            "packed": self.packed,
            "carry": self.carry,
            "provenance": dict(self.provenance),
        }


def build_plan(family: str, model: Any = None, geometry: Any = None, *,
               label: Optional[str] = None, n_steps: Optional[int] = None,
               batch: Optional[int] = None, chunk: Optional[int] = None,
               mesh: Any = None, sparse: bool = False, dedup: bool = False,
               provenance: Optional[dict] = None,
               **extra) -> KernelPlan:
    """A KernelPlan for `family`, contract fields filled from the
    registry (which JTL407 + verify_registry pin to contracts.json).
    `mesh` accepts a jax Mesh or a MeshSpec."""
    ent = family_entry(family)
    if mesh is not None and not isinstance(mesh, MeshSpec):
        mesh = MeshSpec.from_mesh(mesh)
    return KernelPlan(
        family=family, label=label or family, model=model,
        geometry=geometry, n_steps=n_steps, batch=batch, chunk=chunk,
        mesh=mesh, sparse=sparse, dedup=dedup,
        extra=tuple(sorted(extra.items())),
        donates=tuple(ent["donates"]), packed=ent["packed"],
        carry=ent["carry"],
        provenance=tuple(sorted((provenance or {}).items())))


def plan_report(family: Optional[str] = None) -> dict:
    """The `jepsen-tpu plan --print` document: per-family resolved plan
    skeletons (contract facts + backend + current-platform mesh hints)
    plus the registry↔contracts sync verdict — the plan layer's
    tools/print_profile.py equivalent."""
    from ..ops.limits import limits

    fams = [family] if family else sorted(PLAN_FAMILIES)
    for f in fams:
        family_entry(f)             # unknown family fails loudly
    lim = limits()
    try:
        import jax

        devices = jax.device_count()
        processes = jax.process_count()
    except Exception:
        devices = processes = None
    report = {
        "contracts": str(repo_root() / CONTRACTS_FILE),
        "sync": verify_registry() or "ok",
        "devices": devices,
        "processes": processes,
        "limits": {"sparse_mode": lim.sparse_mode,
                   "dedup_mode": lim.dedup_mode,
                   "long_scan_chunk": lim.long_scan_chunk,
                   "step_bucket_floor": lim.step_bucket_floor,
                   "batch_bucket_floor": lim.batch_bucket_floor},
        "families": {},
    }
    for f in fams:
        ent = family_entry(f)
        report["families"][f] = {
            "module": ent["module"], "factory": ent["factory"],
            "entry": ent.get("entry") or ent["factory"],
            "role": ent["role"], "donates": list(ent["donates"]),
            "packed": ent["packed"], "carry": ent["carry"],
            "axes": list(ent["axes"]),
        }
    return report
