"""Plan dispatch — the ONE routing point under every kernel launch.

Before this module, the backend choice was copied wherever a launch
happened: ``wgl3_pallas.packed_batch_checker`` picked pallas-vs-XLA for
single-device batches, ``parallel.dense.sharded_packed_batch_checker``
re-made the same choice per shard, ``sched._dense_bucket_launcher``
re-made the sharded-vs-local choice per bucket, and
``check_encoded_general`` / ``run_long_dense`` each carried their own
lattice-vs-pallas-vs-XLA ladder for long sweeps. Those four copies now
live here once, as PLANNERS that return a :class:`KernelPlan`:

  plan_dense_batch    one batched dense launch (single- or multi-
                      device, pallas or XLA, grouped or not)
  plan_long_sweep     the host-chunked long-sweep family (lattice /
                      pallas-resumable / sparse / dedup / plain chunk)
  plan_stream_chunk   the streaming engine's resumable chunk kernel
  plan_resumable      the wgl2 sort-ladder chunk kernel
  plan_elle_batch     the vmapped corpus-of-graphs closure

and EXECUTORS — ``resolve(plan)`` (the compiled launch, through the
sched kernel LRU keyed by ``plan.cache_key()``, which carries the mesh
identity: an elastic re-shard can only miss) and ``dispatch(plan,
...)`` / ``dispatch_long(...)`` (launch it). The first resolve in a
process verifies the registry against contracts.json
(``core.check_registry``) so a drifted plan layer fails loudly before
it launches anything.
"""

from __future__ import annotations

from typing import Any, Optional

from .core import (KernelPlan, MeshSpec, build_plan, check_registry,
                   load_contracts)
from .registry import PLAN_FAMILIES, backend_callable

_CHECKED = False


def _ensure_checked() -> None:
    global _CHECKED
    if not _CHECKED:
        # Trees without a contracts.json (installed package) skip the
        # gate; in-repo, drift fails the first dispatch loudly.
        if load_contracts() is not None:
            check_registry()
        _CHECKED = True


def resolve(plan: KernelPlan):
    """The compiled launch callable for a plan, through the sched
    kernel LRU (hit/miss accounted; bounded by
    limits().kernel_cache_entries). The key is plan.cache_key() — mesh
    identity included, so a re-shard (device count changed between
    runs) misses into a fresh build instead of aliasing a compiled
    launch for a mesh that no longer exists."""
    from ..sched.compile_cache import kernel_cache

    _ensure_checked()
    builder = _BUILDERS.get(plan.family)
    if builder is None:
        raise KeyError(
            f"no dispatch builder for kernel family {plan.family!r}")
    return kernel_cache().get(plan.cache_key(), lambda: builder(plan))


def dispatch(plan: KernelPlan, *args, **kwargs):
    """Resolve + launch: the single choke point (KernelPlan.dispatch).
    Launches carry the plan's identity into the scaling ledger
    (obs/ledger.py) — callers that know the padding economics open a
    richer launch_context themselves; the merge keeps their fields."""
    from ..obs import ledger as obs_ledger

    fn = resolve(plan)
    with obs_ledger.launch_context(**obs_ledger.plan_context(plan)):
        return fn(*args, **kwargs)


def _extra(plan: KernelPlan) -> dict:
    return dict(plan.extra)


def _mesh_of(plan: KernelPlan):
    """Rebuild the jax Mesh a plan's MeshSpec describes (the spec is
    the hashable identity; the Mesh itself is rebuilt from the CURRENT
    device set — if the devices moved the ids won't match and the key
    already missed)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    spec = plan.mesh
    by_id = {d.id: d for d in jax.devices()}
    try:
        devs = [by_id[i] for i in spec.device_ids]
    except KeyError as e:
        raise RuntimeError(
            f"plan {plan.family} names device id {e.args[0]} which is "
            f"not visible — re-plan on the current platform (elastic "
            f"re-shard)") from None
    arr = np.array(devs).reshape(spec.shape)
    return Mesh(arr, spec.axes)


# -- per-family builders ---------------------------------------------------
# Each returns the launch callable for one plan. Thin by design: the
# factories (and their obs.instrument_kernel wrapping) stay in the
# backend modules contracts.json points at; this table only maps a
# family to its factory's argument convention.

def _b_wgl2_single(p):
    return backend_callable("wgl2-single")(p.model, p.geometry)


def _b_wgl2_batch(p):
    return backend_callable("wgl2-batch")(p.model, p.geometry)


def _b_wgl2_chunk(p):
    return backend_callable("wgl2-chunk")(p.model, p.geometry,
                                          **_extra(p))


def _b_wgl2_sort_sharded(p):
    return backend_callable("wgl2-sort-sharded")(p.model, p.geometry,
                                                 _mesh_of(p))


def _b_wgl3_single(p):
    return backend_callable("wgl3-single")(p.model, p.geometry)


def _b_wgl3_batch(p):
    return backend_callable("wgl3-batch")(p.model, p.geometry)


def _b_wgl3_chunk(p):
    return backend_callable("wgl3-chunk")(p.model, p.geometry, p.chunk)


def _b_wgl3_chunk_dedup(p):
    return backend_callable("wgl3-chunk-dedup")(
        p.model, p.geometry, p.chunk, _extra(p)["min_frontier"])


def _b_wgl3_sparse_chunk(p):
    e = _extra(p)
    return backend_callable("wgl3-sparse-chunk")(
        p.model, p.geometry, e["sparse_plan"], p.chunk,
        memo_slots=e.get("memo_slots", 0))


def _b_wgl3_sparse_chunk_dedup(p):
    e = _extra(p)
    return backend_callable("wgl3-sparse-chunk-dedup")(
        p.model, p.geometry, e["sparse_plan"], p.chunk,
        e["min_frontier"], e.get("memo_slots", 0))


def _b_wgl3_dense_sharded(p):
    return backend_callable("wgl3-dense-sharded")(p.model, p.geometry,
                                                  _mesh_of(p))


def _b_wgl3_pallas(p):
    return backend_callable("wgl3-pallas")(p.model, p.geometry)


def _b_wgl3_pallas_grouped(p):
    return backend_callable("wgl3-pallas-grouped")(
        p.model, p.geometry, _extra(p)["group"])


def _b_wgl3_pallas_prep(p):
    return backend_callable("wgl3-pallas-prep")(p.model, p.geometry)


def _b_wgl3_pallas_resumable(p):
    return backend_callable("wgl3-pallas-resumable")(p.model, p.geometry,
                                                     **_extra(p))


def _b_wgl3_pallas_sparse_resumable(p):
    return backend_callable("wgl3-pallas-sparse-resumable")(
        p.model, p.geometry, **_extra(p))


def _b_wgl3_pallas_sharded(p):
    e = _extra(p)
    return backend_callable("wgl3-pallas-sharded")(
        p.model, p.geometry, _mesh_of(p), group=e.get("group", 1))


def _b_wgl3_lattice_chunk(p):
    e = _extra(p)
    return backend_callable("wgl3-lattice-chunk")(
        p.model, p.geometry, _mesh_of(p), axis=e.get("axis", "lattice"),
        plan=e.get("sparse_plan"), canon=p.dedup,
        min_frontier=e.get("min_frontier", 0),
        memo_slots=e.get("memo_slots", 0))


def _b_lattice_transitions(p):
    return backend_callable("lattice-transitions")(p.model, p.geometry)


def _b_wgl3_multislice(p):
    return backend_callable("wgl3-dense-multislice")(p.model, p.geometry,
                                                     _mesh_of(p))


def _b_wgl3_encode(p):
    e = _extra(p)
    return backend_callable("wgl3-encode")(
        e["k_slots"], e["e_cap"], p.n_steps)


def _b_wgl3_encode_sharded(p):
    e = _extra(p)
    return backend_callable("wgl3-encode-sharded")(
        e["k_slots"], e["e_cap"], p.n_steps, _mesh_of(p))


def _b_elle_closure(p):
    return backend_callable("elle-closure")(_extra(p)["n_pad"])


def _b_elle_closure_batch(p):
    e = _extra(p)
    return backend_callable("elle-closure-batch")(e["n_pad"], p.batch)


def _b_elle_tiled(p):
    e = _extra(p)
    return backend_callable("elle-closure-tiled")(e["nb"], e["tile"])


def _b_elle_tiled_pallas(p):
    e = _extra(p)
    return backend_callable("elle-closure-tiled-pallas")(
        e["nb"], e["tile"], e["cap"], e["use_pallas"],
        interpret=e.get("interpret", False))


_BUILDERS = {
    "elle-closure": _b_elle_closure,
    "elle-closure-batch": _b_elle_closure_batch,
    "elle-closure-tiled": _b_elle_tiled,
    "elle-closure-tiled-pallas": _b_elle_tiled_pallas,
    "lattice-transitions": _b_lattice_transitions,
    "wgl2-batch": _b_wgl2_batch,
    "wgl2-chunk": _b_wgl2_chunk,
    "wgl2-single": _b_wgl2_single,
    "wgl2-sort-sharded": _b_wgl2_sort_sharded,
    "wgl3-batch": _b_wgl3_batch,
    "wgl3-chunk": _b_wgl3_chunk,
    "wgl3-chunk-dedup": _b_wgl3_chunk_dedup,
    "wgl3-dense-multislice": _b_wgl3_multislice,
    "wgl3-dense-sharded": _b_wgl3_dense_sharded,
    "wgl3-encode": _b_wgl3_encode,
    "wgl3-encode-sharded": _b_wgl3_encode_sharded,
    "wgl3-lattice-chunk": _b_wgl3_lattice_chunk,
    "wgl3-pallas": _b_wgl3_pallas,
    "wgl3-pallas-grouped": _b_wgl3_pallas_grouped,
    "wgl3-pallas-prep": _b_wgl3_pallas_prep,
    "wgl3-pallas-resumable": _b_wgl3_pallas_resumable,
    "wgl3-pallas-sharded": _b_wgl3_pallas_sharded,
    "wgl3-pallas-sharded-prep": _b_wgl3_pallas_sharded,
    "wgl3-pallas-sparse-resumable": _b_wgl3_pallas_sparse_resumable,
    "wgl3-single": _b_wgl3_single,
    "wgl3-sparse-chunk": _b_wgl3_sparse_chunk,
    "wgl3-sparse-chunk-dedup": _b_wgl3_sparse_chunk_dedup,
}

assert set(_BUILDERS) == set(PLAN_FAMILIES), (
    sorted(set(_BUILDERS) ^ set(PLAN_FAMILIES)))


# -- planners: the routing policy, in ONE copy -----------------------------

def plan_dense_batch(model, cfg, n_steps: Optional[int] = None,
                     batch: Optional[int] = None,
                     mesh: Any = None, shard: bool = True) -> KernelPlan:
    """THE dense batched-launch route (was three copies:
    wgl3_pallas.packed_batch_checker, dense.sharded_packed_batch_checker
    and sched._dense_bucket_launcher): single- vs multi-device by the
    CURRENT platform (or the caller's mesh), pallas vs XLA by the
    per-device shard's envelope, grouped pallas when the shard splits
    into whole groups. The resolved callable takes the stacked
    (slot_tabs, slot_active, targets) arrays and returns DEVICE packed
    i32 rows — i32[B, 6] (wgl3.PACKED_FIELDS_XLA) on the XLA routes,
    i32[B, 5] (wgl3.PACKED_FIELDS) on pallas; wgl3.unpack_np accepts
    both widths.

    Grouped-kernel rationale (measured on v5e, round 4): G histories
    per pallas program amortize per-step instruction overhead — ~48 ms
    device time for the 1024x150-op bench corpus at G=16 vs ~230 ms
    per-history — bit-identical to the per-history kernel. ONLY for
    Sp=8 models: wider states spill Mosaic's scoped VMEM at full group
    size, and the reduced group that fits (G=4 at Sp=32) measured 14%
    SLOWER than per-history. Small batches stay per-history (grouping
    would pad them with dead work), and feasibility is checked for the
    PADDED batch — grouping rounds B up to a G multiple and the
    prefetch envelope is a worker-kill edge."""
    import jax

    from ..ops import wgl3_pallas
    from ..ops.limits import limits

    long_max = limits().long_scan_max
    if n_steps is not None and n_steps > long_max:
        raise ValueError(
            f"n_steps={n_steps} exceeds one scan program "
            f"(long_scan_max={long_max}); use "
            f"check_batch_encoded_auto or wgl3.check_steps3_long")
    mesh_src = "caller" if mesh is not None else "platform"
    if shard and mesh is None and jax.device_count() > 1 \
            and (batch or 0) > 1:
        from ..parallel.dense import batch_mesh

        mesh = batch_mesh()
    prov = {"mesh": mesh_src, "backend": "envelope"}
    if mesh is not None:
        spec = mesh if isinstance(mesh, MeshSpec) else \
            MeshSpec.from_mesh(mesh)
        d = spec.total
        local_batch = None if batch is None else (batch + d - 1) // d
        if wgl3_pallas.use_pallas(cfg, n_steps, local_batch):
            G = limits().pallas_group
            sp = max(8, (cfg.n_states + 7) // 8 * 8)
            if (sp == 8 and G > 1 and local_batch is not None
                    and local_batch >= G and local_batch % G == 0):
                return build_plan(
                    "wgl3-pallas-sharded", model, cfg,
                    label="wgl3-dense-pallas-grouped-sharded",
                    n_steps=n_steps, batch=batch, mesh=spec, group=G,
                    provenance=prov)
            return build_plan(
                "wgl3-pallas-sharded", model, cfg,
                label="wgl3-dense-pallas-sharded", n_steps=n_steps,
                batch=batch, mesh=spec, provenance=prov)
        return build_plan(
            "wgl3-dense-sharded", model, cfg, label="wgl3-dense-sharded",
            n_steps=n_steps, batch=batch, mesh=spec, provenance=prov)
    if wgl3_pallas.use_pallas(cfg, n_steps, batch):
        G = limits().pallas_group
        sp = max(8, (cfg.n_states + 7) // 8 * 8)
        b_pad = None if batch is None else (batch + G - 1) // G * G
        if (sp == 8 and G > 1 and batch is not None and batch >= G
                and wgl3_pallas.pallas_feasible(cfg, n_steps, b_pad)):
            return build_plan("wgl3-pallas-grouped", model, cfg,
                              label="wgl3-dense-pallas-grouped",
                              n_steps=n_steps, batch=batch, group=G,
                              provenance=prov)
        return build_plan("wgl3-pallas", model, cfg,
                          label="wgl3-dense-pallas", n_steps=n_steps,
                          batch=batch, provenance=prov)
    return build_plan("wgl3-batch", model, cfg, label="wgl3-dense",
                      n_steps=n_steps, batch=batch, provenance=prov)


def launch_multiple(model, cfg, n_steps: Optional[int] = None,
                    batch: Optional[int] = None, mesh: Any = None) -> int:
    """The [B]-axis padding multiple a plan_dense_batch launch of this
    shape needs (sched pads buckets to it BEFORE planning — the bucket
    can inflate a 1-history part onto the sharded route)."""
    import jax

    if mesh is None:
        if jax.device_count() <= 1 or (batch or 0) <= 1:
            return 1
        from ..parallel.dense import batch_mesh

        mesh = batch_mesh()
    from ..parallel.dense import batch_multiple

    return batch_multiple(model, cfg, mesh, n_steps=n_steps, batch=batch)


def plan_long_sweep(model, cfg, lattice_mesh: Any = None,
                    chunk: Optional[int] = None) -> KernelPlan:
    """The host-chunked long-sweep family for this geometry on this
    platform: the lattice-sharded chunk kernel when a mesh is given
    (the caller derived a lattice-feasible cfg), else the fused pallas
    resumable windows when the envelope allows, else the XLA chunk fn —
    with the sparse active-tile engine and the frontier-dedup pass
    reflected in the family exactly as the sweep will engage them. The
    plan is DESCRIPTIVE for the host loop (dispatch_long drives the
    loop); its key is what the loop's chunk kernels resolve under."""
    from ..ops import wgl3, wgl3_pallas
    from ..ops.wgl3_sparse import memo_slots_for, sparse_plan

    prov = {"backend": "envelope"}
    if lattice_mesh is not None:
        from ..parallel.lattice import lattice_sparse_plan
        from ..parallel.mesh import mesh_total

        d = mesh_total(lattice_mesh)
        sp = lattice_sparse_plan(cfg, d)
        return build_plan(
            "wgl3-lattice-chunk", model, cfg,
            label=("wgl3-dense-lattice-sparse" if sp is not None
                   else "wgl3-dense-lattice-sharded"),
            chunk=chunk, mesh=lattice_mesh, sparse=sp is not None,
            sparse_plan=sp, provenance=prov | {"mesh": "lattice"})
    if wgl3_pallas.use_pallas(cfg):
        if wgl3_pallas.pallas_sparse_selected(cfg):
            return build_plan("wgl3-pallas-sparse-resumable", model, cfg,
                              label="wgl3-dense-pallas-sparse-chunked",
                              chunk=chunk, sparse=True, provenance=prov)
        return build_plan("wgl3-pallas-resumable", model, cfg,
                          label="wgl3-dense-pallas-chunked", chunk=chunk,
                          provenance=prov)
    sp = sparse_plan(cfg)
    if sp is not None:
        return build_plan("wgl3-sparse-chunk", model, cfg,
                          label="wgl3-dense-sparse-chunked", chunk=chunk,
                          sparse=True, sparse_plan=sp,
                          memo_slots=memo_slots_for(sp), provenance=prov)
    if _table_dedup_possible():
        # Family only — whether a given HISTORY carries symmetry (and
        # thus takes the dedup twin) is per-call; the host loop decides
        # per history exactly as before.
        return build_plan("wgl3-chunk-dedup", model, cfg,
                          label="wgl3-dense-chunked", chunk=chunk,
                          dedup=True,
                          min_frontier=wgl3.dedup_min_frontier_active(),
                          provenance=prov)
    return build_plan("wgl3-chunk", model, cfg, label="wgl3-dense-chunked",
                      chunk=chunk, provenance=prov)


def _table_dedup_possible() -> bool:
    from ..ops.limits import limits

    return limits().dedup_mode == 2


def dispatch_long(rs, model, cfg, lattice_mesh: Any = None,
                  chunk: Optional[int] = None,
                  time_budget_s: Optional[float] = None) -> dict:
    """Run one long (host-chunked) dense sweep under the planned
    family. This is the one copy of the lattice / pallas / XLA ladder
    that run_long_dense and check_encoded_general each used to carry;
    result schema is the chunked sweep's, with the plan's family
    stamped as `plan_family`."""
    plan = plan_long_sweep(model, cfg, lattice_mesh=lattice_mesh,
                           chunk=chunk)
    if plan.family == "wgl3-lattice-chunk":
        from ..parallel.lattice import check_steps_lattice_long

        out = check_steps_lattice_long(rs, model, cfg, mesh=lattice_mesh,
                                       chunk=chunk,
                                       time_budget_s=time_budget_s)
    elif plan.family in ("wgl3-pallas-resumable",
                         "wgl3-pallas-sparse-resumable"):
        from ..ops.wgl3_pallas import check_steps3_long_pallas

        out = check_steps3_long_pallas(rs, model, cfg,
                                       time_budget_s=time_budget_s)
    else:
        from ..ops.wgl3 import check_steps3_long

        out = check_steps3_long(rs, model, cfg, chunk=chunk,
                                time_budget_s=time_budget_s)
    out.setdefault("kernel", plan.label)
    out["plan_family"] = plan.family
    return out


def plan_stream_chunk(model, cfg, chunk: int) -> KernelPlan:
    """The streaming engine's resumable chunk kernel: ALWAYS the plain
    (no-canonicalization) wgl3 chunk fn — a live stream cannot know
    which pending ops never return (ops/canon.py), and post-hoc sweeps
    of short histories are canon-free too, so streamed and post-hoc
    metrics stay bit-identical."""
    return build_plan("wgl3-chunk", model, cfg,
                      label="wgl3-dense-stream-chunked", chunk=chunk,
                      provenance={"backend": "stream"})


def plan_resumable(model, cfg, canon: bool = False) -> KernelPlan:
    """The wgl2 sort-ladder resumable chunk kernel; `canon` selects the
    frontier-canonicalizing twin (ops/canon.py — the sort ladder is
    where dedup pays, so AUTO mode engages it per history)."""
    extra = {"canon": True} if canon else {}
    return build_plan("wgl2-chunk", model, cfg, label="wgl2-sort-resumable",
                      dedup=canon, provenance={"backend": "sort-ladder"},
                      **extra)


def plan_elle_batch(n_pad: int, batch: int) -> KernelPlan:
    """One bucketed corpus-of-graphs closure launch (ops/cycles.py)."""
    return build_plan("elle-closure-batch", batch=batch, n_pad=n_pad,
                      label="elle-closure-batch",
                      provenance={"backend": "elle"})


def plan_elle_single(n_pad: int) -> KernelPlan:
    """One single-graph dense closure launch (ops/cycles.py)."""
    return build_plan("elle-closure", n_pad=n_pad, label="elle-closure",
                      provenance={"backend": "elle"})


def plan_device_encode(k_slots: int, e_cap: int, r_cap: int,
                       batch: Optional[int] = None,
                       mesh: Any = None) -> KernelPlan:
    """One device-side history-encode launch (ops/encode_device.py):
    events[(B,) e_cap, 6] -> the return-major slot-table arrays the
    dense checkers consume, built on-device. Sharded over the batch
    mesh when the caller passes one (parallel/dense.py — each shard
    expands its own histories; only the compact event stream crosses
    the H2D boundary), single-device otherwise."""
    prov = {"backend": "device-encode"}
    if mesh is not None:
        spec = mesh if isinstance(mesh, MeshSpec) else \
            MeshSpec.from_mesh(mesh)
        return build_plan("wgl3-encode-sharded",
                          label="wgl3-encode-sharded", n_steps=r_cap,
                          batch=batch, mesh=spec, k_slots=k_slots,
                          e_cap=e_cap, provenance=prov | {"mesh": "caller"})
    return build_plan("wgl3-encode", label="wgl3-encode", n_steps=r_cap,
                      batch=batch, k_slots=k_slots, e_cap=e_cap,
                      provenance=prov)


class LaunchPipeline:
    """Depth-bounded in-flight launch window for bucketed corpus
    dispatch — the ``wgl3.check_steps3_long`` double-buffering
    discipline lifted to WHOLE launches. The caller stages + dispatches
    launch N+1 (async: host prep and the H2D enqueue overlap launch N's
    device execute) and push()es an entry per launch; once
    ``limits().pod_pipeline_depth`` launches are in flight, submit()
    resolves (fetches) the OLDEST entry before admitting the new one,
    so undrained device results stay bounded and fetch round trips hide
    under real device work instead of stalling the tail.

    depth=1 restores the fetch-after-every-launch synchronous loop; a
    depth at or beyond the launch count reproduces the old unbounded
    dispatch-all-then-drain behaviour. Ordering and results are
    bit-identical at any depth — the window only reorders WHEN fetches
    happen, never what was launched.

    ``rollback()`` is the mid-pipeline falsification escape hatch: it
    discards every speculative in-flight entry WITHOUT resolving it
    (speculated launches were wasted device work, not wrong answers)
    and marks the pipeline aborted so a fail-fast caller stops
    submitting (tests/test_pod_scaling.py pins depth bounding and
    rollback)."""

    def __init__(self, depth: Optional[int] = None, resolve=None):
        from ..ops.limits import limits
        from ..sched.pipeline import InflightWindow

        if depth is None:
            depth = limits().pod_pipeline_depth
        self._win = InflightWindow(depth)
        self._resolve = resolve
        self._aborted = False
        self.dispatched = 0
        self.rolled_back = 0

    @property
    def depth(self) -> int:
        return self._win.depth

    @property
    def aborted(self) -> bool:
        return self._aborted

    def __len__(self) -> int:
        return len(self._win)

    def _resolve_one(self):
        entry = self._win.pop()
        return self._resolve(entry) if self._resolve is not None else entry

    def submit(self, entry) -> list:
        """Admit one dispatched launch; returns the resolved entries the
        window had to retire to make room (possibly none)."""
        if self._aborted:
            raise RuntimeError("submit after rollback")
        drained = []
        while self._win.full():
            drained.append(self._resolve_one())
        self._win.push(entry)
        self.dispatched += 1
        return drained

    def drain(self) -> list:
        """Resolve every remaining in-flight entry, oldest first."""
        out = []
        while self._win:
            out.append(self._resolve_one())
        return out

    def rollback(self) -> int:
        """Discard the speculative window (mid-pipeline falsification):
        in-flight entries are dropped unresolved, the pipeline refuses
        further submits. Returns the number of launches discarded."""
        n = len(self._win)
        self._win.clear()
        self._aborted = True
        self.rolled_back += n
        return n
