"""Cycle detection over dependency graphs — the MXU path for elle.

The reference ships elle 0.1.2 in its dependency tree (jepsen.etcdemo.iml:46,
reached transitively through jepsen.checker; SURVEY.md §2.2): a
transactional anomaly checker whose core is finding cycles in a
transaction dependency graph. This module is the TPU-native compute core
for that capability, grown from the seed's single dense kernel into the
routed engine the WGL stack already has (ISSUE 11):

  * **Dense squaring** (small graphs): the graph lives as a dense
    boolean adjacency matrix and reachability is computed by REPEATED
    MATRIX SQUARING — O(log N) [N, N] matmuls, exactly MXU food (f32
    matmuls on 128-aligned tiles), instead of elle's JVM depth-first
    search. Since ISSUE 11 the squaring loop carries a fixpoint early
    exit (short-diameter graphs converge in a couple of rounds) and the
    per-size jitted wrappers live in the sched kernel LRU
    (sched/compile_cache.py) with hit accounting instead of an
    unbounded functools.lru_cache.

        R_1 = A                      (paths of length 1)
        R_{2k} = R_k | R_k @ R_k     (paths of length <= 2k, >= 1 edge)
        node i lies on a cycle  <=>  R⁺[i, i]

  * **Batched corpus-of-graphs closure** (reach_and_cycles_batch /
    cycle_masks_batch): many graphs grouped into {2^k, 1.5*2^k}
    padded-size buckets, each bucket's batch axis bucketed too
    (limits().elle_batch_floor) and closed in ONE vmapped launch — the
    sched/ bucket discipline applied to dependency graphs, so the
    classification ladder and component fan-out below check hundreds of
    graphs per launch instead of one kernel call each.

  * **Component routing** (cycle_mask): a big sparse dependency graph
    decomposes into weak components (host union-find, O(E α));
    components are closed independently — small ones batched, large
    ones through the blocked/tiled work-list kernel
    (ops/cycles_tiled.py), and components whose padded f32 matrix would
    exceed limits().elle_cell_budget fall back to the exact host
    Tarjan/SCC oracle. Routing is driven by limits().elle_mode /
    elle_dense_max_nodes — verdicts are route-independent because the
    closure fixpoint is unique (differential-tested against the Tarjan
    oracle in tests/test_elle_kernels.py).

Cycle-presence probes (`has_cycle` / `cycle_mask`) fetch ONLY the
diagonal — O(N) bytes — never the [N, N+1] reach slab (ISSUE 11
satellite); `reach_and_cycles` keeps the single packed fetch for
callers that need the closure itself (witness extraction). The
pure-Python Tarjan SCC oracle used by the differential tests lives in
checkers/elle.py.
"""

from __future__ import annotations

import numpy as np

from .. import obs
from ..obs import instrument_kernel
from .limits import limits

# Kernel names (obs attribution, contracts.json kernel family).
DENSE_KERNEL = "elle-closure"
BATCH_KERNEL = "elle-closure-batch"


def _pad_to(n: int, mult: int = 128) -> int:
    return max(mult, (n + mult - 1) // mult * mult)


def _bucket(n: int, floor: int) -> int:
    """{2^k, 1.5*2^k} growth from `floor` — the sched/engine.py bucket
    ladder, local so graph bucketing never drags the wgl3 import in."""
    r = max(1, floor)
    while r < n:
        if r + r // 2 >= n:
            return r + r // 2
        r *= 2
    return r


def _kernel_cache():
    from ..sched.compile_cache import kernel_cache

    return kernel_cache()


def _closure_steps(n_pad: int) -> int:
    # ceil(log2(n_pad)) squarings bound the longest simple path.
    return max(1, int(np.ceil(np.log2(n_pad))))


def _closure_body(n_pad: int):
    """The shared squaring loop: adj f32[n_pad, n_pad] (0/1) ->
    (packed f32[n_pad, n_pad+1] — reach plus the cycle column, one
    fetchable slab — cycle_mask bool[n_pad], rounds i32). Boolean
    semiring via f32 matmul + threshold: the matmul is the MXU op; the
    clamp keeps entries in {0, 1} so values never overflow f32
    exactness (n_pad < 2^24). The while_loop exits as soon as a round
    changes nothing — the fixpoint early exit short-diameter graphs
    (and the streaming engine's warm-started re-checks) convert into
    skipped matmuls."""
    import jax
    import jax.numpy as jnp

    steps = _closure_steps(n_pad)

    def closure(adj):
        def cond(st):
            i, _, changed = st
            return changed & (i < steps)

        def body(st):
            i, r, _ = st
            r2 = jnp.minimum(r + r @ r, 1.0)
            return i + 1, r2, jnp.any(r2 != r)

        rounds, r, _ = jax.lax.while_loop(
            cond, body, (jnp.int32(0), adj, jnp.bool_(True)))
        cyc = jnp.diagonal(r) > 0.5
        packed = jnp.concatenate([r, cyc[:, None].astype(jnp.float32)],
                                 axis=1)
        return packed, cyc, rounds

    return closure


def _closure_fn(n_pad: int):
    """The jitted single-graph closure for one padded size, resolved
    through the sched kernel LRU (bounded by
    limits().kernel_cache_entries, hit/miss accounted) — the seed's
    `functools.lru_cache(maxsize=None)` was the one kernel cache in the
    tree that ignored the cache-entry limit (jtlint JTL105 notes the
    lru IS the cache; ISSUE 11 satellite)."""
    import jax

    def build():
        return instrument_kernel("elle-closure",
                                 jax.jit(_closure_body(n_pad)))

    return _kernel_cache().get((DENSE_KERNEL, n_pad), build)


def _batch_closure_fn(n_pad: int, batch: int):
    """The vmapped corpus-of-graphs closure for one (padded size,
    batch-bucket) shape — same math per graph, one launch per bucket.
    Under vmap the fixpoint while_loop runs until the SLOWEST graph in
    the batch converges (converged lanes ride along as no-ops)."""
    import jax

    def build():
        return instrument_kernel(
            "elle-closure-batch", jax.jit(jax.vmap(_closure_body(n_pad))))

    return _kernel_cache().get((BATCH_KERNEL, n_pad, batch), build)


def _planned_closure(n_pad: int):
    """The single-graph closure, resolved through the KernelPlan layer
    (plan/dispatch.py — the elle lane's entry onto the one plan spine;
    the plan key carries the padded size, so bucketed shapes keep their
    own LRU entries)."""
    from ..plan import plan_elle_single, resolve

    return resolve(plan_elle_single(n_pad))


def _planned_batch_closure(n_pad: int, batch: int):
    """The vmapped corpus-of-graphs closure, through the plan layer
    (family elle-closure-batch)."""
    from ..plan import plan_elle_batch, resolve

    return resolve(plan_elle_batch(n_pad, batch))


def _pad_graph(adj: np.ndarray, n_pad: int) -> np.ndarray:
    n = adj.shape[0]
    a = np.zeros((n_pad, n_pad), np.float32)
    a[:n, :n] = adj.astype(np.float32)
    return a


def _route(route: str | None = None) -> str:
    if route is not None:
        return route
    return {0: "auto", 1: "dense", 2: "tiled"}[limits().elle_mode]


def _cells_ok(n_pad: int) -> bool:
    return n_pad * n_pad <= limits().elle_cell_budget


def reach_and_cycles(adj: np.ndarray, route: str | None = None
                     ) -> tuple[np.ndarray, np.ndarray]:
    """adj: bool[N, N] (edge i->j). Returns (reach_plus bool[N, N] —
    paths with >= 1 edge — and cycle_mask bool[N]), both host numpy,
    via one device computation + one packed fetch. Routed by
    limits().elle_mode (or the explicit `route` override): "dense" is
    the seed squaring kernel, "tiled" the blocked work-list kernel
    (ops/cycles_tiled.py), "auto" picks by elle_dense_max_nodes. A
    graph whose padded matrix exceeds elle_cell_budget falls back to
    the exact host closure (no device allocation)."""
    import jax.numpy as jnp

    n = adj.shape[0]
    if n == 0:
        return np.zeros((0, 0), bool), np.zeros((0,), bool)
    r = _route(route)
    n_pad = _pad_to(n)
    if not _cells_ok(n_pad):
        obs.get_metrics().counter("elle.graphs_oracle").add(1)
        return _host_reach_and_cycles(adj)
    if r == "tiled" or (r == "auto" and n > limits().elle_dense_max_nodes):
        from . import cycles_tiled

        return cycles_tiled.reach_and_cycles_tiled(adj)
    m = obs.get_metrics()
    m.counter("elle.graphs_dense").add(1)
    m.counter("elle.closure_launches").add(1)
    packed, _cyc, _rounds = _planned_closure(n_pad)(
        jnp.asarray(_pad_graph(adj, n_pad)))
    # Single packed fetch: [N, N+1] slab (reach plus the cycle column).
    out = np.asarray(packed)[:n]
    return out[:, :n] > 0.5, out[:, n_pad] > 0.5


def cycle_mask(adj: np.ndarray, route: str | None = None) -> np.ndarray:
    """bool[N] — which nodes lie on a cycle. The cycle-presence probe:
    fetches ONLY the diagonal column (O(N) bytes), never the O(N^2)
    reach slab, and on the auto route decomposes big sparse graphs into
    weak components checked batched (small) / tiled (large) / host SCC
    (over elle_cell_budget)."""
    import jax.numpy as jnp

    n = adj.shape[0]
    if n == 0:
        return np.zeros((0,), bool)
    r = _route(route)
    n_pad = _pad_to(n)
    if r == "dense" or (r == "auto"
                        and n <= limits().elle_dense_max_nodes):
        if not _cells_ok(n_pad):
            obs.get_metrics().counter("elle.graphs_oracle").add(1)
            return _host_cycle_mask(adj)
        m = obs.get_metrics()
        m.counter("elle.graphs_dense").add(1)
        m.counter("elle.closure_launches").add(1)
        _packed, cyc, _rounds = _planned_closure(n_pad)(
            jnp.asarray(_pad_graph(adj, n_pad)))
        return np.asarray(cyc)[:n]
    if r == "tiled":
        from . import cycles_tiled

        # Budget the padded size the tiled kernel ACTUALLY allocates
        # (the 128-rounded tile, not the raw knob value).
        if not _cells_ok(_pad_to(n, cycles_tiled._tile())):
            obs.get_metrics().counter("elle.graphs_oracle").add(1)
            return _host_cycle_mask(adj)
        return cycles_tiled.cycle_mask_tiled(adj)
    # Auto route, big graph: weak-component decomposition.
    return _cycle_mask_decomposed(adj)


def _cycle_mask_decomposed(adj: np.ndarray) -> np.ndarray:
    """Weak components closed independently: singletons host-checked
    (cycle iff self-edge), small components batched through the
    vmapped bucketed kernel, big ones through the tiled kernel (or the
    host oracle past the cell budget). Exact: a cycle never spans two
    weak components."""
    n = adj.shape[0]
    out = np.zeros((n,), bool)
    comps = weak_components(adj)
    small: list[np.ndarray] = []
    small_idx: list[np.ndarray] = []
    dense_max = limits().elle_dense_max_nodes
    for comp in comps:
        if comp.size == 1:
            out[comp[0]] = bool(adj[comp[0], comp[0]])
            continue
        sub = adj[np.ix_(comp, comp)]
        if comp.size <= dense_max:
            small.append(sub)
            small_idx.append(comp)
            continue
        from . import cycles_tiled

        if not _cells_ok(_pad_to(comp.size, cycles_tiled._tile())):
            obs.get_metrics().counter("elle.graphs_oracle").add(1)
            out[comp] = _host_cycle_mask(sub)
            continue
        out[comp] = cycles_tiled.cycle_mask_tiled(sub)
    if small:
        for comp, cyc in zip(small_idx, cycle_masks_batch(small)):
            out[comp] = cyc
    return out


def has_cycle(adj: np.ndarray) -> bool:
    """Cycle-presence probe: moves O(N) bytes (the diagonal mask), not
    the O(N^2) reach slab (ISSUE 11 satellite)."""
    return bool(cycle_mask(adj).any())


# -- batched corpus-of-graphs closure ---------------------------------------

def _batched_launches(adjs: dict):
    """Group graphs ({index: adj}, pre-filtered to the cell budget by
    _batch_partition) into {2^k, 1.5*2^k} padded-size buckets, bucket
    each group's batch axis from limits().elle_batch_floor, and chunk
    launches under the stacked-element budget. Yields
    (indices, n_pad, batch, stacked f32[b, n_pad, n_pad])."""
    lim = limits()
    buckets: dict[int, list[int]] = {}
    for i, a in adjs.items():
        n_pad = _bucket(_pad_to(a.shape[0]), floor=128)
        buckets.setdefault(n_pad, []).append(i)
    for n_pad in sorted(buckets):
        idxs = buckets[n_pad]
        per_graph = n_pad * n_pad
        chunk = max(1, lim.stack_element_budget // per_graph)
        for c0 in range(0, len(idxs), chunk):
            part = idxs[c0:c0 + chunk]
            b = min(_bucket(len(part), floor=lim.elle_batch_floor), chunk)
            b = max(b, len(part))
            stacked = np.zeros((b, n_pad, n_pad), np.float32)
            for j, i in enumerate(part):
                a = adjs[i]
                stacked[j, :a.shape[0], :a.shape[0]] = a
            yield part, n_pad, b, stacked


def batchable(n: int) -> bool:
    """True when same-size ladder graphs should close in ONE vmapped
    batch launch: auto/dense routes, inside the dense crossover and the
    cell budget. Past any of those, callers route each graph through
    cycle_mask individually (decomposition / tiled / host oracle) —
    stacking full-size copies of a big graph is exactly the allocation
    the budget exists to prevent."""
    return (_route() != "tiled" and n <= limits().elle_dense_max_nodes
            and _cells_ok(_bucket(_pad_to(n), floor=128)))


def _batch_partition(adjs):
    """(batchable indices, over-budget indices): a graph whose padded
    BUCKET would exceed elle_cell_budget never stacks — it takes the
    host oracle instead (the batch allocation is b * n_pad^2, so the
    budget applies per graph at bucket granularity)."""
    ok, over = [], []
    for i, a in enumerate(adjs):
        n_pad = _bucket(_pad_to(a.shape[0]), floor=128)
        (ok if _cells_ok(n_pad) else over).append(i)
    return ok, over


def cycle_masks_batch(adjs) -> list[np.ndarray]:
    """Per-graph cycle masks for a corpus of graphs — bucketed vmapped
    launches, diagonal-only fetches. Returns a list aligned with
    `adjs` (bool[N_i] each). Graphs past elle_cell_budget fall back to
    the host Tarjan oracle instead of stacking."""
    import jax.numpy as jnp

    out: list = [None] * len(adjs)
    m = obs.get_metrics()
    ok, over = _batch_partition(adjs)
    for i in over:
        m.counter("elle.graphs_oracle").add(1)
        out[i] = _host_cycle_mask(adjs[i])
    adjs = {i: adjs[i] for i in ok}
    for part, n_pad, b, stacked in _batched_launches(adjs):
        _packed, cyc, _rounds = _planned_batch_closure(n_pad, b)(
            jnp.asarray(stacked))
        m.counter("elle.graphs_batched").add(len(part))
        m.counter("elle.closure_launches").add(1)
        m.gauge("elle.batch_fill").set(len(part) / b)
        fetched = np.asarray(cyc)
        for j, i in enumerate(part):
            out[i] = fetched[j, :adjs[i].shape[0]]
    return out


def reach_and_cycles_batch(adjs) -> list[tuple[np.ndarray, np.ndarray]]:
    """(reach, cycle_mask) per graph for a corpus of graphs — the same
    bucketed vmapped launches, one packed slab fetch per launch.
    Returns a list aligned with `adjs`; over-budget graphs take the
    host closure."""
    import jax.numpy as jnp

    out: list = [None] * len(adjs)
    m = obs.get_metrics()
    ok, over = _batch_partition(adjs)
    for i in over:
        m.counter("elle.graphs_oracle").add(1)
        out[i] = _host_reach_and_cycles(adjs[i])
    adjs = {i: adjs[i] for i in ok}
    for part, n_pad, b, stacked in _batched_launches(adjs):
        packed, _cyc, _rounds = _planned_batch_closure(n_pad, b)(
            jnp.asarray(stacked))
        m.counter("elle.graphs_batched").add(len(part))
        m.counter("elle.closure_launches").add(1)
        m.gauge("elle.batch_fill").set(len(part) / b)
        fetched = np.asarray(packed)
        for j, i in enumerate(part):
            n = adjs[i].shape[0]
            out[i] = (fetched[j, :n, :n] > 0.5,
                      fetched[j, :n, n_pad] > 0.5)
    return out


# -- component decomposition ------------------------------------------------

def weak_components(adj: np.ndarray) -> list[np.ndarray]:
    """Weakly-connected components of the digraph (host union-find with
    path halving over the edge list, O(E α)). Returns index arrays,
    each sorted ascending, ordered by their smallest node — a pure
    function of the graph, so routing through components is
    deterministic."""
    n = adj.shape[0]
    parent = np.arange(n, dtype=np.intp)

    def find(x: int) -> int:
        while parent[x] != x:
            parent[x] = parent[parent[x]]   # path halving
            x = parent[x]
        return x

    for a, b in zip(*np.nonzero(adj)):
        ra, rb = find(int(a)), find(int(b))
        if ra != rb:
            parent[max(ra, rb)] = min(ra, rb)
    roots: dict[int, list[int]] = {}
    for i in range(n):
        roots.setdefault(find(i), []).append(i)
    return [np.asarray(v, dtype=np.intp)
            for _, v in sorted(roots.items())]


def cycle_mask_stream(n: int, edge_chunks,
                      tag: str = "elle-stream") -> np.ndarray:
    """bool[n] cycle mask from a STREAM of (src, dst) edge chunks — the
    out-of-core elle route (ISSUE 20): the [N, N] adjacency never
    materializes. Pass 1 streams the chunks through the host union-find
    (O(N) state), spilling the deduped edge runs to the active spill
    tier (store/spill.py) once their bytes outgrow the host RSS budget
    (below it, or without an active tier, the runs stay in RAM — same
    code path, same verdicts). Pass 2 re-streams the runs, binning each
    edge by its weak-component root into bounded bucket spools; pass 3
    loads one bucket at a time and closes each component through the
    SAME ladder as cycle_mask (batched vmapped / tiled / host oracle),
    so peak host memory is O(N) + one bucket + one component — never
    O(E) or O(N^2). Exact: a cycle never spans two weak components, and
    self-loops (dropped from the runs — they add no cross-node paths)
    are OR-ed into the mask directly."""
    from ..store import spill as _spill

    out = np.zeros((n,), bool)
    if n == 0:
        return out
    parent = np.arange(n, dtype=np.int64)

    def find(x: int) -> int:
        while parent[x] != x:
            parent[x] = parent[parent[x]]   # path halving
            x = parent[x]
        return x

    sdir = _spill.active_spill()
    runs: list[str] = []          # spilled run names, in stream order
    ram: list[np.ndarray] = []    # RAM-resident runs (pre-spill window)
    ram_bytes = 0
    self_edge = np.zeros((n,), bool)
    spilled = False
    scratch: list[str] = []       # every spool to clean up at the end
    try:
        for chunk in edge_chunks:
            arr = np.asarray(chunk, dtype=np.int64).reshape(-1, 2)
            if arr.size == 0:
                continue
            arr = np.unique(arr, axis=0)
            loop = arr[:, 0] == arr[:, 1]
            if loop.any():
                self_edge[arr[loop, 0]] = True
                arr = arr[~loop]
            if arr.size == 0:
                continue
            for a, b in arr:
                ra, rb = find(int(a)), find(int(b))
                if ra != rb:
                    parent[max(ra, rb)] = min(ra, rb)
            if sdir is not None and not spilled:
                est_mb = (ram_bytes + arr.nbytes) / (1 << 20)
                spilled = _spill.spill_active(est_mb)
                if spilled:       # flush the RAM window to disk runs
                    for r in ram:
                        name = f"{tag}.run{len(runs)}"
                        if sdir.write(name, r.tobytes()) is None:
                            raise RuntimeError(
                                "elle stream: edge-run spill failed "
                                f"({name})")
                        runs.append(name)
                        scratch.append(name)
                    ram, ram_bytes = [], 0
            if spilled:
                name = f"{tag}.run{len(runs)}"
                if sdir.write(name, arr.tobytes()) is None:
                    raise RuntimeError(
                        f"elle stream: edge-run spill failed ({name})")
                runs.append(name)
                scratch.append(name)
            else:
                ram.append(arr)
                ram_bytes += arr.nbytes
        # Flatten to component roots (vectorized pointer jumping).
        while True:
            p2 = parent[parent]
            if np.array_equal(p2, parent):
                break
            parent = p2
        root_of = parent

        def _iter_runs():
            for r in ram:
                yield r
            for name in runs:
                blob = sdir.read(name)
                if blob is None:
                    # Unlike a torn CHECKPOINT (recompute), a vanished
                    # edge run would silently change the graph — fail.
                    raise RuntimeError(
                        f"elle stream: edge run vanished ({name})")
                yield np.frombuffer(blob, dtype=np.int64).reshape(-1, 2)

        # Pass 2: bin edges by component root into bounded buckets.
        n_buckets = 64 if spilled else 1
        bucket_ram: dict[int, list[np.ndarray]] = {}
        bucket_used: set[int] = set()
        for arr in _iter_runs():
            bkt = (root_of[arr[:, 0]] % n_buckets).astype(np.int64)
            for b in np.unique(bkt):
                part = arr[bkt == b]
                b = int(b)
                bucket_used.add(b)
                if spilled:
                    name = f"{tag}.bkt{b}"
                    if b not in bucket_ram:
                        bucket_ram[b] = []      # marks spool created
                        scratch.append(name)
                    if not sdir.append(name, part.tobytes()):
                        raise RuntimeError(
                            f"elle stream: bucket spill failed ({name})")
                else:
                    bucket_ram.setdefault(b, []).append(part)
        ram = []   # runs consumed; drop the RAM window before closing
        # Pass 3: close one bucket at a time, one component at a time.
        dense_max = limits().elle_dense_max_nodes
        for b in sorted(bucket_used):
            if spilled:
                blob = sdir.read(f"{tag}.bkt{b}")
                if blob is None:
                    raise RuntimeError(
                        f"elle stream: bucket vanished ({tag}.bkt{b})")
                arr = np.frombuffer(blob, dtype=np.int64).reshape(-1, 2)
            else:
                arr = np.concatenate(bucket_ram.pop(b))
            roots = root_of[arr[:, 0]]
            order = np.argsort(roots, kind="stable")
            arr, roots = arr[order], roots[order]
            cuts = np.flatnonzero(np.diff(roots)) + 1
            small: list[np.ndarray] = []
            small_nodes: list[np.ndarray] = []
            for comp_edges in np.split(arr, cuts):
                nodes = np.unique(comp_edges)
                m = nodes.size
                sub = np.zeros((m, m), bool)
                sub[np.searchsorted(nodes, comp_edges[:, 0]),
                    np.searchsorted(nodes, comp_edges[:, 1])] = True
                if m <= dense_max:
                    small.append(sub)
                    small_nodes.append(nodes)
                    continue
                from . import cycles_tiled

                if not _cells_ok(_pad_to(m, cycles_tiled._tile())):
                    obs.get_metrics().counter("elle.graphs_oracle").add(1)
                    out[nodes] = _host_cycle_mask(sub)
                else:
                    out[nodes] = cycles_tiled.cycle_mask_tiled(sub)
            if small:
                for nodes, cyc in zip(small_nodes,
                                      cycle_masks_batch(small)):
                    out[nodes] = cyc
    finally:
        if sdir is not None:
            for name in scratch:
                sdir.delete(name)
    out[self_edge] = True
    return out


def reach_pairs(adj: np.ndarray, pairs) -> np.ndarray:
    """Reachability answers for specific (src, dst) queries without
    materializing the full closure: pairs in different weak components
    are unreachable for free; components with queries are closed once
    each (dense / tiled / host by the same routing as cycle_mask) and
    looked up. Returns bool[len(pairs)]."""
    pairs = list(pairs)
    out = np.zeros((len(pairs),), bool)
    if not pairs:
        return out
    n = adj.shape[0]
    if n <= limits().elle_dense_max_nodes and _route() != "tiled":
        reach, _ = reach_and_cycles(adj)
        for i, (s, d) in enumerate(pairs):
            out[i] = reach[s, d]
        return out
    comps = weak_components(adj)
    label = np.zeros((n,), np.intp)
    for ci, comp in enumerate(comps):
        label[comp] = ci
    by_comp: dict[int, list[int]] = {}
    for i, (s, d) in enumerate(pairs):
        if label[s] != label[d]:
            continue                      # cross-component: unreachable
        by_comp.setdefault(int(label[s]), []).append(i)
    for ci, idxs in sorted(by_comp.items()):
        comp = comps[ci]
        pos = {int(v): j for j, v in enumerate(comp)}
        sub = adj[np.ix_(comp, comp)]
        reach, _ = reach_and_cycles(sub)
        for i in idxs:
            s, d = pairs[i]
            out[i] = reach[pos[int(s)], pos[int(d)]]
    return out


# -- host fallbacks (over-budget graphs; exact by construction) -------------

def _host_cycle_mask(adj: np.ndarray) -> np.ndarray:
    """Exact host cycle mask via iterative Tarjan SCC: a node lies on a
    cycle iff its SCC has >= 2 nodes or it has a self-edge. The
    over-budget fallback route — O(N + E), no device allocation."""
    n = adj.shape[0]
    succ = [np.flatnonzero(adj[i]) for i in range(n)]
    index = np.full(n, -1, np.intp)
    low = np.zeros(n, np.intp)
    on_stack = np.zeros(n, bool)
    stack: list[int] = []
    out = np.zeros(n, bool)
    counter = 0
    for root in range(n):
        if index[root] != -1:
            continue
        work = [(root, 0)]
        while work:
            v, pi = work[-1]
            if pi == 0:
                index[v] = low[v] = counter
                counter += 1
                stack.append(v)
                on_stack[v] = True
            advanced = False
            for j in range(pi, len(succ[v])):
                w = int(succ[v][j])
                if index[w] == -1:
                    work[-1] = (v, j + 1)
                    work.append((w, 0))
                    advanced = True
                    break
                if on_stack[w]:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack[w] = False
                    scc.append(w)
                    if w == v:
                        break
                if len(scc) > 1:
                    out[scc] = True
                elif adj[v, v]:
                    out[v] = True
    return out


def _host_reach_and_cycles(adj: np.ndarray
                           ) -> tuple[np.ndarray, np.ndarray]:
    """Exact host closure (per-node BFS over the adjacency) for graphs
    past the device cell budget — the reach-needing fallback, O(N * E)
    worst case; callers that only need cycle presence take
    _host_cycle_mask instead."""
    from collections import deque

    n = adj.shape[0]
    succ = [np.flatnonzero(adj[i]) for i in range(n)]
    reach = np.zeros((n, n), bool)
    for s in range(n):
        q = deque(int(x) for x in succ[s])
        seen = np.zeros(n, bool)
        for x in succ[s]:
            seen[x] = True
        while q:
            v = q.popleft()
            reach[s, v] = True
            for w in succ[v]:
                w = int(w)
                if not seen[w]:
                    seen[w] = True
                    q.append(w)
    return reach, reach.diagonal().copy()


# -- witnesses --------------------------------------------------------------

def bfs_path(adj: np.ndarray, src: int, dst: int) -> list[int] | None:
    """Shortest path src -> dst (node list incl. both ends) by BFS over
    the boolean adjacency matrix; None if unreachable."""
    from collections import deque

    if src == dst:
        return [src]
    parent = {src: None}
    q = deque([src])
    while q:
        v = q.popleft()
        for s in np.flatnonzero(adj[v]):
            s = int(s)
            if s in parent:
                continue
            parent[s] = v
            if s == dst:
                path = [s]
                while parent[path[-1]] is not None:
                    path.append(parent[path[-1]])
                return path[::-1]
            q.append(s)
    return None


def extract_cycle(adj: np.ndarray, reach: np.ndarray,
                  cycles: np.ndarray) -> list[int]:
    """Reconstruct one explicit cycle (node list, first == last) from the
    reachability closure — the witness elle renders for a failing check.
    BFS from a cycle node's successor back to the node: shortest witness
    and guaranteed termination (a greedy reach-guided walk can oscillate
    forever between interlocking cycles)."""
    starts = np.flatnonzero(cycles)
    if starts.size == 0:
        return []
    c = int(starts[0])
    for s in np.flatnonzero(adj[c]):
        s = int(s)
        if s == c:
            return [c, c]
        if reach[s, c]:
            back = bfs_path(adj, s, c)
            assert back is not None, "closure says s reaches c"
            return [c] + back
    raise AssertionError("cycle node has no successor on its cycle")


def extract_cycle_any(adj: np.ndarray, cycles: np.ndarray) -> list[int]:
    """Witness reconstruction from a cycle MASK alone (no closure
    materialized — the route the decomposed/tiled/oracle paths take):
    BFS from each successor of the first cycle node back to it. Exact
    and terminating for the same reason extract_cycle is; at most
    out-degree(c) BFS passes, on the (rare) invalid path only."""
    starts = np.flatnonzero(cycles)
    if starts.size == 0:
        return []
    c = int(starts[0])
    for s in np.flatnonzero(adj[c]):
        s = int(s)
        if s == c:
            return [c, c]
        back = bfs_path(adj, s, c)
        if back is not None:
            return [c] + back
    raise AssertionError("cycle node has no successor on its cycle")
