"""Cycle detection over dependency graphs — the MXU path for elle.

The reference ships elle 0.1.2 in its dependency tree (jepsen.etcdemo.iml:46,
reached transitively through jepsen.checker; SURVEY.md §2.2): a
transactional anomaly checker whose core is finding cycles in a
transaction dependency graph. This module is the TPU-native compute core
for that capability: the graph lives as a dense boolean adjacency matrix
and reachability is computed by REPEATED MATRIX SQUARING — O(log N)
[N, N] matmuls, which is exactly MXU food (f32 matmuls on 128-aligned
tiles), instead of elle's JVM depth-first search.

    R_1 = A                      (paths of length 1)
    R_{2k} = R_k | R_k @ R_k     (paths of length <= 2k, >= 1 edge)
    node i lies on a cycle  <=>  R⁺[i, i]

Everything is jitted and shape-bucketed (N padded to a multiple of 128);
results come back as ONE packed device fetch. The pure-Python Tarjan SCC
oracle used by the differential tests lives in checkers/elle.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import instrument_kernel


def _pad_to(n: int, mult: int = 128) -> int:
    return max(mult, (n + mult - 1) // mult * mult)


@functools.lru_cache(maxsize=None)
def _closure_fn(n_pad: int):
    """jitted: adj f32[n_pad, n_pad] (0/1) -> (reach_plus f32 0/1,
    cycle_mask bool[n_pad])."""

    def closure(adj):
        # ceil(log2(n_pad)) squarings bound the longest simple path.
        steps = max(1, int(np.ceil(np.log2(n_pad))))

        def body(r, _):
            # Boolean semiring via f32 matmul + threshold: the matmul is
            # the MXU op; the threshold keeps entries in {0, 1} so values
            # never overflow f32 exactness (n_pad < 2^24).
            r = jnp.minimum(r + r @ r, 1.0)
            return r, None

        r, _ = jax.lax.scan(body, adj, None, length=steps)
        return r, jnp.diagonal(r) > 0.5

    # obs/ compile/execute attribution (PR 1 invariant, jtlint JTL105):
    # the lru_cache IS this kernel's cache — one wrapper (one first-call
    # flag) per padded size, like the WGL kernel caches.
    return instrument_kernel("elle-closure", jax.jit(closure))


def reach_and_cycles(adj: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """adj: bool[N, N] (edge i->j). Returns (reach_plus bool[N, N] — paths
    with >= 1 edge — and cycle_mask bool[N]), both host numpy, via one
    device computation + one fetch."""
    n = adj.shape[0]
    if n == 0:
        return np.zeros((0, 0), bool), np.zeros((0,), bool)
    n_pad = _pad_to(n)
    a = np.zeros((n_pad, n_pad), np.float32)
    a[:n, :n] = adj.astype(np.float32)
    r, cyc = _closure_fn(n_pad)(jnp.asarray(a))
    # Single packed fetch: [N, N+1] slab (reach plus the cycle column).
    packed = np.asarray(jnp.concatenate(
        [r[:n, :n], cyc[:n, None].astype(jnp.float32)], axis=1))
    return packed[:, :n] > 0.5, packed[:, n] > 0.5


def has_cycle(adj: np.ndarray) -> bool:
    return bool(reach_and_cycles(adj)[1].any())


def bfs_path(adj: np.ndarray, src: int, dst: int) -> list[int] | None:
    """Shortest path src -> dst (node list incl. both ends) by BFS over
    the boolean adjacency matrix; None if unreachable."""
    from collections import deque

    if src == dst:
        return [src]
    parent = {src: None}
    q = deque([src])
    while q:
        v = q.popleft()
        for s in np.flatnonzero(adj[v]):
            s = int(s)
            if s in parent:
                continue
            parent[s] = v
            if s == dst:
                path = [s]
                while parent[path[-1]] is not None:
                    path.append(parent[path[-1]])
                return path[::-1]
            q.append(s)
    return None


def extract_cycle(adj: np.ndarray, reach: np.ndarray,
                  cycles: np.ndarray) -> list[int]:
    """Reconstruct one explicit cycle (node list, first == last) from the
    reachability closure — the witness elle renders for a failing check.
    BFS from a cycle node's successor back to the node: shortest witness
    and guaranteed termination (a greedy reach-guided walk can oscillate
    forever between interlocking cycles)."""
    starts = np.flatnonzero(cycles)
    if starts.size == 0:
        return []
    c = int(starts[0])
    for s in np.flatnonzero(adj[c]):
        s = int(s)
        if s == c:
            return [c, c]
        if reach[s, c]:
            back = bfs_path(adj, s, c)
            assert back is not None, "closure says s reaches c"
            return [c] + back
    raise AssertionError("cycle node has no successor on its cycle")
