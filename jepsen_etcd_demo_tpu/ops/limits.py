"""Kernel deployment limits — one tunable profile, not inlined constants.

Round-2 review (VERDICT.md weak #4): the dense/sort/pallas kernels had one
specific deployment's kill and allocation thresholds (the axon TPU worker
tunnel) baked into library control flow as magic numbers. They live here
instead, as ONE dataclass whose default instance IS the axon profile.

Three kinds of fields, flagged per-field via ``field(metadata=...)`` and
surfaced in doc/perf.md's reference table (tools/check_limits_doc.py
enforces the tag + safe range on every field):
  * [worker]   — empirical envelope of the axon worker (program-kill
    timeout, allocation faults, SMEM prefetch ceiling). Wrong on other
    deployments in the conservative direction only: raising them on a
    roomier runtime is safe and buys speed. The autotuner (tune/) never
    probes these past their default in the RISKY direction.
  * [arch]     — derived from TPU architecture (VMEM block budget, unroll
    cost) or a semantic mode switch. Portable across deployments of the
    same chip family; not searched by default.
  * [tunable]  — pure performance knobs (chunk sizes, bucket floors,
    crossovers, pipeline depths) whose best value is a property of the
    MACHINE, measured by ``jepsen-tpu tune`` and persisted per
    ``(backend, device kind, device count)`` (tune/profile.py).

Resolution precedence, per field (doc/perf.md "Autotuning"):

    JEPSEN_TPU_LIMIT_<FIELD> env  >  set_limits()  >  tuned profile
                                  >  dataclass default

``limits()`` returns the resolved instance; ``limits_provenance()`` says
where each field's value came from (``env``/``set``/``tuned``/
``default``) — ``tools/print_profile.py`` dumps both. A malformed env
override (non-int, or outside the field's safe range) raises
:class:`LimitsEnvError` naming the variable and the accepted range, at
import/reload time — loudly, not as a bare ``ValueError`` from ``int()``.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field, fields, replace

# Provenance kinds for the doc/tooling contract (tools/check_limits_doc.py
# asserts every field's doc row carries its tag + safe range).
KINDS = ("worker", "arch", "tunable")


def _f(default: int, kind: str, lo: int, hi: int, *, group: str | None = None,
       conservative: str | None = None):
    """A KernelLimits field: default + machine-readable tuning metadata.

    kind         — worker/arch/tunable (module docstring).
    (lo, hi)     — the SAFE range: env overrides and tuner candidates are
                   validated against it.
    group        — probe group the autotuner measures this knob under
                   (tune/probes.py); None = not searched.
    conservative — for [worker] fields the tuner may still touch: "down"
                   means only values <= default are safe to probe ("up"
                   the reverse). The search clamps candidates accordingly.
    """
    assert kind in KINDS, kind
    return field(default=default, metadata={
        "kind": kind, "range": (lo, hi), "group": group,
        "conservative": conservative})


@dataclass(frozen=True)
class KernelLimits:
    # [worker] Largest dense table (S * 2^K cells) the DEFAULT dense kernel
    # builds per history. Past K ~ 17 the live frontier is invariably tiny
    # relative to the lattice (sort kernel wins), and a K=20 dense chunk
    # measured ~35 s per 4k steps on axon — near its program-kill window.
    dense_cell_budget: int = _f(1 << 20, "worker", 1 << 8, 1 << 30)
    # [worker] Relaxed cell budget for the CHUNKED dense rung (host-driven
    # loop of small scans; each program stays short, so only allocation
    # size limits the table).
    dense_cell_budget_chunked: int = _f(1 << 26, "worker", 1 << 8, 1 << 32,
                                        group="dense_sweep",
                                        conservative="down")
    # [worker] Step-axis chunk for the host-driven long-scan loop: one
    # ~100k-step scan program crashes the axon worker; 40k is fine. 16k
    # leaves ~2x margin. Probed by the dense_sweep tune group in the
    # conservative (smaller) direction only; the env range stays wide
    # above the default because raising a [worker] envelope on a roomier
    # runtime is the documented-safe direction.
    long_scan_chunk: int = _f(16384, "worker", 256, 1 << 20,
                              group="dense_sweep", conservative="down")
    # [worker] Longest single scan program the non-chunked XLA path emits.
    long_scan_max: int = _f(32768, "worker", 1024, 1 << 20)
    # [worker] Sort rows (f_cap * (k_slots + 1) keys) per launch; the axon
    # worker faults allocating past ~2M rows.
    sort_row_budget: int = _f(1 << 21, "worker", 1 << 10, 1 << 28)
    # [worker] Element budget for a stacked batch launch of the sort
    # kernel (keeps host->device transfers a few hundred MB).
    stack_element_budget: int = _f(1 << 26, "worker", 1 << 12, 1 << 32)
    # [arch] The pallas kernel unrolls the slot sweep K times and carries a
    # u32[S, 2^(K-5)] table in VMEM; K=16 is 64 KiB of table and a sane
    # compile time.
    max_k_pallas: int = _f(16, "arch", 5, 20, group="pallas",
                           conservative="down")
    # [arch] Return steps per colmask block: 512 x (8,128) u32 = 2 MiB,
    # double-buffered well inside the 16 MiB VMEM budget. Probed by the
    # pallas tune group where Mosaic compiles.
    pallas_step_chunk: int = _f(512, "arch", 64, 4096, group="pallas")
    # [worker] Per-history step ceiling for the pallas scalar-prefetch
    # targets table ([1, ~98k] kills the axon worker; 16k runs routinely;
    # env range wide above the default — raising on a roomier runtime is
    # the safe direction).
    max_r_pallas: int = _f(16384, "worker", 256, 1 << 20)
    # [worker] Total prefetch entries (batch * steps) per pallas launch.
    max_prefetch_pallas: int = _f(1 << 18, "worker", 1 << 10, 1 << 22)
    # [tunable] Event-count crossover below which a SINGLE history on a
    # live TPU backend routes to the exact host oracle instead of a
    # device launch: the dispatch+fetch round trip exceeds the oracle's
    # whole runtime at tutorial scale. -1 (default) = MEASURED per
    # platform at first use (ops/calibrate.py: dispatch floor x oracle
    # events/s, persisted in the tuned profile); 0 = never route
    # (bench.py pins 0 for its kernel lanes); >0 = fixed crossover.
    # Batches are never routed regardless.
    oracle_crossover_events: int = _f(-1, "tunable", -1, 1 << 16)
    # [arch] Concurrency ceiling for the oracle route: the frontier can
    # hold up to 2^pending configurations per state, so a wide-pending
    # history must take the capped/budgeted device ladder even when its
    # event count is tiny. 12 pending ops bounds the closure at ~4k
    # masks/state — comfortably inside the config budget below.
    oracle_route_max_pending: int = _f(12, "arch", 1, 20)
    # [arch] Transition-attempt budget for a routed oracle run; on
    # expiry the route abandons the host search and falls through to the
    # device ladder (ADVICE r4: no unbounded exponential host search on
    # the product path). ~2M step_py calls is <1 s of host time.
    oracle_config_budget: int = _f(2_000_000, "arch", 1, 1 << 28)
    # [arch] Histories per pallas program in the grouped batch kernel
    # (tables stacked on a leading group axis; amortizes per-step
    # instruction overhead — measured 1.6-2.1x end-to-end / ~2.3x
    # kernel-side at G=16 on v5e, plateau past 16). 0 or 1 disables
    # grouping; batches smaller than the group stay per-history.
    pallas_group: int = _f(16, "arch", 0, 64)
    # [tunable] Floor of the step-axis length buckets the corpus scheduler
    # (sched/engine.py) and the scan-length bucketing (wgl3.step_bucket)
    # pad to. {2^k, 1.5*2^k} buckets bound per-bucket padding waste to
    # <1.5x and distinct jit compilations per kernel to the bucket count;
    # a lower floor trades a few extra compilations for tighter padding
    # on short-history corpora. 32 chosen from the step-padding gauge
    # (PR 1); the sched tune group measures the padding-vs-compile
    # tradeoff per machine.
    step_bucket_floor: int = _f(32, "tunable", 8, 512, group="sched")
    # [tunable] Floor of the batch-axis buckets the scheduler pads
    # launches to (with all-pad histories, targets=-1 — stripped from
    # results).
    batch_bucket_floor: int = _f(8, "tunable", 1, 128, group="sched")
    # [tunable] In-flight chunks of the double-buffered resumable sort
    # sweep (ops/wgl2.py check_steps_resumable): chunk N+1 dispatches
    # before chunk N's overflow flag is fetched, hiding the per-chunk
    # host<->device round trip. 1 restores the fully synchronous loop;
    # deeper pipelines only buy anything on high-latency (tunneled)
    # backends — which is exactly what the pipeline tune group measures.
    sched_pipeline_depth: int = _f(2, "tunable", 1, 8, group="pipeline")
    # [tunable] Death-poll interval (in chunks) of the pipelined dense
    # long sweep (wgl3.check_steps3_long without a time budget): the
    # early-exit fetch costs a host round trip per poll, so the pipeline
    # only syncs every N chunks; dead chunks in between are near-free
    # (empty closures).
    sched_poll_chunks: int = _f(8, "tunable", 1, 64, group="pipeline")
    # [tunable] History-encoding placement (ops/encode.py routing to the
    # device encoder kernel, ops/encode_device.py): 0 = auto (device on
    # the mesh-sharded batch lane where the packed-table H2D dominates,
    # host elsewhere), 1 = host always, 2 = device whenever the geometry
    # fits a jittable event bucket. Rows are bit-identical in every mode
    # (tests/test_pod_scaling.py pins host/device differentials), so
    # this is purely a transfer/fusion placement choice — the pod tune
    # group measures which side of the H2D boundary wins per machine.
    encode_mode: int = _f(0, "tunable", 0, 2, group="pod")
    # [tunable] In-flight launch window of the pod dispatch pipeline
    # (plan/dispatch.py LaunchPipeline): bucket launch N+1's host prep +
    # H2D staging overlaps launch N's device execute, bounding both the
    # speculative depth and the undrained device-result memory. 1
    # restores the fetch-after-every-launch synchronous loop; the old
    # unbounded drain-at-end behaviour is depth >= the launch count.
    pod_pipeline_depth: int = _f(4, "tunable", 1, 8, group="pod")
    # [tunable] Shard-aware bucketing (sched/engine.py + parallel/
    # dense.py): 1 = split sharded launches into per-step-length buckets
    # and LPT-pack histories into contiguous per-shard blocks balanced
    # by REAL step count, so one ragged straggler no longer pads the
    # whole mesh (the MULTICHIP_r06 smoking gun); 0 = legacy one-bucket
    # corpus padding. Verdicts are bit-identical either way — packing
    # permutes launch order only, never the per-history scan.
    shard_bucket_mode: int = _f(1, "tunable", 0, 1, group="pod")
    # [arch] Entry capacity of the scheduler's in-process kernel LRU
    # (sched/compile_cache.py, keyed by (kernel, model, bucket shape)).
    kernel_cache_entries: int = _f(256, "arch", 16, 4096)
    # [arch] Words of the packed table per occupancy tile of the sparse
    # active-tile sweep engine (ops/wgl3_sparse.py). Power of two; one
    # tile is TILE*32 configs per state row. 8 words (256 configs/state)
    # keeps the occupancy bitmap tiny (W/8 bits) while a gathered tile
    # is still a meaningful vector width.
    sparse_tile_words: int = _f(8, "arch", 1, 64)
    # [tunable] Live-tile density (percent of tiles occupied) above which
    # a closure round runs the DENSE sweep instead of gather->expand->
    # scatter — the direction-optimizing switch (Beamer et al., SC'12):
    # past ~1/4 occupancy the gather/scatter overhead exceeds the work
    # skipped. Applies per round, so a frontier that fills up mid-step
    # crosses over mid-sweep (and back) with no host involvement. The
    # sparse tune group measures the real crossover per machine (PR 3
    # hardcoded a CPU measurement).
    sparse_density_threshold_pct: int = _f(25, "tunable", 1, 100,
                                           group="sparse")
    # [tunable] Static capacity (in tiles) of the sparse engine's gather
    # work list. XLA shapes are static, so the gathered frontier is
    # padded to this many tiles; a round whose live-tile count exceeds
    # it falls back to the dense sweep for that round (never drops
    # configs). Per-round sparse cost is O(cap * tile_words), so the
    # cap bounds worst-case sparse work regardless of K.
    sparse_worklist_cap: int = _f(512, "tunable", 64, 8192)
    # [tunable] Minimum tile count (W / sparse_tile_words) before the
    # sparse engine engages in AUTO mode: below the crossover the dense
    # sweep's straight-line vector code beats the gather/nonzero/scatter
    # overhead even at <1% occupancy. The default (2048 tiles = K >= 19
    # at the default 8-word tile) encodes ONE CPU measurement; the
    # sparse tune group sweeps live-tile density per machine. A TPU's
    # VPU widens the dense side's advantage, so raising this on real
    # hardware is the conservative direction; sparse_mode=2 forces the
    # engine on regardless for measurement.
    sparse_min_tiles: int = _f(2048, "tunable", 1, 1 << 20, group="sparse")
    # [arch] Sweep-mode override for the dense lattice kernels:
    # 0 = auto (sparse engine on eligible geometries, per-round density
    # switch), 1 = dense-only (sparse engine off), 2 = prefer-sparse
    # (density threshold ignored; the work-list capacity still forces
    # dense rounds on overflow — configs are never dropped). 2 is the
    # bench/test lane for exercising the sparse path deterministically.
    sparse_mode: int = _f(0, "arch", 0, 2)
    # [tunable] Frontier dedup/canonicalization mode (ops/canon.py):
    # 0 = auto — canonicalize where frontier size directly drives cost
    # (the resumable sort ladder, wgl2.check_steps_resumable: measured
    # 4x on symmetry-heavy histories via avoided capacity escalations)
    # plus the sparse engine's per-tile seen memo; the packed-TABLE
    # sweeps (dense/sparse/lattice) stay canon-free — their sweep cost
    # is fixed in the table size, so the pass is pure overhead there
    # unless measured otherwise.
    # 1 = off (every kernel byte-identical to the pre-dedup build).
    # 2 = force — the table sweeps canonicalize too (the bench/test
    # lane, or a tuned profile on a machine where the `dedup` probe
    # measured it faster). Exact in every mode: canonicalization is a
    # verdict-preserving quotient (doc/perf.md "Frontier dedup"), so
    # the tuner may search it freely.
    dedup_mode: int = _f(0, "tunable", 0, 2, group="dedup")
    # [tunable] Slot capacity of the sparse engine's device-side `seen`
    # memo (one consumed-popcount slot per occupancy tile, direct
    # indexed — collision-free by construction). Geometries with more
    # tiles than slots FAIL OPEN to no-memo (every live tile re-swept,
    # exactly the pre-dedup behavior) so verdicts stay exact; the memo
    # array costs 4 bytes/slot of device memory per compiled geometry.
    dedup_hash_slots: int = _f(4096, "tunable", 64, 1 << 20, group="dedup")
    # [tunable] Converged-frontier size below which the per-step TABLE
    # canonicalization pass is skipped: the pass costs a few table
    # gathers per symmetry pair, which tiny frontiers never repay.
    # Skipping is always sound (canonicalization is an optimization,
    # not a correctness pass); orthogonal to dedup_mode.
    dedup_min_frontier: int = _f(64, "tunable", 0, 1 << 20, group="dedup")
    # [arch] Route override for the elle transitive-closure engine
    # (ops/cycles.py): 0 = auto (dense squaring below
    # elle_dense_max_nodes; component decomposition + bucketed batch +
    # tiled work-list kernel above), 1 = dense-only (the seed [N, N]
    # matrix-squaring path regardless of size — the bench's baseline
    # arm), 2 = prefer-tiled (the blocked work-list kernel even for
    # small graphs — the bench/test lane for exercising the tiled path
    # deterministically). Exact in every mode: the closure fixpoint is
    # unique, so anomaly verdicts never depend on the route.
    elle_mode: int = _f(0, "arch", 0, 2)
    # [tunable] Node-count crossover below which a single dependency
    # graph routes to the dense [N, N] matrix-squaring kernel: under it
    # the straight-line MXU/BLAS closure beats the decompose/gather
    # overhead; above it the graph is decomposed into weak components
    # checked batched (small) or tiled (large). 2048 encodes ONE CPU
    # measurement; the elle tune probe group measures it per machine.
    elle_dense_max_nodes: int = _f(2048, "tunable", 128, 1 << 16,
                                   group="elle")
    # [tunable] Tile edge of the blocked transitive-closure kernel
    # (ops/cycles_tiled.py); rounded to a multiple of 128 (the MXU/lane
    # geometry). Smaller tiles sharpen occupancy skipping on very
    # sparse closures, larger tiles amortize per-product dispatch.
    elle_tile: int = _f(256, "tunable", 128, 1024, group="elle")
    # [tunable] Batch-axis bucket floor of the corpus-of-graphs closure
    # launches (ops/cycles.py reach_and_cycles_batch): graphs grouped
    # into padded-size buckets pad their batch axis to {2^k, 1.5*2^k}
    # buckets from this floor, so corpora of varying graph counts reuse
    # the same compiled vmapped shapes (the sched bucket discipline
    # applied to dependency graphs).
    elle_batch_floor: int = _f(8, "tunable", 1, 128, group="elle")
    # [tunable] Static capacity (in tile products) of the tiled closure
    # kernel's gather work list. XLA shapes are static, so each sparse
    # round pads its eligible (i, k, j) product set to this many
    # entries; a round whose eligible count exceeds it runs the dense
    # block sweep for that round instead (never drops reachability).
    elle_worklist_cap: int = _f(4096, "tunable", 64, 1 << 16)
    # [tunable] Eligible-product density (percent of nb^3 block
    # products live) above which a tiled closure round runs the dense
    # block sweep instead of gather->matmul->scatter — the
    # direction-optimizing crossover of the wgl3_sparse engine applied
    # to the closure's block products, taken per round.
    elle_density_threshold_pct: int = _f(35, "tunable", 1, 100,
                                         group="elle")
    # [worker] Padded-cell ceiling (n_pad^2) for one device closure
    # launch, dense or tiled: past it the f32 reachability matrix
    # outgrows what a single launch should allocate, and the closure
    # routes to the exact host Tarjan/SCC oracle instead (same
    # verdicts, no device allocation). 2^28 cells = 16384^2 = 1 GiB
    # f32. The floor sits BELOW the smallest padded graph (128^2 =
    # 2^14) so the oracle route can be force-pinned for certification
    # (the bench elle lane's "tarjan" arm).
    elle_cell_budget: int = _f(1 << 28, "worker", 1 << 12, 1 << 34,
                               conservative="down")
    # [tunable] Completed txns per incremental dependency-graph
    # re-check of the streaming elle session (stream/elle.py): smaller
    # flushes tighten the --fail-fast falsification bound, larger ones
    # amortize the incremental closure launches.
    elle_stream_flush: int = _f(64, "tunable", 1, 1 << 16, group="elle")
    # [tunable] Return steps per streamed check chunk (stream/engine.py):
    # the stable-prefix dispatcher accumulates this many stable return
    # steps before feeding one resumable dense chunk to the device.
    # Smaller chunks start overlapping with the live run earlier and
    # tighten the fail-fast detection bound; larger chunks amortize
    # per-dispatch overhead (one jitted launch per chunk). Verdicts are
    # chunk-size-independent (the carry chains exactly).
    stream_flush_ops: int = _f(256, "tunable", 8, 1 << 16, group="stream")
    # [tunable] Death-poll bound of the streaming dispatcher: at most
    # this many chunks are dispatched between fetches of the frontier's
    # death flag, so the falsification LAG behind the live run is
    # bounded by stream_max_lag_chunks * stream_flush_ops return steps.
    # 1 polls every chunk (fastest --fail-fast, one host<->device round
    # trip per chunk); deeper lets the async dispatch pipeline run
    # ahead between syncs.
    stream_max_lag_chunks: int = _f(4, "tunable", 1, 64, group="stream")
    # [tunable] Max-linger of the serve daemon's continuous-batching
    # scheduler (serve/scheduler.py): after the first pending request
    # arrives, the dispatcher waits up to this many milliseconds for
    # more requests to coalesce into the same bucketed launch before
    # dispatching. 0 = dispatch immediately (no cross-request
    # coalescing beyond what is already queued); larger values trade
    # per-request latency for batch fill — the capacity-planning knob
    # (doc/serve.md ties it to the sched bucket fill).
    serve_coalesce_ms: int = _f(10, "tunable", 0, 1000)
    # [tunable] Most requests one coalesced serve batch may carry; the
    # dispatcher drains the per-tenant queues weighted-fair up to this
    # many per launch cycle (the batch still splits into sched's
    # {2^k, 1.5*2^k} bucket launches downstream).
    serve_max_batch: int = _f(64, "tunable", 1, 4096)
    # [arch] Per-tenant bound of admitted-but-unfinished serve requests
    # (queued + in a dispatching batch): a tenant at the bound has new
    # submissions rejected (HTTP 429) until verdicts drain — the
    # admission-control half of the serve daemon's backpressure
    # (supervisor state drives the other half: shed/503).
    serve_max_inflight: int = _f(256, "arch", 1, 4096)
    # [tunable] Replica count the fleet supervisor (serve/fleet.py)
    # spawns when `jepsen-tpu serve --fleet` is not given an explicit
    # --replicas: how many `serve --check` daemons share the traffic
    # behind the shape-affine router. The right value is a property of
    # the MACHINE (cores / chips per replica), not the code — more
    # replicas buy isolation of each shard's kernel LRU at the cost of
    # per-replica batch fill (doc/serve.md "Fleet").
    fleet_replicas: int = _f(2, "tunable", 1, 64)
    # [arch] Router spillover policy when a routed replica is
    # unavailable (serve/router.py): 0 = affine-with-spillover (walk
    # the rendezvous preference order past unhealthy/failed replicas —
    # the default), 1 = strict affinity (no spillover; 503 when the
    # owning replica cannot take the request), 2 = random routing
    # (shape affinity off — the bench's comparison arm and the
    # locality-off escape hatch).
    fleet_spillover_mode: int = _f(0, "arch", 0, 2)
    # [arch] Salt mixed into the router's rendezvous hash
    # (serve/router.py routing_key -> replica scores): changing it
    # re-deals the shape->replica placement wholesale, which is the
    # operational lever for breaking a pathological placement (one
    # replica owning every hot bucket) without restarting the fleet.
    # Same salt fleet-wide or routing is not a function.
    fleet_hash_salt: int = _f(0, "arch", 0, 1 << 30)
    # [tunable] Host spill routing for the out-of-core checking tier
    # (store/spill.py): 0 = auto (spill encoded chunks / frontier
    # checkpoints to disk only when the estimated working set exceeds
    # host_rss_budget_mb), 1 = off (everything stays in RAM — the seed
    # behaviour), 2 = force (every checkpoint/chunk goes through the
    # spill tier — the bench/test lane). Verdicts are bit-identical in
    # every mode: the spill tier moves bytes, never meaning.
    host_spill_mode: int = _f(0, "tunable", 0, 2, group="spill")
    # [tunable] Host-RAM working-set budget (MiB) of the out-of-core
    # tier: the bounded in-RAM window of spilled encoded chunks and
    # frontier checkpoints evicts to disk past this budget, and the
    # long-haul bench lane pins its RSS-growth ceiling to it.
    host_rss_budget_mb: int = _f(4096, "tunable", 64, 1 << 20,
                                 group="spill")
    # [tunable] Spilled frontier-checkpoint compression (store/spill.py
    # FrontierCodec): 0 = auto (canon-quotient per-class counts when the
    # frontier is canonical, raw packed rows otherwise), 1 = raw always,
    # 2 = force-canonical (refuse the raw fallback — the codec test
    # lane). Decompression is bit-identical in every mode; a payload
    # that fails its digest reads as absent (recompute), never as data.
    spill_compress_mode: int = _f(0, "tunable", 0, 2, group="spill")
    # [tunable] On-disk size cap (MiB) of the content-addressed encode
    # cache (store/encode_cache.py): past it, store() garbage-collects
    # least-recently-used entries (mtime order) until under the cap.
    # 0 disables collection (the seed's unbounded growth).
    encode_cache_cap_mb: int = _f(2048, "tunable", 0, 1 << 20,
                                  group="spill")


def field_meta() -> dict[str, dict]:
    """Machine-readable tuning metadata per field: {name: {kind, range,
    group, conservative, default}} — the doc lint's and the autotuner's
    single source of truth for tags and search bounds."""
    out = {}
    for f in fields(KernelLimits):
        out[f.name] = dict(f.metadata) | {"default": f.default}
    return out


class LimitsEnvError(ValueError):
    """A JEPSEN_TPU_LIMIT_<FIELD> override that cannot apply: non-integer
    or outside the field's safe range. The message names the env var and
    the accepted range so the operator can fix it without reading code."""


def env_var(name: str) -> str:
    return f"JEPSEN_TPU_LIMIT_{name.upper()}"


def _parse_env() -> dict[str, int]:
    """Validated env overrides. Loud failure (satellite of ISSUE 4): a
    malformed value must name the variable and the accepted range, not
    surface as a bare ValueError from int()."""
    overrides: dict[str, int] = {}
    for f in fields(KernelLimits):
        var = env_var(f.name)
        raw = os.environ.get(var)
        if raw is None:
            continue
        lo, hi = f.metadata["range"]
        try:
            # Plain decimal first (accepts zero-padded "010" like the
            # pre-ISSUE-4 parser did), then prefixed literals (0x…).
            val = int(raw)
        except ValueError:
            try:
                val = int(raw, 0)
            except ValueError:
                raise LimitsEnvError(
                    f"{var}={raw!r} is not an integer (accepted range "
                    f"for {f.name}: {lo}..{hi})") from None
        if not lo <= val <= hi:
            raise LimitsEnvError(
                f"{var}={val} is outside the safe range for {f.name}: "
                f"{lo}..{hi} (doc/perf.md 'KernelLimits reference')")
        overrides[f.name] = val
    return overrides


# -- resolution state -------------------------------------------------------
#
# _ENV     validated env overrides, parsed at import (and on _reload()).
# _SET     the programmatic profile installed by set_limits(), or None.
# _TUNED   the persisted tuned profile's field dict for this platform, or
#          None when not yet loaded (lazy — loading may need a jax
#          backend, see tune/profile.py), or {} when loaded-and-absent.
# _LIMITS  the memoized resolved instance (invalidated on any change).

_ENV: dict[str, int] = _parse_env()
_SET: KernelLimits | None = None
_TUNED: dict[str, int] | None = None
_LIMITS: KernelLimits | None = None


def _tuned_overrides() -> dict[str, int]:
    """The tuned profile's overrides for this platform, loaded lazily on
    the first resolution that CAN determine them. tune/profile.py only
    touches a jax backend when a profile FILE exists (an operator ran
    `jepsen-tpu tune` on this machine), so processes on machines with no
    profile never risk initializing a wedged backend from here. While
    the answer is UNDETERMINED (a profile file exists but jax is not
    imported yet, so the platform key cannot resolve), nothing is cached
    — a limits() call made before backend init must not freeze an empty
    tuned set for the process lifetime (tuned_limits() returns None for
    that case, {} for a definitive no-profile answer)."""
    global _TUNED
    if _TUNED is None:
        try:
            from ..tune import profile as _profile

            tuned = _profile.tuned_limits()
        except Exception:
            # The tuned profile is an optimization, never a failure mode
            # (a torn file / unimportable jax must not break limits()).
            tuned = {}
        if tuned is None:
            return {}            # undetermined: retry on a later call
        _TUNED = dict(tuned)
    return _TUNED


def _resolve() -> KernelLimits:
    base = _SET if _SET is not None else \
        replace(KernelLimits(), **_tuned_overrides())
    return replace(base, **_ENV) if _ENV else base


def limits() -> KernelLimits:
    """The active limits profile, resolved with precedence
    env > set_limits() > tuned profile > dataclass default. The
    resolution is memoized only once the tuned-profile question is
    settled (or a set_limits profile shadows it) — see
    _tuned_overrides."""
    global _LIMITS
    if _LIMITS is not None:
        return _LIMITS
    lim = _resolve()
    if _SET is not None or _TUNED is not None:
        _LIMITS = lim
    return lim


def limits_provenance() -> dict[str, str]:
    """Where each resolved field's value came from: "env" (a
    JEPSEN_TPU_LIMIT_* override), "set" (set_limits() installed a value
    differing from the default), "tuned" (the persisted tuned profile),
    or "default" (the dataclass / axon profile). Surfaced by
    tools/print_profile.py, the bench records, and run telemetry."""
    lim = limits()
    out = {}
    for f in fields(KernelLimits):
        if f.name in _ENV:
            out[f.name] = "env"
        elif _SET is not None:
            out[f.name] = ("set" if getattr(lim, f.name) != f.default
                           else "default")
        elif f.name in (_TUNED or {}):
            out[f.name] = "tuned"
        else:
            out[f.name] = "default"
    return out


def set_limits(lim: KernelLimits | None) -> KernelLimits | None:
    """Install a programmatic profile (tests / embedding runtimes);
    returns the PREVIOUS programmatic profile — None when there was none
    — so the save/restore idiom ``prev = set_limits(x); ...;
    set_limits(prev)`` restores the exact prior state (in particular it
    does NOT freeze a resolved snapshot that would mask a tuned profile
    loaded later). Env overrides still win over the installed instance
    (precedence above); the tuned profile does not apply while a
    set_limits profile is active — the caller chose a complete instance.
    ``None`` clears the programmatic profile, re-enabling tuned-profile
    resolution. When an env override SHADOWS a differing installed value
    (e.g. a bench pin under an exported JEPSEN_TPU_LIMIT_*), that is
    logged once per field — a measurement pin being silently ignored is
    exactly the surprise the precedence doc alone doesn't prevent."""
    global _SET, _LIMITS
    prev = _SET
    _SET = lim
    _LIMITS = None
    if lim is not None and _ENV:
        shadowed = [f for f, v in _ENV.items() if getattr(lim, f) != v]
        new = [f for f in shadowed if f not in _WARNED_SHADOWED]
        if new:
            _WARNED_SHADOWED.update(new)
            import logging

            logging.getLogger(__name__).warning(
                "set_limits value(s) shadowed by env overrides "
                "(precedence env > set_limits): %s",
                ", ".join(f"{f} ({env_var(f)}={_ENV[f]})"
                          for f in sorted(new)))
    return prev


_WARNED_SHADOWED: set = set()


def _reload() -> None:
    """Re-parse env and drop the memoized resolution AND the cached tuned
    profile (tests, and tune/profile.py after persisting a new profile).
    Raises LimitsEnvError on malformed env, like import does."""
    global _ENV, _TUNED, _LIMITS
    _ENV = _parse_env()
    _TUNED = None
    _LIMITS = None
