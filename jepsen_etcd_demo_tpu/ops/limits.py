"""Kernel deployment limits — one tunable profile, not inlined constants.

Round-2 review (VERDICT.md weak #4): the dense/sort/pallas kernels had one
specific deployment's kill and allocation thresholds (the axon TPU worker
tunnel) baked into library control flow as magic numbers. They live here
instead, as ONE dataclass whose default instance IS the axon profile; a pod
or a newer runtime overrides per-field via environment variables
(``JEPSEN_TPU_LIMIT_<FIELD>=<int>``, upper-cased field name) or
programmatically via :func:`set_limits`.

Two kinds of fields, flagged per-field below:
  * [worker]  — empirical envelope of the axon worker (program-kill timeout,
    allocation faults, SMEM prefetch ceiling). Wrong on other deployments in
    the conservative direction only: raising them on a roomier runtime is
    safe and buys speed.
  * [arch]    — derived from TPU architecture (VMEM block budget, unroll
    cost). Portable across deployments of the same chip family.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, fields, replace


@dataclass(frozen=True)
class KernelLimits:
    # [worker] Largest dense table (S * 2^K cells) the DEFAULT dense kernel
    # builds per history. Past K ~ 17 the live frontier is invariably tiny
    # relative to the lattice (sort kernel wins), and a K=20 dense chunk
    # measured ~35 s per 4k steps on axon — near its program-kill window.
    dense_cell_budget: int = 1 << 20
    # [worker] Relaxed cell budget for the CHUNKED dense rung (host-driven
    # loop of small scans; each program stays short, so only allocation
    # size limits the table).
    dense_cell_budget_chunked: int = 1 << 26
    # [worker] Step-axis chunk for the host-driven long-scan loop: one
    # ~100k-step scan program crashes the axon worker; 40k is fine. 16k
    # leaves ~2x margin.
    long_scan_chunk: int = 16384
    # [worker] Longest single scan program the non-chunked XLA path emits.
    long_scan_max: int = 32768
    # [worker] Sort rows (f_cap * (k_slots + 1) keys) per launch; the axon
    # worker faults allocating past ~2M rows.
    sort_row_budget: int = 1 << 21
    # [worker] Element budget for a stacked batch launch of the sort
    # kernel (keeps host->device transfers a few hundred MB).
    stack_element_budget: int = 1 << 26
    # [arch] The pallas kernel unrolls the slot sweep K times and carries a
    # u32[S, 2^(K-5)] table in VMEM; K=16 is 64 KiB of table and a sane
    # compile time.
    max_k_pallas: int = 16
    # [arch] Return steps per colmask block: 512 x (8,128) u32 = 2 MiB,
    # double-buffered well inside the 16 MiB VMEM budget.
    pallas_step_chunk: int = 512
    # [worker] Per-history step ceiling for the pallas scalar-prefetch
    # targets table ([1, ~98k] kills the axon worker; 16k runs routinely).
    max_r_pallas: int = 16384
    # [worker] Total prefetch entries (batch * steps) per pallas launch.
    max_prefetch_pallas: int = 1 << 18
    # [worker] Event-count crossover below which a SINGLE history on a
    # live TPU backend routes to the exact host oracle instead of a
    # device launch: the dispatch+fetch round trip exceeds the oracle's
    # whole runtime at tutorial scale. -1 (default) = MEASURED per
    # platform at first use (ops/calibrate.py: dispatch floor x oracle
    # events/s, persisted next to the compile cache); 0 = never route
    # (bench.py pins 0 for its kernel lanes); >0 = fixed crossover.
    # Batches are never routed regardless.
    oracle_crossover_events: int = -1
    # [arch] Concurrency ceiling for the oracle route: the frontier can
    # hold up to 2^pending configurations per state, so a wide-pending
    # history must take the capped/budgeted device ladder even when its
    # event count is tiny. 12 pending ops bounds the closure at ~4k
    # masks/state — comfortably inside the config budget below.
    oracle_route_max_pending: int = 12
    # [arch] Transition-attempt budget for a routed oracle run; on
    # expiry the route abandons the host search and falls through to the
    # device ladder (ADVICE r4: no unbounded exponential host search on
    # the product path). ~2M step_py calls is <1 s of host time.
    oracle_config_budget: int = 2_000_000
    # [arch] Histories per pallas program in the grouped batch kernel
    # (tables stacked on a leading group axis; amortizes per-step
    # instruction overhead — measured 1.6-2.1x end-to-end / ~2.3x
    # kernel-side at G=16 on v5e, plateau past 16). 0 or 1 disables
    # grouping; batches smaller than the group stay per-history.
    pallas_group: int = 16
    # [arch] Floor of the step-axis length buckets the corpus scheduler
    # (sched/engine.py) and the scan-length bucketing (wgl3.step_bucket)
    # pad to. {2^k, 1.5*2^k} buckets bound per-bucket padding waste to
    # <1.5x and distinct jit compilations per kernel to the bucket count;
    # a lower floor trades a few extra compilations for tighter padding
    # on short-history corpora. 32 chosen from the step-padding gauge
    # (PR 1): tutorial-scale fuzz corpora (10-120 ops) measured >2x
    # padded/real under the old 64 floor, <1.6x at 32.
    step_bucket_floor: int = 32
    # [arch] Floor of the batch-axis buckets the scheduler pads launches
    # to (with all-pad histories, targets=-1 — stripped from results).
    batch_bucket_floor: int = 8
    # [arch] In-flight chunks of the double-buffered resumable sort sweep
    # (ops/wgl2.py check_steps_resumable): chunk N+1 dispatches before
    # chunk N's overflow flag is fetched, hiding the per-chunk host<->
    # device round trip. 1 restores the fully synchronous loop; deeper
    # pipelines only buy anything on high-latency (tunneled) backends.
    sched_pipeline_depth: int = 2
    # [worker] Death-poll interval (in chunks) of the pipelined dense
    # long sweep (wgl3.check_steps3_long without a time budget): the
    # early-exit fetch costs a host round trip per poll, so the pipeline
    # only syncs every N chunks; dead chunks in between are near-free
    # (empty closures).
    sched_poll_chunks: int = 8
    # [arch] Entry capacity of the scheduler's in-process kernel LRU
    # (sched/compile_cache.py, keyed by (kernel, model, bucket shape)).
    kernel_cache_entries: int = 256
    # [arch] Words of the packed table per occupancy tile of the sparse
    # active-tile sweep engine (ops/wgl3_sparse.py). Power of two; one
    # tile is TILE*32 configs per state row. 8 words (256 configs/state)
    # keeps the occupancy bitmap tiny (W/8 bits) while a gathered tile
    # is still a meaningful vector width.
    sparse_tile_words: int = 8
    # [arch] Live-tile density (percent of tiles occupied) above which a
    # closure round runs the DENSE sweep instead of gather->expand->
    # scatter — the direction-optimizing switch (Beamer et al., SC'12):
    # past ~1/4 occupancy the gather/scatter overhead exceeds the work
    # skipped. Applies per round, so a frontier that fills up mid-step
    # crosses over mid-sweep (and back) with no host involvement.
    sparse_density_threshold_pct: int = 25
    # [arch] Static capacity (in tiles) of the sparse engine's gather
    # work list. XLA shapes are static, so the gathered frontier is
    # padded to this many tiles; a round whose live-tile count exceeds
    # it falls back to the dense sweep for that round (never drops
    # configs). Per-round sparse cost is O(cap * tile_words), so the
    # cap bounds worst-case sparse work regardless of K.
    sparse_worklist_cap: int = 512
    # [arch] Minimum tile count (W / sparse_tile_words) before the
    # sparse engine engages in AUTO mode: below the crossover the dense
    # sweep's straight-line vector code beats the gather/nonzero/scatter
    # overhead even at <1% occupancy. MEASURED on the CPU backend
    # (bench.py sparse lane, long register history, warm): K=16 0.62x,
    # K=18 0.78x, K=20 2.33x sparse-vs-dense — so the default engages at
    # K >= 19 (2048 tiles at the default 8-word tile). A TPU's VPU
    # widens the dense side's advantage, so raising this on real
    # hardware is the conservative direction; sparse_mode=2 forces the
    # engine on regardless for measurement.
    sparse_min_tiles: int = 2048
    # [arch] Sweep-mode override for the dense lattice kernels:
    # 0 = auto (sparse engine on eligible geometries, per-round density
    # switch), 1 = dense-only (sparse engine off), 2 = prefer-sparse
    # (density threshold ignored; the work-list capacity still forces
    # dense rounds on overflow — configs are never dropped). 2 is the
    # bench/test lane for exercising the sparse path deterministically.
    sparse_mode: int = 0


def _from_env() -> KernelLimits:
    lim = KernelLimits()
    overrides = {}
    for f in fields(KernelLimits):
        raw = os.environ.get(f"JEPSEN_TPU_LIMIT_{f.name.upper()}")
        if raw is not None:
            overrides[f.name] = int(raw)
    return replace(lim, **overrides) if overrides else lim


_LIMITS: KernelLimits = _from_env()


def limits() -> KernelLimits:
    """The active limits profile (axon defaults + env overrides)."""
    return _LIMITS


def set_limits(lim: KernelLimits) -> KernelLimits:
    """Swap the active profile (tests / embedding runtimes); returns the
    previous one so callers can restore it."""
    global _LIMITS
    prev = _LIMITS
    _LIMITS = lim
    return prev
