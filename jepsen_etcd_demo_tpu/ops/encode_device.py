"""Device-side history encoding: the return-major table built ON device.

The host encoder (ops/encode.py encode_return_steps) materializes the
packed slot-table tensor — R*(K*5+1) int32 cells per history — on the
host and ships the WHOLE thing across the host->device boundary on
every launch. The compact event stream it derives from (events[E, 6],
roughly K times smaller) is the real information content; everything
else is a deterministic expansion. This module is that expansion as a
jittable XLA program: the event tensor crosses once, and the slot-table
snapshot per return step is rebuilt on-device, so the packed-table H2D
disappears from the dispatch critical path and the encode fuses into
the launch pipeline (plan/dispatch.py LaunchPipeline).

Routing lives behind ``limits().encode_mode`` (ops/limits.py): 0 = auto
(device on the mesh-sharded batch lane, host elsewhere), 1 = host
always, 2 = device whenever the geometry fits. Both the post-hoc
encoder and the streaming ``IncrementalEncoder`` prefix route through
``ops.encode.encode_return_steps``, so one knob governs every path.

Bit-identity contract: for any EncodedHistory, the first n_steps rows
of the device output equal ``encode_return_steps(enc)`` exactly, and
the padded tail equals ``ReturnSteps.padded_to`` (tabs 0, active False,
targets -1) — all arithmetic is int32/bool, no floating point, so the
mirror is exact by construction and tests/test_pod_scaling.py pins it
with golden + fuzz differentials (crashed-op pinning and LIFO slot
reuse included).

Static shapes: the kernel compiles per (k_slots, e_cap, r_cap). Event
capacity buckets through the same {2^k, 1.5*2^k} ladder as the step
axis (wgl3.step_bucket) so ragged corpora share compiled shapes.
"""

from __future__ import annotations

import time
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import get_ledger, get_metrics, instrument_kernel
from .encode import (EV_INVOKE, EV_RETURN, EVENT_WIDTH, EncodedHistory,
                     ReturnSteps)

_CACHE: dict[tuple, Any] = {}

#: Floor of the event-axis capacity buckets. Events per history are
#: bounded by 2x the return count plus open invokes, so the event floor
#: tracks 2x the step-bucket floor's scale.
EVENT_BUCKET_FLOOR = 32


def event_bucket(n_events: int, floor: int = EVENT_BUCKET_FLOOR) -> int:
    """{2^k, 1.5*2^k} capacity bucket for the event axis — the event-
    tensor twin of the scheduler's step-length buckets, so nearby
    history sizes share one compiled encoder."""
    from . import wgl3

    return wgl3.step_bucket(max(1, int(n_events)), floor=floor)


def returns_count(enc: EncodedHistory) -> int:
    """Return-step count straight from the event stream (what
    encode_return_steps would report as n_steps) — no table expansion."""
    if enc.n_events == 0:
        return 0
    ev = np.asarray(enc.events[: enc.n_events])
    return int((ev[:, 0] == EV_RETURN).sum())


def _encode_fn(k_slots: int, e_cap: int, r_cap: int):
    """The un-jitted single-history encoder body:
    events i32[e_cap, 6] -> (slot_tabs i32[r_cap, K, 4],
    slot_active bool[r_cap, K], targets i32[r_cap]).

    Mirrors ops.encode.encode_return_steps' vectorized host algorithm
    term-for-term (one-hot cumsums, running last-invoke positions, the
    strictly-before return count), with two deviations forced by static
    shapes, both masked by `valid`: return positions are extracted with
    a fixed-size nonzero (fill rows gather event 0 and are zeroed), and
    the [r_cap] tail beyond the real return count reproduces
    ReturnSteps.padded_to's all-pad rows."""

    def encode(events):
        kinds = events[:, 0]
        slots = events[:, 1]
        sid = jnp.arange(k_slots, dtype=jnp.int32)
        is_inv = kinds == EV_INVOKE
        is_ret = kinds == EV_RETURN
        inv_oh = is_inv[:, None] & (slots[:, None] == sid)
        ret_oh = is_ret[:, None] & (slots[:, None] == sid)
        inv_cum = jnp.cumsum(inv_oh.astype(jnp.int32), axis=0)
        ret_cum = jnp.cumsum(ret_oh.astype(jnp.int32), axis=0)
        pos = jnp.arange(e_cap, dtype=jnp.int32)
        # Last invoke position of each slot at-or-before each event
        # position (host: np.maximum.accumulate over the masked iota).
        last_inv = jax.lax.cummax(
            jnp.where(inv_oh, pos[:, None], -1), axis=0)
        (ret_pos,) = jnp.nonzero(is_ret, size=r_cap, fill_value=0)
        n_ret = jnp.sum(is_ret.astype(jnp.int32))
        valid = jnp.arange(r_cap, dtype=jnp.int32) < n_ret
        # Event p is a return, so "invokes before p" == inv_cum[p];
        # "returns strictly before p" excludes p's own return.
        active = valid[:, None] & (
            inv_cum[ret_pos]
            > (ret_cum[ret_pos] - ret_oh[ret_pos].astype(jnp.int32)))
        last = last_inv[ret_pos]
        tabs = jnp.where(
            (last[:, :, None] >= 0) & valid[:, None, None],
            events[jnp.maximum(last, 0)][:, :, 2:6], 0).astype(jnp.int32)
        targets = jnp.where(valid, slots[ret_pos], -1).astype(jnp.int32)
        return tabs, active, targets

    return encode


def cached_device_encoder(k_slots: int, e_cap: int, r_cap: int):
    """Jitted single-history device encoder for one (K, E, R) geometry,
    instrumented for compile/execute attribution like every production
    kernel (the encoder must not be a telemetry blind spot — its whole
    point is moving seconds between ledger buckets)."""
    key = ("encode", k_slots, e_cap, r_cap)
    if key not in _CACHE:
        _CACHE[key] = instrument_kernel(
            "wgl3-encode", jax.jit(_encode_fn(k_slots, e_cap, r_cap)))
    return _CACHE[key]


def stack_events(encs: Sequence[EncodedHistory], e_cap: int):
    """Host-side half of the batched device encode: pad every event
    stream to the shared capacity, stack to i32[B, e_cap, 6], transfer
    (the ONLY per-launch H2D of the device-encode lane — ~K times
    smaller than the packed table it replaces)."""
    ev = np.stack([e.padded_to(e_cap).events for e in encs])
    nbytes = int(ev.nbytes)
    get_metrics().counter("wgl.h2d_bytes").add(nbytes)
    t0_ns = time.monotonic_ns()
    out = jnp.asarray(ev)
    get_ledger().record_h2d(nbytes, t0_ns, time.monotonic_ns())
    return out


def encode_return_steps_device(enc: EncodedHistory,
                               e_cap: int | None = None,
                               r_cap: int | None = None) -> ReturnSteps:
    """Single-history device encode, fetched back as a host ReturnSteps
    bit-identical to ``encode_return_steps(enc)`` (the encode_mode=2
    routing target and the differential-test subject). `r_cap` pads the
    compiled step axis; the result is trimmed back to the real return
    count so downstream shapes match the host encoder's exactly."""
    t_enc = time.monotonic()
    k = enc.k_slots
    n_ret = returns_count(enc)
    if n_ret == 0:
        return ReturnSteps(
            slot_tabs=np.zeros((0, k, 4), np.int32),
            slot_active=np.zeros((0, k), bool),
            targets=np.zeros((0,), np.int32),
            n_steps=0, n_ops=enc.n_ops, k_slots=k,
            max_pending=enc.max_pending, max_value=enc.max_value)
    if e_cap is None:
        e_cap = event_bucket(enc.n_events)
    if r_cap is None:
        from . import wgl3

        r_cap = wgl3.step_bucket(n_ret, floor=EVENT_BUCKET_FLOOR)
    fn = cached_device_encoder(k, e_cap, r_cap)
    ev_dev = stack_events([enc], e_cap)[0]
    tabs, act, tgt = (np.asarray(x) for x in fn(ev_dev))
    dt_enc = time.monotonic() - t_enc
    get_metrics().counter("encode.encode_s").add(dt_enc)
    get_ledger().record_encode(dt_enc)
    return ReturnSteps(
        slot_tabs=tabs[:n_ret], slot_active=act[:n_ret],
        targets=tgt[:n_ret].astype(np.int32),
        n_steps=n_ret, n_ops=enc.n_ops, k_slots=k,
        max_pending=enc.max_pending, max_value=enc.max_value)


def device_encode_feasible(enc: EncodedHistory) -> bool:
    """Whether the device encoder can take this history at all: the
    event stream must be non-degenerate and the one-hot expansion
    (e_cap * k_slots cells) must stay far inside the element budget a
    single launch is allowed to stack."""
    from .limits import limits

    if enc.n_events == 0:
        return False
    e_cap = event_bucket(enc.n_events)
    return e_cap * max(1, enc.k_slots) <= limits().stack_element_budget
