"""Blocked, sparsity-aware transitive closure — elle's big-history kernel.

The dense squaring kernel (ops/cycles.py) pays O(N^2) memory and
O(N^3 log N) matmul flops on a pad-to-128 [N, N] matrix regardless of
how sparse the dependency graph is; past a few thousand transactions
that is the whole check's cost (ISSUE 11). This module is the closure
counterpart of the wgl3_sparse active-tile engine: the reachability
matrix lives as an [nb, nb] grid of T x T f32 tiles
(T = limits().elle_tile, a multiple of 128 — MXU geometry), and each
squaring round

    R' = min(R + R @ R, 1)

is computed over BLOCK PRODUCTS R[i,k] @ R[k,j] gathered through an
occupancy work list instead of the full block cube:

  * **Occupancy.** A tile is live when any entry is nonzero; the
    eligible product set is {(i,k,j) : occ[i,k] and occ[k,j]}. Products
    with an empty operand tile contribute exactly zero, so the sparse
    round equals the dense round bit-for-bit — the monotone-fixpoint
    argument the wgl3_sparse engine uses, in its simplest form.
  * **Bucketed work list.** The eligible products are gathered into a
    static-capacity work list (jnp.nonzero(size=cap)); the capacity is
    BUCKETED per round ({2^k, 1.5*2^k} from 64, capped at
    limits().elle_worklist_cap) so a round with 50 live products pays
    50-ish block matmuls, not the full static cap.
  * **The crossover.** A round whose eligible count exceeds the work
    list (or whose product density exceeds
    limits().elle_density_threshold_pct of nb^3) runs the plain dense
    squaring for THAT round — the wgl3_sparse direction-optimizing
    switch; reachability is never dropped.
  * **Fixpoint early exit.** The host round loop stops the moment a
    round changes nothing — short-diameter graphs (and the streaming
    engine's warm-started re-checks) converge in a couple of rounds
    where the seed kernel always ran ceil(log2 N) squarings. Each
    round's launch returns (changed, next-round eligibility) packed in
    one tiny fetch, so the loop costs one host round trip per round.
  * **Pallas blocked accumulate.** Where Mosaic compiles (and in
    interpret mode for the tier-1 differential), the gather->matmul->
    scatter of a sparse round runs as ONE pallas program: the work
    list (sorted by destination tile, one zero-init entry per
    destination) is scalar-prefetched, each grid step DMAs its two
    operand tiles and accumulates A @ B into the resident destination
    block — the blocked-matmul shape of SNIPPETS.md [3].

Verdicts are bit-identical to the dense path and the Tarjan oracle by
the fixpoint-uniqueness argument (every round computes exactly
min(R + R @ R, 1)); tests/test_elle_kernels.py pins golden + fuzz
differentials, tile-boundary sizes, the early exit, and the pallas
round in interpret mode (plus a slow-marked real-TPU Mosaic
differential).
"""

from __future__ import annotations

import numpy as np

from .. import obs
from ..obs import instrument_kernel
from .limits import limits

from .cycles import _bucket, _kernel_cache

TILED_KERNEL = "elle-closure-tiled"
TILED_PALLAS_KERNEL = "elle-closure-tiled-pallas"

_WORKLIST_FLOOR = 64


def _tile() -> int:
    """The active tile edge, rounded to the MXU-aligned multiple of 128
    inside the knob's safe range."""
    t = limits().elle_tile
    return max(128, t // 128 * 128)


def pallas_round_available() -> bool:
    """True when the Mosaic blocked-accumulate round can compile here
    (TPU backends; the XLA gather/scatter round is the routed default
    elsewhere)."""
    from . import wgl3_pallas

    return wgl3_pallas.pallas_available()


def _stats_vec(R_new, changed, nb: int):
    """The per-round device stats row fetched by the host loop — packed
    so one tiny fetch answers 'did it change' AND 'how much work next
    round': [changed, next_eligible_count, occupied_tiles]."""
    import jax.numpy as jnp

    occ = jnp.sum(R_new, axis=(2, 3)) > 0
    eligible = occ[:, :, None] & occ[None, :, :]
    return jnp.stack([changed.astype(jnp.int32).astype(jnp.float32),
                      jnp.sum(eligible).astype(jnp.float32),
                      jnp.sum(occ).astype(jnp.float32)])


def _occ_fn(nb: int, T: int):
    """jitted: R f32[nb, nb, T, T] -> the round-0 stats row (changed is
    reported 1 — nothing ran yet)."""
    import jax
    import jax.numpy as jnp

    def occ(R):
        return _stats_vec(R, jnp.bool_(True), nb)

    def build():
        return instrument_kernel("elle-closure-tiled", jax.jit(occ))

    return _kernel_cache().get((TILED_KERNEL, "occ", nb, T), build)


def _dense_round_fn(nb: int, T: int):
    """jitted dense block round: the whole-matrix squaring reshaped
    through the tile layout — the crossover target when the work list
    would overflow or the product set is dense. Donates R (the round
    loop threads it linearly)."""
    import jax
    import jax.numpy as jnp

    n_pad = nb * T

    def round_(R):
        Rf = R.transpose(0, 2, 1, 3).reshape(n_pad, n_pad)
        Rf2 = jnp.minimum(Rf + Rf @ Rf, 1.0)
        R_new = Rf2.reshape(nb, T, nb, T).transpose(0, 2, 1, 3)
        changed = jnp.any(R_new != R)
        return R_new, _stats_vec(R_new, changed, nb)

    def build():
        return instrument_kernel(
            "elle-closure-tiled", jax.jit(round_, donate_argnums=(0,)))

    return _kernel_cache().get((TILED_KERNEL, "dense", nb, T), build)


def _sparse_round_fn(nb: int, T: int, cap: int, use_pallas: bool,
                     interpret: bool = False):
    """jitted sparse block round for one work-list capacity bucket:
    gather the eligible (i, k, j) block products, batched-matmul them,
    scatter-add into the destination tiles, clamp. With `use_pallas`
    the product/accumulate stage runs as one Mosaic program
    (_pallas_accumulate); the XLA form is the routed default. Exact
    either way: padding entries contribute zero. Donates R."""
    import jax
    import jax.numpy as jnp

    nbb = nb * nb

    def round_(R):
        occ = jnp.sum(R, axis=(2, 3)) > 0
        eligible = (occ[:, :, None] & occ[None, :, :]).reshape(-1)
        (flat,) = jnp.nonzero(eligible, size=cap, fill_value=-1)
        valid = flat >= 0
        idx = jnp.where(valid, flat, 0)
        ii = idx // (nb * nb)
        kk = (idx // nb) % nb
        jj = idx % nb
        R_flat = R.reshape(nbb, T, T)
        # Dummy sources/destination for padding entries: one zero tile
        # appended at index nbb; their products are zero and land in
        # the dummy block, so reachability is exact at any fill level.
        sa = jnp.where(valid, ii * nb + kk, nbb)
        sb = jnp.where(valid, kk * nb + jj, nbb)
        dd = jnp.where(valid, ii * nb + jj, nbb)
        if use_pallas:
            acc = _pallas_accumulate(nb, T, cap, interpret)(
                R_flat, sa, sb, dd)
        else:
            Rz = jnp.concatenate(
                [R_flat, jnp.zeros((1, T, T), jnp.float32)])
            A = Rz[sa]
            B = Rz[sb]
            P = jnp.einsum("gab,gbc->gac", A, B,
                           preferred_element_type=jnp.float32)
            acc = jnp.zeros((nbb + 1, T, T), jnp.float32).at[dd].add(P)
        R_new = jnp.minimum(R + acc[:nbb].reshape(nb, nb, T, T), 1.0)
        changed = jnp.any(R_new != R)
        return R_new, _stats_vec(R_new, changed, nb)

    name = TILED_PALLAS_KERNEL if use_pallas else TILED_KERNEL

    def build():
        if use_pallas:
            return instrument_kernel("elle-closure-tiled-pallas",
                                     jax.jit(round_, donate_argnums=(0,)))
        return instrument_kernel("elle-closure-tiled",
                                 jax.jit(round_, donate_argnums=(0,)))

    return _kernel_cache().get(
        (name, "sparse", nb, T, cap, bool(interpret)), build)


def _pallas_accumulate(nb: int, T: int, cap: int, interpret: bool):
    """The Mosaic blocked product-accumulate: one grid step per work
    (or init) entry, work list scalar-prefetched and SORTED by
    destination tile with one zero-init entry per destination first —
    so every output block is visited, initialized exactly once, and
    accumulated while resident (grid steps with equal destinations are
    consecutive). Returns acc f32[nb*nb + 1, T, T] (the last block is
    the padding-entry sink)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nbb = nb * nb
    G = cap + nbb + 1     # product entries + one init entry per block

    def kernel(dd_ref, sa_ref, sb_ref, a_ref, b_ref, o_ref):
        g = pl.program_id(0)

        @pl.when(sa_ref[g] == nbb)
        def _init():
            o_ref[...] = jnp.zeros((1, T, T), jnp.float32)

        @pl.when(sa_ref[g] != nbb)
        def _acc():
            o_ref[...] += jnp.dot(
                a_ref[0], b_ref[0],
                preferred_element_type=jnp.float32)[None]

    def accumulate(R_flat, sa, sb, dd):
        Rz = jnp.concatenate([R_flat, jnp.zeros((1, T, T), jnp.float32)])
        # Init entries: destination d with the dummy source (== nbb,
        # the kernel's "zero this block" marker).
        d_init = jnp.arange(nbb + 1, dtype=dd.dtype)
        s_init = jnp.full((nbb + 1,), nbb, dtype=sa.dtype)
        dd_all = jnp.concatenate([d_init, dd])
        sa_all = jnp.concatenate([s_init, sa])
        sb_all = jnp.concatenate([s_init, sb])
        # Stable sort by destination: init entries (concatenated first)
        # stay first within each destination group.
        order = jnp.argsort(dd_all, stable=True)
        dd_all, sa_all, sb_all = dd_all[order], sa_all[order], sb_all[order]

        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,          # dd, sa, sb — SMEM
            grid=(G,),
            in_specs=[
                pl.BlockSpec((1, T, T),
                             lambda g, dd, sa, sb: (sa[g], 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, T, T),
                             lambda g, dd, sa, sb: (sb[g], 0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((1, T, T),
                                   lambda g, dd, sa, sb: (dd[g], 0, 0),
                                   memory_space=pltpu.VMEM),
        )
        return pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((nbb + 1, T, T), jnp.float32),
            interpret=interpret,
        )(dd_all, sa_all, sb_all, Rz, Rz)

    return accumulate


def closure_tiled(adj: np.ndarray, pallas: bool | None = None,
                  interpret: bool = False
                  ) -> tuple["object", np.ndarray, dict]:
    """Run the blocked fixpoint closure. Returns (R_dev — the converged
    device tile grid f32[nb, nb, T, T] — cyc bool[N], stats dict).
    `pallas=None` auto-selects the Mosaic accumulate where it compiles;
    tests force it with pallas=True, interpret=True on CPU."""
    import jax.numpy as jnp

    n = adj.shape[0]
    T = _tile()
    nb = max(1, -(-n // T))
    n_pad = nb * T
    lim = limits()
    use_pallas = pallas if pallas is not None else pallas_round_available()
    a = np.zeros((n_pad, n_pad), np.float32)
    a[:n, :n] = adj.astype(np.float32)
    R = jnp.asarray(a.reshape(nb, T, nb, T).transpose(0, 2, 1, 3))

    m = obs.get_metrics()
    m.counter("elle.graphs_tiled").add(1)
    stats = {"rounds": 0, "rounds_sparse": 0, "rounds_dense": 0,
             "tile": T, "nb": nb}
    max_rounds = max(1, int(np.ceil(np.log2(n_pad))))
    row = np.asarray(_occ_fn(nb, T)(R))
    m.counter("elle.closure_launches").add(1)
    nb3 = nb * nb * nb
    while row[0] and stats["rounds"] < max_rounds:
        count = int(row[1])
        density_pct = 100.0 * count / nb3
        m.gauge("elle.tile_density").set(density_pct / 100.0)
        if count > lim.elle_worklist_cap \
                or density_pct > lim.elle_density_threshold_pct:
            R, srow = _dense_round_fn(nb, T)(R)
            stats["rounds_dense"] += 1
            m.counter("elle.tiled_rounds_dense").add(1)
        else:
            cap = min(_bucket(max(1, count), _WORKLIST_FLOOR),
                      lim.elle_worklist_cap)
            R, srow = _sparse_round_fn(nb, T, cap, use_pallas,
                                       interpret)(R)
            stats["rounds_sparse"] += 1
            m.counter("elle.tiled_rounds_sparse").add(1)
        m.counter("elle.closure_launches").add(1)
        stats["rounds"] += 1
        # Bounded per-round fetch: one tiny [3] f32 stats row answers
        # both "reached fixpoint?" and "next round's work-list size" —
        # the same host-loop poll discipline as the wgl3 death polls
        # (<= ceil(log2 N) rounds per closure).
        row = np.asarray(srow)
    stats["occupied_tiles"] = int(row[2])
    # Diagonal fetch: gather the nb diagonal tiles' diagonals into ONE
    # O(N)-byte transfer, never the O(N^2) grid.
    diag = np.asarray(jnp.concatenate(
        [jnp.diagonal(R[i, i]) for i in range(nb)]))
    cyc = diag[:n] > 0.5
    return R, cyc, stats


def cycle_mask_tiled(adj: np.ndarray, pallas: bool | None = None,
                     interpret: bool = False) -> np.ndarray:
    """bool[N] cycle mask via the blocked kernel — diagonal-only
    fetch."""
    _R, cyc, _stats = closure_tiled(adj, pallas=pallas,
                                    interpret=interpret)
    return cyc


def reach_and_cycles_tiled(adj: np.ndarray, pallas: bool | None = None,
                           interpret: bool = False
                           ) -> tuple[np.ndarray, np.ndarray]:
    """(reach bool[N, N], cyc bool[N]) via the blocked kernel — for
    callers that need the closure itself (witness reconstruction). The
    O(N^2) fetch happens here and only here."""
    n = adj.shape[0]
    R, cyc, _stats = closure_tiled(adj, pallas=pallas,
                                   interpret=interpret)
    T = R.shape[-1]
    nb = R.shape[0]
    full = np.asarray(R).transpose(0, 2, 1, 3).reshape(nb * T, nb * T)
    return full[:n, :n] > 0.5, cyc
