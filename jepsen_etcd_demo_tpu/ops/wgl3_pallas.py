"""Pallas TPU kernel for the dense subset-lattice WGL search (wgl3).

Same search as ops/wgl3.py (knossos :linear semantics, reference call site
src/jepsen/etcdemo.clj:117 [dep]), fused into ONE kernel per history batch:
the whole return-step scan runs inside the kernel with the reachability
table held on-chip, instead of an XLA `lax.scan` whose per-step closure
round-trips the batched table through HBM.

Why a hand kernel wins here (and what it does differently from wgl3's
XLA formulation):
  * The table u32[S, W] for typical geometry (S=8 states, K=12 slots ⇒
    W=2^7=128 words) is EXACTLY one (8,128) VPU tile. The kernel carries
    it as a loop value — zero HBM traffic between steps; XLA's scan over
    a [B, S, W] batch streams ~1 MiB of table (plus closure temporaries)
    per step.
  * The per-history closure `while_loop` converges independently per
    program. Under `vmap`, XLA lock-steps the loop across the whole batch
    (every history pays the slowest history's round count per step).
  * The mask-bit exposure for slot j >= 5, a [S, hi, 2, lo] reshape in
    XLA (a lane shuffle), becomes a static lane ROLL by 2^(j-5): firing
    slot j moves a config from word w (bit j-5 clear) to word w + 2^(j-5)
    (pltpu.roll + iota mask — the VPU-native formulation).
  * Transition matrices are pre-bitpacked host-side to column masks
    colmask[r, s', j] = bitmask over SOURCE states s (S <= 32 fits u32),
    so the state OR-reduce is S broadcast-selects per slot with no
    scalar loads: sel[s'] = (colmask[:, j] >> s) & 1, a [S,1]x[1,W]
    broadcast against table row s.

Layout contract (prepare_pallas_batch):
  colmask  u32[B, R, Sp, 128]   Sp = S padded to 8 sublanes; lane axis is
                                the slot j (K <= 128); one (8,128) tile
                                per return step.
  targets  i32[B, R]            target slot per return step, -1 = pad.

The kernel is exact (dense table = whole config space, no overflow), so
results match wgl3 bit-for-bit; tests run it in interpreter mode on CPU
against the XLA kernel and the oracle (tests/test_wgl3_pallas.py).

Tuning notes (measured on TPU v5e, 1024x150-op corpus, k=12/S=8; kept
here so the next round doesn't re-run dead ends). Round-4 profiling
(jax.profiler device-busy, not wall: on the tunneled axon backend wall
adds a fixed ~0.1 s dispatch+fetch round trip that is NOT kernel time)
re-attributed the r3 numbers and drove a 2.4x kernel redesign, 110 ms ->
45 ms device time for the grouped corpus launch:
  * The data-dependent fixpoint `while_loop` was ~60% of device time:
    Mosaic pays ~4 us per loop entry/exit (scalar cond round trip +
    carry materialization), and per-sweep popcount reduces rode along.
    Now each step runs a PAIR of sweeps unconditionally, loops on pairs
    only while the pair's 2nd sweep grew (vector compare, one scalar
    cond per step typical), and takes the metrics popcount ONCE after
    convergence. Bit-identical: extra sweeps past the fixpoint are
    idempotent, and converged T gives the same popcount the per-sweep
    loop exited with.
  * Padded step tails were ~40% of all steps (R bucketing): the launch
    now prefetches per-history step counts and bounds the scan trip by
    the group max (`trip = clip(max_len - c*RC, 0, RC)`) — pad steps
    never execute at all.
  * The s-loop's [*,Sp,1]-shaped bit-extract + where(select) chain was
    broadcast-bound: broadcasting the colmask column ONCE per slot to
    full [*,Sp,W] width and selecting with arithmetic masks
    (0 - ((colb >> s) & 1)) dropped 61 -> 45 ms.
  * Dead ends so the next round doesn't re-run them: tree-OR of the
    s-loop partials — no change (16 independent per-vreg chains already
    fill the VPU pipeline); packing 4 targets per SMEM word — no change
    (g3 scalar reads are not a bottleneck: ablating them entirely moved
    0.3 ms); 2-sweep speculation with host-side escalation — dead, the
    flag rate is 100% of corpus histories (every history has at least
    one step needing a 2nd pair, so everything would re-run); G=32/64
    groups — scoped-VMEM OOM (the colmask block + live set crosses the
    16 MB scoped limit), and the old G=32 measurement was already
    neutral; Sp=32 grouping REVISITED with this design (VERDICT r3 item
    3): G=2 compiles once the step chunk halves (the default RC formula
    overshoots scoped VMEM by ~350 KB at G=2·Sp=32) and measures 137 ms
    vs 145 ms per-history on the gset corpus — +6%, not worth the
    routing complexity — while G=4 still OOMs; the gset lane's 1.5x
    target was met by the redesign itself (374 -> 236 ms wall);
    replacing the K-way prune switch (per-history kernel) with dynamic
    shift+roll+select — 12% slower (r3 measurement, still believed);
    Sp=32 SUBLANE-packing (two 32-state tables per 64-sublane block, the
    r4 verdict's one untried shape) — closed by measurement without
    building the kernel, because the r5 overhead probe shows there is no
    overhead pool for ANY packing to reclaim: a 256-history gset corpus
    of ~3-step histories costs 0.406 ms/history wall, which IS the
    irreducible per-launch tunnel RT (0.104 s / 256 — see the wall-vs-
    device note above), so the 150-op lane's 0.935 ms/history splits as
    ~0.41 launch floor + ~0.53 device work; and 0.53 ms/history of
    device work is already BELOW the 16x dense-table work ratio vs the
    Sp=8 grouped lane (32 source-state selects over 4x the rows =>
    16 x 0.047 ms/history = 0.75 predicted). The per-history Sp=32
    kernel thus runs ABOVE the grouped kernel's per-op efficiency;
    sublane-packing would add a rows<32 select per source state (+2 ops
    in the innermost loop) to amortize per-program costs that measure
    near zero. The gset <=0.5 ms/history wall target is unreachable on
    this backend not by kernel shape but by the launch floor itself.
  * Wall vs device (r5): the corpus wall's non-device share is the
    tunnel's per-launch round trip itself — an EMPTY compiled launch +
    one-word fetch measures ~0.104 s, more than the whole wall-minus-
    device gap (~0.06 s), so the single batched launch already sits on
    the floor. Wave-pipelining is a measured dead end on this backend:
    dispatching W sub-batches before any fetch costs ~0.1 s PER WAVE,
    serialized (2 waves 0.20 s, 4 waves 0.44 s, 8 waves 0.85 s vs
    0.15 s single) — async dispatch does not overlap tunnel RTs. The
    bench records empty_launch_s / pipelined_2wave_s every run
    (bench.py _dispatch_floor); on a local-PCIe runtime the same probes
    would show a lower floor and waves worth revisiting.
  * Calibration: a peak microbench (independent 8-chain int32 ALU loop,
    zero memory traffic, 5 ops/chain-iteration) sustains ~4.0 G
    vreg-ops/s (~4.1 T word-ops/s) on this v5e core — the honest VPU
    ceiling for this kernel's op mix, vs the 6.1 T spec-sheet estimate
    bench.py's roofline also reports. A single serial dependent chain
    sustains only ~0.7 G vreg-ops/s, which is why ILP shape (not op
    count) dominates kernel cost here.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..models.base import Model
from ..obs import instrument_kernel, record_check_result
from .encode import EncodedHistory
from .limits import limits
from .wgl3 import DenseConfig, _LO_MASK, batch_arrays3, dense_config


def prepare_pallas_batch(model: Model, cfg: DenseConfig, slot_tabs, slot_active,
                         targets):
    """Host/XLA-side prep: transition matrices -> bit-packed column masks.

    slot_tabs [B,R,K,4] i32, slot_active [B,R,K] bool, targets [B,R] i32
    (the batched return-major arrays of wgl3.batch_arrays3).
    Returns (colmask u32[B,R,Sp,128], targets i32[B,R], lengths i32[B]):
    `lengths` counts each history's real (non-pad) return steps so the
    kernel can bound its scan trip and skip the padded tail entirely
    (pad targets are -1 and always a suffix — wgl3.stack_steps3).
    """
    K, S, off = cfg.k_slots, cfg.n_states, cfg.state_offset
    state_vals = jnp.arange(S, dtype=jnp.int32) - off
    s_ids = jnp.arange(S, dtype=jnp.int32)

    def trans_one(row, active):
        legal, nxt = model.step(state_vals, row[0], row[1], row[2], row[3])
        nxt_row = nxt + off
        ok = legal & (nxt_row >= 0) & (nxt_row < S) & active
        return ok[:, None] & (nxt_row[:, None] == s_ids[None, :])  # [S,S']

    def pack(tabs, act):                      # [R,K,4],[R,K] for one history
        tj = jax.vmap(jax.vmap(trans_one))(tabs, act)      # [R,K,S,S'] bool
        bits = (tj.astype(jnp.uint32)
                << jnp.arange(S, dtype=jnp.uint32)[None, None, :, None])
        colmask = jnp.sum(bits, axis=2, dtype=jnp.uint32)  # [R,K,S'] over s
        colmask = jnp.swapaxes(colmask, 1, 2)              # [R,S',K]
        sp = max(8, (S + 7) // 8 * 8)
        return jnp.pad(colmask, ((0, 0), (0, sp - S), (0, 128 - K)))

    colmask = jax.vmap(pack)(slot_tabs, slot_active)
    tg = targets.astype(jnp.int32)
    lengths = jnp.sum((tg >= 0).astype(jnp.int32), axis=1)
    return colmask, tg, lengths


def _kernel_body(cfg: DenseConfig, resume: bool = False):
    """Per-history kernel. With resume=True the search state enters and
    leaves through operands — extra prefetch `mt` i32[B,5] (dead,
    dead_step, maxf, cfgs, global step offset), extra input T_in and
    extra output T_out — so a host loop (check_steps3_long_pallas) can
    chain launches over step windows: the SMEM prefetch ceiling
    (limits().max_r_pallas) bounds one LAUNCH, not the history. Per-step
    semantics identical either way."""
    K, S, off = cfg.k_slots, cfg.n_states, cfg.state_offset
    W = 1 << (K - 5)
    Sp = max(8, (S + 7) // 8 * 8)
    init_row = None  # bound in closure below

    # NB: every jnp array used by the kernel is constructed INSIDE `body`
    # (pallas kernels may not capture traced constants from build time;
    # Python ints become literals, which is fine).

    def _lane():
        return jax.lax.broadcasted_iota(jnp.int32, (1, W), 1)

    def allowed_mask(t):
        """u32[1, W]: positions whose config mask has bit t CLEAR."""
        full = jnp.uint32(0xFFFFFFFF)
        inword = jnp.uint32(_LO_MASK[4])
        for b in range(3, -1, -1):
            inword = jnp.where(t == b, jnp.uint32(_LO_MASK[b]), inword)
        word_ok = ((_lane() >> jnp.maximum(t - 5, 0)) & 1) == 0
        return jnp.where(t < 5, jnp.broadcast_to(inword, (1, W)),
                         jnp.where(word_ok, full, jnp.uint32(0)))

    def closure(T, cm, allowed):
        """One Gauss-Seidel sweep over all K slots (static unroll).

        The colmask column is broadcast to full [Sp, W] width ONCE per
        slot and the source-state select is an arithmetic mask
        (0 - bit), not a [Sp,1]-shaped where: the narrow-shape variant
        was broadcast-bound (r4 tuning notes)."""
        for j in range(K):
            src = T & allowed                                # [Sp, W]
            colb = jnp.broadcast_to(cm[:, j:j + 1], (Sp, W))  # u32[Sp, W]
            fired = jnp.zeros_like(T)
            for s in range(S):
                selm = (jnp.uint32(0)
                        - ((colb >> jnp.uint32(s)) & jnp.uint32(1)))
                fired = fired | (selm & src[s:s + 1, :])
            if j < 5:
                T = T | ((fired & jnp.uint32(_LO_MASK[j]))
                         << jnp.uint32(1 << j))
            else:
                d = 1 << (j - 5)
                tgt = ((_lane() >> (j - 5)) & 1) == 1        # bit-set lanes
                T = T | jnp.where(tgt, pltpu.roll(fired, d, axis=1),
                                  jnp.uint32(0))
        return T

    def prune(T, t, allowed):
        def br(j):
            def f(_):
                if j < 5:
                    return (T >> jnp.uint32(1 << j)) & allowed
                d = 1 << (j - 5)
                return pltpu.roll(T, W - d, axis=1) & allowed
            return f
        return jax.lax.switch(t, [br(j) for j in range(K)], None)

    # Paired-sweep fixpoint: pairs may overshoot cfg.rounds by one sweep,
    # which is sound because extra sweeps past the fixpoint are
    # idempotent and _require_converging_cap guarantees the cap is never
    # a truncating one (r4 tuning notes — the per-step while_loop entry
    # was ~4 us, so a pair per loop trip halves the scalar conds and
    # drops the per-sweep popcounts entirely).
    MAX_PAIRS = (cfg.rounds + 1) // 2

    def body(ln_ref, *rest):
        """Grid is (B, NC): history b, step-chunk c. The colmask block is
        one RC-step chunk (long histories would blow the 16 MiB VMEM limit
        as a single block); the search state (table + metadata) carries
        across chunks in scratch, which persists over the sequential TPU
        grid. The scan trip is bounded by the history's REAL step count
        (ln_ref scalar prefetch): bucket-pad steps never execute."""
        if resume:
            (mt_ref, tg_ref, cm_ref, Tin_ref, out_ref, Tout_ref, T_s,
             meta_s) = rest
        else:
            mt_ref = Tin_ref = Tout_ref = None
            tg_ref, cm_ref, out_ref, T_s, meta_s = rest
        b = pl.program_id(0)
        c = pl.program_id(1)
        NC = pl.num_programs(1)
        RC = cm_ref.shape[1]

        @pl.when(c == 0)
        def _init():
            if resume:
                # Continue the previous window's search state.
                T_s[:, :] = Tin_ref[0]
                meta_s[0] = mt_ref[b, 0]    # dead
                meta_s[1] = mt_ref[b, 1]    # dead_step (global)
                meta_s[2] = mt_ref[b, 2]    # max_frontier
                meta_s[3] = mt_ref[b, 3]    # configs_explored
            else:
                # Initial table: bit 0 of word 0 in the init-state row
                # (built with iota masks — scatter has no Mosaic
                # lowering).
                rows = jax.lax.broadcasted_iota(jnp.int32, (Sp, W), 0)
                cols = jax.lax.broadcasted_iota(jnp.int32, (Sp, W), 1)
                T_s[:, :] = jnp.where((rows == init_row) & (cols == 0),
                                      jnp.uint32(1), jnp.uint32(0))
                meta_s[0] = 0    # dead
                meta_s[1] = -1   # dead_step
                meta_s[2] = 1    # max_frontier
                meta_s[3] = 0    # configs_explored

        trip = jnp.clip(ln_ref[b] - c * RC, 0, RC)
        # Global step offset: dead_step stays comparable across windows.
        off0 = mt_ref[b, 4] if resume else 0

        def step(i, carry):
            T, dead, dead_step, maxf, cfgs = carry
            r = off0 + c * RC + i
            # trip excludes pads (-1)
            t = jnp.maximum(tg_ref[b, c * RC + i], 0)
            allowed = allowed_mask(t)
            cm = cm_ref[0, i]                                # u32[Sp, 128]

            # One sweep, then PAIRS of sweeps while the last sweep still
            # grew (vector compare; fixpoint detection unchanged, so the
            # result is bit-identical — extra sweeps past the fixpoint
            # are idempotent, and the metrics popcount of a converged
            # table equals the one the per-sweep loop exited with).
            # Single-history steps are often already saturated (first
            # sweep silent): those pay exactly the old 1 sweep + 1 cond,
            # while multi-sweep steps pay roughly half the old scalar
            # conds.
            T1 = closure(T, cm, allowed)

            def wbody(st):
                Tw, _ch, pairs = st
                Ta = closure(Tw, cm, allowed)
                Tb = closure(Ta, cm, allowed)
                return Tb, jnp.any(Ta != Tb), pairs + 1

            def wcond(st):
                return st[1] & (st[2] < MAX_PAIRS)

            T, _ch, _p = jax.lax.while_loop(
                wcond, wbody, (T1, jnp.any(T1 != T), jnp.int32(0)))
            n = jnp.sum(jax.lax.population_count(T), dtype=jnp.int32)

            pruned = prune(T, t, allowed)
            alive = jnp.any(pruned != 0)
            died = ~dead & ~alive
            dead = dead | died
            T_new = jnp.where(dead, jnp.zeros_like(pruned), pruned)
            return (T_new, dead,
                    jnp.where(died & (dead_step < 0), r, dead_step),
                    jnp.maximum(maxf, n),
                    cfgs + n)

        # cfgs accumulates as i32 (a scalar f32 bitcast has no Mosaic
        # lowering); exact up to 2^31 summed configs, beyond which the f32
        # accumulator of the XLA kernel is approximate anyway.
        init = (T_s[:, :], meta_s[0] != 0, meta_s[1], meta_s[2], meta_s[3])
        T, dead, dead_step, maxf, cfgs = jax.lax.fori_loop(0, trip, step,
                                                           init)
        T_s[:, :] = T
        meta_s[0] = dead.astype(jnp.int32)
        meta_s[1] = dead_step
        meta_s[2] = maxf
        meta_s[3] = cfgs

        # ONE flat whole-[5B] 1-D SMEM output block, each program writing
        # its 5 slots (the wgl3 PACKED_FIELDS layout, so the host unpacks
        # both kernels' results identically). Shape matters enormously
        # here: separate [B] output blocks (or one 2-D [B,5] block) cost
        # ~0.33 s/launch at B=256 in per-program block flushes — 3x the
        # whole search — and the TPU lowering rejects 1-element blocks
        # outright, so per-program blocks are not an option either.
        @pl.when(c == NC - 1)
        def _emit():
            # jtflow: packed-width=5 wgl3.PACKED_FIELDS
            out_ref[5 * b + 0] = jnp.where(dead, 0, 1).astype(jnp.int32)
            out_ref[5 * b + 1] = jnp.int32(0)  # overflow: impossible (dense)
            out_ref[5 * b + 2] = dead_step
            out_ref[5 * b + 3] = maxf
            out_ref[5 * b + 4] = cfgs
            if resume:
                Tout_ref[0] = T_s[:, :]

    def bind(row):
        nonlocal init_row
        init_row = row
        return body

    return bind


def local_pallas_launcher_resumable(model: Model, cfg: DenseConfig,
                                    interpret: bool = False):
    """launch(R) for the RESUMABLE per-history kernel (B=1 windows):
    jitted (ln i32[1], mt i32[1,5], tg i32[1,R], cm u32[1,R,Sp,128],
    Tin u32[1,Sp,W], end i32) -> (out i32[5], Tout u32[1,Sp,W],
    mt_next i32[1,5]). The host loop in check_steps3_long_pallas chains
    windows, feeding (Tout, mt_next) straight into the next launch — the
    whole chain is device-side and ONE compiled program per geometry."""
    max_k = limits().max_k_pallas
    if cfg.k_slots > max_k:
        raise ValueError(f"pallas kernel supports k_slots <= {max_k}, "
                         f"got {cfg.k_slots}")
    _require_converging_cap(cfg)
    Sp = max(8, (cfg.n_states + 7) // 8 * 8)
    W = 1 << (cfg.k_slots - 5)
    row = int(model.init_state()) + cfg.state_offset
    kernel = _kernel_body(cfg, resume=True)(row)

    import functools

    @functools.lru_cache(maxsize=None)
    def launch(R: int):
        RC = min(R, limits().pallas_step_chunk)
        NC = (R + RC - 1) // RC
        R_pad = NC * RC
        grid_spec = pltpu.PrefetchScalarGridSpec(
            # lengths [1] + meta [1,5] + targets [1,R_pad], all SMEM
            num_scalar_prefetch=3,
            grid=(1, NC),
            in_specs=[
                pl.BlockSpec((1, RC, Sp, 128),
                             lambda b, c, ln, mt, tg: (b, c, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, Sp, W),
                             lambda b, c, ln, mt, tg: (b, 0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=[
                pl.BlockSpec((5,), lambda b, c, ln, mt, tg: (0,),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((1, Sp, W),
                             lambda b, c, ln, mt, tg: (b, 0, 0),
                             memory_space=pltpu.VMEM),
            ],
            scratch_shapes=[
                pltpu.VMEM((Sp, W), jnp.uint32),   # table carry
                pltpu.SMEM((4,), jnp.int32),        # dead/step/maxf/cfgs
            ],
        )

        def run(ln, mt, tg, cm, Tin, end):
            if R_pad != R:
                tg = jnp.pad(tg, ((0, 0), (0, R_pad - R)),
                             constant_values=-1)
                cm = jnp.pad(cm, ((0, 0), (0, R_pad - R), (0, 0), (0, 0)))
            out, Tout = pl.pallas_call(
                kernel,
                grid_spec=grid_spec,
                out_shape=[jax.ShapeDtypeStruct((5,), jnp.int32),
                           jax.ShapeDtypeStruct((1, Sp, W), jnp.uint32)],
                interpret=interpret,
            )(ln, mt, tg, cm, Tin)
            # The NEXT window's metadata, chained device-side INSIDE the
            # jit. `end` (the global step offset after this window) is an
            # OPERAND, not a Python int: embedding it as a constant gave
            # every window its own one-off XLA program, and on a remote-
            # compile backend those tiny compiles (~2 s each over the
            # tunnel) dwarfed the kernel compile itself — the r4 "16.6 s
            # cold" was 5 windows of constant-baked stack() programs, not
            # Mosaic (measured r5: prep 1.5 s + kernel 1.8 s + first
            # sweep 10.5 s -> 0.4 s warm).
            mt_next = jnp.stack([1 - out[0], out[2], out[3], out[4],
                                 end])[None]
            return out, Tout, mt_next

        # obs/ compile/execute attribution: lru_cache gives one wrapper
        # (and so one first-call flag) per compiled window shape R.
        # mt/Tin are DONATED: the host loop threads them linearly
        # (window N's outputs are window N+1's inputs and nothing else
        # reads the old buffers), so the table aliases in place across
        # the whole chain.
        return instrument_kernel("wgl3-pallas-resumable",
                                 jax.jit(run, donate_argnums=(1, 4)))

    return launch


def check_steps3_long_pallas(rs, model: Model, cfg: DenseConfig,
                             time_budget_s: float | None = None,
                             interpret: bool = False) -> dict:
    """Host-chained fused-kernel sweep for single histories whose step
    count exceeds one launch's SMEM prefetch budget (the 100k-op lane):
    windows of limits().max_r_pallas steps, the search state (table +
    metadata + global step offset) carried between launches as operands.
    Same verdict/metrics contract as wgl3.check_steps3_long, with the
    kernel-side i32 configs accumulator (exact where the XLA path's f32
    partial sums are approximate past 2^24).

    Geometries the density signal selects sparse for (the SAME
    sparse_plan policy the XLA engine routes by — prefer-sparse
    sparse_mode=2 forces it, auto mode engages past the measured
    crossover) take the sparse work-list kernel instead
    (check_steps3_long_pallas_sparse) — bit-identical verdicts, plus
    the sweep telemetry record. This is the routed DEFAULT since
    ISSUE 10; sparse_mode=1 keeps the dense kernel unconditionally."""
    import time as _time

    from . import wgl3
    from .wgl import verdict

    lim = limits()
    if pallas_sparse_selected(cfg):
        return check_steps3_long_pallas_sparse(
            rs, model, cfg, time_budget_s=time_budget_s,
            interpret=interpret)
    t0 = _time.monotonic()
    # Window pads never execute — the kernel bounds its trip with the
    # prefetched length.
    window = _long_window(lim)
    launch = _cached_resumable_launcher(model, cfg, interpret)
    prep = _cached_prep(model, cfg)
    Sp = max(8, (cfg.n_states + 7) // 8 * 8)
    W = 1 << (cfg.k_slots - 5)
    Tin = np.zeros((1, Sp, W), np.uint32)
    Tin[0, int(model.init_state()) + cfg.state_offset, 0] = 1
    Tin = jnp.asarray(Tin)
    meta = jnp.asarray(np.array([[0, -1, 1, 0, 0]], np.int32))
    n = rs.n_steps
    if n == 0:
        # The initial state trivially survives an empty history (same
        # contract as the XLA path finalizing its init carry).
        return {"survived": True, "overflow": False, "dead_step": -1,
                "max_frontier": 1, "configs_explored": 0, "valid": True}
    out = None
    # Unbudgeted: all windows dispatch ASYNC, metadata chained
    # device-side, ONE fetch at the end (a dead table makes the
    # remaining windows near-free — empty closures — so no early-exit
    # fetch: on a tunneled backend it would cost more than the sweep).
    # Budgeted: sync per window so the budget check sees device time —
    # overshoot bounded by one window, same contract as the XLA rung.
    for w0 in range(0, n, window):
        if (time_budget_s is not None
                and _time.monotonic() - t0 > time_budget_s):
            return {"valid": "unknown", "survived": False, "overflow": True,
                    "dead_step": -1, "max_frontier": -1,
                    "configs_explored": -1, "kernel": "exhausted",
                    "error": f"pallas long sweep exceeded its "
                             f"{time_budget_s:.0f}s time budget at return "
                             f"step {w0}"}
        wn = min(window, n - w0)
        sl = slice(w0, w0 + wn)
        pad = ((0, window - wn),)
        tg = np.pad(rs.targets[sl], pad, constant_values=-1)[None]
        tabs = np.pad(rs.slot_tabs[sl],
                      pad + ((0, 0), (0, 0)))[None]
        act = np.pad(rs.slot_active[sl], pad + ((0, 0),))[None]
        cm, tgd, ln = prep(jnp.asarray(tabs), jnp.asarray(act),
                           jnp.asarray(tg))
        out, Tin, meta = launch(window)(
            ln, meta, tgd, cm, Tin, jnp.asarray(w0 + wn, jnp.int32))
        if time_budget_s is not None:
            np.asarray(out)   # sync: bound overshoot by one window
    out_np = np.asarray(out)
    cfgs = int(out_np[4])
    if cfgs < 0:
        # The i32 accumulator wrapped across windows: saturate, matching
        # the XLA path's clip of its (equally approximate past 2^24) f32
        # partial sums. A wrapped-back-to-positive count is undetectable
        # here — both paths' counters are documented approximate at this
        # scale; verdict fields are unaffected.
        cfgs = 2**31 - 1
    res = {
        "survived": bool(out_np[0]),
        "overflow": False,
        "dead_step": int(out_np[2]),
        "max_frontier": int(out_np[3]),
        "configs_explored": cfgs,
    }
    res["valid"] = verdict(res)
    record_check_result(res)
    return res


def _long_window(lim) -> int:
    """Window length of the host-chained resumable sweeps: the largest
    step BUCKET that fits one launch's SMEM prefetch ceiling
    (lim.max_r_pallas), so every window reuses ONE compiled shape; a
    sub-64 cap skips bucketing entirely. One copy shared by the dense
    and sparse long sweeps so they window — and cache compiled window
    shapes — identically."""
    from . import wgl3

    window = lim.max_r_pallas
    if window >= 64:
        b = 64
        while wgl3.step_bucket(b + 1) <= lim.max_r_pallas:
            b = wgl3.step_bucket(b + 1)
        window = b
    return window


# -- sparse work-list kernel (opt-in: limits().sparse_mode == 2) -----------

SPARSE_BLOCK_LANES = 128   # one VPU lane-tile of packed words per block


def pallas_sparse_blocks(cfg: DenseConfig) -> int:
    """Work-list block count of the sparse pallas kernel for this
    geometry, or 0 when it cannot engage: the table must span at least
    two 128-lane blocks (K >= 13) inside the pallas envelope, and the
    sweep cap must be converging (same constraint as the dense paired
    sweeps). NOTE the envelope means sparsity buys less here than in the
    XLA engine (K <= max_k_pallas caps the table at a handful of lane
    tiles, and per-block scalar control costs ~the block's own vector
    work — the r4 tuning notes' overhead analysis); the kernel is
    therefore OPT-IN via sparse_mode=2, and K > max_k_pallas geometries
    take the XLA/lattice sparse engine, which is where the 2^K waste
    actually lives."""
    if cfg.k_slots > limits().max_k_pallas:
        return 0
    if cfg.max_rounds and cfg.max_rounds < cfg.k_slots:
        return 0
    w = 1 << (cfg.k_slots - 5)
    nb = w // SPARSE_BLOCK_LANES
    return nb if nb >= 2 else 0


def pallas_sparse_selected(cfg: DenseConfig) -> bool:
    """Routing predicate of the pallas long sweep: take the sparse
    work-list kernel wherever the DENSITY SIGNAL already selects sparse
    for this geometry — literally the XLA engine's own sparse_plan
    policy (sparse_mode 0 engages past the measured sparse_min_tiles
    crossover, 2 forces it, 1 disables) — provided the table spans
    work-list blocks at all. ISSUE 10 flipped this from the old
    explicit sparse_mode=2 opt-in: a tuned profile that lowers the
    crossover (tune/probes.py `sparse` and `pallas` groups) now routes
    the Mosaic work-list kernel by default, no operator pin needed."""
    if not pallas_sparse_blocks(cfg):
        return False
    from .wgl3_sparse import sparse_plan

    return sparse_plan(cfg) is not None


def _kernel_body_sparse_resumable(cfg: DenseConfig, nb: int,
                                  thresh_blocks: int):
    """Resumable per-history kernel with the sparse active-block sweep:
    each closure round builds an SMEM WORK LIST of live 128-lane blocks
    (one pass of per-block any-nonzero scalar probes), then sweeps only
    the listed blocks — in-word and in-block mask bits expand locally
    with Gauss-Seidel chaining, block-index bits read-modify-write the
    destination block of the table carry directly (the fori over the
    work list is sequential, so the RMW is race-free). Rounds whose live
    count crosses `thresh_blocks` run the dense closure instead (the
    direction-optimizing switch; the list always has capacity for all
    `nb` blocks, so overflow cannot occur here). Same fixpoint, same
    metadata contract as _kernel_body(resume=True) widened to 8 slots:
    [dead, dead_step, maxf, cfgs, offset, live_sum, sparse_steps,
    real_steps]."""
    K, S, off = cfg.k_slots, cfg.n_states, cfg.state_offset
    W = 1 << (K - 5)
    Sp = max(8, (S + 7) // 8 * 8)
    BLK = SPARSE_BLOCK_LANES
    bbits = BLK.bit_length() - 1          # 7: lane bits inside a block
    assert nb * BLK == W and nb >= 2
    # No init_row/bind() here: this kernel is resume-only — the table
    # always enters through the Tin operand (the host seeds window 0).

    def _lane():
        return jax.lax.broadcasted_iota(jnp.int32, (1, BLK), 1)

    def _lane_full():
        return jax.lax.broadcasted_iota(jnp.int32, (1, W), 1)

    def allowed_full(t):
        full = jnp.uint32(0xFFFFFFFF)
        inword = jnp.uint32(_LO_MASK[4])
        for b in range(3, -1, -1):
            inword = jnp.where(t == b, jnp.uint32(_LO_MASK[b]), inword)
        word_ok = ((_lane_full() >> jnp.maximum(t - 5, 0)) & 1) == 0
        return jnp.where(t < 5, jnp.broadcast_to(inword, (1, W)),
                         jnp.where(word_ok, full, jnp.uint32(0)))

    def allowed_block(b, t):
        """u32[1, BLK]: the allowed mask restricted to block b (global
        word index = b * BLK + lane)."""
        full = jnp.uint32(0xFFFFFFFF)
        inword = jnp.uint32(_LO_MASK[4])
        for k in range(3, -1, -1):
            inword = jnp.where(t == k, jnp.uint32(_LO_MASK[k]), inword)
        lane_g = b * BLK + _lane()
        word_ok = ((lane_g >> jnp.maximum(t - 5, 0)) & 1) == 0
        return jnp.where(t < 5, jnp.broadcast_to(inword, (1, BLK)),
                         jnp.where(word_ok, full, jnp.uint32(0)))

    def fire_slot(cm, j, src):
        """OR-reduce over source states for slot j, any width: the
        colmask column broadcast + arithmetic-select formulation of the
        dense closure (r4 tuning notes)."""
        colb = jnp.broadcast_to(cm[:, j:j + 1], (Sp, src.shape[-1]))
        fired = jnp.zeros_like(src)
        for s in range(S):
            selm = (jnp.uint32(0)
                    - ((colb >> jnp.uint32(s)) & jnp.uint32(1)))
            fired = fired | (selm & src[s:s + 1, :])
        return fired

    def dense_closure(T, cm, allowed):
        """One full-width Gauss-Seidel sweep — the dense fallback round
        (same algebra as _kernel_body's closure)."""
        for j in range(K):
            src = T & allowed
            fired = fire_slot(cm, j, src)
            if j < 5:
                T = T | ((fired & jnp.uint32(_LO_MASK[j]))
                         << jnp.uint32(1 << j))
            else:
                d = 1 << (j - 5)
                tgt = ((_lane_full() >> (j - 5)) & 1) == 1
                T = T | jnp.where(tgt, pltpu.roll(fired, d, axis=1),
                                  jnp.uint32(0))
        return T

    def body(ln_ref, mt_ref, tg_ref, cm_ref, Tin_ref, out_ref, Tout_ref,
             T_s, meta_s, wl_s):
        b0 = pl.program_id(0)
        c = pl.program_id(1)
        NC = pl.num_programs(1)
        RC = cm_ref.shape[1]

        @pl.when(c == 0)
        def _init():
            T_s[:, :] = Tin_ref[0]
            for i, slot in enumerate((0, 1, 2, 3, 5, 6, 7)):
                meta_s[i] = mt_ref[b0, slot]

        trip = jnp.clip(ln_ref[b0] - c * RC, 0, RC)
        off0 = mt_ref[b0, 4]

        def count_live(T):
            def probe(bi, cnt):
                blk = jax.lax.dynamic_slice(T, (0, bi * BLK), (Sp, BLK))
                return cnt + jnp.any(blk != 0).astype(jnp.int32)
            return jax.lax.fori_loop(0, nb, probe, jnp.int32(0))

        def build_worklist(T):
            def probe(bi, cnt):
                blk = jax.lax.dynamic_slice(T, (0, bi * BLK), (Sp, BLK))
                liveb = jnp.any(blk != 0)

                @pl.when(liveb)
                def _():
                    wl_s[cnt] = bi
                return cnt + liveb.astype(jnp.int32)
            return jax.lax.fori_loop(0, nb, probe, jnp.int32(0))

        def step(i, carry):
            (T, dead, dead_step, maxf, cfgs, live_sum, sp_steps,
             real_steps) = carry
            r = off0 + c * RC + i
            t = jnp.maximum(tg_ref[b0, c * RC + i], 0)
            allowed = allowed_full(t)
            cm = cm_ref[0, i]                                # u32[Sp, 128]

            def sparse_sweep(T):
                def do_blk(wi, T):
                    bi = wl_s[wi]
                    blk = jax.lax.dynamic_slice(T, (0, bi * BLK),
                                                (Sp, BLK))
                    ab = allowed_block(bi, t)
                    newblk = blk
                    src = blk & ab
                    for j in range(min(K, 5 + bbits)):
                        fired = fire_slot(cm, j, src)
                        if j < 5:
                            newblk = newblk | (
                                (fired & jnp.uint32(_LO_MASK[j]))
                                << jnp.uint32(1 << j))
                        else:
                            d = 1 << (j - 5)
                            tgt = ((_lane() >> (j - 5)) & 1) == 1
                            newblk = newblk | jnp.where(
                                tgt, pltpu.roll(fired, d, axis=1),
                                jnp.uint32(0))
                        src = newblk & ab   # Gauss-Seidel inside the block
                    T = jax.lax.dynamic_update_slice(T, newblk,
                                                     (0, bi * BLK))
                    for j in range(5 + bbits, K):
                        # Block-index bit: RMW the destination block.
                        bb = j - 5 - bbits
                        fired = fire_slot(cm, j, src)
                        fired = jnp.where(((bi >> bb) & 1) == 0, fired,
                                          jnp.uint32(0))
                        dest = bi | (1 << bb)
                        dblk = jax.lax.dynamic_slice(T, (0, dest * BLK),
                                                     (Sp, BLK))
                        T = jax.lax.dynamic_update_slice(
                            T, dblk | fired, (0, dest * BLK))
                    return T
                live = build_worklist(T)
                return jax.lax.fori_loop(0, live, do_blk, T)

            def wbody(st):
                Tw, _ch, rounds, sp_rounds = st
                live = count_live(Tw)
                take = live <= thresh_blocks
                Tn = jax.lax.cond(take, sparse_sweep,
                                  lambda T: dense_closure(T, cm, allowed),
                                  Tw)
                return (Tn, jnp.any(Tn != Tw), rounds + 1,
                        sp_rounds + take.astype(jnp.int32))

            def wcond(st):
                return st[1] & (st[2] < cfg.rounds)

            T, _ch, rounds, sp_rounds = jax.lax.while_loop(
                wcond, wbody, (T, jnp.bool_(True), jnp.int32(0),
                               jnp.int32(0)))
            n = jnp.sum(jax.lax.population_count(T), dtype=jnp.int32)
            live_fin = count_live(T)

            # Prune: full-width switch, same as the dense kernel.
            def br(j):
                def f(_):
                    if j < 5:
                        return (T >> jnp.uint32(1 << j)) & allowed
                    d = 1 << (j - 5)
                    return pltpu.roll(T, W - d, axis=1) & allowed
                return f
            pruned = jax.lax.switch(t, [br(j) for j in range(K)], None)
            alive = jnp.any(pruned != 0)
            died = ~dead & ~alive
            dead = dead | died
            T_new = jnp.where(dead, jnp.zeros_like(pruned), pruned)
            return (T_new, dead,
                    jnp.where(died & (dead_step < 0), r, dead_step),
                    jnp.maximum(maxf, n), cfgs + n,
                    live_sum + live_fin,
                    sp_steps + (sp_rounds == rounds).astype(jnp.int32),
                    real_steps + 1)

        init = (T_s[:, :], meta_s[0] != 0, meta_s[1], meta_s[2], meta_s[3],
                meta_s[4], meta_s[5], meta_s[6])
        (T, dead, dead_step, maxf, cfgs, live_sum, sp_steps,
         real_steps) = jax.lax.fori_loop(0, trip, step, init)
        T_s[:, :] = T
        meta_s[0] = dead.astype(jnp.int32)
        meta_s[1] = dead_step
        meta_s[2] = maxf
        meta_s[3] = cfgs
        meta_s[4] = live_sum
        meta_s[5] = sp_steps
        meta_s[6] = real_steps

        @pl.when(c == NC - 1)
        def _emit():
            out_ref[0] = jnp.where(dead, 0, 1).astype(jnp.int32)
            out_ref[1] = jnp.int32(0)   # overflow: impossible (dense table)
            out_ref[2] = dead_step
            out_ref[3] = maxf
            out_ref[4] = cfgs
            out_ref[5] = live_sum
            out_ref[6] = sp_steps
            out_ref[7] = real_steps
            Tout_ref[0] = T_s[:, :]

    return body


def local_pallas_launcher_sparse_resumable(model: Model, cfg: DenseConfig,
                                           interpret: bool = False):
    """launch(R) for the SPARSE resumable kernel: jitted (ln i32[1],
    mt i32[1,8], tg i32[1,R], cm u32[1,R,Sp,128], Tin u32[1,Sp,W], end)
    -> (out i32[8], Tout, mt_next i32[1,8]) — the 8-slot twin of
    local_pallas_launcher_resumable, carrying the sweep telemetry
    (live-block sum, sparse-step count, real steps) through the window
    chain alongside the verdict metadata."""
    nb = pallas_sparse_blocks(cfg)
    if not nb:
        raise ValueError(f"sparse pallas kernel infeasible for "
                         f"k_slots={cfg.k_slots}")
    _require_converging_cap(cfg)
    lim = limits()
    thresh = (nb if lim.sparse_mode == 2
              else max(1, nb * lim.sparse_density_threshold_pct // 100))
    Sp = max(8, (cfg.n_states + 7) // 8 * 8)
    W = 1 << (cfg.k_slots - 5)
    kernel = _kernel_body_sparse_resumable(cfg, nb, thresh)

    import functools

    @functools.lru_cache(maxsize=None)
    def launch(R: int):
        RC = min(R, limits().pallas_step_chunk)
        NC = (R + RC - 1) // RC
        R_pad = NC * RC
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(1, NC),
            in_specs=[
                pl.BlockSpec((1, RC, Sp, 128),
                             lambda b, c, ln, mt, tg: (b, c, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, Sp, W),
                             lambda b, c, ln, mt, tg: (b, 0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=[
                pl.BlockSpec((8,), lambda b, c, ln, mt, tg: (0,),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((1, Sp, W),
                             lambda b, c, ln, mt, tg: (b, 0, 0),
                             memory_space=pltpu.VMEM),
            ],
            scratch_shapes=[
                pltpu.VMEM((Sp, W), jnp.uint32),   # table carry
                pltpu.SMEM((7,), jnp.int32),        # metadata carry
                pltpu.SMEM((nb,), jnp.int32),       # the block work list
            ],
        )

        def run(ln, mt, tg, cm, Tin, end):
            if R_pad != R:
                tg = jnp.pad(tg, ((0, 0), (0, R_pad - R)),
                             constant_values=-1)
                cm = jnp.pad(cm, ((0, 0), (0, R_pad - R), (0, 0), (0, 0)))
            out, Tout = pl.pallas_call(
                kernel,
                grid_spec=grid_spec,
                out_shape=[jax.ShapeDtypeStruct((8,), jnp.int32),
                           jax.ShapeDtypeStruct((1, Sp, W), jnp.uint32)],
                interpret=interpret,
            )(ln, mt, tg, cm, Tin)
            mt_next = jnp.stack([1 - out[0], out[2], out[3], out[4], end,
                                 out[5], out[6], out[7]])[None]
            return out, Tout, mt_next

        return instrument_kernel("wgl3-pallas-sparse-resumable",
                                 jax.jit(run, donate_argnums=(1, 4)))

    return launch


def _cached_sparse_resumable_launcher(model: Model, cfg: DenseConfig,
                                      interpret: bool = False):
    lim = limits()
    key = ("pallas-sparse-resumable", model.cache_key(), cfg, interpret,
           lim.sparse_mode, lim.sparse_density_threshold_pct)
    if key not in _CACHE:
        _CACHE[key] = local_pallas_launcher_sparse_resumable(
            model, cfg, interpret)
    return _CACHE[key]


def check_steps3_long_pallas_sparse(rs, model: Model, cfg: DenseConfig,
                                    time_budget_s: float | None = None,
                                    interpret: bool = False) -> dict:
    """Host-chained SPARSE fused-kernel sweep: the work-list kernel's
    twin of check_steps3_long_pallas (same windowing, same budget
    contract, bit-identical verdicts), plus the sweep-mode/live-block
    telemetry record. Routed by default wherever the density signal
    selects sparse (pallas_sparse_selected — the ISSUE 10 flip;
    sparse_mode=2 still forces it for measurement)."""
    import time as _time

    from . import wgl3
    from .wgl import verdict

    nb = pallas_sparse_blocks(cfg)
    if not nb:
        raise ValueError(f"sparse pallas kernel infeasible for "
                         f"k_slots={cfg.k_slots}")
    t0 = _time.monotonic()
    lim = limits()
    window = _long_window(lim)
    launch = _cached_sparse_resumable_launcher(model, cfg, interpret)
    prep = _cached_prep(model, cfg)
    Sp = max(8, (cfg.n_states + 7) // 8 * 8)
    W = 1 << (cfg.k_slots - 5)
    Tin = np.zeros((1, Sp, W), np.uint32)
    Tin[0, int(model.init_state()) + cfg.state_offset, 0] = 1
    Tin = jnp.asarray(Tin)
    meta = jnp.asarray(np.array([[0, -1, 1, 0, 0, 0, 0, 0]], np.int32))
    n = rs.n_steps
    if n == 0:
        return {"survived": True, "overflow": False, "dead_step": -1,
                "max_frontier": 1, "configs_explored": 0, "valid": True}
    out = None
    for w0 in range(0, n, window):
        if (time_budget_s is not None
                and _time.monotonic() - t0 > time_budget_s):
            return {"valid": "unknown", "survived": False, "overflow": True,
                    "dead_step": -1, "max_frontier": -1,
                    "configs_explored": -1, "kernel": "exhausted",
                    "error": f"sparse pallas long sweep exceeded its "
                             f"{time_budget_s:.0f}s time budget at return "
                             f"step {w0}"}
        wn = min(window, n - w0)
        sl = slice(w0, w0 + wn)
        pad = ((0, window - wn),)
        tg = np.pad(rs.targets[sl], pad, constant_values=-1)[None]
        tabs = np.pad(rs.slot_tabs[sl], pad + ((0, 0), (0, 0)))[None]
        act = np.pad(rs.slot_active[sl], pad + ((0, 0),))[None]
        cm, tgd, ln = prep(jnp.asarray(tabs), jnp.asarray(act),
                           jnp.asarray(tg))
        out, Tin, meta = launch(window)(
            ln, meta, tgd, cm, Tin, jnp.asarray(w0 + wn, jnp.int32))
        if time_budget_s is not None:
            np.asarray(out)
    out_np = np.asarray(out)
    cfgs = int(out_np[4])
    if cfgs < 0:
        cfgs = 2**31 - 1
    res = {
        "survived": bool(out_np[0]),
        "overflow": False,
        "dead_step": int(out_np[2]),
        "max_frontier": int(out_np[3]),
        "configs_explored": cfgs,
        "kernel": "wgl3-dense-pallas-sparse-chunked",
    }
    res["sweep"] = wgl3.sweep_summary(
        cfg, live_sum=float(max(0, int(out_np[5]))),
        real_steps=int(out_np[7]), sparse_steps=int(out_np[6]),
        tiling=(SPARSE_BLOCK_LANES, nb))
    res["live_tile_ratio"] = res["sweep"]["live_tile_ratio"]
    res["valid"] = verdict(res)
    record_check_result(res)
    return res


def _cached_resumable_launcher(model: Model, cfg: DenseConfig,
                               interpret: bool = False):
    key = ("pallas-resumable", model.cache_key(), cfg, interpret)
    if key not in _CACHE:
        _CACHE[key] = local_pallas_launcher_resumable(model, cfg,
                                                      interpret)
    return _CACHE[key]


def _cached_prep(model: Model, cfg: DenseConfig):
    import functools

    key = ("pallas-prep", model.cache_key(), cfg)
    if key not in _CACHE:
        _CACHE[key] = instrument_kernel(
            "wgl3-pallas-prep",
            jax.jit(functools.partial(prepare_pallas_batch, model, cfg)))
    return _CACHE[key]


def _require_converging_cap(cfg: DenseConfig) -> None:
    """The paired-sweep loops assume cfg.rounds never TRUNCATES the
    closure (pairs can overshoot a sub-convergence cap, diverging from
    the XLA kernel's exact per-sweep cut-off). With the default
    max_rounds=0 the cap is k_slots, which provably bounds the fixpoint
    (each firing sets a distinct slot bit), so this only rejects explicit
    sub-convergence caps — no production config sets one."""
    if cfg.max_rounds and cfg.max_rounds < cfg.k_slots:
        raise ValueError(
            f"pallas kernels require a converging sweep cap: "
            f"max_rounds={cfg.max_rounds} < k_slots={cfg.k_slots} would "
            f"truncate the closure; use the XLA kernel for truncated "
            f"sweeps")


def local_pallas_launcher(model: Model, cfg: DenseConfig,
                          interpret: bool = False):
    """The pallas-call half of the checker: launch(B, R) -> jitted
    (ln i32[B], tg i32[B,R], cm u32[B,R,Sp,128]) -> i32[B,5]. Exposed
    separately so the mesh-sharded form (parallel/dense.py) can run it
    under shard_map, each device launching its own (B/D, NC) grid over
    its batch shard. `ln` is the per-history real step count
    (prepare_pallas_batch's third output) bounding the kernel's scan
    trip."""
    max_k = limits().max_k_pallas
    if cfg.k_slots > max_k:
        raise ValueError(f"pallas kernel supports k_slots <= {max_k}, "
                         f"got {cfg.k_slots}")
    _require_converging_cap(cfg)
    Sp = max(8, (cfg.n_states + 7) // 8 * 8)
    W = 1 << (cfg.k_slots - 5)
    row = int(model.init_state()) + cfg.state_offset
    kernel = _kernel_body(cfg)(row)

    import functools

    @functools.lru_cache(maxsize=None)
    def launch(B: int, R: int):
        # Chunk the step axis: one colmask block of RC steps per grid
        # iteration (a whole 10k-step history as a single block would need
        # 32 MiB of VMEM against the 16 MiB limit); search state carries
        # across chunks in scratch.
        RC = min(R, limits().pallas_step_chunk)
        NC = (R + RC - 1) // RC
        R_pad = NC * RC
        grid_spec = pltpu.PrefetchScalarGridSpec(
            # lengths [B] + targets [B,R_pad], both whole in SMEM
            num_scalar_prefetch=2,
            grid=(B, NC),
            in_specs=[
                pl.BlockSpec((1, RC, Sp, 128),
                             lambda b, c, ln_ref, tg_ref: (b, c, 0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=[pl.BlockSpec((5 * B,),
                                    lambda b, c, ln_ref, tg_ref: (0,),
                                    memory_space=pltpu.SMEM)],
            scratch_shapes=[
                pltpu.VMEM((Sp, W), jnp.uint32),   # table carry
                pltpu.SMEM((4,), jnp.int32),        # dead/step/maxf/cfgs
            ],
        )

        def run(ln, tg, cm):
            if R_pad != R:
                tg = jnp.pad(tg, ((0, 0), (0, R_pad - R)),
                             constant_values=-1)
                cm = jnp.pad(cm, ((0, 0), (0, R_pad - R), (0, 0), (0, 0)))
            return pl.pallas_call(
                kernel,
                grid_spec=grid_spec,
                out_shape=[jax.ShapeDtypeStruct((5 * B,), jnp.int32)],
                interpret=interpret,
            )(ln, tg, cm)[0].reshape(B, 5)

        # jtflow: packed wgl3.PACKED_FIELDS
        return instrument_kernel("wgl3-pallas", jax.jit(run))

    return launch


def cached_pallas_launcher(model: Model, cfg: DenseConfig,
                           interpret: bool = False):
    key = ("pallas-launch", model.cache_key(), cfg, interpret)
    if key not in _CACHE:
        _CACHE[key] = local_pallas_launcher(model, cfg, interpret)
    return _CACHE[key]


def make_batch_checker_pallas(model: Model, cfg: DenseConfig,
                              interpret: bool = False):
    """check(slot_tabs[B,R,K,4], slot_active[B,R,K], targets[B,R]) ->
    DEVICE i32[B, 5] packed results (wgl3.PACKED_FIELDS / unpack_np)."""
    import functools

    # Two SEPARATE jits, sequenced in Python: fusing the transition prep
    # into the same XLA program as the pallas custom-call serializes
    # pathologically on TPU (0.54 s vs 0.12 s for the identical work at
    # B=256); as separate dispatches they pipeline. The prep jit is
    # shared with the resumable long sweep (_cached_prep).
    prep = _cached_prep(model, cfg)
    launch = cached_pallas_launcher(model, cfg, interpret)

    def check(slot_tabs, slot_active, targets):
        """DEVICE i32[B, 5] in the wgl3 PACKED_FIELDS layout — the caller
        fetches once and splits host-side (wgl3.unpack_np). One fetch per
        launch is the difference between ~0.12 s and ~0.6 s per call on a
        tunneled TPU backend (~0.1 s round trip per fetch)."""
        colmask, tg, lengths = prep(slot_tabs, slot_active, targets)
        B, R = targets.shape
        return launch(B, R)(lengths, tg, colmask)

    return check


def _kernel_body_grouped(cfg: DenseConfig, G: int):
    """Grouped kernel: G histories per pallas program, tables stacked on a
    leading group axis (u32[G, Sp, W] in VMEM — G tiles of (8,128)).

    Why: the per-history kernel measures ~3-4 us per return step against
    ~0.3 us of actual tile work — per-step instruction overhead (loop
    control, the prune switch, scalar SMEM reads, popcount fixpoint
    checks) dominates on one (8,128) tile. Stacking G histories makes
    every vector instruction carry G tiles, amortizing that overhead ~G
    times; the costs are lockstep convergence (each step runs max rounds
    over the group) and a vectorized data-driven prune (every variant
    computed once per step, selected per history) instead of one switch
    branch. Measured on v5e, 1024x150-op corpus (r4 paired-sweep design):
    48 ms device time vs ~230 ms per-history — grouping plus the r4
    redesign together are ~2.3x over r3's grouped kernel (110 ms) and the
    corpus wall sits at ~0.10 s including the tunnel round trip.

    Semantics are identical to _kernel_body per history (same banking,
    same fixpoint sweep order, same metrics; pads contribute nothing)."""
    K, S, off = cfg.k_slots, cfg.n_states, cfg.state_offset
    W = 1 << (K - 5)
    Sp = max(8, (S + 7) // 8 * 8)
    init_row = None

    # Mosaic cannot shape-cast 1-D vectors to higher rank ([G] -> [G,1,1]
    # is an unsupported tpu.reshape), so per-history values are built
    # DIRECTLY in [G,1,1] form: an iota-select chain over the G scalars,
    # and scalars are read back out as masked full-reductions. No 1-D
    # vectors exist anywhere in this kernel.

    def _lane3():
        return jax.lax.broadcasted_iota(jnp.int32, (1, 1, W), 2)

    def _gidx():
        return jax.lax.broadcasted_iota(jnp.int32, (G, 1, 1), 0)

    def g3(scalars, dtype=jnp.int32):
        """[G,1,1] from G scalars (static G, tiny select chain)."""
        acc = jnp.zeros((G, 1, 1), dtype)
        gi = _gidx()
        for g, s in enumerate(scalars):
            acc = jnp.where(gi == g, s.astype(dtype), acc)
        return acc

    def scalar_of(vec3, g):
        """Scalar extraction as a masked full-reduce ([G,1,1] is tiny and
        element extraction from vectors does not lower)."""
        return jnp.sum(jnp.where(_gidx() == g, vec3, 0))

    def allowed_mask(tv3):
        """u32[G, 1, W] from per-history targets tv3 i32[G,1,1]."""
        full = jnp.uint32(0xFFFFFFFF)
        inword = jnp.broadcast_to(jnp.uint32(_LO_MASK[4]), (G, 1, 1))
        for b in range(3, -1, -1):
            inword = jnp.where(tv3 == b, jnp.uint32(_LO_MASK[b]), inword)
        shift = jnp.maximum(tv3 - 5, 0)
        word_ok = ((_lane3() >> shift) & 1) == 0              # [G,1,W]
        word_level = jnp.where(word_ok, full, jnp.uint32(0))
        return jnp.where(tv3 < 5, inword, word_level)

    def closure(T, cm, allowed):
        """One Gauss-Seidel sweep, all G histories: T u32[G,Sp,W],
        cm u32[G,Sp,128], allowed u32[G,1,W]. Column broadcast once per
        slot + arithmetic select masks (r4 tuning notes: the [G,Sp,1]
        where-chain was broadcast-bound, 61 -> 45 ms)."""
        for j in range(K):
            src = T & allowed
            colb = jnp.broadcast_to(cm[:, :, j:j + 1], (G, Sp, W))
            fired = jnp.zeros_like(T)
            for s in range(S):
                selm = (jnp.uint32(0)
                        - ((colb >> jnp.uint32(s)) & jnp.uint32(1)))
                fired = fired | (selm & src[:, s:s + 1, :])
            if j < 5:
                T = T | ((fired & jnp.uint32(_LO_MASK[j]))
                         << jnp.uint32(1 << j))
            else:
                d = 1 << (j - 5)
                tgt = ((_lane3() >> (j - 5)) & 1) == 1
                T = T | jnp.where(tgt, pltpu.roll(fired, d, axis=2),
                                  jnp.uint32(0))
        return T

    def prune(T, tv3, allowed):
        """Data-driven prune: per-history dynamic targets preclude one
        switch branch — compute every slot's variant once (static
        addressing) and select per history. K ~ 12 extra shifted copies
        per STEP, amortized over G histories."""
        acc = jnp.zeros_like(T)
        for j in range(K):
            if j < 5:
                pj = (T >> jnp.uint32(1 << j)) & allowed
            else:
                d = 1 << (j - 5)
                pj = pltpu.roll(T, W - d, axis=2) & allowed
            acc = jnp.where(tv3 == j, pj, acc)
        return acc

    def popcounts(T):
        """i32[G,1,1] per-history frontier sizes. Two single-axis reduces:
        Mosaic's layout inference Check-fails on a multi-axis keepdims
        reduce straight to [G,1,1]."""
        pc = jax.lax.population_count(T).astype(jnp.int32)
        return jnp.sum(jnp.sum(pc, axis=2, keepdims=True), axis=1,
                       keepdims=True)

    MAX_PAIRS = (cfg.rounds + 1) // 2

    def body(ln_ref, tg_ref, cm_ref, out_ref, T_s, dead_s, step_s, maxf_s,
             cfgs_s):
        b = pl.program_id(0)
        c = pl.program_id(1)
        NC = pl.num_programs(1)
        RC = cm_ref.shape[1]

        @pl.when(c == 0)
        def _init():
            rows = jax.lax.broadcasted_iota(jnp.int32, (G, Sp, W), 1)
            cols = jax.lax.broadcasted_iota(jnp.int32, (G, Sp, W), 2)
            T_s[...] = jnp.where((rows == init_row) & (cols == 0),
                                 jnp.uint32(1), jnp.uint32(0))
            dead_s[...] = jnp.zeros((G, 1, 1), jnp.int32)
            step_s[...] = jnp.full((G, 1, 1), -1, jnp.int32)
            maxf_s[...] = jnp.ones((G, 1, 1), jnp.int32)
            cfgs_s[...] = jnp.zeros((G, 1, 1), jnp.int32)

        # Bound the trip by the LONGEST history in the group: steps past
        # every member's length are pure pad and never execute (shorter
        # members' tail steps inside the trip stay guarded by is_pad).
        rg = ln_ref[b * G]
        for g in range(1, G):
            rg = jnp.maximum(rg, ln_ref[b * G + g])
        trip = jnp.clip(rg - c * RC, 0, RC)

        def step(i, carry):
            # dead carried as i32[G,1,1]: loop-carried rank-3 BOOL vectors
            # fail scf.for legalization in Mosaic.
            T, dead_i, dead_step, maxf, cfgs = carry
            r = c * RC + i
            t_raw = g3([tg_ref[b * G + g, r] for g in range(G)])
            is_pad = t_raw < 0                                 # [G,1,1]
            tv3 = jnp.maximum(t_raw, 0)
            allowed = allowed_mask(tv3)
            cm = cm_ref[:, i]                                  # [G,Sp,128]

            # Paired sweeps, loop while the pair's second sweep grew
            # ANYWHERE in the group (vector compare; one scalar cond per
            # step typical — see the r4 tuning notes). Pad histories'
            # colmask columns are zero, so their tables never change and
            # never extend the loop.
            T1 = closure(T, cm, allowed)
            T2 = closure(T1, cm, allowed)

            def wbody(st):
                Tw, _ch, pairs = st
                Ta = closure(Tw, cm, allowed)
                Tb = closure(Ta, cm, allowed)
                return Tb, jnp.any(Ta != Tb), pairs + 1

            def wcond(st):
                return st[1] & (st[2] < MAX_PAIRS)

            T, _c2, _p2 = jax.lax.while_loop(
                wcond, wbody, (T2, jnp.any(T1 != T2), jnp.int32(1)))
            n = popcounts(T)

            pruned = prune(T, tv3, allowed)
            T_new = jnp.where(is_pad, T, pruned)
            alive = popcounts(T_new) > 0
            died = ~is_pad & (dead_i == 0) & ~alive
            dead_i = dead_i | died.astype(jnp.int32)
            T_new = jnp.where(dead_i != 0, jnp.zeros_like(T_new), T_new)
            return (T_new, dead_i,
                    jnp.where(died & (dead_step < 0), r, dead_step),
                    jnp.maximum(maxf, n),
                    cfgs + jnp.where(is_pad, 0, n))

        init = (T_s[...], dead_s[...], step_s[...], maxf_s[...],
                cfgs_s[...])
        T, dead_i, dead_step, maxf, cfgs = jax.lax.fori_loop(0, trip, step,
                                                             init)
        T_s[...] = T
        dead_s[...] = dead_i
        step_s[...] = dead_step
        maxf_s[...] = maxf
        cfgs_s[...] = cfgs

        @pl.when(c == NC - 1)
        def _emit():
            for g in range(G):
                out_ref[5 * (b * G + g) + 0] = 1 - scalar_of(dead_i, g)
                out_ref[5 * (b * G + g) + 1] = jnp.int32(0)
                out_ref[5 * (b * G + g) + 2] = scalar_of(dead_step, g)
                out_ref[5 * (b * G + g) + 3] = scalar_of(maxf, g)
                out_ref[5 * (b * G + g) + 4] = scalar_of(cfgs, g)

    def bind(row):
        nonlocal init_row
        init_row = row
        return body

    return bind


def local_pallas_launcher_grouped(model: Model, cfg: DenseConfig, G: int,
                                  interpret: bool = False):
    """launch(B, R) for the grouped kernel; B must be a multiple of G."""
    max_k = limits().max_k_pallas
    if cfg.k_slots > max_k:
        raise ValueError(f"pallas kernel supports k_slots <= {max_k}, "
                         f"got {cfg.k_slots}")
    _require_converging_cap(cfg)
    Sp = max(8, (cfg.n_states + 7) // 8 * 8)
    W = 1 << (cfg.k_slots - 5)
    row = int(model.init_state()) + cfg.state_offset
    kernel = _kernel_body_grouped(cfg, G)(row)

    import functools

    @functools.lru_cache(maxsize=None)
    def launch(B: int, R: int):
        if B % G:
            raise ValueError(f"grouped launch: batch {B} % group {G} != 0")
        # The colmask block is G histories x RC steps x (Sp,128) tiles;
        # shrink RC so the block stays ~2 MiB (like the per-history
        # kernel's) whatever the group size and state width.
        RC = min(R, max(8, limits().pallas_step_chunk * 8 // (G * Sp)))
        NC = (R + RC - 1) // RC
        R_pad = NC * RC
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,   # lengths [B] + targets [B,R_pad]
            grid=(B // G, NC),
            in_specs=[
                pl.BlockSpec((G, RC, Sp, 128),
                             lambda b, c, ln_ref, tg_ref: (b, c, 0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=[pl.BlockSpec((5 * B,),
                                    lambda b, c, ln_ref, tg_ref: (0,),
                                    memory_space=pltpu.SMEM)],
            scratch_shapes=[
                pltpu.VMEM((G, Sp, W), jnp.uint32),    # table carry
                pltpu.VMEM((G, 1, 1), jnp.int32),      # dead
                pltpu.VMEM((G, 1, 1), jnp.int32),      # dead_step
                pltpu.VMEM((G, 1, 1), jnp.int32),      # max_frontier
                pltpu.VMEM((G, 1, 1), jnp.int32),      # configs_explored
            ],
        )

        def run(ln, tg, cm):
            if R_pad != R:
                tg = jnp.pad(tg, ((0, 0), (0, R_pad - R)),
                             constant_values=-1)
                cm = jnp.pad(cm, ((0, 0), (0, R_pad - R), (0, 0), (0, 0)))
            return pl.pallas_call(
                kernel,
                grid_spec=grid_spec,
                out_shape=[jax.ShapeDtypeStruct((5 * B,), jnp.int32)],
                interpret=interpret,
            )(ln, tg, cm)[0].reshape(B, 5)

        # jtflow: packed wgl3.PACKED_FIELDS
        return instrument_kernel("wgl3-pallas-grouped", jax.jit(run))

    return launch


def make_batch_checker_pallas_grouped(model: Model, cfg: DenseConfig,
                                      group: int | None = None,
                                      interpret: bool = False):
    """Grouped-kernel twin of make_batch_checker_pallas. The batch is
    padded to a group multiple with all-pad histories (targets=-1) and
    results stripped, so any B works."""
    import functools

    G = group or limits().pallas_group
    prep = _cached_prep(model, cfg)
    launch = local_pallas_launcher_grouped(model, cfg, G, interpret)

    def check(slot_tabs, slot_active, targets):
        B, R = targets.shape
        B_pad = (B + G - 1) // G * G
        if B_pad != B:
            extra = B_pad - B
            slot_tabs = jnp.concatenate(
                [slot_tabs, jnp.zeros((extra,) + slot_tabs.shape[1:],
                                      slot_tabs.dtype)])
            slot_active = jnp.concatenate(
                [slot_active, jnp.zeros((extra,) + slot_active.shape[1:],
                                        slot_active.dtype)])
            targets = jnp.concatenate(
                [targets, jnp.full((extra, R), -1, targets.dtype)])
        colmask, tg, lengths = prep(slot_tabs, slot_active, targets)
        return launch(B_pad, R)(lengths, tg, colmask)[:B]

    return check


def cached_batch_checker_pallas_grouped(model: Model, cfg: DenseConfig,
                                        group: int | None = None,
                                        interpret: bool = False):
    G = group or limits().pallas_group
    key = ("pallas-grouped", model.cache_key(), cfg, G, interpret)
    if key not in _CACHE:
        _CACHE[key] = make_batch_checker_pallas_grouped(model, cfg, G,
                                                        interpret)
    return _CACHE[key]


_CACHE: dict[tuple, object] = {}


def cached_batch_checker_pallas(model: Model, cfg: DenseConfig,
                                interpret: bool = False):
    key = ("pallas", model.cache_key(), cfg, interpret)
    if key not in _CACHE:
        _CACHE[key] = make_batch_checker_pallas(model, cfg, interpret)
    return _CACHE[key]


def pallas_feasible(cfg: DenseConfig | None,
                    n_steps: int | None = None,
                    batch: int | None = None) -> bool:
    """Does this launch fit the pallas kernel's envelope? Bounds (all in
    limits()): k_slots <= max_k_pallas (table stays a handful of VMEM
    tiles), per-history steps <= max_r_pallas and batch * steps <=
    max_prefetch_pallas (the scalar-prefetched targets table lands whole
    in SMEM — the worker-profile caps keep launches inside the
    tested-good envelope with ~2x margin). Anything bigger routes to the
    XLA kernel, whose scan streams targets from HBM."""
    lim = limits()
    return (cfg is not None and cfg.k_slots <= lim.max_k_pallas
            and (n_steps is None or n_steps <= lim.max_r_pallas)
            and (n_steps is None or batch is None
                 or batch * n_steps <= lim.max_prefetch_pallas))


def pallas_available() -> bool:
    """Compiled pallas path runs only on a real TPU backend (tests use
    interpret=True explicitly on CPU)."""
    import jax
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def use_pallas(cfg: DenseConfig | None,
               n_steps: int | None = None,
               batch: int | None = None) -> bool:
    """Production routing predicate: dense geometry fits the kernel AND a
    TPU backend is live."""
    return pallas_feasible(cfg, n_steps, batch) and pallas_available()


def check_batch_encoded_pallas(encs: Sequence[EncodedHistory],
                               model: Model | None = None,
                               interpret: bool = False) -> list[dict]:
    """Batch entry point mirroring wgl3.check_batch_encoded3."""
    from .wgl3 import assemble_batch_results, unpack_np

    if model is None:
        from ..models import CASRegister
        model = CASRegister()
    cfg, arrays, steps = batch_arrays3(encs, model)
    if not pallas_feasible(cfg, n_steps=arrays[2].shape[1]):
        raise ValueError(
            f"pallas infeasible for k_slots={cfg.k_slots}, "
            f"n_steps={arrays[2].shape[1]}")
    check = cached_batch_checker_pallas(model, cfg, interpret)
    return assemble_batch_results(unpack_np(check(*arrays)), steps, cfg)


def check_encoded_general(enc: EncodedHistory, model: Model,
                          f_cap: int = 256,
                          f_cap_max: int | None = None,
                          time_budget_s: float | None = None) -> dict:
    """The exact-verdict ladder for geometries OUTSIDE the dense budget
    (wide pending sets / huge values):

      1. resumable sort kernel, with f_cap capped so the per-step sort
         stays under the axon worker's allocation fault (~2M keys);
      2. if the live frontier outgrows that cap, the dense subset-lattice
         run CHUNKED with a budget override — per-step cost is 2^K bits
         but capacity is unconditionally exact, and small chunks keep
         each program under the worker's kill threshold.

    Every rung is exact; there is no oracle fallback. The result carries
    a "kernel" key naming the rung that produced the verdict. When the
    geometry defeats every rung (frontier past every permissible f_cap
    AND a lattice too wide to sweep — seen at ~28 pending ops, where the
    dense table would be 2^31 cells), the verdict is the honest tri-state
    "unknown" with overflow=True, never a crash: the jepsen checker
    contract (and knossos' behavior at its own limits) is an
    indeterminate result, and merge_valid propagates it so the run exits
    nonzero."""
    import time as _time

    from . import wgl2, wgl3
    from .encode import encode_return_steps, reslot_events

    t0 = _time.monotonic()           # ONE clock for the whole ladder: the
    #                                  dense rung gets the REMAINING budget,
    #                                  so a check never spends ~2x the
    #                                  configured bound (ADVICE r2)
    tight = wgl2.sort_k_slots(enc)   # f_cap_max sizing must match the
    #                                  width the sort kernel really uses
    # A CHUNKED dense lattice under the relaxed 2^26-cell budget, when one
    # exists, beats the sort kernel's high rungs: past a few thousand live
    # configs each expansion round sorts f_cap*(k+1) keys, while the dense
    # sweep's bit-parallel cost is fixed — combinatorial frontiers (e.g. a
    # mutex history with m indeterminate acquires AND releases pending:
    # ~C(2m, m) reachable configs) DNF the sort ladder but sweep in
    # seconds. So cap the sort rungs early when dense-chunked is waiting.
    lim = limits()
    cfg_dense = wgl3.dense_config(model, tight, enc.max_value,
                                  budget=lim.dense_cell_budget_chunked)
    # Multi-device: the lattice-sharded sweep (parallel/lattice.py)
    # upgrades the dense rung — its cell budget scales with the device
    # count and each device sweeps 1/D of the table, so geometries the
    # single-device rung must refuse become checkable at all.
    cfg_lat = None
    if jax.device_count() > 1:
        from ..parallel.lattice import lattice_dense_config

        cfg_lat = lattice_dense_config(model, tight, enc.max_value,
                                       jax.device_count())
    cfg_sweep = cfg_lat if cfg_lat is not None else cfg_dense
    if f_cap_max is None:
        # The sort-row allocation fault is a worker-profile limit; other
        # backends take the sort kernel as far as memory goes.
        if pallas_available():
            f_cap_max = max(4096, min(1 << 20,
                                      lim.sort_row_budget // (tight + 1)))
        else:
            f_cap_max = 1 << 20
        if cfg_sweep is not None:
            # Stop the sort ladder where the dense sweep becomes cheaper:
            # a sort rung costs ~f_cap*(k+1) sorted keys per step, the
            # dense sweep a fixed ~cells bit-ops per step — PER DEVICE
            # when the lattice-sharded rung will run it, so wide
            # geometries route to the cheap sweep early instead of
            # burning the budget on huge sort rungs. (Only for the
            # computed default — an explicit caller f_cap_max stands.)
            cells = cfg_sweep.n_states * cfg_sweep.n_masks
            if cfg_lat is not None:
                cells //= jax.device_count()
            f_cap_max = min(f_cap_max, max(f_cap, cells // (tight + 1)))

    def dense_chunked(enc):
        # Remaining budget only (ADVICE r2: the fallback used to restart
        # the clock, spending up to 2x the configured bound). A launched
        # chunk cannot be preempted, so overshoot is bounded by ONE chunk;
        # with nothing left, don't start the rung at all.
        remaining = (None if time_budget_s is None else
                     time_budget_s - (_time.monotonic() - t0))
        if remaining is not None and remaining <= 0.5:
            return {"valid": "unknown", "survived": False, "overflow": True,
                    "dead_step": -1, "max_frontier": -1,
                    "configs_explored": -1, "op_count": enc.n_ops,
                    "f_cap": cfg_sweep.n_states * cfg_sweep.n_masks,
                    "escalations": 0, "kernel": "exhausted",
                    "error": f"sort ladder consumed the whole "
                             f"{time_budget_s:.0f}s budget; dense-chunked "
                             f"rung not started"}
        if enc.k_slots != tight:
            enc = reslot_events(enc, tight)
        rs = encode_return_steps(enc)
        # The lattice / pallas / XLA ladder lives in the KernelPlan
        # layer now (plan.dispatch_long — ONE copy shared with
        # run_long_dense); this rung only picks the geometry (the
        # lattice cfg when the mesh shards it, the relaxed chunked
        # budget otherwise) and threads the remaining budget.
        from ..plan import dispatch_long

        if cfg_lat is not None:
            from ..parallel.lattice import lattice_mesh

            out = dispatch_long(rs, model, cfg_lat,
                                lattice_mesh=lattice_mesh(),
                                time_budget_s=remaining)
        else:
            out = dispatch_long(rs, model, cfg_dense,
                                time_budget_s=remaining)
        out["op_count"] = enc.n_ops
        out["f_cap"] = cfg_sweep.n_states * cfg_sweep.n_masks
        out["escalations"] = 0
        return out

    try:
        # keep_death_checkpoint: zero-cost until death, and on an invalid
        # verdict it hands the witness rung the exact frontier nearest
        # the death point so no second search is needed
        # (checkers/witness.py reconstruct_witness_from_sort_checkpoint).
        out = wgl2.check_encoded_resumable(enc, model, f_cap=f_cap,
                                           f_cap_max=f_cap_max,
                                           time_budget_s=time_budget_s,
                                           keep_death_checkpoint=True)
        out["kernel"] = "wgl2-sort-resumable"
        return out
    except MemoryError as e:
        # Capacity OR time exhausted: the dense-chunked rung (no frontier
        # capacity at all) when one exists, else the honest tri-state.
        if cfg_sweep is None:
            return {"valid": "unknown", "survived": False, "overflow": True,
                    "dead_step": -1, "max_frontier": -1,
                    "op_count": enc.n_ops, "f_cap": f_cap_max,
                    "escalations": -1, "kernel": "exhausted",
                    "error": str(e)}
        return dense_chunked(enc)


def packed_batch_checker(model: Model, cfg: DenseConfig,
                         n_steps: int | None = None,
                         batch: int | None = None):
    """The SINGLE-DEVICE dense routing point, now a shim over the
    KernelPlan layer (plan/dispatch.py plan_dense_batch — one copy of
    the pallas-vs-XLA/grouped policy this function, the sharded router
    and the sched bucket launcher each used to carry; the grouped-
    kernel tuning notes live on its docstring). Returns
    (packed_check_fn, kernel_name). `shard=False` pins the local form:
    this entry is the deliberately-unsharded router (bench kernel arms,
    single-history launches) — multi-device callers go through
    plan_dense_batch / check_batch_encoded_auto, which shard the batch
    axis over the mesh."""
    from ..plan import plan_dense_batch, resolve

    p = plan_dense_batch(model, cfg, n_steps=n_steps, batch=batch,
                         shard=False)
    return resolve(p), p.label


def check_batch_encoded_auto(encs: Sequence[EncodedHistory],
                             model: Model | None = None
                             ) -> tuple[list[dict], str]:
    """Route a batch to the best dense backend for this platform; returns
    (per-history results, kernel_name — "mixed" when histories split
    across backends).

    The batch is PARTITIONED by per-history dense feasibility: one wide
    or huge-value history must not demote a whole corpus to sequential
    ladder runs — the feasible majority still goes through one batched
    launch.

    Tiny SINGLE histories on a live TPU backend route to the exact host
    oracle instead (VERDICT r3 item 5): below the crossover — measured
    per platform, ops/calibrate.py — the device dispatch+fetch round
    trip alone exceeds the oracle's whole runtime (tutorial-scale
    analyze, ~150 ops, is ~5 ms host vs ~100 ms of dispatch latency on
    the axon tunnel). This is the SAME exact algorithm — not a soundness
    fallback — and batches never take it (batching amortizes the
    dispatch). The route is bounded both ways: wide-pending histories
    are excluded up front and a transition budget aborts into the device
    ladder (ADVICE r4 medium)."""
    from . import wgl3

    if model is None:
        from ..models import CASRegister
        model = CASRegister()
    if (len(encs) == 1 and pallas_available()
            and encs[0].n_events <= _oracle_crossover()
            and encs[0].max_pending <= limits().oracle_route_max_pending):
        # max_pending gate + transition budget (ADVICE r4 medium): the
        # frontier holds up to 2^pending masks per state, so a tiny-event
        # but wide-concurrency history could grind an exponential host
        # search. Wide histories and budget expiries take the capped
        # device ladder below instead — same verdicts, bounded cost.
        from ..checkers.oracle import OracleBudgetExceeded

        try:
            return ([_oracle_result(encs[0], model,
                                    limits().oracle_config_budget)],
                    "oracle-small-history")
        except OracleBudgetExceeded:
            pass
    dense_idx, general_idx = partition_dense(encs, model)

    results: list = [None] * len(encs)
    kernels: set[str] = set()
    if dense_idx:
        sub = [encs[i] for i in dense_idx]
        try:
            cfg, steps, r_cap = wgl3.batch_steps3(sub, model)
        except ValueError:
            # Individually feasible but not under one SHARED geometry
            # (e.g. one history's k with another's value range): ladder
            # each — rare extreme, correctness over batching.
            general_idx = sorted(general_idx + dense_idx)
            dense_idx = []
        else:
            if r_cap > limits().long_scan_max:
                # Step count exceeds one scan program: host-driven chunked
                # sweeps, one history at a time — arrays never stacked or
                # transferred. On a live TPU the fused kernel runs in
                # launch-sized windows with the search state carried
                # between launches (check_steps3_long_pallas — the 100k-op
                # lane); elsewhere the XLA scan streams chunk by chunk.
                for i, s in zip(dense_idx, steps):
                    one = run_long_dense(s, model, cfg)
                    results[i] = one
                    kernels.add(one["kernel"])
            elif jax.device_count() > 1 and len(sub) > 1:
                # Multi-device: shard the batch axis over all devices —
                # the PRODUCTION multi-chip path (corpus / independent
                # keys ride it automatically; VERDICT r2 missing #1).
                from ..parallel.dense import check_steps_sharded

                batch_out, name = check_steps_sharded(
                    model, cfg, steps, r_cap)
                for i, one in zip(dense_idx, batch_out):
                    results[i] = one
                kernels.add(name)
            else:
                arrays = wgl3.stack_steps3(steps, r_cap)
                check, name = packed_batch_checker(
                    model, cfg, n_steps=r_cap, batch=len(sub))
                batch_out = wgl3.assemble_batch_results(
                    wgl3.unpack_np(check(*arrays)), steps, cfg)
                for i, one in zip(dense_idx, batch_out):
                    results[i] = one
                kernels.add(name)
    if general_idx:
        overflowed, too_long, top = _batch_general(encs, general_idx, model,
                                                   results, kernels)
        # The batched tiers PROVED capacities up to `top` overflow for
        # these: start the ladder past every dead rung.
        ladder_tail(encs, model, results, kernels, too_long,
                    [(i, LADDER_SEED_FACTOR * top) for i in overflowed])
    return results, (kernels.pop() if len(kernels) == 1 else "mixed")


def _oracle_crossover() -> int:
    """Active oracle-route crossover: a non-negative limits() value is
    authoritative (0 = route off — bench.py pins this for kernel lanes;
    >0 = fixed); -1 (the default) defers to the per-platform measurement
    (ops/calibrate.py — dispatch floor x oracle throughput, persisted)."""
    fixed = limits().oracle_crossover_events
    if fixed >= 0:
        return fixed
    from .calibrate import get_calibration

    return get_calibration().crossover_events


def _oracle_result(enc: EncodedHistory, model: Model,
                   max_configs: int | None = None) -> dict:
    """Host-oracle run shaped like a kernel result (the schema of
    wgl3.assemble_batch_results — `valid`/`dead_step`/`overflow` agree
    field-for-field with the dense kernel; the search metrics
    `max_frontier`/`configs_explored` count the SAME quantities — live
    configs high-water mark and transition attempts — but can differ in
    value because the oracle's JIT closure regenerates beyond-boundary
    configs the dense table keeps, see tests/test_oracle.py's
    field-agreement test): dead_event (event index) translates to the v2
    kernel's return-step index by counting returns strictly before it.
    Raises OracleBudgetExceeded past `max_configs` transition attempts —
    the router falls back to the device ladder."""
    import numpy as np

    from ..checkers.oracle import check_events_oracle
    from .encode import EV_RETURN

    from . import wgl3

    res = check_events_oracle(enc, model, max_configs)
    if res.dead_event < 0:
        dead_step = -1
    else:
        ev = np.asarray(enc.events[:res.dead_event, 0])
        dead_step = int((ev == EV_RETURN).sum())
    # table_cells: schema parity with assemble_batch_results (the
    # independent checker reads it as the exact path's capacity). The
    # oracle has no dense table; report the cells the dense kernel WOULD
    # have used, or 0 for a dense-infeasible tiny history (the oracle is
    # exact either way).
    cfg = wgl3.dense_config(model, wgl3.tight_k_slots(enc), enc.max_value)
    out = {
        "survived": bool(res.valid), "overflow": False,
        "dead_step": dead_step, "max_frontier": res.max_frontier,
        "configs_explored": int(res.configs_explored),
        "valid": res.valid, "op_count": enc.n_ops,
        "table_cells": 0 if cfg is None else cfg.n_states * cfg.n_masks,
        "kernel": "oracle-small-history",
    }
    record_check_result(out)
    return out


# First ladder rung after the batched tiers prove `top` overflows — shared
# by check_batch_encoded_auto and the independent checker's f_cap_floor
# threading (checkers/independent.py) so the seeding policy has one copy.
LADDER_SEED_FACTOR = 4


# -- routing policy shared with the corpus scheduler (sched/engine.py) -----
# The scheduler changes HOW dense batches are padded and launched, never
# WHICH kernel checks what: partition criteria, the long-history sweep
# dispatch, and the general-lane ladder tail live here, in exactly one
# copy, so the two batched entry points cannot drift.

def partition_dense(encs: Sequence[EncodedHistory], model: Model
                    ) -> tuple[list[int], list[int]]:
    """Per-history dense feasibility split: (dense_idx, general_idx)."""
    from . import wgl3

    dense_idx, general_idx = [], []
    for i, e in enumerate(encs):
        ok = dense_config(model, wgl3.tight_k_slots(e), e.max_value)
        (dense_idx if ok is not None else general_idx).append(i)
    return dense_idx, general_idx


def run_long_dense(rs, model: Model, cfg: DenseConfig) -> dict:
    """One dense-feasible history whose step count exceeds a scan
    program: the host-chunked sweep, routed through the KernelPlan
    layer (plan.dispatch_long — fused pallas windows on a live TPU,
    the XLA chunk loop elsewhere, the sparse engine where the density
    plan engages), result normalized to the batched schema
    (op_count/table_cells/kernel)."""
    from ..plan import dispatch_long

    one = dispatch_long(rs, model, cfg)
    one["op_count"] = rs.n_ops
    one["table_cells"] = cfg.n_states * cfg.n_masks
    return one


def ladder_tail(encs, model: Model, results: list, kernels: set,
                too_long: Sequence[int],
                overflow_seeds: Sequence[tuple[int, int]]) -> None:
    """The general lane's per-history tail after the batched sort tiers:
    too-long histories ladder from scratch; tier-proven overflows ladder
    seeded past every capacity the tiers showed dead."""
    for i in too_long:
        one = check_encoded_general(encs[i], model)
        results[i] = one
        kernels.add(one["kernel"])
    for i, seed in overflow_seeds:
        one = check_encoded_general(encs[i], model, f_cap=seed)
        results[i] = one
        kernels.add(one["kernel"])


# Batched-tier capacities for the non-dense pass. Start small: sort cost
# per launch is linear in f_cap (measured on a 256-history fifo corpus:
# 3.2 s at f_cap=256 vs 1.1 s at 64), typical frontiers are tiny, and an
# overflowed history re-batches at the next tier — still one launch per
# tier, vs ~0.5 s per history for a per-history ladder run.
GENERAL_TIERS = (64, 256, 1024)


def _batch_general(encs, idxs, model, results, kernels, f_cap: int = 256
                   ) -> tuple[list[int], list[int], int]:
    """Batched pass for the NON-dense partition of a batch (wide pending
    sets / huge-value states — queue and multi-register corpora live
    here): vmapped sort-kernel launches over a shared geometry instead of
    a sequential per-history ladder, escalating the frontier capacity in
    BATCHED tiers (GENERAL_TIERS, extended to cover the caller's f_cap).
    Exact verdicts (survived, or dead without overflow — soundness
    argument in ops/wgl2.py) land in `results`; returns (overflowed,
    too_long, top_tier): `overflowed` stayed "unknown" at every tier,
    `too_long` exceed one scan program (limits().long_scan_max) and were never
    launched — both must ladder per history. Launches are chunked so
    batch*f_cap*(k_slots+1) stays inside the tested-good sort-row budget
    (limits().sort_row_budget — the worker profile faults past ~2M rows)
    AND the stacked slot tables stay a few hundred MB."""
    import jax.numpy as jnp

    from . import wgl, wgl2, wgl3
    from .encode import encode_return_steps, reslot_events

    sub = [(i, encs[i]) for i in idxs]
    k = max(wgl2.sort_k_slots(e) for _, e in sub)
    max_value = max(e.max_value for _, e in sub)
    steps, too_long = [], []
    for i, e in sub:
        rs = encode_return_steps(
            reslot_events(e, k) if e.k_slots != k else e)
        if rs.n_steps > limits().long_scan_max:
            too_long.append(i)   # needs host-chunked scans, not one program
        else:
            steps.append((i, rs))
    if not steps:
        return [], too_long, GENERAL_TIERS[-1]
    r_cap = min(wgl3.step_bucket(max(1, max(s.n_steps for _, s in steps))),
                limits().long_scan_max)
    # Every GENERAL_TIERS rung runs regardless of the caller's f_cap (the
    # point of tiering is re-batching overflows instead of laddering them
    # per history); f_cap joins as an extra rung when it is larger. No
    # tier may exceed the sort-row budget for ONE history — chunking
    # shrinks the batch, never a single lane's f_cap*(k+1) rows.
    cap_max = max(GENERAL_TIERS[0], limits().sort_row_budget // (k + 1))
    tiers = sorted({min(t, cap_max) for t in (*GENERAL_TIERS, f_cap)})

    n_dev = jax.device_count()

    def launch(tier_steps, tier_cap):
        cfg = wgl2.make_config(model, k, tier_cap, max_value)
        lim = limits()
        chunk = max(1, min(
            lim.sort_row_budget // (tier_cap * (k + 1)),
            lim.stack_element_budget // max(1, r_cap * (k + 1))))
        sharded = n_dev > 1 and chunk >= n_dev
        from ..plan import build_plan, resolve

        if sharded:
            # Multi-device: the NON-dense production path (queue /
            # multi-register corpora) shards its batch axis too, like the
            # dense path (VERDICT r2 missing #1) — family
            # wgl2-sort-sharded, through the plan spine (mesh-keyed).
            from ..parallel.dense import batch_mesh

            check = resolve(build_plan("wgl2-sort-sharded", model, cfg,
                                       mesh=batch_mesh(),
                                       label="wgl2-sort-sharded"))
        else:
            check = resolve(build_plan("wgl2-batch", model, cfg,
                                       label="wgl2-sort-batched"))
        overflowed = []
        for c0 in range(0, len(tier_steps), chunk):
            part = tier_steps[c0:c0 + chunk]
            # Bucket the batch axis too: bounded recompiles across corpora
            # of varying size (pad histories are all-pad scans — no work);
            # sharded launches additionally pad to the device count.
            b_cap = min(wgl3.step_bucket(len(part), floor=8), chunk)
            if sharded:
                b_cap = (b_cap + n_dev - 1) // n_dev * n_dev
            padded = [s.padded_to(r_cap) for _, s in part]
            tabs = np.zeros((b_cap,) + padded[0].slot_tabs.shape, np.int32)
            act = np.zeros((b_cap,) + padded[0].slot_active.shape, bool)
            tgt = np.full((b_cap, r_cap), -1, np.int32)
            for j, p in enumerate(padded):
                tabs[j] = p.slot_tabs
                act[j] = p.slot_active
                tgt[j] = p.targets
            out = {name: np.asarray(v) for name, v in check(
                jnp.asarray(tabs), jnp.asarray(act),
                jnp.asarray(tgt)).items()}
            for j, (i, s) in enumerate(part):
                one = {name: out[name][j].item() for name in out}
                v = wgl.verdict(one)
                if v == "unknown":
                    overflowed.append((i, s))
                    continue
                results[i] = {
                    "valid": v, "survived": one["survived"],
                    "overflow": one["overflow"],
                    "dead_step": one["dead_step"],
                    "max_frontier": one["max_frontier"], "op_count": s.n_ops,
                    "f_cap": tier_cap, "escalations": 0,
                    "kernel": "wgl2-sort-batched",
                }
                record_check_result(results[i])
                kernels.add("wgl2-sort-batched")
        return overflowed

    remaining = steps
    for tier_cap in tiers:
        remaining = launch(remaining, tier_cap)
        if not remaining:
            break
    return [i for i, _ in remaining], too_long, tiers[-1]
