"""WGL linearizability search v3: dense subset-lattice kernel.

The v1/v2 kernels (ops/wgl.py, ops/wgl2.py) keep the frontier as a compacted
LIST of (state, mask) configs and pay a sort-based dedup over
f_cap*(k_slots+1) keys per expansion round — the dominant cost on TPU, and
the reason round 1's bench lost to the CPU oracle. This kernel replaces the
list with the DENSE characteristic function of the frontier:

    table: bool[S, 2^K]   table[s, m] == "config (state s-offset, mask m)
                           is reachable"

where S bounds the model's reachable states (known host-side from the
history's values, models/base.py pack_bits rationale) and K = k_slots is the
pending-op slot count. This is viable exactly when S * 2^K is small — true
for every realistic jepsen history (concurrency ~10 ⇒ K ≈ 10-12, register
values ⇒ S ≈ 8), and decidable host-side (`dense_feasible`). Large-K
histories fall back to the sort kernel.

Why this is the TPU-native shape of the search:
  * dedup DISAPPEARS: the table is a canonical set representation; OR-ing
    candidates in is idempotent. No sort, no scatter, no compaction.
  * expanding "fire pending op j from every config" is, for the mask axis, a
    static reshape exposing bit j ([S, hi, 2, lo] with lo = 2^j) — the b=0
    half ORs into the b=1 half — and, for the state axis, a tiny [S,S]
    one-hot transition matmul (MXU food, S ≈ 8-64).
  * pruning at a return (keep configs that linearized the target, clear its
    bit) is ONE gather: table[:, m | (1<<t)] masked to bit-t-clear columns.
  * overflow CANNOT happen: the table holds the whole config space, so every
    verdict is exact — no capacity escalation, no oracle fallback
    (VERDICT.md round-1 item 4).

Search semantics are identical to v2 (and knossos :linear, reference call
site src/jepsen/etcdemo.clj:117): just-in-time linearization banks configs
that already fired the returning op (they are excluded as expansion sources
via the bit-t column mask), and the closure runs to fixpoint under a
lax.while_loop with a Gauss-Seidel sweep over slots (in-round chaining keeps
typical round counts at 1-2).

Consumes the same return-major encoding (encode.py ReturnSteps) as v2, so it
drops into the same scan/vmap/shard harness.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..models.base import Model
from ..obs import (get_ledger, get_metrics, instrument_kernel,
                   record_check_result)
from .encode import EncodedHistory, ReturnSteps, encode_return_steps
from .limits import limits


@dataclass(frozen=True)
class DenseConfig:
    k_slots: int          # K: mask width; table mask axis is 2^K
    n_states: int         # S: table state axis (covers every reachable state)
    state_offset: int     # state value -> row index shift (NIL=-1 -> 0)
    max_rounds: int = 0   # closure sweep bound; default k_slots

    @property
    def n_masks(self) -> int:
        return 1 << self.k_slots

    @property
    def rounds(self) -> int:
        return self.max_rounds or self.k_slots


def dense_config(model: Model, k_slots: int, max_value: int,
                 budget: int | None = None) -> DenseConfig | None:
    """DenseConfig for this (model, history) — or None when infeasible.

    Feasible iff the model's states are boundable from the history's values
    (same precondition as the packed sort-key dedup), S <= 32 (the packed
    kernel unrolls its state OR-reduce), K >= 5 (the mask axis is packed 32
    configs per uint32 word), and the table fits the cell budget. S is
    rounded up (multiple of 4) so nearby value ranges share one jit cache
    entry, mirroring wgl2.make_config.

    The default budget (limits().dense_cell_budget) caps cells because
    per-step sweep cost is O(K * S * 2^K) regardless of how few configs
    are LIVE, while the sort kernel (wgl2) pays O(f_cap * K) — past
    K ~ 17 the live frontier is invariably tiny relative to the lattice,
    so dense sweeps waste >100x the work; 2^20 cells admits typical
    jepsen geometries (concurrency 10 gives K=12, a 4 KiB table) and
    routes wider ones to wgl2 (or the sharded lattice, parallel/)."""
    if budget is None:
        budget = limits().dense_cell_budget
    if not model.packable_states or k_slots < 5:
        return None
    s = model.state_bound(max_value) + 1
    s = (s + 3) // 4 * 4
    if s > 32 or s * (1 << k_slots) > budget:
        return None
    return DenseConfig(k_slots=k_slots, n_states=s,
                       state_offset=model.state_offset)


class _Carry3(NamedTuple):
    table: jax.Array        # u32[S, W]: bit b of word w = mask (w*32 + b)
    dead: jax.Array         # bool
    dead_step: jax.Array    # i32 (return-step index, -1 if alive)
    max_frontier: jax.Array  # i32 (popcount high-water mark)


# LO_MASK[j] (j < 5): bits p in 0..31 whose index has bit j CLEAR — the
# in-word "mask bit j not yet fired" positions. 32 = 2^5 configs pack
# per u32 word: every `1 << (K - 5)` table-width computation here and in
# wgl3_sparse/wgl3_pallas/parallel.lattice derives from THIS packing —
# the jtflow pass (JTL403) pins their shift literals to it.
# jtflow: table-word-bits=5
_LO_MASK = tuple(
    np.uint32(sum(1 << p for p in range(32) if not (p >> j) & 1))
    for j in range(5))


class _TableOps(NamedTuple):
    """The bit-algebra building blocks of the dense lattice sweep, shared
    by the dense step fn (make_step_fn3), the sparse active-tile engine
    (ops/wgl3_sparse.py), and the lattice-sharded form (parallel/
    lattice.py builds its own shard-local variants of the same ops)."""
    allowed_mask: Any       # t -> u32[W] (mask-bit-t-CLEAR positions)
    or_reduce: Any          # ([S,S'] trans, u32[S,...]) -> u32[S,...]
    transitions: Any        # (slot_tab[K,4], slot_active[K]) -> [K,S,S']
    dense_sweep: Any        # (T, allowed, trans) -> T — one G-S round
    prune: Any              # (T, t, allowed) -> pruned table


def table_ops(model: Model, cfg: DenseConfig) -> _TableOps:
    """Build the per-geometry table operations (see _TableOps).

    The mask axis is packed 32 configs/word: masks' low 5 bits index bits
    inside a uint32, the high K-5 bits index words. Every set operation
    becomes word-wise bit algebra (32x less memory traffic than a bool
    table, no bool->f32 conversions, no MXU needed):
      * expanding slot j<5  = in-word shift:  (src & LO_MASK[j]) << 2^j
      * expanding slot j>=5 = word-axis reshape exposing word-bit j-5
      * state transition    = OR-reduce over source states (S unrolled,
        S <= 32 guaranteed by dense_config)
      * pruning at return t = word gather + in-word shift, then mask
      * frontier size       = population_count
    """
    K, S, off = cfg.k_slots, cfg.n_states, cfg.state_offset
    assert K >= 5 and S <= 32
    W = 1 << (K - 5)
    state_vals = jnp.arange(S, dtype=jnp.int32) - off
    s_ids = jnp.arange(S, dtype=jnp.int32)
    w_idx = jnp.arange(W, dtype=jnp.int32)
    lo_masks = jnp.asarray(np.array(_LO_MASK, dtype=np.uint32))
    full = jnp.uint32(0xFFFFFFFF)

    def allowed_mask(t):
        """uint32[W]: per-word mask of config positions with mask-bit t
        CLEAR (not-yet-fired-t). Serves both banking and prune."""
        in_word = lo_masks[jnp.minimum(t, 4)]
        word_level = jnp.where(
            ((w_idx >> jnp.maximum(t - 5, 0)) & 1) == 0, full, jnp.uint32(0))
        return jnp.where(t < 5, jnp.broadcast_to(in_word, (W,)), word_level)

    def or_reduce(tj, src):
        """OR over source states: out[s', ...] = OR_s tj[s, s'] & src[s].
        S is small (<=32): unrolled selects, no matmul."""
        acc = jnp.zeros_like(src)
        for s in range(S):
            sel = tj[s].reshape((S,) + (1,) * (src.ndim - 1))
            acc = acc | jnp.where(sel, src[s][None], jnp.uint32(0))
        return acc

    def transitions(slot_tab, slot_active):
        """Per-slot transition matrices over the state axis: [K, S, S'].
        Pure function of the scan inputs — computed for ALL steps in one
        vectorized shot before the scan (keeps the sequential per-step
        critical path to pure bit algebra)."""
        legal, nxt = jax.vmap(
            lambda row: model.step(state_vals, row[0], row[1], row[2],
                                   row[3]))(slot_tab)
        nxt_row = nxt + off
        ok = legal & (nxt_row >= 0) & (nxt_row < S) & slot_active[:, None]
        return (ok[:, :, None]
                & (nxt_row[:, :, None] == s_ids[None, None, :]))

    def dense_sweep(T, allowed, trans):
        """One Gauss-Seidel sweep: fire each slot once, updating T in
        place so same-round chains propagate. Static python loop — K is
        small and each j needs its own static bit/word addressing."""
        for j in range(K):
            src = T & allowed[None, :]
            if j < 5:
                fired = or_reduce(trans[j], src & _LO_MASK[j])
                T = T | (fired << np.uint32(1 << j))
            else:
                lo_w, hi = 1 << (j - 5), W >> (j - 4)
                Tr = T.reshape(S, hi, 2, lo_w)
                srcj = src.reshape(S, hi, 2, lo_w)[:, :, 0, :]
                fired = or_reduce(trans[j], srcj)
                T = jnp.stack([Tr[:, :, 0, :], Tr[:, :, 1, :] | fired],
                              axis=2).reshape(S, W)
        return T

    def prune(T, t, allowed):
        """Keep configs that linearized the target, re-addressed with its
        bit cleared. t<5: in-word shift down; t>=5: word gather."""
        shift = jnp.where(t < 5, jnp.uint32(1) << jnp.minimum(
            t.astype(jnp.uint32), jnp.uint32(4)), jnp.uint32(0))
        wsel = jnp.where(t < 5, w_idx,
                         w_idx | (jnp.int32(1) << jnp.maximum(t - 5, 0)))
        return (T[:, wsel] >> shift) & allowed[None, :]

    return _TableOps(allowed_mask=allowed_mask, or_reduce=or_reduce,
                     transitions=transitions, dense_sweep=dense_sweep,
                     prune=prune)


def live_tile_geometry(cfg: DenseConfig,
                       words: int | None = None) -> tuple[int, int]:
    """(tile_words, n_tiles) of the occupancy tiling for this geometry:
    limits().sparse_tile_words clamped (and rounded down to a power of
    two) against the table width — `words` overrides the width for
    SHARDED tables (per-device word count). THE single copy of the
    tiling policy: the sparse engine (ops/wgl3_sparse.sparse_plan), the
    lattice shard tiling (parallel/lattice.py), and the live-tile-ratio
    telemetry all derive from here, so the gauge's denominator and the
    sweep's actual work unit cannot disagree."""
    w = words if words is not None else (1 << (cfg.k_slots - 5))
    tile = max(1, min(limits().sparse_tile_words, w))
    if tile & (tile - 1):
        tile = 1 << (tile.bit_length() - 1)
    return tile, w // tile


def make_step_fn3(model: Model, cfg: DenseConfig, canon: bool = False,
                  min_frontier: int = 0):
    """Scan body over the bit-packed table (see table_ops for the bit
    algebra). Each step additionally emits the converged table's live-
    TILE count (occupancy over live_tile_geometry tiles) — the telemetry
    behind the wgl.live_tile_ratio gauge and the sparse engine's density
    signal (ops/wgl3_sparse.py); one O(S*W) reduce per step, ~1/K of a
    single sweep's cost.

    With ``canon=True`` the scan inputs gain a per-step compare-exchange
    network (ops/canon.py canon_pairs) and each step canonicalizes the
    CONVERGED frontier before metrics and prune — symmetry-reducing
    equal-effect forever-pending ops, a verdict-preserving quotient (the
    soundness argument lives in ops/canon.py). The step then emits two
    extra outputs (configs pruned by canonicalization, the pre-canon
    count at canon-applied steps). ``min_frontier`` skips the pass on
    converged frontiers below it (always sound; dedup_mode=2 passes 0).
    The default build is byte-identical to the pre-dedup kernel."""
    ops = table_ops(model, cfg)
    allowed_mask, transitions = ops.allowed_mask, ops.transitions
    tile, n_tiles = live_tile_geometry(cfg)
    if canon:
        from .canon import apply_step_canon, make_table_canon

        canon_fn = make_table_canon(1 << (cfg.k_slots - 5))

    def live_tiles(T):
        any_w = jnp.any(T != jnp.uint32(0), axis=0)
        return jnp.sum(jnp.any(any_w.reshape(n_tiles, tile), axis=1),
                       dtype=jnp.int32)

    def step(carry: _Carry3, xs):
        if canon:
            trans, target, idx, pairs = xs
        else:
            trans, target, idx = xs
        is_pad = target < 0
        t = jnp.maximum(target, 0)

        # JIT-linearization banking: configs that already fired the target
        # are kept but never expanded.
        allowed = allowed_mask(t)                            # u32[W]

        def body(st):
            T, n_prev, _changed, rounds = st
            T = ops.dense_sweep(T, allowed, trans)
            n_now = jnp.sum(jax.lax.population_count(T), dtype=jnp.int32)
            return T, n_now, n_now > n_prev, rounds + 1

        def cond(st):
            return st[2] & (st[3] < cfg.rounds)

        n0 = jnp.sum(jax.lax.population_count(carry.table), dtype=jnp.int32)
        T, n, _c, _r = jax.lax.while_loop(
            cond, body, (carry.table, n0, ~is_pad, jnp.int32(0)))

        if canon:
            # Canonicalize the converged frontier BEFORE metrics and
            # prune: max_frontier / configs_explored count UNIQUE
            # (canonical) configs, and the occupancy the sparse signal
            # sees is the reduced one (apply_step_canon gates the pass
            # so quiet steps pay nothing).
            T, n, canon_pruned, canon_base = apply_step_canon(
                canon_fn, T, pairs, n, is_pad, min_frontier)
        live = live_tiles(T)
        pruned = ops.prune(T, t, allowed)
        T_new = jnp.where(is_pad, T, pruned)
        alive = jnp.any(T_new != 0)
        died = ~is_pad & ~carry.dead & ~alive
        dead = carry.dead | died
        T_new = jnp.where(dead, jnp.zeros_like(T_new), T_new)
        outs = (jnp.where(is_pad, 0, n),
                jnp.where(is_pad, 0, live))
        #       pads do no search work: keep the configs-explored and
        #       live-tile metrics padding-invariant (scan buckets here,
        #       chunk alignment in the pallas kernel — both must agree
        #       exactly)
        if canon:
            outs = outs + (canon_pruned, canon_base)
        return _Carry3(
            table=T_new, dead=dead,
            dead_step=jnp.where(died & (carry.dead_step < 0), idx,
                                carry.dead_step),
            max_frontier=jnp.maximum(carry.max_frontier, n)), outs

    return step, transitions


def _init_carry3(model: Model, cfg: DenseConfig) -> _Carry3:
    row = int(model.init_state()) + cfg.state_offset
    table = jnp.zeros((cfg.n_states, 1 << (cfg.k_slots - 5)), jnp.uint32
                      ).at[row, 0].set(jnp.uint32(1))
    return _Carry3(table=table, dead=jnp.bool_(False),
                   dead_step=jnp.int32(-1), max_frontier=jnp.int32(1))


def _check_one_fn(model: Model, cfg: DenseConfig):
    step, transitions = make_step_fn3(model, cfg)
    _, n_tiles = live_tile_geometry(cfg)

    def check(slot_tabs, slot_active, targets):
        carry = _init_carry3(model, cfg)
        idxs = jnp.arange(targets.shape[0], dtype=jnp.int32)
        trans_all = jax.vmap(transitions)(slot_tabs, slot_active)
        final, (ns, lives) = jax.lax.scan(
            step, carry, (trans_all, targets, idxs))
        real = jnp.sum((targets >= 0).astype(jnp.int32))
        # Mean live-tile occupancy over real steps, in per-mille (i32 so
        # it packs with the verdict fields): the telemetry behind the
        # wgl.live_tile_ratio gauge and the sparse engine's motivation —
        # -1 when the history had no real steps.
        live_pm = jnp.where(
            real > 0,
            (jnp.sum(lives.astype(jnp.float32)) * 1000.0
             / (jnp.maximum(real, 1).astype(jnp.float32) * n_tiles)
             ).astype(jnp.int32),
            jnp.int32(-1))
        return {
            "survived": ~final.dead,
            # The dense table is the whole config space: exact by
            # construction. Constant False keeps the v2 result schema (and
            # wgl.verdict's tri-state logic) unchanged.
            "overflow": jnp.bool_(False),
            "dead_step": final.dead_step,
            "max_frontier": final.max_frontier,
            # §5.1 checker metric: total configs live across all return
            # steps (the kernel's unit of search work; configs/sec = this
            # over wall time). f32 accumulator: x64 is disabled under jit
            # and a throughput metric tolerates rounding past 2^24.
            "configs_explored": jnp.sum(ns.astype(jnp.float32)),
            "live_tile_pm": live_pm,
        }

    return check


def make_checker3(model: Model, cfg: DenseConfig):
    """jitted check(slot_tabs[R,K,4], slot_active[R,K], targets[R])."""
    return jax.jit(_check_one_fn(model, cfg))


def _chunk_fn(model: Model, cfg: DenseConfig):
    """jitted (carry, tabs[C,K,4], act[C,K], tgts[C], idx0) ->
    (carry', configs-partial f32 scalar) — the partial sums accumulate
    device-side across chunks and are fetched once at the end. The carry
    is DONATED: every caller threads it linearly (chunk N's output is
    chunk N+1's input and nothing else reads the old buffer), so XLA can
    alias the table in place instead of allocating a fresh one per
    chunk."""
    step, transitions = make_step_fn3(model, cfg)

    def run(carry, tabs, act, tgts, idx0):
        trans = jax.vmap(transitions)(tabs, act)
        idxs = idx0 + jnp.arange(tgts.shape[0], dtype=jnp.int32)
        carry, (ns, lives) = jax.lax.scan(step, carry, (trans, tgts, idxs))
        # Partial sums accumulate device-side across chunks, fetched once
        # at the end — the row layout every chunk consumer (the long
        # sweep below, stream/engine.py finalize) indexes into.
        # jtflow: partials configs_explored,live_tile_sum,real_steps
        return carry, jnp.stack([
            jnp.sum(ns.astype(jnp.float32)),
            jnp.sum(lives.astype(jnp.float32)),
            jnp.sum((tgts >= 0).astype(jnp.float32))])

    return jax.jit(run, donate_argnums=(0,))


def _chunk_fn_dedup(model: Model, cfg: DenseConfig, min_frontier: int):
    """Canonicalizing twin of _chunk_fn: the scan inputs gain the
    per-step exchange network (pairs i32[C, P, 2]) and the partial row
    grows the dedup accounting — configs pruned by canonicalization and
    the pre-canon config count at canon-applied steps (the
    frontier_dedup_ratio denominator). Built ONLY for histories whose
    network is non-empty (canon_pairs returned rows), so the default
    path's compiled shapes never change."""
    step, transitions = make_step_fn3(model, cfg, canon=True,
                                      min_frontier=min_frontier)

    def run(carry, tabs, act, tgts, pairs, idx0):
        trans = jax.vmap(transitions)(tabs, act)
        idxs = idx0 + jnp.arange(tgts.shape[0], dtype=jnp.int32)
        carry, (ns, lives, pruned, base) = jax.lax.scan(
            step, carry, (trans, tgts, idxs, pairs))
        # jtflow: partials configs_explored,live_tile_sum,real_steps,canon_pruned,canon_base
        return carry, jnp.stack([
            jnp.sum(ns.astype(jnp.float32)),
            jnp.sum(lives.astype(jnp.float32)),
            jnp.sum((tgts >= 0).astype(jnp.float32)),
            jnp.sum(pruned.astype(jnp.float32)),
            jnp.sum(base.astype(jnp.float32))])

    return jax.jit(run, donate_argnums=(0,))


def dedup_min_frontier_active(lim=None) -> int:
    """Lazy alias of ops/canon.dedup_min_frontier_active (the policy's
    one copy lives there; the alias keeps wgl3 the import point for the
    sweep-side consumers without a module-level canon import, which
    would be circular)."""
    from .canon import dedup_min_frontier_active as _f

    return _f(lim)


def history_canon_pairs(rs: ReturnSteps, max_bit: int | None = None,
                        table: bool = False):
    """Lazy alias of ops/canon.history_canon_pairs — the ONE copy of
    the dedup engage policy (see its docstring for the auto/force
    scoping rationale)."""
    from .canon import history_canon_pairs as _f

    return _f(rs, max_bit=max_bit, table=table)


def attach_dedup_record(out: dict, pruned: float, base: float) -> None:
    """Fold the chunked partials' canonicalization accounting into the
    result dict: configs pruned, the pre-canon base count, and the
    frontier_dedup_ratio (pruned/base over canon-applied steps) behind
    the wgl.configs_pruned counter and wgl.frontier_dedup_ratio gauge
    (obs.record_check_result). ONE copy shared by the dense, sparse,
    and lattice long sweeps."""
    pruned = max(0, int(pruned))
    base = max(0, int(base))
    out["dedup"] = {
        "configs_pruned": pruned,
        "canon_base": base,
        "frontier_dedup_ratio": round(pruned / base, 4) if base else 0.0,
    }


def default_scan_chunk(cfg: DenseConfig) -> int:
    """Host-loop chunk size: scales inversely with table width (sweep cost
    per step is proportional to cells). Floor 128: at the chunked-budget
    cell ceiling a step costs ~70 ms, so even the floor chunk stays ~10 s
    — safely under the worker's program-kill threshold. ONE copy shared by
    the long sweep and witness frontier recovery so a tuning change can't
    leave one of them outside the envelope."""
    cells = cfg.n_states * cfg.n_masks
    base = limits().long_scan_chunk
    return min(base, max(128, base * (1 << 15) // max(cells, 1)))


def _cached_chunk_run(model: Model, cfg: DenseConfig, chunk: int):
    key = ("chunk3", model.cache_key(), cfg, chunk)
    if key not in _CACHE:
        # instrument_kernel (obs/): compile/execute attribution, one
        # first-call flag per compiled geometry (this cache's key).
        _CACHE[key] = instrument_kernel("wgl3-chunk", _chunk_fn(model, cfg))
    return _CACHE[key]


def _cached_chunk_run_dedup(model: Model, cfg: DenseConfig, chunk: int,
                            min_frontier: int):
    key = ("chunk3-dedup", model.cache_key(), cfg, chunk, min_frontier)
    if key not in _CACHE:
        _CACHE[key] = instrument_kernel(
            "wgl3-chunk-dedup", _chunk_fn_dedup(model, cfg, min_frontier))
    return _CACHE[key]


def sweep_summary(cfg: DenseConfig, live_sum: float, real_steps: int,
                  sparse_steps: int = 0,
                  tiling: tuple[int, int] | None = None,
                  overflow_rounds: int = 0) -> dict:
    """The per-run sweep-mode/occupancy record the long sweeps attach to
    their result dicts (and record_check_result folds into the metrics
    registry): which sweep mode the steps ran under and the mean live-
    tile ratio of the converged tables. One copy shared by the dense and
    sparse long sweeps (and the lattice-sharded form, which passes its
    own (tile_words, global tile count) `tiling`) so the bench's
    `sparse` lane and the telemetry artifact cannot drift apart."""
    tile, n_tiles = tiling if tiling is not None else live_tile_geometry(cfg)
    real = max(0, int(real_steps))
    sparse = min(max(0, int(sparse_steps)), real)
    dense = real - sparse
    if real == 0 or sparse == 0:
        mode = "dense"
    elif dense == 0:
        mode = "sparse"
    else:
        mode = "mixed"
    ratio = (float(live_sum) / (real * n_tiles)) if real else 0.0
    return {"mode": mode,
            "live_tile_ratio": round(min(max(ratio, 0.0), 1.0), 4),
            "steps_sparse": sparse, "steps_dense": dense,
            "tiles": n_tiles, "tile_words": tile,
            # Work-list overflows that forced a dense closure round
            # (ops/wgl3_sparse.py — the previously-silent fallback,
            # surfaced as the wgl.sparse_overflow_rounds counter).
            "overflow_rounds": max(0, int(overflow_rounds))}


def check_steps3_long(rs: ReturnSteps, model: Model, cfg: DenseConfig,
                      chunk: int | None = None,
                      time_budget_s: float | None = None,
                      spill_tag: str | None = None) -> dict:
    """Single-history dense check for histories whose step count exceeds
    one scan program: pad to a chunk multiple, loop chunks host-side.
    Bit-identical to check_steps3 (same step fn; pads contribute nothing).

    Geometries with enough occupancy tiles route to the sparse
    active-tile engine (ops/wgl3_sparse.py — limits().sparse_mode gates
    it): same chunked host loop, but each closure round gathers only the
    LIVE tiles of the table and falls back to a dense sweep past the
    density threshold, so per-step cost tracks the live frontier instead
    of 2^K. Verdicts are bit-identical either way (the sparse round
    reaches the same closure fixpoint).

    Chunk size scales inversely with table width so one chunk's wall time
    stays far under the axon worker's program-kill threshold (sweep cost
    per step is proportional to the cell count). `time_budget_s` bounds
    wall time between chunks; expiry returns the honest tri-state
    "unknown" with overflow=True (same contract as the sort ladder,
    ops/wgl2.py).

    Without a budget the chunk loop is PIPELINED (sched/pipeline.py):
    chunk N+1's slices transfer while chunk N executes (async dispatch —
    the carry chains device-side, with the frontier buffer donated), and
    the death-poll fetch happens only every limits().sched_poll_chunks
    chunks instead of per chunk — dead chunks in between are near-free
    (the closure exits immediately on an empty table) and death-sticky
    carries keep dead_step/max_frontier exact, so the result is
    bit-identical to the per-chunk loop. The budgeted path stays
    synchronous per chunk: the budget check must see device time.

    `spill_tag` (with an active store/spill.py SpillDir and the
    host_spill_mode policy engaged) spills the packed table at chunk
    seams — the death-poll cadence, so the explicit host fetch the
    DONATED carry requires rides the same sync the poll already pays —
    and resumes from a matching checkpoint on re-entry. A torn or
    mismatched checkpoint degrades to recompute from the start, never
    a wrong verdict. The sparse-engine route ignores the tag (its
    carry is gathered, not a whole table)."""
    import time as _time

    from ..sched.pipeline import double_buffer
    from .wgl3_sparse import check_steps3_long_sparse, sparse_plan

    plan = sparse_plan(cfg)
    if plan is not None:
        return check_steps3_long_sparse(rs, model, cfg, plan, chunk=chunk,
                                        time_budget_s=time_budget_s)
    t0 = _time.monotonic()
    if chunk is None:
        chunk = default_scan_chunk(cfg)
    n = rs.n_steps
    n_pad = (n + chunk - 1) // chunk * chunk
    rs = rs.padded_to(n_pad)
    # Frontier canonicalization (ops/canon.py): when the history carries
    # equal-effect forever-pending ops (and dedup_mode allows), the scan
    # threads the per-step exchange network and every step symmetry-
    # reduces the converged frontier. Histories with no symmetry — the
    # common case — take the byte-identical pre-dedup chunk fn.
    pairs = history_canon_pairs(rs, table=True)
    if pairs is not None:
        run = _cached_chunk_run_dedup(model, cfg, chunk,
                                      dedup_min_frontier_active())
    else:
        run = _cached_chunk_run(model, cfg, chunk)
    carry = _init_carry3(model, cfg)
    cfgs_dev = None
    # Out-of-core seam checkpoints (ISSUE 20): engaged only with an
    # active SpillDir, a caller tag, and the host_spill_mode policy
    # saying yes for this history's host working set.
    from ..store import spill as _spill

    sdir = _spill.active_spill() if spill_tag is not None else None
    do_spill = False
    ck_name = None
    start_c = 0
    n_words = 1 << (cfg.k_slots - 5)
    if sdir is not None:
        est_mb = (rs.slot_tabs.nbytes + rs.slot_active.nbytes
                  + rs.targets.nbytes) / (1 << 20)
        do_spill = _spill.spill_active(est_mb)
    if do_spill:
        ck_name = f"{spill_tag}.ck3"
        d = _spill.load_frontier(sdir, ck_name)
        mt = (d or {}).get("meta") or {}
        if d is not None and mt.get("n_steps") == n_pad \
                and mt.get("chunk") == chunk \
                and mt.get("shape") == [cfg.n_states, n_words] \
                and 0 < int(mt.get("pos", 0)):
            # Resume from the spilled seam checkpoint (only live seams
            # are spilled, so dead/dead_step reset is exact).
            carry = _Carry3(table=jnp.asarray(d["masks"]),
                            dead=jnp.bool_(False),
                            dead_step=jnp.int32(-1),
                            max_frontier=jnp.int32(
                                int(mt.get("max_frontier", 1))))
            if mt.get("cfgs") is not None:
                cfgs_dev = jnp.asarray(
                    np.asarray(mt["cfgs"], np.float32))
            start_c = int(mt["pos"])

    def seam_spill(done_c: int) -> None:
        # The chunk fn DONATES its carry, so the seam checkpoint pays
        # an explicit host fetch — scheduled at the death-poll cadence,
        # where the pipeline already syncs. Raw codec route: the packed
        # table is not per-config class bits, but a sparse table is
        # mostly zero words and the frame compresses it anyway.
        tbl = np.asarray(carry.table)
        cf = None if cfgs_dev is None \
            else [float(x) for x in np.asarray(cfgs_dev)]
        _spill.spill_frontier(
            sdir, ck_name, np.arange(tbl.shape[0], dtype=np.int32),
            tbl, np.ones(tbl.shape[0], bool),
            meta={"pos": done_c, "n_steps": n_pad, "chunk": chunk,
                  "shape": [int(tbl.shape[0]), int(tbl.shape[1])],
                  "max_frontier": int(np.asarray(carry.max_frontier)),
                  "cfgs": cf})

    if time_budget_s is None:
        poll = max(1, limits().sched_poll_chunks)

        def stage(c):
            sl = slice(c * chunk, (c + 1) * chunk)
            staged = (jnp.asarray(rs.slot_tabs[sl]),
                      jnp.asarray(rs.slot_active[sl]),
                      jnp.asarray(rs.targets[sl]))
            if pairs is not None:
                staged = staged + (jnp.asarray(pairs[sl]),)
            return staged + (jnp.int32(c * chunk),)

        done = 0
        for staged in double_buffer(range(start_c, n_pad // chunk),
                                    stage):
            carry, part = run(carry, *staged)
            cfgs_dev = part if cfgs_dev is None else cfgs_dev + part
            done += 1
            if done % poll == 0:
                # jtlint: disable=JTL103 -- bounded death poll: one
                # fetch per sched_poll_chunks chunks (the [tunable]
                # knob), not per iteration — the doc/perf.md early-exit
                # contract; the seam spill rides the same sync.
                if bool(np.asarray(carry.dead)):
                    break
                if do_spill:
                    seam_spill(start_c + done)
    else:
        for c in range(start_c, n_pad // chunk):
            if _time.monotonic() - t0 > time_budget_s:
                return {"valid": "unknown", "survived": False,
                        "overflow": True, "dead_step": -1,
                        "max_frontier": -1, "configs_explored": -1,
                        "kernel": "exhausted",
                        "error": f"dense-chunked sweep exceeded its "
                                 f"{time_budget_s:.0f}s time budget at "
                                 f"return step {c * chunk}"}
            sl = slice(c * chunk, (c + 1) * chunk)
            args = (jnp.asarray(rs.slot_tabs[sl]),
                    jnp.asarray(rs.slot_active[sl]),
                    jnp.asarray(rs.targets[sl]))
            if pairs is not None:
                args = args + (jnp.asarray(pairs[sl]),)
            carry, part = run(carry, *args, jnp.int32(c * chunk))
            cfgs_dev = part if cfgs_dev is None else cfgs_dev + part
            # Early exit on death: one 1-byte fetch per chunk (~0.1 s on
            # a tunneled backend) vs minutes of dead chunks on wide
            # tables.
            # jtlint: disable=JTL103 -- budgeted lane is synchronous BY
            # CONTRACT: the budget check must see device time, so the
            # per-chunk fetch is the bound on overshoot.
            if bool(np.asarray(carry.dead)):
                break
            if do_spill:
                seam_spill(c + 1)
    from .wgl import verdict

    n_parts = 5 if pairs is not None else 3
    if cfgs_dev is None:
        cfgs_dev = jnp.zeros((n_parts,), jnp.float32)
    # One packed fetch at the end (chunks chain device-side): 3 verdict
    # fields + the chunk fn's declared partial row.
    # jtflow: partials-from wgl3._chunk_fn
    # jtflow: partials-from wgl3._chunk_fn_dedup
    packed = np.asarray(jnp.concatenate([
        jnp.stack([jnp.where(carry.dead, 0, 1),
                   carry.dead_step, carry.max_frontier]),
        jnp.clip(cfgs_dev, 0, 2**31 - 1).astype(jnp.int32)]))
    out = {
        "survived": bool(packed[0]),
        "overflow": False,
        "dead_step": int(packed[1]),
        "max_frontier": int(packed[2]),
        "configs_explored": int(packed[3]),
    }
    out["sweep"] = sweep_summary(cfg, live_sum=float(packed[4]),
                                 real_steps=int(packed[5]))
    out["live_tile_ratio"] = out["sweep"]["live_tile_ratio"]
    if pairs is not None:
        # The canon columns are the LAST two of the dedup layout by
        # construction (wgl3._chunk_fn_dedup) — negative indexing keeps
        # the base-layout reads above layout-checkable (JTL401).
        attach_dedup_record(out, pruned=float(packed[-2]),
                            base=float(packed[-1]))
    out["valid"] = verdict(out)
    record_check_result(out)
    return out


def recover_table3(rs: ReturnSteps, model: Model, cfg: DenseConfig,
                   upto_step: int,
                   chunk: int | None = None) -> list[tuple[int, int]]:
    """EXACT reachable-config set after the first `upto_step` return steps:
    run the chunked dense scan that far, fetch the table once, decode the
    set bits host-side. Returns [(state_value, linearized-mask), ...].

    This is the frontier-recovery half of big-history witness extraction
    (checkers/witness.py): the kernel knows WHERE a search died
    (dead_step) but keeps no lineage; recovering the frontier shortly
    before the death point lets the host replay only a bounded window
    instead of the whole exponential prefix."""
    if chunk is None:
        chunk = default_scan_chunk(cfg)
    run = _cached_chunk_run(model, cfg, chunk)
    upto = min(upto_step, rs.n_steps)
    # Truncate to the prefix, then pad the tail chunk with -1 targets
    # (pad steps leave the table untouched).
    n_pad = max(1, (upto + chunk - 1) // chunk) * chunk
    pre = ReturnSteps(rs.slot_tabs[:upto], rs.slot_active[:upto],
                      rs.targets[:upto], upto, rs.n_ops, rs.k_slots,
                      rs.max_pending, rs.max_value).padded_to(n_pad)
    carry = _init_carry3(model, cfg)
    for c in range(n_pad // chunk):
        sl = slice(c * chunk, (c + 1) * chunk)
        carry, _ = run(carry, jnp.asarray(pre.slot_tabs[sl]),
                       jnp.asarray(pre.slot_active[sl]),
                       jnp.asarray(pre.targets[sl]),
                       jnp.int32(c * chunk))
    table = np.asarray(carry.table)            # u32[S, W]
    configs = []
    for srow in range(cfg.n_states):
        for w in np.nonzero(table[srow])[0]:
            bits = int(table[srow, w])
            while bits:
                b = bits & -bits
                configs.append((srow - cfg.state_offset,
                                int(w) * 32 + b.bit_length() - 1))
                bits ^= b
    return configs


def make_batch_checker3(model: Model, cfg: DenseConfig):
    """jitted check over a batch: slot_tabs[B,R,K,4], ... -> [B] results."""
    return jax.jit(jax.vmap(_check_one_fn(model, cfg)))


# -- packed results ------------------------------------------------------
# One device->host fetch per launch: the result dict is stacked into a
# single i32[..., 5] tensor on device and split on host. This matters a
# lot on tunneled/remote TPU backends where every small fetch pays a full
# network round trip (~0.1 s each: fetching the 5-key dict costs more
# than the whole search at tutorial scale).

PACKED_FIELDS = ("survived", "overflow", "dead_step", "max_frontier",
                 "configs_explored")
# The XLA checkers append a 6th telemetry column: mean live-tile
# occupancy in per-mille (live_tile_pm; -1 = not measured). The pallas
# kernels keep the 5-column layout — unpack_np accepts both widths, so
# the two backends' fetch contract stays one packed i32 tensor.
PACKED_FIELDS_XLA = PACKED_FIELDS + ("live_tile_pm",)


# jtflow: packs wgl3.PACKED_FIELDS_XLA
def _pack_result(out: dict) -> jax.Array:
    cfgs = jnp.clip(out["configs_explored"], 0, 2**31 - 1).astype(jnp.int32)
    return jnp.stack([out["survived"].astype(jnp.int32),
                      out["overflow"].astype(jnp.int32),
                      out["dead_step"], out["max_frontier"], cfgs,
                      out["live_tile_pm"]], axis=-1)


# jtflow: unpacks wgl3.PACKED_FIELDS_XLA
def unpack_np(arr) -> dict:
    """np i32[..., 5|6] (one fetch) -> result dict of np arrays/scalars.
    The 6th column (live_tile_pm), when present, is the XLA checkers'
    occupancy telemetry; pallas launches emit 5 columns and report -1."""
    arr = np.asarray(arr)
    get_metrics().counter("wgl.d2h_bytes").add(int(arr.nbytes))
    pm = (arr[..., 5] if arr.shape[-1] > 5
          else np.full(arr.shape[:-1], -1, np.int32))
    return {"survived": arr[..., 0] != 0, "overflow": arr[..., 1] != 0,
            "dead_step": arr[..., 2], "max_frontier": arr[..., 3],
            "configs_explored": arr[..., 4], "live_tile_pm": pm}


_CACHE: dict[tuple, Any] = {}


def cached_batch_checker3(model: Model, cfg: DenseConfig):
    key = ("batch3", model.cache_key(), cfg)
    if key not in _CACHE:
        _CACHE[key] = instrument_kernel("wgl3-batch",
                                        make_batch_checker3(model, cfg))
    return _CACHE[key]


def cached_checker3_packed(model: Model, cfg: DenseConfig):
    key = ("single3p", model.cache_key(), cfg)
    if key not in _CACHE:
        fn = _check_one_fn(model, cfg)
        # jtflow: packed wgl3.PACKED_FIELDS_XLA
        _CACHE[key] = instrument_kernel(
            "wgl3-single", jax.jit(lambda *a: _pack_result(fn(*a))))
    return _CACHE[key]


def cached_batch_checker3_packed(model: Model, cfg: DenseConfig):
    key = ("batch3p", model.cache_key(), cfg)
    if key not in _CACHE:
        fn = jax.vmap(_check_one_fn(model, cfg))
        # jtflow: packed wgl3.PACKED_FIELDS_XLA
        _CACHE[key] = instrument_kernel(
            "wgl3-batch", jax.jit(lambda *a: _pack_result(fn(*a))))
    return _CACHE[key]


def tight_k_for_pending(max_pending: int) -> int:
    """Smallest mask width serving this max_pending, rounded up to even
    so nearby concurrencies share one jit cache entry; floor 6 because
    the packed table needs K >= 5 (and 2^6 masks = 2 words is already
    tiny). The ONE definition of the tight geometry — the streaming
    engine (stream/engine.py) keys on it over a running max_pending, so
    any retune here keeps streamed and post-hoc geometries identical."""
    return max(6, (max_pending + 1) // 2 * 2)


def tight_k_slots(enc: EncodedHistory) -> int:
    """tight_k_for_pending over an encoded history."""
    return tight_k_for_pending(enc.max_pending)


def step_bucket(n_steps: int, floor: int | None = None) -> int:
    """Pad scan lengths to {2^k, 1.5*2^k} buckets: bounded recompiles
    across a corpus of varying history lengths, ≤33% padded steps (pads are
    cheap — the closure while_loop exits immediately on a pad step — but
    the scan still walks them). The default floor is the tunable
    limits().step_bucket_floor — the same boundary set the corpus
    scheduler (sched/engine.py) groups launches by."""
    if floor is None:
        floor = limits().step_bucket_floor
    r = floor
    while r < n_steps:
        if r + r // 2 >= n_steps:
            return r + r // 2
        r *= 2
    return r


def check_steps3(rs: ReturnSteps, model: Model | None = None,
                 cfg: DenseConfig | None = None) -> dict:
    """Single-history entry point over the return-major encoding.

    Low-level: uses rs.k_slots as the mask width verbatim. Callers with an
    EncodedHistory should prefer check_encoded3, which first tightens the
    slot table to the history's real concurrency (a default 32-wide encoding
    would always be rejected here)."""
    from .wgl import verdict

    if model is None:
        from ..models import CASRegister
        model = CASRegister()
    if cfg is None:
        cfg = dense_config(model, rs.k_slots, rs.max_value)
    if cfg is None:
        raise ValueError(
            f"dense kernel infeasible for k_slots={rs.k_slots}, "
            f"max_value={rs.max_value}; use the sort kernel (wgl2)")
    check = cached_checker3_packed(model, cfg)
    out = unpack_np(check(jnp.asarray(rs.slot_tabs),
                          jnp.asarray(rs.slot_active),
                          jnp.asarray(rs.targets)))
    out["valid"] = verdict(out)
    out["configs_explored"] = int(out["configs_explored"])
    out["max_frontier"] = int(out["max_frontier"])
    attach_live_ratio(out)
    record_check_result(out)
    return out


def prepare_dense(enc: EncodedHistory, model: Model,
                  cfg: DenseConfig | None = None
                  ) -> tuple[DenseConfig, ReturnSteps]:
    """Host-side single-history prep shared by check_encoded3 and the
    driver entry (__graft_entry__): tighten the slot table to the
    history's real concurrency, decide dense feasibility, and bucket the
    scan length. `cfg` (when the caller already computed the feasibility
    decision) must come from dense_config(model, tight_k_slots(enc),
    enc.max_value)."""
    from .encode import reslot_events

    k = tight_k_slots(enc)
    if cfg is None:
        cfg = dense_config(model, k, enc.max_value)
    if cfg is None:
        raise ValueError(
            f"dense kernel infeasible: max_pending={enc.max_pending}, "
            f"max_value={enc.max_value}; use the sort kernel (wgl2)")
    if enc.k_slots != k:
        enc = reslot_events(enc, k)
    rs = encode_return_steps(enc)
    padded = rs.padded_to(step_bucket(rs.n_steps))
    _record_padding([rs], padded.slot_tabs.shape[0])
    return cfg, padded


def check_encoded3(enc: EncodedHistory, model: Model | None = None,
                   cfg: DenseConfig | None = None) -> dict:
    """Tighten the slot table to the history's real concurrency, bucket the
    scan length, and run the dense kernel."""
    if model is None:
        from ..models import CASRegister
        model = CASRegister()
    cfg, rs = prepare_dense(enc, model, cfg)
    return check_steps3(rs, model, cfg)


def batch_steps3(encs: Sequence[EncodedHistory], model: Model,
                 cfg: DenseConfig | None = None):
    """HOST-side half of the batched-launch plumbing: tighten/reslot/
    encode a batch into per-history ReturnSteps and the bucketed common
    step count. No device transfer happens here, so routers can inspect
    (cfg, r_cap) and choose a backend before committing tens of MB to a
    (possibly tunneled) device."""
    from .encode import reslot_events

    k = max(tight_k_slots(e) for e in encs)
    if cfg is None:
        cfg = dense_config(model, k, max(e.max_value for e in encs))
    if cfg is None:
        raise ValueError("dense kernel infeasible for this batch")
    steps = [encode_return_steps(
        reslot_events(e, k) if e.k_slots != k else e) for e in encs]
    r_cap = step_bucket(max(s.n_steps for s in steps))
    return cfg, steps, r_cap


def _record_padding(steps, r_cap: int) -> None:
    """Telemetry (obs/): per-launch step-bucket padding waste. Pads are
    cheap (the closure exits immediately; the fused kernel never even
    executes them) but the scan still walks them in the XLA path — the
    gauges make the waste visible per launch instead of folklore. Two
    views per launch: the padded percentage (step_padding_pct) and the
    padded/real RATIO (step_padding_ratio — the number the scheduler's
    <2x bucket-waste bound is stated in), plus running real/padded step
    counters so consumers can aggregate an exact corpus-wide ratio
    instead of averaging per-launch gauges."""
    real = int(sum(s.n_steps for s in steps))
    total = len(steps) * int(r_cap)
    if total:
        m = get_metrics()
        m.gauge("wgl.step_padding_pct").set(100.0 * (1.0 - real / total))
        m.counter("wgl.steps_real").add(real)
        m.counter("wgl.steps_padded").add(total)
        if real:
            m.gauge("wgl.step_padding_ratio").set(total / real)


def stack_steps3(steps, r_cap: int):
    """DEVICE-side half: pad to the common step count, stack, transfer."""
    padded = [s.padded_to(r_cap) for s in steps]
    tabs = np.stack([p.slot_tabs for p in padded])
    act = np.stack([p.slot_active for p in padded])
    tgt = np.stack([p.targets for p in padded])
    _record_padding(steps, r_cap)
    nbytes = int(tabs.nbytes + act.nbytes + tgt.nbytes)
    get_metrics().counter("wgl.h2d_bytes").add(nbytes)
    # Scaling ledger: the host->device staging enqueue wall + bytes (a
    # lower bound on transfer time — async backends overlap the copy).
    t0_ns = time.monotonic_ns()
    out = jnp.asarray(tabs), jnp.asarray(act), jnp.asarray(tgt)
    get_ledger().record_h2d(nbytes, t0_ns, time.monotonic_ns())
    return out


def batch_arrays3(encs: Sequence[EncodedHistory], model: Model,
                  cfg: DenseConfig | None = None):
    """Tighten/reslot/encode/pad/stack a batch of event encodings for one
    vmapped dense launch. Returns (cfg, (tabs, act, tgt), steps) — `steps`
    are the per-history ReturnSteps (for op counts etc)."""
    cfg, steps, r_cap = batch_steps3(encs, model, cfg)
    return cfg, stack_steps3(steps, r_cap), steps


def attach_live_ratio(out: dict) -> None:
    """Fold the packed live_tile_pm telemetry column into the friendly
    live_tile_ratio float (dropped when the launch didn't measure it —
    pallas emits -1)."""
    pm = out.pop("live_tile_pm", -1)
    try:
        pm = int(pm)
    except (TypeError, ValueError):
        pm = -1
    if pm >= 0:
        out["live_tile_ratio"] = min(pm / 1000.0, 1.0)


def assemble_batch_results(out: dict, steps, cfg: DenseConfig) -> list[dict]:
    """Unpacked [B]-array results -> one result dict per history
    (v2-compatible schema + valid). Shared by the XLA and pallas batch
    entry points so the two backends cannot drift apart in schema."""
    from .wgl import verdict

    results = []
    for i, s in enumerate(steps):
        one = {k: out[k][i].item() for k in out}
        one["valid"] = verdict(one)
        one["op_count"] = s.n_ops
        one["configs_explored"] = int(one["configs_explored"])
        one["table_cells"] = cfg.n_states * cfg.n_masks
        attach_live_ratio(one)
        record_check_result(one)
        results.append(one)
    return results


def check_batch_encoded3(encs: Sequence[EncodedHistory],
                         model: Model | None = None) -> list[dict]:
    """Check a batch of histories in one vmapped dense launch; returns one
    result dict per history (v2-compatible schema + valid)."""
    if model is None:
        from ..models import CASRegister
        model = CASRegister()
    cfg, arrays, steps = batch_arrays3(encs, model)
    check = cached_batch_checker3_packed(model, cfg)
    return assemble_batch_results(unpack_np(check(*arrays)), steps, cfg)
