"""Operation records — the atoms of a history.

Mirrors the reference's op shape `{:type, :f, :value, :process, :time, :index,
:error}` (op constructors at reference src/jepsen/etcdemo.clj:67-69; completion
types assigned in Client.invoke! at src/jepsen/etcdemo.clj:83-105).

Completion semantics (load-bearing for the checker, see reference
src/jepsen/etcdemo.clj:100-105):
  ok    — the op definitely took effect.
  fail  — the op definitely did NOT take effect (excluded from linearizability).
  info  — indeterminate: may have taken effect at any point after its invoke,
          arbitrarily far in the future ("open forever").
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field, asdict
from typing import Any, Optional

# Op types.
INVOKE = "invoke"
OK = "ok"
FAIL = "fail"
INFO = "info"

COMPLETION_TYPES = (OK, FAIL, INFO)


@dataclass
class Op:
    """One history entry: either an invocation or its completion."""

    type: str                      # invoke | ok | fail | info
    f: str                         # e.g. read | write | cas | add | start | stop
    value: Any = None              # op-dependent payload (may be a (key, v) tuple)
    process: Any = None            # logical process id (int) or "nemesis"
    time: int = 0                  # nanoseconds relative to test start
    index: int = -1                # position in the recorded history
    error: Optional[Any] = None    # e.g. "timeout", "not-found"
    # Monotonic record sequence number, assigned by the HistoryRecorder
    # at append time from a process-local counter (NOT wall clock): the
    # total order the streaming checker's stable-prefix watermark keys
    # on, stable under thread-scheduling jitter even when monotonic_ns
    # ties. -1 = never recorded (hand-built ops, pre-seq artifacts).
    seq: int = -1
    extra: dict = field(default_factory=dict)

    def is_invoke(self) -> bool:
        return self.type == INVOKE

    def is_completion(self) -> bool:
        return self.type in COMPLETION_TYPES

    def to_json(self) -> str:
        d = asdict(self)
        if not d["extra"]:
            d.pop("extra")
        if d["seq"] < 0:
            d.pop("seq")   # keep pre-seq artifacts byte-stable
        return json.dumps(d, default=_jsonable)

    @staticmethod
    def from_json(line: str) -> "Op":
        d = json.loads(line)
        d.setdefault("extra", {})
        d.setdefault("seq", -1)
        # JSON round-trips tuples as lists; normalize 2-lists back to tuples so
        # (key, value) independent-tuples survive store round trips.
        v = d.get("value")
        if isinstance(v, list) and len(v) == 2:
            d["value"] = tuple(v)
        return Op(**d)


def _jsonable(x):
    if isinstance(x, (set, frozenset)):
        return sorted(x)
    if isinstance(x, tuple):
        return list(x)
    return str(x)


def invoke(f: str, value: Any = None, process: Any = 0, time: int = 0) -> Op:
    return Op(type=INVOKE, f=f, value=value, process=process, time=time)


def history_to_jsonl(history: list[Op]) -> str:
    return "\n".join(op.to_json() for op in history) + "\n"


def history_from_jsonl(text: str) -> list[Op]:
    return [Op.from_json(line) for line in text.splitlines() if line.strip()]
