"""Wing–Gong linearizability search as a JAX/XLA kernel.

This replaces the reference's compute hot loop — knossos's JVM state-space
search invoked at src/jepsen/etcdemo.clj:117 — with a static-shape, TPU-
compilable frontier search (BASELINE.json north star).

Shape of the computation:

  * A *configuration* is (model state: int32, linearized set: bitmask over
    `k_slots` pending-op slots). The frontier is a fixed-capacity tensor of
    configurations: states[F], masks[F, W] (W = k_slots/32 uint32 words),
    valid[F].
  * `lax.scan` walks the event tensor (encode.py). EV_INVOKE loads the op
    into its slot table row. EV_RETURN runs the expansion closure — a bounded
    `lax.while_loop` that repeatedly fires every legal pending op from every
    config (vmapped model step over frontier × slots), merges candidates with
    the existing frontier, and dedups by sort — then prunes to configs that
    linearized the returning op, clears its bit, and frees the slot.
  * Dedup is sort-based (jnp.lexsort over state + mask words) because a hash
    set is not a TPU-friendly structure; this mirrors knossos's memoization
    (high-scale-lib concurrent sets on the JVM) with sorted uniqueness.

Soundness under overflow: dropping configurations when the frontier exceeds
capacity can only lose linearization witnesses. A run that *survives* is
therefore a genuine proof of linearizability regardless of overflow; a run
that dies after overflowing is reported "unknown" rather than invalid.

The whole search is data-independent in shape, so it vmaps over a batch of
histories (the per-key axis of jepsen.independent, src/jepsen/etcdemo.clj:115,
120-125) and shards over a device mesh (parallel/).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.base import Model
from .encode import EncodedHistory, EV_INVOKE, EV_RETURN, EVENT_WIDTH


class _Carry(NamedTuple):
    states: jax.Array       # i32[F]
    masks: jax.Array        # u32[F, W]
    valid: jax.Array        # bool[F]
    slot_tab: jax.Array     # i32[K, 4] (f, a1, a2, rv)
    slot_active: jax.Array  # bool[K]
    dead: jax.Array         # bool
    overflow: jax.Array     # bool
    dead_event: jax.Array   # i32
    max_frontier: jax.Array  # i32


@dataclass(frozen=True)
class WGLConfig:
    k_slots: int = 32       # pending-op slot capacity (bitmask width)
    f_cap: int = 256        # frontier capacity (configs kept after dedup)
    max_expand_rounds: int | None = None  # closure depth bound; default k_slots
    # >0 enables the packed single-uint32 dedup in the v2 kernel: every
    # reachable model state must fit in `state_bits` bits after the model's
    # state_offset. Derive from the HISTORY's actual values
    # (model.pack_bits(enc.max_value)) — never assume a value range.
    state_bits: int = 0

    @property
    def words(self) -> int:
        return (self.k_slots + 31) // 32

    @property
    def rounds(self) -> int:
        return self.max_expand_rounds or self.k_slots


def _slot_constants(cfg: WGLConfig):
    k, w = cfg.k_slots, cfg.words
    word = np.arange(k) // 32
    bit = np.arange(k) % 32
    slot_bitmask = np.zeros((k, w), dtype=np.uint32)
    slot_bitmask[np.arange(k), word] = np.uint32(1) << bit.astype(np.uint32)
    return (jnp.asarray(word, jnp.int32), jnp.asarray(bit, jnp.uint32),
            jnp.asarray(slot_bitmask))


def _dedup(states, masks, valid, f_cap):
    """Sort rows by (valid desc, state, mask words), keep unique valid rows,
    compact into a fresh fixed-capacity frontier."""
    w = masks.shape[-1]
    invalid = (~valid).astype(jnp.int32)
    # lexsort: last key is primary. Primary: invalid flag (valid rows first);
    # then state; then mask words for a total order on content.
    keys = tuple(masks[:, i].astype(jnp.uint32) for i in range(w - 1, -1, -1))
    order = jnp.lexsort(keys + (states, invalid))
    s_states = states[order]
    s_masks = masks[order]
    s_valid = valid[order]
    eq_prev = jnp.concatenate([
        jnp.array([False]),
        (s_states[1:] == s_states[:-1])
        & jnp.all(s_masks[1:] == s_masks[:-1], axis=-1),
    ])
    unique = s_valid & ~eq_prev
    n_unique = jnp.sum(unique.astype(jnp.int32))
    dest = jnp.where(unique, jnp.cumsum(unique.astype(jnp.int32)) - 1, f_cap)
    new_states = jnp.zeros((f_cap,), jnp.int32).at[dest].set(
        s_states, mode="drop")
    new_masks = jnp.zeros((f_cap, masks.shape[-1]), jnp.uint32).at[dest].set(
        s_masks, mode="drop")
    new_valid = jnp.arange(f_cap) < jnp.minimum(n_unique, f_cap)
    return new_states, new_masks, new_valid, n_unique


def make_step_fn(model: Model, cfg: WGLConfig):
    """Build the per-event scan body (the jittable unit)."""
    word_of, bit_of, slot_bitmask = _slot_constants(cfg)
    f_cap, k = cfg.f_cap, cfg.k_slots

    def bits_set(masks):
        # masks u32[F, W] -> {0,1}[F, K]: is each slot's bit set?
        return (masks[:, word_of] >> bit_of) & jnp.uint32(1)

    def expand_once(states, masks, valid, slot_tab, slot_active, t_word,
                    t_bit):
        f = slot_tab[:, 0]
        a1 = slot_tab[:, 1]
        a2 = slot_tab[:, 2]
        rv = slot_tab[:, 3]
        legal, nxt = jax.vmap(lambda s: model.step(s, f, a1, a2, rv))(states)
        # Just-in-time linearization (Lowe; knossos :linear): only expand
        # configs that have NOT yet fired the returning op. Once the target
        # is fired a config is banked as-is — anything reachable beyond it
        # is regenerable at the next return's closure, so storing only the
        # boundary keeps the frontier minimal.
        not_done = ((masks[:, t_word] >> t_bit) & jnp.uint32(1)) == 0  # [F]
        cand_valid = (valid[:, None] & not_done[:, None]
                      & slot_active[None, :]
                      & (bits_set(masks) == 0) & legal)          # [F, K]
        cand_masks = masks[:, None, :] | slot_bitmask[None, :, :]  # [F, K, W]
        all_states = jnp.concatenate([states, nxt.reshape(-1)])
        all_masks = jnp.concatenate(
            [masks, cand_masks.reshape(-1, cfg.words)])
        all_valid = jnp.concatenate([valid, cand_valid.reshape(-1)])
        return _dedup(all_states, all_masks, all_valid, f_cap)

    def closure(states, masks, valid, slot_tab, slot_active, overflow,
                t_word, t_bit):
        n0 = jnp.sum(valid.astype(jnp.int32))

        def cond(st):
            _s, _m, _v, n_prev, changed, _o, it = st
            return changed & (it < cfg.rounds)

        def body(st):
            s, m, v, n_prev, _c, o, it = st
            s2, m2, v2, n_unique = expand_once(s, m, v, slot_tab,
                                               slot_active, t_word, t_bit)
            o = o | (n_unique > f_cap)
            n_now = jnp.minimum(n_unique, f_cap)
            return (s2, m2, v2, n_now, n_now > n_prev, o, it + 1)

        init = (states, masks, valid, n0, jnp.bool_(True), overflow,
                jnp.int32(0))
        s, m, v, n, _c, o, _it = jax.lax.while_loop(cond, body, init)
        return s, m, v, n, o

    def step(carry: _Carry, ev_and_idx):
        ev, idx = ev_and_idx
        kind, slot = ev[0], ev[1]

        def on_invoke(c: _Carry) -> _Carry:
            slot_tab = c.slot_tab.at[slot].set(ev[2:6])
            slot_active = c.slot_active.at[slot].set(True)
            return c._replace(slot_tab=slot_tab, slot_active=slot_active)

        def on_return(c: _Carry) -> _Carry:
            s, m, v, n, overflow = closure(
                c.states, c.masks, c.valid, c.slot_tab, c.slot_active,
                c.overflow, word_of[slot], bit_of[slot])
            bit_word = jnp.take(m, word_of[slot], axis=-1)
            has_bit = ((bit_word >> bit_of[slot]) & jnp.uint32(1)) == 1
            keep = v & has_bit
            cleared = m & ~slot_bitmask[slot][None, :]
            slot_active = c.slot_active.at[slot].set(False)
            died = ~jnp.any(keep)
            return c._replace(
                states=s, masks=cleared, valid=keep,
                slot_active=slot_active,
                dead=died, overflow=overflow,
                dead_event=jnp.where(died & (c.dead_event < 0), idx,
                                     c.dead_event),
                max_frontier=jnp.maximum(c.max_frontier, n))

        def active_step(c: _Carry) -> _Carry:
            return jax.lax.cond(kind == EV_INVOKE, on_invoke, on_return, c)

        skip = carry.dead | (kind != EV_INVOKE) & (kind != EV_RETURN)
        carry = jax.lax.cond(skip, lambda c: c, active_step, carry)
        return carry, None

    return step


def _init_carry(model: Model, cfg: WGLConfig) -> _Carry:
    f_cap, k, w = cfg.f_cap, cfg.k_slots, cfg.words
    return _Carry(
        states=jnp.zeros((f_cap,), jnp.int32).at[0].set(model.init_state()),
        masks=jnp.zeros((f_cap, w), jnp.uint32),
        valid=jnp.zeros((f_cap,), bool).at[0].set(True),
        slot_tab=jnp.zeros((k, 4), jnp.int32),
        slot_active=jnp.zeros((k,), bool),
        dead=jnp.bool_(False),
        overflow=jnp.bool_(False),
        dead_event=jnp.int32(-1),
        max_frontier=jnp.int32(1),
    )


def make_checker(model: Model, cfg: WGLConfig = WGLConfig()):
    """Returns jitted check(events[E,6] int32) -> result dict of scalars."""
    step = make_step_fn(model, cfg)

    @jax.jit
    def check(events):
        carry = _init_carry(model, cfg)
        idxs = jnp.arange(events.shape[0], dtype=jnp.int32)
        final, _ = jax.lax.scan(step, carry, (events, idxs))
        return {
            "survived": ~final.dead,
            "overflow": final.overflow,
            "dead_event": final.dead_event,
            "max_frontier": final.max_frontier,
        }

    return check


def make_batch_checker(model: Model, cfg: WGLConfig = WGLConfig()):
    """Returns jitted check(events[B,E,6]) -> dict of [B] result vectors.

    The batch axis is the per-key axis of the independent checker
    (src/jepsen/etcdemo.clj:115,120-125) and/or a corpus of stored histories;
    it is the natural data-parallel axis to shard over a TPU mesh.
    """
    step = make_step_fn(model, cfg)

    def check_one(events):
        carry = _init_carry(model, cfg)
        idxs = jnp.arange(events.shape[0], dtype=jnp.int32)
        final, _ = jax.lax.scan(step, carry, (events, idxs))
        return (~final.dead, final.overflow, final.dead_event,
                final.max_frontier)

    @jax.jit
    def check(events_batch):
        survived, overflow, dead_event, max_frontier = jax.vmap(check_one)(
            events_batch)
        return {
            "survived": survived,
            "overflow": overflow,
            "dead_event": dead_event,
            "max_frontier": max_frontier,
        }

    return check


def verdict(result: dict[str, Any]) -> bool | str:
    """Map kernel outputs to jepsen's tri-state validity."""
    survived = bool(result["survived"])
    overflow = bool(result["overflow"])
    if survived:
        return True
    return "unknown" if overflow else False


# Jitted checkers are cached per (model identity, config) so repeated checks
# (per-key loops, overflow retries) don't pay XLA retrace/compile each time.
_CHECKER_CACHE: dict[tuple, Any] = {}


def cached_checker(model: Model, cfg: WGLConfig):
    key = ("single", model.cache_key(), cfg)
    if key not in _CHECKER_CACHE:
        _CHECKER_CACHE[key] = make_checker(model, cfg)
    return _CHECKER_CACHE[key]


def cached_batch_checker(model: Model, cfg: WGLConfig):
    key = ("batch", model.cache_key(), cfg)
    if key not in _CHECKER_CACHE:
        _CHECKER_CACHE[key] = make_batch_checker(model, cfg)
    return _CHECKER_CACHE[key]


def check_encoded(enc: EncodedHistory, model: Model | None = None,
                  f_cap: int = 256) -> dict[str, Any]:
    """Convenience single-history entry point (jit-cached per config)."""
    if model is None:
        from ..models import CASRegister
        model = CASRegister()
    check = cached_checker(model, WGLConfig(enc.k_slots, f_cap))
    out = check(jnp.asarray(enc.events))
    out = {k: np.asarray(v) for k, v in out.items()}
    out["valid"] = verdict(out)
    return out
