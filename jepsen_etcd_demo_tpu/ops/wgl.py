"""Shared verdict helper (all that remains of the v1 event-major kernel).

The v1 WGL kernel that lived here — frontier-as-list over [E, 6] event
tensors with an EV_INVOKE/EV_RETURN lax.cond per scan step — was retired in
round 3: it lost the round-1 bench to the CPU oracle, was superseded by the
return-major sort kernel (ops/wgl2.py) and the dense subset-lattice kernels
(ops/wgl3.py, ops/wgl3_pallas.py), and by round 2 existed only to be
mesh-sharded; the production shardings now wrap the dense kernels directly
(parallel/dense.py, parallel/lattice.py). Its search geometry config and
sort-dedup helpers moved to ops/wgl2.py with the sort kernel, their only
remaining user.
"""

from __future__ import annotations

from typing import Any


def verdict(result: dict[str, Any]) -> bool | str:
    """Map kernel outputs to jepsen's tri-state validity: a surviving
    search proves linearizability; a dead search refutes it UNLESS configs
    were dropped along the way (overflow), which can only lose
    linearization witnesses — then the honest answer is "unknown"."""
    survived = bool(result["survived"])
    overflow = bool(result["overflow"])
    if survived:
        return True
    return "unknown" if overflow else False
