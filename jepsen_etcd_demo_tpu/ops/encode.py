"""Encode a concurrent history into padded int32 event tensors for the checker.

This is the boundary between the host plane (Python op records) and the device
plane (JAX kernels). A register-workload history (reference ops constructed at
src/jepsen/etcdemo.clj:67-69, completed at :83-105) becomes:

  events[E, 6] int32 rows: (kind, slot, f, a1, a2, rv)

    kind: EV_INVOKE — an op becomes pending (its fields are loaded into `slot`)
          EV_RETURN — the op in `slot` returned ok; every surviving
                      linearization must have linearized it by now
          EV_PAD    — padding (no-op)
    f:    F_READ / F_WRITE / F_CAS
    a1,a2: op arguments (write value; cas old/new)
    rv:   observed value for reads (NIL when the key was missing)

Completion-status handling (the correctness-critical part, reference
src/jepsen/etcdemo.clj:100-105):
  ok    -> EV_INVOKE at the invoke's history position, EV_RETURN at the
           completion's position.
  info  -> EV_INVOKE only: the op stays pending forever and may be linearized
           at any later point, but never must be. (Indeterminate reads impose
           no constraint at all and are dropped entirely.)
  fail  -> dropped: the op is known not to have taken effect.

Slots: because per-process ops are sequential, the number of simultaneously
pending ops is bounded by concurrency plus the number of accumulated `info`
ops. Each pending op occupies one of `k_slots` slots for the duration of its
pendingness; a config's "linearized set" is then a fixed-width bitmask over
slots rather than an unbounded set — this is what makes the search frontier a
static-shape tensor. Slot ids are freed on EV_RETURN (at that point every
surviving config has linearized the op, so its bit is cleared everywhere).
"""

from __future__ import annotations

import heapq
import itertools
import time
from dataclasses import dataclass
from typing import Any, Optional, Sequence

import numpy as np

from ..obs import get_ledger, get_metrics
from .op import Op, INVOKE, OK, FAIL, INFO

# Value encoding. The reference register draws values from (rand-int 5), i.e.
# 0..4 (src/jepsen/etcdemo.clj:68-69); NIL encodes "key missing" observed by a
# read (parse-long of nil at :71-74,87-90). Any int32 value >= 0 is supported.
NIL = -1

# Function codes. F_READ is, BY CONVENTION, the pure-observation code in
# every model's op language (it never mutates model state): the encoder
# relies on this to drop indeterminate observations — an :info op with no
# state effect imposes no constraint (reference :info mapping,
# src/jepsen/etcdemo.clj:100-102) — without consulting the model. Codes
# 3..5 are claimed by the non-register model families (models/gset.py,
# models/queues.py); codes are only meaningful within one model's language,
# so families may reuse them.
F_READ, F_WRITE, F_CAS = 0, 1, 2
F_ADD, F_ENQ, F_DEQ = 3, 4, 5
FUNC_CODES = {"read": F_READ, "write": F_WRITE, "cas": F_CAS}

# Event kinds.
EV_INVOKE, EV_RETURN, EV_PAD = 0, 1, 2

EVENT_WIDTH = 6  # (kind, slot, f, a1, a2, rv)

# Bump on ANY change to the encoder's input->tensor mapping (pairing,
# slot assignment, field layout, value encoding): the content-addressed
# encoded-tensor cache (store/encode_cache.py) keys on it, so a stale
# persisted encoding can never survive an encoder fix.
ENCODING_VERSION = 1


class EncodeError(ValueError):
    pass


class SlotOverflow(EncodeError):
    """More simultaneously-pending ops than k_slots."""


@dataclass
class Invocation:
    """One paired invocation: invoke entry + (optional) completion entry."""

    f: int                 # F_READ / F_WRITE / F_CAS
    a1: int
    a2: int
    rv: int                # observed read value (NIL if none / missing key)
    status: str            # ok | fail | info
    invoke_index: int      # position of the invoke entry in the history
    complete_index: int    # position of the completion entry; -1 if none
    process: Any = None


@dataclass
class EncodedHistory:
    """Padded event tensor plus bookkeeping, ready for the WGL kernels."""

    events: np.ndarray     # [E, 6] int32
    n_events: int          # real (non-pad) events
    n_ops: int             # invocations included (ok + open info)
    k_slots: int
    max_pending: int       # high-water mark of simultaneously pending ops
    max_value: int = 0     # largest encoded value (a1/a2/rv); bounds the
    #                        model state space for packed-key dedup

    def padded_to(self, e_cap: int) -> "EncodedHistory":
        if e_cap < self.events.shape[0]:
            raise EncodeError(
                f"cannot pad events of length {self.events.shape[0]} to {e_cap}"
            )
        ev = np.full((e_cap, EVENT_WIDTH), 0, dtype=np.int32)
        ev[:, 0] = EV_PAD
        ev[: self.events.shape[0]] = self.events
        return EncodedHistory(ev, self.n_events, self.n_ops, self.k_slots,
                              self.max_pending, self.max_value)

    def to_arrays(self) -> dict[str, np.ndarray]:
        """npz-ready dict (trimmed to real events) — ONE serialization
        shape shared by the store's history-tensor artifacts and the
        encoded-tensor cache, so the two cannot drift."""
        return {"events": np.asarray(self.events[: self.n_events]),
                "n_ops": np.asarray(self.n_ops),
                "k_slots": np.asarray(self.k_slots),
                "max_pending": np.asarray(self.max_pending),
                "max_value": np.asarray(self.max_value)}

    @classmethod
    def from_arrays(cls, z) -> "EncodedHistory":
        """Inverse of to_arrays over any mapping of arrays (an open
        np.load handle included)."""
        events = np.asarray(z["events"], dtype=np.int32)
        return cls(events=events, n_events=int(events.shape[0]),
                   n_ops=int(z["n_ops"]), k_slots=int(z["k_slots"]),
                   max_pending=int(z["max_pending"]),
                   max_value=int(z["max_value"]))


def _encode_value(v: Any) -> int:
    if v is None:
        return NIL
    v = int(v)
    if v < 0:
        # NIL (-1) is the reserved "key missing" sentinel; admitting negative
        # payloads would both collide with it and corrupt the packed-key
        # dedup (uint32 wraparound). Reject loudly instead of mis-checking.
        raise EncodeError(
            f"negative history values are unsupported (got {v}); "
            f"-1 is the NIL sentinel")
    return v


def register_fields(f_name: str, invoke_value: Any, ok_value: Any,
                    status: str) -> tuple[int, int, int, int]:
    """The register op language (reference ops at src/jepsen/etcdemo.clj:67-69):
    read -> rv = observed value; write -> a1 = value; cas -> a1,a2 = old,new.
    Default codec for models that don't define their own (models/base.py)."""
    if f_name not in FUNC_CODES:
        raise EncodeError(f"unsupported register op f={f_name!r}")
    f = FUNC_CODES[f_name]
    a1 = a2 = 0
    rv = NIL
    if f == F_READ:
        if status == OK:
            rv = _encode_value(ok_value)
    elif f == F_WRITE:
        a1 = _encode_value(invoke_value)
    elif f == F_CAS:
        old, new = invoke_value
        a1, a2 = _encode_value(old), _encode_value(new)
    return f, a1, a2, rv


def pair_history(history: Sequence[Op], model=None) -> list[Invocation]:
    """Pair invoke entries with their completions by process id.

    Mirrors the framework recorder's pairing [dep]; a process has at most one
    outstanding invocation at a time (jepsen worker model). Invocations whose
    completion never arrives are treated as `info` (crashed mid-op), exactly
    like jepsen treats them when a run ends.

    `model` supplies the op-language codec (Model.encode_invocation); None
    uses the register conventions — the language of the reference demo and
    of every model whose prepare_history translates into it.
    """
    pending: dict[Any, tuple[int, Op]] = {}
    out: list[Invocation] = []
    for idx, op in enumerate(history):
        if op.type == INVOKE:
            if op.process in pending:
                raise EncodeError(
                    f"process {op.process} invoked twice without completing "
                    f"(history indices {pending[op.process][0]} and {idx})"
                )
            pending[op.process] = (idx, op)
        elif op.type in (OK, FAIL, INFO):
            if op.process not in pending:
                raise EncodeError(
                    f"completion for process {op.process} at history index "
                    f"{idx} has no pending invocation"
                )
            inv_idx, inv = pending.pop(op.process)
            out.append(_make_invocation(inv, op, inv_idx, idx, model))
        else:
            raise EncodeError(f"unknown op type {op.type!r} at index {idx}")
    # Unfinished invocations: open forever.
    for proc, (inv_idx, inv) in pending.items():
        out.append(_make_invocation(inv, None, inv_idx, -1, model))
    out.sort(key=lambda i: i.invoke_index)
    return out


def _make_invocation(inv: Op, comp: Optional[Op], inv_idx: int,
                     comp_idx: int, model=None) -> Invocation:
    status = comp.type if comp is not None else INFO
    # The completion value reaches the codec for OK *and* INFO: an
    # indeterminate op may still carry the value it tried to take (e.g. a
    # dequeue whose compare-and-delete response was lost after claiming a
    # known element — clients/etcd.py IndeterminateDequeue), which is what
    # makes it encodable as a pending op.
    comp_value = (comp.value if comp is not None
                  and comp.type in (OK, INFO) else None)
    codec = register_fields if model is None else model.encode_invocation
    f, a1, a2, rv = codec(inv.f, inv.value, comp_value, status)
    return Invocation(f=f, a1=a1, a2=a2, rv=rv, status=status,
                      invoke_index=inv_idx, complete_index=comp_idx,
                      process=inv.process)


def _timeline_points(invocations: Sequence[Invocation]
                     ) -> list[tuple[int, int, Invocation]]:
    """(history_index, is_return, invocation) per event, in event order.

    Single source of the event-ordering rule shared by encode_events and
    event_sources: each included invocation contributes an invoke point and,
    when status == ok, a return point; `fail` ops and `info` reads are
    excluded (see module docstring)."""
    points: list[tuple[int, int, Invocation]] = []
    for inv in invocations:
        if inv.status == FAIL:
            continue
        if inv.status == INFO and inv.f == F_READ:
            continue  # an indeterminate read imposes no constraint
        points.append((inv.invoke_index, 0, inv))
        if inv.status == OK:
            points.append((inv.complete_index, 1, inv))
    points.sort(key=lambda p: (p[0], p[1]))
    return points


def event_sources(invocations: Sequence[Invocation]) -> list[Invocation]:
    """The invocation behind each encoded event row, in event order —
    row i of encode_events(invocations).events describes event_sources[i].
    Used by the witness reconstructor to map kernel/oracle event indices
    back to concrete history operations."""
    return [inv for _, _, inv in _timeline_points(invocations)]


def encode_events(invocations: Sequence[Invocation], k_slots: int = 32
                  ) -> EncodedHistory:
    """Build the (kind, slot, f, a1, a2, rv) event stream with slot assignment.

    Events are emitted in history order: each included invocation contributes
    an EV_INVOKE at its invoke position and, when status == ok, an EV_RETURN at
    its completion position. `fail` ops and `info` reads are excluded (see
    module docstring).
    """
    t_enc = time.monotonic()
    points = _timeline_points(invocations)

    free = list(range(k_slots - 1, -1, -1))  # pop() yields lowest slot first
    slot_of: dict[int, int] = {}             # invoke_index -> slot
    rows: list[list[int]] = []
    max_pending = 0
    for hist_idx, is_return, inv in points:
        if not is_return:
            if not free:
                raise SlotOverflow(
                    f"more than {k_slots} simultaneously pending ops at "
                    f"history index {hist_idx}; raise k_slots"
                )
            slot = free.pop()
            slot_of[inv.invoke_index] = slot
            rows.append([EV_INVOKE, slot, inv.f, inv.a1, inv.a2, inv.rv])
            max_pending = max(max_pending, k_slots - len(free))
        else:
            slot = slot_of.pop(inv.invoke_index)
            rows.append([EV_RETURN, slot, inv.f, inv.a1, inv.a2, inv.rv])
            free.append(slot)

    events = np.asarray(rows, dtype=np.int32).reshape(-1, EVENT_WIDTH)
    n_ops = sum(1 for _, r, _i in points if not r)
    max_value = int(events[:, 3:6].max()) if len(rows) else 0
    # Telemetry (obs/): host-side encode cost and the event-tensor bytes
    # that will cross the host->device boundary (SURVEY §5.1 — the
    # harness's own hot loop needs a breakdown, not just the op history).
    m = get_metrics()
    dt_enc = time.monotonic() - t_enc
    m.counter("encode.encode_s").add(dt_enc)
    get_ledger().record_encode(dt_enc)
    m.counter("encode.histories").add(1)
    m.counter("encode.event_bytes").add(int(events.nbytes))
    return EncodedHistory(events=events, n_events=len(rows), n_ops=n_ops,
                          k_slots=k_slots, max_pending=max_pending,
                          max_value=max_value)


class IncrementalEncoder:
    """Streaming counterpart of pair_history + encode_events (stream/).

    Consumes history entries ONE AT A TIME (in recorded order) and emits
    event rows exactly when they become STABLE — when no future history
    entry can change, remove, or reorder them. The stable-prefix rule:

      * an op's events are determined only once its completion is
        recorded (``fail`` -> dropped entirely, indeterminate read ->
        dropped, ``info`` -> EV_INVOKE only, carrying the completion's
        value, ``ok`` -> EV_INVOKE + EV_RETURN);
      * therefore every event at a history position at or after the
        earliest STILL-OPEN invoke is unstable: that op's eventual
        completion may insert (or not) an EV_INVOKE at that earlier
        position, shifting everything behind it.

    The *watermark* is that earliest-open-invoke position, ordered by
    the recorder's monotonic per-entry sequence (``Op.seq`` /
    append order — never wall clock). An op that will crash pins the
    watermark from its invoke until its ``:info`` completion is
    recorded; after that it is encoded pending-forever (WGL open-op
    semantics — its slot is never freed) and the watermark moves on.
    Ops still open when the run ends are resolved as ``info`` by
    :meth:`finalize`, exactly like pair_history's end-of-run rule.

    The emitted rows are BIT-IDENTICAL to the corresponding prefix of
    ``encode_events(pair_history(history, model))``: same point order
    (invoke/return points sorted by (position, kind)), same slot
    assignment — encode_events pops fresh slots in increasing order and
    reuses freed slots LIFO, which depends only on the event
    interleaving, never on the slot-table capacity, so the unbounded
    stack here reproduces any non-overflowing capacity's ids — and the
    same n_ops / max_pending / max_value bookkeeping
    (tests/test_stream.py pins it on fuzz histories).
    """

    def __init__(self, model=None):
        self.model = model
        self._open: dict[Any, tuple[int, Op]] = {}   # process -> (idx, op)
        self._heap: list = []      # (pos, is_return, tiebreak, Invocation)
        self._tie = itertools.count()
        self._idx = 0              # history entries consumed
        self._free: list[int] = []  # freed slot ids (LIFO stack)
        self._next_slot = 0
        self._slot_of: dict[int, int] = {}           # invoke_index -> slot
        self._cur_pending = 0
        self._row_max: Optional[int] = None
        self._last_seq = -1        # last recorder seq consumed
        self._finalized = False
        self.rows: list[list[int]] = []              # stable event rows
        self.n_ops = 0
        self.max_pending = 0

    @property
    def max_value(self) -> int:
        # Exactly encode_events' bookkeeping: max over the emitted rows'
        # (a1, a2, rv) fields, 0 when no rows were emitted.
        return 0 if self._row_max is None else self._row_max

    def watermark(self) -> int:
        """First UNSTABLE history position: the earliest still-open
        invoke's index (== entries consumed when nothing is open)."""
        if self._finalized or not self._open:
            return self._idx
        return min(idx for idx, _ in self._open.values())

    def lag(self) -> int:
        """History entries consumed but not yet stable (the
        stream.watermark_lag gauge)."""
        return self._idx - self.watermark()

    def append(self, op: Op) -> list[list[int]]:
        """Consume one history entry; returns the newly-STABLE event
        rows (possibly none). Raises EncodeError on the same malformed
        shapes pair_history rejects."""
        if self._finalized:
            raise EncodeError("append after finalize")
        # Recorder-stamped entries must arrive in strictly increasing
        # seq — the total order the watermark's stability argument rests
        # on. A violation means the feed path reordered (or duplicated)
        # entries; encoding on would silently corrupt the prefix.
        if op.seq >= 0:
            if op.seq <= self._last_seq:
                raise EncodeError(
                    f"out-of-order feed: seq {op.seq} after "
                    f"{self._last_seq} (history index {self._idx})")
            self._last_seq = op.seq
        idx = self._idx
        self._idx += 1
        if op.type == INVOKE:
            if op.process in self._open:
                raise EncodeError(
                    f"process {op.process} invoked twice without completing "
                    f"(history indices {self._open[op.process][0]} and {idx})"
                )
            self._open[op.process] = (idx, op)
        elif op.type in (OK, FAIL, INFO):
            if op.process not in self._open:
                raise EncodeError(
                    f"completion for process {op.process} at history index "
                    f"{idx} has no pending invocation"
                )
            inv_idx, inv = self._open.pop(op.process)
            self._resolve(inv, op, inv_idx, idx)
        else:
            raise EncodeError(f"unknown op type {op.type!r} at index {idx}")
        return self._drain()

    def finalize(self) -> list[list[int]]:
        """Resolve every still-open invocation as ``info`` (crashed
        mid-op — pair_history's end-of-run rule) and drain everything;
        returns the remaining rows. Idempotent."""
        if not self._finalized:
            for inv_idx, inv in sorted(self._open.values()):
                self._resolve(inv, None, inv_idx, -1)
            self._open.clear()
            self._finalized = True
        return self._drain()

    def encoded_history(self, k_slots: int = 32) -> EncodedHistory:
        """The stable rows as an EncodedHistory — after finalize, this is
        exactly what ``encode_history(history, model, k_slots)`` under
        the checker's slot-escalation ladder (k doubles past
        max_pending, checkers/linearizable.py) would have produced."""
        k = max(1, int(k_slots))
        while self.max_pending > k:
            k *= 2
        events = (np.asarray(self.rows, dtype=np.int32)
                  .reshape(-1, EVENT_WIDTH))
        return EncodedHistory(events=events, n_events=len(self.rows),
                              n_ops=self.n_ops, k_slots=k,
                              max_pending=self.max_pending,
                              max_value=self.max_value)

    # -- internals --------------------------------------------------------
    def _resolve(self, inv: Op, comp: Optional[Op], inv_idx: int,
                 comp_idx: int) -> None:
        invocation = _make_invocation(inv, comp, inv_idx, comp_idx,
                                      self.model)
        # The _timeline_points exclusions, applied at resolution time.
        if invocation.status == FAIL:
            return
        if invocation.status == INFO and invocation.f == F_READ:
            return
        heapq.heappush(self._heap,
                       (inv_idx, 0, next(self._tie), invocation))
        if invocation.status == OK:
            heapq.heappush(self._heap,
                           (comp_idx, 1, next(self._tie), invocation))

    def _drain(self) -> list[list[int]]:
        wm = self.watermark()
        new: list[list[int]] = []
        while self._heap and self._heap[0][0] < wm:
            _pos, is_return, _t, inv = heapq.heappop(self._heap)
            if not is_return:
                # encode_events' exact policy: its free list is a stack
                # seeded [k-1..0], so fresh slots come out in increasing
                # order and FREED slots are reused most-recent-first.
                if self._free:
                    slot = self._free.pop()
                else:
                    slot = self._next_slot
                    self._next_slot += 1
                self._slot_of[inv.invoke_index] = slot
                row = [EV_INVOKE, slot, inv.f, inv.a1, inv.a2, inv.rv]
                self.n_ops += 1
                self._cur_pending += 1
                self.max_pending = max(self.max_pending, self._cur_pending)
            else:
                slot = self._slot_of.pop(inv.invoke_index)
                row = [EV_RETURN, slot, inv.f, inv.a1, inv.a2, inv.rv]
                self._free.append(slot)
                self._cur_pending -= 1
            hi = max(row[3], row[4], row[5])
            self._row_max = hi if self._row_max is None \
                else max(self._row_max, hi)
            self.rows.append(row)
            new.append(row)
        return new


def encode_register_history(history: Sequence[Op], k_slots: int = 32
                            ) -> EncodedHistory:
    """History of register ops (read/write/cas) -> padded event tensor."""
    return encode_events(pair_history(history), k_slots=k_slots)


def encode_history(history: Sequence[Op], model, k_slots: int = 32
                   ) -> EncodedHistory:
    """History in `model`'s op language -> padded event tensor.

    Does NOT apply model.prepare_history — the checker seam translates once
    (checkers/linearizable.py) so witness reconstruction sees the same op
    language the encoder did."""
    return encode_events(pair_history(history, model), k_slots=k_slots)


def reslot_events(enc: EncodedHistory, k_slots: int) -> EncodedHistory:
    """Remap slot ids into a smaller slot table (k_slots >= max_pending).

    Uses the same greedy lowest-free assignment as encode_events over the
    same event order, so the result is exactly what encoding with the
    smaller k_slots would have produced. Lets the dense lattice kernel
    (wgl3) shrink its 2^K mask axis to the history's REAL concurrency after
    a conservative first encoding."""
    if k_slots < enc.max_pending:
        raise EncodeError(
            f"cannot reslot to {k_slots} slots: history has "
            f"{enc.max_pending} simultaneously pending ops")
    ev = enc.events[: enc.n_events].copy()
    free = list(range(k_slots - 1, -1, -1))
    mapping: dict[int, int] = {}
    for row in ev:
        if row[0] == EV_INVOKE:
            new = free.pop()
            mapping[int(row[1])] = new
            row[1] = new
        elif row[0] == EV_RETURN:
            new = mapping.pop(int(row[1]))
            row[1] = new
            free.append(new)
    return EncodedHistory(events=ev, n_events=enc.n_events, n_ops=enc.n_ops,
                          k_slots=k_slots, max_pending=enc.max_pending,
                          max_value=enc.max_value)


@dataclass
class ReturnSteps:
    """Return-event-major encoding: one row per EV_RETURN, with a full
    pending-slot snapshot.

    The WGL search only does real work at returns (closure + prune); invokes
    are just slot-table bookkeeping. Precomputing the slot table per return
    on the host gives the device kernel a scan whose every step does
    identical work — no invoke/return branching, which matters enormously
    under vmap (a lax.cond over batch-varying event kinds becomes a select
    that executes BOTH branches for every lane).

    slot_tabs[i] is the snapshot just before processing return i: every op
    invoked earlier (in history order) and not yet returned is active,
    including the returning op itself."""

    slot_tabs: np.ndarray    # [R, K, 4] int32 (f, a1, a2, rv)
    slot_active: np.ndarray  # [R, K] bool
    targets: np.ndarray      # [R] int32 slot of the returning op; -1 = pad
    n_steps: int             # real (non-pad) returns
    n_ops: int
    k_slots: int
    max_pending: int
    max_value: int = 0

    def padded_to(self, r_cap: int) -> "ReturnSteps":
        r = self.slot_tabs.shape[0]
        if r_cap < r:
            raise EncodeError(f"cannot pad {r} return steps to {r_cap}")
        tabs = np.zeros((r_cap,) + self.slot_tabs.shape[1:], np.int32)
        act = np.zeros((r_cap, self.k_slots), bool)
        tgt = np.full((r_cap,), -1, np.int32)
        tabs[:r] = self.slot_tabs
        act[:r] = self.slot_active
        tgt[:r] = self.targets
        return ReturnSteps(tabs, act, tgt, self.n_steps, self.n_ops,
                           self.k_slots, self.max_pending, self.max_value)


def encode_return_steps(enc: EncodedHistory) -> ReturnSteps:
    """Derive the return-major encoding from the event encoding.

    Placement routes through ``limits().encode_mode``: mode 2 expands
    the table ON DEVICE (ops/encode_device.py — bit-identical rows, the
    event stream crosses the H2D boundary instead of the packed table);
    modes 0/1 run the host expansion below. Every consumer — post-hoc
    checks AND the streaming IncrementalEncoder prefix (stream/
    engine.py calls this on its stable rows) — funnels through here, so
    the one knob governs both paths."""
    from .limits import limits

    if limits().encode_mode == 2:
        from . import encode_device

        if encode_device.device_encode_feasible(enc):
            return encode_device.encode_return_steps_device(enc)
    return _encode_return_steps_host(enc)


def _encode_return_steps_host(enc: EncodedHistory) -> ReturnSteps:
    """The host expansion, vectorized (no per-return [K,4] snapshot
    loop): for each return event at position p, slot k's table row is
    the fields of the last EV_INVOKE of slot k before p, and slot k is
    active iff its invokes before p outnumber its returns strictly
    before p (the returning op itself counts active)."""
    t_enc = time.monotonic()
    k = enc.k_slots
    n = enc.n_events
    ev = np.asarray(enc.events[:n])
    if n == 0 or not (ev[:, 0] == EV_RETURN).any():
        return ReturnSteps(
            slot_tabs=np.zeros((0, k, 4), np.int32),
            slot_active=np.zeros((0, k), bool),
            targets=np.zeros((0,), np.int32),
            n_steps=0, n_ops=enc.n_ops, k_slots=k,
            max_pending=enc.max_pending, max_value=enc.max_value)
    kinds, slots = ev[:, 0], ev[:, 1]
    slot_ids = np.arange(k)
    inv_onehot = (kinds == EV_INVOKE)[:, None] & (slots[:, None] == slot_ids)
    ret_onehot = (kinds == EV_RETURN)[:, None] & (slots[:, None] == slot_ids)
    inv_cum = np.cumsum(inv_onehot, axis=0)   # invokes in events[0..p]
    ret_cum = np.cumsum(ret_onehot, axis=0)   # returns in events[0..p]
    # Last invoke position of each slot at-or-before each event position.
    last_inv = np.maximum.accumulate(
        np.where(inv_onehot, np.arange(n)[:, None], -1), axis=0)

    ret_pos = np.nonzero(kinds == EV_RETURN)[0]
    # Event p is a return, so "invokes before p" == inv_cum[p]; "returns
    # strictly before p" excludes p's own return.
    active = inv_cum[ret_pos] > (ret_cum[ret_pos] - ret_onehot[ret_pos])
    last = last_inv[ret_pos]                   # [R, K]
    tabs = np.where(last[:, :, None] >= 0,
                    ev[np.maximum(last, 0)][:, :, 2:6], 0).astype(np.int32)
    dt_enc = time.monotonic() - t_enc
    get_metrics().counter("encode.encode_s").add(dt_enc)
    get_ledger().record_encode(dt_enc)
    return ReturnSteps(
        slot_tabs=tabs,
        slot_active=active,
        targets=slots[ret_pos].astype(np.int32),
        n_steps=len(ret_pos), n_ops=enc.n_ops, k_slots=k,
        max_pending=enc.max_pending, max_value=enc.max_value)

