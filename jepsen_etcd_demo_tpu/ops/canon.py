"""Frontier canonicalization: symmetry reduction over equal-effect
forever-pending ops (ROADMAP item 4b — knossos' own state-space trick,
applied device-side to the packed WGL frontiers).

The combinatorial frontiers that DNF the sort ladder (ops/wgl2.py's own
docstring: "dozens of forever-pending ops interleaving factorially") and
bloat the dense tables' live occupancy are dominated by SYMMETRY: when
two pending ops have identical encoded rows (same ``(f, a1, a2, rv)``)
and NEITHER ever returns in the remaining history, linearizing either
one first reaches exactly the same model state, and no future prune can
ever distinguish them (prunes address ops by slot, and these slots never
appear as targets again). Swapping the two slots is therefore an
automorphism of the remaining search: a config that fired ``{hi}`` out
of such a class is equivalent to the config that fired ``{lo}``, and in
general only the COUNT of fired ops per class matters — ``C(n, k)``
masks collapse to ``n + 1``.

Canonicalization picks the representative with the fired bits packed
into the LOWEST slots of each class, implemented as a compare-exchange
network over the class's slot bits: ``CE(lo, hi)`` rewrites every config
with bit ``hi`` set and bit ``lo`` clear to the config with the bits
swapped (a binary selection-sort network, ``c·(c-1)/2`` exchanges per
class of size ``c``). On the dense packed table (ops/wgl3.py) a CE is
pure bit algebra — position-mask selects plus an index-bit toggle (an
in-word butterfly for bits < 5, a word-axis gather for higher bits) —
and merging is the table's own idempotent OR. On the sort kernel's
explicit mask rows (ops/wgl2.py) a CE is one vectorized conditional
XOR, and the merge happens in the existing sort-dedup.

Soundness (why verdicts are bit-identical to dedup-off): the quotient
map ``canon`` commutes with every kernel operation over the remaining
history — expansion (class rows are identical, so firable effect
multisets match), JIT-linearization banking and pruning (class slots
are never targets, so the banked/pruned bit is canon-invariant), and
death (canon merges configs, never empties a nonempty frontier). The
frontier after canonicalization is ``canon(frontier)`` at every step,
so survival at every prune — and with it ``valid`` / ``survived`` /
``overflow`` / ``dead_step`` — is exactly the dedup-off kernel's.
The SEARCH-SIZE metrics (``max_frontier``, ``configs_explored``) do
shrink: that is the point, and the bench's ``dedup`` lane reports raw
vs unique configs/s separately so the headline metric cannot silently
improve by pruning.

Host side, :func:`canon_pairs` derives the per-step exchange network
from the return-major encoding alone (no model needed — equal rows imply
equal ``model.step`` behavior for every model): slot ``j`` is
forever-pending at step ``t`` iff it is active and never appears in
``targets[t:]``, which is monotone in ``t``, so the network changes at
most ``K`` times per history and the ``[R, P, 2]`` scan input is cheap
to build even for 100k-step histories.

Gating lives in :mod:`ops.limits`: ``dedup_mode`` (0 auto / 1 off /
2 force), ``dedup_min_frontier`` (skip the pass on tiny frontiers —
always sound), ``dedup_hash_slots`` (the sparse engine's seen-memo
capacity, ops/wgl3_sparse.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .encode import ReturnSteps
from .limits import limits

# Re-exported bit constant (ops/wgl3.py owns the packing rationale).
# jtflow: table-word-bits=5
from .wgl3 import _LO_MASK

# Pair-capacity buckets: the exchange network rides the scan inputs with
# a static per-step capacity P; bucketing P bounds compiled shapes per
# geometry the same way step_bucket bounds scan lengths.
PAIR_CAP_FLOOR = 4


def pair_capacity(n_pairs: int) -> int:
    """Static per-step pair capacity for a history whose densest step
    has `n_pairs` exchanges: next power of two, floor PAIR_CAP_FLOOR."""
    cap = PAIR_CAP_FLOOR
    while cap < n_pairs:
        cap *= 2
    return cap


def _selection_network(slots: list[int]) -> list[tuple[int, int]]:
    """Binary selection-sort network over one class's slot indices
    (ascending): CE(lo, hi) for every lo < hi pairs the fired bits into
    the lowest class slots — c·(c-1)/2 exchanges, exact for 0/1 keys."""
    out = []
    for i in range(len(slots) - 1):
        for k in range(i + 1, len(slots)):
            out.append((slots[i], slots[k]))
    return out


def canon_pairs(rs: ReturnSteps,
                max_bit: int | None = None) -> np.ndarray | None:
    """The per-step compare-exchange network ``i32[R, P, 2]`` for this
    history, or None when no step has any symmetry to reduce.

    A pair ``(lo, hi)`` at step ``t`` means slots ``lo < hi`` hold
    equal-effect ops that are both forever-pending from ``t`` on (active
    at ``t``, never again a target). Pad entries are ``(-1, -1)``
    (identity). ``max_bit`` drops pairs touching slot bits >= it — the
    lattice-sharded table canonicalizes shard-locally
    (parallel/lattice.py), which is sound because every CE is
    individually sound.

    Eligibility is monotone in ``t`` (a forever-pending slot stays
    active and untargeted through the end), so the network is piecewise
    constant over at most K+1 segments — the [R, P, 2] array is built
    per segment, not per step."""
    R = rs.slot_tabs.shape[0]
    K = rs.k_slots
    n = rs.n_steps
    if n == 0:
        return None
    targets = np.asarray(rs.targets[:n])
    active = np.asarray(rs.slot_active[:n])
    tabs = np.asarray(rs.slot_tabs[:n])
    # forever_from[j]: first step index from which slot j never returns
    # again (0 when j is never a target at all).
    forever_from = np.zeros(K, dtype=np.int64)
    for t, j in enumerate(targets):
        if 0 <= j < K:
            forever_from[j] = t + 1
    # start[j]: first step where slot j is BOTH active and past its last
    # return — the op occupying it from here on never returns. -1 when
    # the slot is never forever-pending.
    start = np.full(K, -1, dtype=np.int64)
    for j in range(K):
        f0 = int(forever_from[j])
        if f0 >= n:
            continue
        tail = active[f0:, j]
        hit = np.argmax(tail)
        if tail[hit]:
            start[j] = f0 + int(hit)
    eligible = [j for j in range(K) if start[j] >= 0]
    if len(eligible) < 2:
        return None
    boundaries = sorted({int(start[j]) for j in eligible})
    seg_pairs: list[tuple[int, list[tuple[int, int]]]] = []
    max_pairs = 0
    for b in boundaries:
        live = [j for j in eligible if start[j] <= b]
        by_row: dict[tuple, list[int]] = {}
        for j in live:
            by_row.setdefault(tuple(tabs[start[j], j].tolist()),
                              []).append(j)
        pairs: list[tuple[int, int]] = []
        for slots in by_row.values():
            if len(slots) >= 2:
                pairs.extend(_selection_network(sorted(slots)))
        if max_bit is not None:
            pairs = [(lo, hi) for lo, hi in pairs
                     if lo < max_bit and hi < max_bit]
        seg_pairs.append((b, pairs))
        max_pairs = max(max_pairs, len(pairs))
    if max_pairs == 0:
        return None
    P = pair_capacity(max_pairs)
    out = np.full((R, P, 2), -1, dtype=np.int32)
    for i, (b, pairs) in enumerate(seg_pairs):
        if not pairs:
            continue
        end = seg_pairs[i + 1][0] if i + 1 < len(seg_pairs) else n
        row = np.full((P, 2), -1, dtype=np.int32)
        row[:len(pairs)] = np.asarray(pairs, dtype=np.int32)
        out[b:end] = row
    return out


def history_canon_pairs(rs: ReturnSteps, max_bit: int | None = None,
                        table: bool = False):
    """The padded history's exchange network under the active limits —
    the ONE copy of the dedup engage policy, shared by the sort ladder
    (ops/wgl2.py) and every table sweep (wgl3 / wgl3_sparse /
    parallel/lattice). None when dedup is off (dedup_mode=1) or the
    history has no symmetry to reduce (the common case: the compiled
    kernels are then byte-identical to the pre-dedup build).

    ``table=True`` marks a packed-TABLE sweep, where canonicalization
    engages under dedup_mode=2 (force — the bench/test lane, or a tuned
    profile that measured it faster) ONLY: a table sweep's cost is
    fixed in the table size, so the pass pays there only when the
    shrunken occupancy feeds something downstream — which the dedup
    tune probe measures per machine. AUTO (0) keeps the pass where
    frontier size directly drives cost: the resumable sort ladder
    (measured 4x on symmetry-heavy histories via avoided capacity
    escalations) and the sparse engine's seen memo."""
    lim = limits()
    if lim.dedup_mode == 1 or (table and lim.dedup_mode != 2):
        return None
    return canon_pairs(rs, max_bit=max_bit)


def dedup_min_frontier_active(lim=None) -> int:
    """The per-step table-canonicalization gate under the active limits
    — ONE copy shared by the dense, sparse, and lattice rungs so they
    gate identically. Orthogonal to dedup_mode: the gate is a per-step
    COST control (a few table gathers per pair, never repaid by tiny
    frontiers), not a soundness switch."""
    if lim is None:
        lim = limits()
    return lim.dedup_min_frontier


def apply_step_canon(canon_fn, T, pairs, n, is_pad, min_frontier: int,
                     count_fn=None):
    """The post-closure canonicalization step shared by the dense,
    sparse, and lattice scan bodies: gate on (real step, non-empty
    network, frontier >= min_frontier), canonicalize under the cond so
    quiet steps pay nothing, and account the shrink. Returns
    (T', n', canon_pruned, canon_base). ``count_fn`` overrides the
    popcount reduce — the lattice passes its psum'd variant so the
    gate (already on the GLOBAL n) and the accounting stay uniform
    across the mesh."""
    if count_fn is None:
        def count_fn(T):
            return jnp.sum(jax.lax.population_count(T), dtype=jnp.int32)

    do = (~is_pad) & (pairs[0, 0] >= 0) & (n >= jnp.int32(min_frontier))

    def apply(T):
        Tc = canon_fn(T, pairs)
        return Tc, count_fn(Tc)

    T2, n2 = jax.lax.cond(do, apply, lambda T: (T, n), T)
    return T2, n2, n - n2, jnp.where(do, n, 0)


def make_table_canon(w_local: int):
    """``canon(T u32[S, W], pairs i32[P, 2]) -> T'`` over the packed
    dense table (ops/wgl3.py word packing: 32 configs per u32, mask bit
    b < 5 in-word, bit b >= 5 in the word index). Valid for pairs whose
    bits are < 5 + log2(w_local) — the caller (canon_pairs max_bit)
    guarantees it for sharded tables; the full-width table accepts every
    bit by construction. Pair indices are TRACED (scan inputs), so one
    compiled program serves every step's network."""
    lo_masks = jnp.asarray(np.array(_LO_MASK, dtype=np.uint32))
    w_idx = jnp.arange(w_local, dtype=jnp.int32)
    full = jnp.uint32(0xFFFFFFFF)

    def clear_mask(b):
        """u32[W]: config positions whose mask bit b is CLEAR."""
        in_word = lo_masks[jnp.minimum(b, 4)]
        word_level = jnp.where(
            ((w_idx >> jnp.maximum(b - 5, 0)) & 1) == 0, full,
            jnp.uint32(0))
        return jnp.where(b < 5, jnp.broadcast_to(in_word, (w_local,)),
                         word_level)

    def toggle(T, b):
        """Re-address every config to the index with mask bit b
        TOGGLED: an in-word butterfly swap for b < 5, a word-axis XOR
        gather for b >= 5 (both branches computed, selected — the same
        traced-bit style as wgl3.table_ops' prune)."""
        bi = jnp.minimum(b, 4).astype(jnp.uint32)
        sh = jnp.uint32(1) << bi
        lom = lo_masks[jnp.minimum(b, 4)]
        inw = ((T & lom) << sh) | ((T >> sh) & lom)
        wsel = jnp.where(b < 5, w_idx,
                         w_idx ^ (jnp.int32(1) << jnp.maximum(b - 5, 0)))
        return jnp.where(b < 5, inw, T[:, wsel])

    def ce(T, lo, hi):
        """One compare-exchange: configs with bit hi set / bit lo clear
        move to the bit-swapped index (OR-merge with whatever is
        there); everything else is untouched."""
        amask = clear_mask(lo) & ~clear_mask(hi)
        src = T & amask[None, :]
        moved = toggle(toggle(src, hi), lo)
        return (T & ~amask[None, :]) | moved

    def canon(T, pairs):
        def body(i, T):
            lo = pairs[i, 0]
            hi = pairs[i, 1]
            return jax.lax.cond(lo >= 0,
                                lambda t: ce(t, lo, hi),
                                lambda t: t, T)
        return jax.lax.fori_loop(0, pairs.shape[0], body, T)

    return canon


def canon_keys_packed(keys, pairs, sbits: int, invalid):
    """Canonicalize packed single-word sort keys (ops/wgl2.py
    ``state | mask << sbits`` layout): one conditional XOR per traced
    pair. `invalid` is the all-ones sentinel key (never rewritten)."""
    sb = jnp.uint32(sbits)

    def body(i, keys):
        lo = pairs[i, 0]
        hi = pairs[i, 1]

        def apply(keys):
            bl = jnp.uint32(1) << (lo.astype(jnp.uint32) + sb)
            bh = jnp.uint32(1) << (hi.astype(jnp.uint32) + sb)
            cond = ((keys != invalid) & ((keys & bh) != 0)
                    & ((keys & bl) == 0))
            return jnp.where(cond, keys ^ (bl | bh), keys)

        return jax.lax.cond(lo >= 0, apply, lambda k: k, keys)

    return jax.lax.fori_loop(0, pairs.shape[0], body, keys)


def canon_masks_words(masks, pairs, slot_bitmask):
    """Canonicalize explicit multi-word mask rows (ops/wgl2.py unpacked
    path): ``masks u32[N, W]``, ``slot_bitmask u32[K, W]``
    (wgl2._slot_constants). Rows without the hi bit (including all-zero
    invalid lanes) are untouched."""

    def body(i, masks):
        lo = pairs[i, 0]
        hi = pairs[i, 1]

        def apply(m):
            bl = slot_bitmask[lo]
            bh = slot_bitmask[hi]
            has_hi = jnp.any((m & bh[None]) != 0, axis=-1)
            has_lo = jnp.any((m & bl[None]) != 0, axis=-1)
            cond = (has_hi & ~has_lo)[:, None]
            return jnp.where(cond, m ^ (bl | bh)[None], m)

        return jax.lax.cond(lo >= 0, apply, lambda m: m, masks)

    return jax.lax.fori_loop(0, pairs.shape[0], body, masks)
