"""Sparse active-tile sweep engine for the dense WGL lattice kernels.

The dense subset-lattice kernel (ops/wgl3.py) pays O(K * S * 2^K) word-ops
per return step regardless of how few configs are LIVE — and past K ~ 17
"the live frontier is invariably tiny relative to the lattice" (the
dense_config docstring's own admission; it is why the cell budget routes
wide geometries away from the dense sweep at all). This module removes
that waste the way direction-optimizing BFS removes it from graph
traversal (Beamer, Asanović & Patterson, SC'12): sweep only where the
frontier IS, and switch back to the dense formulation when the frontier
gets dense enough that skipping stops paying.

Mechanics, per closure round (the sweep inside each return step's
fixpoint loop):

  * the packed table u32[S, W] is viewed as W / TILE occupancy TILES of
    TILE contiguous words (TILE = limits().sparse_tile_words); a tile is
    live when any of its words is nonzero in any state row;
  * a static-capacity work list (limits().sparse_worklist_cap) gathers
    the LIVE tiles' indices (jnp.nonzero with a static size — XLA shapes
    stay static) and the sweep runs gather -> expand -> scatter:
      - slot j < 5:              in-word shift — local to the gathered tile
      - 5 <= j-5 < log2(TILE):   word-axis reshape — local to the tile
      - j-5 >= log2(TILE):       the mask bit lives in the TILE index —
                                 fired configs scatter-OR into tile
                                 (t | 1 << bit), a per-slot scatter with
                                 provably unique destinations;
  * when the live-tile count crosses the density threshold
    (limits().sparse_density_threshold_pct) or overflows the work list,
    THAT ROUND runs the ordinary dense sweep instead — the
    direction-optimizing switch. Work-list overflow therefore never
    drops configs; it only costs the dense round the engine would have
    run anyway.

Why verdicts are bit-identical to the dense sweep: the closure is a
monotone OR-fixpoint, and one sparse round is a superset of one Jacobi
round of the full table (every config at firing-distance 1 from the
current table has its source in a live tile, and every such firing is
computed — locally with in-round chaining, across tiles via the
scatter). K Jacobi rounds provably converge (each firing sets a distinct
slot bit), the round cap is cfg.rounds >= K (sparse_plan refuses
truncating caps), and the fixpoint is unique — so the converged table,
and with it every verdict field (survived / overflow / dead_step /
max_frontier / configs_explored), is exactly the dense kernel's.
Differential tests pin this on the golden + fuzz corpora
(tests/test_sparse_sweep.py).

Cost: a sparse round is O(K * S * cap * TILE) plus an O(S * W) occupancy
reduce — per-step cost tracks the LIVE frontier, which is what lets long
sparse histories scale past K ~ 20 (the lattice-sharded twin in
parallel/lattice.py shards the same engine over devices).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..models.base import Model
from ..obs import instrument_kernel, record_check_result
from .encode import ReturnSteps
from .limits import limits
from .wgl3 import (DenseConfig, _Carry3, _LO_MASK, _init_carry3,
                   default_scan_chunk, live_tile_geometry, sweep_summary,
                   table_ops)

_CACHE: dict[tuple, Any] = {}


@dataclass(frozen=True)
class SparsePlan:
    """The static shape of one geometry's sparse sweep: tile size, tile
    count, gather capacity, and the effective live-tile threshold above
    which a round runs dense. Hashable — part of the jit cache key.

    ``thresh_density`` keeps the RAW density threshold (before the min
    with the work-list cap) so the step fn can tell an overflow-forced
    dense round (live <= density but > cap — the silent fallback the
    wgl.sparse_overflow_rounds counter surfaces) from a density-chosen
    one."""
    tile_words: int     # TILE: packed words per occupancy tile (pow2)
    n_tiles: int        # W / TILE
    cap: int            # static work-list capacity (tiles gathered)
    thresh_tiles: int   # live-tile count above which the round is dense
    thresh_density: int = 0   # raw density threshold (>= thresh_tiles)


def sparse_plan(cfg: DenseConfig, words: int | None = None
                ) -> SparsePlan | None:
    """The sparse plan for this geometry under the active limits — or
    None when the engine must stay off: sparse_mode=1 (dense-only), a
    truncating max_rounds (the hybrid's round ORDER differs from the
    Gauss-Seidel sweep, so a sub-convergence cap could diverge), or too
    few tiles to be worth the per-round occupancy + gather overhead.

    `words` overrides the table width for SHARDED tables (parallel/
    lattice.py passes its per-device word count so each shard's work
    list is sized to the shard)."""
    lim = limits()
    if lim.sparse_mode == 1:
        return None
    if cfg.max_rounds and cfg.max_rounds < cfg.k_slots:
        return None
    tile, n_tiles = live_tile_geometry(cfg, words=words)
    if n_tiles < 2:
        return None     # structurally too narrow to tile at all
    if lim.sparse_mode != 2 and n_tiles < lim.sparse_min_tiles:
        # AUTO mode engages only past the measured static crossover
        # (see the sparse_min_tiles rationale in ops/limits.py);
        # prefer-sparse (2) is the explicit measurement override.
        return None
    cap = max(1, min(lim.sparse_worklist_cap, n_tiles))
    if lim.sparse_mode == 2:
        thresh = n_tiles
    else:
        thresh = max(1, n_tiles * lim.sparse_density_threshold_pct // 100)
    return SparsePlan(tile_words=tile, n_tiles=n_tiles, cap=cap,
                      thresh_tiles=min(thresh, cap), thresh_density=thresh)


def memo_slots_for(plan: SparsePlan, lim=None) -> int:
    """Slot count of the device-side `seen` memo for this plan — the
    tile count when the memo engages, 0 when it stays off. The memo is
    direct-indexed (one consumed-popcount slot per tile: collision-free
    by construction), so a geometry with more tiles than
    limits().dedup_hash_slots FAILS OPEN to no-memo — every live tile
    re-swept each round, the exact pre-dedup behavior — rather than
    risking a collision-aliased skip."""
    if lim is None:
        lim = limits()
    if lim.dedup_mode == 1 or plan.n_tiles > lim.dedup_hash_slots:
        return 0
    return plan.n_tiles


def make_sparse_sweep(model: Model, cfg: DenseConfig, plan: SparsePlan):
    """(T, allowed, trans, idx, count) -> T': one gather->expand->
    scatter round over the tiles listed in ``idx`` (u32 tile indices,
    CAP-padded; ``count`` real entries). The caller builds the list from
    live occupancy — or, with the seen memo, from the tiles whose
    content GREW since they were last swept this step (skipping a
    non-grown tile is sound: the table is monotone, so equal popcount
    means equal content and its expansion is already applied).

    LOCKSTEP NOTE: parallel/lattice.py `sweep_sparse` is this sweep's
    shard-local mirror (same gather, same in-word/in-tile/tile-bit
    branches and pad masking, plus a device-bit branch that scatters to
    shard width and ppermutes). The two cannot share code without
    threading the shard closure's or_reduce/axis context through here,
    so any fix to the bit algebra or the valid/src_ok masking MUST be
    applied to both — tests/test_sparse_sweep.py's lattice cases are the
    drift tripwire."""
    ops = table_ops(model, cfg)
    K, S = cfg.k_slots, cfg.n_states
    W = 1 << (K - 5)
    TILE, NT, CAP = plan.tile_words, plan.n_tiles, plan.cap
    assert NT * TILE == W
    tbits = TILE.bit_length() - 1
    tile_off = jnp.arange(TILE, dtype=jnp.int32)
    cap_ids = jnp.arange(CAP, dtype=jnp.int32)

    def sweep(T, allowed, trans, idx, count):
        # Static-capacity gathered work list (built by the caller). Pad
        # entries index tile 0 and are zeroed via `valid`, so their
        # scatter adds are zeros (harmless under the unique-destination
        # adds below).
        valid = cap_ids < count
        cols = idx[:, None] * TILE + tile_off[None, :]        # [CAP, TILE]
        flat = cols.reshape(-1)
        G = jnp.where(valid[None, :, None], T[:, cols], jnp.uint32(0))
        aG = allowed[cols][None]                              # [1,CAP,TILE]
        crossT = T
        for j in range(K):
            src = G & aG
            if j < 5:
                fired = ops.or_reduce(trans[j], src & _LO_MASK[j])
                G = G | (fired << np.uint32(1 << j))
            elif j - 5 < tbits:
                # Mask bit j lives in the tile's own word bits: the same
                # [hi, 2, lo] exposure as the dense sweep, per tile.
                lo_w, hi = 1 << (j - 5), TILE >> (j - 4)
                Gr = G.reshape(S, CAP, hi, 2, lo_w)
                srcj = src.reshape(S, CAP, hi, 2, lo_w)[:, :, :, 0, :]
                fired = ops.or_reduce(trans[j], srcj)
                G = jnp.stack([Gr[:, :, :, 0, :], Gr[:, :, :, 1, :] | fired],
                              axis=3).reshape(S, CAP, TILE)
            else:
                # Mask bit j lives in the TILE index: fired configs move
                # from tile t (bit clear) to tile t | 1<<b. Destinations
                # are unique across live source tiles (they differ in
                # their other bits), so a scatter-ADD into a zero buffer
                # is exactly a scatter-OR; cross fires land in the full
                # table, where the NEXT round's work list picks the
                # newly-live tiles up (Jacobi across tiles — the round
                # bound below still holds).
                b = j - 5 - tbits
                src_ok = ((idx >> b) & 1) == 0
                fired = ops.or_reduce(trans[j], src)
                fired = jnp.where((valid & src_ok)[None, :, None], fired,
                                  jnp.uint32(0))
                dcols = ((idx | (1 << b))[:, None] * TILE
                         + tile_off[None, :]).reshape(-1)
                crossT = crossT | jnp.zeros_like(T).at[:, dcols].add(
                    fired.reshape(S, CAP * TILE))
        Gv = jnp.where(valid[None, :, None], G, jnp.uint32(0))
        localT = jnp.zeros_like(T).at[:, flat].add(
            Gv.reshape(S, CAP * TILE))
        return crossT | localT

    return sweep


def make_step_fn3_sparse(model: Model, cfg: DenseConfig, plan: SparsePlan,
                         canon: bool = False, min_frontier: int = 0,
                         memo_slots: int = 0):
    """Scan body mirroring wgl3.make_step_fn3 with the closure round
    replaced by the density-switched sparse/dense hybrid. Per-step scan
    outputs: (configs live after convergence, live tiles after
    convergence, every-round-ran-sparse flag, overflow-forced dense
    rounds) — pads emit zeros.

    ``memo_slots`` (memo_slots_for) enables the device-side `seen`
    memo: one consumed-popcount slot per occupancy tile, reset each
    step. A sparse round then gathers only the tiles whose content GREW
    since last swept — exact because the table is a monotone OR-lattice
    (equal popcount ⟺ equal content), so a non-grown tile's expansion
    is already in the table (its local fires landed in the scatter, its
    cross-tile fires in the destination tiles). A round with nothing
    eligible skips the gather/expand entirely (the fixpoint-
    verification round costs one reduce instead of a sweep), and a
    dense round invalidates the memo wholesale (Gauss-Seidel consumes
    mid-sweep content, so per-tile consumed counts are undefined).

    ``canon``/``min_frontier``: the per-step frontier canonicalization
    pass (ops/canon.py), applied to the CONVERGED table exactly like
    wgl3.make_step_fn3 — the scan inputs gain the exchange network and
    the outputs gain (canon_pruned, canon_base)."""
    ops = table_ops(model, cfg)
    sweep = make_sparse_sweep(model, cfg, plan)
    TILE, NT, CAP = plan.tile_words, plan.n_tiles, plan.cap
    thresh = plan.thresh_tiles
    thresh_density = max(plan.thresh_density, plan.thresh_tiles)
    transitions = ops.transitions
    memo = memo_slots > 0
    assert not memo or memo_slots == NT, (memo_slots, NT)
    cap_ids = jnp.arange(CAP, dtype=jnp.int32)
    if canon:
        from .canon import apply_step_canon, make_table_canon

        canon_fn = make_table_canon(1 << (cfg.k_slots - 5))

    def occupancy(T):
        any_w = jnp.any(T != jnp.uint32(0), axis=0)
        occ_t = jnp.any(any_w.reshape(NT, TILE), axis=1)
        return occ_t, jnp.sum(occ_t, dtype=jnp.int32)

    def tile_popcounts(T):
        """i32[NT] per-tile config counts — the memo's change detector.
        Sum-of-tiles = the table popcount, and the memo loop CARRIES the
        vector between rounds, so eligibility and the convergence check
        share one O(S*W) reduce per round."""
        pc = jax.lax.population_count(T).astype(jnp.int32)
        return jnp.sum(pc.reshape(cfg.n_states, NT, TILE), axis=(0, 2))

    def worklist(mask, count):
        idx = jnp.nonzero(mask, size=CAP, fill_value=0)[0]
        return idx, jnp.minimum(count, jnp.int32(CAP))

    def step(carry, xs):
        if canon:
            trans, target, idx, pairs = xs
        else:
            trans, target, idx = xs
        is_pad = target < 0
        t = jnp.maximum(target, 0)
        allowed = ops.allowed_mask(t)

        def body(st):
            if memo:
                (T, pc, n_prev, _changed, rounds, sp_rounds, ovf_rounds,
                 swept) = st
                occ_t = pc > 0
                live = jnp.sum(occ_t, dtype=jnp.int32)
                elig_t = occ_t & (pc != swept)
                elig = jnp.sum(elig_t, dtype=jnp.int32)
            else:
                T, n_prev, _changed, rounds, sp_rounds, ovf_rounds = st
                occ_t, live = occupancy(T)
                elig_t, elig = occ_t, live
            # The direction-optimizing switch, PER ROUND: a frontier
            # that fills up mid-closure crosses to dense (and back) with
            # no host involvement; a work-list overflow (live > cap) is
            # just a dense round — configs are never dropped, but the
            # fallback is COUNTED (wgl.sparse_overflow_rounds).
            use_sparse = live <= thresh
            ovf = (~use_sparse) & (live <= jnp.int32(thresh_density))
            wl, count = worklist(elig_t, elig)

            def run_sparse(T):
                if memo:
                    # Skip the whole gather/expand when nothing grew —
                    # the fixpoint-verification round for free.
                    return jax.lax.cond(
                        elig > 0,
                        lambda T: sweep(T, allowed, trans, wl, count),
                        lambda T: T, T)
                return sweep(T, allowed, trans, wl, count)

            T = jax.lax.cond(
                use_sparse, run_sparse,
                lambda T: ops.dense_sweep(T, allowed, trans),
                T)
            if memo:
                # One reduce serves next round's eligibility AND this
                # round's convergence check.
                pc2 = tile_popcounts(T)
                n_now = jnp.sum(pc2, dtype=jnp.int32)
                # Record each gathered tile's CONSUMED count (its
                # content may grow during its own sweep — it then
                # mismatches and re-sweeps next round, which is the
                # convergence check). A dense round invalidates all.
                swept2 = swept.at[
                    jnp.where(cap_ids < count, wl, jnp.int32(NT))].set(
                        pc[wl], mode="drop")
                swept = jnp.where(use_sparse, swept2,
                                  jnp.full((NT,), -1, jnp.int32))
                return (T, pc2, n_now, n_now > n_prev, rounds + 1,
                        sp_rounds + use_sparse.astype(jnp.int32),
                        ovf_rounds + ovf.astype(jnp.int32), swept)
            n_now = jnp.sum(jax.lax.population_count(T), dtype=jnp.int32)
            return (T, n_now, n_now > n_prev, rounds + 1,
                    sp_rounds + use_sparse.astype(jnp.int32),
                    ovf_rounds + ovf.astype(jnp.int32))

        ci = 3 if memo else 2   # index of `changed` in the loop state

        def cond(st):
            return st[ci] & (st[ci + 1] < cfg.rounds)

        if memo:
            pc0 = tile_popcounts(carry.table)
            init = (carry.table, pc0,
                    jnp.sum(pc0, dtype=jnp.int32), ~is_pad,
                    jnp.int32(0), jnp.int32(0), jnp.int32(0),
                    jnp.full((NT,), -1, jnp.int32))
            fin = jax.lax.while_loop(cond, body, init)
            T, _pc, n, _c, rounds, sp_rounds, ovf_rounds = fin[:7]
        else:
            n0 = jnp.sum(jax.lax.population_count(carry.table),
                         dtype=jnp.int32)
            init = (carry.table, n0, ~is_pad, jnp.int32(0), jnp.int32(0),
                    jnp.int32(0))
            fin = jax.lax.while_loop(cond, body, init)
            T, n, _c, rounds, sp_rounds, ovf_rounds = fin[:6]
        if canon:
            T, n, canon_pruned, canon_base = apply_step_canon(
                canon_fn, T, pairs, n, is_pad, min_frontier)
        _occ, live_fin = occupancy(T)
        pruned = ops.prune(T, t, allowed)
        T_new = jnp.where(is_pad, T, pruned)
        alive = jnp.any(T_new != 0)
        died = ~is_pad & ~carry.dead & ~alive
        dead = carry.dead | died
        T_new = jnp.where(dead, jnp.zeros_like(T_new), T_new)
        sparse_all = (~is_pad) & (rounds > 0) & (sp_rounds == rounds)
        outs = (jnp.where(is_pad, 0, n),
                jnp.where(is_pad, 0, live_fin),
                sparse_all.astype(jnp.int32),
                jnp.where(is_pad, 0, ovf_rounds))
        if canon:
            outs = outs + (canon_pruned, canon_base)
        return _Carry3(
            table=T_new, dead=dead,
            dead_step=jnp.where(died & (carry.dead_step < 0), idx,
                                carry.dead_step),
            max_frontier=jnp.maximum(carry.max_frontier, n)), outs

    return step, transitions


def _chunk_fn_sparse(model: Model, cfg: DenseConfig, plan: SparsePlan,
                     memo_slots: int = 0):
    """Sparse twin of wgl3._chunk_fn: jitted (carry, tabs, act, tgts,
    idx0) -> (carry', f32[5] partials [configs, live-tile sum, real
    steps, sparse steps, overflow-forced dense rounds]). The carry is
    DONATED (threaded linearly by every caller, like the dense chunk
    fn)."""
    step, transitions = make_step_fn3_sparse(model, cfg, plan,
                                             memo_slots=memo_slots)

    def run(carry, tabs, act, tgts, idx0):
        trans = jax.vmap(transitions)(tabs, act)
        idxs = idx0 + jnp.arange(tgts.shape[0], dtype=jnp.int32)
        carry, (ns, lives, sp, ovf) = jax.lax.scan(step, carry,
                                                   (trans, tgts, idxs))
        # jtflow: partials configs_explored,live_tile_sum,real_steps,sparse_steps,overflow_rounds
        return carry, jnp.stack([
            jnp.sum(ns.astype(jnp.float32)),
            jnp.sum(lives.astype(jnp.float32)),
            jnp.sum((tgts >= 0).astype(jnp.float32)),
            jnp.sum(sp.astype(jnp.float32)),
            jnp.sum(ovf.astype(jnp.float32))])

    return jax.jit(run, donate_argnums=(0,))


def _chunk_fn_sparse_dedup(model: Model, cfg: DenseConfig,
                           plan: SparsePlan, min_frontier: int,
                           memo_slots: int):
    """Canonicalizing twin of _chunk_fn_sparse (pairs scan input, two
    extra partial columns) — built only for histories whose exchange
    network is non-empty, like wgl3._chunk_fn_dedup."""
    step, transitions = make_step_fn3_sparse(model, cfg, plan, canon=True,
                                             min_frontier=min_frontier,
                                             memo_slots=memo_slots)

    def run(carry, tabs, act, tgts, pairs, idx0):
        trans = jax.vmap(transitions)(tabs, act)
        idxs = idx0 + jnp.arange(tgts.shape[0], dtype=jnp.int32)
        carry, (ns, lives, sp, ovf, pr, base) = jax.lax.scan(
            step, carry, (trans, tgts, idxs, pairs))
        # jtflow: partials configs_explored,live_tile_sum,real_steps,sparse_steps,overflow_rounds,canon_pruned,canon_base
        return carry, jnp.stack([
            jnp.sum(ns.astype(jnp.float32)),
            jnp.sum(lives.astype(jnp.float32)),
            jnp.sum((tgts >= 0).astype(jnp.float32)),
            jnp.sum(sp.astype(jnp.float32)),
            jnp.sum(ovf.astype(jnp.float32)),
            jnp.sum(pr.astype(jnp.float32)),
            jnp.sum(base.astype(jnp.float32))])

    return jax.jit(run, donate_argnums=(0,))


def _cached_sparse_chunk(model: Model, cfg: DenseConfig, plan: SparsePlan,
                         chunk: int, memo_slots: int = 0):
    key = ("sparse-chunk", model.cache_key(), cfg, plan, chunk,
           memo_slots)
    if key not in _CACHE:
        _CACHE[key] = instrument_kernel(
            "wgl3-sparse-chunk",
            _chunk_fn_sparse(model, cfg, plan, memo_slots=memo_slots))
    return _CACHE[key]


def _cached_sparse_chunk_dedup(model: Model, cfg: DenseConfig,
                               plan: SparsePlan, chunk: int,
                               min_frontier: int, memo_slots: int):
    key = ("sparse-chunk-dedup", model.cache_key(), cfg, plan, chunk,
           min_frontier, memo_slots)
    if key not in _CACHE:
        _CACHE[key] = instrument_kernel(
            "wgl3-sparse-chunk-dedup",
            _chunk_fn_sparse_dedup(model, cfg, plan, min_frontier,
                                   memo_slots))
    return _CACHE[key]


def check_steps3_long_sparse(rs: ReturnSteps, model: Model,
                             cfg: DenseConfig, plan: SparsePlan,
                             chunk: int | None = None,
                             time_budget_s: float | None = None) -> dict:
    """Chunked single-history sweep through the sparse engine: the same
    host loop as wgl3.check_steps3_long (double-buffered staging,
    periodic death polls, one packed fetch at the end; synchronous when
    budgeted), bit-identical verdicts, plus the sweep-mode/live-tile
    record behind the telemetry gauges and the bench's `sparse` lane."""
    import time as _time

    from ..sched.pipeline import double_buffer
    from .wgl import verdict

    t0 = _time.monotonic()
    if chunk is None:
        chunk = default_scan_chunk(cfg)
    n = rs.n_steps
    n_pad = (n + chunk - 1) // chunk * chunk
    rs = rs.padded_to(n_pad)
    from .canon import dedup_min_frontier_active, history_canon_pairs
    from .wgl3 import attach_dedup_record

    memo = memo_slots_for(plan)
    pairs = history_canon_pairs(rs, table=True)
    if pairs is not None:
        run = _cached_sparse_chunk_dedup(model, cfg, plan, chunk,
                                         dedup_min_frontier_active(),
                                         memo)
    else:
        run = _cached_sparse_chunk(model, cfg, plan, chunk,
                                   memo_slots=memo)
    carry = _init_carry3(model, cfg)
    parts_dev = None
    if time_budget_s is None:
        poll = max(1, limits().sched_poll_chunks)

        def stage(c):
            sl = slice(c * chunk, (c + 1) * chunk)
            staged = (jnp.asarray(rs.slot_tabs[sl]),
                      jnp.asarray(rs.slot_active[sl]),
                      jnp.asarray(rs.targets[sl]))
            if pairs is not None:
                staged = staged + (jnp.asarray(pairs[sl]),)
            return staged + (jnp.int32(c * chunk),)

        done = 0
        for staged in double_buffer(range(n_pad // chunk), stage):
            carry, part = run(carry, *staged)
            parts_dev = part if parts_dev is None else parts_dev + part
            done += 1
            # jtlint: disable=JTL103 -- bounded death poll: one fetch per
            # sched_poll_chunks chunks (the [tunable] knob), not per
            # iteration — same contract as the dense twin in wgl3.py.
            if done % poll == 0 and bool(np.asarray(carry.dead)):
                break
    else:
        for c in range(n_pad // chunk):
            if _time.monotonic() - t0 > time_budget_s:
                return {"valid": "unknown", "survived": False,
                        "overflow": True, "dead_step": -1,
                        "max_frontier": -1, "configs_explored": -1,
                        "kernel": "exhausted",
                        "error": f"sparse-chunked sweep exceeded its "
                                 f"{time_budget_s:.0f}s time budget at "
                                 f"return step {c * chunk}"}
            sl = slice(c * chunk, (c + 1) * chunk)
            args = (jnp.asarray(rs.slot_tabs[sl]),
                    jnp.asarray(rs.slot_active[sl]),
                    jnp.asarray(rs.targets[sl]))
            if pairs is not None:
                args = args + (jnp.asarray(pairs[sl]),)
            carry, part = run(carry, *args, jnp.int32(c * chunk))
            parts_dev = part if parts_dev is None else parts_dev + part
            # jtlint: disable=JTL103 -- budgeted lane: synchronous per-
            # chunk fetch bounds budget overshoot to one chunk (the
            # wgl3.py contract).
            if bool(np.asarray(carry.dead)):
                break

    n_parts = 7 if pairs is not None else 5
    if parts_dev is None:
        parts_dev = jnp.zeros((n_parts,), jnp.float32)
    # jtflow: partials-from wgl3_sparse._chunk_fn_sparse
    # jtflow: partials-from wgl3_sparse._chunk_fn_sparse_dedup
    packed = np.asarray(jnp.concatenate([
        jnp.stack([jnp.where(carry.dead, 0, 1),
                   carry.dead_step, carry.max_frontier]),
        jnp.clip(parts_dev, 0, 2**31 - 1).astype(jnp.int32)]))
    out = {
        "survived": bool(packed[0]),
        "overflow": False,
        "dead_step": int(packed[1]),
        "max_frontier": int(packed[2]),
        "configs_explored": int(packed[3]),
        "kernel": "wgl3-dense-sparse-chunked",
    }
    out["sweep"] = sweep_summary(cfg, live_sum=float(packed[4]),
                                 real_steps=int(packed[5]),
                                 sparse_steps=int(packed[6]),
                                 overflow_rounds=int(packed[7]))
    out["live_tile_ratio"] = out["sweep"]["live_tile_ratio"]
    if pairs is not None:
        # Canon columns are the LAST two of the dedup layout by
        # construction (_chunk_fn_sparse_dedup) — negative indexing
        # keeps the base-layout reads above layout-checkable (JTL401).
        attach_dedup_record(out, pruned=float(packed[-2]),
                            base=float(packed[-1]))
    out["valid"] = verdict(out)
    record_check_result(out)
    return out


__all__ = [
    "SparsePlan",
    "check_steps3_long_sparse",
    "make_sparse_sweep",
    "make_step_fn3_sparse",
    "sparse_plan",
]
