"""Sparse active-tile sweep engine for the dense WGL lattice kernels.

The dense subset-lattice kernel (ops/wgl3.py) pays O(K * S * 2^K) word-ops
per return step regardless of how few configs are LIVE — and past K ~ 17
"the live frontier is invariably tiny relative to the lattice" (the
dense_config docstring's own admission; it is why the cell budget routes
wide geometries away from the dense sweep at all). This module removes
that waste the way direction-optimizing BFS removes it from graph
traversal (Beamer, Asanović & Patterson, SC'12): sweep only where the
frontier IS, and switch back to the dense formulation when the frontier
gets dense enough that skipping stops paying.

Mechanics, per closure round (the sweep inside each return step's
fixpoint loop):

  * the packed table u32[S, W] is viewed as W / TILE occupancy TILES of
    TILE contiguous words (TILE = limits().sparse_tile_words); a tile is
    live when any of its words is nonzero in any state row;
  * a static-capacity work list (limits().sparse_worklist_cap) gathers
    the LIVE tiles' indices (jnp.nonzero with a static size — XLA shapes
    stay static) and the sweep runs gather -> expand -> scatter:
      - slot j < 5:              in-word shift — local to the gathered tile
      - 5 <= j-5 < log2(TILE):   word-axis reshape — local to the tile
      - j-5 >= log2(TILE):       the mask bit lives in the TILE index —
                                 fired configs scatter-OR into tile
                                 (t | 1 << bit), a per-slot scatter with
                                 provably unique destinations;
  * when the live-tile count crosses the density threshold
    (limits().sparse_density_threshold_pct) or overflows the work list,
    THAT ROUND runs the ordinary dense sweep instead — the
    direction-optimizing switch. Work-list overflow therefore never
    drops configs; it only costs the dense round the engine would have
    run anyway.

Why verdicts are bit-identical to the dense sweep: the closure is a
monotone OR-fixpoint, and one sparse round is a superset of one Jacobi
round of the full table (every config at firing-distance 1 from the
current table has its source in a live tile, and every such firing is
computed — locally with in-round chaining, across tiles via the
scatter). K Jacobi rounds provably converge (each firing sets a distinct
slot bit), the round cap is cfg.rounds >= K (sparse_plan refuses
truncating caps), and the fixpoint is unique — so the converged table,
and with it every verdict field (survived / overflow / dead_step /
max_frontier / configs_explored), is exactly the dense kernel's.
Differential tests pin this on the golden + fuzz corpora
(tests/test_sparse_sweep.py).

Cost: a sparse round is O(K * S * cap * TILE) plus an O(S * W) occupancy
reduce — per-step cost tracks the LIVE frontier, which is what lets long
sparse histories scale past K ~ 20 (the lattice-sharded twin in
parallel/lattice.py shards the same engine over devices).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..models.base import Model
from ..obs import instrument_kernel, record_check_result
from .encode import ReturnSteps
from .limits import limits
from .wgl3 import (DenseConfig, _Carry3, _LO_MASK, _init_carry3,
                   default_scan_chunk, live_tile_geometry, sweep_summary,
                   table_ops)

_CACHE: dict[tuple, Any] = {}


@dataclass(frozen=True)
class SparsePlan:
    """The static shape of one geometry's sparse sweep: tile size, tile
    count, gather capacity, and the effective live-tile threshold above
    which a round runs dense. Hashable — part of the jit cache key."""
    tile_words: int     # TILE: packed words per occupancy tile (pow2)
    n_tiles: int        # W / TILE
    cap: int            # static work-list capacity (tiles gathered)
    thresh_tiles: int   # live-tile count above which the round is dense


def sparse_plan(cfg: DenseConfig, words: int | None = None
                ) -> SparsePlan | None:
    """The sparse plan for this geometry under the active limits — or
    None when the engine must stay off: sparse_mode=1 (dense-only), a
    truncating max_rounds (the hybrid's round ORDER differs from the
    Gauss-Seidel sweep, so a sub-convergence cap could diverge), or too
    few tiles to be worth the per-round occupancy + gather overhead.

    `words` overrides the table width for SHARDED tables (parallel/
    lattice.py passes its per-device word count so each shard's work
    list is sized to the shard)."""
    lim = limits()
    if lim.sparse_mode == 1:
        return None
    if cfg.max_rounds and cfg.max_rounds < cfg.k_slots:
        return None
    tile, n_tiles = live_tile_geometry(cfg, words=words)
    if n_tiles < 2:
        return None     # structurally too narrow to tile at all
    if lim.sparse_mode != 2 and n_tiles < lim.sparse_min_tiles:
        # AUTO mode engages only past the measured static crossover
        # (see the sparse_min_tiles rationale in ops/limits.py);
        # prefer-sparse (2) is the explicit measurement override.
        return None
    cap = max(1, min(lim.sparse_worklist_cap, n_tiles))
    if lim.sparse_mode == 2:
        thresh = n_tiles
    else:
        thresh = max(1, n_tiles * lim.sparse_density_threshold_pct // 100)
    return SparsePlan(tile_words=tile, n_tiles=n_tiles, cap=cap,
                      thresh_tiles=min(thresh, cap))


def make_sparse_sweep(model: Model, cfg: DenseConfig, plan: SparsePlan):
    """(T, allowed, trans, occ_t, live) -> T': one gather->expand->
    scatter round over the live tiles.

    LOCKSTEP NOTE: parallel/lattice.py `sweep_sparse` is this sweep's
    shard-local mirror (same gather, same in-word/in-tile/tile-bit
    branches and pad masking, plus a device-bit branch that scatters to
    shard width and ppermutes). The two cannot share code without
    threading the shard closure's or_reduce/axis context through here,
    so any fix to the bit algebra or the valid/src_ok masking MUST be
    applied to both — tests/test_sparse_sweep.py's lattice cases are the
    drift tripwire."""
    ops = table_ops(model, cfg)
    K, S = cfg.k_slots, cfg.n_states
    W = 1 << (K - 5)
    TILE, NT, CAP = plan.tile_words, plan.n_tiles, plan.cap
    assert NT * TILE == W
    tbits = TILE.bit_length() - 1
    tile_off = jnp.arange(TILE, dtype=jnp.int32)
    cap_ids = jnp.arange(CAP, dtype=jnp.int32)

    def sweep(T, allowed, trans, occ_t, live):
        # Static-capacity gather of the live tiles. Pad entries index
        # tile 0 and are zeroed via `valid`, so their scatter adds are
        # zeros (harmless under the unique-destination adds below).
        idx = jnp.nonzero(occ_t, size=CAP, fill_value=0)[0]
        valid = cap_ids < live
        cols = idx[:, None] * TILE + tile_off[None, :]        # [CAP, TILE]
        flat = cols.reshape(-1)
        G = jnp.where(valid[None, :, None], T[:, cols], jnp.uint32(0))
        aG = allowed[cols][None]                              # [1,CAP,TILE]
        crossT = T
        for j in range(K):
            src = G & aG
            if j < 5:
                fired = ops.or_reduce(trans[j], src & _LO_MASK[j])
                G = G | (fired << np.uint32(1 << j))
            elif j - 5 < tbits:
                # Mask bit j lives in the tile's own word bits: the same
                # [hi, 2, lo] exposure as the dense sweep, per tile.
                lo_w, hi = 1 << (j - 5), TILE >> (j - 4)
                Gr = G.reshape(S, CAP, hi, 2, lo_w)
                srcj = src.reshape(S, CAP, hi, 2, lo_w)[:, :, :, 0, :]
                fired = ops.or_reduce(trans[j], srcj)
                G = jnp.stack([Gr[:, :, :, 0, :], Gr[:, :, :, 1, :] | fired],
                              axis=3).reshape(S, CAP, TILE)
            else:
                # Mask bit j lives in the TILE index: fired configs move
                # from tile t (bit clear) to tile t | 1<<b. Destinations
                # are unique across live source tiles (they differ in
                # their other bits), so a scatter-ADD into a zero buffer
                # is exactly a scatter-OR; cross fires land in the full
                # table, where the NEXT round's work list picks the
                # newly-live tiles up (Jacobi across tiles — the round
                # bound below still holds).
                b = j - 5 - tbits
                src_ok = ((idx >> b) & 1) == 0
                fired = ops.or_reduce(trans[j], src)
                fired = jnp.where((valid & src_ok)[None, :, None], fired,
                                  jnp.uint32(0))
                dcols = ((idx | (1 << b))[:, None] * TILE
                         + tile_off[None, :]).reshape(-1)
                crossT = crossT | jnp.zeros_like(T).at[:, dcols].add(
                    fired.reshape(S, CAP * TILE))
        Gv = jnp.where(valid[None, :, None], G, jnp.uint32(0))
        localT = jnp.zeros_like(T).at[:, flat].add(
            Gv.reshape(S, CAP * TILE))
        return crossT | localT

    return sweep


def make_step_fn3_sparse(model: Model, cfg: DenseConfig, plan: SparsePlan):
    """Scan body mirroring wgl3.make_step_fn3 with the closure round
    replaced by the density-switched sparse/dense hybrid. Per-step scan
    outputs: (configs live after convergence, live tiles after
    convergence, every-round-ran-sparse flag) — pads emit zeros."""
    ops = table_ops(model, cfg)
    sweep = make_sparse_sweep(model, cfg, plan)
    TILE, NT = plan.tile_words, plan.n_tiles
    thresh = plan.thresh_tiles
    transitions = ops.transitions

    def occupancy(T):
        any_w = jnp.any(T != jnp.uint32(0), axis=0)
        occ_t = jnp.any(any_w.reshape(NT, TILE), axis=1)
        return occ_t, jnp.sum(occ_t, dtype=jnp.int32)

    def step(carry, xs):
        trans, target, idx = xs
        is_pad = target < 0
        t = jnp.maximum(target, 0)
        allowed = ops.allowed_mask(t)

        def body(st):
            T, n_prev, _changed, rounds, sp_rounds = st
            occ_t, live = occupancy(T)
            # The direction-optimizing switch, PER ROUND: a frontier
            # that fills up mid-closure crosses to dense (and back) with
            # no host involvement; a work-list overflow (live > cap) is
            # just a dense round — configs are never dropped.
            use_sparse = live <= thresh
            T = jax.lax.cond(
                use_sparse,
                lambda T: sweep(T, allowed, trans, occ_t, live),
                lambda T: ops.dense_sweep(T, allowed, trans),
                T)
            n_now = jnp.sum(jax.lax.population_count(T), dtype=jnp.int32)
            return (T, n_now, n_now > n_prev, rounds + 1,
                    sp_rounds + use_sparse.astype(jnp.int32))

        def cond(st):
            return st[2] & (st[3] < cfg.rounds)

        n0 = jnp.sum(jax.lax.population_count(carry.table),
                     dtype=jnp.int32)
        T, n, _c, rounds, sp_rounds = jax.lax.while_loop(
            cond, body, (carry.table, n0, ~is_pad, jnp.int32(0),
                         jnp.int32(0)))
        _occ, live_fin = occupancy(T)
        pruned = ops.prune(T, t, allowed)
        T_new = jnp.where(is_pad, T, pruned)
        alive = jnp.any(T_new != 0)
        died = ~is_pad & ~carry.dead & ~alive
        dead = carry.dead | died
        T_new = jnp.where(dead, jnp.zeros_like(T_new), T_new)
        sparse_all = (~is_pad) & (rounds > 0) & (sp_rounds == rounds)
        return _Carry3(
            table=T_new, dead=dead,
            dead_step=jnp.where(died & (carry.dead_step < 0), idx,
                                carry.dead_step),
            max_frontier=jnp.maximum(carry.max_frontier, n)), (
                jnp.where(is_pad, 0, n),
                jnp.where(is_pad, 0, live_fin),
                sparse_all.astype(jnp.int32))

    return step, transitions


def _chunk_fn_sparse(model: Model, cfg: DenseConfig, plan: SparsePlan):
    """Sparse twin of wgl3._chunk_fn: jitted (carry, tabs, act, tgts,
    idx0) -> (carry', f32[4] partials [configs, live-tile sum, real
    steps, sparse steps]). The carry is DONATED (threaded linearly by
    every caller, like the dense chunk fn)."""
    step, transitions = make_step_fn3_sparse(model, cfg, plan)

    def run(carry, tabs, act, tgts, idx0):
        trans = jax.vmap(transitions)(tabs, act)
        idxs = idx0 + jnp.arange(tgts.shape[0], dtype=jnp.int32)
        carry, (ns, lives, sp) = jax.lax.scan(step, carry,
                                              (trans, tgts, idxs))
        # jtflow: partials configs_explored,live_tile_sum,real_steps,sparse_steps
        return carry, jnp.stack([
            jnp.sum(ns.astype(jnp.float32)),
            jnp.sum(lives.astype(jnp.float32)),
            jnp.sum((tgts >= 0).astype(jnp.float32)),
            jnp.sum(sp.astype(jnp.float32))])

    return jax.jit(run, donate_argnums=(0,))


def _cached_sparse_chunk(model: Model, cfg: DenseConfig, plan: SparsePlan,
                         chunk: int):
    key = ("sparse-chunk", model.cache_key(), cfg, plan, chunk)
    if key not in _CACHE:
        _CACHE[key] = instrument_kernel("wgl3-sparse-chunk",
                                        _chunk_fn_sparse(model, cfg, plan))
    return _CACHE[key]


def check_steps3_long_sparse(rs: ReturnSteps, model: Model,
                             cfg: DenseConfig, plan: SparsePlan,
                             chunk: int | None = None,
                             time_budget_s: float | None = None) -> dict:
    """Chunked single-history sweep through the sparse engine: the same
    host loop as wgl3.check_steps3_long (double-buffered staging,
    periodic death polls, one packed fetch at the end; synchronous when
    budgeted), bit-identical verdicts, plus the sweep-mode/live-tile
    record behind the telemetry gauges and the bench's `sparse` lane."""
    import time as _time

    from ..sched.pipeline import double_buffer
    from .wgl import verdict

    t0 = _time.monotonic()
    if chunk is None:
        chunk = default_scan_chunk(cfg)
    run = _cached_sparse_chunk(model, cfg, plan, chunk)
    n = rs.n_steps
    n_pad = (n + chunk - 1) // chunk * chunk
    rs = rs.padded_to(n_pad)
    carry = _init_carry3(model, cfg)
    parts_dev = None
    if time_budget_s is None:
        poll = max(1, limits().sched_poll_chunks)

        def stage(c):
            sl = slice(c * chunk, (c + 1) * chunk)
            return (jnp.asarray(rs.slot_tabs[sl]),
                    jnp.asarray(rs.slot_active[sl]),
                    jnp.asarray(rs.targets[sl]),
                    jnp.int32(c * chunk))

        done = 0
        for staged in double_buffer(range(n_pad // chunk), stage):
            carry, part = run(carry, *staged)
            parts_dev = part if parts_dev is None else parts_dev + part
            done += 1
            # jtlint: disable=JTL103 -- bounded death poll: one fetch per
            # sched_poll_chunks chunks (the [tunable] knob), not per
            # iteration — same contract as the dense twin in wgl3.py.
            if done % poll == 0 and bool(np.asarray(carry.dead)):
                break
    else:
        for c in range(n_pad // chunk):
            if _time.monotonic() - t0 > time_budget_s:
                return {"valid": "unknown", "survived": False,
                        "overflow": True, "dead_step": -1,
                        "max_frontier": -1, "configs_explored": -1,
                        "kernel": "exhausted",
                        "error": f"sparse-chunked sweep exceeded its "
                                 f"{time_budget_s:.0f}s time budget at "
                                 f"return step {c * chunk}"}
            sl = slice(c * chunk, (c + 1) * chunk)
            carry, part = run(carry, jnp.asarray(rs.slot_tabs[sl]),
                              jnp.asarray(rs.slot_active[sl]),
                              jnp.asarray(rs.targets[sl]),
                              jnp.int32(c * chunk))
            parts_dev = part if parts_dev is None else parts_dev + part
            # jtlint: disable=JTL103 -- budgeted lane: synchronous per-
            # chunk fetch bounds budget overshoot to one chunk (the
            # wgl3.py contract).
            if bool(np.asarray(carry.dead)):
                break

    if parts_dev is None:
        parts_dev = jnp.zeros((4,), jnp.float32)
    # jtflow: partials-from wgl3_sparse._chunk_fn_sparse
    packed = np.asarray(jnp.concatenate([
        jnp.stack([jnp.where(carry.dead, 0, 1),
                   carry.dead_step, carry.max_frontier]),
        jnp.clip(parts_dev, 0, 2**31 - 1).astype(jnp.int32)]))
    out = {
        "survived": bool(packed[0]),
        "overflow": False,
        "dead_step": int(packed[1]),
        "max_frontier": int(packed[2]),
        "configs_explored": int(packed[3]),
        "kernel": "wgl3-dense-sparse-chunked",
    }
    out["sweep"] = sweep_summary(cfg, live_sum=float(packed[4]),
                                 real_steps=int(packed[5]),
                                 sparse_steps=int(packed[6]))
    out["live_tile_ratio"] = out["sweep"]["live_tile_ratio"]
    out["valid"] = verdict(out)
    record_check_result(out)
    return out


__all__ = [
    "SparsePlan",
    "check_steps3_long_sparse",
    "make_sparse_sweep",
    "make_step_fn3_sparse",
    "sparse_plan",
]
