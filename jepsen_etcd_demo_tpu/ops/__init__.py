"""History core: op records, invoke/complete pairing, tensor encoding, WGL kernel."""

from .op import Op, INVOKE, OK, FAIL, INFO  # noqa: F401
from .encode import (  # noqa: F401
    NIL,
    F_READ,
    F_WRITE,
    F_CAS,
    EV_INVOKE,
    EV_RETURN,
    EV_PAD,
    Invocation,
    pair_history,
    encode_events,
    encode_register_history,
    EncodedHistory,
)
