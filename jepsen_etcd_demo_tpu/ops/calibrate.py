"""Per-platform oracle/device crossover calibration.

Round-4 review (VERDICT.md weak #2 / next #3): the event-count crossover
below which a single tiny history routes to the exact host oracle instead
of a device launch was a hardcoded constant (2048) that encoded ONE
backend's ~0.1 s dispatch floor. On a runtime with fast dispatch the
router would still refuse the TPU for the reference's entire default
envelope (~150-op tutorial histories, BASELINE.md), and on a slower
tunnel it would under-route. The crossover is a property of the PLATFORM,
so it is measured per platform here, once, and persisted next to the XLA
compile cache:

  crossover_events = dispatch_floor_s * oracle_events_per_s

i.e. the history size at which the oracle's whole runtime equals the
device dispatch+fetch round trip that a launch pays before any compute.
Below it the host oracle finishes before a device launch could even
report back; above it the kernel wins. Both factors are measured, not
assumed:

  * dispatch_floor_s — best observed round trip of an already-compiled
    trivial launch (dispatch + fetch of one word). The minimum over a few
    repeats deliberately estimates the FLOOR, not the mean: routing only
    needs "a launch cannot possibly beat the oracle below this size".
  * oracle_events_per_s — `check_events_oracle` throughput on a synthetic
    register history at tutorial-like concurrency (utils/fuzz.py, fixed
    seed), the same regime the route serves.

The router consumes this via `limits().oracle_crossover_events == -1`
(auto, the default); a fixed positive value or the
`JEPSEN_TPU_LIMIT_ORACLE_CROSSOVER_EVENTS` env override bypasses
measurement entirely, and 0 disables oracle routing (bench.py pins 0 for
its kernel lanes). Persistence is keyed by the JAX backend + device kind,
so one cache file serves a laptop CPU run and a TPU pod worker without
cross-talk.

Persistence lives in the SHARED tuning-profile store (tune/profile.py —
ISSUE 4: one file, one version bump discipline) as this platform's
``calibration`` section. The pre-autotuner ``calibration.json`` sidecar
is a LEGACY migration source: read once when the store has no
calibration for this platform, re-persisted into the store, and ignored
thereafter (the store's copy is authoritative even if the sidecar later
changes).
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import asdict, dataclass

# Bump whenever the PROBE SEMANTICS change (shape, concurrency, p_info):
# a persisted calibration measured under an old probe must be invalidated
# or routers keep consuming crossovers the change existed to correct.
# v2: probe moved to the 10-thread no-:info canonical envelope (the
# 5-proc v1 probe over-measured oracle rate ~8x).
CAL_VERSION = 2

# Clamp bounds for the derived crossover: even on an instant-dispatch
# runtime the oracle is never beaten below a few dozen events (launch
# bookkeeping alone), and above ~64k events the dense/chunked kernels win
# regardless of dispatch cost (the oracle is super-linear in the worst
# case there, so extrapolating its measured rate would over-route).
CROSSOVER_MIN = 64
CROSSOVER_MAX = 1 << 16

# Probe shape: the reference's default envelope — 10 threads per key
# (BASELINE.md), no forever-pending :info ops. Oracle throughput is
# geometry-sensitive (the closure explores ~2^pending masks per state):
# 5-proc histories measure ~175k events/s, 10-proc ~21k, and each
# pending-forever :info op drags the rest of the history (~9k at
# p_info=0.002, ~4k at 2000 ops) — measured r5 on this image. Probing
# the canonical envelope puts the derived crossover at ~2k events on
# the axon tunnel, which matches the bench's own routed-lane break-even
# (1000-op history: oracle 0.085 s ≈ the 0.09 s dispatch floor).
# Wider/slower histories mis-route only within the bounded band the
# max_pending gate + transition budget allow.
PROBE_OPS = 1000
PROBE_PROCS = 10


@dataclass(frozen=True)
class Calibration:
    platform: str              # "<backend>/<device_kind>"
    dispatch_floor_s: float
    oracle_events_per_s: float
    crossover_events: int
    measured_at: str
    version: int = CAL_VERSION


_CAL: Calibration | None = None


def calibration_path() -> str:
    """The LEGACY sidecar path (next to the persistent XLA compile
    cache). New calibrations persist into the shared tuning-profile
    store (tune/profile.py); this file is only ever read, once, as a
    migration source."""
    base = os.environ.get("JAX_COMPILATION_CACHE_DIR",
                          os.path.expanduser("~/.cache/jepsen_tpu_xla"))
    return os.path.join(base, "calibration.json")


def platform_tag() -> str:
    import jax

    try:
        dev = jax.devices()[0]
        return f"{jax.default_backend()}/{dev.device_kind}"
    except Exception:
        return "unknown/unknown"


def measure_dispatch_floor(repeats: int = 5) -> float:
    """Round trip of an already-compiled trivial launch: dispatch one
    jitted add on a [8,128] i32 tile and fetch one word back. np.asarray
    (not block_until_ready) forces the fetch — on the tunneled axon
    backend block_until_ready returns before the result is host-visible
    (bench.py measures the same way)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    x = jnp.zeros((8, 128), jnp.int32)
    # jtlint: disable=JTL105 -- a calibration PROBE, not a production
    # kernel: instrument_kernel would fold this throwaway launch into
    # wgl.compile_s/execute_s and skew the attribution it calibrates.
    run = jax.jit(lambda a: (a + 1).sum())
    np.asarray(run(x))   # compile outside the timed region
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        np.asarray(run(x))
        best = min(best, time.perf_counter() - t0)
    return best


def measure_oracle_rate(repeats: int = 3) -> float:
    """`check_events_oracle` throughput (events/s) on a fixed-seed
    register history at tutorial concurrency."""
    import random

    from ..checkers.oracle import check_events_oracle
    from ..models import CASRegister
    from .encode import encode_register_history
    from ..utils.fuzz import gen_register_history

    rng = random.Random(0xCA11B)
    enc = encode_register_history(
        gen_register_history(rng, n_ops=PROBE_OPS, n_procs=PROBE_PROCS,
                             p_info=0.0))
    model = CASRegister()
    check_events_oracle(enc, model)      # warm (imports, caches)
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        check_events_oracle(enc, model)
        best = min(best, time.perf_counter() - t0)
    return enc.n_events / best


def measure() -> Calibration:
    floor = measure_dispatch_floor()
    rate = measure_oracle_rate()
    crossover = int(min(max(floor * rate, CROSSOVER_MIN), CROSSOVER_MAX))
    return Calibration(
        platform=platform_tag(), dispatch_floor_s=round(floor, 6),
        oracle_events_per_s=round(rate, 1), crossover_events=crossover,
        measured_at=time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()))


def _validate(data) -> Calibration | None:
    """A Calibration from a raw dict, or None when it is torn, from an
    older probe (CAL_VERSION mismatch), or from another platform."""
    try:
        cal = Calibration(**data)
    except (ValueError, TypeError):
        return None
    if cal.version != CAL_VERSION or cal.platform != platform_tag():
        return None
    return cal


def _load() -> Calibration | None:
    """This platform's calibration from the shared profile store."""
    from ..tune import profile

    data = profile.load_calibration()
    return None if data is None else _validate(data)


def _load_legacy_sidecar() -> Calibration | None:
    """The pre-ISSUE-4 calibration.json sidecar, consulted only when the
    profile store has no calibration for this platform (the migration
    read — after re-persisting into the store, the sidecar is ignored
    even if it changes)."""
    try:
        data = json.loads(open(calibration_path()).read())
    except (OSError, ValueError):
        return None
    return _validate(data) if isinstance(data, dict) else None


def _persist(cal: Calibration) -> None:
    """Into the shared profile store (atomic replace inside); like the
    old sidecar write, persistence is an optimization, never a failure
    mode."""
    from ..tune import profile

    profile.save_calibration(asdict(cal))


def get_calibration() -> Calibration:
    """Active calibration: in-memory, else the profile store, else the
    legacy sidecar (migrated into the store on first read), else
    measured now and persisted into the store."""
    global _CAL
    if _CAL is not None:
        return _CAL
    cal = _load()
    if cal is None:
        cal = _load_legacy_sidecar()
        if cal is not None:
            _persist(cal)           # migrate: store copy is authoritative
    if cal is None:
        cal = measure()
        _persist(cal)
    _CAL = cal
    return cal


def set_calibration(cal: Calibration | None) -> Calibration | None:
    """Swap the in-memory calibration (tests / embedding runtimes);
    returns the previous one. None re-enables load-or-measure."""
    global _CAL
    prev = _CAL
    _CAL = cal
    return prev
