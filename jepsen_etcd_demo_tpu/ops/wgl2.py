"""WGL linearizability search v2: return-major scan (the production kernel).

Same search as ops/wgl.py (frontier of (state, linearized-bitmask) configs,
sort-dedup compaction, just-in-time linearization) but scanning the
`ReturnSteps` encoding (encode.py): one scan step per RETURN event, with the
pending-slot table precomputed host-side as scan inputs.

Why this shape wins on TPU (vs the event-major v1 kernel):
  * every scan step does identical work — no EV_INVOKE/EV_RETURN lax.cond.
    Under vmap, a batch-varying cond lowers to a select that executes BOTH
    branches for every lane; here the batch path does exactly the work the
    single path does;
  * half the scan steps (invokes contribute no steps);
  * the slot table leaves the loop carry (scan input instead), shrinking the
    state XLA threads through the loop.

The closure is a lax.while_loop; under vmap it runs until every lane's
frontier reaches fixpoint, which costs max-rounds-over-lanes — fine, since
rounds ≈ longest firing chain ending at the returning op (usually 1-2).

Replaces the reference's knossos hot loop (src/jepsen/etcdemo.clj:117);
soundness-under-overflow argument as in ops/wgl.py.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.base import Model
from ..obs import instrument_kernel, record_check_result
from .encode import EncodedHistory, ReturnSteps, encode_return_steps
from .limits import limits


@dataclass(frozen=True)
class WGLConfig:
    """Sort-kernel search geometry (formerly ops/wgl.py, the retired v1
    event-major kernel; the config and its helpers moved here when v1 was
    deleted — the return-major sort kernel is their only remaining user)."""
    k_slots: int = 32       # pending-op slot capacity (bitmask width)
    f_cap: int = 256        # frontier capacity (configs kept after dedup)
    max_expand_rounds: int | None = None  # closure depth bound; default k_slots
    # >0 enables the packed single-uint32 dedup: every reachable model
    # state must fit in `state_bits` bits after the model's state_offset.
    # Derive from the HISTORY's actual values
    # (model.pack_bits(enc.max_value)) — never assume a value range.
    state_bits: int = 0

    @property
    def words(self) -> int:
        return (self.k_slots + 31) // 32

    @property
    def rounds(self) -> int:
        return self.max_expand_rounds or self.k_slots


def _slot_constants(cfg: WGLConfig):
    k, w = cfg.k_slots, cfg.words
    word = np.arange(k) // 32
    bit = np.arange(k) % 32
    slot_bitmask = np.zeros((k, w), dtype=np.uint32)
    slot_bitmask[np.arange(k), word] = np.uint32(1) << bit.astype(np.uint32)
    return (jnp.asarray(word, jnp.int32), jnp.asarray(bit, jnp.uint32),
            jnp.asarray(slot_bitmask))


def _dedup(states, masks, valid, f_cap):
    """Sort rows by (valid desc, state, mask words), keep unique valid rows,
    compact into a fresh fixed-capacity frontier."""
    w = masks.shape[-1]
    invalid = (~valid).astype(jnp.int32)
    # lexsort: last key is primary. Primary: invalid flag (valid rows first);
    # then state; then mask words for a total order on content.
    keys = tuple(masks[:, i].astype(jnp.uint32) for i in range(w - 1, -1, -1))
    order = jnp.lexsort(keys + (states, invalid))
    s_states = states[order]
    s_masks = masks[order]
    s_valid = valid[order]
    eq_prev = jnp.concatenate([
        jnp.array([False]),
        (s_states[1:] == s_states[:-1])
        & jnp.all(s_masks[1:] == s_masks[:-1], axis=-1),
    ])
    unique = s_valid & ~eq_prev
    n_unique = jnp.sum(unique.astype(jnp.int32))
    dest = jnp.where(unique, jnp.cumsum(unique.astype(jnp.int32)) - 1, f_cap)
    new_states = jnp.zeros((f_cap,), jnp.int32).at[dest].set(
        s_states, mode="drop")
    new_masks = jnp.zeros((f_cap, masks.shape[-1]), jnp.uint32).at[dest].set(
        s_masks, mode="drop")
    new_valid = jnp.arange(f_cap) < jnp.minimum(n_unique, f_cap)
    return new_states, new_masks, new_valid, n_unique


class _Carry2(NamedTuple):
    states: jax.Array       # i32[F]
    masks: jax.Array        # u32[F, W]
    valid: jax.Array        # bool[F]
    dead: jax.Array         # bool
    overflow: jax.Array     # bool
    dead_step: jax.Array    # i32 (return-step index, -1 if alive)
    max_frontier: jax.Array  # i32


PACKED_INVALID = np.uint32(0xFFFFFFFF)


class SearchBudgetExceeded(MemoryError):
    """Wall-clock budget expiry during the resumable search.

    Subclasses MemoryError so existing exact-or-unknown fallbacks keep
    working, while callers that care can tell a timeout (retryable with a
    bigger budget) from genuine capacity infeasibility (retryable only on
    bigger hardware)."""


def packable(model: Model, cfg: WGLConfig) -> bool:
    """Can (state, mask) live in one uint32 sort key? Needs a bounded model
    state space (cfg.state_bits, derived from the history's values) and a
    single mask word with headroom."""
    return (cfg.state_bits > 0 and model.packable_states
            and cfg.state_bits + cfg.k_slots <= 31)


def _dedup_packed(keys, f_cap):
    """Single-key dedup: sort uint32 config keys (invalid = all-ones sorts
    last), blank neighbor duplicates, and compact with a SECOND sort —
    duplicates become PACKED_INVALID which sorts last, so the unique keys
    land in the first n_unique slots, still ascending. Two cheap sorts beat
    one scatter: vmapped scatter lowers very badly on TPU."""
    s = jnp.sort(keys)
    eq_prev = jnp.concatenate([jnp.array([False]), s[1:] == s[:-1]])
    unique = (s != PACKED_INVALID) & ~eq_prev
    n_unique = jnp.sum(unique.astype(jnp.int32))
    out = jnp.sort(jnp.where(unique, s, PACKED_INVALID))[:f_cap]
    return out, n_unique


def make_step_fn2(model: Model, cfg: WGLConfig, canon: bool = False):
    """Sort-kernel scan body. With ``canon=True`` the scan inputs gain
    the per-step compare-exchange network (ops/canon.py) and every
    expansion round canonicalizes frontier + candidate masks BEFORE the
    sort-dedup, so symmetric configs (equal-effect forever-pending ops
    fired in different orders) merge as duplicates — the frontier stays
    small enough that combinatorial histories stop escalating f_cap.
    Verdict-exact (the canonical config is reachable by a real
    linearization; soundness argument in ops/canon.py); the default
    build is byte-identical to the pre-dedup kernel."""
    word_of, bit_of, slot_bitmask = _slot_constants(cfg)
    f_cap, k = cfg.f_cap, cfg.k_slots
    use_packed = packable(model, cfg)
    sbits = cfg.state_bits
    soff = model.state_offset
    if canon:
        from .canon import canon_keys_packed, canon_masks_words

    def bits_set(masks):
        return (masks[:, word_of] >> bit_of) & jnp.uint32(1)

    def pack(states, mask_word, valid):
        key = ((states + soff).astype(jnp.uint32)
               | (mask_word << jnp.uint32(sbits)))
        return jnp.where(valid, key, PACKED_INVALID)

    def unpack(keys):
        valid = keys != PACKED_INVALID
        states = (keys & jnp.uint32((1 << sbits) - 1)).astype(jnp.int32) - soff
        masks = (keys >> jnp.uint32(sbits))[:, None]
        return jnp.where(valid, states, 0), \
            jnp.where(valid[:, None], masks, jnp.uint32(0)), valid

    def step(carry: _Carry2, xs):
        if canon:
            slot_tab, slot_active, target, idx, pairs = xs
        else:
            slot_tab, slot_active, target, idx = xs
        is_pad = target < 0
        tgt = jnp.maximum(target, 0)
        t_word, t_bit = word_of[tgt], bit_of[tgt]
        f = slot_tab[:, 0]
        a1 = slot_tab[:, 1]
        a2 = slot_tab[:, 2]
        rv = slot_tab[:, 3]

        def candidates(states, masks, valid):
            legal, nxt = jax.vmap(
                lambda s: model.step(s, f, a1, a2, rv))(states)
            # JIT linearization: don't expand configs that already fired the
            # returning op (ops/wgl.py expand_once for the argument).
            not_done = ((masks[:, t_word] >> t_bit) & jnp.uint32(1)) == 0
            cand_valid = (valid[:, None] & not_done[:, None] & ~is_pad
                          & slot_active[None, :]
                          & (bits_set(masks) == 0) & legal)
            return nxt, cand_valid

        def expand_once(states, masks, valid):
            nxt, cand_valid = candidates(states, masks, valid)
            if use_packed:
                cand_words = masks[:, None, 0] | slot_bitmask[None, :, 0]
                all_keys = jnp.concatenate([
                    pack(states, masks[:, 0], valid),
                    pack(nxt.reshape(-1), cand_words.reshape(-1),
                         cand_valid.reshape(-1))])
                if canon:
                    all_keys = canon_keys_packed(all_keys, pairs, sbits,
                                                 PACKED_INVALID)
                keys, n_unique = _dedup_packed(all_keys, f_cap)
                s2, m2, v2 = unpack(keys)
                return s2, m2, v2, n_unique
            cand_masks = masks[:, None, :] | slot_bitmask[None, :, :]
            all_states = jnp.concatenate([states, nxt.reshape(-1)])
            all_masks = jnp.concatenate(
                [masks, cand_masks.reshape(-1, cfg.words)])
            all_valid = jnp.concatenate([valid, cand_valid.reshape(-1)])
            if canon:
                all_masks = canon_masks_words(all_masks, pairs,
                                              slot_bitmask)
            return _dedup(all_states, all_masks, all_valid, f_cap)

        def cond(st):
            _s, _m, _v, _n, changed, _o, it = st
            return changed & (it < cfg.rounds)

        def body(st):
            s, m, v, n_prev, _c, o, it = st
            s2, m2, v2, n_unique = expand_once(s, m, v)
            o = o | (n_unique > f_cap)
            n_now = jnp.minimum(n_unique, f_cap)
            return (s2, m2, v2, n_now, n_now > n_prev, o, it + 1)

        n0 = jnp.sum(carry.valid.astype(jnp.int32))
        init = (carry.states, carry.masks, carry.valid, n0, ~is_pad,
                carry.overflow, jnp.int32(0))
        s, m, v, n, _c, overflow = jax.lax.while_loop(cond, body, init)[:6]

        bit_word = jnp.take(m, t_word, axis=-1)
        has_bit = ((bit_word >> t_bit) & jnp.uint32(1)) == 1
        keep = v & jnp.where(is_pad, True, has_bit)
        cleared = jnp.where(is_pad, m, m & ~slot_bitmask[tgt][None, :])
        died = ~is_pad & ~carry.dead & ~jnp.any(keep)
        dead = carry.dead | died
        return _Carry2(
            states=s, masks=cleared, valid=keep & ~jnp.bool_(dead),
            dead=dead, overflow=overflow,
            dead_step=jnp.where(died & (carry.dead_step < 0), idx,
                                carry.dead_step),
            max_frontier=jnp.maximum(carry.max_frontier, n)), None

    return step


def _init_carry2(model: Model, cfg: WGLConfig) -> _Carry2:
    f_cap, w = cfg.f_cap, cfg.words
    return _Carry2(
        states=jnp.zeros((f_cap,), jnp.int32).at[0].set(model.init_state()),
        masks=jnp.zeros((f_cap, w), jnp.uint32),
        valid=jnp.zeros((f_cap,), bool).at[0].set(True),
        dead=jnp.bool_(False),
        overflow=jnp.bool_(False),
        dead_step=jnp.int32(-1),
        max_frontier=jnp.int32(1),
    )


def _seed_carry2(cfg: WGLConfig, states_np: np.ndarray) -> _Carry2:
    """A carry seeded from a QUIESCENT frontier: a plain state set. At
    a history point where every invoked op has returned, each config's
    pending mask is zero, so a cross-segment carry is fully described
    by its surviving states — the out-of-core segment chaining
    (stream/longhaul.py) threads exactly this between segments."""
    f_cap, w = cfg.f_cap, cfg.words
    n = int(states_np.size)
    assert 0 < n <= f_cap, (n, f_cap)
    st = np.zeros((f_cap,), np.int32)
    st[:n] = states_np
    vd = np.zeros((f_cap,), bool)
    vd[:n] = True
    return _Carry2(
        states=jnp.asarray(st),
        masks=jnp.zeros((f_cap, w), jnp.uint32),
        valid=jnp.asarray(vd),
        dead=jnp.bool_(False),
        overflow=jnp.bool_(False),
        dead_step=jnp.int32(-1),
        max_frontier=jnp.int32(n),
    )


def _check_one_fn(model: Model, cfg: WGLConfig):
    step = make_step_fn2(model, cfg)

    def check(slot_tabs, slot_active, targets):
        carry = _init_carry2(model, cfg)
        idxs = jnp.arange(targets.shape[0], dtype=jnp.int32)
        final, _ = jax.lax.scan(
            step, carry, (slot_tabs, slot_active, targets, idxs))
        return {
            "survived": ~final.dead,
            "overflow": final.overflow,
            "dead_step": final.dead_step,
            "max_frontier": final.max_frontier,
        }

    return check


def make_checker2(model: Model, cfg: WGLConfig = WGLConfig()):
    """jitted check(slot_tabs[R,K,4], slot_active[R,K], targets[R])."""
    return jax.jit(_check_one_fn(model, cfg))


def make_batch_checker2(model: Model, cfg: WGLConfig = WGLConfig()):
    """jitted check over a batch: slot_tabs[B,R,K,4], ... -> [B] results."""
    return jax.jit(jax.vmap(_check_one_fn(model, cfg)))


_CACHE: dict[tuple, Any] = {}


def cached_checker2(model: Model, cfg: WGLConfig):
    key = ("single2", model.cache_key(), cfg)
    if key not in _CACHE:
        # instrument_kernel (obs/): compile/execute attribution, one
        # first-call flag per compiled geometry (this cache's key).
        _CACHE[key] = instrument_kernel("wgl2-single",
                                        make_checker2(model, cfg))
    return _CACHE[key]


def cached_batch_checker2(model: Model, cfg: WGLConfig):
    key = ("batch2", model.cache_key(), cfg)
    if key not in _CACHE:
        _CACHE[key] = instrument_kernel("wgl2-batch",
                                        make_batch_checker2(model, cfg))
    return _CACHE[key]


def steps_arrays(rs: ReturnSteps):
    return (jnp.asarray(rs.slot_tabs), jnp.asarray(rs.slot_active),
            jnp.asarray(rs.targets))


def make_config(model: Model, k_slots: int, f_cap: int,
                max_value: int) -> WGLConfig:
    """WGLConfig with packing bits derived from the history's real values.

    Bits are rounded up to a multiple of 4 (when headroom allows) so nearby
    value ranges share one jit cache entry; when the key cannot be packed at
    all (bits + k_slots > 31) the bits are canonicalized to 0 — they would
    be unused, and distinct values must not force spurious recompiles."""
    bits = model.pack_bits(max_value)
    if bits:
        rounded = (bits + 3) // 4 * 4
        if rounded + k_slots <= 31:
            bits = rounded
        elif bits + k_slots > 31:
            bits = 0  # unpackable: state_bits is dead config
    return WGLConfig(k_slots, f_cap, state_bits=bits)


def config_for(rs: ReturnSteps, model: Model, f_cap: int) -> WGLConfig:
    return make_config(model, rs.k_slots, f_cap, rs.max_value)


def check_steps(rs: ReturnSteps, model: Model | None = None,
                f_cap: int = 256) -> dict[str, Any]:
    """Single-history entry point over the return-major encoding."""
    from .wgl import verdict

    if model is None:
        from ..models import CASRegister
        model = CASRegister()
    check = cached_checker2(model, config_for(rs, model, f_cap))
    out = {k: np.asarray(v) for k, v in check(*steps_arrays(rs)).items()}
    out["valid"] = verdict(out)
    return out


# --- resumable / checkpointed search (SURVEY.md §5.4, §5.7) ---------------
#
# lax.scan cannot early-exit, so an overflow mid-history used to force a
# full restart (and ultimately a Python-oracle fallback — the exact DNF the
# framework exists to avoid, VERDICT round-1 item 4). Instead: scan the
# return steps in CHUNKS, checkpointing the frontier carry on the host at
# every chunk boundary. When a chunk overflows, migrate the pre-chunk
# checkpoint into a larger frontier capacity and re-run JUST that chunk.
# Verdicts are exact: a chunk's output is only accepted when it completed
# without overflow (or died — death is sound regardless, because dropping
# configs can only make death MORE likely... dropping cannot create
# death-free runs; a died+overflowed chunk is re-run too).

def _chunk_fn(model: Model, cfg: WGLConfig, canon: bool = False):
    step = make_step_fn2(model, cfg, canon=canon)

    if canon:
        def run(carry, slot_tabs, slot_active, targets, idxs, pairs):
            final, _ = jax.lax.scan(
                step, carry, (slot_tabs, slot_active, targets, idxs,
                              pairs))
            return final
    else:
        def run(carry, slot_tabs, slot_active, targets, idxs):
            final, _ = jax.lax.scan(
                step, carry, (slot_tabs, slot_active, targets, idxs))
            return final

    return jax.jit(run)


def cached_chunk2(model: Model, cfg: WGLConfig, canon: bool = False):
    key = ("chunk2", model.cache_key(), cfg, canon)
    if key not in _CACHE:
        _CACHE[key] = instrument_kernel(
            "wgl2-chunk", _chunk_fn(model, cfg, canon=canon))
    return _CACHE[key]


def _migrate_carry(carry: _Carry2, f_new: int) -> _Carry2:
    """Grow the frontier capacity of a host checkpoint (overflow retry)."""
    f_old = carry.states.shape[0]
    pad = f_new - f_old
    return _Carry2(
        states=jnp.pad(carry.states, (0, pad)),
        masks=jnp.pad(carry.masks, ((0, pad), (0, 0))),
        valid=jnp.pad(carry.valid, (0, pad)),
        dead=carry.dead, overflow=carry.overflow,
        dead_step=carry.dead_step, max_frontier=carry.max_frontier)


DEFAULT_CHUNK = 256   # return steps per scan chunk = checkpoint granularity


def check_steps_resumable(rs: ReturnSteps, model: Model | None = None,
                          f_cap: int = 256, chunk: int = DEFAULT_CHUNK,
                          f_cap_max: int = 1 << 20,
                          time_budget_s: float | None = None,
                          keep_death_checkpoint: bool = False,
                          init_frontier: np.ndarray | None = None,
                          return_frontier: bool = False,
                          spill_tag: str | None = None
                          ) -> dict[str, Any]:
    """Exact verdict via chunked scan + checkpointed capacity escalation.

    Never falls back to the Python oracle: capacity grows 4x per overflow,
    resuming from the last good chunk boundary, until the frontier fits or
    f_cap_max is exceeded (at which point the search genuinely does not fit
    device memory and raises MemoryError). `time_budget_s` bounds WALL
    time — combinatorial frontiers (dozens of forever-pending ops
    interleaving factorially, e.g. a mutex history full of indeterminate
    acquires AND releases) otherwise grind through ever-bigger sorts for
    hours; on expiry SearchBudgetExceeded (a MemoryError subclass) is
    raised so callers take the same exact-or-unknown fallback while still
    being able to tell timeout from capacity infeasibility, mirroring how
    knossos DNFs on these histories.

    `keep_death_checkpoint=True` (the witness path, VERDICT r3 item 6)
    additionally returns, on death, the EXACT frontier at the boundary of
    the chunk the search died in — `death_checkpoint` = (states, masks,
    valid, checkpoint_step) as host arrays — so wide geometries the dense
    recovery cannot sweep can still seed a bounded lineage replay without
    re-running the search. Zero cost until death: the pre-chunk carry is
    just a retained device reference, fetched only when the search dies.
    Checkpoints are exact by construction: a chunk's output is only
    accepted when it ran without overflow.

    The chunk loop is DOUBLE-BUFFERED (sched/pipeline.py InflightWindow,
    depth limits().sched_pipeline_depth): chunk N+1 is dispatched — its
    carry chained device-side off chunk N's (still in-flight) output —
    before chunk N's overflow flag is fetched, so the per-chunk status
    round trip hides under the next chunk's execution. Speculation is
    discarded, never trusted: when a resolved chunk overflowed, every
    later in-flight chunk (computed from the overflowed carry) is
    dropped and the loop re-runs from the pre-chunk checkpoint at the
    escalated capacity, exactly like the synchronous loop did. The carry
    is NOT donated here: the pre-chunk buffer must survive as the
    escalation/death checkpoint. The budget check happens at each
    resolution, so overshoot grows from one chunk to at most the
    pipeline depth.

    Out-of-core extensions (ISSUE 20): `init_frontier` seeds the carry
    from a QUIESCENT frontier — a plain i32 state set; sound only at a
    history point where every invoked op has returned (masks all
    zero), which is exactly what the out-of-core segment chaining
    (stream/longhaul.py) guarantees at segment boundaries.
    `return_frontier=True` returns the final carry as host arrays
    under `"frontier"`. `spill_tag` (with an active store/spill.py
    SpillDir and the `host_spill_mode` policy engaged) writes a
    canon-quotient-compressed frontier checkpoint at every
    resolved-clean chunk boundary — while later chunks are still in
    flight, so the spill write overlaps device execute — and resumes
    from a matching checkpoint on re-entry (a torn or mismatched
    checkpoint degrades to recompute from the start, never a wrong
    verdict)."""
    import time as _time

    from ..sched.pipeline import InflightWindow

    if model is None:
        from ..models import CASRegister
        model = CASRegister()
    t0 = _time.monotonic()
    r = rs.n_steps
    padded = rs.padded_to(((r + chunk - 1) // chunk or 1) * chunk)
    tabs, act, tgt = steps_arrays(padded)
    # Frontier canonicalization (ops/canon.py): symmetric configs over
    # equal-effect forever-pending ops merge in the sort-dedup, which is
    # exactly what keeps the combinatorial histories this resumable
    # ladder exists for from escalating f_cap 4x per overflow. None for
    # histories with no symmetry (or dedup_mode gating it off): the
    # compiled kernel is then byte-identical to the pre-dedup build.
    from .canon import history_canon_pairs

    pairs_np = history_canon_pairs(padded)
    pairs_dev = None if pairs_np is None else jnp.asarray(pairs_np)
    if init_frontier is not None:
        seed = np.asarray(init_frontier, dtype=np.int32).reshape(-1)
        while f_cap < seed.size:
            f_cap *= 4
    cfg = config_for(rs, model, f_cap)
    carry = _init_carry2(model, cfg) if init_frontier is None \
        else _seed_carry2(cfg, seed)
    escalations = 0
    death_ckpt = None
    n_pad = int(padded.targets.shape[0])
    # Spill-tier routing (store/spill.py): engaged only with an active
    # SpillDir, a caller tag, and the host_spill_mode policy saying yes
    # for this history's host working set.
    from ..store import spill as _spill

    sdir = _spill.active_spill() if spill_tag is not None else None
    do_spill = False
    ck_name = None
    start_pos = 0
    if sdir is not None:
        est_mb = (padded.slot_tabs.nbytes + padded.slot_active.nbytes
                  + padded.targets.nbytes) / (1 << 20)
        do_spill = _spill.spill_active(est_mb)
    if do_spill:
        ck_name = f"{spill_tag}.ck"
        d = _spill.load_frontier(sdir, ck_name)
        mt = (d or {}).get("meta") or {}
        if d is not None and mt.get("n_steps") == n_pad \
                and mt.get("chunk") == chunk \
                and mt.get("k_slots") == int(rs.k_slots) \
                and 0 < int(mt.get("pos", 0)) and "f_cap" in mt:
            # Resume from the spilled chunk checkpoint: the carry is
            # exact by construction (only resolved-clean chunks are
            # spilled), so the continuation is bit-identical to a
            # from-scratch run reaching the same boundary.
            f_cap = int(mt["f_cap"])
            escalations = int(mt.get("escalations", 0))
            cfg = config_for(rs, model, f_cap)
            carry = _Carry2(
                states=jnp.asarray(d["states"]),
                masks=jnp.asarray(d["masks"]),
                valid=jnp.asarray(d["valid"]),
                dead=jnp.bool_(False), overflow=jnp.bool_(False),
                dead_step=jnp.int32(-1),
                max_frontier=jnp.int32(int(mt.get("max_frontier", 1))))
            start_pos = int(mt["pos"])

    def budget_check(c0: int) -> None:
        if (time_budget_s is not None
                and _time.monotonic() - t0 > time_budget_s):
            raise SearchBudgetExceeded(
                f"WGL search exceeded its {time_budget_s:.0f}s time "
                f"budget at return step {c0} (chunk boundary "
                f"{c0 // chunk} of {len(chunk_starts)}, chunk={chunk}; "
                f"f_cap={f_cap} of f_cap_max={f_cap_max}, "
                f"escalations={escalations}); the frontier is growing "
                f"combinatorially. Raise the budget (--check-budget-s / "
                f"the caller's time_budget_s; 0 = unbounded) to search "
                f"longer, or raise limits().sort_row_budget "
                f"(JEPSEN_TPU_LIMIT_SORT_ROW_BUDGET) on a roomier "
                f"backend so capacity escalations go further per chunk")

    # Chunk kernels resolve through the KernelPlan layer (family
    # wgl2-chunk; plan/dispatch.py) — the sort ladder's entry onto the
    # one plan spine. The plan is rebuilt per dispatch because `cfg`
    # rebinds on every capacity escalation (the resolve is an LRU hit
    # for every chunk at the same capacity); the canon flag rides the
    # plan's extra args.
    from ..plan import plan_resumable

    def dispatch(c0: int, pre: _Carry2) -> _Carry2:
        run = plan_resumable(model, cfg, canon=pairs_dev is not None)
        sl = slice(c0, c0 + chunk)
        idxs = jnp.arange(c0, c0 + chunk, dtype=jnp.int32)
        if pairs_dev is not None:
            return run.dispatch(
                pre, tabs[sl], act[sl], tgt[sl], idxs, pairs_dev[sl])
        return run.dispatch(
            pre, tabs[sl], act[sl], tgt[sl], idxs)

    chunk_starts = list(range(0, padded.targets.shape[0], chunk))
    window = InflightWindow(limits().sched_pipeline_depth)
    pos = start_pos
    while pos < len(chunk_starts) or window:
        while pos < len(chunk_starts) and not window.full():
            c0 = chunk_starts[pos]
            out = dispatch(c0, carry)
            window.push((c0, carry, out))
            carry = out
            pos += 1
        c0, pre, out = window.pop()
        budget_check(c0)
        # jtlint: disable=JTL103 -- THE InflightWindow resolution fetch:
        # chunk N's flag resolves while chunks N+1..N+depth are already
        # dispatched, so this round trip hides under real work (the
        # pipelining contract this loop exists for).
        if bool(out.overflow):
            # Every later in-flight chunk chained off this overflowed
            # carry: discard the speculation, escalate, resume from the
            # pre-chunk checkpoint, and refill the pipeline from here.
            window.clear()
            while True:
                f_cap *= 4
                escalations += 1
                if f_cap > f_cap_max:
                    raise MemoryError(
                        f"WGL frontier exceeds f_cap_max={f_cap_max} at "
                        f"return step {c0} (chunk boundary {c0 // chunk} "
                        f"of {len(chunk_starts)}, chunk={chunk}; "
                        f"escalations={escalations}). Raise "
                        f"limits().sort_row_budget "
                        f"(JEPSEN_TPU_LIMIT_SORT_ROW_BUDGET, currently "
                        f"{limits().sort_row_budget}) to permit a larger "
                        f"f_cap_max, or let the router take the dense "
                        f"sweep — chunked (ops/wgl3.py) or "
                        f"lattice-sharded (parallel/lattice.py)")
                cfg = config_for(rs, model, f_cap)
                pre = _migrate_carry(pre, f_cap)
                budget_check(c0)
                out = dispatch(c0, pre)
                # jtlint: disable=JTL103 -- escalation retry: the re-run
                # chunk's overflow flag MUST resolve before the capacity
                # decision; escalations are rare and already synchronous.
                if not bool(out.overflow):
                    break
            carry = out
            pos = c0 // chunk + 1
        # jtlint: disable=JTL103 -- same resolution fetch as the overflow
        # flag above: one bounded fetch per RESOLVED chunk (pipeline-depth
        # chunks stay in flight), and death must stop the dispatch loop.
        if bool(out.dead):
            # The first resolved dead chunk (earlier chunks resolved
            # clean). Later in-flight chunks are death-sticky no-ops —
            # drop them; `out` carries the exact final verdict fields.
            if keep_death_checkpoint:
                # The host checkpoint row checkers/witness.py replays
                # from (reconstruct_witness_from_sort_checkpoint).
                # jtflow: partials states,masks,valid,checkpoint_step
                death_ckpt = (np.asarray(pre.states),
                              np.asarray(pre.masks),
                              np.asarray(pre.valid), c0)
            window.clear()
            carry = out
            break
        if do_spill:
            # Spill this resolved-clean boundary's frontier — while
            # chunks c0+chunk.. are still in flight on the device, so
            # the disk write rides under real execute (the overlap
            # contract). Classes from the last step the canon pass ran
            # with; the codec verifies packed-low per row and falls
            # back to raw, so compression is an attempt, soundness is
            # unconditional.
            classes = None
            if pairs_np is not None:
                classes = _spill.classes_from_pairs(
                    pairs_np[min(c0 + chunk, n_pad) - 1])
            _spill.spill_frontier(
                sdir, ck_name, np.asarray(out.states),
                np.asarray(out.masks), np.asarray(out.valid),
                classes=classes,
                meta={"pos": c0 // chunk + 1, "f_cap": f_cap,
                      "escalations": escalations,
                      "max_frontier": int(out.max_frontier),
                      "n_steps": n_pad, "chunk": chunk,
                      "k_slots": int(rs.k_slots)})
    res = {
        "survived": not bool(carry.dead),
        "overflow": False,
        "n_steps": r,
        "dead_step": int(carry.dead_step),
        "max_frontier": int(carry.max_frontier),
        "f_cap": f_cap,
        "escalations": escalations,
        "valid": not bool(carry.dead),
    }
    if death_ckpt is not None:
        res["death_checkpoint"] = death_ckpt
    if return_frontier:
        res["frontier"] = (np.asarray(carry.states),
                           np.asarray(carry.masks),
                           np.asarray(carry.valid))
    return res


def checkpoint_configs(states, masks, valid) -> list[tuple[int, int]]:
    """Host view of a checkpoint frontier: (state, mask-int) per valid
    lane, mask words combined little-endian (word j covers slots
    32j..32j+31 — _slot_constants)."""
    states, masks, valid = (np.asarray(a) for a in (states, masks, valid))
    out = []
    for i in np.nonzero(valid)[0]:
        m = 0
        for j in range(masks.shape[1]):
            m |= int(masks[i, j]) << (32 * j)
        out.append((int(states[i]), m))
    return out


def check_encoded2(enc: EncodedHistory, model: Model | None = None,
                   f_cap: int = 256) -> dict[str, Any]:
    return check_steps(encode_return_steps(enc), model, f_cap)


def sort_k_slots(enc: EncodedHistory) -> int:
    """Slot-table width the sort kernel runs at for this history (real
    concurrency rounded up to a multiple of 4, floor 8). Single source:
    f_cap_max sizing in the routing ladder depends on this EXACT value."""
    return max(8, (enc.max_pending + 3) // 4 * 4)


def check_encoded_resumable(enc: EncodedHistory, model: Model | None = None,
                            f_cap: int = 256,
                            f_cap_max: int = 1 << 20,
                            time_budget_s: float | None = None,
                            keep_death_checkpoint: bool = False,
                            init_frontier: np.ndarray | None = None,
                            return_frontier: bool = False,
                            spill_tag: str | None = None
                            ) -> dict[str, Any]:
    """The general-geometry production path (huge values or wide pending
    sets where the dense lattice is infeasible): tighten the slot table to
    the history's real concurrency, then run the resumable chunked sort
    kernel. Shared by the Linearizable checker and the auto router.
    Raises MemoryError when the frontier outgrows f_cap_max (callers may
    then fall back to the dense-chunked lattice, which has no frontier
    capacity at all)."""
    from .encode import reslot_events

    if model is None:
        from ..models import CASRegister
        model = CASRegister()
    tight = sort_k_slots(enc)
    if tight < enc.k_slots:
        enc = reslot_events(enc, tight)
    # Clamp the STARTING capacity too: the escalation loop only checks
    # f_cap_max after an overflow, so an oversized initial f_cap would
    # run its first sort past the very limit f_cap_max protects.
    f_cap = max(4, min(f_cap, f_cap_max))
    out = check_steps_resumable(encode_return_steps(enc), model,
                                f_cap=f_cap, f_cap_max=f_cap_max,
                                time_budget_s=time_budget_s,
                                keep_death_checkpoint=keep_death_checkpoint,
                                init_frontier=init_frontier,
                                return_frontier=return_frontier,
                                spill_tag=spill_tag)
    out["op_count"] = enc.n_ops
    # Telemetry (obs/): the kernel paths record their own search metrics
    # at the launch/exit sites — consumers (checkers/linearizable.py)
    # must NOT record again, or wgl.configs_explored double-counts.
    record_check_result(out)
    return out
