"""On-disk run persistence — jepsen.store equivalent.

Layout mirrors the reference's store (evidenced by store/ symlinks in tree,
SURVEY.md §2.1 #7): store/<test-name>/<timestamp>/ holding the test config,
the full history, results, charts and logs, with `latest` and `current`
symlinks per test name and at the root. The reference serializes history with
fressian [dep]; this build uses JSONL for the host artifact plus .npz for the
encoded tensor form the TPU checker consumes (check is re-runnable from a
stored history without re-running the cluster — the corpus-replay workflow,
BASELINE.json configs[4]).
"""

from .store import Store, RunDir  # noqa: F401
