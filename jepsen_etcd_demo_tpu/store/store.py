"""Store implementation: run dirs, symlinks, (de)serialization."""

from __future__ import annotations

import json
import os
from datetime import datetime, timezone
from pathlib import Path
from typing import Any, Optional

import numpy as np

from ..ops.op import Op, history_to_jsonl, history_from_jsonl

TEST_FILE = "test.json"
HISTORY_FILE = "history.jsonl"
HISTORY_TENSOR_FILE = "history.npz"
RESULTS_FILE = "results.json"
# The campaign's regression-corpus bank (campaign/bank.py) lives under
# <store>/corpus/<signature>/<hash>.json — NOT run dirs; runs() below
# must skip it or the web index (and `jepsen-tpu corpus`) would try to
# render every banked witness as a broken run.
CORPUS_DIRNAME = "corpus"


def _jsonable_test(test: dict) -> dict:
    """The test map holds live objects (client, checker, generator); persist
    the data fields and the repr of the rest, like jepsen prunes its test map
    before serialization. Credentials never reach disk: the ssh password
    (control/runner.py routes it via the SSHPASS env precisely to keep it
    out of observable surfaces) is redacted here — the store is a shareable
    results artifact."""
    out = {}
    for k, v in test.items():
        if isinstance(v, (str, int, float, bool, type(None), list, dict)):
            out[k] = v
        else:
            out[k] = repr(v)
    ssh = out.get("ssh")
    if isinstance(ssh, dict) and ssh.get("password"):
        out["ssh"] = {**ssh, "password": "<redacted>"}
    return out


class RunDir:
    def __init__(self, path: Path):
        self.path = Path(path)

    # -- writing ----------------------------------------------------------
    def write_run(self, test: dict, history: list[Op], result: dict) -> None:
        self.write_test(test)
        self.write_history(history)
        self.write_results(result)

    def write_test(self, test: dict) -> None:
        (self.path / TEST_FILE).write_text(
            json.dumps(_jsonable_test(test), indent=2, default=str))

    def write_history(self, history: list[Op]) -> None:
        (self.path / HISTORY_FILE).write_text(history_to_jsonl(history))

    def write_results(self, result: dict) -> None:
        (self.path / RESULTS_FILE).write_text(
            json.dumps(result, indent=2, default=str))

    def write_history_tensor(self, name: str, events: np.ndarray,
                             **meta) -> None:
        """Persist an encoded event tensor (corpus-replay input)."""
        np.savez_compressed(self.path / f"{name}.npz", events=events,
                            **{k: np.asarray(v) for k, v in meta.items()})

    # -- reading ----------------------------------------------------------
    def read_history(self) -> list[Op]:
        return history_from_jsonl((self.path / HISTORY_FILE).read_text())

    def read_results(self) -> dict:
        return json.loads((self.path / RESULTS_FILE).read_text())

    def read_test(self) -> dict:
        return json.loads((self.path / TEST_FILE).read_text())


class Store:
    def __init__(self, root: str | Path = "store"):
        self.root = Path(root)

    def new_run(self, test_name: str) -> RunDir:
        ts = datetime.now(timezone.utc).strftime("%Y%m%dT%H%M%S.%f")[:-3] + "Z"
        path = self.root / test_name / ts
        path.mkdir(parents=True, exist_ok=True)
        self._symlink(self.root / test_name / "latest", ts)
        self._symlink(self.root / "latest", Path(test_name) / ts)
        self._symlink(self.root / "current", Path(test_name) / ts)
        return RunDir(path)

    @staticmethod
    def _symlink(link: Path, target) -> None:
        link.parent.mkdir(parents=True, exist_ok=True)
        if link.is_symlink() or link.exists():
            link.unlink()
        os.symlink(str(target), str(link))

    def latest(self, test_name: Optional[str] = None) -> Optional[RunDir]:
        link = (self.root / test_name / "latest" if test_name
                else self.root / "latest")
        if not link.exists():
            return None
        return RunDir(link.parent / os.readlink(str(link))
                      if not Path(os.readlink(str(link))).is_absolute()
                      else Path(os.readlink(str(link))))

    def runs(self) -> list[RunDir]:
        out = []
        if not self.root.exists():
            return out
        for test_dir in sorted(self.root.iterdir()):
            if not test_dir.is_dir() or test_dir.name in (
                    "latest", "current", CORPUS_DIRNAME):
                continue
            for run in sorted(test_dir.iterdir()):
                if run.is_dir() and not run.is_symlink():
                    out.append(RunDir(run))
        return out


def read_encoded_tensors(store_dir, model_name: str):
    """Load a run's per-key device-plane tensors (the write_encoded_tensor
    artifacts) back into EncodedHistory objects as (key, enc) pairs with
    STRING keys, in str-sorted key order (the same order the JSONL path's
    sorted(..., key=str) produces). Returns [] when none exist, any fails
    to load (e.g. a truncated .npz from an interrupted run — np.load
    raises zipfile.BadZipFile, hence the broad except), or any was encoded
    under a DIFFERENT model (its event fields follow that model's op
    language — the caller must re-encode from JSONL instead)."""
    from ..ops.encode import EncodedHistory

    out = []
    for path in sorted(Path(store_dir).glob("history*.npz")):
        try:
            with np.load(path) as z:
                if str(z["model"]) != model_name:
                    return []
                name = path.stem
                key = name[len("history-"):] if "-" in name else None
                out.append((key, EncodedHistory.from_arrays(z)))
        except Exception:
            return []
    return out


def write_encoded_tensor(store_dir, key, enc, model_name: str) -> None:
    """Persist the checker's device input alongside the run (the
    history-tensor artifact of SURVEY.md §5.4: the store is JSONL for the
    host plane plus the encoded int32 event tensor for the device plane).
    `key` is the independent-wrapper key (None for whole-run histories).

    WRITE-ONCE: an existing artifact is the record of what the run-time
    check actually consumed — a later `analyze` under --model/--workload
    overrides (or a second checker pass over the same key) must not
    clobber it."""
    name = "history" if key is None else f"history-{key}"
    if (Path(store_dir) / f"{name}.npz").exists():
        return
    arrays = enc.to_arrays()
    RunDir(store_dir).write_history_tensor(
        name, arrays.pop("events"), model=model_name, **arrays)
