"""store/spill.py — the out-of-core spill tier (ISSUE 20 tentpole).

The resumable-carry discipline (wgl2/wgl3 chunked kernels, stream
watermarks, the incremental ElleGraph) bounds DEVICE memory per chunk;
this module extends the same discipline to the HOST. Three pieces:

  * :class:`SpillDir` — an atomic, digest-framed blob store next to the
    content-addressed encode cache. Every read/write is timed into the
    ledger's first-class ``spill_read``/``spill_write`` buckets
    (obs/ledger.py) and counted on the ``spill.*`` registry families,
    so ``scaling_report`` shows where the disk-seconds go.
  * :class:`FrontierCodec` framing (:func:`encode_frontier` /
    :func:`decode_frontier`) — spilled wgl2/wgl3 frontier checkpoints,
    compressed with the PR 10 canon quotient: a CANONICAL frontier
    row's fired bits inside each equal-effect class are packed into the
    class's lowest slots (ops/canon.py), so those bits are fully
    determined by a per-class fired COUNT. The encoder verifies the
    packed-low invariant per row per class and stores counts + a
    residual table with the class bits cleared; rows that fail the
    check (non-canonical carries, invalid lanes) keep their raw words.
    Decoding is bit-identical by construction — the residual is exact
    and the class bits are a deterministic function of the counts. A
    sha256 digest frames every blob: a torn/truncated checkpoint reads
    as ABSENT (recompute), never as data.
  * :class:`SpillWindow` — the bounded in-RAM tier: blobs write through
    to disk immediately (crash-durable) and stay resident until the
    window exceeds its byte budget (sized from ``host_rss_budget_mb``),
    then the oldest RAM copies drop (``spill.evictions``); a get() that
    misses RAM re-reads the disk tier.

Routing policy (:func:`spill_active`): ``host_spill_mode`` 0 = auto
(spill only when the caller's working-set estimate exceeds
``host_rss_budget_mb``), 1 = off (the seed's all-RAM behaviour),
2 = force (the bench/test lane). Verdicts are bit-identical in every
mode — the spill tier moves bytes, never meaning.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import resource
import sys
import tempfile
import time
from contextlib import contextmanager
from pathlib import Path
from typing import Iterator, Optional

import numpy as np

from ..obs import get_ledger, get_metrics
from ..ops.limits import limits

SPILL_DIRNAME = ".spill"

_MAGIC = b"JTSPILL1"
_DIGEST_LEN = 32


def rss_mb() -> float:
    """This process's peak RSS so far, in MiB (``ru_maxrss`` is KiB on
    Linux, bytes on macOS). Callers wanting a ceiling on a LANE take
    the delta of two samples — the absolute peak includes every
    allocation since process start."""
    peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    div = 1 << 20 if sys.platform == "darwin" else 1 << 10
    return peak / div


def spill_active(estimate_mb: Optional[float] = None) -> bool:
    """Whether the out-of-core tier should engage: forced on (mode 2),
    forced off (mode 1), or — in auto — only when the caller's
    working-set estimate exceeds the host RSS budget."""
    lim = limits()
    if lim.host_spill_mode == 1:
        return False
    if lim.host_spill_mode == 2:
        return True
    return estimate_mb is not None \
        and estimate_mb > lim.host_rss_budget_mb


# -- canon-quotient frontier codec ------------------------------------------

def classes_from_pairs(pairs: Optional[np.ndarray]) -> list[list[int]]:
    """Equal-effect bit classes at one history step, from that step's
    canon compare-exchange pair row (ops/canon.py canon_pairs[t]):
    connected components (size >= 2) of the pair graph. The selection
    network canon_pairs emits connects every lo<hi pair inside a class,
    so components ARE the classes."""
    if pairs is None:
        return []
    arr = np.asarray(pairs).reshape(-1, 2)
    parent: dict[int, int] = {}

    def find(x: int) -> int:
        while parent.setdefault(x, x) != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for lo, hi in arr:
        lo, hi = int(lo), int(hi)
        if lo < 0 or hi < 0:
            continue
        ra, rb = find(lo), find(hi)
        if ra != rb:
            parent[max(ra, rb)] = min(ra, rb)
    groups: dict[int, list[int]] = {}
    for x in parent:
        groups.setdefault(find(x), []).append(x)
    return sorted(sorted(g) for g in groups.values() if len(g) > 1)


def _class_bits(masks: np.ndarray, cls: list[int]) -> np.ndarray:
    """bool[n, len(cls)]: each row's fired bit per class member."""
    cols = [(masks[:, b // 32] >> np.uint32(b % 32)) & np.uint32(1)
            for b in cls]
    return np.stack(cols, axis=1).astype(bool)


def _clear_class_bits(masks: np.ndarray, cls: list[int],
                      rows: np.ndarray) -> None:
    for b in cls:
        masks[rows, b // 32] &= np.uint32(~(np.uint32(1) << (b % 32))
                                          & 0xFFFFFFFF)


def _set_packed_bits(masks: np.ndarray, cls: list[int],
                     rows: np.ndarray, counts: np.ndarray) -> None:
    for j, b in enumerate(cls):
        hit = rows[counts > j]
        masks[hit, b // 32] |= np.uint32(1) << np.uint32(b % 32)


def encode_frontier(states: np.ndarray, masks: np.ndarray,
                    valid: np.ndarray, *,
                    classes: Optional[list[list[int]]] = None,
                    meta: Optional[dict] = None,
                    mode: Optional[int] = None) -> bytes:
    """Serialize one frontier checkpoint (states i32[F], masks
    u32[F, W], valid bool[F] — the wgl2 carry layout) into a
    digest-framed blob. `classes` are the equal-effect bit classes at
    the checkpoint step (:func:`classes_from_pairs`); when the valid
    rows satisfy the canonical packed-low invariant, class bits are
    stored as per-class counts (the canon-quotient compression),
    otherwise the raw words are kept. `mode` defaults to the
    ``spill_compress_mode`` knob: 1 pins raw, 2 refuses the raw
    fallback (raises on a non-canonical frontier — the codec test
    lane). Round-trips bit-identically in every mode."""
    if mode is None:
        mode = limits().spill_compress_mode
    states = np.ascontiguousarray(states, dtype=np.int32)
    masks = np.ascontiguousarray(masks, dtype=np.uint32)
    valid = np.ascontiguousarray(valid, dtype=bool)
    raw_bytes = states.nbytes + masks.nbytes + valid.nbytes
    rows = np.flatnonzero(valid)
    use_canon = bool(classes) and mode != 1 and rows.size > 0
    counts: Optional[np.ndarray] = None
    residual = masks
    if use_canon:
        vm = masks[rows]
        ok = all(len(c) < 256 for c in classes)
        cols = []
        for cls in classes:
            if not ok:
                break
            bits = _class_bits(vm, cls)
            cnt = bits.sum(axis=1)
            # Packed-low invariant: the fired bits must be exactly the
            # class's lowest `cnt` members (canonical rows only).
            expect = np.arange(len(cls))[None, :] < cnt[:, None]
            if not np.array_equal(bits, expect):
                ok = False
                break
            cols.append(cnt.astype(np.uint8))
        if ok and cols:
            counts = np.stack(cols, axis=1)
            residual = masks.copy()
            for cls in classes:
                _clear_class_bits(residual, cls, rows)
        elif mode == 2:
            raise ValueError(
                "spill_compress_mode=2 (force-canonical) but the "
                "frontier is not canonically packed — run with "
                "dedup_mode canonicalization or compress_mode 0/1")
        else:
            use_canon = False
    payload = io.BytesIO()
    arrays = {"states": states, "residual": residual,
              "valid": np.packbits(valid)}
    if counts is not None:
        arrays["counts"] = counts
    np.savez_compressed(payload, **arrays)
    payload = payload.getvalue()
    header = {
        "v": 1,
        "mode": "canon" if use_canon else "raw",
        "f": int(states.shape[0]),
        "w": int(masks.shape[1]) if masks.ndim == 2 else 0,
        "classes": classes if use_canon else None,
        "meta": meta or {},
        "raw_bytes": int(raw_bytes),
    }
    hdr = json.dumps(header, separators=(",", ":")).encode()
    body = _MAGIC + len(hdr).to_bytes(4, "big") + hdr + payload
    return body + hashlib.sha256(body).digest()


def decode_frontier(blob: Optional[bytes]) -> Optional[dict]:
    """Inverse of :func:`encode_frontier`: ``{"states", "masks",
    "valid", "meta", "mode", "raw_bytes"}`` — or None for a torn,
    truncated, or digest-failing blob (the caller recomputes; a bad
    checkpoint can degrade throughput, never a verdict)."""
    if blob is None or len(blob) < len(_MAGIC) + 4 + _DIGEST_LEN:
        return None
    body, digest = blob[:-_DIGEST_LEN], blob[-_DIGEST_LEN:]
    if not body.startswith(_MAGIC) \
            or hashlib.sha256(body).digest() != digest:
        return None
    try:
        hlen = int.from_bytes(body[len(_MAGIC):len(_MAGIC) + 4], "big")
        hdr = json.loads(body[len(_MAGIC) + 4:len(_MAGIC) + 4 + hlen])
        with np.load(io.BytesIO(body[len(_MAGIC) + 4 + hlen:])) as z:
            states = z["states"]
            masks = z["residual"].copy()
            valid = np.unpackbits(
                z["valid"], count=int(hdr["f"])).astype(bool)
            counts = z["counts"] if "counts" in z.files else None
    except Exception:
        return None
    if hdr["mode"] == "canon" and counts is not None:
        rows = np.flatnonzero(valid)
        for j, cls in enumerate(hdr["classes"]):
            _set_packed_bits(masks, [int(b) for b in cls], rows,
                             counts[:, j].astype(np.int64))
    return {"states": states, "masks": masks, "valid": valid,
            "meta": hdr.get("meta") or {}, "mode": hdr["mode"],
            "raw_bytes": int(hdr.get("raw_bytes") or 0)}


def spill_frontier(sdir: "SpillDir", name: str, states, masks, valid, *,
                   classes: Optional[list[list[int]]] = None,
                   meta: Optional[dict] = None) -> Optional[Path]:
    """Encode + write one frontier checkpoint, updating the
    ``spill.compress_ratio`` gauge (raw packed bytes over stored
    bytes — >1 means the canon-quotient codec beat raw)."""
    blob = encode_frontier(np.asarray(states), np.asarray(masks),
                           np.asarray(valid), classes=classes, meta=meta)
    raw = (np.asarray(states).nbytes + np.asarray(masks).nbytes
           + np.asarray(valid).nbytes)
    if len(blob) > 0:
        get_metrics().gauge("spill.compress_ratio").set(
            round(raw / len(blob), 4))
    return sdir.write(name, blob)


def load_frontier(sdir: "SpillDir", name: str) -> Optional[dict]:
    """Read + decode one frontier checkpoint; None when absent, torn,
    or digest-failing (the caller recomputes)."""
    return decode_frontier(sdir.read(name))


# -- the disk tier ----------------------------------------------------------

class SpillDir:
    """Digest-framed blob store for the out-of-core tier. Writes are
    atomic (mkstemp + os.replace — a crash mid-spill leaves either the
    previous entry or a tmp file, never a torn named entry; the codec
    digest catches everything else). Every transfer is timed into the
    ledger's spill buckets and counted on the spill.* families."""

    def __init__(self, root: str | os.PathLike):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    def path(self, name: str) -> Path:
        return self.root / name

    def write(self, name: str, blob: bytes) -> Optional[Path]:
        t0 = time.monotonic_ns()
        path = self.path(name)
        try:
            fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(blob)
                os.replace(tmp, path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        except OSError:
            return None   # spill is an optimization tier, not a fault
        t1 = time.monotonic_ns()
        m = get_metrics()
        m.counter("spill.writes").add(1)
        m.counter("spill.bytes_written").add(len(blob))
        get_ledger().record_spill("spill_write", len(blob), t0, t1)
        return path

    def append(self, name: str, blob: bytes) -> bool:
        """Unframed append spool (streamed edge runs): NOT atomic and
        NOT digest-framed — spools are same-call scratch, never
        checkpoints, so a crash discards the whole spool rather than
        resuming from it. Same ledger/counter accounting as write()."""
        t0 = time.monotonic_ns()
        try:
            with open(self.path(name), "ab") as f:
                f.write(blob)
        except OSError:
            return False
        t1 = time.monotonic_ns()
        m = get_metrics()
        m.counter("spill.writes").add(1)
        m.counter("spill.bytes_written").add(len(blob))
        get_ledger().record_spill("spill_write", len(blob), t0, t1)
        return True

    def read(self, name: str) -> Optional[bytes]:
        t0 = time.monotonic_ns()
        try:
            blob = self.path(name).read_bytes()
        except OSError:
            return None
        t1 = time.monotonic_ns()
        m = get_metrics()
        m.counter("spill.reads").add(1)
        m.counter("spill.bytes_read").add(len(blob))
        get_ledger().record_spill("spill_read", len(blob), t0, t1)
        return blob

    def delete(self, name: str) -> None:
        try:
            self.path(name).unlink()
        except OSError:
            pass

    def names(self) -> list[str]:
        try:
            return sorted(p.name for p in self.root.iterdir()
                          if p.is_file() and not p.name.endswith(".tmp"))
        except OSError:
            return []


class SpillWindow:
    """The bounded in-RAM tier over a :class:`SpillDir`: put() writes
    through to disk immediately (crash-durable) and keeps the blob
    resident; past the byte budget the OLDEST resident copies drop
    (``spill.evictions``) — eviction is free, the disk already has the
    bytes. get() serves RAM hits without I/O and re-reads the disk
    tier on a miss."""

    def __init__(self, sdir: SpillDir,
                 budget_mb: Optional[float] = None):
        self.sdir = sdir
        if budget_mb is None:
            budget_mb = limits().host_rss_budget_mb / 4
        self.budget_bytes = int(budget_mb * (1 << 20))
        self._ram: dict[str, bytes] = {}
        self._ram_bytes = 0

    def put(self, name: str, blob: bytes) -> None:
        self.sdir.write(name, blob)
        old = self._ram.pop(name, None)
        if old is not None:
            self._ram_bytes -= len(old)
        self._ram[name] = blob
        self._ram_bytes += len(blob)
        self._evict()

    def _evict(self) -> None:
        m = None
        while self._ram_bytes > self.budget_bytes and len(self._ram) > 1:
            name = next(iter(self._ram))
            self._ram_bytes -= len(self._ram.pop(name))
            if m is None:
                m = get_metrics()
            m.counter("spill.evictions").add(1)

    def get(self, name: str) -> Optional[bytes]:
        blob = self._ram.get(name)
        if blob is not None:
            return blob
        return self.sdir.read(name)

    @property
    def resident_bytes(self) -> int:
        return self._ram_bytes


# -- session routing --------------------------------------------------------
# Like the encode cache, the spill tier is OFF unless activated (the
# bench long-haul lane and the CLI activate it); library callers pay
# one module-global read. wgl2/wgl3 consult `active_spill()` +
# `spill_active()` before spilling their chunk checkpoints.

_active_dir: Optional[SpillDir] = None


def activate_spill(root: str | os.PathLike | None) -> Optional[SpillDir]:
    """Point the spill tier at `root` (created lazily); None
    deactivates. Returns the PREVIOUS SpillDir for save/restore."""
    global _active_dir
    prev = _active_dir
    _active_dir = SpillDir(root) if root is not None else None
    return prev


def active_spill() -> Optional[SpillDir]:
    return _active_dir


@contextmanager
def spilling(root: str | os.PathLike | None) -> Iterator[Optional[SpillDir]]:
    global _active_dir
    prev = activate_spill(root)
    try:
        yield _active_dir
    finally:
        _active_dir = prev
