"""Content-addressed encoded-tensor cache (ISSUE 2 satellite).

`analyze` / `corpus` replays re-encode the same stored histories on every
invocation; for a big store the host encode dominates the warm path the
compile cache just made cheap. This cache persists the encoder's OUTPUT —
the padded int32 event tensor — keyed by a sha256 over the encoder's
INPUT (the translated op sequence's (type, f, value, process) fields,
the model name, and the requested slot width), so an unchanged history
loads its tensor instead of re-pairing/re-encoding.

The cache is OFF unless activated (the CLI activates it for `analyze` /
`corpus`, with `--no-encode-cache` as the escape hatch); library callers
pay one module-global read. Entries are plain npz files
(EncodedHistory.to_arrays) written atomically, safe under concurrent
replays. A hash is a pure function of the encoder's observable input, so
a cache hit is bit-identical to a fresh encode; corrupt/unreadable
entries fall through to a re-encode, never an error.
"""

from __future__ import annotations

import hashlib
import os
import tempfile
from contextlib import contextmanager
from pathlib import Path
from typing import Iterator, Optional, Sequence

import numpy as np

from ..obs import get_metrics
from ..ops.encode import ENCODING_VERSION, EncodedHistory
from ..ops.limits import limits

CACHE_DIRNAME = ".encode-cache"

_active_root: Optional[Path] = None
_refresh: bool = False

# Stores between size-capped GC sweeps (gc() stats the whole cache dir,
# so store() amortizes it instead of paying the scan per entry).
_GC_EVERY = 32
_stores_since_gc = 0


def activate(root: str | os.PathLike | None,
             refresh: bool = False) -> tuple[Optional[Path], bool]:
    """Point the cache at `root` (created lazily); None deactivates.
    `refresh=True` bypasses lookups but still writes entries — the
    `--reencode` contract: re-encode everything from source AND replace
    whatever the cache held. Returns the previous (root, refresh) so
    callers can restore them."""
    global _active_root, _refresh
    prev = (_active_root, _refresh)
    _active_root = Path(root) if root is not None else None
    _refresh = bool(refresh)
    return prev


def active_root() -> Optional[Path]:
    return _active_root


@contextmanager
def activated(root: str | os.PathLike | None,
              refresh: bool = False) -> Iterator[None]:
    prev_root, prev_refresh = activate(root, refresh)
    try:
        yield
    finally:
        activate(prev_root, prev_refresh)


def history_fingerprint(history: Sequence, model_name: str,
                        k_slots: int) -> str:
    """sha256 over exactly the fields the encoder consumes (encode.py
    pair_history: type, f, value, process — time/index never reach the
    tensors), plus the codec (model), requested slot width, and the
    encoder version (an encoder fix invalidates every entry)."""
    h = hashlib.sha256()
    h.update(f"v{ENCODING_VERSION}|{model_name}|{k_slots}".encode())
    for op in history:
        h.update(
            f"\n{op.type}|{op.f}|{op.value!r}|{op.process!r}".encode())
    return h.hexdigest()


def _entry_path(fingerprint: str) -> Optional[Path]:
    if _active_root is None:
        return None
    return _active_root / f"{fingerprint}.npz"


def lookup(history: Sequence, model_name: str,
           k_slots: int) -> Optional[EncodedHistory]:
    """Cached EncodedHistory for this (history, model, k_slots), or None
    (cache inactive, refresh mode, miss, or unreadable entry)."""
    if _refresh:
        return None
    path = _entry_path(history_fingerprint(history, model_name, k_slots))
    if path is None:
        return None
    m = get_metrics()
    try:
        with np.load(path) as z:
            enc = EncodedHistory.from_arrays(z)
    except Exception:   # missing or torn entry: re-encode, never fail
        m.counter("encode.cache_misses").add(1)
        return None
    m.counter("encode.cache_hits").add(1)
    try:
        # Touch for the size-capped GC's LRU (mtime) ordering: a hit
        # is a use, so hot entries survive collection.
        os.utime(path)
    except OSError:
        pass
    return enc


def store(history: Sequence, model_name: str, k_slots: int,
          enc: EncodedHistory) -> None:
    """Persist an encoding under its input fingerprint (atomic replace:
    concurrent replays of the same store race benignly)."""
    path = _entry_path(history_fingerprint(history, model_name, k_slots))
    if path is None:
        return
    try:
        path.parent.mkdir(parents=True, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=path.parent, suffix=".npz")
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez_compressed(f, **enc.to_arrays())
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
    except OSError:
        pass   # the cache is an optimization, never a failure mode
    global _stores_since_gc
    _stores_since_gc += 1
    if _stores_since_gc >= _GC_EVERY:
        _stores_since_gc = 0
        gc()


def gc(cap_mb: Optional[int] = None) -> int:
    """Size-capped LRU collection (ISSUE 20 satellite): while the
    cache's on-disk bytes exceed ``encode_cache_cap_mb`` (0 = the
    seed's unbounded growth), evict least-recently-USED entries —
    mtime order; lookup() touches its hit, so hot entries survive.
    Concurrent-pod safe: writers land entries via O_EXCL mkstemp +
    atomic replace, so the sweep never sees a half-written named
    entry, and a concurrently vanished file (another pod's GC, or a
    replace) is skipped, never an error. Returns the eviction count
    (`encode.cache_evictions` on the registry)."""
    root = _active_root
    if root is None:
        return 0
    if cap_mb is None:
        cap_mb = limits().encode_cache_cap_mb
    if cap_mb <= 0:
        return 0
    cap = int(float(cap_mb) * (1 << 20))
    entries = []
    total = 0
    try:
        it = list(root.iterdir())
    except OSError:
        return 0
    for p in it:
        if not p.name.endswith(".npz"):
            continue
        try:
            st = p.stat()
        except OSError:
            continue   # vanished under a concurrent pod's sweep
        entries.append((st.st_mtime, st.st_size, p))
        total += st.st_size
    if total <= cap:
        return 0
    evicted = 0
    for _, size, p in sorted(entries):
        if total <= cap:
            break
        try:
            p.unlink()
        except OSError:
            continue   # already gone: the other pod won the race
        total -= size
        evicted += 1
    if evicted:
        get_metrics().counter("encode.cache_evictions").add(evicted)
    return evicted
