"""Content-addressed encoded-tensor cache (ISSUE 2 satellite).

`analyze` / `corpus` replays re-encode the same stored histories on every
invocation; for a big store the host encode dominates the warm path the
compile cache just made cheap. This cache persists the encoder's OUTPUT —
the padded int32 event tensor — keyed by a sha256 over the encoder's
INPUT (the translated op sequence's (type, f, value, process) fields,
the model name, and the requested slot width), so an unchanged history
loads its tensor instead of re-pairing/re-encoding.

The cache is OFF unless activated (the CLI activates it for `analyze` /
`corpus`, with `--no-encode-cache` as the escape hatch); library callers
pay one module-global read. Entries are plain npz files
(EncodedHistory.to_arrays) written atomically, safe under concurrent
replays. A hash is a pure function of the encoder's observable input, so
a cache hit is bit-identical to a fresh encode; corrupt/unreadable
entries fall through to a re-encode, never an error.
"""

from __future__ import annotations

import hashlib
import os
import tempfile
from contextlib import contextmanager
from pathlib import Path
from typing import Iterator, Optional, Sequence

import numpy as np

from ..obs import get_metrics
from ..ops.encode import ENCODING_VERSION, EncodedHistory

CACHE_DIRNAME = ".encode-cache"

_active_root: Optional[Path] = None
_refresh: bool = False


def activate(root: str | os.PathLike | None,
             refresh: bool = False) -> tuple[Optional[Path], bool]:
    """Point the cache at `root` (created lazily); None deactivates.
    `refresh=True` bypasses lookups but still writes entries — the
    `--reencode` contract: re-encode everything from source AND replace
    whatever the cache held. Returns the previous (root, refresh) so
    callers can restore them."""
    global _active_root, _refresh
    prev = (_active_root, _refresh)
    _active_root = Path(root) if root is not None else None
    _refresh = bool(refresh)
    return prev


def active_root() -> Optional[Path]:
    return _active_root


@contextmanager
def activated(root: str | os.PathLike | None,
              refresh: bool = False) -> Iterator[None]:
    prev_root, prev_refresh = activate(root, refresh)
    try:
        yield
    finally:
        activate(prev_root, prev_refresh)


def history_fingerprint(history: Sequence, model_name: str,
                        k_slots: int) -> str:
    """sha256 over exactly the fields the encoder consumes (encode.py
    pair_history: type, f, value, process — time/index never reach the
    tensors), plus the codec (model), requested slot width, and the
    encoder version (an encoder fix invalidates every entry)."""
    h = hashlib.sha256()
    h.update(f"v{ENCODING_VERSION}|{model_name}|{k_slots}".encode())
    for op in history:
        h.update(
            f"\n{op.type}|{op.f}|{op.value!r}|{op.process!r}".encode())
    return h.hexdigest()


def _entry_path(fingerprint: str) -> Optional[Path]:
    if _active_root is None:
        return None
    return _active_root / f"{fingerprint}.npz"


def lookup(history: Sequence, model_name: str,
           k_slots: int) -> Optional[EncodedHistory]:
    """Cached EncodedHistory for this (history, model, k_slots), or None
    (cache inactive, refresh mode, miss, or unreadable entry)."""
    if _refresh:
        return None
    path = _entry_path(history_fingerprint(history, model_name, k_slots))
    if path is None:
        return None
    m = get_metrics()
    try:
        with np.load(path) as z:
            enc = EncodedHistory.from_arrays(z)
    except Exception:   # missing or torn entry: re-encode, never fail
        m.counter("encode.cache_misses").add(1)
        return None
    m.counter("encode.cache_hits").add(1)
    return enc


def store(history: Sequence, model_name: str, k_slots: int,
          enc: EncodedHistory) -> None:
    """Persist an encoding under its input fingerprint (atomic replace:
    concurrent replays of the same store race benignly)."""
    path = _entry_path(history_fingerprint(history, model_name, k_slots))
    if path is None:
        return
    try:
        path.parent.mkdir(parents=True, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=path.parent, suffix=".npz")
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez_compressed(f, **enc.to_arrays())
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
    except OSError:
        pass   # the cache is an optimization, never a failure mode
