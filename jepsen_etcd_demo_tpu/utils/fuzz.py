"""Random concurrent-history generation for differential testing and bench.

`gen_register_history` simulates a real linearizable CAS register with an
explicit linearization point chosen inside each op's invoke/complete window,
so the produced history is linearizable *by construction* (unless mutated).
It exercises every completion status the reference client can produce
(ok/fail/info — src/jepsen/etcdemo.clj:83-105):

  * ok ops linearize at some point inside their window;
  * cas ops that linearize against a mismatched value complete :fail
    (the reference client maps a false cas! to :fail, :95-98);
  * some ops take effect but never complete (:info — timeout after effect);
  * some ops fail before taking effect (:fail — timeout before effect is NOT
    how the reference maps write timeouts, but read timeouts map to :fail,
    :100-102).

`mutate_history` breaks a valid history (corrupt a read, resurrect a failed
write) to produce likely-invalid inputs; differential tests only require the
two checkers to AGREE, so mutants that stay valid are fine.
"""

from __future__ import annotations

import random
from typing import Optional

from ..ops.op import Op, INVOKE, OK, FAIL, INFO


def gen_register_history(
    rng: random.Random,
    n_ops: int = 50,
    n_procs: int = 5,
    value_range: int = 5,
    p_read: float = 0.4,
    p_write: float = 0.35,
    p_info: float = 0.05,
    p_fail_read: float = 0.05,
) -> list[Op]:
    """Generate a valid (linearizable) single-register history."""
    value: Optional[int] = None  # the register; None == key missing
    history: list[Op] = []
    # pending: proc -> dict(op fields, linearized?, result)
    pending: dict[int, dict] = {}
    free = list(range(n_procs))
    invoked = 0

    def emit(op: Op):
        op.index = len(history)
        op.time = len(history) * 1000
        history.append(op)

    while invoked < n_ops or pending:
        choices = []
        if invoked < n_ops and free:
            choices.append("invoke")
        unlin = [p for p, d in pending.items() if not d["lin"]]
        lin = [p for p, d in pending.items() if d["lin"]]
        if unlin:
            choices.append("linearize")
            choices.append("fail_read")
        if lin:
            choices.append("complete")
        action = rng.choice(choices)

        if action == "invoke":
            proc = free.pop(rng.randrange(len(free)))
            x = rng.random()
            if x < p_read:
                f, v = "read", None
            elif x < p_read + p_write:
                f, v = "write", rng.randrange(value_range)
            else:
                f, v = "cas", (rng.randrange(value_range),
                               rng.randrange(value_range))
            emit(Op(type=INVOKE, f=f, value=v, process=proc))
            pending[proc] = {"f": f, "value": v, "lin": False, "result": None}
            invoked += 1
        elif action == "linearize":
            proc = rng.choice(unlin)
            d = pending[proc]
            if d["f"] == "read":
                d["result"] = value
            elif d["f"] == "write":
                value = d["value"]
            else:  # cas
                old, new = d["value"]
                if value == old:
                    value = new
                    d["result"] = True
                else:
                    d["result"] = False
            d["lin"] = True
        elif action == "fail_read":
            # A read that times out maps to :fail (didn't logically happen).
            reads = [p for p in unlin if pending[p]["f"] == "read"]
            if not reads or rng.random() > p_fail_read * 4:
                continue
            proc = rng.choice(reads)
            emit(Op(type=FAIL, f="read", value=None, process=proc,
                    error="timeout"))
            del pending[proc]
            free.append(proc)
        else:  # complete
            proc = rng.choice(lin)
            d = pending.pop(proc)
            if rng.random() < p_info and d["f"] != "read":
                # Took effect but the ack was lost: indeterminate forever.
                emit(Op(type=INFO, f=d["f"], value=d["value"], process=proc,
                        error="timeout"))
                # jepsen crashes the worker and allocates a fresh process id;
                # model that so the process never completes this op.
                free.append(max(list(free) + list(pending) + [proc]) + 1)
                continue
            if d["f"] == "read":
                emit(Op(type=OK, f="read", value=d["result"], process=proc))
            elif d["f"] == "write":
                emit(Op(type=OK, f="write", value=d["value"], process=proc))
            else:
                status = OK if d["result"] else FAIL
                emit(Op(type=status, f="cas", value=d["value"], process=proc))
            free.append(proc)
    return history


def mutate_history(rng: random.Random, history: list[Op],
                   value_range: int = 5) -> list[Op]:
    """Corrupt a valid history so it is (probably) not linearizable."""
    out = [Op(**{**op.__dict__}) for op in history]
    candidates = [i for i, op in enumerate(out)
                  if op.type == OK and op.f == "read"]
    if candidates:
        i = rng.choice(candidates)
        old = out[i].value
        choices = [v for v in range(value_range) if v != old] + [None]
        out[i].value = rng.choice([c for c in choices if c != old])
        return out
    # No ok read to corrupt: flip a failed cas to ok.
    candidates = [i for i, op in enumerate(out)
                  if op.type == FAIL and op.f == "cas"]
    if candidates:
        out[rng.choice(candidates)].type = OK
    return out
