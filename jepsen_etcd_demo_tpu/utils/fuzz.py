"""Random concurrent-history generation for differential testing and bench.

`gen_register_history` simulates a real linearizable CAS register with an
explicit linearization point chosen inside each op's invoke/complete window,
so the produced history is linearizable *by construction* (unless mutated).
It exercises every completion status the reference client can produce
(ok/fail/info — src/jepsen/etcdemo.clj:83-105):

  * ok ops linearize at some point inside their window;
  * cas ops that linearize against a mismatched value complete :fail
    (the reference client maps a false cas! to :fail, :95-98);
  * some ops take effect but never complete (:info — timeout after effect);
  * some ops fail before taking effect (:fail — timeout before effect is NOT
    how the reference maps write timeouts, but read timeouts map to :fail,
    :100-102).

`mutate_history` breaks a valid history (corrupt a read, resurrect a failed
write) to produce likely-invalid inputs; differential tests only require the
two checkers to AGREE, so mutants that stay valid are fine.
"""

from __future__ import annotations

import random
from typing import Optional

from ..ops.op import Op, INVOKE, OK, FAIL, INFO


def gen_register_history(
    rng: random.Random,
    n_ops: int = 50,
    n_procs: int = 5,
    value_range: int = 5,
    p_read: float = 0.4,
    p_write: float = 0.35,
    p_info: float = 0.05,
    p_fail_read: float = 0.05,
    initial_value: Optional[int] = None,
) -> list[Op]:
    """Generate a valid (linearizable) single-register history.
    `initial_value` seeds the simulated register (None == key missing) —
    the out-of-core segment chain (stream/longhaul.py) uses it so each
    segment is valid FROM the previous segment's final state."""
    value = initial_value  # the register; None == key missing
    history: list[Op] = []
    # pending: proc -> dict(op fields, linearized?, result)
    pending: dict[int, dict] = {}
    free = list(range(n_procs))
    invoked = 0

    def emit(op: Op):
        op.index = len(history)
        op.time = len(history) * 1000
        history.append(op)

    while invoked < n_ops or pending:
        choices = []
        if invoked < n_ops and free:
            choices.append("invoke")
        unlin = [p for p, d in pending.items() if not d["lin"]]
        lin = [p for p, d in pending.items() if d["lin"]]
        if unlin:
            choices.append("linearize")
            choices.append("fail_read")
        if lin:
            choices.append("complete")
        action = rng.choice(choices)

        if action == "invoke":
            proc = free.pop(rng.randrange(len(free)))
            x = rng.random()
            if x < p_read:
                f, v = "read", None
            elif x < p_read + p_write:
                f, v = "write", rng.randrange(value_range)
            else:
                f, v = "cas", (rng.randrange(value_range),
                               rng.randrange(value_range))
            emit(Op(type=INVOKE, f=f, value=v, process=proc))
            pending[proc] = {"f": f, "value": v, "lin": False, "result": None}
            invoked += 1
        elif action == "linearize":
            proc = rng.choice(unlin)
            d = pending[proc]
            if d["f"] == "read":
                d["result"] = value
            elif d["f"] == "write":
                value = d["value"]
            else:  # cas
                old, new = d["value"]
                if value == old:
                    value = new
                    d["result"] = True
                else:
                    d["result"] = False
            d["lin"] = True
        elif action == "fail_read":
            # A read that times out maps to :fail (didn't logically happen).
            reads = [p for p in unlin if pending[p]["f"] == "read"]
            if not reads or rng.random() > p_fail_read * 4:
                continue
            proc = rng.choice(reads)
            emit(Op(type=FAIL, f="read", value=None, process=proc,
                    error="timeout"))
            del pending[proc]
            free.append(proc)
        else:  # complete
            proc = rng.choice(lin)
            d = pending.pop(proc)
            if rng.random() < p_info and d["f"] != "read":
                # Took effect but the ack was lost: indeterminate forever.
                emit(Op(type=INFO, f=d["f"], value=d["value"], process=proc,
                        error="timeout"))
                # jepsen crashes the worker and allocates a fresh process id;
                # model that so the process never completes this op.
                free.append(max(list(free) + list(pending) + [proc]) + 1)
                continue
            if d["f"] == "read":
                emit(Op(type=OK, f="read", value=d["result"], process=proc))
            elif d["f"] == "write":
                emit(Op(type=OK, f="write", value=d["value"], process=proc))
            else:
                status = OK if d["result"] else FAIL
                emit(Op(type=status, f="cas", value=d["value"], process=proc))
            free.append(proc)
    return history


def mutate_history(rng: random.Random, history: list[Op],
                   value_range: int = 5) -> list[Op]:
    """Corrupt a valid history so it is (probably) not linearizable."""
    out = [Op(**{**op.__dict__}) for op in history]
    candidates = [i for i, op in enumerate(out)
                  if op.type == OK and op.f == "read"]
    if candidates:
        i = rng.choice(candidates)
        old = out[i].value
        choices = [v for v in range(value_range) if v != old] + [None]
        out[i].value = rng.choice([c for c in choices if c != old])
        return out
    # No ok read to corrupt: flip a failed cas to ok.
    candidates = [i for i, op in enumerate(out)
                  if op.type == FAIL and op.f == "cas"]
    if candidates:
        out[rng.choice(candidates)].type = OK
    return out


def interleave_keyed(per_key, proc_stride: int = 1000) -> list[Op]:
    """Round-robin interleave per-key histories into the single keyed op
    stream a live independent-key run's recorder would produce: values
    wrapped as ``(key, v)`` tuples, process ids namespaced into disjoint
    ``proc_stride``-wide ranges per key so no process spans keys.
    ``per_key`` is a list of histories (key = position) or a dict
    ``{key: history}``. Shared by the bench streaming lane, the stream
    tune probe, and tests/test_stream.py — one definition of the
    stream's expected record order."""
    items = list(per_key.items()) if isinstance(per_key, dict) \
        else list(enumerate(per_key))
    ops: list[Op] = []
    cursors = [0] * len(items)
    while any(c < len(h) for c, (_, h) in zip(cursors, items)):
        for i, (k, h) in enumerate(items):
            if cursors[i] < len(h):
                op = h[cursors[i]]
                cursors[i] += 1
                ops.append(Op(type=op.type, f=op.f, value=(k, op.value),
                              process=proc_stride * i + int(op.process),
                              time=op.time, error=op.error))
    return ops


# -- other model families (models/gset.py, queues.py, multi_register.py) --
#
# Same construction as gen_register_history: simulate the REAL object with
# an explicit linearization point inside each op's invoke/complete window,
# so the produced history is linearizable by construction. The family
# plugs in as three callbacks:
#   choose(rng)                 -> (f, invoke_value)
#   linearize(sim, f, value)    -> (ok, result)  [mutates sim; ok=False
#                                  completes as :fail — e.g. empty dequeue]
#   may_info(f)                 -> op may take effect yet never complete
#                                  (dequeues may NOT: the encoder rejects
#                                  indeterminate dequeues as unencodable)


def _gen_history(rng: random.Random, n_ops: int, n_procs: int,
                 choose, linearize, may_info, sim,
                 p_info: float = 0.05) -> list[Op]:
    history: list[Op] = []
    pending: dict[int, dict] = {}
    free = list(range(n_procs))
    invoked = 0

    def emit(op: Op):
        op.index = len(history)
        op.time = len(history) * 1000
        history.append(op)

    while invoked < n_ops or pending:
        choices = []
        if invoked < n_ops and free:
            choices.append("invoke")
        unlin = [p for p, d in pending.items() if not d["lin"]]
        lin = [p for p, d in pending.items() if d["lin"]]
        if unlin:
            choices.append("linearize")
        if lin:
            choices.append("complete")
        action = rng.choice(choices)

        if action == "invoke":
            proc = free.pop(rng.randrange(len(free)))
            f, v = choose(rng)
            emit(Op(type=INVOKE, f=f, value=v, process=proc))
            pending[proc] = {"f": f, "value": v, "lin": False}
            invoked += 1
        elif action == "linearize":
            proc = rng.choice(unlin)
            d = pending[proc]
            d["ok"], d["result"] = linearize(sim, d["f"], d["value"])
            d["lin"] = True
        else:  # complete
            proc = rng.choice(lin)
            d = pending.pop(proc)
            if (d["ok"] and may_info(d["f"]) and rng.random() < p_info):
                emit(Op(type=INFO, f=d["f"], value=d["value"], process=proc,
                        error="timeout"))
                # Reincarnate the worker as a fresh process id, like jepsen.
                free.append(max(list(free) + list(pending) + [proc]) + 1)
                continue
            emit(Op(type=OK if d["ok"] else FAIL, f=d["f"],
                    value=d["result"], process=proc))
            free.append(proc)
    return history


def gen_gset_history(rng: random.Random, n_ops: int = 40, n_procs: int = 5,
                     value_range: int = 5, p_info: float = 0.05) -> list[Op]:
    """Valid grow-only-set history: concurrent adds + exact-set reads."""
    def choose(rng):
        if rng.random() < 0.4:
            return "read", None
        return "add", rng.randrange(value_range)

    def linearize(sim, f, v):
        if f == "add":
            sim.add(v)
            return True, v
        return True, sorted(sim)  # read observes the current set

    return _gen_history(rng, n_ops, n_procs, choose, linearize,
                        lambda f: f == "add", set(), p_info)


def gen_queue_history(rng: random.Random, n_ops: int = 20, n_procs: int = 4,
                      fifo: bool = True, value_range: int = 5,
                      max_enqueues: int = 10,
                      p_info: float = 0.05) -> list[Op]:
    """Valid queue history. fifo=True dequeues the head (FIFOQueue model,
    values drawn from 0..value_range-1, at most max_enqueues of them);
    fifo=False dequeues a RANDOM queued element with unique values
    (UnorderedQueue model)."""
    counter = iter(range(10_000))
    budget = {"enq": max_enqueues if fifo else 31}

    def choose(rng):
        if budget["enq"] > 0 and rng.random() < 0.55:
            budget["enq"] -= 1
            v = rng.randrange(value_range) if fifo else next(counter)
            return "enqueue", v
        return "dequeue", None

    def linearize(sim, f, v):
        if f == "enqueue":
            sim.append(v)
            return True, v
        if not sim:
            return False, None  # empty dequeue fails (did not take effect)
        i = 0 if fifo else rng.randrange(len(sim))
        return True, sim.pop(i)

    return _gen_history(rng, n_ops, n_procs, choose, linearize,
                        lambda f: f == "enqueue", [], p_info)


def gen_multireg_history(rng: random.Random, n_ops: int = 40,
                         n_procs: int = 5, n_registers: int = 3,
                         value_range: int = 5,
                         p_info: float = 0.05) -> list[Op]:
    """Valid multi-register history: (index, value) writes, indexed reads."""
    def choose(rng):
        i = rng.randrange(n_registers)
        if rng.random() < 0.45:
            return "read", (i, None)
        return "write", (i, rng.randrange(value_range))

    def linearize(sim, f, v):
        if f == "write":
            i, val = v
            sim[i] = val
            return True, v
        i = v[0]
        return True, (i, sim.get(i))  # read observes register i (None=NIL)

    return _gen_history(rng, n_ops, n_procs, choose, linearize,
                        lambda f: f == "write", {}, p_info)


def mutate_family_history(rng: random.Random, history: list[Op],
                          family: str, value_range: int = 5) -> list[Op]:
    """Corrupt a valid family history so it is (probably) not linearizable:
    gset — flip an element's membership in an ok read; fifo-queue — swap
    two dequeued values (reorder) or corrupt one; unordered-queue —
    duplicate a delivered value; multi-register — corrupt an ok read."""
    out = [Op(**{**op.__dict__}) for op in history]
    if family == "gset":
        reads = [i for i, op in enumerate(out)
                 if op.type == OK and op.f == "read"]
        if reads:
            i = rng.choice(reads)
            s = set(out[i].value)
            v = rng.randrange(value_range)
            out[i].value = sorted(s ^ {v})
        return out
    if family in ("fifo-queue", "unordered-queue"):
        deqs = [i for i, op in enumerate(out)
                if op.type == OK and op.f == "dequeue"]
        if family == "fifo-queue" and len(deqs) >= 2:
            a, b = rng.sample(deqs, 2)
            out[a].value, out[b].value = out[b].value, out[a].value
        elif deqs:
            i = rng.choice(deqs)
            others = [out[j].value for j in deqs if j != i]
            out[i].value = rng.choice(others) if others else (
                (out[i].value + 1) % 31)
        return out
    if family == "multi-register":
        reads = [i for i, op in enumerate(out)
                 if op.type == OK and op.f == "read"]
        if reads:
            i = rng.choice(reads)
            reg, old = out[i].value
            choices = [v for v in range(value_range) if v != old] + [None]
            out[i].value = (reg, rng.choice(
                [c for c in choices if c != old]))
        return out
    raise ValueError(f"unknown family {family!r}")


def gen_append_txns(rng: random.Random, n_txns: int = 50,
                    n_keys: int = 8, max_len: int = 3,
                    p_read: float = 0.5, first_key: int = 0) -> list[tuple]:
    """Serializable-by-construction list-append txn corpus (the elle
    workload shape, checkers/elle.py): txns execute SERIALLY against a
    per-key list store with unique append values, so every read is the
    true list at its serialization point — anomaly-free by
    construction. Returns ("ok", [micro-op, ...]) tuples; use
    `append_txn_ops` to expand them into an invoke/completion history
    and `mutate_append_txns` to break one."""
    store: dict = {}
    counters: dict = {}
    txns = []
    for _ in range(n_txns):
        mops = []
        for _ in range(1 + rng.randrange(max_len)):
            k = f"k{first_key + rng.randrange(n_keys)}"
            if rng.random() < p_read:
                mops.append(("r", k, tuple(store.get(k, ()))))
            else:
                counters[k] = counters.get(k, 0) + 1
                v = counters[k]
                store[k] = tuple(store.get(k, ())) + (v,)
                mops.append(("append", k, v))
        txns.append(("ok", mops))
    return txns


def append_txn_ops(txns) -> list[Op]:
    """Expand ("ok"|"fail"|"info", [mops]) txn tuples into the
    invoke/completion Op history the elle checkers pair — one process
    per txn, reads blanked to None on the invoke."""
    h = []
    for p, (typ, mops) in enumerate(txns):
        inv = [(m[0], m[1], None) if m[0] == "r" else m for m in mops]
        h.append(Op(type=INVOKE, f="txn", value=inv, process=p))
        h.append(Op(type=typ, f="txn",
                    value=mops if typ == "ok" else inv, process=p))
    return h


def mutate_append_txns(rng: random.Random, txns) -> list[tuple]:
    """Corrupt a valid append-txn corpus so it is (probably) anomalous:
    drop an element from an observed list (lost-append / rw cycles),
    duplicate one, or swap two (incompatible-order / G0). Differential
    tests only require the routes to AGREE, so mutants that stay valid
    are fine."""
    out = [(typ, [tuple(m) for m in mops]) for typ, mops in txns]
    reads = [(i, j) for i, (typ, mops) in enumerate(out)
             for j, m in enumerate(mops)
             if typ == "ok" and m[0] == "r" and len(m[2]) >= 1]
    if not reads:
        return out
    i, j = reads[rng.randrange(len(reads))]
    typ, mops = out[i]
    k, vs = mops[j][1], list(mops[j][2])
    mode = rng.randrange(3)
    if mode == 0 and len(vs) >= 1:
        vs.pop(rng.randrange(len(vs)))            # lost element
    elif mode == 1:
        vs.insert(rng.randrange(len(vs) + 1),
                  vs[rng.randrange(len(vs))])     # duplicate
    elif len(vs) >= 2:
        a, b = rng.sample(range(len(vs)), 2)
        vs[a], vs[b] = vs[b], vs[a]               # reorder
    else:
        vs = vs + vs                              # duplicate the singleton
    mops[j] = ("r", k, tuple(vs))
    return out
