"""Force JAX onto a virtual n-device CPU platform (tests + dryruns).

Single source of the forcing recipe used by tests/conftest.py and
__graft_entry__.dryrun_multichip (SURVEY.md §4: mesh tests run on simulated
devices). Must run BEFORE any JAX backend initialization — the environment
may pre-import jax with a TPU backend via sitecustomize, so setting
JAX_PLATFORMS in os.environ alone can be too late; jax.config.update works
as long as no backend has been initialized yet (i.e. before the first
jax.devices() call).
"""

from __future__ import annotations

import os


def force_virtual_cpu(n_devices: int) -> None:
    """Best-effort: point JAX at a virtual CPU platform with n devices.

    Raises RuntimeError (with the observed device count) when the forcing
    didn't take — a backend was already initialized, or a conflicting
    xla_force_host_platform_device_count was inherited from the
    environment.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"  # for any subprocesses
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n_devices}"
        ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n_devices)
    except Exception:
        pass  # older jax: XLA_FLAGS above covers it
    if len(jax.devices()) < n_devices:
        raise RuntimeError(
            f"force_virtual_cpu({n_devices}): only {len(jax.devices())} "
            f"device(s) visible. Either a JAX backend was initialized "
            f"before this call (use a fresh process), or the environment "
            f"carried a conflicting XLA_FLAGS="
            f"{os.environ.get('XLA_FLAGS')!r}")
