"""Clocks, logging, fuzzing, misc."""
