"""Test composition (L7) — the etcd-test / workloads layer.

Mirror of the reference's test assembly (src/jepsen/etcdemo.clj:110-190):
workload registry {"set", "register"} (:128-131), the phased generator
with rate limiting + cycling nemesis schedule (add-phase-generator,
:134-144) and the main → heal → recover → final-phase shape (:168-174),
all merged over noop-test-style defaults (:156-157).

Two entry compositions:
  * etcd_test  — the real thing: etcd DB over SSH, partition nemesis.
  * fake_test  — same wiring over the in-process FakeKVStore (hermetic; the
    build's "distributed-without-cluster" capability, SURVEY.md §4).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from . import generators as gen
from .checkers import Compose, IndependentChecker, Linearizable, SetChecker
from .checkers.perf import PerfChecker
from .checkers.timeline import TimelineChecker
from .clients.etcd import etcd_conn_factory
from .clients.fake_kv import FakeKVStore
from .clients.register import RegisterClient, fake_conn_factory
from .clients.set_client import SetClient
from .db.debian import debian_setup
from .db.etcd import EtcdDB
from .db.fake import FakeDB
from .nemesis import (ClockSkewNemesis, ClockStrobeNemesis,
                      FakeClockSkewNemesis, FakePartitionNemesis,
                      KillNemesis, NoopNemesis, PartitionRandomHalves,
                      PauseNemesis)

# noop-test-style defaults (reference tests/noop-test [dep]: n1..n5,
# concurrency, time-limit; overridden by CLI opts then by the demo map,
# src/jepsen/etcdemo.clj:156-157).
DEFAULTS: dict[str, Any] = {
    "nodes": ["n1", "n2", "n3", "n4", "n5"],
    "concurrency": 10,
    "time_limit": 30,
    "rate": 10.0,           # Hz (reference :180-183)
    "ops_per_key": 100,     # (:184-187)
    "quorum": False,        # (:179)
    "seed": 0,
    "store_root": "store",
}


def r(ctx):
    """{:type :invoke, :f :read} (reference :67)."""
    return {"f": "read", "value": None}


def w(ctx):
    """write of (rand-int 5) (reference :68)."""
    return {"f": "write", "value": ctx.rng.randrange(5)}


def cas(ctx):
    """cas of a random [old new] over 0-4 (reference :69)."""
    return {"f": "cas", "value": (ctx.rng.randrange(5), ctx.rng.randrange(5))}


def check_budget(opts: dict) -> Optional[float]:
    """Wall-clock bound for the linearizability search (None = unbounded,
    the default 120 s catches combinatorially exploding frontiers —
    PARITY.md "Wall-clock search budgets"). check_budget_s=0/None in opts
    disables it."""
    if "check_budget_s" in opts:
        v = opts["check_budget_s"]
        return float(v) if v else None
    return 120.0


def register_workload(opts: dict, conn_factory: Callable) -> dict:
    """Register workload (reference :110-126): mixed r/w/cas over many
    independent keys, checked {linear: TPU-WGL cas-register, timeline: html}
    per key under the independent wrapper."""
    return {
        "client": RegisterClient(conn_factory),
        "checker": IndependentChecker(Compose({
            "linear": Linearizable("cas-register", backend="jax",
                                   time_budget_s=check_budget(opts)),
            "timeline": TimelineChecker(),
        })),
        "generator": gen.concurrent_generator(
            10, _key_stream(), lambda k: gen.limit(
                int(opts.get("ops_per_key", 100)), gen.mix([r, w, cas]))),
        "final_generator": None,
    }


def _key_stream():
    i = 0
    while True:
        yield i
        i += 1


def set_workload(opts: dict, conn_factory: Callable) -> dict:
    """Grow-only-set workload (reference set.clj:42-49): infinite adds of
    successive ints, one final read after healing, set-durability checker."""
    counter = iter(range(10**9))
    return {
        "client": SetClient(conn_factory),
        "checker": SetChecker(),
        "generator": gen.repeat(lambda ctx: {"f": "add",
                                             "value": next(counter)}),
        "final_generator": gen.once({"f": "read", "value": None}),
    }


def _elle_txn_workload(opts: dict, conn_factory: Callable, write_mop: str,
                       method: str, checker_cls) -> dict:
    """Shared shape of the two elle txn workloads: random multi-key txns
    of reads and writes (write values unique per key), a final
    read-everything phase after healing (the tail of writes is observed,
    tightening the inferred version order), the elle checker family in
    the run's strictness. Requires a transactional connection (the fake
    cluster; etcd v2 has no transactions)."""
    from .clients.txn import TxnClient

    n_keys = int(opts.get("txn_keys", 3))
    max_len = int(opts.get("txn_len", 4))
    counters: dict = {}

    def txn_gen(ctx):
        mops = []
        for _ in range(1 + ctx.rng.randrange(max_len)):
            k = f"k{ctx.rng.randrange(n_keys)}"
            if ctx.rng.random() < 0.5:
                mops.append(("r", k, None))
            else:
                counters[k] = counters.get(k, 0) + 1
                mops.append((write_mop, k, counters[k]))
        return {"f": "txn", "value": mops}

    return {
        "client": TxnClient(conn_factory, method=method),
        "checker": Compose({"elle": checker_cls(
                                realtime=bool(opts.get("elle_realtime"))),
                            "timeline": TimelineChecker()}),
        "generator": gen.repeat(txn_gen),
        "final_generator": gen.once({
            "f": "txn",
            "value": [("r", f"k{i}", None) for i in range(n_keys)]}),
    }


def append_workload(opts: dict, conn_factory: Callable) -> dict:
    """Elle list-append workload: random multi-key txns of reads and
    appends (values unique per key), checked by the MXU-backed elle
    checker (checkers/elle.py). No reference-demo counterpart — the demo
    only ships elle as a dependency (jepsen.etcdemo.iml:46) — but the
    capability is part of the dependency surface SURVEY.md §2.2 lists."""
    from .checkers.elle import ElleChecker

    return _elle_txn_workload(opts, conn_factory, "append", "txn",
                              ElleChecker)


def txnregister_workload(opts: dict, conn_factory: Callable) -> dict:
    """Elle rw-register workload: random multi-key REGISTER txns (writes
    unique per key), checked by ElleRwChecker — elle 0.1.2's other
    inference family (jepsen.etcdemo.iml:46; VERDICT r3 item 8)."""
    from .checkers.elle import ElleRwChecker

    return _elle_txn_workload(opts, conn_factory, "w", "txn_register",
                              ElleRwChecker)


def queue_workload(opts: dict, conn_factory: Callable) -> dict:
    """FIFO-queue workload over independent per-key queues: enqueues of
    random small values and dequeues, checked {linear: TPU-WGL fifo-queue,
    timeline} per key. No reference-demo counterpart — the queue MODELS
    mirror knossos's model family (models/queues.py).

    Per-key enqueue count is capped at the model's bounded capacity
    (FIFOQueue.prepare_history rejects histories that could overflow the
    bit-packed state), so each key's history stays checkable; the
    independent wrapper supplies the scale axis instead of history length.
    """
    from .clients.queue_client import QueueClient
    from .models import FIFOQueue

    model = FIFOQueue()  # values 0..4, capacity 10
    per_key_ops = min(int(opts.get("ops_per_key", 100)), 2 * model.capacity)

    def per_key(k):
        budget = {"enq": model.capacity}

        def step(ctx):
            if budget["enq"] > 0 and ctx.rng.random() < 0.55:
                budget["enq"] -= 1
                return {"f": "enqueue",
                        "value": ctx.rng.randrange(model.max_value + 1)}
            return {"f": "dequeue", "value": None}

        return gen.limit(per_key_ops, gen.repeat(step))

    return {
        "client": QueueClient(conn_factory),
        "checker": IndependentChecker(Compose({
            "linear": Linearizable(model, backend="jax",
                                   time_budget_s=check_budget(opts)),
            "timeline": TimelineChecker(),
        })),
        "generator": gen.concurrent_generator(10, _key_stream(), per_key),
        "final_generator": None,
    }


def multiregister_workload(opts: dict, conn_factory: Callable) -> dict:
    """Whole-store linearizability: reads/writes over a small register
    file, checked as ONE history against the multi-register model
    (models/multi_register.py — knossos's multi-register family). Unlike
    the independent-keys register workload, cross-register ordering
    violations are in scope here: the model state is the whole file."""
    from .clients.register import MultiRegisterClient
    from .models import MultiRegister

    model = MultiRegister()  # 3 registers over values 0..4

    def step(ctx):
        i = ctx.rng.randrange(model.n_registers)
        if ctx.rng.random() < 0.5:
            return {"f": "read", "value": (i, None)}
        return {"f": "write",
                "value": (i, ctx.rng.randrange(model.max_value + 1))}

    return {
        "client": MultiRegisterClient(conn_factory),
        "checker": Compose({
            "linear": Linearizable(model, backend="jax",
                                   time_budget_s=check_budget(opts)),
            "timeline": TimelineChecker(),
        }),
        "generator": gen.repeat(step),
        "final_generator": None,
    }


def gset_workload(opts: dict, conn_factory: Callable) -> dict:
    """Set ops checked for READ LINEARIZABILITY under the gset model
    (models/gset.py): every read must observe exactly the adds linearized
    before it. Complements the `set` workload, which owns durability
    attribution (unique successive values, reference set.clj:46 algebra);
    here values cycle over the reference's small domain (rand-int 5,
    src/jepsen/etcdemo.clj:68) — adds are idempotent, the whole 32-state
    space fits the dense lattice kernel in one VPU tile, and the target
    bug class is stale/invented READS, which durability checking cannot
    see."""
    counter = {"i": 0}

    def step(ctx):
        if ctx.rng.random() < 0.3:
            return {"f": "read", "value": None}
        counter["i"] += 1
        return {"f": "add", "value": counter["i"] % 5}

    return {
        "client": SetClient(conn_factory),
        "checker": Compose({
            "linear": Linearizable("gset", backend="jax",
                                   time_budget_s=check_budget(opts)),
            "timeline": TimelineChecker(),
        }),
        "generator": gen.repeat(step),
        "final_generator": gen.once({"f": "read", "value": None}),
    }


def mutex_workload(opts: dict, conn_factory: Callable) -> dict:
    """Distributed-lock workload over the mutex model (knossos model
    family, models/mutex.py): every worker thread alternates
    acquire/release forever (failed CASes drop out of the history; the
    model judges the acknowledged ones), checked as ONE whole-run history."""
    from .clients.mutex_client import MutexClient

    def thread_gen():
        state = {"i": 0}

        def step(ctx):
            i = state["i"]
            state["i"] = i + 1
            return {"f": "acquire" if i % 2 == 0 else "release",
                    "value": None}

        return gen.repeat(step)

    return {
        "client": MutexClient(conn_factory),
        "checker": Compose({
            # Long partitions pile up indeterminate acquires AND releases,
            # whose interleavings explode combinatorially (~C(2m, m)
            # configs for m of each) — a genuinely knossos-DNF shape. The
            # time budget converts that grind into the honest tri-state
            # "unknown" (run exits nonzero either way).
            "linear": Linearizable("mutex", backend="jax",
                                   time_budget_s=check_budget(opts)),
            "timeline": TimelineChecker(),
        }),
        "generator": gen.each_thread(thread_gen),
        "final_generator": None,
    }


WORKLOADS = {
    "register": register_workload,
    "set": set_workload,
    "gset": gset_workload,
    "append": append_workload,
    "txnregister": txnregister_workload,
    "queue": queue_workload,
    "multiregister": multiregister_workload,
    "mutex": mutex_workload,
}


def add_phase_generator(opts: dict, workload_gen, final_gen) -> gen.Phases:
    """Rate-limit the client stream, overlay the cycling nemesis schedule,
    cap wall time; then the heal → recover → final-read phases
    (reference :134-144 and :168-174)."""
    rate = float(opts.get("rate", 10.0))
    main = gen.time_limit(
        float(opts.get("time_limit", 30)),
        _merge(
            gen.clients_gen(gen.stagger(1.0 / rate, workload_gen)),
            gen.nemesis_gen(gen.cycle(lambda: [
                gen.sleep(float(opts.get("nemesis_interval", 5))),
                gen.once({"f": "start", "value": None}),
                gen.sleep(float(opts.get("nemesis_interval", 5))),
                gen.once({"f": "stop", "value": None}),
            ])) if not opts.get("no_nemesis") else gen.Gen()))
    phases = [
        main,
        gen.log("Healing cluster"),
        gen.nemesis_gen(gen.once({"f": "stop", "value": None})),
        gen.log("Waiting for recovery"),
        gen.sleep(float(opts.get("recovery_wait", 10))),
    ]
    if final_gen is not None:
        phases.append(gen.clients_gen(final_gen))
    return gen.phases(*phases)


class _merge(gen.Gen):
    """Interleave two channel-routed generators: each asker takes from
    whichever answers (clients stream + nemesis stream side by side,
    reference :136-143)."""

    def __init__(self, *gens):
        self.gens = list(gens)

    def next_for(self, ctx):
        best_wake = None
        exhausted = 0
        for g in self.gens:
            out = g.next_for(ctx)
            if isinstance(out, gen.Pending):
                if out.wake is not None:
                    best_wake = (out.wake if best_wake is None
                                 else min(best_wake, out.wake))
            elif out is None:
                exhausted += 1
            else:
                return out
        if exhausted == len(self.gens):
            return None
        return gen.Pending(best_wake)


def compose_test(opts: dict, conn_factory: Callable,
                 workload_name: Optional[str] = None) -> dict:
    """Build the test map: defaults ← opts ← workload wiring
    (merge order mirrors reference :156-175)."""
    test = dict(DEFAULTS)
    test.update(opts)
    name = workload_name or test.get("workload", "register")
    workload = WORKLOADS[name](test, conn_factory)
    test.setdefault("name", f"etcd q={str(test['quorum']).lower()}")
    test["workload"] = name
    test["client"] = workload["client"]
    test["generator"] = add_phase_generator(
        test, workload["generator"], workload.get("final_generator"))
    test["checker"] = Compose({
        "perf": PerfChecker(),
        "indep": workload["checker"],
    })
    return test


def pick_nemesis(opts: dict, store: Optional[FakeKVStore] = None, db=None):
    """Nemesis registry (jepsen.nemesis family, SURVEY.md §2.2:
    partition, kill, pause, clock skew). `store` selects the hermetic
    twins; kill/pause need a real DB."""
    kind = opts.get("nemesis", "partition")
    seed = int(opts.get("seed", 0))
    if store is not None:
        from .nemesis.partition import FakeIsolatedNodeNemesis

        fakes = {
            "partition": lambda: FakePartitionNemesis(store, seed=seed),
            "partition-node": lambda: FakeIsolatedNodeNemesis(store,
                                                              seed=seed),
            "clock": lambda: FakeClockSkewNemesis(store, seed=seed),
            "noop": NoopNemesis,
        }
        if kind not in fakes:
            raise ValueError(
                f"nemesis {kind!r} not available in --fake mode "
                f"(have: {sorted(fakes)})")
        return fakes[kind]()
    from .nemesis.partition import (PartitionBridge, PartitionIsolatedNode,
                                    PartitionMajoritiesRing)

    reals = {
        "partition": lambda: PartitionRandomHalves(seed=seed),
        # The rest of the jepsen.nemesis partition family (same iptables
        # machinery, different grudge): REAL clusters only — the fake
        # store models reachability as one isolated set and cannot
        # express bridge/ring overlap.
        "partition-node": lambda: PartitionIsolatedNode(seed=seed),
        "partition-bridge": lambda: PartitionBridge(seed=seed),
        "partition-ring": lambda: PartitionMajoritiesRing(seed=seed),
        "clock": lambda: ClockSkewNemesis(seed=seed),
        "clock-strobe": lambda: ClockStrobeNemesis(seed=seed),
        "kill": lambda: KillNemesis(db, seed=seed),
        "pause": lambda: _pause_nemesis(seed),
        "noop": NoopNemesis,
    }
    if kind not in reals:
        raise ValueError(f"unknown nemesis {kind!r} (have: {sorted(reals)})")
    return reals[kind]()


def _pause_nemesis(seed: int):
    # Per-node resolution: co-hosted nodes (PORT_MAP) have their own
    # pidfiles; everywhere else this resolves to the shared default.
    from .db.etcd import pidfile_for
    return PauseNemesis(pidfile_for, seed=seed)


def etcd_test(opts: dict) -> dict:
    """The real composition (reference etcd-test, :146-175): Debian OS prep,
    etcd v3.1.5 DB, SSH control, iptables partition nemesis."""
    # The factory resolves each node's client port through the DB layer
    # (env override and per-node PORT_MAP included), so the data plane
    # dials wherever that node's daemon actually listens.
    test = compose_test(opts, etcd_conn_factory())
    test["db"] = EtcdDB(version=opts.get("version", "v3.1.5"))
    test["os_setup"] = lambda runner, node: debian_setup(runner, node)
    test["nemesis"] = pick_nemesis(test, db=test["db"])
    return test


def fake_test(opts: dict, store: Optional[FakeKVStore] = None) -> dict:
    """Hermetic composition over the in-process fake cluster."""
    opts = dict(opts)
    opts["local_mode"] = True
    if store is None:
        store = FakeKVStore(seed=int(opts.get("seed", 0)),
                            op_delay_s=float(opts.get("op_delay", 0.0)),
                            stale_read_prob=float(
                                opts.get("stale_read_prob", 0.0)),
                            lost_write_prob=float(
                                opts.get("lost_write_prob", 0.0)),
                            duplicate_cas_prob=float(
                                opts.get("duplicate_cas_prob", 0.0)),
                            reorder_prob=float(
                                opts.get("reorder_prob", 0.0)),
                            duplicate_delivery_prob=float(
                                opts.get("duplicate_delivery_prob", 0.0)))
    test = compose_test(opts, fake_conn_factory(store))
    test["db"] = FakeDB()
    test["nemesis"] = pick_nemesis(test, store=store)
    test["fake_store"] = store
    return test
