"""tune — profile-guided autotuner for the KernelLimits knob space.

ISSUE 4 tentpole. The hot loop's speed is governed by ~20 `KernelLimits`
knobs whose defaults encode exactly one deployment (the axon worker);
`ops/calibrate.py` already measured ONE of them (the oracle crossover)
per backend and persisted it. This package generalizes that pattern —
the same profile-guided shape XLA and Triton autotuning use:

  * probes.py   — deterministic microbenchmarks per knob group, timing
                  the real production code paths under candidate limits
  * search.py   — bounded coordinate descent + successive halving inside
                  each field's safe range, under a wall-clock budget
  * profile.py  — the versioned on-disk profile store, keyed by
                  (jax backend, device kind, device count), auto-loaded
                  by `limits()` with precedence
                  env > set_limits() > tuned profile > default

Entry points: `jepsen-tpu tune` (cli/main.py), `run_tune()` below for
embedding, `tools/print_profile.py` for the resolved view.
"""

from __future__ import annotations

from . import profile
from .search import default_knobs, resolve_knobs, search

__all__ = ["default_knobs", "profile", "resolve_knobs", "run_tune",
           "search"]


def run_tune(knobs: list[str] | None = None, budget_s: float = 60.0,
             repeats: int = 2, scale: float = 1.0, model=None,
             dry_run: bool = False, calibrate_too: bool = True) -> dict:
    """Measure, choose, persist. Returns the summary record the CLI
    prints: the search output plus the persisted profile's identity
    (path/hash/platform) — or `"dry_run": True` with nothing written.

    `calibrate_too` folds a fresh oracle-crossover calibration
    (ops/calibrate.py) into the same profile entry, so one `tune` run
    produces the COMPLETE per-machine measurement set."""
    res = search(knobs=knobs, budget_s=budget_s, repeats=repeats,
                 scale=scale, model=model)
    out = dict(res)
    out["platform"] = profile.platform_key(require_jax_loaded=False) \
        or "unknown"
    if dry_run:
        out["dry_run"] = True
        return out
    calibration = None
    if calibrate_too:
        from dataclasses import asdict

        from ..ops import calibrate

        cal = calibrate.measure()
        calibrate.set_calibration(cal)
        calibration = asdict(cal)
        out["calibration"] = calibration
    path = profile.save_entry(res["values"], probes=res["probes"],
                              budget_s=budget_s, calibration=calibration)
    out["profile_path"] = path
    out["profile_hash"] = profile.profile_hash()
    out["dry_run"] = False
    return out
