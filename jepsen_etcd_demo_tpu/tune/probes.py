"""Autotuner probes — small deterministic microbenchmarks per knob group.

Each probe group builds its fixtures ONCE (fixed-seed histories from
utils/fuzz.py, so two tune runs on the same machine measure the same
work) and then times the real production code path — the same kernels,
the same routers — under candidate `KernelLimits` overrides installed
via `set_limits`. Probes measure; the search (tune/search.py) decides.

Groups (the `group` metadata on KernelLimits fields, ops/limits.py):

  dense_sweep  — the host-chunked dense long sweep
                 (wgl3.check_steps3_long): events/s vs `long_scan_chunk`
                 and `dense_cell_budget_chunked` (conservative-down
                 candidates only — [worker] envelope fields).
  sparse       — the sparse active-tile engine's crossover
                 (ops/wgl3_sparse.py): live-tile density sweep tuning
                 `sparse_density_threshold_pct` / `sparse_min_tiles`
                 (PR 3 hardcoded a CPU measurement for these).
  sched        — the bucketed corpus scheduler (sched/engine.py):
                 padding-vs-compile tradeoff for `step_bucket_floor` /
                 `batch_bucket_floor` on a mixed-length corpus.
  pipeline     — `sched_pipeline_depth` (resumable sort sweep,
                 wgl2.check_steps_resumable) and `sched_poll_chunks`
                 (pipelined dense long sweep).
  pallas       — `pallas_step_chunk` / `max_k_pallas` where Mosaic
                 compiles (skipped wholesale off-TPU).
  stream       — the streaming check engine (stream/engine.py):
                 `stream_flush_ops` / `stream_max_lag_chunks` via a
                 full-speed replay of a fixed keyed op stream through
                 the stable-prefix dispatcher.
  dedup        — the frontier canonicalization pass + sparse seen memo
                 (ops/canon.py / ops/wgl3_sparse.py): a symmetry-heavy
                 history (small value domain, many forever-pending
                 duplicates) through the chunked dense sweep, tuning
                 `dedup_mode` / `dedup_hash_slots` /
                 `dedup_min_frontier`. Exact in every mode, so the
                 search is free to pick whatever measures fastest.
  elle         — the elle transitive-closure engine (ops/cycles.py /
                 ops/cycles_tiled.py / stream/elle.py):
                 `elle_dense_max_nodes` / `elle_tile` /
                 `elle_batch_floor` / `elle_density_threshold_pct` /
                 `elle_stream_flush` on fixed-seed dependency graphs
                 and a fixed txn stream (every route verdict-exact).
  spill        — the out-of-core spill tier (store/spill.py +
                 stream/longhaul.py): `host_spill_mode` /
                 `host_rss_budget_mb` / `spill_compress_mode` /
                 `encode_cache_cap_mb` via a fixed multi-segment
                 long-haul mini-lane through a scratch SpillDir
                 (verdict-exact in every mode).

Every measurement is warmup-then-best-of-N: the warmup call eats the
compile (the persistent XLA cache makes it cheap on re-tunes), the min
over repeats estimates the machine's floor — the quantity routing
decisions care about — rather than a load-dependent mean.
"""

from __future__ import annotations

import random
import time
from dataclasses import replace
from typing import Callable

from ..ops.limits import KernelLimits, limits, set_limits

# Fixed probe seeds — one per group, so fixtures never alias.
SEED_DENSE = 0xD5E1
SEED_SPARSE = 0x5BA5
SEED_SCHED = 0x5C4ED
SEED_PIPE = 0x919E
SEED_PALLAS = 0x9A11
SEED_STREAM = 0x57E4
SEED_DEDUP = 0xDED0
SEED_ELLE = 0xE17E
SEED_POD = 0x90D5
SEED_SPILL = 0x5B11

# Per-knob limit pins applied UNDER the candidate override while probing
# (e.g. the density threshold only matters once the sparse engine is
# eligible, so its probe pins the engagement floor to 1).
KNOB_PINS: dict[str, dict[str, int]] = {
    "sparse_density_threshold_pct": {"sparse_min_tiles": 1},
    # The memo only runs under the sparse engine; the min-frontier gate
    # only matters once the table pass is forced on.
    "dedup_hash_slots": {"sparse_mode": 2, "sparse_min_tiles": 1},
    "dedup_min_frontier": {"dedup_mode": 2},
    # Spill-window / codec knobs only matter once the out-of-core tier
    # is actually engaged, so their probes pin force-spill.
    "host_rss_budget_mb": {"host_spill_mode": 2},
    "spill_compress_mode": {"host_spill_mode": 2},
    "encode_cache_cap_mb": {"host_spill_mode": 2},
}


class ProbeContext:
    """Shared probe configuration. `scale` shrinks every fixture
    proportionally (the tier-1 CPU smoke runs at scale ~0.1, seconds of
    wall clock); `repeats` is the best-of count per measurement."""

    def __init__(self, model=None, scale: float = 1.0, repeats: int = 2):
        if model is None:
            from ..models import CASRegister

            model = CASRegister()
        self.model = model
        self.scale = max(0.02, float(scale))
        self.repeats = max(1, int(repeats))

    def n(self, full: int, floor: int) -> int:
        return max(floor, int(full * self.scale))


def _timed(fn: Callable[[], object], repeats: int) -> float:
    fn()                          # warmup: compile + caches
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def _with_overrides(overrides: dict[str, int], fn: Callable[[], object],
                    repeats: int) -> float:
    """Time `fn` under a fresh default profile + `overrides`. The base is
    the DATACLASS default, not the currently-resolved profile: the tuner
    measures what a shipped profile would do, not what the previous
    profile already did. Env overrides still win (ops/limits.py
    precedence), which is why the search excludes env-pinned knobs."""
    prev = set_limits(replace(KernelLimits(), **overrides))
    try:
        return _timed(fn, repeats)
    finally:
        set_limits(prev)


class _LongSweepFixture:
    """One fixed-seed register history prepared for the chunked dense
    long sweep — shared shape between the dense_sweep, pipeline, and
    pallas groups (each with its own seed/geometry)."""

    def __init__(self, ctx: ProbeContext, seed: int, n_ops: int,
                 k_slots: int | None = None, budget: int | None = None):
        from ..ops import wgl3
        from ..ops.encode import (encode_register_history,
                                  encode_return_steps, reslot_events)
        from ..utils.fuzz import gen_register_history

        h = gen_register_history(random.Random(seed), n_ops=n_ops,
                                 n_procs=8, p_info=0.002)
        enc = encode_register_history(h, k_slots=32)
        k = k_slots if k_slots is not None else wgl3.tight_k_slots(enc)
        self.cfg = wgl3.dense_config(ctx.model, k, enc.max_value,
                                     budget=budget)
        if self.cfg is None:
            raise RuntimeError(f"probe geometry infeasible (k={k})")
        self.enc = reslot_events(enc, k) if enc.k_slots != k else enc
        self.rs = encode_return_steps(self.enc)
        self.model = ctx.model


class DenseSweepProbe:
    """events/s of the host-chunked dense sweep vs the chunking knobs.
    The history is long enough that `long_scan_chunk` candidates below
    its step count really change the chunk loop's shape."""

    knobs = ("long_scan_chunk", "dense_cell_budget_chunked")

    def __init__(self, ctx: ProbeContext):
        self.ctx = ctx
        self.fix = _LongSweepFixture(ctx, SEED_DENSE,
                                     n_ops=ctx.n(4000, 400))

    def candidates(self, knob: str) -> list[int] | None:
        if knob == "long_scan_chunk":
            # Ladder below the fixture's step count so every candidate
            # exercises a different chunk-loop shape; the conservative
            # clamp (<= default) is applied by the search.
            steps = self.fix.rs.n_steps
            return sorted({max(256, steps // 8), max(256, steps // 4),
                           max(256, steps // 2), 16384})
        return None

    def measure(self, knob: str, overrides: dict[str, int]) -> float:
        from ..ops import wgl3

        return _with_overrides(
            overrides,
            lambda: wgl3.check_steps3_long(self.fix.rs, self.fix.model,
                                           self.fix.cfg),
            self.ctx.repeats)


class SparseProbe:
    """Sparse-vs-dense crossover: a WIDE table (k_slots beyond the
    history's real concurrency — the tiny-live-frontier regime the
    sparse engine exists for) swept under candidate density thresholds
    and engagement floors. Chosen values replace PR 3's hardcoded CPU
    measurement with THIS machine's."""

    knobs = ("sparse_density_threshold_pct", "sparse_min_tiles")

    def __init__(self, ctx: ProbeContext):
        self.ctx = ctx
        k = 13 if ctx.scale < 0.5 else 18
        self.fix = _LongSweepFixture(ctx, SEED_SPARSE,
                                     n_ops=ctx.n(1500, 150),
                                     k_slots=k, budget=1 << 28)

    def tiles(self) -> int:
        lim = limits()
        w = self.fix.cfg.n_masks // 32
        return max(1, w // lim.sparse_tile_words)

    def candidates(self, knob: str) -> list[int] | None:
        if knob == "sparse_min_tiles":
            # Bracket THIS geometry's tile count: the engage/stay-dense
            # decision is what the candidates toggle.
            t = self.tiles()
            return sorted({max(1, t // 2), t, 2 * t, 2048})
        return None

    def measure(self, knob: str, overrides: dict[str, int]) -> float:
        from ..ops import wgl3

        return _with_overrides(
            overrides,
            lambda: wgl3.check_steps3_long(self.fix.rs, self.fix.model,
                                           self.fix.cfg),
            self.ctx.repeats)


class SchedProbe:
    """Bucketed-scheduler floors on a fixed mixed-length corpus: lower
    floors pad tighter but compile more shapes; the measurement is the
    warm steady state (the persistent XLA cache amortizes compiles
    across processes, so steady-state is what production pays)."""

    knobs = ("step_bucket_floor", "batch_bucket_floor")

    def __init__(self, ctx: ProbeContext):
        from ..ops.encode import encode_register_history
        from ..utils.fuzz import gen_register_history

        self.ctx = ctx
        rng = random.Random(SEED_SCHED)
        n_hist = ctx.n(192, 24)
        hi = ctx.n(300, 60)
        self.encs = [encode_register_history(
            gen_register_history(rng, n_ops=rng.randrange(10, hi),
                                 n_procs=8, p_info=0.002), k_slots=32)
            for _ in range(n_hist)]

    def measure(self, knob: str, overrides: dict[str, int]) -> float:
        from .. import sched

        return _with_overrides(
            overrides,
            lambda: sched.check_corpus(self.encs, self.ctx.model),
            self.ctx.repeats)


class PipelineProbe:
    """Chunk-pipelining depth knobs. `sched_pipeline_depth` drives the
    resumable sort sweep's in-flight window (only buys anything on
    high-latency backends — which is the point of measuring it HERE);
    `sched_poll_chunks` drives the dense long sweep's death-poll
    interval."""

    knobs = ("sched_pipeline_depth", "sched_poll_chunks")

    def __init__(self, ctx: ProbeContext):
        self.ctx = ctx
        self.fix = _LongSweepFixture(ctx, SEED_PIPE, n_ops=ctx.n(3000, 300))

    def measure(self, knob: str, overrides: dict[str, int]) -> float:
        from ..ops import wgl2, wgl3

        if knob == "sched_pipeline_depth":
            fn = lambda: wgl2.check_steps_resumable(  # noqa: E731
                self.fix.rs, self.fix.model, chunk=256)
        else:
            fn = lambda: wgl3.check_steps3_long(  # noqa: E731
                self.fix.rs, self.fix.model, self.fix.cfg)
        return _with_overrides(overrides, fn, self.ctx.repeats)


class PallasProbe:
    """Mosaic-compiled resumable kernel knobs — only meaningful where
    pallas actually compiles; constructing the probe off-TPU raises
    ProbeUnavailable and the search records the group as skipped."""

    knobs = ("pallas_step_chunk", "max_k_pallas")

    def __init__(self, ctx: ProbeContext):
        from ..ops import wgl3_pallas

        if not wgl3_pallas.pallas_available():
            raise ProbeUnavailable("pallas unavailable on this backend")
        self.ctx = ctx
        self.fix = _LongSweepFixture(ctx, SEED_PALLAS,
                                     n_ops=ctx.n(3000, 300))
        # Second fixture at K=13 (>= 2 work-list blocks): with the
        # sparse work-list kernel routed by default wherever the
        # density signal selects it (wgl3_pallas.pallas_sparse_selected,
        # ISSUE 10), the tuned pallas geometry must be measured through
        # BOTH kernels — the chosen step chunk sizes the sparse
        # kernel's colmask blocks and 8-slot metadata windows too.
        self.fix_sparse = _LongSweepFixture(ctx, SEED_PALLAS + 1,
                                            n_ops=ctx.n(1200, 120),
                                            k_slots=13, budget=1 << 28)

    def measure(self, knob: str, overrides: dict[str, int]) -> float:
        from ..ops import wgl3_pallas

        def both():
            wgl3_pallas.check_steps3_long_pallas(
                self.fix.rs, self.fix.model, self.fix.cfg)
            wgl3_pallas.check_steps3_long_pallas(
                self.fix_sparse.rs, self.fix_sparse.model,
                self.fix_sparse.cfg)

        return _with_overrides(overrides, both, self.ctx.repeats)


class StreamProbe:
    """Streaming check engine knobs: a fixed keyed op stream (disjoint
    process-id ranges per key, round-robin interleaved — the record
    order a live independent-key run produces) replayed at full feed
    speed through the stable-prefix dispatcher (stream/engine.py).
    Measures the chunk-size / poll-lag tradeoff: smaller chunks start
    overlapping earlier but pay more dispatches, more frequent death
    polls sync the pipeline."""

    knobs = ("stream_flush_ops", "stream_max_lag_chunks")

    def __init__(self, ctx: ProbeContext):
        from ..utils.fuzz import gen_register_history, interleave_keyed

        self.ctx = ctx
        rng = random.Random(SEED_STREAM)
        n_keys = max(2, ctx.n(8, 2))
        per_key = [gen_register_history(rng, n_ops=ctx.n(1200, 120),
                                        n_procs=8, p_info=0.002)
                   for _ in range(n_keys)]
        self.ops = interleave_keyed(per_key)

    def measure(self, knob: str, overrides: dict[str, int]) -> float:
        from ..stream import StreamSession

        def replay():
            session = StreamSession(self.ctx.model, keyed=True)
            for op in self.ops:
                session.feed(op)
            res = session.finalize()
            assert res, "stream probe fixture must stream"
            return res

        return _with_overrides(overrides, replay, self.ctx.repeats)


class DedupProbe:
    """Frontier canonicalization + sparse seen-memo knobs on a
    SYMMETRY-HEAVY fixture: a small value domain with a sizeable
    forever-pending population gives the canonicalization pass real
    equal-effect classes to reduce (a symmetry-free history would
    measure the knobs as pure no-ops). Every mode is verdict-exact
    (ops/canon.py), so the search may pick whatever measures fastest —
    including OFF on machines where the pass never pays."""

    knobs = ("dedup_mode", "dedup_hash_slots", "dedup_min_frontier")

    def __init__(self, ctx: ProbeContext):
        from ..ops import wgl2, wgl3
        from ..ops.encode import (encode_register_history,
                                  encode_return_steps, reslot_events)
        from ..utils.fuzz import gen_register_history

        self.ctx = ctx
        k = 13 if ctx.scale < 0.5 else 16
        h = gen_register_history(random.Random(SEED_DEDUP),
                                 n_ops=ctx.n(2000, 150), n_procs=8,
                                 value_range=2, p_info=0.04)
        enc = encode_register_history(h, k_slots=32)
        self.cfg = wgl3.dense_config(ctx.model, k, max(enc.max_value, 4),
                                     budget=1 << 28)
        if self.cfg is None:
            raise RuntimeError(f"dedup probe geometry infeasible (k={k})")
        self.enc = reslot_events(enc, k) if enc.k_slots != k else enc
        self.rs = encode_return_steps(self.enc)
        # Second fixture for the SORT-LADDER arm: in auto mode the
        # TABLE sweep is canon-free (dedup_mode 0 and 1 compile the
        # same kernel — history_canon_pairs(table=True)), so without
        # this arm the 0-vs-1 candidates would tie and the tuner could
        # persist `off` by timing noise, silently disabling the sort
        # ladder's measured escalation-avoidance win and the seen memo.
        n_sort = ctx.n(200, 60)
        hs = gen_register_history(random.Random(SEED_DEDUP + 1),
                                  n_ops=n_sort, n_procs=8, value_range=1,
                                  p_info=15.0 / n_sort)
        enc_s = encode_register_history(hs, k_slots=32)
        ks = wgl2.sort_k_slots(enc_s)
        self.rs_sort = encode_return_steps(
            reslot_events(enc_s, ks) if enc_s.k_slots != ks else enc_s)
        self.model = ctx.model

    def tiles(self) -> int:
        lim = limits()
        w = self.cfg.n_masks // 32
        return max(1, w // lim.sparse_tile_words)

    def candidates(self, knob: str) -> list[int] | None:
        if knob == "dedup_mode":
            return [0, 1, 2]
        if knob == "dedup_hash_slots":
            # Bracket THIS geometry's tile count: the memo's engage /
            # fail-open decision is what the candidates toggle.
            t = self.tiles()
            return sorted({max(64, t // 2), max(64, t), max(64, 2 * t),
                           4096})
        if knob == "dedup_min_frontier":
            return [0, 16, 64, 256]
        return None

    def measure(self, knob: str, overrides: dict[str, int]) -> float:
        from ..ops import wgl2, wgl3

        def both():
            wgl3.check_steps3_long(self.rs, self.model, self.cfg)
            wgl2.check_steps_resumable(self.rs_sort, self.model,
                                       f_cap=64)

        return _with_overrides(overrides, both, self.ctx.repeats)


class ElleProbe:
    """Elle transitive-closure engine knobs (ops/cycles.py /
    ops/cycles_tiled.py / stream/elle.py) on fixed-seed fixtures: a
    corpus of small random dependency graphs (the batched corpus-of-
    graphs lane), one big BLOCK-STRUCTURED sparse graph (contiguous
    per-key chains — real empty tiles for the tiled kernel's occupancy
    work list to skip), and a fixed serial txn stream for the
    streaming flush cadence. Every route is verdict-exact (the closure
    fixpoint is unique), so the search picks whatever measures
    fastest."""

    knobs = ("elle_dense_max_nodes", "elle_tile", "elle_batch_floor",
             "elle_density_threshold_pct", "elle_stream_flush")

    def __init__(self, ctx: ProbeContext):
        import numpy as np

        from ..utils.fuzz import append_txn_ops, gen_append_txns

        self.ctx = ctx
        rng = np.random.default_rng(SEED_ELLE)
        # Small-graph corpus: the batched bucketed launches.
        self.small = []
        for _ in range(max(8, ctx.n(48, 8))):
            n = int(rng.integers(16, max(32, ctx.n(300, 40))))
            a = rng.random((n, n)) < 3.0 / n
            np.fill_diagonal(a, False)
            self.small.append(a)
        # One big block-diagonal sparse graph: per-key chains with a
        # few intra-block cross edges — the tiled kernel's regime.
        nb = max(600, ctx.n(5000, 600))
        blk = 100
        big = np.zeros((nb, nb), bool)
        for b0 in range(0, nb - 1, blk):
            hi = min(nb, b0 + blk)
            for i in range(b0, hi - 1):
                big[i, i + 1] = True
            extra = rng.integers(b0, hi, size=(max(2, blk // 8), 2))
            for s, d in extra:
                if s < d:
                    big[s, d] = True
        self.big = big
        # Streaming fixture: a fixed serial append-txn op stream.
        import random as _random

        self.ops = append_txn_ops(gen_append_txns(
            _random.Random(SEED_ELLE), n_txns=ctx.n(1500, 150),
            n_keys=8, max_len=2))

    def candidates(self, knob: str) -> list[int] | None:
        if knob == "elle_tile":
            return [128, 256, 512]
        if knob == "elle_dense_max_nodes":
            # Bracket the big fixture's node count: the dense-vs-
            # decomposed routing decision is what candidates toggle.
            n = self.big.shape[0]
            return sorted({max(128, n // 4), max(128, n // 2), n, 2048})
        return None

    def measure(self, knob: str, overrides: dict[str, int]) -> float:
        from ..ops import cycles

        if knob == "elle_stream_flush":
            from ..checkers.elle import ElleChecker
            from ..stream.elle import ElleStreamSession

            checker = ElleChecker()

            def replay():
                session = ElleStreamSession(checker)
                for op in self.ops:
                    session.feed(op)
                res = session.finalize()
                assert res, "elle stream probe fixture must stream"
                return res

            return _with_overrides(overrides, replay, self.ctx.repeats)
        if knob == "elle_batch_floor":
            return _with_overrides(
                overrides, lambda: cycles.cycle_masks_batch(self.small),
                self.ctx.repeats)
        if knob in ("elle_tile", "elle_density_threshold_pct"):
            from ..ops import cycles_tiled

            return _with_overrides(
                overrides,
                lambda: cycles_tiled.cycle_mask_tiled(self.big),
                self.ctx.repeats)
        # elle_dense_max_nodes: the auto route end to end on the big
        # graph — dense squaring below the crossover, decomposition
        # above it.
        return _with_overrides(
            overrides, lambda: cycles.cycle_mask(self.big),
            self.ctx.repeats)


class PodProbe:
    """Pod-scaling knobs (ISSUE 17) on a fixed ragged corpus through
    the mesh-sharded batch lane: `encode_mode` trades host encode + big
    packed-table H2D against the on-device expansion; `shard_bucket_mode`
    toggles the LPT shard packing; `pod_pipeline_depth` sets how many
    launches the dispatch window keeps in flight. All three only earn
    their keep on real multi-device meshes — measuring HERE (the current
    platform's mesh, virtual or not) is the point, exactly like the
    pipeline group."""

    knobs = ("encode_mode", "pod_pipeline_depth", "shard_bucket_mode")

    def __init__(self, ctx: ProbeContext):
        import jax

        from ..ops.encode import encode_register_history
        from ..utils.fuzz import gen_register_history

        if jax.device_count() < 2:
            raise ProbeUnavailable(
                "pod probe needs a multi-device mesh (the knobs are "
                "no-ops on one device)")
        self.ctx = ctx
        rng = random.Random(SEED_POD)
        n_hist = ctx.n(128, 16)
        hi = ctx.n(240, 48)
        self.encs = [encode_register_history(
            gen_register_history(rng, n_ops=rng.randrange(10, hi),
                                 n_procs=8, p_info=0.002), k_slots=32)
            for _ in range(n_hist)]

    def measure(self, knob: str, overrides: dict[str, int]) -> float:
        from ..parallel import dense as pdense

        return _with_overrides(
            overrides,
            lambda: pdense.check_batch_sharded(self.encs, self.ctx.model),
            self.ctx.repeats)


class SpillProbe:
    """Out-of-core spill-tier knobs (ISSUE 20): a fixed multi-segment
    long-haul mini-lane (stream/longhaul.py) replayed through an active
    scratch SpillDir. host_spill_mode off/auto/force trades disk I/O
    against host RSS; spill_compress_mode trades canon-quotient encode
    cycles against checkpoint bytes; the RSS budget and encode-cache
    cap steer the in-RAM window and GC cadence. Every mode is
    verdict-exact (store/spill.py), so the search may pick whatever
    measures fastest on this host's disk."""

    knobs = ("host_spill_mode", "host_rss_budget_mb",
             "spill_compress_mode", "encode_cache_cap_mb")

    def __init__(self, ctx: ProbeContext):
        self.ctx = ctx
        self.events = ctx.n(60_000, 6_000)
        self.seg_events = max(1024, ctx.n(8192, 1024))

    def measure(self, knob: str, overrides: dict[str, int]) -> float:
        import shutil
        import tempfile

        from ..store import spill
        from ..stream import longhaul

        def lane():
            td = tempfile.mkdtemp(prefix="jepsen-spill-probe-")
            try:
                with spill.spilling(td):
                    res = longhaul.run_longhaul(
                        self.ctx.model, events=self.events,
                        seg_events=self.seg_events, seed=SEED_SPILL,
                        resume=False)
                assert res["survived"], \
                    "spill probe fixture must survive"
                return res
            finally:
                shutil.rmtree(td, ignore_errors=True)

        return _with_overrides(overrides, lane, self.ctx.repeats)


class ProbeUnavailable(RuntimeError):
    """This probe group cannot run on this backend (recorded as skipped,
    never an error — a CPU tune simply has no pallas lane)."""


# Group name -> probe class; the search instantiates lazily (fixture
# encoding costs host time) and in this order.
PROBES = {
    "dense_sweep": DenseSweepProbe,
    "sparse": SparseProbe,
    "sched": SchedProbe,
    "pipeline": PipelineProbe,
    "pallas": PallasProbe,
    "stream": StreamProbe,
    "dedup": DedupProbe,
    "elle": ElleProbe,
    "pod": PodProbe,
    "spill": SpillProbe,
}
