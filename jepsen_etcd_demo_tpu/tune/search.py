"""Autotuner search — bounded coordinate descent with successive halving.

The knob space is small (~a dozen searched fields) and each measurement
is a real kernel run, so the search optimizes for MEASUREMENT ECONOMY,
not search-space cleverness:

  * coordinate descent — knobs are searched one at a time, in group
    order, each against the best values already chosen for earlier knobs
    (the groups are nearly independent by construction: chunking, sparse
    crossover, bucketing, pipelining touch different code paths);
  * successive halving per knob — every candidate is timed once, the
    slower half is dropped, survivors are re-timed (best-of accumulates
    across rounds) until one remains, so obvious losers cost one cheap
    measurement and the final winner is backed by several;
  * a wall-clock budget — checked before every measurement; expiry keeps
    the defaults for everything not yet measured (a partial profile is
    valid — un-searched knobs simply stay at their dataclass defaults);
  * safety envelopes — candidates come from each field's safe range
    (ops/limits.py field metadata); [worker] fields are additionally
    clamped to the conservative side of their default, so the tuner can
    never produce a profile that probes PAST a kill threshold the
    default encodes;
  * a noise guard — the winner must beat the default by >3% or the
    default is kept: a tuned profile should encode real measurements,
    not scheduler jitter.

Probe timings and chosen values land in obs gauges
(`tune.probe_s.<knob>`, `tune.chosen.<knob>`) when a telemetry capture
is active, and in the returned record (persisted into the profile's
`probes` section for provenance).
"""

from __future__ import annotations

import math
import os
import time

from .. import obs
from ..ops.limits import env_var, field_meta
from .probes import PROBES, KNOB_PINS, ProbeContext, ProbeUnavailable

# Winner must be at least this much faster than the default to displace
# it (fraction of the default's best time).
NOISE_MARGIN = 0.03

# Multiplicative ladder around the default for knobs whose probe offers
# no geometry-aware candidates.
LADDER = (0.25, 0.5, 1.0, 2.0, 4.0)


def default_knobs() -> list[str]:
    """Every field with a probe group — the `jepsen-tpu tune` default."""
    return [name for name, m in field_meta().items() if m.get("group")]


def resolve_knobs(spec: str | None) -> list[str]:
    """--knobs value -> field list: comma-separated field OR group names
    (unknown names raise with the valid vocabulary)."""
    if not spec:
        return default_knobs()
    meta = field_meta()
    by_group: dict[str, list[str]] = {}
    for name, m in meta.items():
        if m.get("group"):
            by_group.setdefault(m["group"], []).append(name)
    out: list[str] = []
    for tok in (t.strip() for t in spec.split(",")):
        if not tok:
            continue
        if tok in by_group:
            out.extend(n for n in by_group[tok] if n not in out)
        elif tok in meta:
            if meta[tok].get("group") is None:
                raise ValueError(
                    f"knob {tok!r} has no probe group (kind "
                    f"{meta[tok]['kind']}); tunable knobs: "
                    f"{', '.join(default_knobs())}")
            if tok not in out:
                out.append(tok)
        else:
            raise ValueError(
                f"unknown knob/group {tok!r}; knobs: "
                f"{', '.join(default_knobs())}; groups: "
                f"{', '.join(sorted(by_group))}")
    return out


def candidates_for(name: str, probe) -> list[int]:
    """Candidate values: the probe's geometry-aware list when it offers
    one, else a multiplicative ladder around the default — always
    clamped to the safe range, and for [worker] fields to the
    conservative side of the default."""
    m = field_meta()[name]
    default = m["default"]
    lo, hi = m["range"]
    cons = m.get("conservative")
    if cons == "down":
        hi = min(hi, default)
    elif cons == "up":
        lo = max(lo, default)
    raw = None
    if hasattr(probe, "candidates"):
        raw = probe.candidates(name)
    if raw is None:
        raw = [int(default * f) for f in LADDER]
    vals = sorted({min(hi, max(lo, int(v))) for v in raw} | {default})
    return vals


def _measure(probe, knob: str, value: int, chosen: dict[str, int]) -> float:
    overrides = dict(chosen)
    overrides.update(KNOB_PINS.get(knob, {}))
    overrides[knob] = value
    return probe.measure(knob, overrides)


def _search_knob(probe, knob: str, chosen: dict[str, int],
                 deadline: float) -> dict:
    """Successive halving over one knob's candidates; returns the probe
    record ({chosen, default, candidates, best_s, seconds} or a skip)."""
    m = field_meta()[knob]
    default = m["default"]
    cands = candidates_for(knob, probe)
    best_s: dict[int, float] = {}
    t0 = time.perf_counter()
    # The DEFAULT is measured first: if the budget expires mid-knob the
    # noise guard must still have its baseline — a winner may never
    # displace a default that was not itself timed (the documented
    # "expiry keeps defaults" contract).
    live = [default] + [v for v in cands if v != default]
    measured = 0
    while live:
        for v in list(live):
            if time.perf_counter() > deadline:
                # Budget expired mid-knob: candidates measured so far
                # still count, unmeasured ones drop out.
                live = [x for x in live if x in best_s]
                break
            s = _measure(probe, knob, v, chosen)
            best_s[v] = min(best_s.get(v, math.inf), s)
            measured += 1
        if len(live) <= 1 or time.perf_counter() > deadline:
            break
        live = sorted(live, key=lambda v: best_s.get(v, math.inf))
        live = live[: max(1, math.ceil(len(live) / 2))]
        if len(live) == 1:
            break
    record = {
        "default": default,
        "candidates": cands,
        "best_s": {str(v): round(s, 5) for v, s in sorted(best_s.items())},
        "measurements": measured,
        "seconds": round(time.perf_counter() - t0, 3),
    }
    if default not in best_s:
        record["skipped"] = "budget exhausted before the default baseline"
        record["chosen"] = default
        return record
    winner = min(best_s, key=best_s.get)
    if winner != default \
            and best_s[winner] >= best_s[default] * (1.0 - NOISE_MARGIN):
        winner = default       # noise guard: the default keeps ties
    record["chosen"] = winner
    met = obs.get_metrics()
    # jtlint: disable=JTL107 -- bounded family: knob iterates the fixed
    # tunable-field set of ops/limits.py field_meta(); exported as one
    # labeled Prometheus family (obs/export.py LABELED_FAMILIES).
    met.gauge(f"tune.probe_s.{knob}").set(record["seconds"])
    # jtlint: disable=JTL107 -- bounded family: same knob set as above.
    met.gauge(f"tune.chosen.{knob}").set(winner)
    met.counter("tune.measurements").add(measured)
    return record


def search(knobs: list[str] | None = None, budget_s: float = 60.0,
           repeats: int = 2, scale: float = 1.0, model=None) -> dict:
    """Measure the knob space within `budget_s` seconds of wall clock;
    returns {"values": {field: tuned}, "probes": {field: record},
    "skipped": {field/group: reason}, "spent_s": float}. `values` holds
    only fields whose winner differs from the default — the persisted
    profile stays minimal and the hash stays "default" when nothing won.

    The active limits profile is restored on exit no matter what: the
    probes swap profiles via set_limits for every measurement."""
    from ..ops import limits as limits_mod

    meta = field_meta()
    knobs = list(knobs) if knobs is not None else default_knobs()
    ctx = ProbeContext(model=model, scale=scale, repeats=repeats)
    deadline = time.perf_counter() + budget_s
    t_start = time.perf_counter()

    by_group: dict[str, list[str]] = {}
    skipped: dict[str, str] = {}
    for name in knobs:
        m = meta.get(name)
        if m is None or not m.get("group"):
            skipped[name] = "no probe group"
            continue
        if os.environ.get(env_var(name)) is not None:
            # An env pin wins over any tuned value (precedence) — probing
            # it would measure a knob the profile can never move.
            skipped[name] = f"pinned by {env_var(name)}"
            continue
        by_group.setdefault(m["group"], []).append(name)

    prev_set = limits_mod._SET   # read-only peek; restore goes through
    #                              the public set_limits below
    values: dict[str, int] = {}
    probes_out: dict[str, dict] = {}
    try:
        for group, cls in PROBES.items():
            names = by_group.get(group)
            if not names:
                continue
            if time.perf_counter() > deadline:
                for n in names:
                    skipped[n] = "budget exhausted"
                continue
            obs.get_tracer().event("tune.probe_group", group=group,
                                   knobs=",".join(names))
            try:
                probe = cls(ctx)
            except Exception as e:
                # ProbeUnavailable (pallas off-TPU) or any fixture
                # failure: the GROUP is skipped, the run continues —
                # 'recorded as skipped, never an error'. A tune run must
                # never discard hours of already-measured groups because
                # one fixture couldn't build.
                for n in names:
                    skipped[n] = str(e) or type(e).__name__
                continue
            for knob in names:
                try:
                    rec = _search_knob(probe, knob, values, deadline)
                except Exception as e:
                    # A measurement blowing up mid-knob (e.g. a candidate
                    # geometry Mosaic refuses to compile) skips THIS knob
                    # and keeps its default; earlier winners survive to
                    # be persisted.
                    skipped[knob] = f"probe error: {e}"
                    continue
                probes_out[knob] = rec
                if rec["chosen"] != rec["default"]:
                    values[knob] = rec["chosen"]
    finally:
        limits_mod.set_limits(prev_set)
    return {
        "values": values,
        "probes": probes_out,
        "skipped": skipped,
        "spent_s": round(time.perf_counter() - t_start, 3),
        "budget_s": budget_s,
        "scale": scale,
        "repeats": ctx.repeats,
    }
