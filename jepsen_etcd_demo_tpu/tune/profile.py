"""Persisted tuning profiles — the autotuner's store (ISSUE 4 tentpole).

ONE versioned JSON file, living next to the persistent XLA compile cache
(same lifecycle: a per-user, per-machine measurement cache), holding one
entry per ``(jax backend, device kind, device count)`` platform key:

    {
      "version": 1,
      "profiles": {
        "cpu/TFRT_CPU_0/1": {
          "limits": {"long_scan_chunk": 8192, ...},   # tuned overrides
          "calibration": {...},                        # ops/calibrate.py
          "measured_at": "2026-08-03T...Z",
          "budget_s": 60.0,
          "probes": {...}                              # raw timings
        }
      }
    }

``ops/limits.py`` auto-loads the entry for the running platform lazily
(the first ``limits()`` call after a jax backend exists) and applies it
below env and ``set_limits`` overrides; ``ops/calibrate.py`` reads and
writes its oracle-crossover calibration through the same entry (one
file, one version bump discipline — the old ``calibration.json`` sidecar
is read once as a legacy migration source and ignored thereafter).

Version discipline: bump PROFILE_VERSION whenever the probe semantics or
the schema change; a mismatched file is ignored wholesale (stale
measurements must not steer a newer kernel stack). Unknown fields and
out-of-range values inside an entry are dropped individually — a profile
tuned by a build with wider ranges must not break this one's startup.

Env knobs:
  JEPSEN_TPU_TUNE_PROFILE=<path>  explicit profile file path
  JEPSEN_TPU_TUNE_PROFILE=0       disable tuned-profile loading entirely
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import time

PROFILE_VERSION = 1
PROFILE_FILE = "tuned_profile.json"

_DISABLE = ("0", "false", "no", "off")

# Memoized platform entry: None = not yet determined;
# (profile_path, platform_key|None, entry|None) after — keyed by the
# PATH so a profile-path change (compile cache enabled, env updated)
# after an early "no profile here" answer is not permanently ignored.
_CACHE: tuple[str, str | None, dict | None] | None = None
# Parsed profile FILE, keyed by path — so the undetermined state (file
# present, platform key unresolvable yet) costs dict lookups per
# limits() call, not a disk read + JSON parse. Cleared by reset().
_FILE_CACHE: tuple[str, dict | None] | None = None


def profile_enabled() -> bool:
    return os.environ.get("JEPSEN_TPU_TUNE_PROFILE", "").lower() \
        not in _DISABLE


def profile_path() -> str:
    """The profile file: JEPSEN_TPU_TUNE_PROFILE (explicit path) wins,
    else the profile genuinely lives NEXT TO the persistent XLA compile
    cache — the same directory-precedence ladder as
    sched/compile_cache.py (JEPSEN_TPU_COMPILE_CACHE >
    JAX_COMPILATION_CACHE_DIR > the <store>/.xla-cache dir a CLI run
    enabled > ~/.cache/jepsen_tpu_xla), reusing that module rather than
    re-implementing a truncated copy: 'copy tuned_profile.json into the
    image's cache path' (doc/perf.md) must mean the path the cache
    actually uses."""
    explicit = os.environ.get("JEPSEN_TPU_TUNE_PROFILE")
    if explicit and explicit.lower() not in _DISABLE:
        return explicit
    from ..sched import compile_cache

    env = os.environ.get("JEPSEN_TPU_COMPILE_CACHE") \
        or os.environ.get("JAX_COMPILATION_CACHE_DIR")
    base = env or compile_cache._enabled_dir \
        or compile_cache.compile_cache_dir()
    return os.path.join(base, PROFILE_FILE)


def _backend_ready() -> bool:
    """True only when a jax backend is ALREADY initialized in this
    process. Module-import is NOT the test — the axon sitecustomize
    pre-imports jax into every process, so ``'jax' in sys.modules`` is
    vacuously true there while touching ``jax.devices()`` would still
    dial (and hang on) a wedged TPU tunnel. The xla_bridge backend
    registry is the initialized-state source of truth; if the internal
    moves in a future jax, we fail CLOSED (not ready -> the profile is
    reported "unknown" rather than risking a hang)."""
    if "jax" not in sys.modules:
        return False
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge._backends)
    except Exception:
        return False


def platform_key(require_jax_loaded: bool = True) -> str | None:
    """``backend/device_kind/device_count`` for the running process —
    with ``xH`` (host count) appended on a multi-process pod, so a
    pod's tuned profile is keyed by its MESH SHAPE and an elastic
    re-shard (device or host count changed between runs) can only MISS
    the profile store, never resolve a stale entry tuned for a mesh
    that no longer exists. Single-process keys keep the historical
    3-part form (every existing profile stays valid). None when the
    key cannot be determined. With ``require_jax_loaded`` (the
    default) the key resolves only when a backend is ALREADY initialized
    (_backend_ready): probing devices initializes one, and a lazy
    profile load must never be the thing that dials a wedged TPU tunnel
    (bench.py probes backend health in a subprocess for exactly that
    reason)."""
    if require_jax_loaded and not _backend_ready():
        return None
    try:
        import jax

        dev = jax.devices()[0]
        key = f"{jax.default_backend()}/{dev.device_kind}/" \
              f"{jax.device_count()}"
        if jax.process_count() > 1:
            key += f"x{jax.process_count()}"
        # An explicit mesh shape (--mesh-shape / JEPSEN_TPU_MESH_SHAPE)
        # changes the sharded lanes' layout without changing the device
        # or host counts — 2x4 and 4x2 tune differently, so the shape
        # joins the key (absent = the default mesh for those counts).
        from ..parallel.mesh import requested_shape

        shape = requested_shape()
        if shape is not None:
            key += "@" + "x".join(str(s) for s in shape)
        return key
    except Exception:
        return None


def _read_file(use_cache: bool = True) -> dict | None:
    """The parsed, version-checked profile file (None when absent, torn,
    or version-mismatched). Parses once per path until reset() — the
    undetermined state re-consults this on every limits() resolution."""
    global _FILE_CACHE
    path = profile_path()
    if use_cache and _FILE_CACHE is not None and _FILE_CACHE[0] == path:
        return _FILE_CACHE[1]
    try:
        data = json.loads(open(path).read())
    except (OSError, ValueError):
        data = None
    if not isinstance(data, dict) \
            or data.get("version") != PROFILE_VERSION \
            or not isinstance(data.get("profiles"), dict):
        data = None
    _FILE_CACHE = (path, data)
    return data


def _entry_state() -> tuple[dict | None, bool]:
    """(this platform's entry or None, undetermined?) — the one place
    the lookup ladder lives. ``undetermined`` is True exactly when a
    valid profile file exists but the platform key cannot resolve yet
    (no initialized jax backend): callers must treat that as "ask again
    later", never as "no profile" (ops/limits.py keeps retrying, the
    reporting surfaces say "unknown").

    The no-backend guarantee: the FILE is read first (plain I/O, parse
    cached); the platform key — and thus jax — is only consulted when
    the file exists, and even then only when a backend is ALREADY
    initialized (_backend_ready). Machines where no one ever ran
    ``jepsen-tpu tune`` never touch jax from here."""
    global _CACHE
    if not profile_enabled():
        return None, False
    path = profile_path()
    if _CACHE is not None and _CACHE[0] == path:
        return _CACHE[2], False
    data = _read_file()
    if data is None:
        _CACHE = (path, None, None)
        return None, False
    key = platform_key()
    if key is None:
        # No initialized backend yet: retry on a later call rather than
        # caching a miss the backend could satisfy.
        return None, True
    entry = data["profiles"].get(key)
    entry = entry if isinstance(entry, dict) else None
    _CACHE = (path, key, entry)
    return entry, False


def load_entry() -> dict | None:
    """This platform's profile entry, memoized once determinable. None
    when the profile is disabled, the file is absent/torn/version-
    mismatched, the platform key cannot resolve (yet), or the file has
    no entry for this platform."""
    return _entry_state()[0]


def _valid_limits(entry: dict | None) -> dict[str, int]:
    """An entry's limit overrides validated against the dataclass
    metadata: unknown fields and out-of-range values are dropped
    individually (a stale-but-version-matching profile must degrade
    field-wise, not explode). The SAME validated view feeds both
    resolution (tuned_limits) and identity (profile_hash), so the hash
    can never disagree with what actually applied."""
    raw = (entry or {}).get("limits")
    if not isinstance(raw, dict):
        return {}
    from ..ops.limits import field_meta

    meta = field_meta()
    out: dict[str, int] = {}
    for name, val in raw.items():
        m = meta.get(name)
        if m is None or not isinstance(val, int) \
                or isinstance(val, bool):
            continue
        lo, hi = m["range"]
        if lo <= val <= hi:
            out[name] = val
    return out


def tuned_limits() -> dict[str, int] | None:
    """The validated tuned KernelLimits overrides for this platform.
    Returns ``None`` — not ``{}`` — while the answer is undetermined
    (profile file present, platform key unresolvable without an
    initialized jax backend): ops/limits.py keeps retrying instead of
    freezing an empty tuned set."""
    entry, undetermined = _entry_state()
    if undetermined:
        return None
    return _valid_limits(entry)


def profile_hash(entry: dict | None = None) -> str:
    """Short content hash identifying the tuned overrides that ACTUALLY
    apply (the validated view — a profile whose fields are all dropped
    hashes "default", and two profiles validating identically hash the
    same). ``"default"`` when no tuned entry applies to this platform;
    ``"unknown"`` when a profile file EXISTS but the platform key cannot
    resolve (no initialized jax backend — the bench's all-probes-dead
    path): a degraded record must not claim "default" about a profile it
    simply could not look up. Lands in every bench record and in each
    run's results.json so a number can always be traced back to the knob
    values that produced it."""
    if entry is None:
        entry, undetermined = _entry_state()
        if undetermined:
            return "unknown"
    limits_dict = _valid_limits(entry)
    if not limits_dict:
        return "default"
    blob = json.dumps(limits_dict, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:12]


def save_entry(limits: dict[str, int], probes: dict | None = None,
               budget_s: float | None = None,
               calibration: dict | None = None) -> str:
    """Persist this platform's entry (read-modify-write, atomic replace:
    pod workers share the cache dir and a torn read would discard the
    whole profile). Preserves other platforms' entries and — unless a
    new one is given — this platform's existing calibration section.
    Returns the file path. Invalidates the limits() memo so the new
    profile takes effect in-process."""
    key = platform_key(require_jax_loaded=False)
    if key is None:
        raise RuntimeError("cannot resolve a platform key (no jax "
                           "backend); refusing to persist a profile")
    path = profile_path()
    with _file_lock(path):
        # Fresh read (no parse cache) UNDER the lock: read-modify-write
        # must see what is on disk NOW, not what this process parsed
        # earlier — and no other writer may slip between read and
        # replace.
        data = _read_file(use_cache=False) \
            or {"version": PROFILE_VERSION, "profiles": {}}
        old = data["profiles"].get(key) or {}
        entry = {
            "limits": dict(sorted(limits.items())),
            "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                         time.gmtime()),
        }
        if budget_s is not None:
            entry["budget_s"] = round(budget_s, 3)
        if probes is not None:
            entry["probes"] = probes
        cal = calibration if calibration is not None \
            else old.get("calibration")
        if cal is not None:
            entry["calibration"] = cal
        data["profiles"][key] = entry
        _write_file(path, data)
    reset()
    return path


def save_calibration(calibration: dict) -> None:
    """Persist only the calibration section of this platform's entry
    (ops/calibrate.py's write path), leaving tuned limits untouched.
    Best-effort like the old sidecar: persistence is an optimization,
    never a failure mode."""
    try:
        key = platform_key(require_jax_loaded=False)
        if key is None:
            return
        path = profile_path()
        with _file_lock(path):
            data = _read_file(use_cache=False) \
                or {"version": PROFILE_VERSION, "profiles": {}}
            entry = data["profiles"].setdefault(key, {"limits": {}})
            entry["calibration"] = calibration
            _write_file(path, data)
        reset()
    except OSError:
        pass


def load_calibration() -> dict | None:
    """This platform's calibration section, or None."""
    entry = load_entry()
    cal = (entry or {}).get("calibration")
    return cal if isinstance(cal, dict) else None


def _write_file(path: str, data: dict) -> None:
    import tempfile

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".")
    with os.fdopen(fd, "w") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


class _file_lock:
    """Best-effort O_EXCL lock around the read-modify-write of the
    SHARED multi-platform file: pod workers of different device kinds
    persist calibrations/profiles through one path, and os.replace alone
    prevents torn reads but not lost updates (A and B read at t0, A
    writes, B's write discards A's platform entry). On contention past
    the timeout — or a stale lock from a killed writer — we proceed
    unlocked: persistence is an optimization, never a failure mode."""

    def __init__(self, path: str, timeout_s: float = 5.0):
        self.lock = path + ".lock"
        self.timeout_s = timeout_s
        self.fd: int | None = None

    def __enter__(self):
        import time as _time

        deadline = _time.monotonic() + self.timeout_s
        while True:
            try:
                os.makedirs(os.path.dirname(self.lock) or ".",
                            exist_ok=True)
                self.fd = os.open(self.lock,
                                  os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                return self
            except FileExistsError:
                # Self-heal a stale lock (a writer killed between create
                # and unlink would otherwise disable the protection — and
                # add a full timeout stall — for every later persist).
                try:
                    if _time.time() - os.stat(self.lock).st_mtime \
                            > self.timeout_s:
                        os.unlink(self.lock)
                        continue
                except OSError:
                    pass                 # raced: re-try the O_EXCL open
                if _time.monotonic() > deadline:
                    return self          # contended: best-effort
                _time.sleep(0.05)
            except OSError:
                return self              # unwritable dir: best-effort

    def __exit__(self, *exc):
        if self.fd is not None:
            os.close(self.fd)
            try:
                os.unlink(self.lock)
            except OSError:
                pass
        return False


def reset() -> None:
    """Drop the memoized entry, the parsed-file cache, AND the limits()
    resolution built on them (tests; called automatically after every
    persist)."""
    global _CACHE, _FILE_CACHE
    _CACHE = None
    _FILE_CACHE = None
    from ..ops import limits as limits_mod

    limits_mod._TUNED = None
    limits_mod._LIMITS = None


# -- provenance / reporting -------------------------------------------------

def run_record() -> dict:
    """The compact profile stamp a run/bench record carries: the active
    profile hash, how many fields the PERSISTED profile tunes on this
    platform (counted from the store, so an embedding set_limits that
    merely snapshots the resolution doesn't hide them), and every field
    whose resolved value did not come from the dataclass default (with
    its provenance tag). ``tools/print_profile.py`` prints the full
    table."""
    from ..ops.limits import limits_provenance

    prov = limits_provenance()
    tuned = tuned_limits()
    rec = {
        "hash": profile_hash(),
        "tuned_fields": len(tuned or {}),
        "overrides": {k: v for k, v in sorted(prov.items())
                      if v != "default"},
    }
    if tuned is None:
        rec["note"] = ("profile file present but platform unresolvable "
                       "(no jax backend); run tools/print_profile.py "
                       "on the target machine")
    return rec


def report() -> dict:
    """The full resolved-limits report behind ``tools/print_profile.py``
    and ``jepsen-tpu tune --print-profile``: per-field value, default,
    provenance, kind, safe range and env var, plus the profile file's
    identity.

    This is an EXPLICIT operator diagnostic, so — unlike the lazy
    resolution path — it initializes a jax backend when one isn't up
    yet: a standalone `python tools/print_profile.py` must show the
    tuned values real runs resolve, not an eternal "unknown" (set
    JAX_PLATFORMS=cpu to avoid dialing a TPU). If backend init fails
    (the wedged-tunnel bug report), it degrades to the guarded view:
    platform "unknown", hash "unknown", defaults — still printable."""
    if not _backend_ready():
        try:
            import jax

            jax.devices()
        except Exception:
            pass
    from ..ops.limits import (env_var, field_meta, limits,
                              limits_provenance)

    lim = limits()
    meta = field_meta()
    prov = limits_provenance()
    fields_out = {}
    for name, m in meta.items():
        fields_out[name] = {
            "value": getattr(lim, name),
            "default": m["default"],
            "provenance": prov[name],
            "kind": m["kind"],
            "range": list(m["range"]),
            "env": env_var(name),
        }
    entry = load_entry()
    return {
        "platform": platform_key() or "unknown",
        "profile_path": profile_path(),
        "profile_version": PROFILE_VERSION,
        "profile_hash": profile_hash(),
        "profile_enabled": profile_enabled(),
        "measured_at": (entry or {}).get("measured_at"),
        "calibration": load_calibration(),
        "fields": fields_out,
    }
