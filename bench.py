"""Benchmark: WGL linearizability checking throughput, TPU kernel vs CPU oracle.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "detail": {...}}

The reference publishes no benchmark numbers (BASELINE.md): its checker is
knossos's JVM search, which this build replaces with the JAX/XLA kernels. The
baseline stand-in is therefore this repo's pure-Python oracle WGL checker
(checkers/oracle.py — same algorithm, same event encoding, host CPU), playing
the role of the JVM hot loop. vs_baseline = kernel events/sec ÷ oracle
events/sec on the same histories.

Workloads:
  * corpus — 1024 fuzzed 150-op cas-register histories (valid by
    construction: the checker must run to completion, the worst case for
    the search), checked in ONE batched launch of the dense lattice kernel
    (ops/wgl3.py) on one chip. BASELINE.json configs[2]/[4] (independent
    keys as one vmap, corpus-replay scale).
  * long history — 1k-op and 10k-op single-register histories through the
    single-history dense kernel (BASELINE.json configs[3]; north star:
    10k ops < 60 s where knossos-CPU DNFs).
  * gset corpus — 256 grow-only-set histories through the same batched
    kernel (model-family lane, models/gset.py).
"""

from __future__ import annotations

import json
import os
import random
import sys
import time

import numpy as np

N_OPS = 150           # ops per history (tutorial run scale, BASELINE configs[0])
N_PROCS = 10          # concurrency, matching the reference's 10 threads/key
CORPUS = 1024         # histories per batched launch — the full corpus-replay
#                       scale (BASELINE configs[4]: 1024 stored histories)
REPEATS = 3
LONG_OPS = (1_000, 10_000)


def build_corpus():
    from jepsen_etcd_demo_tpu.ops.encode import encode_register_history
    from jepsen_etcd_demo_tpu.utils.fuzz import gen_register_history

    rng = random.Random(0xBE7C)
    # p_info low: every :info op stays pending forever and occupies a slot
    # for the rest of the history (knossos semantics), so long histories
    # need them rare (or a wide slot table).
    return [encode_register_history(
        gen_register_history(rng, n_ops=N_OPS, n_procs=N_PROCS,
                             p_info=0.002), k_slots=32)
        for _ in range(CORPUS)]


def _measure_corpus(encs, model):
    """Shared measurement harness for batched-corpus lanes: one batched
    launch via the production routing point (wgl3_pallas dispatch), best
    of REPEATS with ONE packed device->host fetch per launch (per-fetch
    round trips dominate wall time on tunneled backends), then the oracle
    over the same histories. The corpus must be valid by construction
    (the checker runs to completion — the search's worst case)."""
    from jepsen_etcd_demo_tpu.checkers.oracle import check_events_oracle
    from jepsen_etcd_demo_tpu.ops import wgl3, wgl3_pallas

    cfg, arrays, _steps = wgl3.batch_arrays3(encs, model)
    check, kernel_name = wgl3_pallas.packed_batch_checker(
        model, cfg, n_steps=arrays[2].shape[1], batch=arrays[2].shape[0])
    out = wgl3.unpack_np(check(*arrays))  # compile + warmup
    assert out["survived"].all(), "bench corpus must be valid by construction"
    best = float("inf")
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        out = wgl3.unpack_np(check(*arrays))
        best = min(best, time.perf_counter() - t0)

    t0 = time.perf_counter()
    for enc in encs:
        assert check_events_oracle(enc, model).valid
    oracle_s = time.perf_counter() - t0
    return {
        "kernel_s": best,
        "oracle_s": oracle_s,
        "kernel": kernel_name,
        "k_slots": cfg.k_slots,
        "table_cells": cfg.n_states * cfg.n_masks,
        # §5.1 checker metric: configs explored per second of kernel wall
        # time (the search's unit of work; the oracle reports the same
        # counter for an apples-to-apples view).
        "configs_per_sec": float(out["configs_explored"].sum()) / best,
    }


def bench_corpus(model):
    encs = build_corpus()
    m = _measure_corpus(encs, model)
    m["events"] = int(sum(e.n_events for e in encs))
    m["histories_per_sec"] = CORPUS / m["kernel_s"]
    return m


def bench_gset_corpus():
    """Model-family lane: 256 grow-only-set histories through the same
    batched dense kernel (models/gset.py — the set state is its int32
    bitmask, 32-state table). Proves the family kernels run at corpus
    scale, not only under test geometries."""
    from jepsen_etcd_demo_tpu.models import GSet
    from jepsen_etcd_demo_tpu.ops.encode import encode_history
    from jepsen_etcd_demo_tpu.utils.fuzz import gen_gset_history

    model = GSet()
    rng = random.Random(0x65E7)
    encs = [encode_history(
        gen_gset_history(rng, n_ops=N_OPS, n_procs=N_PROCS, p_info=0.002),
        model, k_slots=32) for _ in range(256)]
    m = _measure_corpus(encs, model)
    return {"histories": len(encs), "kernel_s": round(m["kernel_s"], 4),
            "oracle_s": round(m["oracle_s"], 4), "kernel": m["kernel"],
            "table_cells": m["table_cells"]}


def bench_long(model, n_ops: int, oracle_too: bool):
    """One long single-register history through the single dense kernel."""
    from jepsen_etcd_demo_tpu.checkers.oracle import check_events_oracle
    from jepsen_etcd_demo_tpu.ops import wgl3_pallas
    from jepsen_etcd_demo_tpu.ops.encode import encode_register_history
    from jepsen_etcd_demo_tpu.utils.fuzz import gen_register_history

    rng = random.Random(0x10C0 + n_ops)
    h = gen_register_history(rng, n_ops=n_ops, n_procs=N_PROCS, p_info=0.0005)
    enc = encode_register_history(h, k_slots=64)
    run = lambda: wgl3_pallas.check_batch_encoded_auto([enc], model)[0][0]

    t0 = time.perf_counter()
    out = run()                             # includes compile (cold)
    cold_s = time.perf_counter() - t0
    assert out["valid"] is True
    t0 = time.perf_counter()
    out = run()
    warm_s = time.perf_counter() - t0
    d = {"ops": n_ops, "kernel_s": warm_s, "kernel_cold_s": cold_s}
    if oracle_too:
        t0 = time.perf_counter()
        res = check_events_oracle(enc, model)
        assert res.valid
        d["oracle_s"] = time.perf_counter() - t0
    return d


def main():
    import jax

    from jepsen_etcd_demo_tpu.models import CASRegister

    model = CASRegister()
    # SURVEY.md §5.1: jax.profiler traces for the checker kernel itself.
    # Opt-in (BENCH_PROFILE=<dir> or --profile <dir>) so the driver's plain
    # `python bench.py` stays fast; view with tensorboard/xprof.
    profile_dir = os.environ.get("BENCH_PROFILE")
    if "--profile" in sys.argv:
        profile_dir = sys.argv[sys.argv.index("--profile") + 1]
    if profile_dir:
        with jax.profiler.trace(profile_dir):
            corpus = bench_corpus(model)
        print(f"# profiler trace written to {profile_dir}",
              file=sys.stderr)
    else:
        corpus = bench_corpus(model)
    longs = [bench_long(model, n, oracle_too=(n <= 1000)) for n in LONG_OPS]
    gset = bench_gset_corpus()

    kernel_eps = corpus["events"] / corpus["kernel_s"]
    oracle_eps = corpus["events"] / corpus["oracle_s"]
    print(json.dumps({
        "metric": "wgl_check_throughput",
        "value": round(kernel_eps, 1),
        "unit": "history-events/sec",
        "vs_baseline": round(kernel_eps / oracle_eps, 2),
        "detail": {
            "device": str(jax.devices()[0]),
            "corpus": CORPUS,
            "ops_per_history": N_OPS,
            "batch_wall_s": round(corpus["kernel_s"], 4),
            "oracle_wall_s": round(corpus["oracle_s"], 4),
            "histories_per_sec": round(corpus["histories_per_sec"], 2),
            "configs_per_sec": round(corpus["configs_per_sec"], 1),
            "kernel": corpus["kernel"],
            "k_slots": corpus["k_slots"],
            "table_cells": corpus["table_cells"],
            "long_history": [
                {k: (round(v, 4) if isinstance(v, float) else v)
                 for k, v in d.items()} for d in longs],
            "gset_corpus": gset,
        },
    }))


if __name__ == "__main__":
    main()
