"""Benchmark: WGL linearizability checking throughput, TPU kernel vs CPU oracle.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference publishes no benchmark numbers (BASELINE.md): its checker is
knossos's JVM search, which this build replaces with the JAX/XLA kernel. The
baseline stand-in is therefore this repo's pure-Python oracle WGL checker
(checkers/oracle.py — same algorithm, same event encoding, host CPU), playing
the role of the JVM hot loop. vs_baseline = kernel events/sec ÷ oracle
events/sec on the same histories.

Workload: a corpus of fuzzed single-register histories (valid by
construction — the checker must run to completion, the worst case for the
search) checked by the vmapped batch kernel on one chip, plus one long
history through the single-history kernel.
"""

from __future__ import annotations

import json
import random
import time

import numpy as np


N_OPS = 150           # ops per history (tutorial run scale, BASELINE configs[0])
N_PROCS = 10          # concurrency, matching the reference's 10 threads/key
K_SLOTS = 24          # pending-op slot capacity (<=28 enables packed dedup)
F_CAP = 2048          # frontier capacity (dense 10-proc frontiers reach ~2k)
CORPUS = 64           # histories per batched launch
REPEATS = 3


def build_corpus():
    from jepsen_etcd_demo_tpu.ops.encode import (encode_register_history,
                                                 encode_return_steps)
    from jepsen_etcd_demo_tpu.utils.fuzz import gen_register_history

    rng = random.Random(0xBE7C)
    # p_info low: every :info op stays pending forever and occupies a slot
    # for the rest of the history (knossos semantics), so long histories
    # need them rare (or a wide slot table).
    encs = [encode_register_history(
        gen_register_history(rng, n_ops=N_OPS, n_procs=N_PROCS,
                             p_info=0.002), k_slots=K_SLOTS)
        for _ in range(CORPUS)]
    steps = [encode_return_steps(e) for e in encs]
    r_cap = max(s.slot_tabs.shape[0] for s in steps)
    padded = [s.padded_to(r_cap) for s in steps]
    tabs = np.stack([p.slot_tabs for p in padded])
    act = np.stack([p.slot_active for p in padded])
    tgt = np.stack([p.targets for p in padded])
    return encs, (tabs, act, tgt)


def main():
    import jax
    import jax.numpy as jnp

    from jepsen_etcd_demo_tpu.checkers.oracle import check_events_oracle
    from jepsen_etcd_demo_tpu.models import CASRegister
    from jepsen_etcd_demo_tpu.ops import wgl

    from jepsen_etcd_demo_tpu.ops import wgl2

    model = CASRegister()
    encs, (tabs, act, tgt) = build_corpus()
    total_events = int(sum(e.n_events for e in encs))

    # --- TPU (or whatever jax.devices() gives) batched v2 kernel ---
    max_value = max(e.max_value for e in encs)
    cfg = wgl2.make_config(model, K_SLOTS, F_CAP, max_value)
    check = wgl2.make_batch_checker2(model, cfg)
    args = tuple(jax.device_put(jnp.asarray(a)) for a in (tabs, act, tgt))
    out = check(*args)  # compile + warmup
    survived = np.asarray(out["survived"])
    assert survived.all(), "bench corpus must be valid by construction"
    best = float("inf")
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        out = check(*args)
        # NB np.asarray (a real device fetch): block_until_ready does not
        # reliably block under the tunneled TPU backend.
        np.asarray(out["survived"])
        best = min(best, time.perf_counter() - t0)
    kernel_eps = total_events / best

    # --- CPU oracle baseline (the JVM-checker stand-in) ---
    t0 = time.perf_counter()
    for enc in encs:
        res = check_events_oracle(enc, model)
        assert res.valid
    oracle_s = time.perf_counter() - t0
    oracle_eps = total_events / oracle_s

    print(json.dumps({
        "metric": "wgl_check_throughput",
        "value": round(kernel_eps, 1),
        "unit": "history-events/sec",
        "vs_baseline": round(kernel_eps / oracle_eps, 2),
        "detail": {
            "device": str(jax.devices()[0]),
            "corpus": CORPUS,
            "ops_per_history": N_OPS,
            "batch_wall_s": round(best, 4),
            "oracle_wall_s": round(oracle_s, 4),
            "histories_per_sec": round(CORPUS / best, 2),
        },
    }))


if __name__ == "__main__":
    main()
