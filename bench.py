"""Benchmark: WGL linearizability checking throughput, TPU kernel vs CPU oracle.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "detail": {...}}

The reference publishes no benchmark numbers (BASELINE.md): its checker is
knossos's JVM search, which this build replaces with the JAX/XLA kernels. The
baseline stand-in is therefore this repo's pure-Python oracle WGL checker
(checkers/oracle.py — same algorithm, same event encoding, host CPU), playing
the role of the JVM hot loop. vs_baseline = kernel events/sec ÷ oracle
events/sec on the same histories.

The oracle denominator is PINNED (VERDICT r2 weak #2): the first run on a
host measures the oracle once per corpus signature and records it in
bench_baseline.json (committed); later runs reuse the recorded seconds, so
vs_baseline is comparable round over round instead of wobbling with host
load. Delete the file (or change the corpus constants) to re-pin.

Workloads:
  * corpus — 1024 fuzzed 150-op cas-register histories (valid by
    construction: the checker must run to completion, the worst case for
    the search), checked in ONE batched launch of the dense lattice kernel
    (ops/wgl3.py) on one chip. BASELINE.json configs[2]/[4] (independent
    keys as one vmap, corpus-replay scale). On TPU the lane also reports a
    roofline estimate (see _roofline).
  * long history — 1k-op and 10k-op single-register histories through the
    single-history dense kernel (BASELINE.json configs[3]; north star:
    10k ops < 60 s where knossos-CPU DNFs). BENCH_100K=1 adds a 100k-op
    lane (minutes); its result is cached in bench_100k.json and merged
    into the detail on every subsequent run.
  * gset corpus — 256 grow-only-set histories through the same batched
    kernel (model-family lane, models/gset.py).
"""

from __future__ import annotations

import json
import os
import random
import sys
import time
from pathlib import Path

import numpy as np

N_OPS = 150           # ops per history (tutorial run scale, BASELINE configs[0])
N_PROCS = 10          # concurrency, matching the reference's 10 threads/key
CORPUS = 1024         # histories per batched launch — the full corpus-replay
#                       scale (BASELINE configs[4]: 1024 stored histories)
REPEATS = 3
LONG_OPS = (1_000, 10_000)

BASELINE_FILE = Path(__file__).parent / "bench_baseline.json"
LONG100K_FILE = Path(__file__).parent / "bench_100k.json"

# Peak numbers for the roofline estimate, per jax device-kind prefix.
# v5e public specs: 197 bf16 TFLOP/s over 4 128x128 MXUs -> ~1.5 GHz core
# clock; the VPU is 8 sublanes x 128 lanes x 4 ALUs at that clock
# => ~6.1e12 int32 word-ops/s. HBM 819 GB/s. The roofline ALSO reports
# utilization against a MEASURED int32 ALU peak (_peak_microbench): the
# spec number assumes every ALU issue slot takes int ops, which this
# hardware does not sustain (~3.4e12 measured), so the spec percentage
# understates real utilization.
PEAKS = {
    "TPU v5": {"vpu_word_ops": 6.1e12, "hbm_Bps": 8.19e11},
}


def _device_seconds(fn) -> float | None:
    """Device-busy seconds for one call of fn (device-side events of a
    jax.profiler trace). On the tunneled axon backend wall time carries a
    fixed ~0.1 s dispatch+fetch round trip that is NOT kernel time
    (VERDICT r3 item 1) — this is the honest kernel denominator. Returns
    None when no device events are captured (CPU backend)."""
    import glob
    import gzip
    import shutil
    import tempfile

    import jax

    tmp = tempfile.mkdtemp(prefix="benchprof")
    try:
        with jax.profiler.trace(tmp):
            fn()
        traces = glob.glob(f"{tmp}/plugins/profile/*/*.trace.json.gz")
        if not traces:
            return None
        with gzip.open(traces[0]) as f:
            d = json.load(f)
        pids = {e["pid"]: e["args"].get("name", "")
                for e in d.get("traceEvents", [])
                if e.get("ph") == "M" and e.get("name") == "process_name"}
        dev_us = sum(
            e.get("dur", 0) for e in d["traceEvents"]
            if e.get("ph") == "X" and "TPU" in pids.get(e["pid"], "")
            and not e.get("name", "").startswith("jit_"))
        return dev_us / 1e6 if dev_us else None
    except (OSError, ValueError, KeyError):
        return None
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _peak_microbench() -> float | None:
    """Measured int32 VPU word-ops/s ceiling: a pallas kernel of 8
    independent 4-op ALU chains on resident vregs (no memory traffic, no
    reduces — the best case for this kernel family's op mix). Pinned in
    bench_baseline.json per device kind; delete the entry to re-measure."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    kind = jax.devices()[0].device_kind
    try:
        rec = json.loads(BASELINE_FILE.read_text())["peak_microbench"]
        if rec.get("device_kind") == kind:
            return rec["word_ops_per_s"]
    except (OSError, ValueError, KeyError):
        pass

    # 5 vector ALU ops per chain-iteration: xor, shift, or, and, add.
    ITERS, UNROLL, OPS, SP, W = 200_000, 8, 5, 8, 128

    def kernel(x_ref, o_ref):
        def body(i, accs):
            out = []
            for a in accs:
                a = a ^ jnp.uint32(0x9E3779B9)
                a = a | (a << jnp.uint32(1))
                a = a & jnp.uint32(0x7FFFFFFF)
                a = a + jnp.uint32(i)
                out.append(a)
            return tuple(out)
        accs = tuple(x_ref[...] + jnp.uint32(k) for k in range(UNROLL))
        accs = jax.lax.fori_loop(0, ITERS, body, accs)
        acc = accs[0]
        for a in accs[1:]:
            acc = acc | a
        o_ref[...] = acc

    @jax.jit
    def run(x):
        return pl.pallas_call(
            kernel, out_shape=jax.ShapeDtypeStruct((SP, W), jnp.uint32))(x)

    x = jnp.asarray(np.arange(SP * W, dtype=np.uint32).reshape(SP, W))
    np.asarray(run(x))  # compile
    dev_s = _device_seconds(lambda: np.asarray(run(x)))
    if not dev_s:
        return None
    peak = ITERS * UNROLL * OPS * SP * W / dev_s
    try:
        data = json.loads(BASELINE_FILE.read_text())
    except (OSError, ValueError):
        data = {}
    data["peak_microbench"] = {
        "device_kind": kind, "word_ops_per_s": round(peak, -9),
        "pinned_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())}
    BASELINE_FILE.write_text(json.dumps(data, indent=2) + "\n")
    print(f"# pinned measured VPU peak {peak/1e12:.2f} T word-ops/s -> "
          f"{BASELINE_FILE.name} (commit it)", file=sys.stderr)
    return peak


def build_corpus():
    from jepsen_etcd_demo_tpu.ops.encode import encode_register_history
    from jepsen_etcd_demo_tpu.utils.fuzz import gen_register_history

    rng = random.Random(0xBE7C)
    # p_info low: every :info op stays pending forever and occupies a slot
    # for the rest of the history (knossos semantics), so long histories
    # need them rare (or a wide slot table).
    return [encode_register_history(
        gen_register_history(rng, n_ops=N_OPS, n_procs=N_PROCS,
                             p_info=0.002), k_slots=32)
        for _ in range(CORPUS)]


def _signature(lane: str, encs) -> dict:
    """Cheap content signature binding a pinned oracle time to the exact
    corpus (seed/constants drift re-pins automatically)."""
    return {
        "lane": lane, "histories": len(encs),
        "events": int(sum(e.n_events for e in encs)),
        "checksum": int(sum(int(np.sum(e.events[: e.n_events],
                                       dtype=np.int64)) for e in encs)
                        & 0x7FFFFFFF),
    }


def _pinned_oracle(lane: str, sig: dict):
    try:
        rec = json.loads(BASELINE_FILE.read_text())[lane]
    except (OSError, ValueError, KeyError):
        return None
    return rec["oracle_s"] if rec.get("sig") == sig else None


def _pin_oracle(lane: str, sig: dict, oracle_s: float) -> None:
    try:
        data = json.loads(BASELINE_FILE.read_text())
    except (OSError, ValueError):
        data = {}
    data[lane] = {"sig": sig, "oracle_s": round(oracle_s, 4),
                  "pinned_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                             time.gmtime())}
    BASELINE_FILE.write_text(json.dumps(data, indent=2) + "\n")
    print(f"# pinned {lane} oracle baseline {oracle_s:.2f}s -> "
          f"{BASELINE_FILE.name} (commit it)", file=sys.stderr)


def _roofline(device_kind: str, cfg, steps, r_pad: int, batch: int,
              kernel_s: float, device_s: float | None = None,
              measured_peak: float | None = None,
              min_sweeps: int = 2) -> dict | None:
    """Lower-bound hardware-utilization estimate for the dense batched
    launch (VERDICT r2 missing #4; r3 item 1 split the denominator). Two
    ceilings:

      * HBM: the fused pallas kernel keeps the table in VMEM; its HBM
        traffic is the streamed colmask blocks (+ the prefetched targets),
        which is exactly computable from the launch shape.
      * VPU: word-ops are modeled from the guaranteed work — min_sweeps
        closure sweeps per real step of K slots x (2S+3) word-ops over
        the Sp x W table. min_sweeps is 2 for the grouped kernel (two
        unconditional sweeps per step) but 1 for the per-history kernel,
        whose first-sweep-silent steps stop after one sweep. Real sweeps
        can exceed the minimum, so vpu_pct is a LOWER bound.

    Utilization is computed on DEVICE time when a profiler measurement is
    available (wall carries the tunneled backend's fixed ~0.1 s
    dispatch+fetch round trip), against BOTH the spec-sheet peak and the
    pinned measured int32 ALU peak (_peak_microbench — the honest ceiling
    for this op mix). roofline_pct stays the spec-peak wall-time figure
    for round-over-round comparability; roofline_pct_device /
    roofline_pct_measured are the sharper views."""
    peaks = next((v for k, v in PEAKS.items() if device_kind.startswith(k)),
                 None)
    if peaks is None:
        return None
    S, K = cfg.n_states, cfg.k_slots
    sp = max(8, (S + 7) // 8 * 8)
    w = 1 << (K - 5)
    real_steps = int(sum(s.n_steps for s in steps))
    colmask_bytes = batch * r_pad * sp * 128 * 4 + batch * r_pad * 4
    word_ops = real_steps * min_sweeps * K * (2 * S + 3) * sp * w
    hbm_pct = colmask_bytes / kernel_s / peaks["hbm_Bps"] * 100
    vpu_pct = word_ops / kernel_s / peaks["vpu_word_ops"] * 100
    out = {
        "achieved_hbm_GBps": round(colmask_bytes / kernel_s / 1e9, 2),
        "achieved_word_Gops": round(word_ops / kernel_s / 1e9, 2),
        "hbm_pct": round(hbm_pct, 2),
        "vpu_pct_lower_bound": round(vpu_pct, 2),
        "roofline_pct": round(max(hbm_pct, vpu_pct), 2),
        "peaks_assumed": {"vpu_word_ops": peaks["vpu_word_ops"],
                          "hbm_Bps": peaks["hbm_Bps"]},
    }
    if device_s:
        out["device_s"] = round(device_s, 4)
        out["dispatch_fetch_s"] = round(max(0.0, kernel_s - device_s), 4)
        out["roofline_pct_device"] = round(
            word_ops / device_s / peaks["vpu_word_ops"] * 100, 2)
        if measured_peak:
            out["vpu_word_ops_measured"] = measured_peak
            out["roofline_pct_measured"] = round(
                word_ops / device_s / measured_peak * 100, 2)
    return out


def _measure_corpus(lane, encs, model):
    """Shared measurement harness for batched-corpus lanes: one batched
    launch via the production routing point (wgl3_pallas dispatch), best
    of REPEATS with ONE packed device->host fetch per launch (per-fetch
    round trips dominate wall time on tunneled backends), then the PINNED
    oracle denominator (measured once per corpus signature, reused after).
    The corpus must be valid by construction (the checker runs to
    completion — the search's worst case)."""
    import jax

    from jepsen_etcd_demo_tpu.checkers.oracle import check_events_oracle
    from jepsen_etcd_demo_tpu.ops import wgl3, wgl3_pallas

    cfg, steps, r_cap = wgl3.batch_steps3(encs, model)
    arrays = wgl3.stack_steps3(steps, r_cap)
    check, kernel_name = wgl3_pallas.packed_batch_checker(
        model, cfg, n_steps=r_cap, batch=len(encs))
    out = wgl3.unpack_np(check(*arrays))  # compile + warmup
    assert out["survived"].all(), "bench corpus must be valid by construction"
    best = float("inf")
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        out = wgl3.unpack_np(check(*arrays))
        best = min(best, time.perf_counter() - t0)

    sig = _signature(lane, encs)
    oracle_s = _pinned_oracle(lane, sig)
    pinned = oracle_s is not None
    if not pinned:
        t0 = time.perf_counter()
        for enc in encs:
            assert check_events_oracle(enc, model).valid
        oracle_s = time.perf_counter() - t0
        _pin_oracle(lane, sig, oracle_s)
    m = {
        "kernel_s": best,
        "oracle_s": oracle_s,
        "oracle_pinned": pinned,
        "kernel": kernel_name,
        "k_slots": cfg.k_slots,
        "table_cells": cfg.n_states * cfg.n_masks,
        # §5.1 checker metric: configs explored per second of kernel wall
        # time (the search's unit of work; the oracle reports the same
        # counter for an apples-to-apples view).
        "configs_per_sec": float(out["configs_explored"].sum()) / best,
    }
    kind = jax.devices()[0].device_kind
    if any(kind.startswith(k) for k in PEAKS):
        # Profiled launch + peak microbench only when a roofline will
        # actually be emitted for this device kind.
        device_s = _device_seconds(lambda: wgl3.unpack_np(check(*arrays)))
        measured_peak = _peak_microbench() if device_s else None
        roof = _roofline(kind, cfg, steps, r_cap, len(encs), best,
                         device_s, measured_peak,
                         min_sweeps=2 if "grouped" in kernel_name else 1)
        if roof:
            if lane == "register_corpus":
                roof["dispatch_floor"] = _dispatch_floor(
                    model, cfg, steps, r_cap, best,
                    roof.get("device_s"))
            m["roofline"] = roof
    return m


def _dispatch_floor(model, cfg, steps, r_cap, batch_wall_s, device_s):
    """VERDICT r4 next #1: attack the dispatch/fetch share of the corpus
    wall, or prove it irreducible WITH A MEASUREMENT. Two probes:

      * empty_launch_s — round trip of an already-compiled trivial
        launch + one-word fetch: the true per-launch floor of this
        backend (on the axon tunnel ~0.10 s — MORE than the entire
        wall-minus-device gap, i.e. the single batched launch is already
        at the floor).
      * pipelined_2wave_s — the corpus split into two sub-batches,
        both dispatched before any fetch. On a backend whose dispatch
        overlapped, this would hide host prep under device compute; on
        the tunnel each launch pays its own serialized RT (measured
        ~2x single-launch wall), so wave-splitting REGRESSES and the
        production path stays one launch.

    floor_irreducible is the recorded conclusion:
    empty_launch_s >= (batch_wall_s - device_s), i.e. the non-device
    share of the wall is within one empty round trip — nothing above
    the floor is left to hide."""
    from jepsen_etcd_demo_tpu.ops import wgl3, wgl3_pallas
    from jepsen_etcd_demo_tpu.ops.calibrate import measure_dispatch_floor

    empty = measure_dispatch_floor()
    B = len(steps) // 2
    waves = [wgl3.stack_steps3(steps[i * B:(i + 1) * B], r_cap)
             for i in range(2)]
    check, _ = wgl3_pallas.packed_batch_checker(model, cfg, n_steps=r_cap,
                                                batch=B)
    wgl3.unpack_np(check(*waves[0]))            # compile the wave shape
    wall2 = float("inf")
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        outs = [check(*w) for w in waves]       # dispatch both, no fetch
        for o in outs:
            wgl3.unpack_np(o)                   # then fetch
        wall2 = min(wall2, time.perf_counter() - t0)
    non_device = (batch_wall_s - device_s) if device_s else None
    return {
        "empty_launch_s": round(empty, 4),
        "pipelined_2wave_s": round(wall2, 4),
        "floor_irreducible": (None if non_device is None
                              else bool(empty >= non_device)),
    }


def bench_corpus(model):
    encs = build_corpus()
    m = _measure_corpus("register_corpus", encs, model)
    m["events"] = int(sum(e.n_events for e in encs))
    m["histories_per_sec"] = CORPUS / m["kernel_s"]
    return m


def bench_gset_corpus():
    """Model-family lane: 256 grow-only-set histories through the same
    batched dense kernel (models/gset.py — the set state is its int32
    bitmask, 32-state table). Proves the family kernels run at corpus
    scale, not only under test geometries."""
    from jepsen_etcd_demo_tpu.models import GSet
    from jepsen_etcd_demo_tpu.ops.encode import encode_history
    from jepsen_etcd_demo_tpu.utils.fuzz import gen_gset_history

    model = GSet()
    rng = random.Random(0x65E7)
    encs = [encode_history(
        gen_gset_history(rng, n_ops=N_OPS, n_procs=N_PROCS, p_info=0.002),
        model, k_slots=32) for _ in range(256)]
    m = _measure_corpus("gset_corpus", encs, model)
    return {"histories": len(encs), "kernel_s": round(m["kernel_s"], 4),
            "oracle_s": round(m["oracle_s"], 4),
            "oracle_pinned": m["oracle_pinned"], "kernel": m["kernel"],
            "table_cells": m["table_cells"]}


def build_mixed_corpus(n_hist: int = 256, ops_range=(20, 300),
                       seed: int = 0x5EDC):
    """Mixed-length register corpus for the bucketed-scheduler lane: the
    length spread is the whole point (a uniform corpus has nothing to
    bucket)."""
    from jepsen_etcd_demo_tpu.ops.encode import encode_register_history
    from jepsen_etcd_demo_tpu.utils.fuzz import gen_register_history

    rng = random.Random(seed)
    lo, hi = ops_range
    return [encode_register_history(
        gen_register_history(rng, n_ops=rng.randrange(lo, hi),
                             n_procs=N_PROCS, p_info=0.002), k_slots=32)
        for _ in range(n_hist)]


def bench_sched_corpus(model, n_hist: int = 256, ops_range=(20, 300)) -> dict:
    """Corpus-throughput lane (ISSUE 2 tentpole): a mixed-length corpus
    through the bucketed scheduler (sched/engine.py), cold then warm.

    Reports events/s, the MEASURED padding-waste ratio (padded/real
    steps across the scheduled launches) next to the counterfactual
    pad-to-max ratio the old single-launch path would have paid, the
    scheduler kernel-LRU hit rate on the warm pass, and the warm pass's
    kernel-phase breakdown — whose compile_s must be 0 when every bucket
    shape was already compiled (the acceptance check
    tests/test_bench_smoke.py pins on a tiny corpus). Runs under its own
    telemetry captures (nested captures shadow the bench-wide one), so
    the lane's numbers are self-contained."""
    from jepsen_etcd_demo_tpu import obs, sched
    from jepsen_etcd_demo_tpu.ops import wgl3
    from jepsen_etcd_demo_tpu.ops.encode import EV_RETURN

    encs = build_mixed_corpus(n_hist, ops_range)
    with obs.capture() as cold_cap:
        t0 = time.perf_counter()
        results, kernel, stats = sched.check_corpus(encs, model)
        cold_s = time.perf_counter() - t0
    assert all(r["valid"] is True for r in results), \
        "sched corpus must be valid by construction"
    with obs.capture() as warm_cap:
        w0_ns = time.monotonic_ns()
        t0 = time.perf_counter()
        results2, kernel, _stats2 = sched.check_corpus(encs, model)
        warm_s = time.perf_counter() - t0
        w1_ns = time.monotonic_ns()
    assert results2 == results, "sched corpus must be deterministic"
    # Scaling-ledger attribution of the warm pass (ISSUE 16): the loss
    # buckets must explain >=95% of the measured wall, and the ledger
    # itself must cost <2% — measured against a ledger-off control arm.
    # Interleaved best-of-3 per arm: min is the robust estimator at the
    # tiny tier-1 corpus scale, alternation cancels machine-load drift
    # across the measurement, and the absolute floor absorbs what's
    # left of the timer noise.
    ledger_att = warm_cap.ledger.attribution(t0_ns=w0_ns, t1_ns=w1_ns)
    assert ledger_att["coverage"] >= 0.95, \
        f"ledger buckets explain only {ledger_att['coverage']:.1%} " \
        f"of the warm sched pass"

    def _warm_pass(with_ledger: bool) -> float:
        with obs.capture(with_ledger=with_ledger):
            p0 = time.perf_counter()
            sched.check_corpus(encs, model)
            return time.perf_counter() - p0

    on_s, off_s = warm_s, float("inf")
    for _ in range(3):
        on_s = min(on_s, _warm_pass(True))
        off_s = min(off_s, _warm_pass(False))
    overhead_pct = 100.0 * (on_s - off_s) / off_s if off_s > 0 else 0.0
    assert on_s <= off_s * 1.02 + 0.05, \
        f"ledger overhead {overhead_pct:.1f}% exceeds the 2% bound " \
        f"(on={on_s:.4f}s off={off_s:.4f}s)"

    events = int(sum(e.n_events for e in encs))
    rets = [int((e.events[: e.n_events, 0] == EV_RETURN).sum())
            for e in encs]
    real = sum(rets)
    pad_to_max = (len(rets) * wgl3.step_bucket(max(rets)) / real
                  if real else 0.0)
    warm_sched = obs.sched_stats(warm_cap.metrics)
    return {
        "histories": n_hist,
        "events": events,
        "cold_s": round(cold_s, 4),
        "warm_s": round(warm_s, 4),
        "events_per_sec": round(events / warm_s, 1),
        "kernel": kernel,
        "launches": stats["launches"],
        "buckets": stats["buckets"],
        "padding_waste": stats["padding_waste"],
        "padding_waste_pad_to_max": round(pad_to_max, 4),
        "cache_hit_rate": warm_sched["cache_hit_rate"],
        "kernel_phases": obs.kernel_phases(warm_cap.metrics),
        "kernel_phases_cold": obs.kernel_phases(cold_cap.metrics),
        "ledger": ledger_att,
        "ledger_overhead_pct": round(max(0.0, overhead_pct), 2),
    }


def bench_sparse(model, n_ops: int = 150, k_slots: int = 20) -> dict:
    """Sparse active-tile lane (ISSUE 3 tentpole): ONE long register
    history reslotted to a WIDE table (k_slots beyond its real
    concurrency — the regime where the dense sweep wastes 2^K work on a
    tiny frontier), run through the chunked dense sweep under
    limits().sparse_mode pinned to dense-only (1) then prefer-sparse
    (2). Verdicts are asserted bit-identical; the lane reports events/s
    for BOTH modes, the measured live-tile ratio, and the sweep-mode
    step counts — the direction-optimizing win measured, not asserted.
    CPU-provable (tests/test_bench_smoke.py runs a tiny geometry), so
    the degraded rerun keeps the lane."""
    from dataclasses import replace

    from jepsen_etcd_demo_tpu.ops import wgl3
    from jepsen_etcd_demo_tpu.ops.encode import (encode_register_history,
                                                 encode_return_steps,
                                                 reslot_events)
    from jepsen_etcd_demo_tpu.ops.limits import limits, set_limits
    from jepsen_etcd_demo_tpu.utils.fuzz import gen_register_history

    rng = random.Random(0x5BA5 + n_ops)
    h = gen_register_history(rng, n_ops=n_ops, n_procs=N_PROCS,
                             p_info=0.002)
    enc = encode_register_history(h, k_slots=32)
    cfg = wgl3.dense_config(model, k_slots, enc.max_value, budget=1 << 28)
    assert cfg is not None, (k_slots, enc.max_value)
    enc = reslot_events(enc, k_slots) if enc.k_slots != k_slots else enc
    rs = encode_return_steps(enc)
    events = enc.n_events
    lane = {"ops": n_ops, "events": events, "k_slots": k_slots,
            "table_cells": cfg.n_states * cfg.n_masks}
    results = {}
    for mode, name in ((1, "dense"), (2, "sparse")):
        prev = set_limits(replace(limits(), sparse_mode=mode))
        try:
            wgl3.check_steps3_long(rs, model, cfg)        # compile/warm
            best = float("inf")
            for _ in range(REPEATS):
                t0 = time.perf_counter()
                out = wgl3.check_steps3_long(rs, model, cfg)
                best = min(best, time.perf_counter() - t0)
        finally:
            set_limits(prev)
        results[name] = out
        lane[f"{name}_s"] = round(best, 4)
        lane[f"{name}_events_per_sec"] = round(events / best, 1)
    sp = results["sparse"]
    for f in ("valid", "survived", "dead_step", "max_frontier",
              "configs_explored"):
        assert results["dense"][f] == sp[f], \
            f"sparse/dense verdict drift on {f}: {results}"
    lane["live_tile_ratio"] = sp.get("live_tile_ratio", -1.0)
    lane["sweep"] = sp.get("sweep", {})
    lane["kernel"] = sp.get("kernel", "")
    lane["speedup_vs_dense"] = (round(lane["dense_s"] / lane["sparse_s"], 2)
                                if lane["sparse_s"] else 0.0)
    return lane


def bench_dedup(model, n_ops: int = 600, k_slots: int = 16,
                sort_ops: int = 300) -> dict:
    """Frontier-dedup lane (ISSUE 10 tentpole), two arms over
    symmetry-heavy fixtures (small value domains + forever-pending
    populations, so equal-effect pending-op classes really exist), each
    run dedup-OFF (dedup_mode=1) then dedup-ON:

      * SORT arm — the GATED measurement (off/on_events_per_sec,
        tools/bench_compare.py): one single-value-domain history whose
        crashed ops interleave factorially, through the resumable sort
        ladder (wgl2.check_steps_resumable), where frontier size
        directly drives cost. Canonicalization collapses C(n,k)
        symmetric masks to n+1, avoiding whole 4x capacity escalations
        — measured 4.1x on the CPU backend at this scale.
      * TABLE arm — informational: the chunked dense sweep under
        dedup_mode=2 (the table passes canonicalize under force/tuned
        profiles only — a table sweep's cost is fixed in the table
        size). Reports the measured frontier_dedup_ratio, the pruned
        count, and raw (dedup-off) vs UNIQUE (canonical) configs/s as
        SEPARATE numbers, so the headline configs metric cannot
        silently improve by pruning.

    Verdict fields are asserted identical in both arms in both modes
    (canonicalization is a verdict-preserving quotient, ops/canon.py)."""
    from dataclasses import replace

    from jepsen_etcd_demo_tpu.ops import wgl2, wgl3
    from jepsen_etcd_demo_tpu.ops.encode import (encode_register_history,
                                                 encode_return_steps,
                                                 reslot_events)
    from jepsen_etcd_demo_tpu.ops.limits import limits, set_limits
    from jepsen_etcd_demo_tpu.utils.fuzz import gen_register_history

    def sym_steps(n, value_range, k_floor):
        # p_info is the symmetry dial AND the slot-pressure dial:
        # crashed ops accumulate for the whole history, so the rate
        # scales as ~6 (table) / ~15 (sort) expected crashes per run;
        # the slot width rides the history's real concurrency.
        rng = random.Random(0xDED1 + n)
        h = gen_register_history(rng, n_ops=n, n_procs=8,
                                 value_range=value_range,
                                 p_info=(6.0 if value_range > 1 else 15.0)
                                 / n)
        enc = encode_register_history(h, k_slots=32)
        k = max(k_floor, wgl3.tight_k_slots(enc))
        enc = reslot_events(enc, k) if enc.k_slots != k else enc
        return enc, encode_return_steps(enc), k

    def timed(fn):
        fn()                                   # compile/warm
        best = float("inf")
        for _ in range(REPEATS):
            t0 = time.perf_counter()
            out = fn()
            best = min(best, time.perf_counter() - t0)
        return best, out

    # -- sort arm (gated) --------------------------------------------
    enc_s, rs_s, _k = sym_steps(sort_ops, value_range=1, k_floor=8)
    lane = {"sort_ops": sort_ops, "sort_events": enc_s.n_events}
    sort_res = {}
    for mode, name in ((1, "off"), (2, "on")):
        prev = set_limits(replace(limits(), dedup_mode=mode))
        try:
            best, out = timed(lambda: wgl2.check_steps_resumable(
                rs_s, model, f_cap=64))
        finally:
            set_limits(prev)
        sort_res[name] = out
        lane[f"{name}_s"] = round(best, 4)
        lane[f"{name}_events_per_sec"] = round(enc_s.n_events / best, 1)
    for f in ("valid", "survived", "dead_step"):
        assert sort_res["off"][f] == sort_res["on"][f], \
            f"dedup sort-arm verdict drift on {f}: {sort_res}"
    lane["sort_f_cap_off"] = sort_res["off"]["f_cap"]
    lane["sort_f_cap_on"] = sort_res["on"]["f_cap"]
    lane["sort_escalations_off"] = sort_res["off"]["escalations"]
    lane["sort_escalations_on"] = sort_res["on"]["escalations"]
    lane["speedup_vs_off"] = (round(lane["off_s"] / lane["on_s"], 3)
                              if lane["on_s"] else 0.0)

    # -- table arm (informational) -----------------------------------
    enc_t, rs_t, k = sym_steps(n_ops, value_range=2, k_floor=k_slots)
    cfg = wgl3.dense_config(model, k, max(enc_t.max_value, 4),
                            budget=1 << 28)
    assert cfg is not None, (k, enc_t.max_value)
    events = enc_t.n_events
    lane.update({"ops": n_ops, "events": events, "k_slots": k,
                 "table_cells": cfg.n_states * cfg.n_masks})
    table_res = {}
    for mode, name in ((1, "off"), (2, "on")):
        prev = set_limits(replace(limits(), dedup_mode=mode,
                                  sparse_mode=1))
        try:
            best, out = timed(lambda: wgl3.check_steps3_long(
                rs_t, model, cfg))
        finally:
            set_limits(prev)
        table_res[name] = out
        lane[f"table_{name}_s"] = round(best, 4)
    off, on = table_res["off"], table_res["on"]
    for f in ("valid", "survived", "overflow", "dead_step"):
        assert off[f] == on[f], \
            f"dedup table-arm verdict drift on {f}: {table_res}"
    dd = on.get("dedup", {})
    assert dd.get("configs_pruned", 0) > 0, \
        f"symmetry-heavy corpus pruned nothing: {on}"
    lane["frontier_dedup_ratio"] = dd.get("frontier_dedup_ratio", 0.0)
    lane["configs_pruned"] = dd.get("configs_pruned", 0)
    # Raw vs unique configs/s, REPORTED SEPARATELY: raw counts the
    # dedup-off search's work, unique the canonical frontier's — gating
    # stays on the sort arm's events/s (bench_compare treats the
    # configs rates as informational).
    lane["raw_configs_per_sec"] = round(
        off["configs_explored"] / lane["table_off_s"], 1) \
        if lane["table_off_s"] else 0
    lane["unique_configs_per_sec"] = round(
        on["configs_explored"] / lane["table_on_s"], 1) \
        if lane["table_on_s"] else 0
    lane["max_frontier_off"] = off["max_frontier"]
    lane["max_frontier_on"] = on["max_frontier"]
    return lane


def bench_tuned(model, n_hist: int = 128, ops_range=(20, 300)) -> dict:
    """Tuned-profile lane (ISSUE 4 tentpole): ONE mixed-length corpus
    through the bucketed scheduler under the DATACLASS-DEFAULT limits
    profile, then under this platform's persisted tuning profile
    (tune/profile.py — whatever `jepsen-tpu tune` measured on this
    machine; the two arms are identical when no profile exists and the
    lane says so). Verdicts are asserted identical between arms — a
    tuned profile reroutes and re-chunks, it must never change an
    answer — and the lane reports both arms' events/s,
    `speedup_vs_default`, and the active profile hash. CPU-provable
    (tests/test_bench_smoke.py), so the degraded rerun keeps it."""
    from jepsen_etcd_demo_tpu import sched
    from jepsen_etcd_demo_tpu.ops.limits import KernelLimits, set_limits
    from jepsen_etcd_demo_tpu.tune import profile as tune_profile

    encs = build_mixed_corpus(n_hist, ops_range, seed=0x7D4E)
    events = int(sum(e.n_events for e in encs))
    # tuned_limits() is None while the platform is undetermined (no
    # initialized backend yet) — treat as "none apply" for the lane.
    tuned_fields = tune_profile.tuned_limits() or {}
    lane = {
        "histories": n_hist,
        "events": events,
        "profile_hash": tune_profile.profile_hash(),
        "tuned_fields": len(tuned_fields),
        "tuned": bool(tuned_fields),
    }
    verdicts = {}
    # set_limits installs a COMPLETE profile (beating the tuned file,
    # ops/limits.py precedence), so the default arm measures the shipped
    # dataclass values even on a machine with a profile; arm two clears
    # the programmatic override so the tuned profile resolves again.
    # set_limits returns the previous programmatic state (None included),
    # so the finally restores exactly what an embedding caller had.
    prev_set = set_limits(KernelLimits())
    try:
        for arm, prof in (("default", KernelLimits()), ("tuned", None)):
            set_limits(prof)
            results, kernel, _stats = sched.check_corpus(encs, model)  # warm
            best = float("inf")
            for _ in range(REPEATS):
                t0 = time.perf_counter()
                results, kernel, _stats = sched.check_corpus(encs, model)
                best = min(best, time.perf_counter() - t0)
            verdicts[arm] = results
            lane[f"{arm}_s"] = round(best, 4)
            lane[f"{arm}_events_per_sec"] = round(events / best, 1)
    finally:
        set_limits(prev_set)
    assert verdicts["default"] == verdicts["tuned"], \
        "tuned profile changed a verdict"
    lane["speedup_vs_default"] = (
        round(lane["default_s"] / lane["tuned_s"], 3)
        if lane["tuned_s"] else 0.0)
    return lane


def bench_serve(model, n_hist: int = 96, clients: int = 8,
                ops_range=(10, 48), n_procs: int = 4,
                coalesce_ms: int = 10, seed: int = 0x5E12E,
                invalid_every: int = 5, min_speedup: float | None = None
                ) -> dict:
    """Checking-as-a-service lane (ISSUE 13 tentpole): K concurrent CPU
    clients against an in-process serve daemon (the CoalescingScheduler
    core, exactly what `jepsen-tpu serve --check` dispatches through)
    vs the serial one-request-at-a-time baseline — SAME histories, SAME
    daemon configuration, daemon restarted between arms. The concurrent
    arm's win is the whole serving thesis: K closed-loop clients fill
    the coalescing window so per-launch dispatch overhead and the
    max-linger amortize across the batch, while the solo client pays
    both on every request (exactly the continuous-batching economics of
    inference serving; on parallel hardware the batched kernel itself
    adds the vectorization win on top — CPU only amortizes overhead).

    The warm pool is shared process state (that is the product), so
    both arms run after a warmup pass that compiles both arms' shapes —
    the lane measures request-path batching, not compile luck. The
    fixture keeps per-history concurrency small and uniform so the
    shared-geometry (max-k) padding of a coalesced batch stays honest
    work on a CPU (no SIMD batch axis to hide it).

    Reports aggregate events/s (gated round-over-round), p50/p99
    request latency and coalesced batch fill (informational), the
    warm-pool hit rate across the concurrent arm, and certifies every
    served verdict bit-identical to the post-hoc analyze route on the
    same encoded histories. A mix of valid and mutated-invalid
    histories keeps the parity check meaningful."""
    import threading

    from jepsen_etcd_demo_tpu import sched
    from jepsen_etcd_demo_tpu.ops import wgl3_pallas
    from jepsen_etcd_demo_tpu.ops.encode import encode_register_history
    from jepsen_etcd_demo_tpu.serve import CoalescingScheduler
    from jepsen_etcd_demo_tpu.utils.fuzz import (gen_register_history,
                                                 mutate_history)

    rng = random.Random(seed)
    lo, hi = ops_range
    encs = []
    for i in range(n_hist):
        hist = gen_register_history(rng, n_ops=rng.randrange(lo, hi),
                                    n_procs=n_procs, p_info=0.002)
        if invalid_every and i % invalid_every == invalid_every - 1:
            hist = mutate_history(rng, hist)
        encs.append(encode_register_history(hist, k_slots=8))
    events = int(sum(e.n_events for e in encs))

    # Post-hoc analyze route (the per-history auto router `analyze`
    # resolves through) — the parity oracle AND the warmup for the
    # serial arm's single-history shapes.
    posthoc = []
    for e in encs:
        outs, _kernel = wgl3_pallas.check_batch_encoded_auto([e], model)
        posthoc.append(outs[0])

    def run_arm(arm_clients: int) -> tuple[float, list, dict]:
        server = CoalescingScheduler(coalesce_ms=coalesce_ms)
        try:
            shards = [encs[i::arm_clients] for i in range(arm_clients)]
            idx_shards = [list(range(n_hist))[i::arm_clients]
                          for i in range(arm_clients)]
            results: list = [None] * n_hist
            errors: list = []

            def client(tenant_i: int):
                # Closed loop: submit, await the verdict, submit the
                # next — K of these concurrently is what the coalescer
                # merges into shared launches.
                try:
                    for idx, enc in zip(idx_shards[tenant_i],
                                        shards[tenant_i]):
                        req = server.submit(f"tenant-{tenant_i}", enc,
                                            model_name=model.name)
                        assert req.wait(300), "serve verdict timed out"
                        results[idx] = req.result
                except Exception as e:   # surfaced below, not swallowed
                    errors.append(e)

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(arm_clients)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            if errors:
                raise errors[0]
            stats = server.stats()
            return wall, results, stats
        finally:
            server.close()

    # Warmup: one throwaway concurrent pass compiles the coalesced
    # batch-bucket shapes the timed concurrent arm will launch.
    run_arm(arm_clients=clients)
    serial_wall, serial_results, _ = run_arm(arm_clients=1)
    cache1 = sched.kernel_cache().stats()
    conc_wall, conc_results, conc_stats = run_arm(arm_clients=clients)
    cache2 = sched.kernel_cache().stats()
    conc_lookups = (cache2["hits"] + cache2["misses"]
                    - cache1["hits"] - cache1["misses"])
    conc_hits = cache2["hits"] - cache1["hits"]

    # Parity: every served verdict (both arms) bit-identical to the
    # post-hoc analyze route on the same encoded history.
    for arm_name, arm in (("serial", serial_results),
                          ("concurrent", conc_results)):
        for i, (srv, post) in enumerate(zip(arm, posthoc)):
            assert srv["valid"] == post["valid"] \
                and srv["dead_step"] == int(post["dead_step"]), \
                (f"serve {arm_name} verdict diverged from analyze at "
                 f"history {i}: {srv['valid']}/{srv['dead_step']} vs "
                 f"{post['valid']}/{post['dead_step']}")

    lats = sorted(r["latency_s"] for r in conc_results)
    agg_eps = events / conc_wall
    serial_eps = events / serial_wall
    speedup = agg_eps / serial_eps if serial_eps else 0.0
    if min_speedup is not None:
        assert speedup >= min_speedup, \
            (f"serve acceptance: aggregate {agg_eps:.0f} ev/s is only "
             f"{speedup:.2f}x the serial baseline {serial_eps:.0f} ev/s "
             f"(need >= {min_speedup}x)")
    return {
        "histories": n_hist,
        "clients": clients,
        "events": events,
        "serial_s": round(serial_wall, 4),
        "concurrent_s": round(conc_wall, 4),
        "events_per_sec": round(agg_eps, 1),
        "serial_events_per_sec": round(serial_eps, 1),
        "speedup_vs_serial": round(speedup, 2),
        "latency_p50_ms": round(1000 * lats[len(lats) // 2], 2),
        "latency_p99_ms": round(
            1000 * lats[min(len(lats) - 1, int(0.99 * len(lats)))], 2),
        "batches": conc_stats["batches"],
        "coalesced_requests": conc_stats["coalesced_requests"],
        "batch_fill_avg": conc_stats["batch_fill_avg"],
        "cache_hit_rate": round(conc_hits / conc_lookups, 4)
        if conc_lookups else 1.0,
        "invalid": sum(1 for r in posthoc if r["valid"] is not True),
        "verdicts_identical": True,
    }


def _fleet_quantile(lats: list[float], q: float) -> float:
    if not lats:
        return 0.0
    s = sorted(lats)
    return s[min(len(s) - 1, int(q * len(s)))]


def fleet_zero_lane() -> dict:
    """The degraded-path fleet record: every contract key present as
    zeros (tools/bench_compare.py check_fleet_record — the same
    zeros-never-absent rule as the ledger object)."""
    arm = {"wall_s": 0.0, "agg_eps": 0.0, "agg_rps": 0.0,
           "p50_s": 0.0, "p99_s": 0.0, "warm_p99_s": 0.0,
           "hit_rate": 0.0, "lookups": 0}
    return {
        "replicas": 0, "histories": 0, "events": 0,
        "affine": dict(arm), "random": dict(arm),
        "hit_rate_delta": 0.0, "agg_eps_ratio": 0.0,
        "knee_rate_rps": 0.0, "agg_eps": 0.0, "p99_s": 0.0,
        "knee_rungs": [], "spillover": 0,
        "replica_fill": {}, "replica_fill_min": 0.0,
        "invalid": 0, "verdicts_identical": False,
    }


def bench_fleet(model, n_hist: int = 48, replicas: int = 2,
                ops_range=(8, 200), n_procs: int = 4,
                seed: int = 0xF1EE7, invalid_every: int = 7,
                max_knee_rungs: int = 4, assert_win: bool = True,
                request_timeout_s: float = 300.0) -> dict:
    """Fleet-scale serving lane (ISSUE 18 tentpole): N subprocess
    `serve --check` replicas behind the in-process shape-affine router
    (serve/router.py), driven OPEN-LOOP — Poisson arrivals at a fixed
    offered rate, the way a production inference fleet is loaded, not
    the closed-loop K-clients of the serve lane (closed loops
    self-throttle at the knee; open loops expose it).

    Two arms on identical corpora, schedules, and fresh fleets:
    *random* routing (the shape-blind control, fleet_spillover_mode=2)
    vs *affine* rendezvous routing. Each arm runs the same Poisson
    schedule twice — a cold pass that pays the compiles its routing
    policy induces, then a warm pass — so the arm aggregate carries the
    structural difference: random compiles ~every bucket on ~every
    replica, affine compiles each bucket once fleet-wide. Replicas run
    with the persistent XLA cache DISABLED (JEPSEN_TPU_NO_COMPILE_CACHE)
    so neither arm can launder its compile bill through the other's
    disk artifacts, and on the CPU backend — two subprocesses cannot
    share one TPU, and the lane measures routing economics (compile
    amortization, LRU locality, spillover), not chip throughput, which
    serve_agg_eps already gates.

    After the arms, an arrival-rate ladder walks the warm affine fleet
    to the latency knee: offered rate doubles per rung until p99
    inflects (> 4x the first rung's) or completions fall behind offered
    (< 0.7x), and the LAST GOOD rung's aggregate events/s and p99 are
    the gated `fleet_agg_eps` / `fleet_p99_s` headline — serving
    capacity at acceptable latency, not peak-burst throughput.

    Every verdict from every arm and pass is asserted bit-identical to
    the post-hoc analyze route; with `assert_win`, affine must beat
    random on whole-arm aggregate events/s AND warm kernel-cache hit
    rate (strictly)."""
    import threading
    import urllib.request

    from http.server import ThreadingHTTPServer

    from jepsen_etcd_demo_tpu.ops import wgl3_pallas
    from jepsen_etcd_demo_tpu.ops.encode import encode_register_history
    from jepsen_etcd_demo_tpu.serve.fleet import (FleetSupervisor,
                                                  make_fleet_handler)
    from jepsen_etcd_demo_tpu.serve.router import RANDOM, FleetRouter
    from jepsen_etcd_demo_tpu.utils.fuzz import (gen_register_history,
                                                 mutate_history)

    rng = random.Random(seed)
    lo, hi = ops_range
    hists, encs = [], []
    for i in range(n_hist):
        h = gen_register_history(rng, n_ops=rng.randrange(lo, hi),
                                 n_procs=n_procs, p_info=0.002)
        if invalid_every and i % invalid_every == invalid_every - 1:
            h = mutate_history(rng, h)
        hists.append(h)
        encs.append(encode_register_history(h, k_slots=8))
    events = int(sum(e.n_events for e in encs))
    bodies = [json.dumps({
        "tenant": f"tenant-{i % 3}", "model": model.name, "wait": True,
        "history": [json.loads(op.to_json()) for op in h],
    }).encode() for i, h in enumerate(hists)]

    posthoc = []
    for e in encs:
        outs, _kernel = wgl3_pallas.check_batch_encoded_auto([e], model)
        posthoc.append(outs[0])

    # One Poisson arrival schedule, reused by every pass of both arms
    # (same seed -> same offered load; the policy is the only variable).
    # The base rate is intentionally modest: the arms measure routing
    # economics under feasible load; the knee ladder finds capacity.
    arm_rate = max(2.0, n_hist / 12.0)
    sched_rng = random.Random(seed ^ 0xA221)
    t_arr, arm_schedule = 0.0, []
    for _ in range(n_hist):
        t_arr += sched_rng.expovariate(arm_rate)
        arm_schedule.append(t_arr)

    child_env = {
        "JAX_PLATFORMS": "cpu",
        "JEPSEN_TPU_NO_WARMUP": "1",
        "JEPSEN_TPU_NO_COMPILE_CACHE": "1",
        "JEPSEN_TPU_TELEMETRY": "0",
    }

    def open_loop_pass(base: str, schedule: list[float]
                       ) -> tuple[float, list, list]:
        """Dispatch every body at its absolute arrival offset; block a
        worker thread per request on the verdict. Returns (wall to last
        verdict, verdicts, latencies)."""
        results: list = [None] * len(bodies)
        lats: list = [0.0] * len(bodies)
        errors: list = []

        def worker(i: int):
            t_req = time.perf_counter()
            try:
                req = urllib.request.Request(
                    base + "/check", data=bodies[i],
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(
                        req, timeout=request_timeout_s) as r:
                    results[i] = json.loads(r.read().decode())
                lats[i] = time.perf_counter() - t_req
            except Exception as e:
                errors.append((i, e))

        threads = []
        t0 = time.perf_counter()
        for i, due in enumerate(schedule):
            delay = t0 + due - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            th = threading.Thread(target=worker, args=(i,))
            th.start()
            threads.append(th)
        for th in threads:
            th.join(request_timeout_s)
        wall = time.perf_counter() - t0
        if errors:
            i, e = errors[0]
            raise RuntimeError(
                f"fleet open-loop request {i} failed: "
                f"{type(e).__name__}: {e}")
        return wall, results, lats

    def fleet_up(mode: int):
        router = FleetRouter(spillover_mode=mode, salt=0,
                             poll_interval_s=0.5)
        sup = FleetSupervisor(_FLEET_STORE.name, n=replicas,
                              router=router, max_inflight=n_hist,
                              env=child_env)
        sup.start()
        httpd = ThreadingHTTPServer(
            ("127.0.0.1", 0),
            make_fleet_handler(_FLEET_STORE.name, router, sup))
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        return sup, router, httpd, base

    def fleet_down(sup, httpd):
        httpd.shutdown()
        httpd.server_close()
        sup.close()

    def replica_cache_totals(base: str) -> tuple[int, int]:
        with urllib.request.urlopen(base + "/serve/stats",
                                    timeout=30) as r:
            st = json.loads(r.read().decode())
        hits = misses = 0
        for rep in st["replicas"].values():
            kc = rep["scheduler"]["kernel_cache"]
            hits += kc["hits"]
            misses += kc["misses"]
        return hits, misses

    import tempfile
    _FLEET_STORE = tempfile.TemporaryDirectory(prefix="bench-fleet-")

    def run_arm(mode: int):
        """Fresh fleet, cold LRUs; the same schedule twice. The arm
        aggregate (both passes) carries the policy's compile bill; the
        warm pass isolates steady-state latency."""
        sup, router, httpd, base = fleet_up(mode)
        try:
            wall_a, res_a, lats_a = open_loop_pass(base, arm_schedule)
            wall_b, res_b, lats_b = open_loop_pass(base, arm_schedule)
            hits, misses = replica_cache_totals(base)
            with urllib.request.urlopen(base + "/fleet/stats",
                                        timeout=30) as r:
                fstats = json.loads(r.read().decode())
            lookups = hits + misses
            wall = wall_a + wall_b
            arm = {
                "wall_s": round(wall, 4),
                "agg_eps": round(2 * events / wall, 1),
                "agg_rps": round(2 * n_hist / wall, 2),
                "p50_s": round(_fleet_quantile(lats_a + lats_b, 0.50), 4),
                "p99_s": round(_fleet_quantile(lats_a + lats_b, 0.99), 4),
                "warm_p99_s": round(_fleet_quantile(lats_b, 0.99), 4),
                "hit_rate": round(hits / lookups, 4) if lookups else 0.0,
                "lookups": lookups,
            }
            return arm, (res_a, res_b), fstats, (sup, router, httpd, base)
        except BaseException:
            fleet_down(sup, httpd)
            raise

    # --- control arm: shape-blind random routing, then torn down ----
    rand_arm, rand_results, _rand_fstats, handles = run_arm(RANDOM)
    fleet_down(handles[0], handles[2])

    # --- affine arm: kept alive (warm) for the knee ladder -----------
    aff_arm, aff_results, aff_fstats, handles = run_arm(0)
    sup, router, httpd, base = handles

    # Verdict parity: every pass of every arm vs the analyze route.
    for arm_name, passes in (("random", rand_results),
                             ("affine", aff_results)):
        for res in passes:
            for i, (srv, post) in enumerate(zip(res, posthoc)):
                assert srv["valid"] == post["valid"] \
                    and srv["dead_step"] == int(post["dead_step"]), \
                    (f"fleet {arm_name} verdict diverged from analyze "
                     f"at history {i}: {srv['valid']}/{srv['dead_step']}"
                     f" vs {post['valid']}/{int(post['dead_step'])}")

    # --- open-loop knee ladder on the warm affine fleet --------------
    knee_rungs = []
    try:
        base_rate = max(arm_rate, 2 * n_hist / max(aff_arm["wall_s"], 1e-6))
        rate = base_rate / 2
        first_p99 = None
        for _ in range(max_knee_rungs):
            k_rng = random.Random(seed ^ int(rate * 1000))
            t_arr, schedule = 0.0, []
            for _ in range(n_hist):
                t_arr += k_rng.expovariate(rate)
                schedule.append(t_arr)
            wall, res, lats = open_loop_pass(base, schedule)
            p99 = _fleet_quantile(lats, 0.99)
            rung = {"offered_rps": round(rate, 2),
                    "agg_rps": round(n_hist / wall, 2),
                    "agg_eps": round(events / wall, 1),
                    "p99_s": round(p99, 4)}
            knee_rungs.append(rung)
            if first_p99 is None:
                first_p99 = max(p99, 1e-4)
            elif p99 > 4 * first_p99 \
                    or rung["agg_rps"] < 0.7 * rate:
                break   # past the knee — the previous rung is it
            rate *= 2
    finally:
        fleet_down(sup, httpd)
        _FLEET_STORE.cleanup()

    # The knee = the last rung still inside the latency/completion
    # envelope (the final entry may be the one that broke it).
    good = [r for r in knee_rungs
            if r["p99_s"] <= 4 * max(knee_rungs[0]["p99_s"], 1e-4)
            and r["agg_rps"] >= 0.7 * r["offered_rps"]]
    knee = good[-1] if good else knee_rungs[0]

    fills = {r["id"]: r["routed"] + r["spilled_in"]
             for r in aff_fstats["replicas"]}
    total_fill = sum(fills.values()) or 1
    spillover = int(aff_fstats["fleet"]["spillover"])

    if assert_win:
        assert aff_arm["agg_eps"] > rand_arm["agg_eps"], \
            (f"fleet acceptance: affine aggregate {aff_arm['agg_eps']} "
             f"ev/s does not beat random {rand_arm['agg_eps']} ev/s")
        assert aff_arm["hit_rate"] > rand_arm["hit_rate"], \
            (f"fleet acceptance: affine warm hit rate "
             f"{aff_arm['hit_rate']} not strictly above random "
             f"{rand_arm['hit_rate']}")

    return {
        "replicas": replicas,
        "histories": n_hist,
        "events": events,
        "affine": aff_arm,
        "random": rand_arm,
        "hit_rate_delta": round(
            aff_arm["hit_rate"] - rand_arm["hit_rate"], 4),
        "agg_eps_ratio": round(
            aff_arm["agg_eps"] / rand_arm["agg_eps"], 2)
        if rand_arm["agg_eps"] else 0.0,
        "knee_rate_rps": knee["offered_rps"],
        "agg_eps": knee["agg_eps"],
        "p99_s": knee["p99_s"],
        "knee_rungs": knee_rungs,
        "spillover": spillover,
        "replica_fill": fills,
        "replica_fill_min": round(
            min(fills.values()) / total_fill, 4) if fills else 0.0,
        "invalid": sum(1 for r in posthoc if r["valid"] is not True),
        "verdicts_identical": True,
    }


def bench_campaign(model, n_specs: int = 48, seed: int = 0xCA3,
                   shrink_ops: int = 140) -> dict:
    """Scenario-factory lane (ISSUE 15 tentpole), three measurements:

      1. **Campaign end-to-end specs/s** — one smoke-scaled campaign
         (deterministic sim scenarios on the virtual-time loop, corpus-
         batched checking on the warm pool, triage + shrink + bank into
         a throwaway store) — the headline gated round over round.
      2. **Shrink-checks/s, batched vs sequential** — the SAME ddmin
         reduction of one seeded-invalid register history driven two
         ways: candidates re-checked as one corpus launch per round
         (the production route) vs one launch per candidate (what a
         naive shrinker pays). Identical candidate sequences by
         construction (verdicts are pure functions of candidates), so
         the speedup isolates the batching.
      3. **Banked-corpus replay wall** — the regression lane's cost:
         re-falsify everything the campaign banked in one batched
         launch per model.
    """
    import shutil
    import tempfile

    from jepsen_etcd_demo_tpu import sched
    from jepsen_etcd_demo_tpu.campaign import replay_corpus, run_campaign
    from jepsen_etcd_demo_tpu.campaign.triage import (ddmin_shrink,
                                                      make_check_batch)
    from jepsen_etcd_demo_tpu.utils.fuzz import (gen_register_history,
                                                 mutate_history)

    td = tempfile.mkdtemp(prefix="bench-campaign-")
    try:
        t0 = time.perf_counter()
        report = run_campaign(n_specs=n_specs, seed=seed, scale=0.4,
                              bug_rate=0.4, workers=4, store_root=td)
        campaign_wall = time.perf_counter() - t0
        rep = report.to_dict()

        # Shrink arms: a seeded-invalid history big enough that the
        # candidate batches have real width.
        rng = random.Random(seed)
        direct = lambda encs, m: sched.check_corpus(encs, m)[0]  # noqa: E731

        def sequential(encs, m):
            out = []
            for e in encs:
                out.extend(sched.check_corpus([e], m)[0])
            return out

        batched_probe = make_check_batch(model, direct)
        bad = None
        for _ in range(16):
            cand = mutate_history(
                rng, gen_register_history(rng, n_ops=shrink_ops,
                                          n_procs=6, p_info=0.01))
            if batched_probe([cand])[0]:
                bad = cand
                break
        assert bad is not None, "could not seed an invalid shrink fixture"
        # Warmup shrink compiles both arms' bucket shapes, then each
        # arm re-runs the identical reduction.
        ddmin_shrink(bad, batched_probe)
        t0 = time.perf_counter()
        sres = ddmin_shrink(bad, batched_probe)
        batched_wall = time.perf_counter() - t0
        t0 = time.perf_counter()
        seq_res = ddmin_shrink(bad, make_check_batch(model, sequential))
        seq_wall = time.perf_counter() - t0
        assert seq_res.to_ops == sres.to_ops \
            and seq_res.checks == sres.checks, \
            "sequential and batched ddmin diverged — candidate order " \
            "is no longer deterministic"

        t0 = time.perf_counter()
        replay = replay_corpus(td)
        replay_wall = time.perf_counter() - t0
        return {
            "specs": n_specs,
            "campaign_wall_s": round(campaign_wall, 4),
            "specs_per_sec": round(n_specs / campaign_wall, 2)
            if campaign_wall else 0.0,
            "keys_checked": rep["keys_checked"],
            "falsified_runs": rep["falsified_runs"],
            "unique_signatures": rep["unique_signatures"],
            "banked": len(rep["banked"]),
            "shrink_from_ops": sres.from_ops,
            "shrink_to_ops": sres.to_ops,
            "shrink_checks": sres.checks,
            "shrink_launches": sres.launches,
            "shrink_one_minimal": sres.one_minimal,
            "shrink_wall_s": round(batched_wall, 4),
            "shrink_checks_per_sec": round(sres.checks / batched_wall, 1)
            if batched_wall else 0.0,
            "sequential_shrink_wall_s": round(seq_wall, 4),
            "speedup_vs_sequential": round(seq_wall / batched_wall, 2)
            if batched_wall else 0.0,
            "replay_entries": replay["entries"],
            "replay_checked": replay["checked"],
            "replay_ok": replay["ok"],
            "replay_wall_s": round(replay_wall, 4),
        }
    finally:
        shutil.rmtree(td, ignore_errors=True)


def build_stream_run(n_keys: int = 16, ops_per_key: int = 400,
                     seed: int = 0x57CA):
    """ONE generated independent-key run for the streaming lane: per-key
    fuzzed register histories (valid by construction) with disjoint
    process-id ranges, round-robin interleaved into the single op stream
    a live run's recorder would produce, values wrapped as (key, v)
    tuples. Returns (interleaved ops, per-key histories) — the same run
    seen by both arms."""
    from jepsen_etcd_demo_tpu.utils.fuzz import (gen_register_history,
                                                 interleave_keyed)

    rng = random.Random(seed)
    per_key = [gen_register_history(rng, n_ops=ops_per_key,
                                    n_procs=N_PROCS, p_info=0.002)
               for _ in range(n_keys)]
    return interleave_keyed(per_key), per_key


def bench_streaming(model, n_keys: int = 16, ops_per_key: int = 400,
                    run_s: float = 0.8) -> dict:
    """Streaming check lane (ISSUE 5 tentpole): post-hoc vs streamed
    end-to-end wall clock on ONE generated run.

    The post arm pays run + the serial check tail
    (sched.check_corpus over the per-key encodings — the production
    post-hoc path); the stream arm replays the SAME op stream paced
    over `run_s` through the streaming session (stream/engine.py), so
    its tail is only the drain of whatever wasn't already swept while
    the "run" was live. Both arms are measured warm (kernels compiled
    by a first pass); verdicts are asserted bit-identical per key, and
    the lane reports the measured overlap_ratio — the acceptance
    criterion requires it > 0 on the CPU backend
    (tests/test_bench_smoke.py pins the contract at tiny scale).
    stream_flush_ops is pinned to 64 for the measurement so the chunk
    cadence (and therefore the lane) is machine-comparable."""
    from dataclasses import replace

    from jepsen_etcd_demo_tpu import sched
    from jepsen_etcd_demo_tpu.ops.encode import encode_register_history
    from jepsen_etcd_demo_tpu.ops.limits import limits, set_limits
    from jepsen_etcd_demo_tpu.stream import StreamSession

    ops, per_key = build_stream_run(n_keys, ops_per_key)
    encs = [encode_register_history(h, k_slots=32) for h in per_key]
    events = int(sum(e.n_events for e in encs))

    prev = set_limits(replace(limits(), stream_flush_ops=64))
    try:
        post_results, _k, _s = sched.check_corpus(encs, model)   # warm
        post_best = float("inf")
        for _ in range(REPEATS):
            t0 = time.perf_counter()
            post_results, _k, _s = sched.check_corpus(encs, model)
            post_best = min(post_best, time.perf_counter() - t0)
        assert all(r["valid"] is True for r in post_results)

        def replay():
            session = StreamSession(model, keyed=True)
            batches = 40
            per = (len(ops) + batches - 1) // batches
            t0 = time.perf_counter()
            for i in range(batches):
                for op in ops[i * per:(i + 1) * per]:
                    session.feed(op)
                time.sleep(run_s / batches)
            feed_wall = time.perf_counter() - t0
            t1 = time.perf_counter()
            results = session.finalize()
            drain = time.perf_counter() - t1
            return session, results, feed_wall, drain, \
                time.perf_counter() - t0

        replay()   # warm the (cfg, chunk) kernels through the session path
        session, sres, feed_wall, drain_s, stream_total = replay()
    finally:
        set_limits(prev)

    assert sres is not None and len(sres) == n_keys, \
        "streaming lane must stream every key"
    for k in range(n_keys):
        s, p = sres[k], post_results[k]
        for f in ("valid", "dead_step", "max_frontier",
                  "configs_explored"):
            assert s[f] == p[f], \
                f"streamed/post-hoc verdict drift on key {k} field {f}: " \
                f"{s[f]} != {p[f]}"
    stats = session.stats()
    post_total = feed_wall + post_best
    return {
        "keys": n_keys,
        "ops": len(ops),
        "events": events,
        "run_s": round(feed_wall, 4),
        "post_check_s": round(post_best, 4),
        "stream_drain_s": round(drain_s, 4),
        "post_total_s": round(post_total, 4),
        "stream_total_s": round(stream_total, 4),
        "speedup_total": (round(post_total / stream_total, 3)
                          if stream_total else 0.0),
        "overlap_ratio": stats["overlap_ratio"],
        "chunks": stats["chunks"],
        "restarts": stats["restarts"],
        "watermark_lag_max": stats["watermark_lag_max"],
        "kernel": "wgl3-dense-stream-chunked",
        "verdicts_identical": True,
    }


def bench_elle(n_txns: int = 10_000, n_keys: int = 100,
               corpus: int = 24, corpus_txns: int = 40) -> dict:
    """Elle transactional-checker lane (ISSUE 11 tentpole): ONE 10k-txn
    sparse list-append history (single-key txns over `n_keys` keys —
    the dependency graph decomposes into per-key components, the shape
    real multi-key workloads produce) checked end to end under three
    closure routes, plus a small mixed-validity corpus certified across
    EVERY route:

      * dense arm — limits().elle_mode=1: the seed [N, N] matrix-
        squaring closure on the whole graph (measured ONCE — at 10k
        nodes this is ~14 squarings of a [10112, 10112] f32 matmul);
      * auto arm (the GATED headline) — elle_mode=0: weak-component
        decomposition, vmapped bucketed batch launches for the small
        components, the tiled work-list kernel for big ones; best of
        REPEATS, events/s and txns/s reported;
      * tiled arm — elle_mode=2: the blocked work-list kernel forced on
        the whole graph (informational — on an interleaved graph most
        tiles are live, so this bounds the kernel, not the route);
      * oracle — the pinned pure-Python Tarjan/SCC cycle check on the
        same dependency graph (bench_baseline.json pinning, like every
        oracle denominator), plus the shared host inference wall.

    Verdicts: the 10k arms must agree (valid=True), and the corpus —
    half mutated to likely-anomalous — must produce BIT-IDENTICAL
    anomaly verdicts across dense / batched-auto / tiled / streamed /
    host-Tarjan-fallback routes (the acceptance criterion's 'all
    routes')."""
    import time as _time
    from dataclasses import replace

    from jepsen_etcd_demo_tpu import obs
    from jepsen_etcd_demo_tpu.checkers.elle import ElleChecker, ElleGraph
    from jepsen_etcd_demo_tpu.ops.limits import limits, set_limits
    from jepsen_etcd_demo_tpu.stream import ElleStreamSession
    from jepsen_etcd_demo_tpu.utils.fuzz import (append_txn_ops,
                                                 gen_append_txns,
                                                 mutate_append_txns)

    rng = random.Random(0xE11E)
    # Per-key txn runs are CONTIGUOUS (the workload-rotating-through-
    # keys shape): the dependency graph is block-diagonal, so the tiled
    # arm's occupancy skipping has real empty tiles to skip and the
    # auto arm's decomposition has real components — while the dense
    # arm still pays the full [N, N] closure either way.
    txns = []
    per_key = max(1, n_txns // n_keys)
    for k in range(n_keys):
        txns.extend(gen_append_txns(rng, n_txns=per_key, n_keys=1,
                                    max_len=1, first_key=k))
    n_txns = len(txns)
    history = append_txn_ops(txns)
    checker = ElleChecker()

    # Shared host inference wall (pairing + incremental graph build) —
    # identical across arms, measured once so the closure arms' deltas
    # are attributable to the closure route alone.
    t0 = _time.perf_counter()
    graph = ElleGraph()
    from jepsen_etcd_demo_tpu.checkers.elle import _pair_txns

    for t in _pair_txns(history):
        graph.add_txn(*t)
    ww, wr, rw = graph.edge_matrices()
    infer_s = _time.perf_counter() - t0
    full = ww | wr | rw
    n_nodes = full.shape[0]
    edges = int(full.sum())

    lane = {"txns": n_txns, "events": len(history), "keys": n_keys,
            "graph_nodes": n_nodes, "graph_edges": edges,
            "infer_s": round(infer_s, 4)}

    def timed_check(mode: int, repeats: int):
        prev = set_limits(replace(limits(), elle_mode=mode))
        try:
            out = checker.check({}, history)       # warm the kernels
            best = float("inf")
            for _ in range(repeats):
                t0 = _time.perf_counter()
                out = checker.check({}, history)
                best = min(best, _time.perf_counter() - t0)
        finally:
            set_limits(prev)
        return best, out

    dense_s, dense_out = timed_check(1, repeats=1)
    auto_s, auto_out = timed_check(0, repeats=REPEATS)
    tiled_s, tiled_out = timed_check(2, repeats=1)
    for name, out in (("dense", dense_out), ("auto", auto_out),
                      ("tiled", tiled_out)):
        assert out["valid"] is True, f"elle lane {name} arm: {out}"
    assert dense_out == auto_out == tiled_out, \
        "elle route verdict drift on the 10k history"

    # Pinned Tarjan/SCC oracle on the same dependency graph.
    from jepsen_etcd_demo_tpu.ops.cycles import _host_cycle_mask

    sig = {"lane": "elle", "txns": n_txns, "nodes": n_nodes,
           "edges": edges,
           "checksum": int(np.flatnonzero(full).sum() & 0x7FFFFFFF)}
    oracle_s = _pinned_oracle("elle", sig)
    pinned = oracle_s is not None
    if not pinned:
        t0 = _time.perf_counter()
        assert not _host_cycle_mask(full).any()
        oracle_s = _time.perf_counter() - t0
        _pin_oracle("elle", sig, oracle_s)

    lane.update({
        "dense_s": round(dense_s, 4),
        "auto_s": round(auto_s, 4),
        "tiled_s": round(tiled_s, 4),
        "oracle_s": round(oracle_s, 4),
        "oracle_pinned": pinned,
        "events_per_sec": round(len(history) / auto_s, 1),
        "txns_per_sec": round(n_txns / auto_s, 1),
        "speedup_vs_dense": round(dense_s / auto_s, 2) if auto_s else 0.0,
        "vs_oracle": (round((infer_s + oracle_s) / auto_s, 2)
                      if auto_s else 0.0),
        "kernel": "elle-closure-batch",
    })

    # Mixed-validity certification across EVERY route: dense, batched
    # auto, tiled, streamed, and the host Tarjan fallback (cell budget
    # pinned below any graph so every closure takes the SCC oracle).
    crng = random.Random(0xE11F)
    cases = []
    for i in range(corpus):
        t = gen_append_txns(crng, n_txns=corpus_txns, n_keys=4, max_len=3)
        if i % 2:
            t = mutate_append_txns(crng, t)
        cases.append(append_txn_ops(t))
    routes = {"dense": {"elle_mode": 1}, "auto": {"elle_mode": 0},
              "tiled": {"elle_mode": 2},
              "tarjan": {"elle_mode": 0, "elle_cell_budget": 1 << 12}}
    verdicts: dict[str, list] = {}
    for name, overrides in routes.items():
        prev = set_limits(replace(limits(), **overrides))
        try:
            with obs.capture() as rcap:
                verdicts[name] = [checker.check({}, h) for h in cases]
            if name == "tarjan":
                # The certification's independence claim: the pinned
                # budget must actually route every closure to the host
                # SCC oracle, not re-run a device route.
                rstats = obs.elle_stats(rcap.metrics)
                assert rstats["graphs_oracle"] > 0, rstats
                assert rstats["graphs_dense"] == 0, rstats
        finally:
            set_limits(prev)
    streamed = []
    for h in cases:
        session = ElleStreamSession(checker)
        for op in h:
            session.feed(op)
        res = session.finalize()
        assert res is not None, "elle lane corpus must stream"
        one = dict(res["elle"])
        one.pop("streamed", None)
        streamed.append(one)
    verdicts["streamed"] = streamed
    ref = verdicts["tarjan"]
    mismatches = sum(
        1 for name, outs in verdicts.items()
        for a, b in zip(outs, ref)
        if (a["valid"], a["anomaly_types"]) != (b["valid"],
                                               b["anomaly_types"]))
    invalid = sum(1 for r in ref if r["valid"] is False)
    assert invalid >= corpus // 4, f"tame elle mutation sweep: {invalid}"
    assert mismatches == 0, f"elle route certification: {verdicts}"
    lane["corpus"] = {"histories": corpus, "invalid": invalid,
                      "routes": sorted(verdicts), "mismatches": 0}
    lane["verdicts_identical"] = True
    return lane


def _profile_record() -> dict:
    """The profile stamp every bench record carries (degraded path
    included — a degraded run still states which profile it intended to
    use): active hash, tuned-field count, every non-default field with
    its provenance tag, and the tool that prints the full table."""
    try:
        from jepsen_etcd_demo_tpu.tune import profile as tune_profile

        rec = tune_profile.run_record()
    except Exception:
        from jepsen_etcd_demo_tpu import obs

        rec = {"hash": obs.active_profile_hash(), "tuned_fields": 0,
               "overrides": {}}
    rec["inspect"] = "python tools/print_profile.py"
    return rec


def bench_invalid_lane(model) -> dict:
    """Mixed-validity certification of the COMPILED pallas kernels
    (VERDICT r3 item 2: every prior bench lane was valid-by-construction,
    so nothing run on hardware had ever returned valid=False). 128
    histories, half mutated to likely-invalid, expected verdicts from the
    host oracle and per-field expectations (dead_step included) from the
    XLA dense kernel; both compiled pallas kernels — per-history and
    grouped — must agree exactly. Mismatches land in the JSON (and a
    nonzero count fails the bench loudly)."""
    from jepsen_etcd_demo_tpu.checkers.oracle import check_events_oracle
    from jepsen_etcd_demo_tpu.ops import wgl3, wgl3_pallas
    from jepsen_etcd_demo_tpu.ops.encode import encode_register_history
    from jepsen_etcd_demo_tpu.utils.fuzz import (gen_register_history,
                                                 mutate_history)

    rng = random.Random(0x1BAD)
    encs, hists, oracle_valid = [], [], []
    for i in range(128):
        h = gen_register_history(rng, n_ops=60, n_procs=8, p_info=0.01)
        if i % 2:
            h = mutate_history(rng, h)
        enc = encode_register_history(h, k_slots=16)
        encs.append(enc)
        hists.append(h)
        oracle_valid.append(check_events_oracle(enc, model).valid)

    cfg, steps, r_cap = wgl3.batch_steps3(encs, model)
    arrays = wgl3.stack_steps3(steps, r_cap)
    expected = wgl3.assemble_batch_results(
        wgl3.unpack_np(wgl3.cached_batch_checker3_packed(model, cfg)
                       (*arrays)), steps, cfg)
    lane = {"histories": len(encs),
            "invalid": sum(1 for v in oracle_valid if v is False),
            "mismatches": 0, "kernels": []}
    # The lane must actually exercise the dead/prune path, not fuzz tame.
    assert lane["invalid"] >= 16, f"tame mutation sweep: {lane['invalid']}"
    lane["mismatches"] += sum(
        1 for e, ov in zip(expected, oracle_valid) if e["valid"] is not ov)

    if not wgl3_pallas.use_pallas(cfg, n_steps=r_cap, batch=len(encs)):
        lane["kernels"] = ["skipped: pallas unavailable on this backend"]
        return lane
    pallas_out = None
    for check, name in (
            (wgl3_pallas.cached_batch_checker_pallas(model, cfg),
             "wgl3-dense-pallas"),
            (wgl3_pallas.cached_batch_checker_pallas_grouped(model, cfg),
             "wgl3-dense-pallas-grouped")):
        out = wgl3.assemble_batch_results(
            wgl3.unpack_np(check(*arrays)), steps, cfg)
        if pallas_out is None:
            pallas_out = out
        mm = sum(1 for o, e in zip(out, expected)
                 if (o["valid"], o["dead_step"], o["max_frontier"],
                     o["configs_explored"])
                 != (e["valid"], e["dead_step"], e["max_frontier"],
                     e["configs_explored"]))
        lane["kernels"].append({"kernel": name, "mismatches": mm})
        lane["mismatches"] += mm

    lane["witnesses"] = _certify_witnesses(model, encs, hists, pallas_out,
                                           oracle_valid)
    lane["mismatches"] += lane["witnesses"]["mismatches"]

    # The RESUMABLE windowed kernel's compiled dead path: one long
    # mutated history driven in small windows (state carried across
    # launches), against the XLA chunked sweep.
    from dataclasses import replace

    from jepsen_etcd_demo_tpu.ops.encode import (encode_return_steps,
                                                 reslot_events)
    from jepsen_etcd_demo_tpu.ops.limits import limits, set_limits

    # dedup_mode pinned OFF for this certification: the pallas kernels
    # run no canonicalization pass, and the lane compares the SEARCH
    # metrics (max_frontier) bit-for-bit — the dedup lane owns the
    # canonicalized numbers. replace(), not a fresh KernelLimits: the
    # active profile may carry env overrides that must keep applying.
    prev = set_limits(replace(limits(), dedup_mode=1))
    try:
        for _ in range(20):   # mutations are LIKELY-invalid; insist on it
            h = mutate_history(rng, gen_register_history(
                rng, n_ops=4000, n_procs=8, p_info=0.002))
            enc = encode_register_history(h, k_slots=16)
            k = wgl3.tight_k_slots(enc)
            lcfg = wgl3.dense_config(model, k, enc.max_value)
            enc = reslot_events(enc, k) if enc.k_slots != k else enc
            rs = encode_return_steps(enc)
            ref = wgl3.check_steps3_long(rs, model, lcfg, chunk=512)
            if ref["valid"] is False:
                break
        assert ref["valid"] is False, "no invalid long mutation in 20 tries"
        set_limits(replace(limits(), dedup_mode=1, max_r_pallas=512))
        got = wgl3_pallas.check_steps3_long_pallas(rs, model, lcfg)
    finally:
        set_limits(prev)
    mm = sum(1 for f in ("valid", "survived", "dead_step", "max_frontier")
             if got[f] != ref[f])
    lane["kernels"].append({"kernel": "wgl3-dense-pallas-chunked",
                            "mismatches": mm,
                            "valid": bool(ref["valid"])})
    lane["mismatches"] += mm
    assert lane["mismatches"] == 0, f"invalid-lane certification: {lane}"
    return lane


def _certify_witnesses(model, encs, hists, pallas_out, oracle_valid,
                       n: int = 8) -> dict:
    """VERDICT r4 next #7: witness reconstruction had only ever consumed
    CPU-backend verdicts. Here the full Linearizable._explain ladder runs
    on the TPU kernel's OWN results for `n` invalid histories, and the
    reconstructed failing op must be the op returning at the host
    oracle's dead event — closing the last uncertified TPU surface (the
    dense frontier-recovery rungs re-run kernels downstream of these
    fields). knossos always emits its failing-op analysis
    (/root/reference/src/jepsen/etcdemo.clj:117); this proves ours is
    correct when fed from hardware."""
    from jepsen_etcd_demo_tpu.checkers.linearizable import (Linearizable,
                                                            _event_to_step)
    from jepsen_etcd_demo_tpu.checkers.oracle import check_events_oracle

    lin = Linearizable(model=model)
    out = {"checked": 0, "mismatches": 0, "detail": []}
    for i, valid in enumerate(oracle_valid):
        if valid is not False or out["checked"] >= n:
            continue
        enc = encs[i]
        ores = check_events_oracle(enc, model)
        ev = enc.events[ores.dead_event]
        want_op = model.describe_op(int(ev[2]), int(ev[3]), int(ev[4]),
                                    int(ev[5]))
        res = dict(pallas_out[i])          # the HARDWARE-produced verdict
        if res["valid"] is not False:
            # Kernel/oracle disagreement: already counted by the lane's
            # per-field mismatch pass; record it here too rather than
            # aborting the whole bench on an assert.
            out["checked"] += 1
            out["mismatches"] += 1
            out["detail"].append({"history": i,
                                  "kernel_valid": res["valid"],
                                  "oracle_valid": False})
            continue
        lin._explain(res, enc, model.prepare_history(hists[i]), None)
        ok = (res.get("failed_op") == want_op
              and res.get("witness") not in (None, "skipped")
              and res["dead_step"] == _event_to_step(enc, ores.dead_event))
        out["checked"] += 1
        if not ok:
            out["mismatches"] += 1
            out["detail"].append({
                "history": i, "want_op": want_op,
                "failed_op": res.get("failed_op"),
                "witness": res.get("witness")})
    assert out["checked"] >= 4, f"too few invalid histories: {out}"
    return out


def bench_long(model, n_ops: int, oracle_too: bool, p_info: float = 0.0005):
    """One long single-register history through the single dense kernel.

    p_info scales the forever-pending population; past ~17 simultaneously
    pending ops the geometry leaves the dense budget (that axis is the
    lattice-sharded kernel's lane, not this one), so the 100k lane runs
    with p_info=0 — history LENGTH is the variable here."""
    from jepsen_etcd_demo_tpu.checkers.oracle import check_events_oracle
    from jepsen_etcd_demo_tpu.ops import wgl3_pallas
    from jepsen_etcd_demo_tpu.ops.encode import encode_register_history
    from jepsen_etcd_demo_tpu.utils.fuzz import gen_register_history

    from dataclasses import replace

    from jepsen_etcd_demo_tpu.ops.limits import limits, set_limits

    rng = random.Random(0x10C0 + n_ops)
    h = gen_register_history(rng, n_ops=n_ops, n_procs=N_PROCS,
                             p_info=p_info)
    enc = encode_register_history(h, k_slots=64)
    run = lambda: wgl3_pallas.check_batch_encoded_auto([enc], model)

    # This lane measures the DEVICE KERNEL (round-over-round
    # comparability): pin the small-history oracle router off for the
    # measurement, then report the router's production-path wall
    # separately as routed_s when it would engage.
    prev = set_limits(replace(limits(), oracle_crossover_events=0))
    try:
        t0 = time.perf_counter()
        results, kernel = run()             # includes compile (cold)
        cold_s = time.perf_counter() - t0
        out = results[0]
        assert out["valid"] is True
        t0 = time.perf_counter()
        results, kernel = run()
        warm_s = time.perf_counter() - t0
        out = results[0]
    finally:
        set_limits(prev)
    d = {"ops": n_ops, "kernel_s": warm_s, "kernel_cold_s": cold_s,
         # The ROUTER's name, not the per-history dict's (which only the
         # ladder paths stamp): single-history pallas was mislabeled
         # "wgl3-dense" before.
         "kernel": kernel}
    # The resolved (calibrated or pinned) crossover decides whether the
    # production router would take the oracle here — report that path's
    # wall separately when it engages.
    if enc.n_events <= wgl3_pallas._oracle_crossover():
        results, routed_kernel = run()      # warm routed path
        t0 = time.perf_counter()
        results, routed_kernel = run()
        d["routed_s"] = time.perf_counter() - t0
        d["routed_kernel"] = routed_kernel
    if oracle_too:
        t0 = time.perf_counter()
        res = check_events_oracle(enc, model)
        assert res.valid
        d["oracle_s"] = time.perf_counter() - t0
    return d


def longhaul_zero_lane() -> dict:
    """The degraded-path long-haul record: every contract key present
    as zeros (tools/bench_compare.py check_longhaul_record — the same
    zeros-never-absent rule as the ledger/fleet objects)."""
    return {"events": 0, "segments": 0, "segments_run": 0,
            "resumed_from": -1, "survived": False, "dead_step": -1,
            "max_frontier": 0, "escalations": 0, "spilled": False,
            "wall_s": 0.0, "events_per_sec": 0.0, "peak_rss_mb": 0.0,
            "rss_budget_mb": 0, "rss_ok": False,
            "verdicts_identical": False, "crosscheck_events": 0}


def bench_longhaul(model, events: int | None = None,
                   seg_events: int = 16384, seed: int = 0x10A6,
                   rss_budget_mb: int = 512,
                   crosscheck_cap: int = 120_000) -> dict:
    """Long-haul out-of-core lane (ISSUE 20 tentpole): a synthetic
    multi-segment register history is generated chunk-by-chunk (the
    whole history NEVER exists in RAM), encoded through the
    content-addressed cache tier, and checked end-to-end through the
    spilled wgl2 route (stream/longhaul.py) under a PINNED host RSS
    budget — `peak_rss_mb` is the lane's ru_maxrss DELTA, gated
    inverted (lower is better) by tools/bench_compare.py next to the
    gated `longhaul_eps` throughput.

    Default scale keeps the driver's bench round fast;
    JEPSEN_TPU_BENCH_LONGHAUL_EVENTS scales the same lane to 10^8+
    events for the full out-of-core claim. Verdict parity is certified
    every round at the largest cross-checkable scale: the spilled route
    and the all-RAM route (host_spill_mode pinned off) must agree on
    survived/dead_step bit-identically."""
    import shutil
    import tempfile

    from dataclasses import replace

    from jepsen_etcd_demo_tpu.ops.limits import limits, set_limits
    from jepsen_etcd_demo_tpu.store import spill
    from jepsen_etcd_demo_tpu.stream import longhaul

    if events is None:
        events = int(os.environ.get(
            "JEPSEN_TPU_BENCH_LONGHAUL_EVENTS", 120_000))
    # Pay the XLA compile (and its RSS spike) BEFORE the measured lane:
    # the gated peak_rss_mb must measure the out-of-core engine, not
    # the one-time jit of the chunk kernel.
    longhaul.run_longhaul(model, events=4096, seg_events=2048,
                          seed=seed ^ 0x5A5A)
    td = tempfile.mkdtemp(prefix="jepsen-longhaul-")
    prev = set_limits(replace(limits(), host_spill_mode=2,
                              host_rss_budget_mb=rss_budget_mb))
    try:
        with spill.spilling(td):
            rec = longhaul.run_longhaul(
                model, events=events, seg_events=seg_events, seed=seed)
        ce = min(events, crosscheck_cap)
        if ce == events:
            spilled_verdict = (rec["survived"], rec["dead_step"])
        else:
            with spill.spilling(td):
                cc_spill = longhaul.run_longhaul(
                    model, events=ce, seg_events=seg_events, seed=seed,
                    tag="longhaul-cc")
            spilled_verdict = (cc_spill["survived"],
                               cc_spill["dead_step"])
        set_limits(replace(limits(), host_spill_mode=1))
        inram = longhaul.run_longhaul(model, events=ce,
                                      seg_events=seg_events, seed=seed)
        identical = spilled_verdict == (inram["survived"],
                                        inram["dead_step"])
        assert identical, (
            f"longhaul verdict divergence at {ce} events: spilled "
            f"{spilled_verdict} vs in-RAM "
            f"{(inram['survived'], inram['dead_step'])}")
    finally:
        set_limits(prev)
        shutil.rmtree(td, ignore_errors=True)
    rec["verdicts_identical"] = identical
    rec["crosscheck_events"] = ce
    rec["kernel"] = "wgl2-sort-chunked"
    return rec


def bench_100k(model) -> dict:
    """Opt-in 100k-op lane (BENCH_100K=1; minutes of wall clock): one
    100k-op register history through the production router — the step
    count exceeds one scan program, so this exercises the host-chunked
    dense sweep end to end (VERDICT r2 weak #7: record the claim or drop
    it). The result is cached in bench_100k.json (committed) and merged
    into every subsequent bench line."""
    d = bench_long(model, 100_000, oracle_too=False, p_info=0.0)
    d["recorded_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    import jax

    d["device"] = str(jax.devices()[0])
    LONG100K_FILE.write_text(json.dumps(d, indent=2) + "\n")
    print(f"# recorded 100k-op lane -> {LONG100K_FILE.name} (commit it)",
          file=sys.stderr)
    return d


def _backend_alive(timeout_s: float = 240.0,
                   platforms: str | None = None) -> tuple[bool, str]:
    """Probe the default JAX backend in a SUBPROCESS with a hard
    timeout. ISSUE 8 promoted the probe itself into the reusable
    backend-health layer (obs/health.py probe_backend — the state
    machine the runner / stream / sched drive and /healthz exposes);
    this wrapper keeps the bench's historical (ok, reason) shape. The
    wedged-vs-crash distinction rides the reason text (a timeout reason
    names the wedged tunnel), which _drive_supervisor maps back onto
    the state machine."""
    from jepsen_etcd_demo_tpu.obs.health import probe_backend

    ok, reason, _timed_out = probe_backend(timeout_s=timeout_s,
                                           platforms=platforms)
    return ok, reason


def _drive_supervisor(ok: bool, reason: str) -> dict:
    """Fold one probe outcome into the process backend supervisor
    (obs/health.py) and return its snapshot — the bench record's
    `health` field, captured at probe time so a degraded CPU rerun's
    later successes can't repaint the default backend healthy in the
    record."""
    from jepsen_etcd_demo_tpu.obs import health

    sup = health.get_supervisor()
    if ok:
        sup.note_ok(source="bench.probe")
    else:
        # The timeout reason carries the wedged-tunnel marker phrase
        # (health.TIMEOUT_MARKER — the same constant probe_backend
        # composes the reason with, so the classification can't desync
        # from the wording); a fast crash walks the consecutive-failure
        # thresholds instead.
        sup.note_failure(reason, source="bench.probe",
                         wedged=health.TIMEOUT_MARKER in reason)
    return sup.snapshot()


def main():
    from jepsen_etcd_demo_tpu import obs

    ok, reason = _backend_alive()
    health_rec = _drive_supervisor(ok, reason)
    degraded = False
    if not ok:
        # Degraded-mode fallback (VERDICT r5): a dead TPU tunnel used to
        # zero the whole round's record (rc 1, value 0). Re-probe on the
        # CPU backend and, when IT is healthy, rerun the CPU-provable
        # lanes there — a full record tagged degraded/cpu instead of a
        # blank. Only when even CPU can't complete a trivial jit does
        # the bench abort with the all-zero error line.
        cpu_ok, cpu_reason = _backend_alive(platforms="cpu")
        if not cpu_ok:
            # Even the CPU probe failed: emit the FULL tagged record
            # (every PR 2 contract field present as zeros, degraded
            # true, backend "none") and exit 0 — the driver keeps a
            # parseable degraded record instead of an rc-1 round with
            # value 0 (BENCH_r05's failure mode). The error field is
            # the diagnosis; zeros say "nothing ran", not "it ran at
            # zero events/s".
            print(json.dumps({
                "metric": "wgl_check_throughput", "value": 0,
                "unit": "history-events/sec", "vs_baseline": 0,
                # The breakdown contract is "zeros permitted, never
                # absent": an unreachable backend reports all-zero
                # phases, so trend tooling never branches on a missing
                # key.
                "kernel_phases": obs.kernel_phases(None),
                "padding_waste": 0.0,
                "cache_hit_rate": 0.0,
                "sweep": obs.sweep_stats(None),
                "elle": obs.elle_stats(None),
                "serve": obs.serve_stats(None),
                "fleet": obs.fleet_stats(None),
                "campaign": obs.campaign_stats(None),
                "ledger": obs.ledger_stats(None),
                "longhaul": obs.longhaul_stats(None),
                # Which tuning profile the run INTENDED to use (ISSUE 4:
                # tools/print_profile.py prints the full resolved view).
                "profile": _profile_record(),
                "health": health_rec,
                "degraded": True,
                "backend": "none",
                "detail": {"probe": {"default": reason,
                                     "cpu": cpu_reason}},
                "error": f"JAX backend unusable ({reason}); CPU fallback "
                         f"also unusable ({cpu_reason}); bench aborted "
                         f"instead of hanging"}))
            return 0
        print(f"# default backend unusable ({reason}); degraded rerun on "
              f"JAX_PLATFORMS=cpu", file=sys.stderr)
        os.environ["JAX_PLATFORMS"] = "cpu"
        degraded = True

    from jepsen_etcd_demo_tpu.cli.main import _honor_platform_env

    _honor_platform_env()

    import jax

    from jepsen_etcd_demo_tpu.cli.main import enable_compilation_cache
    from jepsen_etcd_demo_tpu.models import CASRegister

    enable_compilation_cache()   # kernel_cold_s amortizes across runs
    model = CASRegister()
    # SURVEY.md §5.1: jax.profiler traces for the checker kernel itself.
    # Opt-in (BENCH_PROFILE=<dir> or --profile <dir>) so the driver's plain
    # `python bench.py` stays fast; view with tensorboard/xprof.
    profile_dir = os.environ.get("BENCH_PROFILE")
    if "--profile" in sys.argv:
        profile_dir = sys.argv[sys.argv.index("--profile") + 1]
    # Every lane runs under one telemetry capture (obs/): the kernel-phase
    # breakdown printed next to the throughput figure is the same
    # compile/execute/encode attribution a test run writes to its
    # metrics.json, aggregated over the whole bench.
    lane_error = None
    with obs.capture() as cap:
        try:
            if profile_dir:
                with jax.profiler.trace(profile_dir):
                    corpus = bench_corpus(model)
                print(f"# profiler trace written to {profile_dir}",
                      file=sys.stderr)
            else:
                corpus = bench_corpus(model)
            longs = [bench_long(model, n, oracle_too=(n <= 1000))
                     for n in LONG_OPS]
            gset = bench_gset_corpus()
            invalid_lane = bench_invalid_lane(model)
            # The lane opens its own nested captures (cold/warm
            # kernel-phase attribution), which shadow this one — its
            # numbers land in the top-level padding_waste /
            # cache_hit_rate fields instead.
            sched_lane = bench_sched_corpus(model)
            # Sparse active-tile lane: dense-vs-sparse sweep on one wide
            # long history (ISSUE 3) — the win measured, not asserted.
            sparse_lane = bench_sparse(model)
            # Frontier-dedup lane (ISSUE 10): dedup-off vs dedup-on on
            # one symmetry-heavy history, verdicts asserted identical,
            # raw vs unique configs/s reported separately.
            dedup_lane = bench_dedup(model)
            # Tuned-profile lane (ISSUE 4): default vs tuned-profile
            # limits on one corpus, verdicts asserted identical.
            tuned_lane = bench_tuned(model)
            # Streaming check lane (ISSUE 5): post-hoc vs streamed
            # end-to-end wall on one generated run, verdicts asserted
            # bit-identical, overlap_ratio measured.
            stream_lane = bench_streaming(model)
            # Elle transactional-checker lane (ISSUE 11): dense vs
            # tiled/batched closure on one 10k-txn sparse history,
            # verdicts certified bit-identical across every route.
            elle_lane = bench_elle()
            # Checking-as-a-service lane (ISSUE 13): K concurrent
            # clients against the in-process continuous-batching
            # daemon vs the serial baseline, verdicts certified
            # bit-identical to the analyze route; acceptance >= 3x.
            serve_lane = bench_serve(model, min_speedup=3.0)
            # Fleet-scale serving lane (ISSUE 18): open-loop Poisson
            # arrivals against N subprocess replicas behind the shape-
            # affine router; affine must beat shape-blind random on
            # aggregate events/s AND warm cache hit rate, p99 reported
            # at the measured latency knee, verdicts certified
            # bit-identical to the analyze route.
            fleet_lane = bench_fleet(model)
            # Scenario-factory lane (ISSUE 15): campaign specs/s end to
            # end, batched-vs-sequential ddmin shrink checks/s, and the
            # banked-corpus replay wall.
            campaign_lane = bench_campaign(model)
            # Long-haul out-of-core lane (ISSUE 20): segment-chained
            # checking through the spill tier under a pinned host RSS
            # budget; spilled vs in-RAM verdicts certified identical.
            longhaul_lane = bench_longhaul(model)
            # Inside the capture: the 100k lane's compile/execute/encode
            # seconds must land in the same kernel_phases breakdown as
            # every other lane when it actually runs.
            long100k = bench_100k(model) if os.environ.get("BENCH_100K") \
                else None
        except Exception as e:
            # BENCH_r05 satellite closure: once the machine is KNOWN
            # sick (the default probe failed and we are limping on the
            # CPU fallback), a lane crash must still produce the full
            # exit-0 degraded record — never an rc-1 round with a bare
            # line or a naked traceback. A lane crash on a HEALTHY
            # backend is a real bug and still fails loudly.
            if not degraded:
                raise
            lane_error = f"{type(e).__name__}: {e}"

    if lane_error is not None:
        print(json.dumps({
            "metric": "wgl_check_throughput", "value": 0,
            "unit": "history-events/sec", "vs_baseline": 0,
            "kernel_phases": obs.kernel_phases(cap.metrics),
            "padding_waste": 0.0,
            "cache_hit_rate": 0.0,
            "sweep": obs.sweep_stats(cap.metrics),
            "elle": obs.elle_stats(cap.metrics),
            "serve": obs.serve_stats(cap.metrics),
            "fleet": obs.fleet_stats(cap.metrics),
            "campaign": obs.campaign_stats(cap.metrics),
            "ledger": obs.ledger_stats(cap.metrics),
            "longhaul": obs.longhaul_stats(cap.metrics),
            "profile": _profile_record(),
            "health": health_rec,
            "degraded": True,
            "backend": "cpu",
            "detail": {"probe": {"default": reason}},
            "error": f"degraded CPU rerun failed mid-lane ({lane_error}); "
                     f"default backend was already unusable ({reason})"}))
        return 0

    if long100k is None:
        try:
            long100k = json.loads(LONG100K_FILE.read_text())
        except (OSError, ValueError):
            long100k = None

    kernel_eps = corpus["events"] / corpus["kernel_s"]
    oracle_eps = corpus["events"] / corpus["oracle_s"]
    detail = {
        "device": str(jax.devices()[0]),
        "corpus": CORPUS,
        "ops_per_history": N_OPS,
        "batch_wall_s": round(corpus["kernel_s"], 4),
        "oracle_wall_s": round(corpus["oracle_s"], 4),
        "oracle_pinned": corpus["oracle_pinned"],
        "histories_per_sec": round(corpus["histories_per_sec"], 2),
        "configs_per_sec": round(corpus["configs_per_sec"], 1),
        "kernel": corpus["kernel"],
        "k_slots": corpus["k_slots"],
        "table_cells": corpus["table_cells"],
        "long_history": [
            {k: (round(v, 4) if isinstance(v, float) else v)
             for k, v in d.items()} for d in longs],
        "gset_corpus": gset,
        "invalid_lane": invalid_lane,
        "corpus_sched": sched_lane,
        "sparse": sparse_lane,
        "dedup": dedup_lane,
        "tuned": tuned_lane,
        "streaming": stream_lane,
        "elle": elle_lane,
        "serve": serve_lane,
        "fleet": fleet_lane,
        "campaign": campaign_lane,
        "longhaul": longhaul_lane,
    }
    if "roofline" in corpus:
        detail["roofline"] = corpus["roofline"]
    try:
        # The measured oracle/device crossover the production router uses
        # on this platform (VERDICT r4 #3: recorded, not assumed).
        from dataclasses import asdict

        from jepsen_etcd_demo_tpu.ops.calibrate import get_calibration
        detail["calibration"] = asdict(get_calibration())
    except Exception as e:
        detail["calibration"] = {"error": str(e)}
    if long100k:
        detail["long_history_100k"] = long100k
    print(json.dumps({
        "metric": "wgl_check_throughput",
        "value": round(kernel_eps, 1),
        "unit": "history-events/sec",
        "vs_baseline": round(kernel_eps / oracle_eps, 2),
        # Where the harness's own time went (obs/): first-call compile vs
        # steady-state execute wall, host encode seconds, and the live-
        # config high-water mark — doc/telemetry.md maps each field to
        # its underlying metric key.
        "kernel_phases": obs.kernel_phases(cap.metrics),
        # The scheduler lane's contract fields (doc/perf.md): measured
        # padded/real step ratio across its bucketed launches and the
        # kernel-LRU hit rate of its warm pass.
        "padding_waste": sched_lane["padding_waste"],
        "cache_hit_rate": sched_lane["cache_hit_rate"],
        # Sparse-sweep accounting aggregated over the whole bench
        # capture (doc/perf.md): live-tile-ratio gauge + per-mode step/
        # check counters — zeros permitted, never absent.
        "sweep": obs.sweep_stats(cap.metrics),
        # Elle closure-engine accounting over the same capture
        # (ISSUE 11): per-route graph counts, launches, tiled rounds,
        # streamed txns — zeros permitted, never absent.
        "elle": obs.elle_stats(cap.metrics),
        # Serve-daemon accounting over the same capture (ISSUE 13):
        # request/batch/admission counters and latency quantiles —
        # zeros permitted, never absent (the degraded records above
        # carry the all-zero shape).
        "serve": obs.serve_stats(cap.metrics),
        # Fleet-router accounting over the same capture (ISSUE 18):
        # routed/spillover/error/reject counters and replica occupancy
        # gauges — zeros permitted, never absent; detail.fleet carries
        # the measured open-loop lane.
        "fleet": obs.fleet_stats(cap.metrics),
        # Scenario-factory accounting over the same capture (ISSUE 15):
        # spec/falsification/shrink/bank counters — zeros permitted,
        # never absent.
        "campaign": obs.campaign_stats(cap.metrics),
        # Scaling-ledger accounting over the same capture (ISSUE 16):
        # launch count and per-bucket seconds (useful execute vs
        # padding/straggler waste, encode, H2D, compile, dispatch gap)
        # — zeros permitted, never absent; the corpus_sched lane's
        # `ledger` object carries the windowed attribution.
        "ledger": obs.ledger_stats(cap.metrics),
        # Spill-tier accounting over the same capture (ISSUE 20):
        # out-of-core read/write/eviction counters, the compress-ratio
        # and peak-RSS gauges — zeros permitted, never absent;
        # detail.longhaul carries the measured RSS-ceiling lane.
        "longhaul": obs.longhaul_stats(cap.metrics),
        # The tuning profile this round resolved (ISSUE 4): hash +
        # non-default fields with provenance; detail.tuned measures it.
        "profile": _profile_record(),
        # The backend supervisor's state at probe time (obs/health.py,
        # ISSUE 8): healthy here; the degraded records above carry the
        # degraded/wedged snapshot with last-transition provenance.
        "health": health_rec,
        "degraded": degraded,
        "backend": "cpu" if degraded else jax.default_backend(),
        "detail": detail,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
