"""jtsan (ISSUE 14): the JTL501-506 interprocedural concurrency rules,
the `# jtsan:` annotation/wrap-name verification, the contracts.json
sync section, --changed dirtiness for the serve-era scopes, the tier-1
wall-clock guard, and the static-vs-runtime cross-validation: every
lock order the sanitizer witnesses under serve-daemon load must be an
edge the static model predicted, and a deliberately injected inversion
is caught by BOTH halves."""

from __future__ import annotations

import json
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
FIXTURES = REPO / "tests" / "lint_fixtures"
PKG = REPO / "jepsen_etcd_demo_tpu"

from jepsen_etcd_demo_tpu import analysis  # noqa: E402
from jepsen_etcd_demo_tpu.analysis import cli as lint_cli  # noqa: E402
from jepsen_etcd_demo_tpu.analysis.core import ProjectRule  # noqa: E402
from jepsen_etcd_demo_tpu.analysis.flow.index import FlowIndex  # noqa: E402
from jepsen_etcd_demo_tpu.analysis.flow.sync import sync_model  # noqa: E402
from jepsen_etcd_demo_tpu.obs import sync as obs_sync  # noqa: E402


def _lint_sync(dirname, rule_id):
    d = FIXTURES / dirname
    rules = analysis.all_rules()
    return analysis.run_lint([d], rules={rule_id: rules[rule_id]},
                             root=d)


# (rule id, positive fixture dir, expected (file, line) findings,
# negative fixture dir). Golden against the checked-in mini-projects —
# editing a fixture means re-blessing deliberately, same contract as
# GOLDEN/FLOW_GOLDEN in test_lint.py.
SYNC_GOLDEN = [
    ("JTL501", "sync_race_pos", [("engine.py", 20)], "sync_race_neg"),
    ("JTL502", "sync_order_pos", [("locker_a.py", 14)],
     "sync_order_neg"),
    ("JTL503", "sync_cta_pos", [("registry.py", 18)], "sync_cta_neg"),
    ("JTL504", "sync_block_pos", [("worker.py", 16)], "sync_block_neg"),
    ("JTL505", "sync_leak_pos", [("daemon.py", 19), ("daemon.py", 28)],
     "sync_leak_neg"),
]


@pytest.mark.parametrize("rule_id,pos,locs,neg", SYNC_GOLDEN,
                         ids=[g[0] for g in SYNC_GOLDEN])
def test_sync_rule_fixture_golden(rule_id, pos, locs, neg):
    res = _lint_sync(pos, rule_id)
    got = sorted((f.path, f.line) for f in res.findings)
    assert got == sorted(locs), (
        f"{rule_id} on {pos}: expected {sorted(locs)}, got {got}:\n"
        + analysis.format_text(res.findings))
    assert all(f.rule == rule_id and f.fingerprint
               for f in res.findings)
    neg_res = _lint_sync(neg, rule_id)
    assert not neg_res.findings, (
        f"{rule_id} false positives on {neg}:\n"
        + analysis.format_text(neg_res.findings))


def test_sync_rules_registered_with_fixture_dirs():
    """The 5xx family rides the same fixture-pair enforcement as the
    4xx rules (JTL506, the contract gate, is pinned by its own tests
    below — like JTL406)."""
    sync_ids = {i for i in analysis.all_rules() if i.startswith("JTL5")}
    assert sync_ids == {"JTL501", "JTL502", "JTL503", "JTL504",
                       "JTL505", "JTL506"}
    assert {g[0] for g in SYNC_GOLDEN} == sync_ids - {"JTL506"}
    for r in (analysis.all_rules()[i] for i in sorted(sync_ids)):
        assert isinstance(r, ProjectRule)
    for _rid, pos, _locs, neg in SYNC_GOLDEN:
        assert (FIXTURES / pos).is_dir() and (FIXTURES / neg).is_dir()


def test_wfq_incident_regression_fixture():
    """The PR 13-era incident class: dispatch rotates the WFQ slot
    under the queue condition, stats() reads the rotation under a
    SEPARATE stats lock — each side individually locked, lock-sets
    disjoint. JTL501 names both locks."""
    res = _lint_sync("sync_wfq_pos", "JTL501")
    assert [(f.path, f.line) for f in res.findings] \
        == [("scheduler.py", 23)]
    msg = res.findings[0].message
    assert "_rotation" in msg
    assert "_cond" in msg and "_stats_lock" in msg
    assert "no common lock-set" in msg


def test_jtsan_clean_on_real_tree():
    """Acceptance: JTL501-506 over the real package report ZERO
    findings — the real races/leaks this pass surfaced were FIXED
    (scheduler tenant-latency, model_for check-then-act, session
    finalize-under-lock, the daemon session-shutdown gap, the metric
    snapshot reads), and what remains is justified inline."""
    rules = {i: r for i, r in analysis.all_rules().items()
             if i.startswith("JTL5")}
    res = analysis.run_lint([PKG], rules=rules, root=REPO)
    assert not res.findings, analysis.format_text(res.findings)
    # The deliberate lock-free fast path + self-terminating pump are
    # suppressed WITH justifications, not silently.
    assert res.suppressed, "expected justified JTL5xx suppressions"
    for f in res.suppressed:
        assert f.rule.startswith("JTL5")


def test_annotation_verification_is_not_trust(tmp_path):
    """JTL506: unknown directives, unbound annotations, and dangling
    references are findings — a `# jtsan:` annotation is VERIFIED
    against the tree, never trusted."""
    (tmp_path / "m.py").write_text(
        "import threading\n\n\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        # jtsan: guarded-by=self._nope\n"
        "        self.items = {}\n\n"
        "    # jtsan: returns=NoSuchClass\n"
        "    def get(self):\n"
        "        return self.items\n\n\n"
        "# jtsan: frobnicate=yes\n"
        "X = 1\n\n"
        "# jtsan: hb=self.done\n"
        "Y = 2\n")
    rules = analysis.all_rules()
    res = analysis.run_lint([tmp_path], rules={"JTL506": rules["JTL506"]},
                            root=tmp_path)
    msgs = sorted(f.message for f in res.findings)
    assert len(msgs) == 4, analysis.format_text(res.findings)
    assert any("guarded-by='self._nope'" in m for m in msgs)
    assert any("unknown class 'NoSuchClass'" in m for m in msgs)
    assert any("unknown jtsan directive `frobnicate`" in m for m in msgs)
    assert any("hb='self.done'" in m for m in msgs)


def test_wrap_name_literal_verified_against_model(tmp_path):
    """JTL506: a maybe_wrap() name literal that drifts from the model's
    canonical lock id is a finding — otherwise a rename silently breaks
    the witnessed-vs-modeled comparison."""
    (tmp_path / "m.py").write_text(
        "import threading\n\n"
        "from jepsen_etcd_demo_tpu.obs.sync import maybe_wrap\n\n\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = maybe_wrap(threading.Lock(),\n"
        "                                'wrong.Name._lock')\n")
    rules = analysis.all_rules()
    res = analysis.run_lint([tmp_path], rules={"JTL506": rules["JTL506"]},
                            root=tmp_path)
    assert len(res.findings) == 1, analysis.format_text(res.findings)
    assert "'wrong.Name._lock'" in res.findings[0].message
    assert "'m.C._lock'" in res.findings[0].message
    # The real tree's wrap names all verify (part of the strict gate,
    # asserted here against drift directly).
    real = analysis.run_lint(
        [PKG], rules={"JTL506": rules["JTL506"]}, root=REPO)
    assert not [f for f in real.findings
                if "wrap name" in f.message], \
        analysis.format_text(real.findings)


def test_contracts_sync_section_checked_in():
    """The checked-in contracts.json carries the regenerated sync
    section: canonical lock ids, thread roots, guarded structures with
    the threads that touch them, and the may-happen lock-order edges
    (the JTL406 byte-diff gates content drift; JTL506 names a deleted
    section)."""
    c = json.loads((REPO / "contracts.json").read_text(encoding="utf-8"))
    sync = c["sync"]
    assert "serve.scheduler.CoalescingScheduler._lock" in sync["locks"]
    assert sync["locks"]["serve.scheduler.CoalescingScheduler._lock"] \
        == "condition"
    assert "thread:serve.scheduler.CoalescingScheduler._run" \
        in sync["threads"]
    assert "handler:web.server.StoreHandler" in sync["threads"]
    g = sync["guarded"]["serve.scheduler.CoalescingScheduler._queues"]
    assert g["lock"] == "serve.scheduler.CoalescingScheduler._lock"
    assert ["serve.scheduler.CoalescingScheduler._lock",
            "obs.metrics.MetricsRegistry._lock"] in sync["order"]
    # Deleting the section is a JTL506 finding on a harness tree.
    model = sync_model(FlowIndex.build(REPO))
    fresh = model.contract_section()
    assert fresh == sync, "sync section stale vs the tree"


def test_sync_section_missing_is_a_finding(tmp_path):
    (tmp_path / "jepsen_etcd_demo_tpu").mkdir()
    (tmp_path / "jepsen_etcd_demo_tpu" / "m.py").write_text("X = 1\n")
    (tmp_path / "contracts.json").write_text("{}\n")
    rules = analysis.all_rules()
    res = analysis.run_lint([tmp_path], rules={"JTL506": rules["JTL506"]},
                            root=tmp_path)
    assert any("no `sync` section" in f.message for f in res.findings), \
        analysis.format_text(res.findings)


def test_strict_lint_wall_clock_with_jtsan():
    """CI/tooling satellite: the FULL strict lint — jtsan's
    interprocedural pass included — stays inside the 5 s tier-1 bound
    PR 8 established; the concurrency model must not eat the budget."""
    t0 = time.monotonic()
    res = analysis.run_lint([PKG], root=REPO)
    wall = time.monotonic() - t0
    assert not res.findings, analysis.format_text(res.findings)
    assert wall < 5.0, f"full lint took {wall:.1f}s — over the bound"


def test_changed_mode_serve_edit_retriggers_sync_rules(tmp_path, capsys):
    """--changed dirtiness satellite: an edit under serve/ (or
    obs/sync.py) dirties the package contract graph and re-runs the
    JTL5xx project rules — the same rule as the flow rules, regressed
    on a scratch git repo."""
    def git(*a):
        subprocess.run(["git", "-c", "user.email=t@t", "-c",
                        "user.name=t", *a], cwd=tmp_path, check=True,
                       capture_output=True)

    (tmp_path / "pyproject.toml").write_text("")
    serve = tmp_path / "jepsen_etcd_demo_tpu" / "serve"
    serve.mkdir(parents=True)
    obs_dir = tmp_path / "jepsen_etcd_demo_tpu" / "obs"
    obs_dir.mkdir()
    clean = (FIXTURES / "sync_race_neg" / "engine.py").read_text()
    racy = (FIXTURES / "sync_race_pos" / "engine.py").read_text()
    (serve / "engine.py").write_text(clean)
    (obs_dir / "sync.py").write_text("TRACE = 0\n")
    git("init")
    git("add", ".")
    git("commit", "-m", "base")
    # Unchanged tree: quiet no-op.
    assert lint_cli.main(["--changed", "HEAD", "--strict",
                          "--no-baseline", "--rules", "JTL501",
                          str(tmp_path)]) == 0
    assert "nothing to lint" in capsys.readouterr().out
    # Edit under serve/: the sync rules re-run and find the race.
    (serve / "engine.py").write_text(racy)
    assert lint_cli.main(["--changed", "HEAD", "--strict",
                          "--no-baseline", "--rules", "JTL501",
                          str(tmp_path)]) == 1
    assert "JTL501" in capsys.readouterr().out
    git("add", ".")
    git("commit", "-m", "racy")
    # Edit ONLY obs/sync.py: the race is in an UNCHANGED file, but the
    # package-graph dirtying re-runs the project rules full-tree.
    (obs_dir / "sync.py").write_text("TRACE = 1\n")
    assert lint_cli.main(["--changed", "HEAD", "--strict",
                          "--no-baseline", "--rules", "JTL501",
                          str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "JTL501" in out and "engine.py" in out


def test_lint_report_and_sarif_carry_5xx(capsys):
    """CI/tooling satellite: tools/lint_report.py buckets the real
    tree's JTL5xx suppressions with their justifications (and the
    ledger is healthy — no stale, no justification-free), and --format
    sarif carries the 5xx rule metadata + findings."""
    out = subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint_report.py"),
         "--json"], capture_output=True, text=True, cwd=REPO,
        timeout=180)
    report = json.loads(out.stdout)
    assert out.returncode == 0, out.stdout + out.stderr
    assert report["ok"], report["stale_suppressions"]
    assert report["rules"]["JTL501"]["suppressed"] == 1
    assert "lock-free" in \
        report["rules"]["JTL501"]["suppressions"][0]["justification"]
    assert report["rules"]["JTL505"]["suppressed"] == 2
    for s in report["rules"]["JTL505"]["suppressions"]:
        assert s["justification"]
    rules = {"JTL503": analysis.all_rules()["JTL503"]}
    res = analysis.run_lint([FIXTURES / "sync_cta_pos"], rules=rules,
                            root=FIXTURES / "sync_cta_pos")
    doc = json.loads(analysis.format_sarif(res.findings, rules))
    run = doc["runs"][0]
    assert {r["id"] for r in run["tool"]["driver"]["rules"]} \
        == {"JTL503"}
    assert [r["ruleId"] for r in run["results"]] == ["JTL503"]


# -- runtime sanitizer + cross-validation (the dynamic half) ---------------

def test_maybe_wrap_is_identity_when_disabled(monkeypatch):
    monkeypatch.delenv(obs_sync.SYNC_TRACE_ENV, raising=False)
    lock = threading.Lock()
    assert obs_sync.maybe_wrap(lock, "x.Y._lock") is lock


def test_witnessed_lock_orders_are_predicted_under_serve_load(
        tmp_path, rng, monkeypatch):
    """THE cross-validation acceptance: drive the serve scheduler under
    load with the sanitizer on; every witnessed acquisition order must
    be an edge the static model predicts, in both health states, with
    the supervisor transitioning mid-run. Disagreement in either
    direction fails."""
    from jepsen_etcd_demo_tpu import obs
    from jepsen_etcd_demo_tpu.obs import health
    from jepsen_etcd_demo_tpu.serve import CoalescingScheduler
    from jepsen_etcd_demo_tpu.ops.encode import encode_register_history
    from jepsen_etcd_demo_tpu.utils.fuzz import gen_register_history

    monkeypatch.setenv(obs_sync.SYNC_TRACE_ENV, "1")
    obs_sync.reset_witness()
    # Constructed AFTER the env gate so every lock is wrapped.
    fake = health.BackendSupervisor(probe=lambda: (True, "", False),
                                    probe_interval_s=3600.0)
    prev = health.reset_supervisor(fake)
    try:
        with obs.capture() as cap:
            s = CoalescingScheduler(coalesce_ms=30, max_batch=8)
            try:
                encs = [encode_register_history(
                    gen_register_history(rng, n_ops=24, n_procs=3),
                    k_slots=8) for _ in range(6)]
                reqs = [s.submit(f"t{i % 2}", e,
                                 model_name="cas-register")
                        for i, e in enumerate(encs)]
                for r in reqs:
                    assert r.wait(120), "verdict timed out"
                s.stats()
                # Supervisor transitions exercise the health-lock ->
                # obs edges (gauge + event under the supervisor lock).
                fake.note_failure("injected degradation", source="test")
                fake.note_ok(source="test")
            finally:
                s.close()
            summary = obs_sync.publish_metrics()
            assert cap.metrics.value("sync.lock_acquisitions") \
                == summary["acquisitions"] > 0
    finally:
        health.reset_supervisor(prev)
    witnessed = obs_sync.witnessed_edges()
    assert witnessed, "sanitizer witnessed no lock nesting under load"
    model = sync_model(FlowIndex.build(REPO))
    problems = obs_sync.cross_validate(model.edge_pairs())
    assert problems == [], "\n".join(problems)
    # The serve-era edges the model predicts were actually exercised.
    assert ("serve.scheduler.CoalescingScheduler._lock",
            "obs.metrics.MetricsRegistry._lock") in witnessed
    assert ("obs.health.BackendSupervisor._lock",
            "obs.metrics.MetricsRegistry._lock") in witnessed


def test_injected_inversion_caught_by_both_halves(monkeypatch):
    """A deliberately injected lock-order inversion is caught by BOTH
    halves: the runtime sanitizer reports the witnessed two-direction
    pair (and the unmodeled-edge direction), and the static model's
    JTL502 reports the same shape written as code (sync_order_pos)."""
    monkeypatch.setenv(obs_sync.SYNC_TRACE_ENV, "1")
    obs_sync.reset_witness()
    a = obs_sync.maybe_wrap(
        threading.Lock(), "serve.scheduler.CoalescingScheduler._lock")
    b = obs_sync.maybe_wrap(
        threading.Lock(), "obs.metrics.MetricsRegistry._lock")
    with a:
        with b:
            pass
    with b:
        with a:   # the inversion
            pass
    unmodeled = obs_sync.maybe_wrap(threading.Lock(),
                                    "nowhere.Fake._lock")
    with a:
        with unmodeled:
            pass
    model = sync_model(FlowIndex.build(REPO))
    problems = obs_sync.cross_validate(model.edge_pairs())
    assert any("inversion" in p for p in problems), problems
    assert any("nowhere.Fake._lock" in p and "not an edge" in p
               for p in problems), problems
    obs_sync.reset_witness()
    # The static half: the same inversion as code is a JTL502 cycle.
    res = _lint_sync("sync_order_pos", "JTL502")
    assert len(res.findings) == 1
    assert "cycle" in res.findings[0].message


def test_condition_wait_records_held_while_blocking(monkeypatch):
    monkeypatch.setenv(obs_sync.SYNC_TRACE_ENV, "1")
    obs_sync.reset_witness()
    outer = obs_sync.maybe_wrap(threading.Lock(), "t.Outer._lock")
    cond = obs_sync.maybe_wrap(threading.Condition(), "t.Inner._cond")
    with outer:
        with cond:
            cond.wait(0.01)
    blocking = obs_sync.witnessed_blocking()
    assert ("t.Outer._lock", "Condition.wait") in blocking
    obs_sync.reset_witness()


# -- the serve fixes jtsan pinned ------------------------------------------

def test_model_for_returns_one_instance_under_race(monkeypatch):
    """The JTL503 fix: racing model_for() callers all get the ONE
    instance the registry holds (setdefault's return is bound)."""
    from jepsen_etcd_demo_tpu.obs import health
    from jepsen_etcd_demo_tpu.serve import CoalescingScheduler

    fake = health.BackendSupervisor(probe=lambda: (True, "", False),
                                    probe_interval_s=3600.0)
    prev = health.reset_supervisor(fake)
    try:
        s = CoalescingScheduler(coalesce_ms=5, max_batch=2)
        try:
            import jepsen_etcd_demo_tpu.models as models

            calls = []
            real = models.get_model

            def counted(name):
                calls.append(name)
                return real(name)

            monkeypatch.setattr(models, "get_model", counted)
            first = s.model_for("cas-register")
            second = s.model_for("cas-register")
            assert first is second
            assert len(calls) == 1
        finally:
            s.close()
    finally:
        health.reset_supervisor(prev)


def test_pre_fix_daemon_shutdown_gap_is_detected(tmp_path):
    """Reverting the ServeDaemon.close fix on a scratch copy of the
    package makes JTL505 fire on the session-shutdown gap — the rule
    genuinely pins the fix (ownership resolved through the
    SessionManager registry AND the close_all -> close delegation)."""
    import shutil

    shutil.copytree(PKG, tmp_path / "jepsen_etcd_demo_tpu")
    d = tmp_path / "jepsen_etcd_demo_tpu" / "serve" / "daemon.py"
    text = d.read_text(encoding="utf-8")
    assert "        self.sessions.close_all()\n" in text
    d.write_text(text.replace("        self.sessions.close_all()\n", ""),
                 encoding="utf-8")
    rules = analysis.all_rules()
    res = analysis.run_lint([tmp_path / "jepsen_etcd_demo_tpu"],
                            rules={"JTL505": rules["JTL505"]},
                            root=tmp_path)
    hits = [f for f in res.findings
            if "ServeDaemon.sessions" in f.message]
    assert hits, analysis.format_text(res.findings)
    assert "never releases it" in hits[0].message


def test_daemon_close_finalizes_open_sessions(tmp_path):
    """The JTL505 fix: ServeDaemon.close() reaches every open streaming
    session — consumer threads are joined, the registry drains (the
    shutdown gap the static pass surfaced)."""
    from jepsen_etcd_demo_tpu.obs import health
    from jepsen_etcd_demo_tpu.serve import ServeDaemon

    fake = health.BackendSupervisor(probe=lambda: (True, "", False),
                                    probe_interval_s=3600.0)
    prev = health.reset_supervisor(fake)
    try:
        d = ServeDaemon(store_root=str(tmp_path / "store"),
                        write_artifacts=False)
        model = d.scheduler.model_for("cas-register")
        sess = d.sessions.open("t1", model, "cas-register")
        consumer = sess._session._thread
        assert consumer.is_alive()
        d.close()
        assert d.sessions.stats()["open_sessions"] == 0
        consumer.join(timeout=10)
        assert not consumer.is_alive(), \
            "session consumer thread leaked past daemon close"
    finally:
        health.reset_supervisor(prev)
