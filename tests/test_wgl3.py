"""v3 (dense subset-lattice) kernel: differential tests vs oracle/v2/brute.

The dense kernel is the production fast path for any realistic concurrency
(checkers/linearizable.py routes to it first), so it gets the full
differential battery the sort kernels got: golden histories, fuzz vs the
oracle, brute force on tiny histories, batched-vs-single equivalence, and
the reslot/bucket plumbing it depends on.
"""

import random

import pytest

from jepsen_etcd_demo_tpu.checkers.oracle import (brute_force_check,
                                                  check_events_oracle)
from jepsen_etcd_demo_tpu.models import CASRegister
from jepsen_etcd_demo_tpu.ops.encode import (encode_register_history,
                                             reslot_events, EncodeError)
from jepsen_etcd_demo_tpu.ops.wgl2 import check_encoded2
from jepsen_etcd_demo_tpu.ops.wgl3 import (check_encoded3, dense_config,
                                           check_batch_encoded3,
                                           tight_k_slots)
from jepsen_etcd_demo_tpu.utils.fuzz import gen_register_history, \
    mutate_history
from golden import GOLDEN


@pytest.mark.parametrize("name,hist,expected", GOLDEN)
def test_golden_histories_v3(name, hist, expected):
    enc = encode_register_history(hist, k_slots=8)
    out = check_encoded3(enc, CASRegister())
    assert out["valid"] == expected, name


def test_v3_matches_oracle_fuzzed():
    rng = random.Random(0xD3)
    model = CASRegister()
    n_invalid = 0
    for i in range(60):
        h = gen_register_history(rng, n_ops=rng.randrange(5, 60),
                                 n_procs=rng.randrange(2, 7))
        if i % 2 == 0:
            h = mutate_history(rng, h)
        enc = encode_register_history(h, k_slots=32)
        expected = check_events_oracle(enc, model).valid
        n_invalid += (not expected)
        got = check_encoded3(enc, model)
        # Dense kernel is exact: never "unknown", never overflow.
        assert got["valid"] is expected
        assert not got["overflow"]
    assert n_invalid >= 5


def test_v3_matches_brute_force_tiny():
    rng = random.Random(0xD4)
    model = CASRegister()
    for i in range(40):
        h = gen_register_history(rng, n_ops=rng.randrange(3, 10),
                                 n_procs=rng.randrange(2, 4))
        if i % 2 == 0:
            h = mutate_history(rng, h)
        enc = encode_register_history(h, k_slots=16)
        bf = brute_force_check(enc, model)
        assert bf is not None
        assert check_encoded3(enc, model)["valid"] is bf


def test_v3_dead_step_matches_v2():
    """Invalid histories die at the same return step in both kernels."""
    rng = random.Random(0xD5)
    model = CASRegister()
    checked = 0
    for _ in range(30):
        h = mutate_history(rng, gen_register_history(
            rng, n_ops=rng.randrange(10, 50), n_procs=4))
        enc = encode_register_history(h, k_slots=16)
        v2 = check_encoded2(enc, model, f_cap=2048)
        v3 = check_encoded3(enc, model)
        assert v3["valid"] == v2["valid"]
        if v2["valid"] is False:
            assert int(v3["dead_step"]) == int(v2["dead_step"])
            checked += 1
    assert checked >= 3


def test_v3_batched_matches_single():
    rng = random.Random(0xD6)
    model = CASRegister()
    encs, singles = [], []
    for i in range(9):
        h = gen_register_history(rng, n_ops=30, n_procs=4)
        if i % 2 == 0:
            h = mutate_history(rng, h)
        enc = encode_register_history(h, k_slots=32)
        singles.append(check_encoded3(enc, model)["valid"])
        encs.append(enc)
    got = [r["valid"] for r in check_batch_encoded3(encs, model)]
    assert got == singles


def test_reslot_preserves_verdicts_and_tightens():
    rng = random.Random(0xD7)
    model = CASRegister()
    for _ in range(10):
        h = gen_register_history(rng, n_ops=40, n_procs=5)
        enc = encode_register_history(h, k_slots=32)
        tight = reslot_events(enc, enc.max_pending)
        assert tight.k_slots == enc.max_pending
        assert int(tight.events[: tight.n_events, 1].max()) \
            < enc.max_pending
        assert check_events_oracle(tight, model).valid \
            == check_events_oracle(enc, model).valid


def test_reslot_below_max_pending_raises():
    h = gen_register_history(random.Random(0), n_ops=30, n_procs=5)
    enc = encode_register_history(h, k_slots=32)
    with pytest.raises(EncodeError):
        reslot_events(enc, enc.max_pending - 1)


def test_dense_config_infeasible_cases():
    model = CASRegister()
    # Too many slots for the cell budget.
    assert dense_config(model, 32, 4) is None
    # Huge values blow the state axis.
    assert dense_config(model, 10, 2**24) is None
    # Normal jepsen-shaped history: feasible.
    assert dense_config(model, 12, 4) is not None


def test_linearizable_routes_to_dense():
    """The production checker prefers the dense kernel and reports exact
    verdicts through it (backend tag jax-dense)."""
    from jepsen_etcd_demo_tpu.checkers import Linearizable
    rng = random.Random(0xD8)
    h = gen_register_history(rng, n_ops=50, n_procs=6)
    res = Linearizable(backend="jax").check({}, h)
    assert res["backend"] == "jax-dense"
    assert res["valid"] in (True, False)   # exact: no "unknown"
    assert res["overflow"] is False
    bad = mutate_history(rng, h)
    enc = encode_register_history(bad, k_slots=32)
    expected = check_events_oracle(enc, CASRegister()).valid
    res2 = Linearizable(backend="jax").check({}, bad)
    assert res2["valid"] is expected


def test_independent_batched_dense_detects_bad_key():
    """Batched dense path: one corrupt key among several must be caught."""
    from jepsen_etcd_demo_tpu.checkers import IndependentChecker, Linearizable
    from jepsen_etcd_demo_tpu.ops.op import Op
    h = []
    for key in range(4):
        p0, p1 = 10 * key, 10 * key + 1
        h.append(Op(type="invoke", f="write", value=(key, 2), process=p0))
        h.append(Op(type="ok", f="write", value=(key, 2), process=p0))
        h.append(Op(type="invoke", f="read", value=(key, None), process=p1))
        rv = 4 if key == 2 else 2   # key 2 reads a never-written value
        h.append(Op(type="ok", f="read", value=(key, rv), process=p1))
    res = IndependentChecker(Linearizable(backend="jax")).check({}, h)
    assert res["valid"] is False
    assert res["results"]["2"]["valid"] is False
    assert res["results"]["0"]["valid"] is True
    # Healthy keys settle in the batched launch; the invalid key re-runs
    # through the single-history path (which reconstructs its witness).
    assert res["results"]["0"]["backend"] == "jax-dense-batched"
    assert res["results"]["2"]["backend"] == "jax-dense"
    assert res["results"]["2"]["failed_op"] == "read -> 4"


def test_configs_explored_metric():
    """SURVEY.md §5.1: the checker reports configs explored (the search's
    unit of work) on both the single and batched dense paths, and the
    count is sane: at least one config per return step, bounded by the
    table size times steps."""
    from jepsen_etcd_demo_tpu.checkers import Linearizable
    rng = random.Random(0x5EC)
    h = gen_register_history(rng, n_ops=60, n_procs=6)
    res = Linearizable(backend="jax").check({}, h)
    n_returns = sum(1 for op in h if op.type in ("ok", "info"))
    assert res["configs_explored"] >= n_returns
    assert res["configs_explored"] <= res["f_cap"] * (2 * n_returns + 2)

    encs = [encode_register_history(
        gen_register_history(random.Random(i), n_ops=40, n_procs=5),
        k_slots=16) for i in range(3)]
    from jepsen_etcd_demo_tpu.ops import wgl3
    batch = wgl3.check_batch_encoded3(encs, CASRegister())
    assert all(one["configs_explored"] > 0 for one in batch)
